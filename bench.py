"""Benchmark ladder: model-suite training/serving throughput on the
available accelerator (reference gate analog: tools/ci_model_benchmark.sh:50
benches a model SUITE, not one config).

Default (TPU): runs the FULL ladder — flagship GPT-1.3B, ViT-L, BERT-base,
decode (bf16 B=8, int8 B=8, bf16 B=32), MoE, ResNet-50, BERT-large,
ViT-H/14, Swin-T, GPT-2.7B — printing ONE JSON line per row as it
completes,
then a final line repeating the flagship row with the whole ladder embedded
under extra.ladder (the driver parses the LAST line; partial output still
carries every completed row).

Protocol (BASELINE.md): steady-state step time via a fused multi-step scan
(ONE launch per measurement, host-read fence), best of 2+ launches, report
tokens-or-images/sec/chip and achieved MFU; vs_baseline = MFU / 0.70 — the
north-star target fraction (BASELINE.json: >=70% per-chip MFU). The reference
repo publishes no absolute numbers (BASELINE.md), so the target line is the
baseline.

Env knobs: PADDLE_TPU_BENCH_MODEL=<row> runs one row (gpt|vit|bert|resnet50|
swin|decode|moe|gpt27|...see _SINGLE); PADDLE_TPU_BENCH_BUDGET_S caps ladder wall time;
per-row B/S/preset overrides as before.
"""
from __future__ import annotations

import json
import os
import sys
import time


def _chip_peak_flops(device) -> float:
    """bf16 peak matmul FLOP/s (moved to paddle_tpu.device so the profiler's
    StepMonitor shares the same MFU denominator)."""
    from paddle_tpu.device import chip_peak_flops
    return chip_peak_flops(device)


def _emit(row):
    print(json.dumps(row), flush=True)
    return row


def _timed_steps(step, iters, *stacked):
    """Shared protocol: warm-compile + warm-shape run, then timed
    run_steps launches (best of 2) with a host-read fence. Attaches a
    profiler.StepMonitor to the TrainStep so every row also carries
    measured HBM peak + recompile count alongside the analytic MFU."""
    from paddle_tpu.device import reset_max_memory_allocated
    from paddle_tpu.profiler import StepMonitor
    reset_max_memory_allocated()   # row-scoped peak, not process-cumulative
    mon = StepMonitor()
    step.monitor = mon
    losses = step.run_steps(iters, *stacked)
    _ = float(losses.numpy()[-1])
    dt = float("inf")
    for _rep in range(2):
        t0 = time.perf_counter()
        losses = step.run_steps(iters, *stacked)
        final = float(losses.numpy()[-1])
        dt = min(dt, time.perf_counter() - t0)
    return dt, final, mon


def _mon_fields(mon):
    """StepMonitor fields merged into a bench row's `extra`: measured peak
    HBM and the recompile count ride along with every row. The monitor's
    own step-time/MFU are NOT used here — its run_steps walls measure
    launch dispatch, while the row's step_ms/mfu come from the fenced
    protocol (_timed_steps), which stays the authoritative figure."""
    if mon is None:
        return {}
    r = mon.report()
    return {"hbm_peak_bytes": r["hbm_peak_bytes"],
            "recompiles": r["recompiles"]}


def _channels_last_ctx(on_tpu):
    """Enable the channels-last vision fast path for a bench row (restored
    by the caller). Default on for TPU (the NHWC/HWIO conv layout + fused
    conv-bn-act epilogues are the point of the vision rows); override with
    PADDLE_TPU_BENCH_CL=0/1."""
    import paddle_tpu as paddle
    want = os.environ.get("PADDLE_TPU_BENCH_CL", "1" if on_tpu else "0") == "1"
    prev = paddle.get_flags("FLAGS_conv_channels_last")[
        "FLAGS_conv_channels_last"]
    paddle.set_flags({"FLAGS_conv_channels_last": want})
    return prev, want


def bench_resnet50(on_tpu):
    """ResNet-50 ImageNet-shape training throughput (BASELINE.md config):
    fused conv-bn-act epilogue blocks, channels-last trunk on TPU."""
    import jax
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.jit.train_step import TrainStep
    from paddle_tpu.vision.models import resnet50
    import paddle_tpu.nn as nn

    B, hw, iters = (64, 224, 8) if on_tpu else (4, 64, 2)
    B = int(os.environ.get("PADDLE_TPU_BENCH_B", B))
    # flag restore wraps EVERYTHING from here (a build/OOM error mid-row
    # must not leak channels-last into later ladder rows)
    prev_cl, use_cl = _channels_last_ctx(on_tpu)
    try:
        paddle.seed(0)
        model = resnet50(num_classes=1000)
        if on_tpu:
            model.to(dtype="bfloat16")
        ce = nn.CrossEntropyLoss()
        opt = paddle.optimizer.Momentum(learning_rate=0.1,
                                        parameters=model.parameters())
        step = TrainStep(model, opt, lambda x, y: ce(model(x), y))
        imgs = paddle.to_tensor(np.random.randn(iters, B, 3, hw, hw).astype(
            "bfloat16" if on_tpu else "float32"))
        lbls = paddle.to_tensor(
            np.random.randint(0, 1000, (iters, B)).astype("int64"))
        # group the ~106 tiny BN-scale/bias updates into one fused
        # elementwise apply: +2-4% measured r5 (GLOBAL grouping measured
        # -12% in r4; only the small-param grouping pays). Scoped to THIS
        # row and restored — later ladder rows must not inherit it.
        prev_fuse = os.environ.get("PADDLE_TPU_FUSE_SMALL_UPDATES")
        os.environ.setdefault("PADDLE_TPU_FUSE_SMALL_UPDATES", "4096")
        try:
            dt, final, mon = _timed_steps(step, iters, imgs, lbls)
        finally:
            if prev_fuse is None:
                os.environ.pop("PADDLE_TPU_FUSE_SMALL_UPDATES", None)
            else:
                os.environ["PADDLE_TPU_FUSE_SMALL_UPDATES"] = prev_fuse
    finally:
        paddle.set_flags({"FLAGS_conv_channels_last": prev_cl})
    ips = B * iters / dt
    # ResNet-50 at 224²: ~3.86 GMACs fwd → 7.7e9 FLOPs at MAC=2, matching
    # the FMA=2 convention of _chip_peak_flops and the transformer benches;
    # train ≈ 3x fwd (fwd + input-grad + weight-grad)
    fwd_flops = 7.7e9 if hw == 224 else 7.7e9 * (hw * hw) / (224 * 224)
    peak = _chip_peak_flops(jax.devices()[0])
    mfu = 3 * fwd_flops * ips / peak
    return _emit({
        "metric": f"images/sec/chip (resnet50 train, B={B} {hw}x{hw}"
                  f"{' nhwc' if use_cl else ''})",
        "value": round(ips, 1), "unit": "images/s",
        "vs_baseline": round(mfu / 0.70, 4),
        "extra": {"mfu": round(mfu, 4),
                  "step_ms": round(dt / iters * 1e3, 2),
                  "channels_last": use_cl,
                  "loss": round(final, 4),
                  **_mon_fields(mon)},
    })


def bench_bert(on_tpu, preset=None, B=None):
    """BERT MLM pretraining throughput (BASELINE.md config): fused
    short-seq MHA kernel with in-kernel PRNG attention dropout."""
    import jax
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.jit.train_step import TrainStep
    from paddle_tpu.models import BertForMaskedLM, bert_config

    preset = preset or os.environ.get("PADDLE_TPU_BENCH_PRESET", "bert-base")
    Bd, S, iters = ((16 if preset == "bert-large" else 32), 512, 8) \
        if on_tpu else (2, 64, 2)
    B = B or int(os.environ.get("PADDLE_TPU_BENCH_B", Bd))
    S = int(os.environ.get("PADDLE_TPU_BENCH_S", S))
    cfg = bert_config(preset, max_position_embeddings=max(512, S))
    paddle.seed(0)
    model = BertForMaskedLM(cfg)
    if on_tpu:
        model.to(dtype="bfloat16")
    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters(),
                                 moment_dtype="bfloat16" if on_tpu
                                 else "float32")

    # fused tied-decoder CE (no [B,S,vocab] logits; BertForMaskedLM.loss)
    step = TrainStep(model, opt,
                     lambda ids, lbl: model.loss(ids, lbl, chunk_size=256))
    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(rng.randint(0, cfg.vocab_size,
                                       (iters, B, S)).astype("int32"))
    lbl = paddle.to_tensor(rng.randint(0, cfg.vocab_size,
                                       (iters, B, S)).astype("int64"))
    dt, final, mon = _timed_steps(step, iters, ids, lbl)
    tps = B * S * iters / dt
    n = sum(p.size for p in model.parameters())
    fpt = 6 * n + 12 * cfg.num_layers * cfg.hidden_size * S
    peak = _chip_peak_flops(jax.devices()[0])
    return _emit({
        "metric": f"tokens/sec/chip ({preset} MLM + dropout, B={B} S={S})",
        "value": round(tps, 1), "unit": "tokens/s",
        "vs_baseline": round(fpt * tps / peak / 0.70, 4),
        "extra": {"mfu": round(fpt * tps / peak, 4),
                  "step_ms": round(dt / iters * 1e3, 2),
                  "loss": round(final, 4), "params": n,
                  **_mon_fields(mon)},
    })


def bench_gpt(on_tpu, preset=None, B=None, S=None, recompute=None,
              moment_dtype=None, q8_emb=None, label=None, iters=None):
    """GPT pretraining step throughput — the flagship row, parameterizable
    for the 2.7B ladder row."""
    import jax
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.jit.train_step import TrainStep
    from paddle_tpu.models import (GPTForCausalLM, GPTPretrainingCriterion,
                                   gpt_config)

    devs = jax.devices()
    if on_tpu:
        # default: the best measured single-chip flagship point. v5e r3
        # ladder (bf16 moments, fused chunked LM-head CE, chunk 512):
        # B=3 S=2048 73.7% MFU; B=6 S=1024 72.4% (max raw tok/s; B=8 and
        # B=4 S=2048 drop to ~69.5% — XLA auto-remats under HBM pressure);
        # B=2 S=4096 73.4%; B=1 S=8192 71.1% with int8 EMBEDDING moments.
        # 2.7B fits with recompute=save_qkv moment int8 B=6.
        preset = preset or os.environ.get("PADDLE_TPU_BENCH_PRESET",
                                          "gpt3-1.3b")
        B = B or int(os.environ.get("PADDLE_TPU_BENCH_B", "3"))
        S = S or int(os.environ.get("PADDLE_TPU_BENCH_S", "2048"))
        iters = iters or 10
    else:  # CPU smoke (driver runs the real thing on TPU)
        preset, B, S, iters = "gpt3-125m", 2, 128, 3

    cfg = gpt_config(preset, max_position_embeddings=max(1024, S))
    rc = (recompute if recompute is not None
          else os.environ.get("PADDLE_TPU_BENCH_RECOMPUTE"))
    if rc:
        cfg.use_recompute = True
        if rc != "1":
            cfg.recompute_policy = rc
    # bf16 moments: compute still f32, halves optimizer HBM; int8 embedding
    # moments (q8_param_fun) free another ~8% for long-context configs
    if q8_emb is None:
        q8_emb = os.environ.get("PADDLE_TPU_BENCH_Q8_EMB",
                                "1" if S >= 8192 else "0") == "1"
    moment_dtype = moment_dtype or os.environ.get(
        "PADDLE_TPU_BENCH_MOMENT_DTYPE",
        "bfloat16" if on_tpu else "float32")
    # fused LM-head CE: no [B,S,vocab] logits in HBM (models/gpt.py loss())
    ce_chunk = int(os.environ.get("PADDLE_TPU_BENCH_CE_CHUNK", "512"))
    # gradient accumulation: activation memory of B/accum at the update
    # math of B (the knob that fits big models without more remat)
    accum = int(os.environ.get("PADDLE_TPU_BENCH_ACCUM", "1"))
    np.random.seed(0)

    def make_step():
        """The benchmarked config, exactly — also what the in-step
        autotuner measures (an unrepresentative step is the trap
        tune_in_step exists to close)."""
        paddle.seed(0)
        m = GPTForCausalLM(cfg)
        if on_tpu:
            m.to(dtype="bfloat16")  # TPU-native bf16 params+compute
        o = paddle.optimizer.AdamW(
            learning_rate=1e-4, parameters=m.parameters(),
            moment_dtype=moment_dtype,
            q8_param_fun=(lambda n: ("wte" in n or "wpe" in n)) if q8_emb
            else None)
        c = GPTPretrainingCriterion(cfg)
        if ce_chunk > 0:
            st = TrainStep(m, o,
                           lambda a, b: m.loss(a, b, chunk_size=ce_chunk),
                           grad_accum_steps=accum)
        else:  # unfused reference path
            st = TrainStep(m, o, lambda a, b: c(m(a), b),
                           grad_accum_steps=accum)
        return m, st

    # in-context autotune (VERDICT r2 #8): measure flash tile candidates
    # inside THIS config's full single step BEFORE the bench model
    # allocates (each candidate holds a full model+optimizer on device)
    if on_tpu and os.environ.get("PADDLE_TPU_BENCH_AUTOTUNE") == "step":
        import logging
        logging.getLogger("paddle_tpu.ops.pallas.autotune").setLevel(
            logging.INFO)
        if not logging.getLogger().handlers:
            logging.basicConfig(level=logging.INFO)
        from paddle_tpu.ops.pallas import autotune as _at

        # candidates are timed over a MULTI-step fused launch (run_steps):
        # per-call dispatch/transfer latency through a remote relay is
        # larger than the per-step differences being measured
        tune_ids = paddle.to_tensor(np.random.randint(
            0, cfg.vocab_size, (4, B, S)).astype("int32"))

        def build_step():
            _, st = make_step()
            return lambda: float(
                st.run_steps(4, tune_ids, tune_ids).numpy()[-1])

        sig = ("in_step4", preset, B, S, ce_chunk, accum,
               moment_dtype, int(q8_emb), rc or "none")
        best = _at.tune_in_step("flash_attention_step", sig,
                                _at.flash_candidates(S, S), build_step)
        os.environ["PADDLE_TPU_FLASH_BQ"] = str(best[0])
        os.environ["PADDLE_TPU_FLASH_BK"] = str(best[1])
        print(f"# in-step autotune picked blocks {best}", file=sys.stderr)

    model, step = make_step()

    # timed region runs `iters` steps as ONE executable (TrainStep.run_steps
    # — lax.scan over stacked batches): amortizes host/relay dispatch and,
    # with the float() host read, measures true device completion rather
    # than async dispatch (block_until_ready through a remote relay is not
    # a reliable fence).
    stacked = paddle.to_tensor(np.random.randint(
        0, cfg.vocab_size, (iters, B, S)).astype("int32"))
    losses = step.run_steps(2, paddle.to_tensor(stacked._data[:2]),
                            paddle.to_tensor(stacked._data[:2]))
    _ = float(losses.numpy()[-1])
    dt, final_loss, mon = _timed_steps(step, iters, stacked, stacked)

    tokens_per_sec = B * S * iters / dt
    n_params = sum(p.size for p in model.parameters())
    L, H = cfg.num_layers, cfg.hidden_size
    flops_per_token = 6 * n_params + 12 * L * H * S
    peak = _chip_peak_flops(devs[0])
    mfu = flops_per_token * tokens_per_sec / peak
    return _emit({
        "metric": f"tokens/sec/chip ({label or preset} pretrain, B={B} "
                  f"S={S}, {'bf16 ' if on_tpu else ''}{devs[0].device_kind})",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/s",
        "vs_baseline": round(mfu / 0.70, 4),
        "extra": {"mfu": round(mfu, 4), "step_ms": round(dt / iters * 1e3, 2),
                  "loss": round(final_loss, 4), "params": n_params,
                  **_mon_fields(mon)},
    })


# dense-twin results are capacity-factor independent; cache across the two
# moe ladder points (cf=1.0 tight, cf=1.25 GShard/model default)
_MOE_DENSE_CACHE = {}


def bench_moe(on_tpu, cf=None):
    """GPT-MoE routed-expert throughput (reference anchor:
    incubate/distributed/models/moe/moe_layer.py:260): 1.3B-class TOTAL
    parameters — gpt3-350m backbone, 8 experts every 2nd layer, top-2
    gshard gate — plus the DENSE twin of the same backbone, so the routing
    overhead is the measured delta at matched per-token FLOPs class."""
    import jax
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.jit.train_step import TrainStep
    from paddle_tpu.models import GPTForCausalLM, gpt_config

    if on_tpu:
        B, S, iters, preset = 8, 1024, 8, "gpt3-350m"
    else:
        B, S, iters, preset = 2, 64, 2, "gpt3-125m"
    B = int(os.environ.get("PADDLE_TPU_BENCH_B", B))
    S = int(os.environ.get("PADDLE_TPU_BENCH_S", S))

    # capacity headroom: the MODEL default stays 1.25 (GShard convention,
    # robust to router imbalance); the bench row runs tight capacity 1.0 —
    # the padding slots compute but are not active FLOPs, and they are the
    # largest routing-overhead term (measured r5: 15.4% overhead at 1.25
    # vs 4.1% at 1.0; drop rate at balanced routing 0.8%). The row's
    # `capacity_factor` extra keeps the config transparent.
    if cf is None:
        cf = float(os.environ.get("PADDLE_TPU_BENCH_MOE_CF", "1.0"))

    def run(num_experts):
        # the dense twin is capacity-factor independent — cache it so a
        # second ladder point (cf=1.25) pays only the MoE run
        dense_key = (preset, B, S, iters)
        if num_experts == 0 and dense_key in _MOE_DENSE_CACHE:
            return _MOE_DENSE_CACHE[dense_key]
        cfg = gpt_config(preset, max_position_embeddings=max(1024, S),
                         moe_num_experts=num_experts, moe_every_n_layers=2,
                         moe_gate="gshard", moe_aux_weight=0.01,
                         moe_capacity_factor=cf)
        paddle.seed(0)
        m = GPTForCausalLM(cfg)
        if on_tpu:
            m.to(dtype="bfloat16")
        o = paddle.optimizer.AdamW(
            learning_rate=1e-4, parameters=m.parameters(),
            moment_dtype="bfloat16" if on_tpu else "float32")
        st = TrainStep(m, o, lambda a, b: m.loss(a, b, chunk_size=512))
        rng = np.random.RandomState(0)
        ids = paddle.to_tensor(rng.randint(
            0, cfg.vocab_size, (iters, B, S)).astype("int32"))
        dt, final, mon = _timed_steps(st, iters, ids, ids)
        # measured (token, slot) drop rate at the TRAINED router state
        # (ADVICE r5: the capacity_factor disclosure needs the drop rate it
        # trades against): one eager forward with the telemetry recorder on
        drop = None
        if num_experts:
            from paddle_tpu.core import autograd as _ag
            from paddle_tpu.incubate.distributed.models.moe import (
                moe_layer as _ml)
            _ml.record_drop_rate(True)
            try:
                with _ag.no_grad():
                    _ = m.loss(paddle.to_tensor(ids._data[0]),
                               paddle.to_tensor(ids._data[0]),
                               chunk_size=512)
                drop = _ml.measured_drop_rate()
            finally:
                _ml.record_drop_rate(False)
        n = sum(p.size for p in m.parameters())
        # ACTIVATED flops/token: dense blocks + top-2 of 8 experts — count
        # the params a token actually visits (standard MoE MFU convention)
        L, H = cfg.num_layers, cfg.hidden_size
        inter = cfg.intermediate_size
        expert_params_per_layer = 2 * H * inter
        n_moe_layers = L // 2
        top_k = 2 if num_experts else 0
        n_active = n - (num_experts * expert_params_per_layer
                        * n_moe_layers) + (top_k * expert_params_per_layer
                                           * n_moe_layers
                                           if num_experts else 0)
        fpt = 6 * n_active + 12 * L * H * S
        res = (dt, final, n, n_active, fpt, drop, mon)
        if num_experts == 0:
            _MOE_DENSE_CACHE[dense_key] = res
        return res

    dt_m, loss_m, n_m, act_m, fpt_m, drop_rate, mon_m = run(8)
    dt_d, _, _, _, fpt_d, _, _ = run(0)
    tps_m = B * S * iters / dt_m
    tps_d = B * S * iters / dt_d
    peak = _chip_peak_flops(jax.devices()[0])
    mfu_m = fpt_m * tps_m / peak
    # routing overhead = slowdown beyond what the EXTRA ACTIVE FLOPs of
    # top-2 experts explain: (time ratio) / (active-FLOP ratio) - 1.
    # Raw dt_m/dt_d alone would conflate expert compute with routing cost.
    routing = (dt_m / dt_d) / (fpt_m / fpt_d) - 1.0
    return _emit({
        "metric": f"tokens/sec/chip (gpt-moe {preset}+8exp top2, "
                  f"{n_m/1e9:.2f}B total/{act_m/1e9:.2f}B active, "
                  f"B={B} S={S} cf={cf})",
        "value": round(tps_m, 1), "unit": "tokens/s",
        "vs_baseline": round(mfu_m / 0.70, 4),
        "extra": {"mfu": round(mfu_m, 4),   # active-FLOP MFU (driver key)
                  "mfu_active_flops": round(mfu_m, 4),
                  "step_ms": round(dt_m / iters * 1e3, 2),
                  "loss": round(loss_m, 4),
                  "dense_twin_tok_s": round(tps_d, 1),
                  "dense_twin_step_ms": round(dt_d / iters * 1e3, 2),
                  "routing_overhead_pct": round(routing * 100, 1),
                  "capacity_factor": cf,
                  # measured (token,slot) overflow at this cf — the cost
                  # the capacity knob trades against padding compute
                  "drop_rate_pct": (None if drop_rate is None
                                    else round(drop_rate * 100, 2)),
                  "params_total": n_m, "params_active": act_m,
                  **_mon_fields(mon_m)},
    })


def bench_decode(on_tpu, B=None, w8=None, c8=None, marginal=False):
    """Autoregressive decode throughput via generate_static (ONE compiled
    program: prefill + lax.scan of fixed-shape KV-cache steps)."""
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.models import GPTForCausalLM, gpt_config

    if on_tpu:
        preset, Bd, p_len, new = "gpt3-1.3b", 8, 128, 128
    else:
        preset, Bd, p_len, new = "gpt3-125m", 2, 16, 16
    preset = os.environ.get("PADDLE_TPU_BENCH_PRESET", preset)
    B = B or int(os.environ.get("PADDLE_TPU_BENCH_B", Bd))
    cfg = gpt_config(preset)
    paddle.seed(0)
    model = GPTForCausalLM(cfg)
    if on_tpu:
        model.to(dtype="bfloat16")
    model.eval()
    # weight-only int8 decode: decode is weight-bandwidth-bound, so halving
    # the scan's weight bytes is the lever; r5 streams the int8 bytes
    # through the Pallas dequant-in-register matmul (ops/pallas/
    # int8_matmul.py) instead of materializing dequantized copies
    wdt = (w8 if w8 is not None
           else os.environ.get("PADDLE_TPU_BENCH_DECODE_W8", "0") == "1")
    # int8 KV cache (r5): codes + per-(pos,head) scales with factored-scale
    # attention — halves the KV bytes each decode step streams; measured
    # 3.46 -> 3.00 ms/step at B=8 on top of int8 weights
    cdt = (c8 if c8 is not None
           else os.environ.get("PADDLE_TPU_BENCH_DECODE_C8", "0") == "1")
    kw = {}
    if wdt:
        kw["weight_dtype"] = "int8"
    if cdt:
        kw["cache_dtype"] = "int8"
    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(
        rng.randint(0, cfg.vocab_size, (B, p_len)).astype("int64"))
    out = model.generate_static(ids, max_new_tokens=new, **kw)  # warm compile
    _ = out.numpy()
    dt = float("inf")
    # best-of-5: decode launches are short (~0.4s) and the relay adds
    # per-launch jitter that in-ladder runs amplify — r5 saw the same
    # program read 2427 in-ladder vs 2619-2667 standalone at 2 reps
    for _rep in range(5):
        t0 = time.perf_counter()
        out = model.generate_static(ids, max_new_tokens=new, **kw)
        _ = out.numpy()
        dt = min(dt, time.perf_counter() - t0)
    tps = B * new / dt
    extra = {"ms_per_step": round(dt / new * 1e3, 3),
             "ms_per_token": round(dt / (new * B) * 1e3, 3),
             "total_s": round(dt, 2)}
    if marginal:
        # whole-launch tok/s folds a fixed per-launch cost (prefill +
        # relay dispatch + host read, measured 20-56 ms varying with
        # relay state across a day) over only `new` steps. A second
        # launch at 2x steps separates it: the marginal rate is the
        # steady-state decode throughput a serving loop actually sees.
        out = model.generate_static(ids, max_new_tokens=2 * new, **kw)
        _ = out.numpy()
        dt2 = float("inf")
        for _rep in range(3):
            t0 = time.perf_counter()
            out = model.generate_static(ids, max_new_tokens=2 * new, **kw)
            _ = out.numpy()
            dt2 = min(dt2, time.perf_counter() - t0)
        marg = dt2 - dt
        # same-state launches measure tight (<4% over 12 reps), but guard
        # the subtraction anyway: a jitter hit on every 2x rep could push
        # marg past dt and the fixed cost negative — report only sane
        # separations, never a nonsensical negative fixed cost
        if 0 < marg <= dt:
            extra["marginal_tok_s"] = round(B * new / marg, 1)
            extra["marginal_ms_per_step"] = round(marg / new * 1e3, 3)
            extra["fixed_launch_ms"] = round((dt - marg) * 1e3, 1)
    return _emit({
        "metric": f"decode tokens/sec/chip ({preset} generate_static"
                  f"{' int8-weights' if wdt else ''}"
                  f"{' int8-kv' if cdt else ''}, "
                  f"B={B} prefill={p_len} new={new})",
        "value": round(tps, 1), "unit": "tokens/s",
        "vs_baseline": None,
        "extra": extra,
    })


def bench_decode_paged(on_tpu):
    """Paged-vs-padded serving decode on long-tail mixed-length traffic
    (ISSUE 5): the same open-loop workload replayed through the padded
    static engine and the block-pool engine with slot-level continuous
    batching. The row value is the PAGED tok/s; extras carry the padded
    twin, the true-KV-occupancy gap, and the decode_static buffer-donation
    saving (satellite: donated caches skip the per-chunk cache re-thread)."""
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.inference import (ServingConfig, ServingEngine,
                                      synthetic_traffic)
    from paddle_tpu.models import GPTForCausalLM, GPTConfig, gpt_config

    if on_tpu:
        preset, B, cap, new, chunk, n_req = "gpt3-1.3b", 8, 128, 128, 32, 48
    else:
        preset, B, cap, new, chunk, n_req = None, 2, 16, 8, 4, 10
    preset = os.environ.get("PADDLE_TPU_BENCH_PRESET", preset) \
        if on_tpu else preset
    paddle.seed(0)
    if preset:
        cfg = gpt_config(preset)
        model = GPTForCausalLM(cfg)
        model.to(dtype="bfloat16")
    else:
        cfg = GPTConfig(vocab_size=128, hidden_size=64, num_layers=2,
                        num_heads=4, max_position_embeddings=128,
                        intermediate_size=128)
        model = GPTForCausalLM(cfg)
    model.eval()
    traffic = synthetic_traffic(n_req, prompt_cap=cap,
                                vocab_size=cfg.vocab_size, rate=1e9,
                                seed=3, length_dist="longtail")

    def run(paged):
        eng = ServingEngine(model, ServingConfig(
            max_batch=B, prompt_cap=cap, max_new_tokens=new,
            decode_chunk=chunk, paged=paged))
        for item in traffic[:B]:            # warmup: compile the pair
            eng.submit(item["prompt"])
        eng.drain()
        eng.metrics = type(eng.metrics)()
        peak = 0.0

        def track():
            nonlocal peak
            peak = max(peak, eng.metrics.gauges.get("kv_occupancy") or 0.0)

        t0 = time.perf_counter()
        for item in traffic:
            eng.submit(item["prompt"])
            while eng.queue_depth >= B:
                eng.step()
                track()
        while eng.busy:           # the drain tail is where occupancy peaks
            eng.step()
            track()
        dt = time.perf_counter() - t0
        toks = eng.metrics.counters["tokens_out"]
        return toks / dt, peak, eng.monitor.recompiles

    padded_tps, padded_kv, rc0 = run(False)
    paged_tps, paged_kv, rc1 = run(True)

    # decode_static donation saving: the same chunked decode with the KV
    # tuples donated (in-place) vs re-threaded by value — the per-chunk
    # fixed-cost delta the satellite asks the row to record
    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(
        rng.randint(1, cfg.vocab_size, (B, cap)).astype("int64"))
    lens = np.full((B,), cap, np.int32)
    n_chunks = max(2, new // chunk)
    times = {}
    for donate in (False, True):
        best = float("inf")
        for _rep in range(3):
            st = model.prefill_static(ids, max_len=cap + new,
                                      prompt_lens=lens)
            t0 = time.perf_counter()
            for _ in range(n_chunks):
                toks, st = model.decode_static(st, chunk,
                                               return_state=True,
                                               donate_cache=donate)
            _ = toks.numpy()
            best = min(best, time.perf_counter() - t0)
        times[donate] = best / n_chunks
    donate_saving_ms = (times[False] - times[True]) * 1e3

    return _emit({
        "metric": f"paged serving decode tokens/sec/chip "
                  f"({preset or 'toy'} longtail traffic, B={B} cap={cap} "
                  f"new={new} chunk={chunk})",
        "value": round(paged_tps, 1), "unit": "tokens/s",
        "vs_baseline": None,
        "extra": {"padded_tok_s": round(padded_tps, 1),
                  "paged_vs_padded": round(paged_tps / padded_tps, 3)
                  if padded_tps else None,
                  "kv_occupancy_paged": round(paged_kv, 3),
                  "kv_occupancy_padded": round(padded_kv, 3),
                  "steady_recompiles": rc0 + rc1,
                  "donate_saving_ms_per_chunk": round(donate_saving_ms, 3),
                  "decode_chunk_ms_donated": round(times[True] * 1e3, 2),
                  "decode_chunk_ms_copied": round(times[False] * 1e3, 2)},
    })


def bench_decode_paged_mp(on_tpu):
    """Multi-chip sharded paged serving (ISSUE 16): the same long-tail
    workload replayed through the head-sharded tensor-parallel paged
    engine — KV pools sharded over the `mp` mesh axis, decode
    communicating through mp-group all-reduces ONLY (the CommPlan the
    graph_lint gpt-paged-sharded target proves statically) — and its
    single-chip twin printed alongside. The row value is the sharded
    tok/s; extras carry the twin, the speedup, and the shard count."""
    import paddle_tpu as paddle
    from paddle_tpu.inference import (ServingConfig, ServingEngine,
                                      synthetic_traffic)
    from paddle_tpu.models import GPTForCausalLM, GPTConfig, gpt_config

    # a CPU host gets a virtual multi-device backend when nothing
    # initialized one yet (XLA reads XLA_FLAGS at first backend init)
    if not on_tpu and "--xla_force_host_platform_device_count" not in \
            os.environ.get("XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8")
    import jax

    if on_tpu:
        preset, B, cap, new, chunk, n_req = "gpt3-1.3b", 8, 128, 128, 32, 48
    else:
        preset, B, cap, new, chunk, n_req = None, 2, 16, 8, 4, 10
    preset = os.environ.get("PADDLE_TPU_BENCH_PRESET", preset) \
        if on_tpu else preset
    paddle.seed(0)
    if preset:
        cfg = gpt_config(preset)
        model = GPTForCausalLM(cfg)
        model.to(dtype="bfloat16")
    else:
        cfg = GPTConfig(vocab_size=128, hidden_size=64, num_layers=2,
                        num_heads=4, max_position_embeddings=128,
                        intermediate_size=128)
        model = GPTForCausalLM(cfg)
    model.eval()

    shards = 1
    lim = min(len(jax.devices()), cfg.num_heads)
    while shards * 2 <= lim and cfg.num_heads % (shards * 2) == 0:
        shards *= 2
    if shards < 2:
        return _emit({
            "metric": "multi-chip paged serving decode tokens/sec",
            "value": None, "unit": "tokens/s", "vs_baseline": None,
            "extra": {"reason": f"{len(jax.devices())} device(s), "
                                f"{cfg.num_heads} heads: no mp axis "
                                f">= 2 available"}})

    traffic = synthetic_traffic(n_req, prompt_cap=cap,
                                vocab_size=cfg.vocab_size, rate=1e9,
                                seed=3, length_dist="longtail")

    def run(s):
        eng = ServingEngine(model, ServingConfig(
            max_batch=B, prompt_cap=cap, max_new_tokens=new,
            decode_chunk=chunk, paged=True, shards=s))
        for item in traffic[:B]:            # warmup: compile the pair
            eng.submit(item["prompt"])
        eng.drain()
        eng.metrics = type(eng.metrics)()
        t0 = time.perf_counter()
        for item in traffic:
            eng.submit(item["prompt"])
            while eng.queue_depth >= B:
                eng.step()
        while eng.busy:
            eng.step()
        dt = time.perf_counter() - t0
        return (eng.metrics.counters["tokens_out"] / dt,
                eng.monitor.recompiles)

    one_tps, rc1 = run(1)
    mp_tps, rc2 = run(shards)

    return _emit({
        "metric": f"multi-chip paged serving decode tokens/sec "
                  f"({preset or 'toy'} longtail traffic, mp={shards}, "
                  f"B={B} cap={cap} new={new} chunk={chunk})",
        "value": round(mp_tps, 1), "unit": "tokens/s",
        "vs_baseline": None,
        "extra": {"shards": shards,
                  "single_chip_tok_s": round(one_tps, 1),
                  "mp_vs_single": round(mp_tps / one_tps, 3)
                  if one_tps else None,
                  "steady_recompiles": rc1 + rc2},
    })


def bench_decode_paged_prefix(on_tpu):
    """Prefix-cached serving on shared-prefix traffic (ISSUE 10): N system
    prompts x random suffixes replayed through the paged engine with the
    radix-trie prefix cache OFF and ON. The row value is the CACHED tok/s;
    extras carry the uncached twin, the hit rate, prefill-tokens-saved and
    the p50 TTFT both ways — the acceptance row for "a repeated prefix
    admits with zero prefill tokens"."""
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.inference import (ServingConfig, ServingEngine,
                                      shared_prefix_traffic)
    from paddle_tpu.models import GPTForCausalLM, GPTConfig, gpt_config

    if on_tpu:
        preset, B, cap, new, chunk, n_req, kvb = \
            "gpt3-1.3b", 8, 128, 128, 32, 48, 16
        n_prefixes, plen = 4, 96
    else:
        preset, B, cap, new, chunk, n_req, kvb = None, 2, 16, 8, 4, 12, 4
        n_prefixes, plen = 2, 8
    preset = os.environ.get("PADDLE_TPU_BENCH_PRESET", preset) \
        if on_tpu else preset
    paddle.seed(0)
    if preset:
        cfg = gpt_config(preset)
        model = GPTForCausalLM(cfg)
        model.to(dtype="bfloat16")
    else:
        cfg = GPTConfig(vocab_size=128, hidden_size=64, num_layers=2,
                        num_heads=4, max_position_embeddings=256,
                        intermediate_size=128)
        model = GPTForCausalLM(cfg)
    model.eval()
    traffic = shared_prefix_traffic(n_req, n_prefixes=n_prefixes,
                                    prefix_len=plen, prompt_cap=cap,
                                    vocab_size=cfg.vocab_size, rate=1e9,
                                    seed=3)

    def run(prefix):
        eng = ServingEngine(model, ServingConfig(
            max_batch=B, prompt_cap=cap, max_new_tokens=new,
            decode_chunk=chunk, paged=True, kv_block=kvb,
            kv_blocks=B * (-(-(cap + new - 1) // kvb)) + 1
            + (n_req * (cap // kvb) if prefix else 0),
            prefix_cache=prefix))
        # warmup: full-prefill + decode, plus (cached leg) the COW and
        # suffix-prefill executables — then start the measured replay cold
        if prefix:
            eng.warmup_prefix_cache(cfg.vocab_size)
        else:
            rng = np.random.RandomState(1)
            wp = rng.randint(1, cfg.vocab_size,
                             ((cap // kvb) * kvb,)).astype(np.int64)
            eng.submit(wp)
            eng.drain()
        eng.metrics = type(eng.metrics)()
        t0 = time.perf_counter()
        for item in traffic:
            eng.submit(item["prompt"])
            while eng.queue_depth >= B:
                eng.step()
        while eng.busy:
            eng.step()
        dt = time.perf_counter() - t0
        s = eng.summary()
        hits, misses = s["prefix_hit_total"], s["prefix_miss_total"]
        return {"tok_s": s["tokens_out_total"] / dt,
                "ttft_p50_ms": s["ttft_seconds"]["p50"] * 1e3
                if "ttft_seconds" in s else None,
                "hit_rate": hits / max(hits + misses, 1),
                "saved": s["prefill_tokens_saved_total"],
                "recompiles": eng.monitor.recompiles}

    off = run(False)
    on = run(True)
    return _emit({
        "metric": f"prefix-cached serving decode tokens/sec/chip "
                  f"({preset or 'toy'} shared-prefix traffic, "
                  f"{n_prefixes}x{plen}-tok prompts, B={B} cap={cap} "
                  f"new={new})",
        "value": round(on["tok_s"], 1), "unit": "tokens/s",
        "vs_baseline": None,
        "extra": {"uncached_tok_s": round(off["tok_s"], 1),
                  "cached_vs_uncached": round(on["tok_s"] / off["tok_s"],
                                              3) if off["tok_s"] else None,
                  "prefix_hit_rate": round(on["hit_rate"], 3),
                  "prefill_tokens_saved": on["saved"],
                  "ttft_p50_ms_cached": round(on["ttft_p50_ms"], 3)
                  if on["ttft_p50_ms"] else None,
                  "ttft_p50_ms_uncached": round(off["ttft_p50_ms"], 3)
                  if off["ttft_p50_ms"] else None,
                  "steady_recompiles": off["recompiles"]
                  + on["recompiles"]},
    })


def bench_decode_spec(on_tpu):
    """Speculative vs plain paged decode at B=8 on shared-prefix repeat
    traffic (ISSUE 11): the same agentic/retry workload (fixed prompts
    repeated verbatim) replayed through the paged+prefix engine with
    speculative decoding OFF and ON. The spec leg drafts from the prefix
    radix trie (a finished chain's cached blocks ARE the draft — no
    draft model) and verifies spec_k tokens per row in one [B, k] call
    through the ragged multi-token kernel, so the sequential depth per
    emitted token drops by the acceptance factor. The row value is the
    SPECULATIVE tok/s; extras carry the plain twin and the acceptance
    metrics — the PR's win as a recorded number."""
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.inference import (ServingConfig, ServingEngine,
                                      repeated_traffic)
    from paddle_tpu.models import GPTForCausalLM, GPTConfig, gpt_config

    if on_tpu:
        preset, B, cap, new, chunk, kvb, sk, n_req, n_prompts = \
            "gpt3-1.3b", 8, 128, 128, 32, 16, 8, 32, 4
    else:
        preset, B, cap, new, chunk, kvb, sk, n_req, n_prompts = \
            None, 8, 16, 48, 4, 4, 4, 32, 2
    preset = os.environ.get("PADDLE_TPU_BENCH_PRESET", preset) \
        if on_tpu else preset
    paddle.seed(0)
    if preset:
        cfg = gpt_config(preset)
        model = GPTForCausalLM(cfg)
        model.to(dtype="bfloat16")
    else:
        # slightly beefier toy than the other serving rows: the spec win
        # is compute-depth per token, which a 2-layer h=64 toy hides
        # under host dispatch noise
        cfg = GPTConfig(vocab_size=128, hidden_size=128, num_layers=3,
                        num_heads=4, max_position_embeddings=256,
                        intermediate_size=256)
        model = GPTForCausalLM(cfg)
    model.eval()
    traffic = repeated_traffic(n_req, n_prompts=n_prompts, prompt_len=cap,
                               vocab_size=cfg.vocab_size, rate=1e9,
                               seed=3)
    # pool sizing: worst-case live slots + the cached CHAINS (spec
    # caches prompt+generation blocks — an undersized pool would starve
    # admission on retained cache blocks and bill it to spec)
    kv_blocks = B * (-(-(cap + new - 1) // kvb)) \
        + n_prompts * (-(-(cap + new) // kvb)) + 16

    def run(spec):
        best = 0.0
        eng = None
        for _rep in range(2):              # best-of-2: box-noise guard
            eng = ServingEngine(model, ServingConfig(
                max_batch=B, prompt_cap=cap, max_new_tokens=new,
                decode_chunk=chunk, paged=True, kv_block=kvb,
                kv_blocks=kv_blocks, prefix_cache=True,
                spec_decode=spec, spec_k=sk))
            eng.warmup_prefix_cache(cfg.vocab_size)
            eng.metrics = type(eng.metrics)()
            t0 = time.perf_counter()
            for item in traffic:
                eng.submit(item["prompt"])
                while eng.queue_depth >= B:
                    eng.step()
            while eng.busy:
                eng.step()
            dt = time.perf_counter() - t0
            best = max(best, eng.metrics.counters["tokens_out"] / dt)
        s = eng.metrics.counters
        acc_hist = eng.metrics.hists["spec_accept_len"]
        return {"tok_s": best,
                "windows": s["spec_windows"],
                "proposed": s["spec_proposed"],
                "accepted": s["spec_accepted"],
                "drafts_trie": s["spec_drafts_trie"],
                "drafts_model": s["spec_drafts_model"],
                "accept_len_p50": acc_hist.percentile(0.5)
                if acc_hist.count else None,
                "recompiles": eng.monitor.recompiles}

    plain = run(False)
    spec = run(True)
    rate = spec["accepted"] / spec["proposed"] if spec["proposed"] else None
    return _emit({
        "metric": f"speculative paged decode tokens/sec/chip "
                  f"({preset or 'toy'} shared-prefix repeat traffic, "
                  f"B={B} cap={cap} new={new} spec_k={sk})",
        "value": round(spec["tok_s"], 1), "unit": "tokens/s",
        "vs_baseline": None,
        "extra": {"plain_paged_tok_s": round(plain["tok_s"], 1),
                  "spec_vs_plain": round(spec["tok_s"] / plain["tok_s"],
                                         3) if plain["tok_s"] else None,
                  "accept_rate": round(rate, 3)
                  if rate is not None else None,
                  "spec_windows": spec["windows"],
                  "accept_len_p50": spec["accept_len_p50"],
                  "drafts_trie": spec["drafts_trie"],
                  "drafts_model": spec["drafts_model"],
                  "steady_recompiles": plain["recompiles"]
                  + spec["recompiles"]},
    })


def bench_vit(on_tpu, preset=None, B=None):
    """ViT (BASELINE.md config) training throughput — fused whole-sequence
    MHA kernel at the ragged patch-sequence length."""
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.jit.train_step import TrainStep
    from paddle_tpu.models import VisionTransformer, vit_config
    import paddle_tpu.nn as nn

    preset = preset or os.environ.get("PADDLE_TPU_BENCH_PRESET", "vit-l16")
    # vit-l B=64 default: the fused whole-sequence MHA kernel pipelines
    # across batch programs — measured 66.2% MFU at B=64 vs 55-58% at
    # B=32 on v5e (B=128 plateaus); vit-h is MXU-heavy enough at B=32
    Bd = 32 if preset == "vit-h14" else 64
    B = B or int(os.environ.get("PADDLE_TPU_BENCH_B", Bd if on_tpu else 2))
    iters = 8 if on_tpu else 2
    if on_tpu:
        cfg = vit_config(preset, image_size=224, num_classes=1000)
    else:  # CPU smoke: tiny config (precedent: GPT drops to 125m off-TPU)
        cfg = vit_config(preset, image_size=32, patch_size=16,
                         hidden_size=64, num_layers=2, num_heads=4,
                         num_classes=1000)
    paddle.seed(0)
    model = VisionTransformer(cfg)
    if on_tpu:
        model.to(dtype="bfloat16")
    ce = nn.CrossEntropyLoss()
    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters(),
                                 moment_dtype="bfloat16" if on_tpu
                                 else "float32")
    step = TrainStep(model, opt, lambda x, y: ce(model(x), y))
    hw = cfg.image_size
    imgs = paddle.to_tensor(np.random.randn(iters, B, 3, hw, hw).astype(
        "bfloat16" if on_tpu else "float32"))
    lbls = paddle.to_tensor(np.random.randint(0, 1000, (iters, B)).astype("int64"))
    dt, final, mon = _timed_steps(step, iters, imgs, lbls)
    ips = B * iters / dt
    n = sum(p.size for p in model.parameters())
    seq = cfg.num_patches + 1
    fpi = 6 * n * seq + 12 * cfg.num_layers * cfg.hidden_size * seq * seq
    import jax as _jax
    peak = _chip_peak_flops(_jax.devices()[0])
    return _emit({
        "metric": f"images/sec/chip ({preset} train, B={B} {hw}x{hw})",
        "value": round(ips, 1), "unit": "images/s",
        "vs_baseline": round(fpi * ips / peak / 0.70, 4),
        "extra": {"mfu": round(fpi * ips / peak, 4),
                  "step_ms": round(dt / iters * 1e3, 2),
                  "loss": round(final, 4), "params": n,
                  **_mon_fields(mon)},
    })


def bench_swin(on_tpu):
    """Swin-T/B (BASELINE.md config) training throughput — batched window
    attention on the MXU."""
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.jit.train_step import TrainStep
    from paddle_tpu.vision.models import swin_t, swin_b
    import paddle_tpu.nn as nn

    B, iters = (32, 8) if on_tpu else (2, 2)
    preset = os.environ.get("PADDLE_TPU_BENCH_PRESET", "swin-t")
    builder = swin_b if preset == "swin-b" else swin_t
    prev_cl, use_cl = _channels_last_ctx(on_tpu)
    try:
        paddle.seed(0)
        if on_tpu:
            model = builder(num_classes=1000)
            model.to(dtype="bfloat16")
            hw = 224
        else:
            from paddle_tpu.vision.models import SwinTransformer
            model = SwinTransformer(image_size=32, patch_size=2, embed_dim=16,
                                    depths=(2, 2), num_heads=(2, 4),
                                    window_size=4, num_classes=10)
            hw = 32
        ce = nn.CrossEntropyLoss()
        opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                     parameters=model.parameters(),
                                     moment_dtype="bfloat16" if on_tpu
                                     else "float32")
        step = TrainStep(model, opt, lambda x, y: ce(model(x), y))
        imgs = paddle.to_tensor(np.random.randn(iters, B, 3, hw, hw).astype(
            "bfloat16" if on_tpu else "float32"))
        ncls = 1000 if on_tpu else 10
        lbls = paddle.to_tensor(
            np.random.randint(0, ncls, (iters, B)).astype("int64"))
        dt, final, mon = _timed_steps(step, iters, imgs, lbls)
    finally:
        paddle.set_flags({"FLAGS_conv_channels_last": prev_cl})
    ips = B * iters / dt
    # swin-t 224²: ~4.5 GMACs fwd -> 9.0e9 FLOPs at MAC=2 (same convention
    # as the resnet row); swin-b ~15.4 GMACs. Train ≈ 3x fwd. Swin is
    # dispatch/relayout-bound, not MXU-bound — img/s is the primary metric,
    # mfu is reported for the ladder's common scale.
    import jax as _jax
    # off-TPU smoke runs a tiny stand-in model, so the swin-t/b FLOP
    # constants would fabricate an mfu — report it on TPU only
    mfu = None
    if on_tpu:
        fwd_flops = 30.8e9 if preset == "swin-b" else 9.0e9
        mfu = 3 * fwd_flops * ips / _chip_peak_flops(_jax.devices()[0])
    return _emit({
        "metric": f"images/sec/chip ({preset} train, B={B} {hw}x{hw}"
                  f"{' nhwc' if use_cl else ''})",
        "value": round(ips, 1), "unit": "images/s",
        "vs_baseline": None if mfu is None else round(mfu / 0.70, 4),
        "extra": {"mfu": None if mfu is None else round(mfu, 4),
                  "step_ms": round(dt / iters * 1e3, 2),
                  "channels_last": use_cl,
                  "loss": round(final, 4),
                  **_mon_fields(mon)},
    })


def _bench_gpt27(on_tpu):
    # best measured r3 point: B=6 S=1024 int8 moments + save_qkv remat
    # (S=2048 at B=6 does NOT fit the 16G chip)
    return bench_gpt(on_tpu, preset="gpt3-2.7b", B=6, S=1024,
                     recompute="save_qkv", moment_dtype="int8",
                     q8_emb=False, iters=6)


def bench_gpt_dp(on_tpu):
    """Data-parallel GPT pretraining with quantized gradient sync (ISSUE
    20): the same config run three ways — single chip, dp with explicit
    per-layer-group f32 gradient all-reduces, and dp with the int8
    factored-scale sync (`TrainStep(grad_comm="int8")`). The row value is
    the int8-sync tok/s; extras carry scaling efficiency both ways, the
    per-run overlap ratio and EXPOSED collective seconds from a captured
    trace, and the static gradient-sync bytes of both dp twins. Exit-1
    gates: static sync bytes >= 3.5x under the f32 twin, CommPlan
    compliance (zero f32-gradient-all-reduce escapes), int8 exposed time
    / overlap ratio no worse than the f32 twin, zero steady recompiles.
    On CPU the trace has no device lanes; the analyzer's host-lane
    fallback still yields real overlap/exposed figures, but scheduler
    noise is large — the timing gates get wide CPU tolerances while the
    static-bytes and plan gates stay exact everywhere."""
    import shutil
    import tempfile
    import numpy as np

    # a CPU host gets a virtual multi-device backend when nothing
    # initialized one yet (XLA reads XLA_FLAGS at first backend init)
    if not on_tpu and "--xla_force_host_platform_device_count" not in \
            os.environ.get("XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8")
    import jax
    import paddle_tpu as paddle
    import paddle_tpu.distributed as dist
    from paddle_tpu.jit.train_step import TrainStep
    from paddle_tpu.jit.api import compile_cache_misses
    from paddle_tpu.analysis import train_comm_plan
    from paddle_tpu.profiler.trace_analysis import analyze
    from paddle_tpu.models import GPTForCausalLM, GPTConfig, gpt_config

    dp = len(jax.devices())
    if dp < 2:
        return _emit({
            "metric": "dp pretrain int8-gradient-sync tokens/sec",
            "value": None, "unit": "tokens/s", "vs_baseline": None,
            "extra": {"reason": f"{dp} device(s): no dp axis available"}})

    if on_tpu:
        # per-chip point = the best measured single-chip 2.7B config
        # (_bench_gpt27): B=6 S=1024, save_qkv remat, int8 moments
        preset, B1, S, iters = "gpt3-2.7b", 6, 1024, 6
        cfg = gpt_config(preset, max_position_embeddings=max(1024, S))
        cfg.use_recompute = True
        cfg.recompute_policy = "save_qkv"
        moment_dtype = "int8"
    else:  # CPU smoke: toy dims, 8 virtual devices
        preset, B1, S, iters = None, 1, 64, 3
        cfg = GPTConfig(vocab_size=512, hidden_size=256, num_layers=4,
                        num_heads=8, max_position_embeddings=64,
                        intermediate_size=1024)
        moment_dtype = "float32"
    np.random.seed(0)

    def make(mesh, mode):
        paddle.seed(0)
        m = GPTForCausalLM(cfg)
        if on_tpu:
            m.to(dtype="bfloat16")
        o = paddle.optimizer.AdamW(learning_rate=1e-4,
                                   parameters=m.parameters(),
                                   moment_dtype=moment_dtype)
        st = TrainStep(m, o,
                       lambda a, b: m.loss(a, b, chunk_size=512),
                       mesh=mesh, grad_comm=mode)
        return m, st

    def ar_bytes(audit):
        return sum(r.get("bytes") or 0 for r in audit.rows
                   if r.get("kind") == "all-reduce")

    def run(mesh, mode, Bx, plan=None):
        """One configuration: fenced throughput + steady-recompile count,
        and for dp runs a captured trace (overlap/exposed) + the static
        collective audit (+ CommPlan findings when a plan is given)."""
        dist.set_mesh(mesh)
        try:
            m, st = make(mesh, mode)
            data = np.random.randint(0, cfg.vocab_size,
                                     (iters, Bx, S)).astype("int32")
            stacked = paddle.to_tensor(data)
            # settle every executable BEFORE the miss snapshot so the
            # timed reps prove the steady state never recompiles
            _ = float(st.run_steps(iters, stacked, stacked).numpy()[-1])
            miss0 = compile_cache_misses()
            dt, final, mon = _timed_steps(st, iters, stacked, stacked)
            out = {"tok_s": Bx * S * iters / dt,
                   "step_ms": dt / iters * 1e3, "loss": final,
                   "steady_recompiles": compile_cache_misses() - miss0,
                   **_mon_fields(mon)}
            if mesh is not None:
                td = tempfile.mkdtemp(prefix=f"bench_dp_{mode}_")
                try:
                    with jax.profiler.trace(td):
                        _ = float(st.run_steps(iters, stacked,
                                               stacked).numpy()[-1])
                    an = analyze(td, steps=iters)
                finally:
                    shutil.rmtree(td, ignore_errors=True)
                ov = an.overlap()
                out["overlap_ratio"] = ov["ratio"]
                out["exposed_s"] = sum(
                    r["exposed_us"] for r in an.collective_rows()
                    if r.get("exposed_us") is not None) / 1e6
                sds = jax.ShapeDtypeStruct((Bx, S), "int32")
                audit = st.sharding_audit(sds, sds, plan=plan)
                out["grad_sync_bytes"] = ar_bytes(audit)
                out["plan_findings"] = [
                    str(f) for f in audit.findings.for_pass("comm_plan")] \
                    if plan is not None else None
                out["n_groups"] = len(st._comm_groups)
            return out
        finally:
            dist.set_mesh(None)

    one = run(None, None, B1)
    mesh = dist.build_mesh({"dp": dp})
    B = B1 * dp
    f32 = run(mesh, "f32", B)
    plan = train_comm_plan(f32["n_groups"], dtype="int8",
                           max_f32_bytes=max(f32["grad_sync_bytes"] // 8,
                                             1))
    i8 = run(mesh, "int8", B, plan=plan)

    ratio = (f32["grad_sync_bytes"] / i8["grad_sync_bytes"]
             if i8["grad_sync_bytes"] else None)
    # CPU: 8 virtual devices share one host's cores — timing gates get
    # wide tolerances there; static bytes + plan stay exact everywhere
    exp_tol = 1.0 if on_tpu else 1.5
    ov_tol = 0.05 if on_tpu else 0.25
    violations = []
    if ratio is None or ratio < 3.5:
        violations.append(f"static gradient-sync bytes ratio {ratio} "
                          f"< 3.5 (f32 {f32['grad_sync_bytes']} / int8 "
                          f"{i8['grad_sync_bytes']})")
    if i8["plan_findings"]:
        violations.append(f"CommPlan violations: {i8['plan_findings']}")
    for name, r in (("single", one), ("dp-f32", f32), ("dp-int8", i8)):
        if r["steady_recompiles"]:
            violations.append(f"{name}: {r['steady_recompiles']} steady "
                              f"recompile(s)")
    if i8["exposed_s"] > f32["exposed_s"] * exp_tol + 1e-3:
        violations.append(f"int8 exposed {i8['exposed_s']:.4f}s worse "
                          f"than f32 twin {f32['exposed_s']:.4f}s "
                          f"(tol x{exp_tol})")
    if (i8["overlap_ratio"] is not None
            and f32["overlap_ratio"] is not None
            and i8["overlap_ratio"] < f32["overlap_ratio"] - ov_tol):
        violations.append(f"int8 overlap ratio {i8['overlap_ratio']:.3f} "
                          f"worse than f32 twin "
                          f"{f32['overlap_ratio']:.3f} - {ov_tol}")
    if violations:
        raise RuntimeError("gpt-dp gates failed: " + "; ".join(violations))

    return _emit({
        "metric": f"tokens/sec ({preset or 'toy'} dp={dp} pretrain, int8 "
                  f"gradient sync, B={B} S={S})",
        "value": round(i8["tok_s"], 1), "unit": "tokens/s",
        "vs_baseline": round(i8["tok_s"] / f32["tok_s"], 3)
        if f32["tok_s"] else None,
        "extra": {
            "shards": dp,
            "scaling_efficiency": round(i8["tok_s"] / (dp * one["tok_s"]),
                                        3) if one["tok_s"] else None,
            "scaling_efficiency_f32": round(
                f32["tok_s"] / (dp * one["tok_s"]), 3)
            if one["tok_s"] else None,
            "single_chip_tok_s": round(one["tok_s"], 1),
            "step_ms": round(i8["step_ms"], 2),
            "overlap_ratio": round(i8["overlap_ratio"], 3)
            if i8["overlap_ratio"] is not None else None,
            "overlap_ratio_f32": round(f32["overlap_ratio"], 3)
            if f32["overlap_ratio"] is not None else None,
            "exposed_s": round(i8["exposed_s"], 4),
            "exposed_s_f32": round(f32["exposed_s"], 4),
            "grad_sync_bytes_int8": i8["grad_sync_bytes"],
            "grad_sync_bytes_f32": f32["grad_sync_bytes"],
            "grad_sync_bytes_ratio": round(ratio, 2),
            "comm_groups": i8["n_groups"],
            "loss_delta_vs_f32": round(abs(i8["loss"] - f32["loss"]), 5),
            "steady_recompiles": (one["steady_recompiles"]
                                  + f32["steady_recompiles"]
                                  + i8["steady_recompiles"]),
            "hbm_peak_bytes": i8.get("hbm_peak_bytes"),
            "recompiles": i8.get("recompiles")},
    })


_SINGLE = {
    "resnet50": bench_resnet50,
    "bert": bench_bert,
    "vit": bench_vit,
    "decode": bench_decode,
    "decode-paged": bench_decode_paged,
    "decode-paged-mp": bench_decode_paged_mp,
    "decode-paged-prefix": bench_decode_paged_prefix,
    "decode-spec": bench_decode_spec,
    "swin": bench_swin,
    "moe": bench_moe,
    "gpt": bench_gpt,
    "gpt27": _bench_gpt27,
    "gpt-2.7b-dp": bench_gpt_dp,
}


def _ladder(on_tpu):
    """All rows, importance-ordered, time-budgeted; one JSON line each plus
    a final flagship line with the ladder embedded (the driver parses the
    last line of stdout)."""
    import gc
    budget = float(os.environ.get("PADDLE_TPU_BENCH_BUDGET_S", "2100"))
    t0 = time.perf_counter()
    rows = []

    def left():
        return budget - (time.perf_counter() - t0)

    plan = [
        ("gpt-1.3b", lambda: bench_gpt(on_tpu), 0),
        ("vit-l16", lambda: bench_vit(on_tpu), 120),
        ("bert-base", lambda: bench_bert(on_tpu), 120),
        ("decode", lambda: bench_decode(on_tpu), 120),
        # serving rows (VERDICT r4 #5): int8 weight-only at the latency
        # point, bf16 at the throughput point
        # int8 weights + int8 KV cache: B=8 3.46 -> 3.00 ms/step (the KV
        # read is the residual bandwidth term once weights are int8)
        ("decode-int8-b8", lambda: bench_decode(on_tpu, B=8, w8=True,
                                                c8=True, marginal=True),
         220),
        ("decode-b32", lambda: bench_decode(on_tpu, B=32, w8=False), 120),
        # paged KV serving (ISSUE 5): block-pool engine vs the padded
        # twin on long-tail traffic + the decode_static donation saving
        ("decode-paged", lambda: bench_decode_paged(on_tpu), 180),
        # multi-chip sharded serving (ISSUE 16): head-sharded pools,
        # tensor-parallel decode over the mp mesh vs the 1-chip twin
        ("decode-paged-mp", lambda: bench_decode_paged_mp(on_tpu), 200),
        # prefix cache (ISSUE 10): shared-prefix traffic, radix-trie
        # block sharing off vs on — hit rate + prefill-tokens-saved
        ("decode-paged-prefix",
         lambda: bench_decode_paged_prefix(on_tpu), 180),
        # speculative decoding (ISSUE 11): trie-drafted draft-verify at
        # the latency point (B=8) vs the plain paged twin + acceptance
        ("decode-spec", lambda: bench_decode_spec(on_tpu), 180),
        ("moe", lambda: bench_moe(on_tpu), 240),
        # the SHIPPED default capacity (GShard 1.25) stays driver-tracked;
        # its dense twin is reused from the cf=1.0 row, so this pays only
        # the MoE model's compile+steps (ADVICE r5)
        ("moe-cf125", lambda: bench_moe(on_tpu, cf=1.25), 150),
        ("resnet50", lambda: bench_resnet50(on_tpu), 150),
        # model-scale depth rows (cheap; measured r4: 49.3% / 67.5%)
        ("bert-large", lambda: bench_bert(on_tpu, preset="bert-large"), 150),
        ("vit-h14", lambda: bench_vit(on_tpu, preset="vit-h14"), 150),
        # swin-t: window-batched fused-bias attention (r5; 655->829 img/s)
        ("swin-t", lambda: bench_swin(on_tpu), 150),
        # long-context point (SURVEY §5.7): flash attention keeps S=4096
        # MXU-bound — driver-captures the long-context claim (r5: 73.4%)
        ("gpt-s4096", lambda: bench_gpt(on_tpu, B=2, S=4096), 180),
        # 2.7B last: longest compile; config = best measured r3 point
        ("gpt-2.7b", lambda: _bench_gpt27(on_tpu), 420),
        # dp scale-out (ISSUE 20): the 2.7B point data-parallel with the
        # int8 factored-scale gradient sync vs its f32 twin — scaling
        # efficiency, overlap/exposed from a captured trace, and the
        # static sync-bytes ratio, all exit-1 gated inside the row
        ("gpt-2.7b-dp", lambda: bench_gpt_dp(on_tpu), 420),
    ]
    flagship = None
    for name, fn, need in plan:
        if left() < need:
            _emit({"metric": f"ladder-skip {name}", "value": None,
                   "unit": None, "vs_baseline": None,
                   "extra": {"reason": f"budget: {left():.0f}s left, "
                                       f"needs ~{need}s"}})
            continue
        try:
            row = fn()
            row["extra"]["row"] = name
            rows.append(row)
            if name == "gpt-1.3b":
                flagship = row
        except Exception as e:  # a failing row must not kill the ladder
            _emit({"metric": f"ladder-error {name}", "value": None,
                   "unit": None, "vs_baseline": None,
                   "extra": {"error": f"{type(e).__name__}: {e}"[:300]}})
        gc.collect()

    if flagship is not None:
        final = dict(flagship)
        final["extra"] = dict(flagship["extra"])
        final["extra"]["ladder"] = [
            {"row": r["extra"].get("row"), "metric": r["metric"],
             "value": r["value"], "unit": r["unit"],
             "vs_baseline": r["vs_baseline"],
             "mfu": r["extra"].get("mfu"),
             "step_ms": r["extra"].get("step_ms"),
             # decode rows: steady-state rate + fixed launch cost (the
             # driver parses only this last line — keep the serving
             # metric visible in it)
             **({"marginal_tok_s": r["extra"]["marginal_tok_s"],
                 "fixed_launch_ms": r["extra"]["fixed_launch_ms"]}
                if "marginal_tok_s" in r["extra"] else {})}
            for r in rows]
        final["extra"]["ladder_wall_s"] = round(time.perf_counter() - t0, 1)
        _emit(final)
    else:
        # the flagship row failed: say so explicitly in the LAST line so
        # the driver cannot silently adopt another row as the headline
        _emit({"metric": "FLAGSHIP-FAILED (gpt-1.3b row errored; see "
                         "ladder-error line above)", "value": None,
               "unit": None, "vs_baseline": None,
               "extra": {"ladder": [
                   {"row": r["extra"].get("row"), "metric": r["metric"],
                    "value": r["value"], "vs_baseline": r["vs_baseline"]}
                   for r in rows]}})


def main():
    which = os.environ.get("PADDLE_TPU_BENCH_MODEL")
    # the sharded rows need a multi-device backend BEFORE first init;
    # scoped to those rows so every other row keeps its 1-device CPU smoke
    if which in ("decode-paged-mp", "gpt-2.7b-dp") and \
            "--xla_force_host_platform_device_count" not in \
            os.environ.get("XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8")
    import jax

    devs = jax.devices()
    on_tpu = devs[0].platform in ("tpu", "axon")

    if which:
        fn = _SINGLE.get(which)
        if fn is None:
            sys.exit(f"unknown PADDLE_TPU_BENCH_MODEL={which!r}; valid rows: "
                     f"{', '.join(sorted(_SINGLE))}")
        return fn(on_tpu)
    if not on_tpu:
        # CPU smoke: single flagship row (the driver runs the ladder on TPU)
        return bench_gpt(on_tpu)
    _ladder(on_tpu)


if __name__ == "__main__":
    main()
