"""Bijective transforms (reference: python/paddle/distribution/transform.py).

Subclasses implement raw-jnp `_forward/_inverse/_forward_log_det_jacobian`;
the public wrappers route through the autograd tape (differentiable w.r.t.
the input value; transform parameters passed as Tensors also join the tape).
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from .distribution import _as_param, _data, _op

__all__ = ["Transform", "AbsTransform", "AffineTransform", "ChainTransform",
           "ExpTransform", "PowerTransform", "SigmoidTransform",
           "SoftmaxTransform", "StickBreakingTransform", "TanhTransform"]


class Transform:
    """reference transform.py:60 Transform base."""

    _codomain_event_rank = 0

    def forward(self, x):
        return _op(f"{type(self).__name__}.fwd", self._forward, x)

    def inverse(self, y):
        return _op(f"{type(self).__name__}.inv", self._inverse, y)

    def forward_log_det_jacobian(self, x):
        return _op(f"{type(self).__name__}.fldj",
                   self._forward_log_det_jacobian, x)

    def inverse_log_det_jacobian(self, y):
        return _op(f"{type(self).__name__}.ildj",
                   lambda yy: -self._forward_log_det_jacobian(self._inverse(yy)),
                   y)

    def __call__(self, x):
        return self.forward(x)

    def _forward(self, x):
        raise NotImplementedError

    def _inverse(self, y):
        raise NotImplementedError

    def _forward_log_det_jacobian(self, x):
        raise NotImplementedError


class AffineTransform(Transform):
    def __init__(self, loc, scale):
        self.loc = _as_param(loc)
        self.scale = _as_param(scale)

    # params join the tape in the public wrappers
    def forward(self, x):
        return _op("affine_fwd", lambda l, s, v: l + s * v,
                   self.loc, self.scale, x)

    def inverse(self, y):
        return _op("affine_inv", lambda l, s, v: (v - l) / s,
                   self.loc, self.scale, y)

    def forward_log_det_jacobian(self, x):
        return _op("affine_fldj",
                   lambda s, v: jnp.broadcast_to(jnp.log(jnp.abs(s)),
                                                 jnp.shape(v)),
                   self.scale, x)

    def _forward(self, x):
        return _data(self.loc) + _data(self.scale) * x

    def _inverse(self, y):
        return (y - _data(self.loc)) / _data(self.scale)

    def _forward_log_det_jacobian(self, x):
        return jnp.broadcast_to(jnp.log(jnp.abs(_data(self.scale))),
                                jnp.shape(x))


class ExpTransform(Transform):
    def _forward(self, x):
        return jnp.exp(x)

    def _inverse(self, y):
        return jnp.log(y)

    def _forward_log_det_jacobian(self, x):
        return x


class PowerTransform(Transform):
    def __init__(self, power):
        self.power = _as_param(power)

    def _forward(self, x):
        return jnp.power(x, _data(self.power))

    def _inverse(self, y):
        return jnp.power(y, 1.0 / _data(self.power))

    def _forward_log_det_jacobian(self, x):
        p = _data(self.power)
        return jnp.log(jnp.abs(p * jnp.power(x, p - 1)))


class AbsTransform(Transform):
    def _forward(self, x):
        return jnp.abs(x)

    def _inverse(self, y):
        return y  # principal branch

    def _forward_log_det_jacobian(self, x):
        return jnp.zeros_like(x)


class SigmoidTransform(Transform):
    def _forward(self, x):
        return jax.nn.sigmoid(x)

    def _inverse(self, y):
        return jnp.log(y) - jnp.log1p(-y)

    def _forward_log_det_jacobian(self, x):
        return -jax.nn.softplus(-x) - jax.nn.softplus(x)


class TanhTransform(Transform):
    def _forward(self, x):
        return jnp.tanh(x)

    def _inverse(self, y):
        return jnp.arctanh(y)

    def _forward_log_det_jacobian(self, x):
        return 2.0 * (math.log(2.0) - x - jax.nn.softplus(-2.0 * x))


class SoftmaxTransform(Transform):
    """Not bijective on R^n; defined on the reference's convention."""

    _codomain_event_rank = 1

    def _forward(self, x):
        return jax.nn.softmax(x, axis=-1)

    def _inverse(self, y):
        return jnp.log(y)


class StickBreakingTransform(Transform):
    """reference transform.py StickBreakingTransform: R^{K-1} -> simplex^K."""

    _codomain_event_rank = 1

    def _forward(self, x):
        k = x.shape[-1]
        offset = jnp.log(jnp.arange(k, 0, -1, dtype=x.dtype))
        z = jax.nn.sigmoid(x - offset)
        zcum = jnp.cumprod(1 - z, axis=-1)
        pad = jnp.ones_like(z[..., :1])
        return jnp.concatenate([z, pad], -1) * jnp.concatenate([pad, zcum], -1)

    def _inverse(self, y):
        ycum = jnp.cumsum(y[..., :-1], axis=-1)
        rem = 1 - jnp.concatenate([jnp.zeros_like(ycum[..., :1]),
                                   ycum[..., :-1]], -1)
        z = y[..., :-1] / rem
        k = z.shape[-1]
        offset = jnp.log(jnp.arange(k, 0, -1, dtype=y.dtype))
        return jnp.log(z) - jnp.log1p(-z) + offset

    def _forward_log_det_jacobian(self, x):
        k = x.shape[-1]
        offset = jnp.log(jnp.arange(k, 0, -1, dtype=x.dtype))
        xo = x - offset
        z = jax.nn.sigmoid(xo)
        # triangular Jacobian: det = prod_i z_i(1-z_i) * prod_{j<i}(1-z_j);
        # the cross term is the sum of all log1p(-z) prefix sums
        detail = jnp.log(z) + jnp.log1p(-z)
        if k > 1:
            zcum = jnp.cumsum(jnp.log1p(-z[..., :-1]), axis=-1)
            return detail.sum(-1) + zcum.sum(-1)
        return detail.sum(-1)


class ChainTransform(Transform):
    """Composes via the child transforms' PUBLIC (tape-aware) methods so
    parameters of member transforms (e.g. a trainable AffineTransform) keep
    their gradients inside TransformedDistribution."""

    def __init__(self, transforms):
        self.transforms = list(transforms)

    def forward(self, x):
        for t in self.transforms:
            x = t.forward(x)
        return x

    def inverse(self, y):
        for t in reversed(self.transforms):
            y = t.inverse(y)
        return y

    def forward_log_det_jacobian(self, x):
        total = None
        for t in self.transforms:
            ldj = t.forward_log_det_jacobian(x)
            total = ldj if total is None else total + ldj
            x = t.forward(x)
        return total

    def inverse_log_det_jacobian(self, y):
        return -self.forward_log_det_jacobian(self.inverse(y))

    def _forward(self, x):
        for t in self.transforms:
            x = t._forward(x)
        return x

    def _inverse(self, y):
        for t in reversed(self.transforms):
            y = t._inverse(y)
        return y

    def _forward_log_det_jacobian(self, x):
        total = 0.0
        for t in self.transforms:
            total = total + t._forward_log_det_jacobian(x)
            x = t._forward(x)
        return total
