"""KL divergence registry (reference: python/paddle/distribution/kl.py:33
kl_divergence + register_kl double-dispatch). All pairs differentiable w.r.t.
both distributions' parameters via the apply_op tape bridge."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .distribution import _op

_KL_REGISTRY = {}


def register_kl(p_cls, q_cls):
    def decorator(fn):
        _KL_REGISTRY[(p_cls, q_cls)] = fn
        return fn
    return decorator


def kl_divergence(p, q):
    # most-specific match by MRO distance, like the reference's dispatch
    best, best_score = None, None
    for (pc, qc), fn in _KL_REGISTRY.items():
        if isinstance(p, pc) and isinstance(q, qc):
            score = (type(p).__mro__.index(pc), type(q).__mro__.index(qc))
            if best_score is None or score < best_score:
                best, best_score = fn, score
    if best is None:
        raise NotImplementedError(
            f"no KL registered for ({type(p).__name__}, {type(q).__name__})")
    return best(p, q)


# -- standard pairs -------------------------------------------------------
from .normal import Normal  # noqa: E402
from .uniform import Uniform  # noqa: E402
from .categorical import Categorical, Bernoulli  # noqa: E402
from .beta import Beta, Dirichlet, Gamma  # noqa: E402
from .exponential import Exponential, Laplace  # noqa: E402

_lgamma = jax.scipy.special.gammaln
_digamma = jax.scipy.special.digamma


@register_kl(Normal, Normal)
def _kl_normal(p, q):
    def f(pl, ps, ql, qs):
        var_ratio = (ps / qs) ** 2
        t1 = ((pl - ql) / qs) ** 2
        return 0.5 * (var_ratio + t1 - 1 - jnp.log(var_ratio))
    return _op("kl_normal", f, p.loc, p.scale, q.loc, q.scale)


@register_kl(Uniform, Uniform)
def _kl_uniform(p, q):
    def f(plo, phi, qlo, qhi):
        result = jnp.log((qhi - qlo) / (phi - plo))
        outside = (qlo > plo) | (qhi < phi)
        return jnp.where(outside, jnp.inf, result)
    return _op("kl_uniform", f, p.low, p.high, q.low, q.high)


@register_kl(Categorical, Categorical)
def _kl_categorical(p, q):
    return _op("kl_categorical",
               lambda pl, ql: (jnp.exp(pl) * (pl - ql)).sum(-1),
               p.logits, q.logits)


@register_kl(Bernoulli, Bernoulli)
def _kl_bernoulli(p, q):
    def f(pp, qp):
        return pp * (jnp.log(pp) - jnp.log(qp)) \
            + (1 - pp) * (jnp.log1p(-pp) - jnp.log1p(-qp))
    return _op("kl_bernoulli", f, p.probs, q.probs)


@register_kl(Beta, Beta)
def _kl_beta(p, q):
    def f(pa, pb, qa, qb):
        sp = pa + pb
        sq = qa + qb
        t = (_lgamma(sp) - _lgamma(pa) - _lgamma(pb)
             - _lgamma(sq) + _lgamma(qa) + _lgamma(qb))
        return t + (pa - qa) * _digamma(pa) + (pb - qb) * _digamma(pb) \
            + (sq - sp) * _digamma(sp)
    return _op("kl_beta", f, p.alpha, p.beta, q.alpha, q.beta)


@register_kl(Dirichlet, Dirichlet)
def _kl_dirichlet(p, q):
    def f(a, b):
        a0 = a.sum(-1)
        t = _lgamma(a0) - _lgamma(a).sum(-1) - _lgamma(b.sum(-1)) \
            + _lgamma(b).sum(-1)
        return t + ((a - b) * (_digamma(a) - _digamma(a0)[..., None])).sum(-1)
    return _op("kl_dirichlet", f, p.concentration, q.concentration)


@register_kl(Gamma, Gamma)
def _kl_gamma(p, q):
    def f(a, b, c, d):
        return (a - c) * _digamma(a) - _lgamma(a) + _lgamma(c) \
            + c * (jnp.log(b) - jnp.log(d)) + a * (d / b - 1)
    return _op("kl_gamma", f, p.concentration, p.rate, q.concentration, q.rate)


@register_kl(Exponential, Exponential)
def _kl_exponential(p, q):
    return _op("kl_exponential",
               lambda pr, qr: jnp.log(pr) - jnp.log(qr) + qr / pr - 1,
               p.rate, q.rate)


@register_kl(Laplace, Laplace)
def _kl_laplace(p, q):
    def f(pl, ps, ql, qs):
        scale_ratio = ps / qs
        loc_abs = jnp.abs(pl - ql) / qs
        return -jnp.log(scale_ratio) + scale_ratio \
            * jnp.exp(-loc_abs / scale_ratio) + loc_abs - 1
    return _op("kl_laplace", f, p.loc, p.scale, q.loc, q.scale)
