"""Uniform (reference: python/paddle/distribution/uniform.py:31)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core import random as _random
from .distribution import Distribution, _as_param, _data, _op


class Uniform(Distribution):
    def __init__(self, low, high, name=None):
        self.low = _as_param(low)
        self.high = _as_param(high)
        shape = jnp.broadcast_shapes(jnp.shape(_data(self.low)),
                                     jnp.shape(_data(self.high)))
        super().__init__(batch_shape=shape)

    @property
    def mean(self):
        shp = self._batch_shape
        return _op("uniform_mean",
                   lambda lo, hi: jnp.broadcast_to((lo + hi) / 2, shp),
                   self.low, self.high)

    @property
    def variance(self):
        shp = self._batch_shape
        return _op("uniform_var",
                   lambda lo, hi: jnp.broadcast_to((hi - lo) ** 2 / 12, shp),
                   self.low, self.high)

    def rsample(self, shape=()):
        u = jax.random.uniform(_random.split_key(), self._extend_shape(shape))
        return _op("uniform_rsample", lambda lo, hi: lo + (hi - lo) * u,
                   self.low, self.high)

    def log_prob(self, value):
        return _op("uniform_log_prob",
                   lambda lo, hi, v: jnp.where((v >= lo) & (v < hi),
                                               -jnp.log(hi - lo), -jnp.inf),
                   self.low, self.high, value)

    def entropy(self):
        shp = self._batch_shape
        return _op("uniform_entropy",
                   lambda lo, hi: jnp.broadcast_to(jnp.log(hi - lo), shp),
                   self.low, self.high)

    def cdf(self, value):
        return _op("uniform_cdf",
                   lambda lo, hi, v: jnp.clip((v - lo) / (hi - lo), 0, 1),
                   self.low, self.high, value)
