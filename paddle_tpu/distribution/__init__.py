"""paddle.distribution analog (reference: python/paddle/distribution/).

Distributions are thin stateless wrappers over jax.scipy/jax.random:
sample() draws with the global splittable key (explicit-key overloads for
jitted code), log_prob/entropy are pure jnp — fully traceable under jit.
"""
from .distribution import Distribution
from .normal import Normal, LogNormal
from .uniform import Uniform
from .categorical import Categorical, Multinomial, Bernoulli
from .beta import Beta, Dirichlet, Gamma
from .exponential import Exponential, Laplace, Gumbel, ExponentialFamily
from .transformed import TransformedDistribution
from . import transform
from .transform import (AbsTransform, AffineTransform, ChainTransform,
                        ExpTransform, PowerTransform, SigmoidTransform,
                        SoftmaxTransform, StickBreakingTransform, TanhTransform,
                        Transform)
from .kl import kl_divergence, register_kl

__all__ = [
    "Distribution", "Normal", "LogNormal", "Uniform", "Categorical",
    "Multinomial", "Bernoulli", "Beta", "Dirichlet", "Gamma", "Exponential",
    "Laplace", "Gumbel", "ExponentialFamily", "TransformedDistribution",
    "Transform", "AbsTransform", "AffineTransform", "ChainTransform",
    "ExpTransform", "PowerTransform", "SigmoidTransform", "SoftmaxTransform",
    "StickBreakingTransform", "TanhTransform", "kl_divergence", "register_kl",
    "transform",
]

from .distribution import Distribution as _D


class Independent(_D):
    """reference: distribution/independent.py — reinterprets `n` rightmost
    batch dims of a base distribution as event dims (sums log_prob over
    them)."""

    def __init__(self, base, reinterpreted_batch_rank):
        self._base = base
        self._rank = int(reinterpreted_batch_rank)
        batch = tuple(getattr(base, "batch_shape", ()) or ())
        if self._rank > len(batch):
            raise ValueError(
                f"reinterpreted_batch_rank {self._rank} exceeds base batch "
                f"rank {len(batch)}")
        split = len(batch) - self._rank
        super().__init__(batch_shape=batch[:split],
                         event_shape=batch[split:] + tuple(
                             getattr(base, "event_shape", ()) or ()))

    def sample(self, shape=()):
        return self._base.sample(shape)

    def rsample(self, shape=()):
        return self._base.rsample(shape)

    @property
    def mean(self):
        return self._base.mean

    @property
    def variance(self):
        return self._base.variance

    def log_prob(self, value):
        from ..core import ops
        lp = self._base.log_prob(value)
        for _ in range(self._rank):
            lp = ops.sum(lp, axis=-1)
        return lp

    def entropy(self):
        from ..core import ops
        e = self._base.entropy()
        for _ in range(self._rank):
            e = ops.sum(e, axis=-1)
        return e

    def prob(self, value):
        from ..core import ops
        return ops.exp(self.log_prob(value))
