"""paddle.distribution analog (reference: python/paddle/distribution/).

Distributions are thin stateless wrappers over jax.scipy/jax.random:
sample() draws with the global splittable key (explicit-key overloads for
jitted code), log_prob/entropy are pure jnp — fully traceable under jit.
"""
from .distribution import Distribution
from .normal import Normal, LogNormal
from .uniform import Uniform
from .categorical import Categorical, Multinomial, Bernoulli
from .beta import Beta, Dirichlet, Gamma
from .exponential import Exponential, Laplace, Gumbel, ExponentialFamily
from .transformed import TransformedDistribution
from . import transform
from .transform import (AbsTransform, AffineTransform, ChainTransform,
                        ExpTransform, PowerTransform, SigmoidTransform,
                        SoftmaxTransform, StickBreakingTransform, TanhTransform,
                        Transform)
from .kl import kl_divergence, register_kl

__all__ = [
    "Distribution", "Normal", "LogNormal", "Uniform", "Categorical",
    "Multinomial", "Bernoulli", "Beta", "Dirichlet", "Gamma", "Exponential",
    "Laplace", "Gumbel", "ExponentialFamily", "TransformedDistribution",
    "Transform", "AbsTransform", "AffineTransform", "ChainTransform",
    "ExpTransform", "PowerTransform", "SigmoidTransform", "SoftmaxTransform",
    "StickBreakingTransform", "TanhTransform", "kl_divergence", "register_kl",
    "transform",
]
