"""Exponential / Laplace / Gumbel + ExponentialFamily base (reference:
python/paddle/distribution/{exponential,laplace,gumbel,exponential_family}.py)."""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ..core import random as _random
from .distribution import Distribution, _as_param, _data, _op

_EULER = 0.5772156649015329


class ExponentialFamily(Distribution):
    """Natural-parameter family base (reference exponential_family.py:24)."""

    @property
    def _natural_parameters(self):
        raise NotImplementedError

    def _log_normalizer(self, *natural_params):
        raise NotImplementedError


class Exponential(ExponentialFamily):
    def __init__(self, rate, name=None):
        self.rate = _as_param(rate)
        super().__init__(batch_shape=jnp.shape(_data(self.rate)))

    @property
    def mean(self):
        return _op("exponential_mean", lambda r: 1.0 / r, self.rate)

    @property
    def variance(self):
        return _op("exponential_var", lambda r: 1.0 / r ** 2, self.rate)

    def rsample(self, shape=()):
        u = jax.random.uniform(_random.split_key(), self._extend_shape(shape),
                               minval=1e-7, maxval=1.0)
        return _op("exponential_rsample", lambda r: -jnp.log(u) / r, self.rate)

    def log_prob(self, value):
        return _op("exponential_log_prob",
                   lambda r, v: jnp.log(r) - r * v, self.rate, value)

    def entropy(self):
        return _op("exponential_entropy", lambda r: 1.0 - jnp.log(r), self.rate)

    def cdf(self, value):
        return _op("exponential_cdf",
                   lambda r, v: 1 - jnp.exp(-r * v), self.rate, value)


class Laplace(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _as_param(loc)
        self.scale = _as_param(scale)
        shape = jnp.broadcast_shapes(jnp.shape(_data(self.loc)),
                                     jnp.shape(_data(self.scale)))
        super().__init__(batch_shape=shape)

    @property
    def mean(self):
        shp = self._batch_shape
        return _op("laplace_mean", lambda l: jnp.broadcast_to(l, shp), self.loc)

    @property
    def variance(self):
        shp = self._batch_shape
        return _op("laplace_var",
                   lambda s: jnp.broadcast_to(2 * s ** 2, shp), self.scale)

    @property
    def stddev(self):
        shp = self._batch_shape
        return _op("laplace_std",
                   lambda s: jnp.broadcast_to(math.sqrt(2) * s, shp), self.scale)

    def rsample(self, shape=()):
        u = jax.random.uniform(_random.split_key(), self._extend_shape(shape),
                               minval=-0.5 + 1e-7, maxval=0.5)
        return _op("laplace_rsample",
                   lambda l, s: l - s * jnp.sign(u) * jnp.log1p(-2 * jnp.abs(u)),
                   self.loc, self.scale)

    def log_prob(self, value):
        return _op("laplace_log_prob",
                   lambda l, s, v: -jnp.abs(v - l) / s - jnp.log(2 * s),
                   self.loc, self.scale, value)

    def entropy(self):
        shp = self._batch_shape
        return _op("laplace_entropy",
                   lambda s: jnp.broadcast_to(1 + jnp.log(2 * s), shp),
                   self.scale)

    def cdf(self, value):
        def f(l, s, v):
            z = (v - l) / s
            return 0.5 - 0.5 * jnp.sign(z) * jnp.expm1(-jnp.abs(z))
        return _op("laplace_cdf", f, self.loc, self.scale, value)

    def icdf(self, value):
        def f(l, s, p):
            term = p - 0.5
            return l - s * jnp.sign(term) * jnp.log1p(-2 * jnp.abs(term))
        return _op("laplace_icdf", f, self.loc, self.scale, value)


class Gumbel(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _as_param(loc)
        self.scale = _as_param(scale)
        shape = jnp.broadcast_shapes(jnp.shape(_data(self.loc)),
                                     jnp.shape(_data(self.scale)))
        super().__init__(batch_shape=shape)

    @property
    def mean(self):
        shp = self._batch_shape
        return _op("gumbel_mean",
                   lambda l, s: jnp.broadcast_to(l + s * _EULER, shp),
                   self.loc, self.scale)

    @property
    def variance(self):
        shp = self._batch_shape
        return _op("gumbel_var",
                   lambda s: jnp.broadcast_to((math.pi ** 2 / 6) * s ** 2, shp),
                   self.scale)

    @property
    def stddev(self):
        return _op("sqrt", jnp.sqrt, self.variance)

    def rsample(self, shape=()):
        g = jax.random.gumbel(_random.split_key(), self._extend_shape(shape))
        return _op("gumbel_rsample", lambda l, s: l + s * g, self.loc, self.scale)

    def log_prob(self, value):
        def f(l, s, v):
            z = (v - l) / s
            return -(z + jnp.exp(-z)) - jnp.log(s)
        return _op("gumbel_log_prob", f, self.loc, self.scale, value)

    def entropy(self):
        shp = self._batch_shape
        return _op("gumbel_entropy",
                   lambda s: jnp.broadcast_to(jnp.log(s) + 1 + _EULER, shp),
                   self.scale)

    def cdf(self, value):
        return _op("gumbel_cdf",
                   lambda l, s, v: jnp.exp(-jnp.exp(-(v - l) / s)),
                   self.loc, self.scale, value)
