"""Distribution ABC (reference: python/paddle/distribution/distribution.py:47).

Design: parameters are stored as passed (Tensor identity preserved) and every
piece of math runs through `core.tensor.apply_op`, so log_prob/rsample/
entropy/mean/variance are differentiable w.r.t. the parameters — the
reference gets this for free from building on paddle ops; here the tape
records one fused vjp node per method call (cheaper than op-by-op).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor, apply_op


def _data(x):
    """Raw jnp array view (for shapes/static decisions only)."""
    if isinstance(x, Tensor):
        return x._data
    return x if isinstance(x, jax.Array) else jnp.asarray(x, jnp.float32)


def _as_param(x):
    """Keep Tensors (differentiable); coerce the rest to jnp constants."""
    if isinstance(x, Tensor):
        return x
    return x if isinstance(x, jax.Array) else jnp.asarray(x, jnp.float32)


def _op(name, fn, *args):
    """Differentiable math bridge: Tensors in args join the tape."""
    return apply_op(name, fn, list(args))


class Distribution:
    def __init__(self, batch_shape=(), event_shape=()):
        self._batch_shape = tuple(batch_shape)
        self._event_shape = tuple(event_shape)

    @property
    def batch_shape(self):
        return self._batch_shape

    @property
    def event_shape(self):
        return self._event_shape

    @property
    def mean(self):
        raise NotImplementedError

    @property
    def variance(self):
        raise NotImplementedError

    def sample(self, shape=()):
        """Non-reparameterised draw (no gradient)."""
        from ..core import autograd
        with autograd.no_grad():
            return self.rsample(shape)

    def rsample(self, shape=()):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def prob(self, value):
        return _op("prob", jnp.exp, self.log_prob(value))

    def entropy(self):
        raise NotImplementedError

    def kl_divergence(self, other):
        from .kl import kl_divergence
        return kl_divergence(self, other)

    def _extend_shape(self, sample_shape):
        return tuple(sample_shape) + self._batch_shape + self._event_shape
