"""Normal / LogNormal (reference: python/paddle/distribution/normal.py:30)."""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ..core import random as _random
from .distribution import Distribution, _as_param, _data, _op

_LOG_2PI = math.log(2 * math.pi)


class Normal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _as_param(loc)
        self.scale = _as_param(scale)
        shape = jnp.broadcast_shapes(jnp.shape(_data(self.loc)),
                                     jnp.shape(_data(self.scale)))
        super().__init__(batch_shape=shape)

    @property
    def mean(self):
        shp = self._batch_shape
        return _op("normal_mean", lambda l: jnp.broadcast_to(l, shp), self.loc)

    @property
    def variance(self):
        shp = self._batch_shape
        return _op("normal_var", lambda s: jnp.broadcast_to(s ** 2, shp),
                   self.scale)

    @property
    def stddev(self):
        shp = self._batch_shape
        return _op("normal_std", lambda s: jnp.broadcast_to(s, shp), self.scale)

    def rsample(self, shape=()):
        eps = jax.random.normal(_random.split_key(), self._extend_shape(shape),
                                jnp.float32)
        return _op("normal_rsample", lambda l, s: l + s * eps, self.loc,
                   self.scale)

    def log_prob(self, value):
        return _op("normal_log_prob",
                   lambda l, s, v: -((v - l) ** 2) / (2 * s ** 2) - jnp.log(s)
                   - 0.5 * _LOG_2PI,
                   self.loc, self.scale, value)

    def entropy(self):
        shp = self._batch_shape
        return _op("normal_entropy",
                   lambda s: jnp.broadcast_to(0.5 + 0.5 * _LOG_2PI + jnp.log(s),
                                              shp), self.scale)

    def cdf(self, value):
        return _op("normal_cdf",
                   lambda l, s, v: 0.5 * (1 + jax.scipy.special.erf(
                       (v - l) / (s * math.sqrt(2)))),
                   self.loc, self.scale, value)

    def icdf(self, value):
        return _op("normal_icdf",
                   lambda l, s, v: l + s * math.sqrt(2)
                   * jax.scipy.special.erfinv(2 * v - 1),
                   self.loc, self.scale, value)


class LogNormal(Distribution):
    """reference lognormal.py:24 — exp-transform of Normal."""

    def __init__(self, loc, scale, name=None):
        self._base = Normal(loc, scale)
        super().__init__(batch_shape=self._base.batch_shape)
        self.loc, self.scale = self._base.loc, self._base.scale

    @property
    def mean(self):
        return _op("lognormal_mean", lambda l, s: jnp.exp(l + s ** 2 / 2),
                   self.loc, self.scale)

    @property
    def variance(self):
        return _op("lognormal_var",
                   lambda l, s: (jnp.exp(s ** 2) - 1) * jnp.exp(2 * l + s ** 2),
                   self.loc, self.scale)

    def rsample(self, shape=()):
        return _op("exp", jnp.exp, self._base.rsample(shape))

    def log_prob(self, value):
        return _op("lognormal_log_prob",
                   lambda l, s, v: -((jnp.log(v) - l) ** 2) / (2 * s ** 2)
                   - jnp.log(s) - 0.5 * _LOG_2PI - jnp.log(v),
                   self.loc, self.scale, value)

    def entropy(self):
        return _op("lognormal_entropy",
                   lambda l, s: 0.5 + 0.5 * _LOG_2PI + jnp.log(s) + l,
                   self.loc, self.scale)
