"""TransformedDistribution (reference:
python/paddle/distribution/transformed_distribution.py:23)."""
from __future__ import annotations

from .distribution import Distribution
from .transform import ChainTransform


class TransformedDistribution(Distribution):
    def __init__(self, base, transforms, name=None):
        self.base = base
        self.transform = ChainTransform(list(transforms))
        super().__init__(batch_shape=base.batch_shape,
                         event_shape=base.event_shape)

    def sample(self, shape=()):
        x = self.base.sample(shape)
        return self.transform.forward(x)

    def rsample(self, shape=()):
        x = self.base.rsample(shape)
        return self.transform.forward(x)

    def log_prob(self, value):
        # composed from tape-recorded pieces: differentiable w.r.t. value and
        # the base distribution's parameters
        x = self.transform.inverse(value)
        base_lp = self.base.log_prob(x)
        ldj = self.transform.forward_log_det_jacobian(x)
        return base_lp - ldj
