"""Categorical / Multinomial / Bernoulli (reference:
python/paddle/distribution/{categorical,multinomial,bernoulli}.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core import random as _random
from .distribution import Distribution, _as_param, _data, _op


class Categorical(Distribution):
    """reference categorical.py:31 — parameterised by unnormalised logits."""

    def __init__(self, logits=None, probs=None, name=None):
        if (logits is None) == (probs is None):
            raise ValueError("pass exactly one of logits/probs")
        if probs is not None:
            self.logits = _op("probs_to_logits",
                              lambda p: jnp.log(p / p.sum(-1, keepdims=True)),
                              _as_param(probs))
        else:
            self.logits = _op(
                "normalize_logits",
                lambda l: l - jax.scipy.special.logsumexp(l, -1, keepdims=True),
                _as_param(logits))
        super().__init__(batch_shape=jnp.shape(_data(self.logits))[:-1])
        self.num_events = jnp.shape(_data(self.logits))[-1]

    @property
    def probs(self):
        return _op("exp", jnp.exp, self.logits)

    def sample(self, shape=()):
        from ..core.tensor import Tensor
        out = jax.random.categorical(_random.split_key(), _data(self.logits),
                                     shape=tuple(shape) + self._batch_shape)
        return Tensor(out)

    def log_prob(self, value):
        idx = _data(value).astype(jnp.int32)
        return _op("categorical_log_prob",
                   lambda l: jnp.take_along_axis(l, idx[..., None],
                                                 axis=-1).squeeze(-1),
                   self.logits)

    def entropy(self):
        return _op("categorical_entropy",
                   lambda l: -(jnp.exp(l) * l).sum(-1), self.logits)

    def kl_divergence(self, other):
        from .kl import kl_divergence
        return kl_divergence(self, other)


class Bernoulli(Distribution):
    """reference bernoulli.py:40."""

    def __init__(self, probs=None, logits=None, name=None):
        if (logits is None) == (probs is None):
            raise ValueError("pass exactly one of logits/probs")
        if probs is not None:
            self._p = _op("clip_probs",
                          lambda p: jnp.clip(p, 1e-7, 1 - 1e-7),
                          _as_param(probs))
            self.logits = _op("probs_to_logits_binary",
                              lambda p: jnp.log(p) - jnp.log1p(-p), self._p)
        else:
            self.logits = _as_param(logits)
            # clip like the probs path: sigmoid saturates to exactly 0/1 in
            # f32 for |logits| > ~17, which would make log1p(-p) = -inf
            self._p = _op("sigmoid_clipped",
                          lambda l: jnp.clip(jax.nn.sigmoid(l),
                                             1e-7, 1 - 1e-7), self.logits)
        super().__init__(batch_shape=jnp.shape(_data(self._p)))

    @property
    def probs(self):
        return self._p

    @property
    def mean(self):
        return self._p

    @property
    def variance(self):
        return _op("bernoulli_var", lambda p: p * (1 - p), self._p)

    def sample(self, shape=()):
        from ..core.tensor import Tensor
        out = jax.random.bernoulli(_random.split_key(), _data(self._p),
                                   self._extend_shape(shape))
        return Tensor(out.astype(jnp.float32))

    def rsample(self, shape=(), temperature=1.0):
        """Gumbel-sigmoid relaxation (reference bernoulli.py rsample)."""
        u = jax.random.uniform(_random.split_key(), self._extend_shape(shape),
                               minval=1e-7, maxval=1 - 1e-7)
        logistic = jnp.log(u) - jnp.log1p(-u)
        return _op("bernoulli_rsample",
                   lambda l: jax.nn.sigmoid((l + logistic) / temperature),
                   self.logits)

    def log_prob(self, value):
        return _op("bernoulli_log_prob",
                   lambda p, v: v * jnp.log(p) + (1 - v) * jnp.log1p(-p),
                   self._p, value)

    def entropy(self):
        return _op("bernoulli_entropy",
                   lambda p: -(p * jnp.log(p) + (1 - p) * jnp.log1p(-p)),
                   self._p)

    def cdf(self, value):
        return _op("bernoulli_cdf",
                   lambda p, v: jnp.where(v < 0, 0.0,
                                          jnp.where(v < 1, 1 - p, 1.0)),
                   self._p, value)


class Multinomial(Distribution):
    """reference multinomial.py:25."""

    def __init__(self, total_count, probs, name=None):
        self.total_count = int(total_count)
        self._p = _op("normalize_probs",
                      lambda p: p / p.sum(-1, keepdims=True), _as_param(probs))
        shape = jnp.shape(_data(self._p))
        super().__init__(batch_shape=shape[:-1], event_shape=shape[-1:])

    @property
    def probs(self):
        return self._p

    @property
    def mean(self):
        n = self.total_count
        return _op("multinomial_mean", lambda p: n * p, self._p)

    @property
    def variance(self):
        n = self.total_count
        return _op("multinomial_var", lambda p: n * p * (1 - p), self._p)

    def sample(self, shape=()):
        from ..core.tensor import Tensor
        logits = jnp.log(_data(self._p))
        draws = jax.random.categorical(
            _random.split_key(), logits,
            shape=(self.total_count,) + tuple(shape) + self._batch_shape)
        k = self._event_shape[0]
        return Tensor(jax.nn.one_hot(draws, k).sum(0))

    def log_prob(self, value):
        n = self.total_count
        return _op(
            "multinomial_log_prob",
            lambda p, v: jax.scipy.special.gammaln(n + 1.0)
            - jax.scipy.special.gammaln(v + 1.0).sum(-1)
            + (v * jnp.log(p)).sum(-1),
            self._p, value)

    def entropy(self):
        # exact entropy has no closed form; use the categorical bound n*H(p)
        n = self.total_count
        return _op("multinomial_entropy",
                   lambda p: -n * (p * jnp.log(p)).sum(-1), self._p)
