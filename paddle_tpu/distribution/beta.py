"""Beta / Dirichlet / Gamma (reference:
python/paddle/distribution/{beta,dirichlet,gamma}.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core import random as _random
from .distribution import Distribution, _as_param, _data, _op

_lgamma = jax.scipy.special.gammaln
_digamma = jax.scipy.special.digamma


class Gamma(Distribution):
    def __init__(self, concentration, rate, name=None):
        self.concentration = _as_param(concentration)
        self.rate = _as_param(rate)
        shape = jnp.broadcast_shapes(jnp.shape(_data(self.concentration)),
                                     jnp.shape(_data(self.rate)))
        super().__init__(batch_shape=shape)

    @property
    def mean(self):
        shp = self._batch_shape
        return _op("gamma_mean", lambda a, b: jnp.broadcast_to(a / b, shp),
                   self.concentration, self.rate)

    @property
    def variance(self):
        shp = self._batch_shape
        return _op("gamma_var", lambda a, b: jnp.broadcast_to(a / b ** 2, shp),
                   self.concentration, self.rate)

    def rsample(self, shape=()):
        # jax.random.gamma is differentiable w.r.t. concentration (implicit
        # reparameterisation); route through the tape.
        key = _random.split_key()
        shp = self._extend_shape(shape)
        return _op("gamma_rsample",
                   lambda a, b: jax.random.gamma(key, a, shp) / b,
                   self.concentration, self.rate)

    def log_prob(self, value):
        return _op("gamma_log_prob",
                   lambda a, b, v: a * jnp.log(b) + (a - 1) * jnp.log(v)
                   - b * v - _lgamma(a),
                   self.concentration, self.rate, value)

    def entropy(self):
        shp = self._batch_shape
        return _op("gamma_entropy",
                   lambda a, b: jnp.broadcast_to(
                       a - jnp.log(b) + _lgamma(a) + (1 - a) * _digamma(a), shp),
                   self.concentration, self.rate)


class Beta(Distribution):
    """reference beta.py:21 — built on two Gammas."""

    def __init__(self, alpha, beta, name=None):
        self.alpha = _as_param(alpha)
        self.beta = _as_param(beta)
        shape = jnp.broadcast_shapes(jnp.shape(_data(self.alpha)),
                                     jnp.shape(_data(self.beta)))
        super().__init__(batch_shape=shape)

    @property
    def mean(self):
        shp = self._batch_shape
        return _op("beta_mean", lambda a, b: jnp.broadcast_to(a / (a + b), shp),
                   self.alpha, self.beta)

    @property
    def variance(self):
        shp = self._batch_shape
        return _op("beta_var",
                   lambda a, b: jnp.broadcast_to(
                       a * b / ((a + b) ** 2 * (a + b + 1)), shp),
                   self.alpha, self.beta)

    def rsample(self, shape=()):
        k1, k2 = jax.random.split(_random.split_key())
        shp = self._extend_shape(shape)

        def draw(a, b):
            ga = jax.random.gamma(k1, a, shp)
            gb = jax.random.gamma(k2, b, shp)
            return ga / (ga + gb)

        return _op("beta_rsample", draw, self.alpha, self.beta)

    def log_prob(self, value):
        return _op("beta_log_prob",
                   lambda a, b, v: (a - 1) * jnp.log(v) + (b - 1) * jnp.log1p(-v)
                   - (_lgamma(a) + _lgamma(b) - _lgamma(a + b)),
                   self.alpha, self.beta, value)

    def entropy(self):
        shp = self._batch_shape

        def ent(a, b):
            lbeta = _lgamma(a) + _lgamma(b) - _lgamma(a + b)
            return jnp.broadcast_to(
                lbeta - (a - 1) * _digamma(a) - (b - 1) * _digamma(b)
                + (a + b - 2) * _digamma(a + b), shp)

        return _op("beta_entropy", ent, self.alpha, self.beta)


class Dirichlet(Distribution):
    """reference dirichlet.py:20."""

    def __init__(self, concentration, name=None):
        self.concentration = _as_param(concentration)
        shape = jnp.shape(_data(self.concentration))
        super().__init__(batch_shape=shape[:-1], event_shape=shape[-1:])

    @property
    def mean(self):
        return _op("dirichlet_mean",
                   lambda a: a / a.sum(-1, keepdims=True), self.concentration)

    @property
    def variance(self):
        def var(a):
            a0 = a.sum(-1, keepdims=True)
            m = a / a0
            return m * (1 - m) / (a0 + 1)
        return _op("dirichlet_var", var, self.concentration)

    def rsample(self, shape=()):
        key = _random.split_key()
        shp = tuple(shape) + self._batch_shape
        return _op("dirichlet_rsample",
                   lambda a: jax.random.dirichlet(key, a, shp),
                   self.concentration)

    def log_prob(self, value):
        return _op("dirichlet_log_prob",
                   lambda a, v: ((a - 1) * jnp.log(v)).sum(-1)
                   - (_lgamma(a).sum(-1) - _lgamma(a.sum(-1))),
                   self.concentration, value)

    def entropy(self):
        def ent(a):
            a0 = a.sum(-1)
            k = a.shape[-1]
            lnorm = _lgamma(a).sum(-1) - _lgamma(a0)
            return lnorm + (a0 - k) * _digamma(a0) \
                - ((a - 1) * _digamma(a)).sum(-1)
        return _op("dirichlet_entropy", ent, self.concentration)
