"""paddle_tpu.profiler — tracing/profiling with the paddle.profiler API shape.

TPU-native redesign of the reference profiler (SURVEY §5.1): the reference
composes HostTracer + CUPTI CudaTracer into an event tree exported as chrome
tracing (platform/profiler/profiler.h:47, chrometracing_logger.cc), driven
from python by paddle.profiler.Profiler with a step scheduler
(profiler/profiler.py:344, make_scheduler:117). Here the device-side tracer
is jax.profiler (XLA XPlane → TensorBoard/perfetto, which subsumes CUPTI),
and the host-side RecordEvent maps to jax.profiler.TraceAnnotation so user
annotations appear inside the XLA trace. Step scheduling, the state machine
(CLOSED/READY/RECORD/RECORD_AND_RETURN), on_trace_ready callbacks and the
op-level summary surface keep the reference semantics.
"""
from __future__ import annotations

import contextlib
import enum
import json
import os
import time
from collections import defaultdict
from typing import Callable, Iterable, Optional

import jax


class ProfilerTarget(enum.Enum):
    CPU = 0
    GPU = 1      # accepted for API parity
    CUSTOM_DEVICE = 2
    TPU = 3


class ProfilerState(enum.Enum):
    """reference: profiler/profiler.py ProfilerState."""
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


def make_scheduler(*, closed: int, ready: int, record: int, repeat: int = 0,
                   skip_first: int = 0) -> Callable[[int], ProfilerState]:
    """reference: profiler/profiler.py:117 make_scheduler — cycle through
    CLOSED*closed → READY*ready → RECORD*record, repeated `repeat` times."""
    period = closed + ready + record

    def scheduler(step: int) -> ProfilerState:
        if step < skip_first:
            return ProfilerState.CLOSED
        s = step - skip_first
        if repeat > 0 and s >= repeat * period:
            return ProfilerState.CLOSED
        pos = s % period
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == period - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD
    return scheduler


def _default_scheduler(step: int) -> ProfilerState:
    return ProfilerState.RECORD


def export_chrome_tracing(dir_name: str, worker_name: str = None) -> Callable:
    """reference: profiler/profiler.py:215 — on_trace_ready callback writing
    chrome-tracing JSON of host RecordEvents (the XLA device trace lands in
    `dir_name` as an XPlane/TensorBoard trace alongside)."""

    def handler(prof: "Profiler"):
        os.makedirs(dir_name, exist_ok=True)
        name = worker_name or f"host_{os.getpid()}"
        path = os.path.join(dir_name, f"{name}_{int(time.time())}.pt.trace.json")
        events = [{
            "name": e["name"], "ph": "X", "pid": os.getpid(), "tid": 0,
            "ts": e["start"] * 1e6, "dur": (e["end"] - e["start"]) * 1e6,
            "cat": "host",
        } for e in prof._host_events]
        with open(path, "w") as f:
            json.dump({"traceEvents": events}, f)
        prof._last_export = path
    return handler


def export_protobuf(dir_name: str, worker_name: str = None) -> Callable:
    return export_chrome_tracing(dir_name, worker_name)


class RecordEvent:
    """User-scope annotation (reference: paddle.profiler.RecordEvent backed
    by platform/profiler RecordEvent instrumentation). Shows up in the XLA
    trace via TraceAnnotation AND in the host-side event list for
    summary()."""

    def __init__(self, name: str, event_type=None):
        self.name = name
        self._ann = None
        self._start = None

    def begin(self):
        self._ann = jax.profiler.TraceAnnotation(self.name)
        self._ann.__enter__()
        self._start = time.perf_counter()
        return self

    def end(self):
        if self._ann is not None:
            self._ann.__exit__(None, None, None)
            self._ann = None
        if self._start is not None and _active_profiler is not None \
                and _active_profiler._recording:
            _active_profiler._host_events.append({
                "name": self.name, "start": self._start,
                "end": time.perf_counter()})
        self._start = None

    def __enter__(self):
        return self.begin()

    def __exit__(self, *exc):
        self.end()
        return False


_active_profiler: Optional["Profiler"] = None


class Profiler:
    """reference: paddle.profiler.Profiler (profiler/profiler.py:344)."""

    def __init__(self, *, targets: Iterable[ProfilerTarget] = None,
                 scheduler=None, on_trace_ready: Callable = None,
                 timer_only: bool = False, record_shapes: bool = False,
                 profile_memory: bool = False, trace_dir: str = None):
        self.targets = list(targets) if targets else [ProfilerTarget.TPU]
        if scheduler is None:
            self._scheduler = _default_scheduler
        elif callable(scheduler):
            self._scheduler = scheduler
        else:  # (start, end) tuple form
            lo, hi = scheduler
            self._scheduler = make_scheduler(closed=lo, ready=0, record=hi - lo,
                                             repeat=1)
        self.on_trace_ready = on_trace_ready
        self.timer_only = timer_only
        self._trace_dir = trace_dir or "./profiler_log"
        self.step_num = 0
        self._state = ProfilerState.CLOSED
        self._recording = False
        self._device_tracing = False
        self._host_events = []
        self._step_times = []
        self._recorded_steps = 0
        self._step_t0 = None
        self._last_export = None

    # -- lifecycle ------------------------------------------------------
    def start(self):
        global _active_profiler
        _active_profiler = self
        self._state = self._scheduler(self.step_num)
        self._apply_state()
        self._step_t0 = time.perf_counter()
        return self

    def stop(self):
        global _active_profiler
        if self._device_tracing:
            jax.profiler.stop_trace()
            self._device_tracing = False
        if self._recording and self.on_trace_ready:
            self.on_trace_ready(self)
        self._recording = False
        self._state = ProfilerState.CLOSED
        _active_profiler = None

    def step(self, num_steps: int = 1):
        now = time.perf_counter()
        if self._step_t0 is not None:
            self._step_times.append(now - self._step_t0)
        if self._recording:
            # the step just closed ran under RECORD — these are the steps
            # inside the device capture (summary's per-step denominator)
            self._recorded_steps += num_steps
        self._step_t0 = now
        self.step_num += num_steps
        new_state = self._scheduler(self.step_num)
        if new_state != self._state:
            if self._state == ProfilerState.RECORD_AND_RETURN and self.on_trace_ready:
                self.on_trace_ready(self)
            self._state = new_state
            self._apply_state()

    def _apply_state(self):
        want_record = self._state in (ProfilerState.RECORD,
                                      ProfilerState.RECORD_AND_RETURN)
        if want_record and not self._recording:
            self._recording = True
            # new capture window: each RECORD phase writes its own trace
            # dump and summary(views=) parses only the newest, so the
            # per-step denominator restarts with it
            self._recorded_steps = 0
            if not self.timer_only:
                try:
                    os.makedirs(self._trace_dir, exist_ok=True)
                    jax.profiler.start_trace(self._trace_dir)
                    self._device_tracing = True
                except Exception:
                    self._device_tracing = False
        elif not want_record and self._recording:
            self._recording = False
            if self._device_tracing:
                jax.profiler.stop_trace()
                self._device_tracing = False

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    # -- reporting ------------------------------------------------------
    def summary(self, sorted_by=None, op_detail=True, thread_sep=False,
                time_unit="ms", views=None, steps=None) -> str:
        """Summary tables (reference: profiler_statistic.py summaries).

        Default: host-event table. With `views` (SummaryView members or
        names), device-trace tables are parsed from the capture under
        `trace_dir` via profiler.trace_analysis — KernelView gives per-op
        device time, DeviceView per-lane busy + category split,
        DistributedView collectives + the compute/comm overlap ratio.
        `steps` divides device totals into per-step figures (defaults to
        the steps counted while recording)."""
        if views is not None:
            from . import trace_analysis
            want = views if isinstance(views, (list, tuple)) else [views]
            parts = []
            device_views = [v for v in want
                            if getattr(v, "name", str(v)) != "OverView"]
            if any(getattr(v, "name", str(v)) == "OverView" for v in want):
                parts.append(self.summary(time_unit=time_unit))
            if device_views:
                if steps is None and self._recorded_steps:
                    steps = self._recorded_steps
                try:
                    parts.append(trace_analysis.summarize(
                        self._trace_dir, views=device_views, steps=steps))
                except FileNotFoundError as e:
                    parts.append(f"(no device trace: {e})")
            return "\n\n".join(parts)
        unit = {"s": 1.0, "ms": 1e3, "us": 1e6}[time_unit]
        agg = defaultdict(lambda: [0, 0.0])
        for e in self._host_events:
            a = agg[e["name"]]
            a[0] += 1
            a[1] += e["end"] - e["start"]
        lines = [f"{'Event':<40}{'Calls':>8}{'Total(' + time_unit + ')':>16}"
                 f"{'Avg(' + time_unit + ')':>16}"]
        for name, (calls, total) in sorted(agg.items(), key=lambda kv: -kv[1][1]):
            lines.append(f"{name:<40}{calls:>8}{total * unit:>16.3f}"
                         f"{total / calls * unit:>16.3f}")
        if self._step_times:
            tot = sum(self._step_times)
            lines.append(f"{'[steps] ' + str(len(self._step_times)):<40}"
                         f"{len(self._step_times):>8}{tot * unit:>16.3f}"
                         f"{tot / len(self._step_times) * unit:>16.3f}")
        return "\n".join(lines)


@contextlib.contextmanager
def profile(**kwargs):
    p = Profiler(**kwargs)
    p.start()
    try:
        yield p
    finally:
        p.stop()


class SortedKeys(enum.Enum):
    """reference: profiler/profiler_statistic.py SortedKeys — summary sort
    orders."""
    CPUTotal = 0
    CPUAvg = 1
    CPUMax = 2
    CPUMin = 3
    GPUTotal = 4
    GPUAvg = 5
    GPUMax = 6
    GPUMin = 7


class SummaryView(enum.Enum):
    """reference: profiler SummaryView — which table summary() prints."""
    DeviceView = 0
    OverView = 1
    ModelView = 2
    DistributedView = 3
    KernelView = 4
    OperatorView = 5
    MemoryView = 6
    MemoryManipulationView = 7
    UDFView = 8


def load_profiler_result(filename: str):
    """reference: profiler.load_profiler_result — reload an exported trace
    (the chrome-tracing JSON this profiler writes)."""
    import json
    with open(filename) as f:
        return json.load(f)


# -- module-scoped tracing ----------------------------------------------


class _AnnotationHandle:
    """Returned by annotate_layers; .remove() restores original forwards."""

    def __init__(self, entries, paths):
        self._entries = entries
        self.paths = paths

    def remove(self):
        for layer, prev in self._entries:
            if prev is None:
                layer.__dict__.pop("forward", None)
            else:
                layer.__dict__["forward"] = prev
        self._entries = []


def annotate_layers(model, root: str = None) -> _AnnotationHandle:
    """Wrap every sublayer's forward in a jax.profiler.TraceAnnotation named
    by its qualified layer path (e.g. `ResNet/layer1/0/conv1`) so device
    traces attribute op time to model modules — the XLA trace viewer nests
    ops under these scopes, and trace_analysis sees them as lanes.

    Returns a handle: `.paths` lists the annotation names, `.remove()`
    restores the original forwards (annotation adds a (cheap) host call per
    layer per step — remove it outside profiling windows if the model is
    sublayer-heavy)."""
    root = root or type(model).__name__
    entries, paths = [], []
    for name, layer in model.named_sublayers(include_self=True):
        path = root if not name else f"{root}/{name.replace('.', '/')}"
        prev = layer.__dict__.get("forward")  # instance-level override, if any
        fwd = layer.forward                   # bound method or override
        if getattr(fwd, "_pt_annotation", None):
            continue

        def _make(f, p):
            def annotated_forward(*args, **kwargs):
                with jax.profiler.TraceAnnotation(p):
                    return f(*args, **kwargs)
            annotated_forward._pt_annotation = p
            return annotated_forward

        layer.__dict__["forward"] = _make(fwd, path)
        entries.append((layer, prev))
        paths.append(path)
    return _AnnotationHandle(entries, paths)


from .monitor import StepMonitor, shape_delta  # noqa: E402,F401
from ._metrics import LogHistogram  # noqa: E402,F401
from . import trace_analysis  # noqa: E402,F401
from . import timeline  # noqa: E402,F401
from . import goodput  # noqa: E402,F401
from .timeline import SpanRecorder  # noqa: E402,F401
from .goodput import GoodputReport  # noqa: E402,F401
