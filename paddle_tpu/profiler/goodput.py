"""GoodputReport — aggregate a job's timeline spans into goodput% and a
per-category badput breakdown, with a conservation guarantee.

Definitions (over one or more stitched `timeline` segments):

  wall        last attributed instant − first attributed instant, across
              ALL segments — restart gaps included (that is the point).
  goodput     time inside `step` spans that were NOT re-runs of already-
              executed steps. goodput% = goodput / wall.
  badput      every other category: `compile`, `input_wait`,
              `ckpt_blocking`, `ckpt_drain`, `restart_downtime`,
              `replay`, `eval`, `other`.
  idle        wall − union(all spans): host time no seam attributed
              (python between-step overhead, un-instrumented work).

Cross-segment attribution (the restart story):

  - `replay`: a `step` span in segment N whose step index was already
    reached by an earlier segment is re-categorized as replay — work the
    job did twice because the checkpoint cadence lagged the kill. The
    replayed-STEP count additionally includes compile-span re-runs (the
    first re-executed step after a restart usually rides a fresh
    compile; its time stays `compile`, its step still counts replayed).
  - `restart_downtime`: the gap between one segment's end (its exit
    stamp, or last span when a SIGKILL outran the stamp) and the next
    segment's first span. Explicit `restart_downtime` spans (recorded by
    `fleet.elastic.run_with_restarts`) take precedence; only the
    uncovered remainder of each gap is derived, so supervisor-recorded
    and stitch-derived downtime never double count.

Conservation: by construction categorized(union) + idle == wall; the
CHECKED property is that the per-category sums tell the same story —
`sum(categories) + idle − wall` equals the spans' mutual overlap, which
must stay under ε (the seams are designed non-overlapping), and idle
must never go negative. `check_conservation()` enforces both;
tests/test_goodput.py asserts it on a real fit loop, a checkpointed
loop, and a chaos kill-and-restart run.

Rendering: `table()` is the human attribution table,
`metrics_text()` the Prometheus gauges (shared `_metrics` conventions —
`goodput_ratio`, labeled `badput_seconds{category="..."}`), `summary()`
the JSON-able dict the chaos driver and the CLI consume.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from ._metrics import format_value, gauge_lines
from .timeline import (CATEGORIES, GOODPUT_CATEGORY, Segment, Span,
                       SpanRecorder, from_recorder, load_segments)
from .trace_analysis import _overlap_us, _union

BADPUT_CATEGORIES = tuple(c for c in CATEGORIES if c != GOODPUT_CATEGORY)


class ConservationError(AssertionError):
    """The attribution ledger does not balance: spans double-count wall
    time (overlap) beyond ε, or idle went negative."""


def _coerce_segments(source) -> List[Segment]:
    if isinstance(source, SpanRecorder):
        return [from_recorder(source)]
    if isinstance(source, Segment):
        return [source]
    out: List[Segment] = []
    for s in source:
        if isinstance(s, SpanRecorder):
            out.append(from_recorder(s))
        elif isinstance(s, Segment):
            out.append(s)
        else:
            raise TypeError(f"expected Segment/SpanRecorder, got {type(s)}")
    # stitch order is absolute start time (load_segments pre-sorts; live
    # recorders passed by hand may not be)
    out.sort(key=lambda s: s.start if s.start is not None else s.wall0)
    return out


class GoodputReport:
    """See module docstring.

        report = GoodputReport(load_segments(run_dir))
        report.check_conservation()
        print(report.table())
        print(f"goodput {report.goodput_ratio:.1%}")

    `eps`: conservation tolerance in seconds (absolute).
    """

    def __init__(self, segments, *, eps: float = 0.05):
        self.segments = _coerce_segments(segments)
        self.eps = float(eps)
        # one report describes ONE job: stitching unrelated runs (e.g. a
        # chaos --sweep's per-seed subdirs through one CLI call) would
        # recategorize every later run's steps as "replay" of the
        # earlier ones and collapse goodput to garbage. Segments that
        # declare a run identity (meta["run"]) must agree.
        runs = {s.meta.get("run") for s in self.segments
                if s.meta and s.meta.get("run") is not None}
        if len(runs) > 1:
            raise ValueError(
                f"timeline segments belong to {len(runs)} different runs "
                f"({sorted(runs)}): goodput attribution is per-job — "
                f"report each run separately (pass the run's own "
                f"segment files/subdirectory)")
        self.category_s: Dict[str, float] = {c: 0.0 for c in CATEGORIES}
        self.replayed_steps: set = set()
        # restarts = worker segments beyond the first. A supervisor
        # segment (run_with_restarts(timeline=...)) carries ONLY
        # restart_downtime spans and describes the outages, not an extra
        # process incarnation — it must not inflate the count.
        workers = [s for s in self.segments
                   if not (s.spans and all(sp.cat == "restart_downtime"
                                           for sp in s.spans))]
        self.restarts = max(0, len(workers) - 1)
        self.derived_downtime_s = 0.0
        self._stitch()

    # ------------------------------------------------------------ stitch
    def _stitch(self):
        intervals: List[Tuple[float, float]] = []   # every attributed span
        explicit_down: List[Tuple[float, float]] = []
        prev_max_step: Optional[int] = None
        self.spans: List[Tuple[str, Span]] = []     # (final category, span)

        for seg in self.segments:
            seg_max = prev_max_step
            for sp in seg.spans:
                cat = sp.cat
                covered = ()
                if sp.step is not None:
                    covered = range(sp.step - sp.steps + 1, sp.step + 1)
                if prev_max_step is not None and sp.step is not None \
                        and cat in (GOODPUT_CATEGORY, "compile"):
                    replayed = [k for k in covered if k <= prev_max_step]
                    if replayed:
                        self.replayed_steps.update(replayed)
                        # time attribution: a re-run `step` is replay
                        # badput; a re-run under a `compile` span stays
                        # compile (a fresh process pays compile whether
                        # or not the step is a re-run)
                        if cat == GOODPUT_CATEGORY and \
                                len(replayed) == len(covered):
                            cat = "replay"
                self.spans.append((cat, sp))
                self.category_s[cat] += sp.dur
                intervals.append((sp.abs0, sp.abs1))
                if cat == "restart_downtime":
                    explicit_down.append((sp.abs0, sp.abs1))
                if sp.step is not None:
                    m = max(covered)
                    seg_max = m if seg_max is None else max(seg_max, m)
            prev_max_step = seg_max

        # restart gaps: segment end -> next segment start, minus whatever
        # an elastic supervisor already recorded explicitly
        down_u = _union(explicit_down)
        for a, b in zip(self.segments, self.segments[1:]):
            end, start = a.end, b.start
            if end is None or start is None or start <= end:
                continue
            gap = (end, start)
            uncovered = (gap[1] - gap[0]) - _overlap_us(down_u, [gap])
            if uncovered > 0:
                self.category_s["restart_downtime"] += uncovered
                self.derived_downtime_s += uncovered
                intervals.append(gap)

        starts = [s.start for s in self.segments if s.start is not None]
        ends = [s.end for s in self.segments if s.end is not None]
        self.start = min(starts) if starts else None
        self.end = max(ends) if ends else None
        self.wall_s = (self.end - self.start) \
            if self.start is not None and self.end is not None else 0.0
        self.categorized_s = sum(
            e - s for s, e in _union(intervals))
        self.idle_s = self.wall_s - self.categorized_s
        # the conservation residual: what per-category sums over-claim
        # relative to the union — nonzero means spans overlapped
        self.overlap_s = sum(self.category_s.values()) - self.categorized_s

    # ------------------------------------------------------------- sums
    @property
    def goodput_s(self) -> float:
        return self.category_s[GOODPUT_CATEGORY]

    @property
    def badput_s(self) -> float:
        return sum(self.category_s[c] for c in BADPUT_CATEGORIES)

    @property
    def goodput_ratio(self) -> Optional[float]:
        return self.goodput_s / self.wall_s if self.wall_s > 0 else None

    # ----------------------------------------------------- conservation
    def check_conservation(self, eps: Optional[float] = None) -> dict:
        """Enforce the ledger balance (module docstring). Returns the
        balance detail; raises ConservationError when it does not hold
        within ε."""
        eps = self.eps if eps is None else float(eps)
        # the residual of "sum(categories) + idle ≡ wall" IS the spans'
        # mutual overlap (idle is wall − union by construction), so two
        # checks cover the ledger: no double counting, no negative idle
        residual = sum(self.category_s.values()) + self.idle_s - self.wall_s
        detail = {"wall_s": self.wall_s,
                  "categorized_s": self.categorized_s,
                  "idle_s": self.idle_s,
                  "overlap_s": self.overlap_s,
                  "residual_s": residual, "eps": eps}
        if self.overlap_s > eps:
            raise ConservationError(
                f"timeline spans double-count {self.overlap_s:.4f}s of "
                f"wall time (> eps {eps}): instrumented seams must not "
                f"nest — {detail}")
        if self.idle_s < -eps:
            raise ConservationError(
                f"idle went negative ({self.idle_s:.4f}s < -{eps}): span "
                f"endpoints extend past the segment window — {detail}")
        return detail

    # ---------------------------------------------------------- summary
    def summary(self) -> dict:
        return {
            "wall_s": round(self.wall_s, 6),
            "goodput_s": round(self.goodput_s, 6),
            "goodput_ratio": (round(self.goodput_ratio, 6)
                              if self.goodput_ratio is not None else None),
            "idle_s": round(self.idle_s, 6),
            "overlap_s": round(self.overlap_s, 6),
            "badput_s": {c: round(self.category_s[c], 6)
                         for c in BADPUT_CATEGORIES},
            "restarts": self.restarts,
            "replayed_steps": len(self.replayed_steps),
            "derived_downtime_s": round(self.derived_downtime_s, 6),
            "segments": len(self.segments),
            "spans": len(self.spans),
        }

    def table(self) -> str:
        """The human attribution table: one row per category, descending
        by seconds, goodput and idle called out."""
        lines = ["---- Goodput attribution "
                 f"({len(self.segments)} segment"
                 f"{'s' if len(self.segments) != 1 else ''}, "
                 f"{self.restarts} restart"
                 f"{'s' if self.restarts != 1 else ''}) ----",
                 f"{'seconds':>12}  {'% wall':>7}  category"]

        def pct(v):
            return 100.0 * v / self.wall_s if self.wall_s > 0 else 0.0

        rows = [(self.category_s[c], c) for c in CATEGORIES
                if self.category_s[c] > 0]
        rows.append((self.idle_s, "idle"))
        for sec, cat in sorted(rows, reverse=True):
            tag = " (goodput)" if cat == GOODPUT_CATEGORY else ""
            lines.append(f"{sec:12.3f}  {pct(sec):6.1f}%  {cat}{tag}")
        lines.append(f"{self.wall_s:12.3f}  {100.0 if self.wall_s else 0.0:6.1f}%  wall")
        gr = self.goodput_ratio
        lines.append(f"goodput {gr:.1%}" if gr is not None
                     else "goodput n/a (no wall time)")
        if self.replayed_steps:
            lines.append(f"replayed steps: {len(self.replayed_steps)} "
                         f"({min(self.replayed_steps)}.."
                         f"{max(self.replayed_steps)})")
        return "\n".join(lines)

    def metrics_text(self, prefix: str = "paddle_tpu") -> str:
        """Prometheus gauges via the shared profiler._metrics renderer:
        scalar gauges plus ONE labeled `badput_seconds` family (one
        sample per taxonomy category — zero categories included, so a
        dashboard's queries never 404 on a healthy job)."""
        lines: List[str] = []
        lines += gauge_lines(prefix, "goodput_ratio", self.goodput_ratio,
                             "goodput fraction of job wall time")
        lines += gauge_lines(prefix, "goodput_seconds",
                             round(self.goodput_s, 6),
                             "productive step-compute seconds")
        lines += gauge_lines(prefix, "wall_seconds", round(self.wall_s, 6),
                             "attributed job wall time (restart gaps "
                             "included)")
        lines += gauge_lines(prefix, "idle_seconds", round(self.idle_s, 6),
                             "wall time no seam attributed")
        full = f"{prefix}_badput_seconds" if prefix else "badput_seconds"
        lines += [f"# HELP {full} badput seconds by taxonomy category",
                  f"# TYPE {full} gauge"]
        for c in BADPUT_CATEGORIES:
            lines.append(
                f'{full}{{category="{c}"}} '
                f"{format_value(round(self.category_s[c], 6))}")
        lines += gauge_lines(prefix, "restarts_total", self.restarts,
                             "restarts observed in the stitched timeline")
        lines += gauge_lines(prefix, "replayed_steps_total",
                             len(self.replayed_steps),
                             "steps re-executed after restarts")
        return "\n".join(lines) + "\n"


def report_from(paths, *, eps: float = 0.05) -> GoodputReport:
    """GoodputReport straight from segment files/dirs/globs."""
    return GoodputReport(load_segments(paths), eps=eps)
