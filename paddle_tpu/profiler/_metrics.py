"""Shared Prometheus-exposition primitives for the observability layer.

One renderer serves every `/metrics` surface in the package —
`StepMonitor.metrics_text()` (training step gauges, r7) and the serving
layer's `ServingMetrics` (request histograms/gauges/counters) — so the
exposition format cannot drift between them. The format is the Prometheus
text format 0.0.4: `# HELP` + `# TYPE` headers, one sample per line,
histograms as cumulative `_bucket{le="..."}` lines plus `_sum`/`_count`.

`LogHistogram` is the latency aggregate the serving layer records into:
log-spaced buckets (no per-observation retention — a serving process
observes millions of requests), with p50/p90/p99 DERIVED from the bucket
counts by linear interpolation inside the containing bucket. The relative
error of a derived percentile is bounded by the bucket ratio
(10^(1/per_decade) − 1: ~26% at the default 10/decade, ~12% at 20/decade);
`tests/test_serving.py` checks the math against numpy on known samples.
"""
from __future__ import annotations

import math
from bisect import bisect_left
from typing import Iterable, List, Optional, Sequence


def format_value(v) -> str:
    """One sample value: integers stay integral, floats use repr-shortest."""
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, int):
        return str(v)
    f = float(v)
    if f != f:
        return "NaN"
    if f in (float("inf"), float("-inf")):
        return "+Inf" if f > 0 else "-Inf"
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _header(prefix: str, name: str, kind: str, help_: str) -> List[str]:
    full = f"{prefix}_{name}" if prefix else name
    return [f"# HELP {full} {help_}", f"# TYPE {full} {kind}"]


def gauge_lines(prefix: str, name: str, value, help_: str,
                labels: Optional[dict] = None) -> List[str]:
    """Render one gauge (or nothing when value is None)."""
    if value is None:
        return []
    full = f"{prefix}_{name}" if prefix else name
    lab = ""
    if labels:
        lab = "{" + ",".join(f'{k}="{v}"' for k, v in labels.items()) + "}"
    return _header(prefix, name, "gauge", help_) + \
        [f"{full}{lab} {format_value(value)}"]


def counter_lines(prefix: str, name: str, value, help_: str) -> List[str]:
    """Render one counter; by convention `name` should end in `_total`."""
    if value is None:
        return []
    full = f"{prefix}_{name}" if prefix else name
    return _header(prefix, name, "counter", help_) + \
        [f"{full} {format_value(value)}"]


def histogram_lines(prefix: str, name: str, hist: "LogHistogram",
                    help_: str) -> List[str]:
    """Render one histogram: cumulative le-buckets, +Inf, _sum, _count.
    Empty buckets are elided (scrape size), but cumulativity and the
    +Inf == _count invariant hold regardless."""
    full = f"{prefix}_{name}" if prefix else name
    lines = _header(prefix, name, "histogram", help_)
    cum = 0
    for bound, count in zip(hist.bounds, hist.counts):
        cum += count
        if count:
            lines.append(
                f'{full}_bucket{{le="{format_value(bound)}"}} {cum}')
    lines.append(f'{full}_bucket{{le="+Inf"}} {hist.count}')
    lines.append(f"{full}_sum {format_value(hist.sum)}")
    lines.append(f"{full}_count {hist.count}")
    return lines


class LogHistogram:
    """Fixed-memory latency histogram with log-spaced buckets.

    Bucket upper bounds are lo·10^(k/per_decade) for k = 0..n (n chosen so
    the last bound covers `hi`), plus an implicit +Inf overflow bucket.
    `observe()` is O(log buckets); percentiles interpolate linearly inside
    the containing bucket and clamp to the observed min/max so the edges
    (p0/p100) are exact.
    """

    def __init__(self, lo: float = 1e-4, hi: float = 1e3,
                 per_decade: int = 10,
                 bounds: Optional[Sequence[float]] = None):
        if bounds is not None:
            self.bounds = [float(b) for b in bounds]
        else:
            if not (0 < lo < hi):
                raise ValueError(f"need 0 < lo < hi, got {lo}, {hi}")
            n = int(math.ceil(per_decade * math.log10(hi / lo))) + 1
            self.bounds = [lo * 10.0 ** (k / per_decade) for k in range(n)]
        self.counts = [0] * (len(self.bounds) + 1)   # last = overflow
        self.count = 0
        self.sum = 0.0
        self._min: Optional[float] = None
        self._max: Optional[float] = None

    def observe(self, v: float):
        v = float(v)
        if v != v:       # refuse NaN loudly: it would poison sum/mean
            raise ValueError("cannot observe NaN")
        self.counts[bisect_left(self.bounds, v)] += 1
        self.count += 1
        self.sum += v
        self._min = v if self._min is None else min(self._min, v)
        self._max = v if self._max is None else max(self._max, v)

    @property
    def mean(self) -> Optional[float]:
        return self.sum / self.count if self.count else None

    def percentile(self, q: float) -> Optional[float]:
        """q in [0, 1]. Derived from buckets — see class docstring for the
        error bound."""
        if not self.count:
            return None
        if not (0.0 <= q <= 1.0):
            raise ValueError(f"q must be in [0, 1], got {q}")
        target = q * self.count
        cum = 0.0
        for i, c in enumerate(self.counts):
            if not c:
                continue
            if cum + c >= target:
                lower = self.bounds[i - 1] if i > 0 else \
                    min(self._min, self.bounds[0])
                upper = self.bounds[i] if i < len(self.bounds) else self._max
                frac = (target - cum) / c
                val = lower + frac * (upper - lower)
                return min(max(val, self._min), self._max)
            cum += c
        return self._max

    def quantiles(self, qs: Iterable[float]) -> dict:
        return {q: self.percentile(q) for q in qs}

    def summary(self) -> dict:
        """The standard percentile triplet + count/mean — what a serving
        report() embeds per latency series."""
        return {"count": self.count,
                "mean": self.mean,
                "p50": self.percentile(0.50),
                "p90": self.percentile(0.90),
                "p99": self.percentile(0.99)}
