"""Shared Prometheus-exposition primitives for the observability layer.

One renderer serves every `/metrics` surface in the package —
`StepMonitor.metrics_text()` (training step gauges, r7) and the serving
layer's `ServingMetrics` (request histograms/gauges/counters) — so the
exposition format cannot drift between them. The format is the Prometheus
text format 0.0.4: `# HELP` + `# TYPE` headers, one sample per line,
histograms as cumulative `_bucket{le="..."}` lines plus `_sum`/`_count`.

`LogHistogram` is the latency aggregate the serving layer records into:
log-spaced buckets (no per-observation retention — a serving process
observes millions of requests), with p50/p90/p99 DERIVED from the bucket
counts by linear interpolation inside the containing bucket. The relative
error of a derived percentile is bounded by the bucket ratio
(10^(1/per_decade) − 1: ~26% at the default 10/decade, ~12% at 20/decade);
`tests/test_serving.py` checks the math against numpy on known samples.
"""
from __future__ import annotations

import math
import re
from bisect import bisect_left
from typing import Iterable, List, Optional, Sequence


def format_value(v) -> str:
    """One sample value: integers stay integral, floats use repr-shortest."""
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, int):
        return str(v)
    f = float(v)
    if f != f:
        return "NaN"
    if f in (float("inf"), float("-inf")):
        return "+Inf" if f > 0 else "-Inf"
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _header(prefix: str, name: str, kind: str, help_: str) -> List[str]:
    full = f"{prefix}_{name}" if prefix else name
    return [f"# HELP {full} {help_}", f"# TYPE {full} {kind}"]


def gauge_lines(prefix: str, name: str, value, help_: str,
                labels: Optional[dict] = None) -> List[str]:
    """Render one gauge (or nothing when value is None)."""
    if value is None:
        return []
    full = f"{prefix}_{name}" if prefix else name
    lab = ""
    if labels:
        lab = "{" + ",".join(f'{k}="{v}"' for k, v in labels.items()) + "}"
    return _header(prefix, name, "gauge", help_) + \
        [f"{full}{lab} {format_value(value)}"]


def labeled_gauge_lines(prefix: str, name: str, label_key: str,
                        samples, help_: str) -> List[str]:
    """Render one gauge family with MULTIPLE labeled samples (gauge_lines
    renders exactly one): `samples` is an iterable of (label_value,
    value) pairs; pairs with a None value are skipped, and a family with
    no surviving samples renders nothing."""
    kept = [(lv, v) for lv, v in samples if v is not None]
    if not kept:
        return []
    full = f"{prefix}_{name}" if prefix else name
    return _header(prefix, name, "gauge", help_) + \
        [f'{full}{{{label_key}="{lv}"}} {format_value(v)}'
         for lv, v in kept]


def counter_lines(prefix: str, name: str, value, help_: str) -> List[str]:
    """Render one counter; by convention `name` should end in `_total`."""
    if value is None:
        return []
    full = f"{prefix}_{name}" if prefix else name
    return _header(prefix, name, "counter", help_) + \
        [f"{full} {format_value(value)}"]


def histogram_lines(prefix: str, name: str, hist: "LogHistogram",
                    help_: str) -> List[str]:
    """Render one histogram: cumulative le-buckets, +Inf, _sum, _count.
    Empty buckets are elided (scrape size), but cumulativity and the
    +Inf == _count invariant hold regardless."""
    full = f"{prefix}_{name}" if prefix else name
    lines = _header(prefix, name, "histogram", help_)
    cum = 0
    for bound, count in zip(hist.bounds, hist.counts):
        cum += count
        if count:
            lines.append(
                f'{full}_bucket{{le="{format_value(bound)}"}} {cum}')
    lines.append(f'{full}_bucket{{le="+Inf"}} {hist.count}')
    lines.append(f"{full}_sum {format_value(hist.sum)}")
    lines.append(f"{full}_count {hist.count}")
    return lines


# ------------------------------------------------------------- parsing

class ExpositionError(ValueError):
    """The text does not conform to the Prometheus exposition format the
    renderers above promise (malformed sample, missing/duplicated HELP or
    TYPE, interleaved families, ...)."""


_SAMPLE_RE = re.compile(
    r'^([a-zA-Z_:][a-zA-Z0-9_:]*)'            # metric name
    r'(\{[^}]*\})? '                          # optional label set
    r'(-?\d+(\.\d+)?([eE][-+]?\d+)?|[+-]Inf|NaN)$')

_FAMILY_SUFFIX_RE = re.compile(r"_(bucket|sum|count)$")


def parse_exposition(text: str) -> dict:
    """Parse text-format 0.0.4 output from the renderers above into an
    ordered ``{family: {"type", "help", "samples"}}`` dict, enforcing the
    structural invariants a scraper relies on:

      - every sample line matches the sample grammar,
      - every family declares HELP then TYPE, exactly once, BEFORE its
        first sample,
      - a family's lines are contiguous (no interleaving — the producer
        of the merged page must not shuffle blocks line-wise),
      - no duplicate sample (same name + label set).

    Histogram-specific invariants (cumulative buckets, +Inf == _count)
    are the job of `obs.registry.lint_exposition`, which builds on this.
    Raises ExpositionError; an empty/whitespace text parses to {}.
    """
    families: dict = {}
    current: Optional[str] = None
    seen_samples = set()
    for ln, line in enumerate(text.split("\n"), 1):
        if not line.strip():
            continue
        if line.startswith("# HELP ") or line.startswith("# TYPE "):
            parts = line.split(None, 3)
            if len(parts) < 4:
                raise ExpositionError(f"line {ln}: truncated {parts[1]} "
                                      f"line: {line!r}")
            kind, name, rest = parts[1], parts[2], parts[3]
            fam = families.get(name)
            if fam is None:
                if kind == "TYPE":
                    raise ExpositionError(
                        f"line {ln}: TYPE for {name} before its HELP")
                families[name] = {"help": rest, "type": None, "samples": []}
            else:
                if fam["samples"] or (kind == "HELP") \
                        or (kind == "TYPE" and fam["type"] is not None):
                    raise ExpositionError(
                        f"line {ln}: duplicate {kind} for family {name}")
                fam["type"] = rest.strip()
            current = name
            continue
        if line.startswith("#"):
            continue                         # comments are legal noise
        m = _SAMPLE_RE.match(line)
        if not m:
            raise ExpositionError(f"line {ln}: malformed sample: {line!r}")
        base, labels = m.group(1), m.group(2) or ""
        fam_name = base if base in families \
            else _FAMILY_SUFFIX_RE.sub("", base)
        fam = families.get(fam_name)
        if fam is None or fam["type"] is None:
            raise ExpositionError(
                f"line {ln}: sample {base!r} has no preceding HELP/TYPE "
                f"declaration")
        if fam_name != current:
            raise ExpositionError(
                f"line {ln}: family {fam_name} resumed after other "
                f"families — samples must be contiguous per family")
        key = (base, labels)
        if key in seen_samples:
            raise ExpositionError(
                f"line {ln}: duplicate sample {base}{labels}")
        seen_samples.add(key)
        fam["samples"].append((base, labels, m.group(3)))
    for name, fam in families.items():
        if fam["type"] is None:
            raise ExpositionError(f"family {name} has HELP but no TYPE")
    return families


class LogHistogram:
    """Fixed-memory latency histogram with log-spaced buckets.

    Bucket upper bounds are lo·10^(k/per_decade) for k = 0..n (n chosen so
    the last bound covers `hi`), plus an implicit +Inf overflow bucket.
    `observe()` is O(log buckets); percentiles interpolate linearly inside
    the containing bucket and clamp to the observed min/max so the edges
    (p0/p100) are exact.
    """

    def __init__(self, lo: float = 1e-4, hi: float = 1e3,
                 per_decade: int = 10,
                 bounds: Optional[Sequence[float]] = None):
        if bounds is not None:
            self.bounds = [float(b) for b in bounds]
        else:
            if not (0 < lo < hi):
                raise ValueError(f"need 0 < lo < hi, got {lo}, {hi}")
            n = int(math.ceil(per_decade * math.log10(hi / lo))) + 1
            self.bounds = [lo * 10.0 ** (k / per_decade) for k in range(n)]
        self.counts = [0] * (len(self.bounds) + 1)   # last = overflow
        self.count = 0
        self.sum = 0.0
        self._min: Optional[float] = None
        self._max: Optional[float] = None

    def observe(self, v: float):
        v = float(v)
        if v != v:       # refuse NaN loudly: it would poison sum/mean
            raise ValueError("cannot observe NaN")
        self.counts[bisect_left(self.bounds, v)] += 1
        self.count += 1
        self.sum += v
        self._min = v if self._min is None else min(self._min, v)
        self._max = v if self._max is None else max(self._max, v)

    @property
    def mean(self) -> Optional[float]:
        return self.sum / self.count if self.count else None

    def percentile(self, q: float) -> Optional[float]:
        """q in [0, 1]. Derived from buckets — see class docstring for the
        error bound."""
        if not self.count:
            return None
        if not (0.0 <= q <= 1.0):
            raise ValueError(f"q must be in [0, 1], got {q}")
        target = q * self.count
        cum = 0.0
        for i, c in enumerate(self.counts):
            if not c:
                continue
            if cum + c >= target:
                lower = self.bounds[i - 1] if i > 0 else \
                    min(self._min, self.bounds[0])
                upper = self.bounds[i] if i < len(self.bounds) else self._max
                frac = (target - cum) / c
                val = lower + frac * (upper - lower)
                return min(max(val, self._min), self._max)
            cum += c
        return self._max

    def quantiles(self, qs: Iterable[float]) -> dict:
        return {q: self.percentile(q) for q in qs}

    def summary(self) -> dict:
        """The standard percentile triplet + count/mean — what a serving
        report() embeds per latency series."""
        return {"count": self.count,
                "mean": self.mean,
                "p50": self.percentile(0.50),
                "p90": self.percentile(0.90),
                "p99": self.percentile(0.99)}
