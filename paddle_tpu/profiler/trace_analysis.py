"""Device-trace analytics — XLA trace.json.gz → op/collective summary tables.

The reference profiler fuses a host tracer and a CUPTI device tracer into one
event tree and renders op-level summary tables (profiler_statistic.py). Here
the device tracer is jax.profiler: `jax.profiler.start_trace` captures an
XPlane that lands on disk as a perfetto/chrome `*.trace.json.gz`. This module
parses that capture into the same summary surface:

  - KernelView:      per-op-name device-time totals (the only trustworthy
                     per-component timing on remote-dispatch runtimes — host
                     timers measure dispatch, not device work)
  - DeviceView:      per-device-lane busy time + a fusion/collective/copy
                     category split
  - DistributedView: a per-collective LEDGER (name, calls, bytes moved, bus
                     bandwidth, overlapped-vs-EXPOSED time) plus the whole-
                     step compute/communication overlap ratio. The single
                     `overlap_ratio` scalar says whether comm is hidden in
                     aggregate; the ledger says WHICH collective is paying
                     the exposed time — the granularity the T3-style
                     per-layer comm/compute scheduling work is judged at
                     (PAPERS.md arxiv 2401.16677).

Used by `Profiler.summary(views=...)`, the `tools/profile_step.py` CLI, and
`obs.collectives.CollectiveLedger` (the exposition/JSONL surface of the
per-collective rows).
"""
from __future__ import annotations

import glob
import gzip
import json
import os
from collections import defaultdict
from typing import Dict, Iterable, List, Optional, Tuple

# op-name markers for the communication category (XLA HLO collective ops;
# -start/-done async pairs share the prefix)
_COLLECTIVE_MARKERS = ("all-reduce", "all-gather", "reduce-scatter",
                       "all-to-all", "collective-permute",
                       "collective-broadcast")
_COPY_MARKERS = ("copy", "bitcast", "transpose", "reshape")


def classify_op(name: str) -> str:
    """Category of an XLA device op name: collective|fusion|copy|compute."""
    low = name.lower()
    if any(m in low for m in _COLLECTIVE_MARKERS):
        return "collective"
    if low.startswith("fusion") or ".fusion" in low or "_fusion" in low:
        return "fusion"
    if any(low.startswith(m) for m in _COPY_MARKERS):
        return "copy"
    return "compute"


# trace-event arg keys that carry the op's data volume. XLA/XPlane exports
# are inconsistent across versions ("bytes accessed" in newer XProf stat
# names, snake_case in chrome-trace re-exports); take the first present.
_BYTES_ARG_KEYS = ("bytes_accessed", "bytes accessed", "bytes",
                   "size_bytes", "shape_bytes")


def event_bytes(e: dict) -> Optional[int]:
    """Data volume an event's args declare, or None when the capture
    carries no byte stat (older jax versions — the ledger then reports
    bytes/bandwidth as unknown rather than guessing from op names)."""
    args = e.get("args")
    if not isinstance(args, dict):
        return None
    for key in _BYTES_ARG_KEYS:
        v = args.get(key)
        if v is None:
            continue
        try:
            return int(float(v))
        except (TypeError, ValueError):
            continue
    return None


def find_trace_file(path: str) -> Optional[str]:
    """Newest `*.trace.json.gz` (or `.trace.json`) under a file/dir path."""
    if os.path.isfile(path):
        return path
    hits = []
    for pat in ("**/*.trace.json.gz", "**/*.trace.json"):
        hits.extend(glob.glob(os.path.join(path, pat), recursive=True))
    return max(hits, key=os.path.getmtime) if hits else None


def load_events(path: str) -> List[dict]:
    """traceEvents list of a chrome-tracing capture (.json or .json.gz)."""
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rt") as f:
        data = json.load(f)
    return data.get("traceEvents", [])


def _union(intervals: List[Tuple[float, float]]) -> List[Tuple[float, float]]:
    """Union of [start, end) intervals, sorted and merged."""
    if not intervals:
        return []
    intervals = sorted(intervals)
    out = [list(intervals[0])]
    for s, e in intervals[1:]:
        if s <= out[-1][1]:
            out[-1][1] = max(out[-1][1], e)
        else:
            out.append([s, e])
    return [(s, e) for s, e in out]

def _overlap_us(a: List[Tuple[float, float]],
                b: List[Tuple[float, float]]) -> float:
    """Total length of the intersection of two merged interval lists."""
    i = j = 0
    total = 0.0
    while i < len(a) and j < len(b):
        lo = max(a[i][0], b[j][0])
        hi = min(a[i][1], b[j][1])
        if hi > lo:
            total += hi - lo
        if a[i][1] < b[j][1]:
            i += 1
        else:
            j += 1
    return total


# the exposition series derived from collective_rows() — ONE definition
# shared by StepMonitor.metrics_text (prefix "paddle_tpu" -> adopted
# gauges) and obs.collectives.CollectiveLedger.metrics_text (prefix
# "paddle_tpu_comm" -> the standalone ledger block); two copies of the
# (name, help, getter) tuples had already drifted help-text-wise
_COLLECTIVE_SERIES = (
    # clock getters are None-safe: static inventory rows (from_static
    # ledgers) carry bytes/dtype but no timing — labeled_gauge_lines
    # drops the None samples
    ("collective_seconds", "device seconds per collective op",
     lambda r: r["dur_us"] / 1e6 if r.get("dur_us") is not None else None),
    ("collective_exposed_seconds", "collective seconds NOT hidden under "
     "compute — the wall the step pays",
     lambda r: r["exposed_us"] / 1e6
     if r.get("exposed_us") is not None else None),
    ("collective_bytes", "bytes moved per collective op",
     lambda r: r.get("bytes")),
    ("collective_bus_gbps", "achieved bus bandwidth per collective op",
     lambda r: r.get("bus_gbps")),
)


def collective_series_lines(rows: List[dict], prefix: str) -> List[str]:
    """Per-op labeled gauge families for a set of collective_rows()."""
    from ._metrics import labeled_gauge_lines
    lines: List[str] = []
    for name, help_, get in _COLLECTIVE_SERIES:
        lines += labeled_gauge_lines(
            prefix, name, "op", [(r["name"], get(r)) for r in rows],
            help_)
    # wire-dtype split (ISSUE 20): rows that carry a dtype (static
    # inventory — e.g. the s8 vs f32 gradient-sync lanes) additionally
    # aggregate their bytes per dtype, so the int8 wire cut is one gauge
    by_dt: Dict[str, int] = {}
    for r in rows:
        dt, b = r.get("dtype"), r.get("bytes")
        if dt and b is not None:
            by_dt[dt] = by_dt.get(dt, 0) + int(b)
    lines += labeled_gauge_lines(
        prefix, "collective_bytes_by_dtype", "dtype",
        sorted(by_dt.items()),
        "bytes moved per collective wire dtype")
    return lines


def format_collective_rows(rows: List[dict],
                           steps: Optional[int] = None,
                           top: int = 20) -> List[str]:
    """Render collective_rows() as table lines — the ONE formatter both
    DistributedView and obs.collectives.CollectiveLedger.table() print
    (two renderers over the same row dicts would drift column by
    column). Header + one line per op; the caller adds its own title and
    totals/overlap footer. The STATIC inventory rows
    (analysis.sharding.collective_inventory — same schema, no clock)
    render through here too: None timing columns print as '-'."""
    div = max(steps or 1, 1)
    unit = "ms/step" if steps else "ms"
    lines = [f"{unit:>10}  {'exposed':>9}  {'hidden%':>7}  {'calls':>6}  "
             f"{'MB':>9}  {'GB/s':>7}  {'dtype':>6}  op"]
    for r in rows[:top]:
        mb = f"{r['bytes'] / 1e6:9.2f}" if r["bytes"] is not None \
            else f"{'-':>9}"
        bus = f"{r['bus_gbps']:7.1f}" if r["bus_gbps"] is not None \
            else f"{'-':>7}"
        dur = f"{r['dur_us'] / div / 1e3:10.3f}" \
            if r["dur_us"] is not None else f"{'-':>10}"
        exp = f"{r['exposed_us'] / div / 1e3:9.3f}" \
            if r["exposed_us"] is not None else f"{'-':>9}"
        hidden = f"{(1.0 - r['exposed_frac']) * 100.0:7.1f}" \
            if r["exposed_frac"] is not None else f"{'-':>7}"
        # the int8-vs-f32 wire split (ISSUE 20): static inventory rows
        # carry the collective's wire dtype; runtime trace rows print '-'
        dt = f"{(r.get('dtype') or '-')[:6]:>6}"
        lines.append(f"{dur}  {exp}  {hidden}  {r['calls']:6d}  "
                     f"{mb}  {bus}  {dt}  {r['name'][:70]}")
    return lines


class TraceAnalysis:
    """Parsed device lanes of one captured trace.

    `steps` (optional) divides totals into per-step figures — the caller
    knows how many training steps ran inside the capture. `window=(lo, hi)`
    keeps only events whose start falls into that fraction of the capture
    span (steady-window trimming: drop warmup/drain at the edges).
    """

    def __init__(self, events: Iterable[dict], steps: Optional[int] = None,
                 window: Tuple[float, float] = (0.0, 1.0)):
        events = list(events)   # two passes below; a generator would drain
        self.steps = steps
        self.pid_name: Dict[int, str] = {}
        self.tid_name: Dict[Tuple[int, int], str] = {}
        for e in events:
            if e.get("ph") == "M":
                if e.get("name") == "process_name":
                    self.pid_name[e.get("pid")] = e.get("args", {}).get("name", "")
                elif e.get("name") == "thread_name":
                    self.tid_name[(e.get("pid"), e.get("tid"))] = \
                        e.get("args", {}).get("name", "")

        def lane_of(e):
            pname = self.pid_name.get(e.get("pid"), "")
            tname = self.tid_name.get((e.get("pid"), e.get("tid")), "")
            return pname, tname

        # device op lanes: device pids, minus whole-module envelopes and
        # step-marker lanes (those double-count every op under them)
        def is_device_op(e):
            pname, tname = lane_of(e)
            if not any(k in pname for k in ("TPU", "device", "Device")):
                return False
            skip = ("XLA Modules", "Steps", "Framework")
            return not any(k in pname or k in tname for k in skip)

        raw = [e for e in events
               if e.get("ph") == "X" and "dur" in e and is_device_op(e)]
        # host-lane fallback (ISSUE 20): a CPU-backend capture has no
        # device pid at all ("/host:CPU" only), but the XLA CPU client's
        # execution threads (tf_XLATfrtCpuClient/...) carry real per-thunk
        # op events — all-reduce, dot, fusion — so overlap/exposed-time
        # stays measurable on the host platform. Runtime bookkeeping
        # envelopes are dropped (they span whole executions and would
        # count every op as "overlapped with compute").
        self.host_lanes = False
        if not raw:
            _skip_host = ("ThreadpoolListener", "ThunkExecutor",
                          "ExecuteHelper", "Dispatch", "CopyToDevice",
                          "Execute")

            def is_host_xla_op(e):
                _, tname = lane_of(e)
                if not tname.startswith("tf_XLA"):
                    return False
                return not any(k in e.get("name", "") for k in _skip_host)

            raw = [e for e in events
                   if e.get("ph") == "X" and "dur" in e
                   and is_host_xla_op(e)]
            self.host_lanes = bool(raw)
        if raw and window != (0.0, 1.0):
            t0 = min(e["ts"] for e in raw)
            t1 = max(e["ts"] + e["dur"] for e in raw)
            span = max(t1 - t0, 1e-9)
            lo, hi = t0 + window[0] * span, t0 + window[1] * span
            raw = [e for e in raw if lo <= e["ts"] <= hi]
        self.device_events = raw

    # ---------------------------------------------------------------- ops
    def op_totals(self) -> List[dict]:
        """Per-op-name rows sorted by total device time (descending)."""
        agg = defaultdict(lambda: {"dur_us": 0.0, "calls": 0})
        for e in self.device_events:
            a = agg[e["name"]]
            a["dur_us"] += e["dur"]
            a["calls"] += 1
        total = sum(a["dur_us"] for a in agg.values()) or 1.0
        rows = [{"name": n, "dur_us": a["dur_us"], "calls": a["calls"],
                 "pct": 100.0 * a["dur_us"] / total,
                 "category": classify_op(n)}
                for n, a in agg.items()]
        rows.sort(key=lambda r: -r["dur_us"])
        return rows

    def total_device_us(self) -> float:
        return sum(e["dur"] for e in self.device_events)

    def category_totals(self) -> Dict[str, float]:
        out = defaultdict(float)
        for e in self.device_events:
            out[classify_op(e["name"])] += e["dur"]
        return dict(out)

    # ------------------------------------------------------------- lanes
    def lane_busy(self) -> List[dict]:
        """Per device lane: merged busy time (overlap-free) and op count."""
        lanes = defaultdict(list)
        for e in self.device_events:
            lanes[(e.get("pid"), e.get("tid"))].append(
                (e["ts"], e["ts"] + e["dur"]))
        rows = []
        for (pid, tid), iv in sorted(lanes.items()):
            merged = _union(iv)
            busy = sum(e - s for s, e in merged)
            name = self.pid_name.get(pid, f"pid{pid}")
            tname = self.tid_name.get((pid, tid), "")
            rows.append({"lane": f"{name}/{tname}" if tname else name,
                         "busy_us": busy, "ops": len(iv)})
        return rows

    # --------------------------------------------------------- distributed
    def collective_rows(self) -> List[dict]:
        """The per-collective ledger: one row per collective op name.

        Each row decomposes that collective's device time against the
        union of ALL non-collective device compute:

          dur_us         summed durations of the op's events
          busy_us        overlap-free union span of the op's events (the
                         denominator for exposure — back-to-back async
                         chunks must not double-count)
          overlapped_us  busy time with compute running concurrently
          exposed_us     busy - overlapped: wall time the step PAYS for
                         this collective (the number scheduling work must
                         drive to zero)
          bytes          data volume from the capture's byte stats (None
                         when the capture carries none)
          bus_gbps       bytes / busy_us — achieved bus bandwidth (None
                         without bytes)

        Sorted by exposed_us descending: the top row is the collective to
        attack first. sum(overlapped_us)/sum(busy_us) over the rows equals
        overlap()'s whole-step ratio up to interval-union bookkeeping, so
        the ledger IS the decomposition of the overlap_ratio gauge."""
        comp: List[Tuple[float, float]] = []
        groups: Dict[str, dict] = {}
        for e in self.device_events:
            iv = (e["ts"], e["ts"] + e["dur"])
            if classify_op(e["name"]) != "collective":
                comp.append(iv)
                continue
            g = groups.setdefault(e["name"],
                                  {"intervals": [], "dur_us": 0.0,
                                   "calls": 0, "bytes": None})
            g["intervals"].append(iv)
            g["dur_us"] += e["dur"]
            g["calls"] += 1
            b = event_bytes(e)
            if b is not None:
                g["bytes"] = (g["bytes"] or 0) + b
        comp_u = _union(comp)
        rows = []
        for name, g in groups.items():
            iv_u = _union(g["intervals"])
            busy = sum(e - s for s, e in iv_u)
            ovl = _overlap_us(iv_u, comp_u)
            exposed = max(busy - ovl, 0.0)
            nbytes = g["bytes"]
            bus = None
            if nbytes is not None and busy > 0:
                bus = nbytes / (busy * 1e-6) / 1e9     # bytes/s -> GB/s
            rows.append({"name": name, "calls": g["calls"],
                         "dur_us": g["dur_us"], "busy_us": busy,
                         "overlapped_us": ovl, "exposed_us": exposed,
                         "exposed_frac": exposed / busy if busy else 0.0,
                         "bytes": nbytes,
                         "bus_gbps": bus,
                         # wire dtype: traces don't carry it (the static
                         # inventory's rows do) — schema parity with
                         # collective_inventory for the shared renderers
                         "dtype": None})
        rows.sort(key=lambda r: (-r["exposed_us"], -r["busy_us"]))
        return rows

    def overlap(self) -> dict:
        """Compute/communication overlap over the device lanes.

        collective_us:   union span of collective ops
        compute_busy_us: union span of non-collective device ops
        overlapped_us:   collective time with compute running concurrently
        ratio:           overlapped / collective (1.0 = fully hidden)
        """
        coll, comp = [], []
        for e in self.device_events:
            iv = (e["ts"], e["ts"] + e["dur"])
            (coll if classify_op(e["name"]) == "collective" else comp).append(iv)
        coll_u, comp_u = _union(coll), _union(comp)
        coll_us = sum(e - s for s, e in coll_u)
        comp_us = sum(e - s for s, e in comp_u)
        ovl = _overlap_us(coll_u, comp_u)
        return {"collective_us": coll_us, "compute_busy_us": comp_us,
                "overlapped_us": ovl,
                "ratio": (ovl / coll_us) if coll_us > 0 else None}

    # -------------------------------------------------------------- views
    def _per_step(self, us: float) -> float:
        return us / (self.steps or 1)

    def kernel_view(self, top: int = 45) -> str:
        """Per-op device-time table (reference KernelView)."""
        rows = self.op_totals()
        n = self.steps
        hdr = (f"{'ms/step' if n else 'ms':>10}  {'%':>5}  {'calls':>6}  "
               f"{'category':<10}  op")
        lines = ["---- KernelView (device op time"
                 + (f", {n} steps" if n else "") + ") ----", hdr]
        for r in rows[:top]:
            lines.append(f"{self._per_step(r['dur_us']) / 1e3:10.3f}  "
                         f"{r['pct']:5.1f}  {r['calls']:6d}  "
                         f"{r['category']:<10}  {r['name'][:100]}")
        tot = self.total_device_us()
        lines.append(f"{'total':>10}  {self._per_step(tot) / 1e3:.3f} ms"
                     + (f"/step over {n} steps" if n else ""))
        return "\n".join(lines)

    def device_view(self) -> str:
        """Per-lane busy time + category split (reference DeviceView)."""
        lines = ["---- DeviceView (device lanes) ----",
                 f"{'busy ms':>10}  {'ops':>7}  lane"]
        for r in self.lane_busy():
            lines.append(f"{self._per_step(r['busy_us']) / 1e3:10.3f}  "
                         f"{r['ops']:7d}  {r['lane'][:90]}")
        cats = self.category_totals()
        total = sum(cats.values()) or 1.0
        lines.append("category split: " + ", ".join(
            f"{k} {v / total * 100:.1f}%" for k, v in
            sorted(cats.items(), key=lambda kv: -kv[1])))
        return "\n".join(lines)

    def distributed_view(self, top: int = 20) -> str:
        """Per-collective ledger + overlap ratio (reference
        DistributedView). Columns: total device ms, EXPOSED ms (the part
        compute does not hide — the actionable number), bytes moved and
        achieved bus bandwidth where the capture carries byte stats."""
        rows = self.collective_rows()
        lines = ["---- DistributedView (collective ledger) ----"]
        if not rows:
            lines.append("no collective ops in capture (single-chip step)")
        else:
            lines += format_collective_rows(rows, steps=self.steps,
                                            top=top)
        ov = self.overlap()
        if ov["ratio"] is not None:
            lines.append(
                f"collective {self._per_step(ov['collective_us']) / 1e3:.3f} ms"
                f", overlapped with compute "
                f"{self._per_step(ov['overlapped_us']) / 1e3:.3f} ms "
                f"(overlap ratio {ov['ratio']:.2f})")
        return "\n".join(lines)


def analyze(path_or_events, steps: Optional[int] = None,
            window: Tuple[float, float] = (0.0, 1.0)) -> TraceAnalysis:
    """TraceAnalysis from a trace file, a directory of captures (newest
    wins), or an already-loaded traceEvents list."""
    if isinstance(path_or_events, str):
        f = find_trace_file(path_or_events)
        if f is None:
            raise FileNotFoundError(
                f"no *.trace.json[.gz] under {path_or_events!r} — was the "
                "device trace captured? (Profiler(timer_only=True) and "
                "failed start_trace skip the device tracer)")
        events = load_events(f)
    else:
        events = list(path_or_events)
    return TraceAnalysis(events, steps=steps, window=window)


# ------------------------------------------------- kernel-level diffing
# (ISSUE 17) — the attribution tool behind tools/perf_diff.py: given two
# captures, name the kernels that got slower.

def kernel_diff(base: "TraceAnalysis", cand: "TraceAnalysis") -> dict:
    """Kernel-granularity regression attribution between two captures.

    Per op name (union of both captures): per-step device time in each
    (`a_us`/`b_us` — the `steps` each analysis was built with normalizes
    unequal capture lengths), the absolute and relative delta, the op's
    occupancy of its step (`a_pct`/`b_pct`: share of total device time)
    and a status — `common`, `new` (only in the candidate) or `vanished`
    (only in the baseline). Collectives additionally diff their EXPOSED
    time (the wall the step pays). Rows sort by |delta| descending: the
    top row is where the regression lives."""
    rows_a = {r["name"]: r for r in base.op_totals()}
    rows_b = {r["name"]: r for r in cand.op_totals()}
    div_a = max(base.steps or 1, 1)
    div_b = max(cand.steps or 1, 1)
    kernels = []
    for name in set(rows_a) | set(rows_b):
        ra, rb = rows_a.get(name), rows_b.get(name)
        a_us = ra["dur_us"] / div_a if ra else 0.0
        b_us = rb["dur_us"] / div_b if rb else 0.0
        kernels.append({
            "name": name, "category": (rb or ra)["category"],
            "status": ("common" if ra and rb
                       else ("new" if rb else "vanished")),
            "a_us": a_us, "b_us": b_us,
            "a_calls": ra["calls"] if ra else 0,
            "b_calls": rb["calls"] if rb else 0,
            "delta_us": b_us - a_us,
            "delta_pct": ((b_us - a_us) / a_us * 100.0
                          if a_us > 0 else None),
            "a_pct": ra["pct"] if ra else 0.0,
            "b_pct": rb["pct"] if rb else 0.0})
    kernels.sort(key=lambda r: (-abs(r["delta_us"]), r["name"]))
    total_a = base.total_device_us() / div_a
    total_b = cand.total_device_us() / div_b
    coll_a = {r["name"]: r for r in base.collective_rows()}
    coll_b = {r["name"]: r for r in cand.collective_rows()}
    collectives = []
    for name in set(coll_a) | set(coll_b):
        ea = coll_a[name]["exposed_us"] / div_a if name in coll_a else 0.0
        eb = coll_b[name]["exposed_us"] / div_b if name in coll_b else 0.0
        collectives.append({
            "name": name,
            "a_exposed_us": ea, "b_exposed_us": eb,
            "delta_us": eb - ea,
            "delta_pct": ((eb - ea) / ea * 100.0 if ea > 0 else None)})
    collectives.sort(key=lambda r: (-abs(r["delta_us"]), r["name"]))
    return {"kernels": kernels, "collectives": collectives,
            "total": {"a_us": total_a, "b_us": total_b,
                      "delta_us": total_b - total_a,
                      "delta_pct": ((total_b - total_a) / total_a * 100.0
                                    if total_a > 0 else None)}}


def diff_regressions(diff: dict, *, regress_pct: float,
                     min_us: float = 50.0) -> List[dict]:
    """The kernels a --regress-pct gate fails on: common kernels whose
    per-step time grew STRICTLY more than `regress_pct` percent, and new
    kernels that appeared at all — both above the `min_us` noise floor
    (per-step device time; sub-floor ops jitter across captures). A
    capture diffed against itself regresses nothing at any threshold."""
    out = []
    for r in diff["kernels"]:
        if r["status"] == "new":
            if r["b_us"] >= min_us:
                out.append(dict(r, reason="new kernel"))
        elif r["status"] == "common":
            if (r["delta_pct"] is not None
                    and r["delta_pct"] > regress_pct
                    and r["delta_us"] >= min_us):
                out.append(dict(r, reason=(
                    f"+{r['delta_pct']:.1f}% "
                    f"(+{r['delta_us'] / 1e3:.3f} ms)")))
    return out


def format_kernel_diff(diff: dict, top: int = 30) -> str:
    """Human table over kernel_diff()'s rows (perf_diff's stdout)."""
    lines = ["---- KernelDiff (per-step device time, baseline -> "
             "candidate) ----",
             f"{'base ms':>10}  {'cand ms':>10}  {'delta ms':>10}  "
             f"{'delta%':>8}  {'occ%':>11}  op"]
    for r in diff["kernels"][:top]:
        dp = f"{r['delta_pct']:8.1f}" if r["delta_pct"] is not None \
            else f"{r['status']:>8}"
        occ = f"{r['a_pct']:5.1f}>{r['b_pct']:5.1f}"
        lines.append(f"{r['a_us'] / 1e3:10.3f}  {r['b_us'] / 1e3:10.3f}  "
                     f"{r['delta_us'] / 1e3:10.3f}  {dp}  {occ}  "
                     f"{r['name'][:70]}")
    t = diff["total"]
    tp = f"{t['delta_pct']:+.1f}%" if t["delta_pct"] is not None else "-"
    lines.append(f"total: {t['a_us'] / 1e3:.3f} -> {t['b_us'] / 1e3:.3f} "
                 f"ms/step ({tp})")
    if diff["collectives"]:
        lines.append("collective exposed-time deltas:")
        for r in diff["collectives"]:
            dp = f"{r['delta_pct']:+.1f}%" if r["delta_pct"] is not None \
                else "new"
            lines.append(f"  {r['a_exposed_us'] / 1e3:10.3f}  "
                         f"{r['b_exposed_us'] / 1e3:10.3f}  {dp:>8}  "
                         f"{r['name'][:70]}")
    return "\n".join(lines)


def summarize(path: str, views=None, steps: Optional[int] = None) -> str:
    """Render the requested views (names or SummaryView members) from the
    newest capture under `path`."""
    an = analyze(path, steps=steps)
    parts = []
    for v in views or ("kernel",):
        name = getattr(v, "name", str(v)).lower()
        if "kernel" in name or "operator" in name:
            parts.append(an.kernel_view())
        elif "device" in name:
            parts.append(an.device_view())
        elif "dist" in name:
            parts.append(an.distributed_view())
        else:
            parts.append(f"(view {name!r} has no device-trace table)")
    return "\n\n".join(parts)
