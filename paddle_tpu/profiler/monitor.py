"""StepMonitor — always-on, low-overhead per-step training telemetry.

The profiler (trace capture + trace_analysis) is the deep-dive tool; this is
the steady-state gauge cluster a production run keeps on every step:

  - per-step wall time and items/sec (tokens or images — caller configures
    `items_per_step` or passes `items=` per step)
  - achieved MFU against the chip's peak matmul FLOP/s
    (paddle_tpu.device.chip_peak_flops)
  - live/peak HBM via paddle_tpu.device.memory_stats()
  - jit cache-miss counts and a RECOMPILATION DETECTOR: when a traced step
    compiles again, the offending abstract-shape delta (old vs new
    shape/dtype signature) is logged and recorded

Each step appends one JSONL row when `jsonl_path` is set, and `on_report`
(if given) is called with the row dict — the hook a metrics exporter or a
live dashboard attaches to. `jit.TrainStep(monitor=...)` wires this in
automatically; `hapi` exposes it as `callbacks.ProfilerCallback`.
"""
from __future__ import annotations

import contextlib
import json
import logging
import time
from typing import Callable, Optional

logger = logging.getLogger("paddle_tpu.profiler.monitor")


def shape_delta(old_sig, new_sig) -> str:
    """Human-readable delta between two abstract-shape signatures (tuples of
    (shape, dtype) leaves) — the payload of a recompilation log line."""
    if old_sig is None:
        return "first compile"
    old, new = list(old_sig), list(new_sig)
    if len(old) != len(new):
        return f"leaf count {len(old)} -> {len(new)}"
    diffs = []
    for i, (o, n) in enumerate(zip(old, new)):
        if o != n:
            diffs.append(f"leaf[{i}]: {o} -> {n}")
    return "; ".join(diffs) if diffs else "signature changed (non-shape key)"


def _jit_cache_misses() -> int:
    from ..jit.api import compile_cache_misses
    return compile_cache_misses()


class StepMonitor:
    """Record per-step metrics; see module docstring.

    flops_per_step / flops_per_item: model FLOPs for the MFU figure (set
    either; `flops_per_item` multiplies the per-step item count). May be
    assigned after the run, before report() — MFU is computed at report
    time. `peak_flops` defaults to the chip's bf16 peak.
    """

    def __init__(self, *, flops_per_step: Optional[float] = None,
                 flops_per_item: Optional[float] = None,
                 items_per_step: Optional[float] = None,
                 unit: str = "items/s", peak_flops: Optional[float] = None,
                 jsonl_path: Optional[str] = None,
                 on_report: Optional[Callable[[dict], None]] = None,
                 track_memory: bool = True,
                 memory_sample_every: Optional[int] = None,
                 log_recompiles: bool = True):
        self.flops_per_step = flops_per_step
        self.flops_per_item = flops_per_item
        self.items_per_step = items_per_step
        self.unit = unit
        self.peak_flops = peak_flops
        self.jsonl_path = jsonl_path
        self.on_report = on_report
        self.track_memory = track_memory
        # allocator counters are cheap to read every step; the live-array
        # fallback (host platforms) scans every live buffer, so it samples
        # every 10th step unless overridden
        self.memory_sample_every = memory_sample_every
        self._mem_every = None
        self.log_recompiles = log_recompiles
        self.records = []          # one dict per end_step
        self.overlap = None        # latest compute/comm overlap (dict)
        self.compiles = 0          # traced-step compiles observed
        self.recompiles = 0        # compiles beyond the first per kind
        self.recompile_events = []  # {step, kind, delta}
        self.numerics_events = []   # NumericsEvent dicts (debugging layer)
        self._last_numerics = {}    # latest fetched loss/grad_norm scalars
        self._steps = 0
        self._t0 = None
        self._jit_miss_0 = None
        self._compiled_this_step = 0

    # ------------------------------------------------------------- steps
    def begin_step(self):
        self._jit_miss_0 = _jit_cache_misses()
        self._compiled_this_step = 0
        self._t0 = time.perf_counter()

    def end_step(self, items: Optional[float] = None, steps: int = 1,
                 wall_s: Optional[float] = None):
        """Close the step opened by begin_step (or record an externally
        timed window via `wall_s`). `steps` > 1 amortizes one fused
        multi-step launch (TrainStep.run_steps) over its step count."""
        if wall_s is None:
            if self._t0 is None:
                return
            wall_s = time.perf_counter() - self._t0
        self._t0 = None
        self._steps += steps
        if items is None and self.items_per_step is not None:
            items = self.items_per_step * steps
        rec = {"step": self._steps, "wall_s": wall_s, "steps": steps,
               "step_ms": wall_s / max(steps, 1) * 1e3,
               "compiled": self._compiled_this_step > 0,
               "recompiles_total": self.recompiles,
               "ts": time.time()}
        if items is not None:
            rec["items"] = items
            rec["items_per_s"] = items / wall_s if wall_s > 0 else None
            mfu = self._mfu(items / max(steps, 1),
                            wall_s / max(steps, 1))
            if mfu is not None:
                rec["mfu"] = round(mfu, 4)
        if self._jit_miss_0 is not None:
            rec["jit_cache_misses"] = _jit_cache_misses() - self._jit_miss_0
        self._jit_miss_0 = None
        self._compiled_this_step = 0
        if self.track_memory and self._memory_due():
            mem = self._memory()
            if mem is not None:
                rec["hbm_bytes_in_use"] = mem.get("bytes_in_use")
                rec["hbm_peak_bytes"] = mem.get("peak_bytes_in_use")
        self.records.append(rec)
        if self.jsonl_path:
            with open(self.jsonl_path, "a") as f:
                f.write(json.dumps(rec) + "\n")
        if self.on_report is not None:
            self.on_report(rec)
        return rec

    @contextlib.contextmanager
    def step(self, items: Optional[float] = None, steps: int = 1):
        self.begin_step()
        try:
            yield self
        finally:
            self.end_step(items=items, steps=steps)

    # ----------------------------------------------------------- compiles
    def record_compile(self, kind: str, sig, prev_sig=None,
                       count: bool = True):
        """Called by the traced-step owner on a compile-cache miss. A miss
        with a prior signature is a RECOMPILE: log the shape delta.

        count=False logs/records the shape-delta WARNING without feeding
        the compiles/recompiles counters — for events where no executable
        was actually (re)built, e.g. a serving request REFUSED because it
        would have forced one. The numeric counters stay a pure signal of
        real executable churn; the event stream carries the warning."""
        if count:
            self.compiles += 1
            self._compiled_this_step += 1
        if prev_sig is not None:
            if count:
                self.recompiles += 1
            delta = shape_delta(prev_sig, sig)
            self.recompile_events.append(
                {"step": self._steps + 1, "kind": kind, "delta": delta})
            if self.log_recompiles:
                logger.warning("%s of %s at step %d: %s",
                               "recompilation" if count
                               else "refused shape change",
                               kind, self._steps + 1, delta)

    # ------------------------------------------------------------ overlap
    def record_overlap(self, overlap):
        """Adopt a compute/communication overlap measurement as a
        first-class gauge. `overlap` is trace_analysis.TraceAnalysis
        .overlap()'s dict (or a bare ratio float). Until now this number
        only existed inside DistributedView's rendered table; recording
        it here puts `overlap_ratio` into report()/metrics_text() so
        dashboards can TRACK it — the baseline the distributed
        compute/comm-overlap work is measured against.
        ProfilerCallback feeds this automatically after each captured
        trace."""
        if overlap is None:
            return
        if not isinstance(overlap, dict):
            overlap = {"ratio": float(overlap)}
        self.overlap = dict(overlap)
        if self.jsonl_path and overlap.get("ratio") is not None:
            with open(self.jsonl_path, "a") as f:
                f.write(json.dumps({"overlap": self.overlap,
                                    "ts": time.time()}) + "\n")
        return self.overlap

    # ----------------------------------------------------------- numerics
    def record_numerics(self, step: int, loss: Optional[float] = None,
                        grad_norm: Optional[float] = None, events=()):
        """Called by the debugging layer at each stats fetch: loss/grad-norm
        land in the JSONL stream (one `numerics` row per fetch), and every
        NumericsEvent is recorded + logged. Cheap: only runs at the fetch
        cadence, never per step."""
        row = {"numerics": {"step": step, "loss": loss,
                            "grad_norm": grad_norm},
               "ts": time.time()}
        self._last_numerics = {"step": step, "loss": loss,
                               "grad_norm": grad_norm}
        evs = [e.to_dict() if hasattr(e, "to_dict") else dict(e)
               for e in events]
        if evs:
            row["numerics"]["events"] = evs
            self.numerics_events.extend(evs)
            for e in evs:
                logger.warning("numerics event at step %s: %s %s — %s",
                               e.get("step"), e.get("kind"),
                               e.get("path") or "", e.get("message"))
        if self.jsonl_path:
            with open(self.jsonl_path, "a") as f:
                f.write(json.dumps(row) + "\n")
        if self.on_report is not None:
            self.on_report(row)
        return row

    # ------------------------------------------------------------ internals
    def _peak(self) -> Optional[float]:
        if self.peak_flops is not None:
            return self.peak_flops
        try:
            from ..device import chip_peak_flops
            self.peak_flops = chip_peak_flops()
        except Exception:
            self.peak_flops = None
        return self.peak_flops

    def _mfu(self, items_per_step, step_s) -> Optional[float]:
        flops = self.flops_per_step
        if flops is None and self.flops_per_item is not None \
                and items_per_step is not None:
            flops = self.flops_per_item * items_per_step
        peak = self._peak()
        if flops is None or peak is None or not step_s:
            return None
        return flops / step_s / peak

    def _memory_due(self) -> bool:
        if self._mem_every is None:
            every = self.memory_sample_every
            if every is None:
                try:
                    from ..device import has_allocator_stats
                    every = 1 if has_allocator_stats() else 10
                except Exception:
                    every = 10
            self._mem_every = max(1, int(every))
        n = len(self.records) + 1   # this end_step call's ordinal
        return n == 1 or n % self._mem_every == 0

    def _memory(self) -> Optional[dict]:
        try:
            from ..device import memory_stats
            return memory_stats()
        except Exception:
            return None

    # -------------------------------------------------- resumable counters
    def state_dict(self) -> dict:
        """Counter continuity across a preemption/resume (the
        resilience.TrainState "monitor" slot): steps keep accumulating and
        the compile counters keep their pre-kill baseline, so the
        telemetry stream shows ONE job with a resume in it — a resumed run
        re-reporting step 0 (or a recompile storm that is really just the
        restart's warm-up compiles) would defeat the dashboards."""
        return {"steps": int(self._steps), "compiles": int(self.compiles),
                "recompiles": int(self.recompiles)}

    def set_state_dict(self, state: dict):
        self._steps = int(state.get("steps", 0))
        self.compiles = int(state.get("compiles", 0))
        self.recompiles = int(state.get("recompiles", 0))
        return self

    # ------------------------------------------------------------- report
    def report(self) -> dict:
        """Aggregate summary. Steady step time is the median over steps
        with no compile in them (compile steps fold XLA compilation into
        wall time and would poison the figure)."""
        steady = [r for r in self.records if not r["compiled"]] or self.records
        step_ms = sorted(r["step_ms"] for r in steady) if steady else []
        med = step_ms[len(step_ms) // 2] if step_ms else None
        items_s = None
        tot_items = sum(r.get("items", 0) for r in steady)
        tot_wall = sum(r["wall_s"] for r in steady)
        if tot_items and tot_wall:
            items_s = tot_items / tot_wall
        mfu = self._mfu(
            tot_items / max(sum(r["steps"] for r in steady), 1) if tot_items
            else None,
            med / 1e3 if med else None)
        peak_hbm = max((r.get("hbm_peak_bytes") or 0 for r in self.records),
                       default=0) or None
        last_hbm = next((r.get("hbm_bytes_in_use") for r in
                         reversed(self.records)
                         if r.get("hbm_bytes_in_use") is not None), None)
        num = {"numerics_events": len(self.numerics_events)}
        if self._last_numerics:
            num["loss"] = self._last_numerics.get("loss")
            num["grad_norm"] = self._last_numerics.get("grad_norm")
        return {"steps": self._steps,
                **num,
                "overlap_ratio": (self.overlap or {}).get("ratio"),
                "step_ms": round(med, 3) if med is not None else None,
                "items_per_s": round(items_s, 1) if items_s else None,
                "unit": self.unit,
                "mfu": round(mfu, 4) if mfu is not None else None,
                "hbm_bytes_in_use": last_hbm,
                "hbm_peak_bytes": peak_hbm,
                "compiles": self.compiles,
                "recompiles": self.recompiles,
                "jit_cache_misses": (
                    sum(r.get("jit_cache_misses", 0) for r in self.records)
                    if any("jit_cache_misses" in r for r in self.records)
                    else None)}

    def metrics_text(self, prefix: str = "paddle_tpu") -> str:
        """Prometheus-exposition dump of report() — the `/metrics` payload a
        serving endpoint returns. Rendered by the shared profiler._metrics
        formatter (the serving layer's ServingMetrics uses the same one, so
        a frontend scrapes both blocks as one page)."""
        from ._metrics import gauge_lines
        r = self.report()
        lines = []

        def gauge(name, val, help_):
            lines.extend(gauge_lines(prefix, name, val, help_))

        gauge("steps_total", r["steps"], "steps recorded")
        if r["step_ms"] is not None:
            gauge("step_seconds", round(r["step_ms"] / 1e3, 6),
                  "median steady step wall time")
        gauge("throughput", r["items_per_s"],
              f"steady throughput ({r['unit']})")
        gauge("mfu", r["mfu"], "achieved model FLOPs utilization")
        gauge("hbm_bytes_in_use", r["hbm_bytes_in_use"],
              "live device memory")
        gauge("hbm_peak_bytes", r["hbm_peak_bytes"], "peak device memory")
        gauge("compiles_total", r["compiles"], "traced-step compiles")
        gauge("recompiles_total", r["recompiles"],
              "recompilations (shape-signature changes)")
        gauge("overlap_ratio", r["overlap_ratio"],
              "compute/comm overlap: fraction of collective time hidden "
              "under device compute (latest captured trace)")
        gauge("jit_cache_misses_total", r["jit_cache_misses"],
              "jit compile-cache misses during monitored steps")
        gauge("numerics_events_total", r["numerics_events"],
              "numerics anomalies detected (nan/inf/grad/loss/dead-layer)")
        gauge("loss", r.get("loss"), "latest fetched training loss")
        gauge("grad_norm", r.get("grad_norm"),
              "latest fetched global gradient norm")
        return "\n".join(lines) + "\n"
