"""StepMonitor — always-on, low-overhead per-step training telemetry.

The profiler (trace capture + trace_analysis) is the deep-dive tool; this is
the steady-state gauge cluster a production run keeps on every step:

  - per-step wall time and items/sec (tokens or images — caller configures
    `items_per_step` or passes `items=` per step)
  - achieved MFU against the chip's peak matmul FLOP/s
    (paddle_tpu.device.chip_peak_flops)
  - live/peak HBM via paddle_tpu.device.memory_stats()
  - jit cache-miss counts and a RECOMPILATION DETECTOR: when a traced step
    compiles again, the offending abstract-shape delta (old vs new
    shape/dtype signature) is logged and recorded
  - per-shard step-wall SKEW (`record_shard_steps`, ISSUE 13): in a
    multi-shard job every shard times its own step; feeding the walls here
    yields slowest-shard / skew-ratio gauges and a structured straggler
    event on the TRANSITION into sustained skew — the fleet-level signal
    that one host/chip is dragging the collective-synchronized step.
  - the per-collective comm ledger (`record_collectives`, ISSUE 13):
    trace_analysis.collective_rows() adopted as tracked gauges, labeled
    per op — the decomposition of `overlap_ratio` the quantized-collective
    and comm-scheduling work is judged against.

Each step appends one JSONL row when `jsonl_path` is set, and `on_report`
(if given) is called with the row dict — the hook a metrics exporter or a
live dashboard attaches to (`_emit` is the shared path, mirroring
ServingMetrics). `jit.TrainStep(monitor=...)` wires this in
automatically; `hapi` exposes it as `callbacks.ProfilerCallback`.
"""
from __future__ import annotations

import contextlib
import json
import logging
import time
from typing import Callable, Optional

logger = logging.getLogger("paddle_tpu.profiler.monitor")


def shape_delta(old_sig, new_sig) -> str:
    """Human-readable delta between two abstract-shape signatures (tuples of
    (shape, dtype) leaves) — the payload of a recompilation log line."""
    if old_sig is None:
        return "first compile"
    old, new = list(old_sig), list(new_sig)
    if len(old) != len(new):
        return f"leaf count {len(old)} -> {len(new)}"
    diffs = []
    for i, (o, n) in enumerate(zip(old, new)):
        if o != n:
            diffs.append(f"leaf[{i}]: {o} -> {n}")
    return "; ".join(diffs) if diffs else "signature changed (non-shape key)"


def _jit_cache_misses() -> int:
    from ..jit.api import compile_cache_misses
    return compile_cache_misses()


class StepMonitor:
    """Record per-step metrics; see module docstring.

    flops_per_step / flops_per_item: model FLOPs for the MFU figure (set
    either; `flops_per_item` multiplies the per-step item count). May be
    assigned after the run, before report() — MFU is computed at report
    time. `peak_flops` defaults to the chip's bf16 peak.
    """

    def __init__(self, *, flops_per_step: Optional[float] = None,
                 flops_per_item: Optional[float] = None,
                 items_per_step: Optional[float] = None,
                 unit: str = "items/s", peak_flops: Optional[float] = None,
                 jsonl_path: Optional[str] = None,
                 on_report: Optional[Callable[[dict], None]] = None,
                 track_memory: bool = True,
                 memory_sample_every: Optional[int] = None,
                 log_recompiles: bool = True,
                 straggler_threshold: float = 1.5,
                 jsonl_flush_every: int = 1):
        self.flops_per_step = flops_per_step
        self.flops_per_item = flops_per_item
        self.items_per_step = items_per_step
        self.unit = unit
        self.peak_flops = peak_flops
        self.jsonl_path = jsonl_path
        # JSONL write cadence (ISSUE 19 satellite, the r16 straggler-
        # granularity follow-up): 1 (default) opens/appends/closes per
        # row — every row durable immediately, the historical behavior.
        # >1 keeps one handle and flushes every N rows (the SpanRecorder
        # economics: a per-line flush costs most of a record()) — but
        # straggler/straggler_clear transitions ALWAYS force a flush, so
        # `load_shard_walls` stitching across live per-shard streams
        # sees skew events at transition granularity, not buffer
        # granularity.
        self.jsonl_flush_every = max(1, int(jsonl_flush_every))
        self._jsonl_f = None
        self._jsonl_unflushed = 0
        self.on_report = on_report
        self.track_memory = track_memory
        # allocator counters are cheap to read every step; the live-array
        # fallback (host platforms) scans every live buffer, so it samples
        # every 10th step unless overridden
        self.memory_sample_every = memory_sample_every
        self._mem_every = None
        self.log_recompiles = log_recompiles
        self.records = []          # one dict per end_step
        self.overlap = None        # latest compute/comm overlap (dict)
        self.collectives = []      # latest per-collective ledger rows
        # shard-skew state (ISSUE 13): `straggler_threshold` is the skew
        # ratio (slowest shard wall / median shard wall) at/above which a
        # shard counts as straggling; the structured event fires once per
        # TRANSITION into (and out of) that state, never per step
        self.straggler_threshold = float(straggler_threshold)
        self.shard_skew = None     # latest record_shard_steps figures
        self.straggler_events = []  # straggler/straggler_clear rows
        self.stragglers_total = 0   # transitions INTO straggling
        self._straggling = False
        self.compiles = 0          # traced-step compiles observed
        self.recompiles = 0        # compiles beyond the first per kind
        self.recompile_events = []  # {step, kind, delta}
        self.numerics_events = []   # NumericsEvent dicts (debugging layer)
        self._last_numerics = {}    # latest fetched loss/grad_norm scalars
        self._steps = 0
        self._t0 = None
        self._jit_miss_0 = None
        self._compiled_this_step = 0
        # anomaly-triggered profiling (ISSUE 17): an attached
        # obs.FlightRecorder rides the step brackets — its capture state
        # machine advances at step boundaries, OUTSIDE the timed window
        # (trace start/stop cost must not pollute step walls)
        self.flightrec = None
        # HBM ledger (ISSUE 18): when attached, per-step memory samples
        # read the ledger's free host counters EVERY step — the
        # live-array scan rationing below becomes moot (it stays the
        # reconciliation path, never the per-step one)
        self.memz = None

    # ------------------------------------------------------------- steps
    def begin_step(self):
        fr = self.flightrec
        if fr is not None:
            fr.begin_step()
        self._jit_miss_0 = _jit_cache_misses()
        self._compiled_this_step = 0
        self._t0 = time.perf_counter()

    def end_step(self, items: Optional[float] = None, steps: int = 1,
                 wall_s: Optional[float] = None):
        """Close the step opened by begin_step (or record an externally
        timed window via `wall_s`). `steps` > 1 amortizes one fused
        multi-step launch (TrainStep.run_steps) over its step count."""
        external = wall_s is not None
        if wall_s is None:
            if self._t0 is None:
                return
            wall_s = time.perf_counter() - self._t0
        self._t0 = None
        self._steps += steps
        if items is None and self.items_per_step is not None:
            items = self.items_per_step * steps
        rec = {"step": self._steps, "wall_s": wall_s, "steps": steps,
               "step_ms": wall_s / max(steps, 1) * 1e3,
               "compiled": self._compiled_this_step > 0,
               "recompiles_total": self.recompiles,
               "ts": time.time()}
        if items is not None:
            rec["items"] = items
            rec["items_per_s"] = items / wall_s if wall_s > 0 else None
            mfu = self._mfu(items / max(steps, 1),
                            wall_s / max(steps, 1))
            if mfu is not None:
                rec["mfu"] = round(mfu, 4)
        if self._jit_miss_0 is not None:
            rec["jit_cache_misses"] = _jit_cache_misses() - self._jit_miss_0
        self._jit_miss_0 = None
        self._compiled_this_step = 0
        if self.track_memory and self._memory_due():
            mem = self._memory()
            if mem is not None:
                rec["hbm_bytes_in_use"] = mem.get("bytes_in_use")
                rec["hbm_peak_bytes"] = mem.get("peak_bytes_in_use")
        self.records.append(rec)
        out = self._emit(rec)
        fr = self.flightrec
        if fr is not None:
            fr.end_step()
            if external:
                # externally timed launches (TrainStep's wall_s path)
                # never call begin_step — each end IS the step boundary,
                # so arm the recorder here for the NEXT launch
                fr.begin_step()
        return out

    @contextlib.contextmanager
    def step(self, items: Optional[float] = None, steps: int = 1):
        self.begin_step()
        try:
            yield self
        finally:
            self.end_step(items=items, steps=steps)

    # ----------------------------------------------------------- emission
    def _emit(self, row: dict, report: bool = True,
              jsonl: bool = True) -> dict:
        """One emission path for every structured row this monitor
        produces (step records, numerics, overlap, straggler events) —
        JSONL append + the on_report exporter hook stay in lockstep,
        mirroring ServingMetrics._emit. `report=False` keeps a row
        JSONL-only (rows that predate the shared path and whose on_report
        delivery would change existing consumers' row counts);
        `jsonl=False` is the inverse, for hook-only rows the JSONL
        stream's one-row-per-step consumers must not see."""
        if jsonl and self.jsonl_path:
            if self.jsonl_flush_every <= 1:
                with open(self.jsonl_path, "a") as f:
                    f.write(json.dumps(row) + "\n")
            else:
                if self._jsonl_f is None:
                    self._jsonl_f = open(self.jsonl_path, "a")
                self._jsonl_f.write(json.dumps(row) + "\n")
                self._jsonl_unflushed += 1
                if self._jsonl_unflushed >= self.jsonl_flush_every:
                    self.flush_jsonl()
        if report and self.on_report is not None:
            self.on_report(row)
        return row

    def flush_jsonl(self):
        """Force buffered JSONL rows to the file. A no-op in the default
        per-row mode; with `jsonl_flush_every` > 1 this is the handle
        every must-be-visible-now row (straggler transitions) rides."""
        if self._jsonl_f is not None:
            self._jsonl_f.flush()
            self._jsonl_unflushed = 0

    def close(self):
        """Flush and release the buffered JSONL handle (idempotent)."""
        if self._jsonl_f is not None:
            self._jsonl_f.flush()
            self._jsonl_f.close()
            self._jsonl_f = None
            self._jsonl_unflushed = 0

    # ----------------------------------------------------------- compiles
    def record_compile(self, kind: str, sig, prev_sig=None,
                       count: bool = True):
        """Called by the traced-step owner on a compile-cache miss. A miss
        with a prior signature is a RECOMPILE: log the shape delta.

        count=False logs/records the shape-delta WARNING without feeding
        the compiles/recompiles counters — for events where no executable
        was actually (re)built, e.g. a serving request REFUSED because it
        would have forced one. The numeric counters stay a pure signal of
        real executable churn; the event stream carries the warning."""
        if count:
            self.compiles += 1
            self._compiled_this_step += 1
        if prev_sig is not None:
            if count:
                self.recompiles += 1
            delta = shape_delta(prev_sig, sig)
            self.recompile_events.append(
                {"step": self._steps + 1, "kind": kind, "delta": delta})
            if self.log_recompiles:
                logger.warning("%s of %s at step %d: %s",
                               "recompilation" if count
                               else "refused shape change",
                               kind, self._steps + 1, delta)
            # structured row (ISSUE 17): recompiles join the on_report
            # stream like straggler/numerics rows, so the flight
            # recorder's trigger bus can pin a capture of the steps
            # around the executable churn. Hook-only: the JSONL file
            # keeps its one-row-per-step cadence (recompile_events and
            # the step rows' `compiled` flag already record it there).
            self._emit({"recompile": {"step": self._steps + 1,
                                      "kind": kind, "delta": delta,
                                      "counted": bool(count)},
                        "ts": time.time()}, jsonl=False)

    # ------------------------------------------------------------ overlap
    def record_overlap(self, overlap):
        """Adopt a compute/communication overlap measurement as a
        first-class gauge. `overlap` is trace_analysis.TraceAnalysis
        .overlap()'s dict (or a bare ratio float). Until now this number
        only existed inside DistributedView's rendered table; recording
        it here puts `overlap_ratio` into report()/metrics_text() so
        dashboards can TRACK it — the baseline the distributed
        compute/comm-overlap work is measured against.
        ProfilerCallback feeds this automatically after each captured
        trace."""
        if overlap is None:
            return
        if not isinstance(overlap, dict):
            overlap = {"ratio": float(overlap)}
        self.overlap = dict(overlap)
        if overlap.get("ratio") is not None:
            self._emit({"overlap": self.overlap, "ts": time.time()},
                       report=False)
        return self.overlap

    def record_collectives(self, rows):
        """Adopt a per-collective ledger (trace_analysis.collective_rows()
        or obs.collectives.CollectiveLedger.rows) as tracked gauges. Where
        record_overlap keeps ONE scalar — "is comm hidden overall" — this
        keeps the decomposition: per-collective seconds / exposed seconds /
        bytes / bus bandwidth land in report() and metrics_text() labeled
        by op, so a dashboard tracks WHICH collective's exposed time the
        comm-scheduling work shrinks. ProfilerCallback feeds this after
        each captured trace, right next to record_overlap."""
        self.collectives = [dict(r) for r in (rows or [])]
        if self.collectives:
            self._emit({"collectives": self.collectives,
                        "ts": time.time()}, report=False)
        return self.collectives

    # --------------------------------------------------------- shard skew
    def record_shard_steps(self, walls, step: Optional[int] = None):
        """Per-shard step walls for ONE step (or fused-step window):
        `walls` maps shard id -> wall seconds. In a collective-synchronized
        step every shard waits for the slowest, so the job's step time IS
        max(walls); the skew ratio max/median says how much wall the
        straggler costs everyone else.

        Updates the `shard_skew` gauges (slowest shard, skew ratio,
        per-shard walls) and runs the straggler state machine: skew at or
        above `straggler_threshold` marks the run straggling, and the
        structured {"straggler": ...} row goes through `_emit` (JSONL +
        on_report) exactly ONCE per transition — with a matching
        {"straggler_clear": ...} when the skew recovers — never a row per
        step (a sustained straggler would otherwise spam the stream at
        step rate)."""
        walls = {str(k): float(v) for k, v in dict(walls).items()}
        if not walls:
            return None
        slowest = max(walls, key=walls.get)
        # baseline = median of the OTHER shards: including the slowest in
        # its own baseline mutes the signal exactly where it matters most
        # (2 shards: max/median-of-all is identically 1.0 or the upper
        # middle — the straggler would judge itself)
        rest = sorted(v for k, v in walls.items() if k != slowest) \
            or [walls[slowest]]
        n = len(rest)
        median = rest[n // 2] if n % 2 \
            else (rest[n // 2 - 1] + rest[n // 2]) / 2.0
        skew = walls[slowest] / median if median > 0 else 1.0
        self.shard_skew = {"step": step, "shards": len(walls),
                           "walls": walls,
                           "slowest_shard": slowest,
                           "slowest_wall_s": walls[slowest],
                           "median_wall_s": median,
                           "skew_ratio": skew}
        straggling = len(walls) > 1 and skew >= self.straggler_threshold
        if straggling != self._straggling:
            self._straggling = straggling
            kind = "straggler" if straggling else "straggler_clear"
            if straggling:
                self.stragglers_total += 1
            event = {kind: dict(self.shard_skew,
                                threshold=self.straggler_threshold),
                     "ts": time.time()}
            self.straggler_events.append(event)
            if straggling:
                logger.warning(
                    "straggler at step %s: shard %s at %.4fs vs median "
                    "%.4fs (skew %.2fx >= %.2fx)", step, slowest,
                    walls[slowest], median, skew,
                    self.straggler_threshold)
            self._emit(event)
            # transition rows must be durable NOW (ISSUE 19 satellite):
            # a buffered stream would hide the skew event from
            # load_shard_walls stitching until 64 unrelated rows later
            self.flush_jsonl()
        return self.shard_skew

    @property
    def straggling(self) -> bool:
        return self._straggling

    # ----------------------------------------------------------- numerics
    def record_numerics(self, step: int, loss: Optional[float] = None,
                        grad_norm: Optional[float] = None, events=()):
        """Called by the debugging layer at each stats fetch: loss/grad-norm
        land in the JSONL stream (one `numerics` row per fetch), and every
        NumericsEvent is recorded + logged. Cheap: only runs at the fetch
        cadence, never per step."""
        row = {"numerics": {"step": step, "loss": loss,
                            "grad_norm": grad_norm},
               "ts": time.time()}
        self._last_numerics = {"step": step, "loss": loss,
                               "grad_norm": grad_norm}
        evs = [e.to_dict() if hasattr(e, "to_dict") else dict(e)
               for e in events]
        if evs:
            row["numerics"]["events"] = evs
            self.numerics_events.extend(evs)
            for e in evs:
                logger.warning("numerics event at step %s: %s %s — %s",
                               e.get("step"), e.get("kind"),
                               e.get("path") or "", e.get("message"))
        return self._emit(row)

    # ------------------------------------------------------------ internals
    def _peak(self) -> Optional[float]:
        if self.peak_flops is not None:
            return self.peak_flops
        try:
            from ..device import chip_peak_flops
            self.peak_flops = chip_peak_flops()
        except Exception:
            self.peak_flops = None
        return self.peak_flops

    def _mfu(self, items_per_step, step_s) -> Optional[float]:
        flops = self.flops_per_step
        if flops is None and self.flops_per_item is not None \
                and items_per_step is not None:
            flops = self.flops_per_item * items_per_step
        peak = self._peak()
        if flops is None or peak is None or not step_s:
            return None
        return flops / step_s / peak

    def _memory_due(self) -> bool:
        if self.memz is not None:
            # ledger host counters are free — sample every record (the
            # r7 every-10th rationing exists only for live-array scans)
            return True
        if self._mem_every is None:
            every = self.memory_sample_every
            if every is None:
                try:
                    from ..device import has_allocator_stats
                    every = 1 if has_allocator_stats() else 10
                except Exception:
                    every = 10
            self._mem_every = max(1, int(every))
        n = len(self.records) + 1   # this end_step call's ordinal
        return n == 1 or n % self._mem_every == 0

    def _memory(self) -> Optional[dict]:
        if self.memz is not None:
            try:
                return self.memz.quick_stats()
            except Exception:
                pass                  # fall through to the device view
        try:
            from ..device import memory_stats
            return memory_stats()
        except Exception:
            return None

    # -------------------------------------------------- resumable counters
    def state_dict(self) -> dict:
        """Counter continuity across a preemption/resume (the
        resilience.TrainState "monitor" slot): steps keep accumulating and
        the compile counters keep their pre-kill baseline, so the
        telemetry stream shows ONE job with a resume in it — a resumed run
        re-reporting step 0 (or a recompile storm that is really just the
        restart's warm-up compiles) would defeat the dashboards."""
        return {"steps": int(self._steps), "compiles": int(self.compiles),
                "recompiles": int(self.recompiles),
                "stragglers": int(self.stragglers_total)}

    def set_state_dict(self, state: dict):
        self._steps = int(state.get("steps", 0))
        self.compiles = int(state.get("compiles", 0))
        self.recompiles = int(state.get("recompiles", 0))
        self.stragglers_total = int(state.get("stragglers", 0))
        return self

    # ------------------------------------------------------------- report
    def report(self) -> dict:
        """Aggregate summary. Steady step time is the median over steps
        with no compile in them (compile steps fold XLA compilation into
        wall time and would poison the figure)."""
        steady = [r for r in self.records if not r["compiled"]] or self.records
        step_ms = sorted(r["step_ms"] for r in steady) if steady else []
        med = step_ms[len(step_ms) // 2] if step_ms else None
        items_s = None
        tot_items = sum(r.get("items", 0) for r in steady)
        tot_wall = sum(r["wall_s"] for r in steady)
        if tot_items and tot_wall:
            items_s = tot_items / tot_wall
        mfu = self._mfu(
            tot_items / max(sum(r["steps"] for r in steady), 1) if tot_items
            else None,
            med / 1e3 if med else None)
        peak_hbm = max((r.get("hbm_peak_bytes") or 0 for r in self.records),
                       default=0) or None
        last_hbm = next((r.get("hbm_bytes_in_use") for r in
                         reversed(self.records)
                         if r.get("hbm_bytes_in_use") is not None), None)
        num = {"numerics_events": len(self.numerics_events)}
        if self._last_numerics:
            num["loss"] = self._last_numerics.get("loss")
            num["grad_norm"] = self._last_numerics.get("grad_norm")
        shard = {}
        if self.shard_skew is not None:
            shard = {"shard_skew_ratio": round(
                         self.shard_skew["skew_ratio"], 4),
                     "slowest_shard": self.shard_skew["slowest_shard"],
                     "stragglers_total": self.stragglers_total,
                     "straggling": self._straggling}
        coll = {}
        if self.collectives:
            coll = {"collectives": [
                {"name": r["name"],
                 "ms": round(r["dur_us"] / 1e3, 3),
                 "exposed_ms": round(r["exposed_us"] / 1e3, 3),
                 "bytes": r.get("bytes"),
                 "bus_gbps": (round(r["bus_gbps"], 2)
                              if r.get("bus_gbps") is not None else None)}
                for r in self.collectives]}
        return {"steps": self._steps,
                **num,
                "overlap_ratio": (self.overlap or {}).get("ratio"),
                **shard, **coll,
                "step_ms": round(med, 3) if med is not None else None,
                "items_per_s": round(items_s, 1) if items_s else None,
                "unit": self.unit,
                "mfu": round(mfu, 4) if mfu is not None else None,
                "hbm_bytes_in_use": last_hbm,
                "hbm_peak_bytes": peak_hbm,
                "compiles": self.compiles,
                "recompiles": self.recompiles,
                "jit_cache_misses": (
                    sum(r.get("jit_cache_misses", 0) for r in self.records)
                    if any("jit_cache_misses" in r for r in self.records)
                    else None)}

    def metrics_text(self, prefix: str = "paddle_tpu") -> str:
        """Prometheus-exposition dump of report() — the `/metrics` payload a
        serving endpoint returns. Rendered by the shared profiler._metrics
        formatter (the serving layer's ServingMetrics uses the same one, so
        a frontend scrapes both blocks as one page)."""
        from ._metrics import gauge_lines
        r = self.report()
        lines = []

        def gauge(name, val, help_):
            lines.extend(gauge_lines(prefix, name, val, help_))

        gauge("steps_total", r["steps"], "steps recorded")
        if r["step_ms"] is not None:
            gauge("step_seconds", round(r["step_ms"] / 1e3, 6),
                  "median steady step wall time")
        gauge("throughput", r["items_per_s"],
              f"steady throughput ({r['unit']})")
        gauge("mfu", r["mfu"], "achieved model FLOPs utilization")
        gauge("hbm_bytes_in_use", r["hbm_bytes_in_use"],
              "live device memory")
        gauge("hbm_peak_bytes", r["hbm_peak_bytes"], "peak device memory")
        gauge("compiles_total", r["compiles"], "traced-step compiles")
        gauge("recompiles_total", r["recompiles"],
              "recompilations (shape-signature changes)")
        gauge("overlap_ratio", r["overlap_ratio"],
              "compute/comm overlap: fraction of collective time hidden "
              "under device compute (latest captured trace)")
        # per-collective ledger (ISSUE 13): one labeled sample per op per
        # series — the decomposition of overlap_ratio; series definition
        # shared with obs.CollectiveLedger
        if self.collectives:
            from .trace_analysis import collective_series_lines
            lines += collective_series_lines(self.collectives, prefix)
        # shard-skew gauges (ISSUE 13)
        if self.shard_skew is not None:
            from ._metrics import labeled_gauge_lines
            lines += labeled_gauge_lines(
                prefix, "shard_step_seconds", "shard",
                sorted(self.shard_skew["walls"].items()),
                "latest per-shard step wall time")
            gauge("shard_skew_ratio", r.get("shard_skew_ratio"),
                  "slowest shard step wall / median shard step wall")
            slowest = self.shard_skew["slowest_shard"]
            try:
                gauge("slowest_shard", int(slowest),
                      "shard id with the slowest latest step wall")
            except (TypeError, ValueError):
                pass                    # non-numeric shard names: the
            #                             labeled walls carry the identity
            gauge("straggling", 1 if self._straggling else 0,
                  "a shard is currently straggling (skew over threshold)")
            gauge("stragglers_total", self.stragglers_total,
                  "transitions into straggling state")
        gauge("jit_cache_misses_total", r["jit_cache_misses"],
              "jit compile-cache misses during monitored steps")
        gauge("numerics_events_total", r["numerics_events"],
              "numerics anomalies detected (nan/inf/grad/loss/dead-layer)")
        gauge("loss", r.get("loss"), "latest fetched training loss")
        gauge("grad_norm", r.get("grad_norm"),
              "latest fetched global gradient norm")
        return "\n".join(lines) + "\n"
