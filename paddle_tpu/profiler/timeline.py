"""Goodput timeline — wall-clock attribution spans for one training job.

The observability stack can say how fast a step is (StepMonitor), whether
its numerics are healthy (debugging) and what a request experienced
(serving traces) — this module answers the remaining question: where did
the JOB's wall-clock go? Every second of a run is attributed to one
category of a FIXED taxonomy (CATEGORIES below): productive step compute
is goodput, everything else — compile, input stalls, blocking checkpoint
work, restart downtime, replayed steps — is badput, and whatever no span
claims is idle. `profiler.goodput.GoodputReport` aggregates the spans
into goodput% + a per-category badput breakdown and enforces the
conservation property (categorized + idle ≡ wall within ε).

Design:

  - `SpanRecorder` is thread-safe and monotonic-clock based: span
    endpoints come from ``time.monotonic()`` relative to the recorder's
    birth, so NTP jumps can't corrupt durations. Each segment file
    additionally records its birth ``time.time()`` anchor, which is how
    segments from DIFFERENT processes (a job that died and restarted)
    stitch onto one absolute timeline.
  - Spans are ring-buffered in memory (`capacity` newest kept for live
    reporting) and appended to a JSONL segment file (one open file
    handle, one flushed line per span — the same one-row-per-event
    convention as StepMonitor's JSONL stream). A SIGKILL mid-run loses
    nothing already flushed; the stitcher tolerates a missing exit stamp.
  - `mark_exit(reason=...)` stamps the segment's end — the preemption
    handler calls it so the gap to the next segment's first span is
    attributable as `restart_downtime`.
  - Instrumented seams (jit.TrainStep, io.DataLoader,
    resilience.CheckpointManager, fleet.elastic) find the recorder via
    the module-global `current()` (set with `install()` /
    `installed()`), or via an explicit `timeline=` handle. When no
    recorder is installed the per-step cost is one attribute read.

Recorder overhead is part of the contract: one `record()` is a lock, a
deque append and one buffered JSONL line — tests assert the per-span
cost stays under 1% of the CPU toy's median step wall.
"""
from __future__ import annotations

import contextlib
import glob as _glob
import json
import os
import threading
import time
import uuid
from collections import deque
from typing import Any, Dict, List, Optional

# The fixed badput taxonomy. `step` is the goodput category; a stitched
# report recategorizes post-restart re-runs of already-seen steps as
# `replay`. Everything else is badput by definition; un-spanned wall time
# is `idle` (computed, never recorded).
CATEGORIES = ("compile", "input_wait", "step", "ckpt_blocking",
              "ckpt_drain", "restart_downtime", "replay", "eval", "other")
GOODPUT_CATEGORY = "step"

SEGMENT_SUFFIX = ".timeline.jsonl"


class Span:
    """One attributed interval. `t0`/`t1` are seconds relative to the
    owning segment's monotonic birth; `abs0`/`abs1` (epoch seconds) exist
    once the segment anchor is applied (load_segments / live recorder)."""

    __slots__ = ("cat", "t0", "t1", "step", "steps", "meta", "abs0", "abs1")

    def __init__(self, cat: str, t0: float, t1: float,
                 step: Optional[int] = None, steps: int = 1,
                 meta: Optional[dict] = None,
                 abs0: Optional[float] = None, abs1: Optional[float] = None):
        self.cat = cat
        self.t0 = t0
        self.t1 = t1
        self.step = step
        self.steps = steps
        self.meta = meta
        self.abs0 = abs0
        self.abs1 = abs1

    @property
    def dur(self) -> float:
        return self.t1 - self.t0

    def to_row(self) -> dict:
        row: Dict[str, Any] = {"cat": self.cat,
                               "t0": round(self.t0, 6),
                               "t1": round(self.t1, 6)}
        if self.step is not None:
            row["step"] = self.step
        if self.steps != 1:
            row["steps"] = self.steps
        if self.meta:
            row["meta"] = self.meta
        return row

    def __repr__(self):
        s = f" step={self.step}" if self.step is not None else ""
        return f"Span({self.cat}, {self.t0:.4f}..{self.t1:.4f}{s})"


class SpanRecorder:
    """Record attribution spans for ONE process segment of a job.

        rec = SpanRecorder("run/seg.timeline.jsonl", meta={"job": "gpt"})
        with rec.span("step", step=12):
            train_step(batch)
        rec.mark_exit(reason="preemption")
        rec.close()

    `path=None` keeps spans in memory only (tests / ad-hoc use).
    `now()` is the recorder's clock — instrumentation that measures a
    wait itself passes explicit `record(cat, t0, t1)` endpoints from it.
    """

    def __init__(self, path: Optional[str] = None, *,
                 capacity: int = 65536, meta: Optional[dict] = None,
                 start_step: Optional[int] = None, flush_every: int = 64):
        self.path = path
        self.segment_id = uuid.uuid4().hex[:12]
        self.wall0 = time.time()
        self._mono0 = time.monotonic()
        self.meta = dict(meta or {})
        self.start_step = start_step
        self._spans: deque = deque(maxlen=int(capacity))
        self._lock = threading.Lock()
        self._f = None
        self._exit: Optional[dict] = None
        self.dropped = 0          # spans evicted from the ring (file keeps all)
        # flush cadence: fsync-less flush per line costs ~50µs — most of
        # a record() — so rows flush every `flush_every` spans plus on
        # mark_exit/close. A real SIGKILL can lose the unflushed tail
        # (it delivers no exit stamp either); the stitcher then measures
        # downtime from the last flushed span — a slight overestimate,
        # on the side that makes badput look worse, never better.
        self._flush_every = max(1, int(flush_every))
        self._unflushed = 0
        # pre-first-span init accounting (ISSUE 17 satellite): install()
        # stamps an anchor; the first record() materializes the
        # install->first-span gap as an `other` span when it is big
        # enough to matter — the report's wall starts at its first span,
        # so un-anchored build/init time would be silently excluded
        self._init_anchor: Optional[float] = None
        self.init_gap_min_s = 0.02
        if path is not None:
            d = os.path.dirname(os.path.abspath(path))
            os.makedirs(d, exist_ok=True)
            self._f = open(path, "a")
            self._write_row({"segment": {
                "id": self.segment_id, "pid": os.getpid(),
                "wall0": self.wall0,
                "start_step": start_step, "meta": self.meta}},
                flush=True)

    # ------------------------------------------------------------- clock
    def now(self) -> float:
        """Seconds since this recorder's birth (monotonic)."""
        return time.monotonic() - self._mono0

    def anchor_init(self):
        """Called by install(): remember 'now' so the wall between
        install and the first recorded span becomes a visible
        `other`-category init span instead of leaking out of the
        goodput ledger. A no-op once spans exist (re-installs of a
        seasoned recorder must not fabricate init time)."""
        with self._lock:
            if not self._spans and self._init_anchor is None:
                self._init_anchor = self.now()

    def _write_row(self, row: dict, flush: bool = False):
        if self._f is None:
            return
        self._f.write(json.dumps(row) + "\n")
        self._unflushed += 1
        if flush or self._unflushed >= self._flush_every:
            self._f.flush()
            self._unflushed = 0

    # ------------------------------------------------------------ record
    def record(self, cat: str, t0: float, t1: float, *,
               step: Optional[int] = None, steps: int = 1,
               **meta) -> Span:
        """Attribute [t0, t1) (recorder-relative seconds, from `now()`)
        to `cat`. Categories are CLOSED — an unknown one raises, because
        a typo'd category would silently leak time out of the
        conservation ledger."""
        if cat not in CATEGORIES:
            raise ValueError(
                f"unknown timeline category {cat!r}; the taxonomy is "
                f"fixed: {CATEGORIES}")
        sp = Span(cat, float(t0), float(t1), step=step, steps=int(steps),
                  meta=meta or None,
                  abs0=self.wall0 + t0, abs1=self.wall0 + t1)
        with self._lock:
            anchor, self._init_anchor = self._init_anchor, None
            if anchor is not None and not self._spans \
                    and t0 - anchor >= self.init_gap_min_s:
                # materialize the install->first-span gap (see
                # anchor_init); sub-threshold gaps stay implicit so fast
                # installs keep recording exactly what they recorded
                isp = Span("other", anchor, float(t0),
                           meta={"init": True},
                           abs0=self.wall0 + anchor,
                           abs1=self.wall0 + t0)
                self._spans.append(isp)
                self._write_row(isp.to_row())
            if len(self._spans) == self._spans.maxlen:
                self.dropped += 1
            self._spans.append(sp)
            if self._f is not None:
                if meta:
                    self._write_row(sp.to_row())
                else:
                    # hot path: hand-format the row — json.dumps costs
                    # ~a third of a record() and plain rows need none
                    # of it (cat is vetted above, the rest is numeric)
                    line = f'{{"cat":"{cat}","t0":{sp.t0:.6f},' \
                           f'"t1":{sp.t1:.6f}'
                    if step is not None:
                        line += f',"step":{int(step)}'
                    if sp.steps != 1:
                        line += f',"steps":{sp.steps}'
                    self._f.write(line + "}\n")
                    self._unflushed += 1
                    if self._unflushed >= self._flush_every:
                        self._f.flush()
                        self._unflushed = 0
        return sp

    @contextlib.contextmanager
    def span(self, cat: str, *, step: Optional[int] = None,
             steps: int = 1, **meta):
        """Context-manager form of record(): times the body."""
        t0 = self.now()
        try:
            yield self
        finally:
            self.record(cat, t0, self.now(), step=step, steps=steps, **meta)

    def mark_exit(self, reason: Optional[str] = None, *,
                  step: Optional[int] = None, **meta):
        """Stamp the segment's end — the restart-downtime anchor. The
        preemption handler calls this right before raising Preempted;
        chaos drivers call it where the simulated SIGKILL landed.
        Idempotent (the first stamp wins: a poll-retry after a failed
        emergency save must not move the recorded death time)."""
        with self._lock:
            if self._exit is not None:
                return
            self._exit = {"t": self.now(), "reason": reason, "step": step,
                          **({"meta": meta} if meta else {})}
            self._write_row({"exit": self._exit}, flush=True)

    # ------------------------------------------------------------- views
    def spans(self) -> List[Span]:
        with self._lock:
            return list(self._spans)

    @property
    def exit_info(self) -> Optional[dict]:
        return self._exit

    def flush(self):
        with self._lock:
            if self._f is not None:
                self._f.flush()
                self._unflushed = 0

    def close(self):
        with self._lock:
            if self._f is not None:
                self._f.close()
                self._f = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


# ------------------------------------------------- module-global recorder

_current: Optional[SpanRecorder] = None
_current_lock = threading.Lock()


def current() -> Optional[SpanRecorder]:
    """The installed recorder (None when goodput accounting is off).
    Instrumented seams call this on their hot path — it is one module
    attribute read."""
    return _current


def install(rec: Optional[SpanRecorder]) -> Optional[SpanRecorder]:
    """Install `rec` as the process-wide recorder; returns the previous
    one (restore it when done — or use `installed()`). Installing a
    fresh recorder anchors its init accounting: wall spent between here
    and its first span lands as an `other` init span (anchor_init)."""
    global _current
    with _current_lock:
        prev, _current = _current, rec
    if rec is not None:
        try:
            rec.anchor_init()
        except AttributeError:
            pass                    # duck-typed recorder without anchors
    return prev


@contextlib.contextmanager
def installed(rec: SpanRecorder):
    prev = install(rec)
    try:
        yield rec
    finally:
        install(prev)


# ------------------------------------------------------ segment loading

class Segment:
    """One loaded segment file: absolute-time spans + the exit stamp."""

    def __init__(self, *, segment_id: str, wall0: float,
                 spans: List[Span], exit_row: Optional[dict] = None,
                 meta: Optional[dict] = None, path: Optional[str] = None,
                 start_step: Optional[int] = None):
        self.segment_id = segment_id
        self.wall0 = wall0
        self.spans = spans
        self.exit_row = exit_row
        self.meta = meta or {}
        self.path = path
        self.start_step = start_step

    @property
    def start(self) -> Optional[float]:
        """Absolute start: first span start (spans are append-ordered but
        not guaranteed sorted — threads interleave)."""
        return min((s.abs0 for s in self.spans), default=None)

    @property
    def end(self) -> Optional[float]:
        """Absolute end: last span end, or the exit stamp if later (a
        segment that died while blocked recorded no span for the tail)."""
        end = max((s.abs1 for s in self.spans), default=None)
        if self.exit_row is not None:
            ex = self.wall0 + self.exit_row["t"]
            end = ex if end is None else max(end, ex)
        return end

    @property
    def max_step(self) -> Optional[int]:
        return max((s.step for s in self.spans if s.step is not None),
                   default=None)


def from_recorder(rec: SpanRecorder) -> Segment:
    """Segment view of a LIVE recorder (ring only — prefer files for
    full-fidelity reports)."""
    return Segment(segment_id=rec.segment_id, wall0=rec.wall0,
                   spans=rec.spans(), exit_row=rec.exit_info,
                   meta=rec.meta, start_step=rec.start_step)


def _load_one(path: str) -> List[Segment]:
    """Parse one JSONL file. A file normally holds one segment, but an
    append-reused path (a restarted process writing to the same file)
    holds several — each `segment` header starts a new one."""
    segs: List[Segment] = []
    cur: Optional[Segment] = None
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                row = json.loads(line)
            except json.JSONDecodeError:
                continue          # torn tail line from a kill mid-write
            if "segment" in row:
                hdr = row["segment"]
                cur = Segment(segment_id=hdr.get("id", "?"),
                              wall0=float(hdr.get("wall0", 0.0)),
                              spans=[], meta=hdr.get("meta"),
                              path=path,
                              start_step=hdr.get("start_step"))
                segs.append(cur)
                continue
            if cur is None:       # header lost: synthesize an anchor
                cur = Segment(segment_id="?", wall0=0.0, spans=[],
                              path=path)
                segs.append(cur)
            if "exit" in row:
                cur.exit_row = row["exit"]
                continue
            if "cat" not in row:
                continue
            sp = Span(row["cat"], float(row["t0"]), float(row["t1"]),
                      step=row.get("step"), steps=int(row.get("steps", 1)),
                      meta=row.get("meta"))
            sp.abs0 = cur.wall0 + sp.t0
            sp.abs1 = cur.wall0 + sp.t1
            cur.spans.append(sp)
    return segs


def load_segments(paths) -> List[Segment]:
    """Load segments from files, directories (all `*.timeline.jsonl`
    under them) or glob patterns; returns them sorted by absolute start
    time — the stitch order GoodputReport consumes."""
    if isinstance(paths, (str, os.PathLike)):
        paths = [paths]
    files: List[str] = []
    for p in paths:
        p = os.fspath(p)
        if os.path.isdir(p):
            files.extend(sorted(_glob.glob(
                os.path.join(p, "**", "*" + SEGMENT_SUFFIX),
                recursive=True)))
        elif os.path.exists(p):
            files.append(p)
        else:
            hits = sorted(_glob.glob(p))
            if not hits:
                raise FileNotFoundError(f"no timeline segments match {p!r}")
            files.extend(hits)
    segs: List[Segment] = []
    for f in files:
        segs.extend(_load_one(f))
    segs = [s for s in segs if s.spans or s.exit_row is not None]
    segs.sort(key=lambda s: (s.start if s.start is not None
                             else s.wall0 + (s.exit_row or {}).get("t", 0)))
    return segs
