"""paddle.fft namespace as an importable module (reference:
python/paddle/fft/__init__.py); implementations on core.ops.fft."""
from .core.ops import fft as _fft

_names = [n for n in dir(_fft) if not n.startswith("_")]
for _n in _names:
    globals()[_n] = getattr(_fft, _n)
__all__ = list(_names)
del _n, _names, _fft
