"""paddle.tensor-style namespace (reference: python/paddle/tensor/__init__.py).

All ops live in core.ops (single lowering to XLA); this module re-exports them
grouped the way the reference groups math/linalg/manipulation/creation/etc.
"""
from ..core import ops as tensor  # noqa: F401
from ..core.ops import *  # noqa: F401,F403
from ..core.tensor import Tensor, to_tensor  # noqa: F401
