"""paddle.linalg namespace as an importable module (reference:
python/paddle/linalg/__init__.py). The implementations live on
core.ops.linalg; this module mirrors them so both `paddle.linalg.svd` and
`import paddle_tpu.linalg` work."""
from .core.ops import linalg as _la

_names = [n for n in dir(_la) if not n.startswith("_")]
for _n in _names:
    globals()[_n] = getattr(_la, _n)
__all__ = list(_names)
del _n, _names, _la
