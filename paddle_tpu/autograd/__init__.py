"""paddle.autograd namespace: PyLayer + backward/grad.

Reference: python/paddle/autograd/py_layer.py:29 (PyLayer),
backward_mode.py (paddle.autograd.backward). PyLayer here records a custom
forward/backward pair onto the same eager tape core.autograd uses, so user
custom ops compose with builtin ops.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..core.autograd import backward, grad, no_grad, enable_grad, Node, is_grad_enabled  # noqa: F401
from ..core.tensor import Tensor


class PyLayerContext:
    """Mirrors reference PyLayerContext (py_layer.py:60): save_for_backward /
    saved_tensor plus arbitrary attribute stashing."""

    def __init__(self):
        self._saved = ()

    def save_for_backward(self, *tensors):
        hooks = saved_tensors_hooks.current()
        if hooks is not None:
            tensors = tuple(hooks.pack_hook(t) for t in tensors)
            self._packed = True
            self.__dict__["_unpack_fn"] = hooks.unpack_hook
        self._saved = tensors

    def saved_tensor(self):
        if getattr(self, "_packed", False):
            hooks = saved_tensors_hooks.current()
            unpack = (hooks.unpack_hook if hooks is not None
                      else self._unpack_fallback)
            return tuple(unpack(t) for t in self._saved)
        return self._saved

    # the hook context may have exited before backward runs; remember the
    # unpack fn that matches the pack that ran
    @property
    def _unpack_fallback(self):
        return self.__dict__.get("_unpack_fn", lambda t: t)


class PyLayerMeta(type):
    def __call__(cls, *a, **k):
        raise RuntimeError("PyLayer is not instantiable; call .apply()")


class PyLayer(metaclass=PyLayerMeta):
    """User-defined differentiable function (reference py_layer.py:29).

    class Exp(PyLayer):
        @staticmethod
        def forward(ctx, x): ...
        @staticmethod
        def backward(ctx, dy): ...
    """

    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *grads):
        raise NotImplementedError

    @classmethod
    def apply(cls, *args, **kwargs):
        ctx = PyLayerContext()
        in_tensors = [a for a in args if isinstance(a, Tensor)]
        record = is_grad_enabled() and any(
            (not t.stop_gradient or t._node is not None) for t in in_tensors)

        with no_grad():
            outs = cls.forward(ctx, *args, **kwargs)
        multi = isinstance(outs, (tuple, list))
        out_list = list(outs) if multi else [outs]
        out_list = [o if isinstance(o, Tensor) else Tensor(jnp.asarray(o)) for o in out_list]

        if record:
            avals = [type("A", (), {"shape": o._data.shape, "dtype": o._data.dtype})()
                     for o in out_list]

            def vjp_fn(cts):
                ct_tensors = tuple(Tensor(c) for c in cts)
                with no_grad():
                    gin = cls.backward(ctx, *ct_tensors)
                gin = gin if isinstance(gin, (tuple, list)) else (gin,)
                if len(gin) != len(in_tensors):
                    raise RuntimeError(
                        f"{cls.__name__}.backward returned {len(gin)} grads for "
                        f"{len(in_tensors)} tensor inputs")
                return [None if g is None else (g._data if isinstance(g, Tensor) else jnp.asarray(g))
                        for g in gin]

            node = Node(cls.__name__, vjp_fn, in_tensors, avals)
            for i, o in enumerate(out_list):
                o._node = node
                o._out_idx = i
                o.stop_gradient = False
        return tuple(out_list) if multi else out_list[0]


class saved_tensors_hooks:  # noqa: N801 — reference name
    """reference: autograd/saved_tensors_hooks — pack/unpack hooks around
    tensors saved for backward. The tape saves activations inside vjp
    closures; hooks fire around PyLayer ctx.save_for_backward and are the
    user-visible contract (e.g. offload-to-host packs)."""

    _stack = []

    def __init__(self, pack_hook, unpack_hook):
        self.pack_hook = pack_hook
        self.unpack_hook = unpack_hook

    def __enter__(self):
        saved_tensors_hooks._stack.append(self)
        return self

    def __exit__(self, *exc):
        saved_tensors_hooks._stack.pop()
        return False

    @classmethod
    def current(cls):
        return cls._stack[-1] if cls._stack else None
