"""paddle.optimizer analog (reference: python/paddle/optimizer/__init__.py:27-38
— Optimizer, Adagrad, Adam, AdamW, Adamax, RMSProp, Adadelta, SGD, Momentum,
Lamb + lr)."""
from . import lr  # noqa: F401
from .optimizer import (  # noqa: F401
    Optimizer, SGD, Momentum, Adam, AdamW, Adamax, Adagrad, Adadelta, RMSProp, Lamb,
    LarsMomentum, DGCMomentum,
)
