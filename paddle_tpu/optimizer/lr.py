"""LR schedulers (reference: python/paddle/optimizer/lr.py).

Same stateful `.step()` contract as the reference; schedulers are host-side
python (cheap scalars) and also exportable as pure `lr(step)` functions for
the jitted TrainStep via `as_functional()`.
"""
from __future__ import annotations

import math
from bisect import bisect_right


class LRScheduler:
    def __init__(self, learning_rate=0.1, last_epoch=-1, verbose=False):
        self.base_lr = float(learning_rate)
        self.last_epoch = last_epoch
        self.last_lr = self.base_lr
        self.verbose = verbose
        self.step()

    def get_lr(self) -> float:
        raise NotImplementedError

    def step(self, epoch=None):
        if epoch is None:
            self.last_epoch += 1
        else:
            self.last_epoch = epoch
        self.last_lr = self.get_lr()

    def __call__(self):
        return self.last_lr

    def state_dict(self):
        return {k: v for k, v in self.__dict__.items()
                if isinstance(v, (int, float, bool, str, list))}

    def set_state_dict(self, state):
        self.__dict__.update(state)

    def as_functional(self):
        """Return pure fn step->lr for use inside jitted train steps."""
        import copy
        proto = copy.deepcopy(self)

        def lr_fn(step: int) -> float:
            proto.last_epoch = step - 1
            proto.step()
            return proto.last_lr
        return lr_fn


class NoamDecay(LRScheduler):
    def __init__(self, d_model, warmup_steps, learning_rate=1.0, last_epoch=-1, verbose=False):
        self.d_model, self.warmup_steps = d_model, warmup_steps
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        step = max(self.last_epoch, 1)
        return (self.base_lr * self.d_model ** -0.5 *
                min(step ** -0.5, step * self.warmup_steps ** -1.5))


class PiecewiseDecay(LRScheduler):
    def __init__(self, boundaries, values, last_epoch=-1, verbose=False):
        self.boundaries, self.values = list(boundaries), list(values)
        super().__init__(values[0], last_epoch, verbose)

    def get_lr(self):
        return self.values[bisect_right(self.boundaries, self.last_epoch)]


class NaturalExpDecay(LRScheduler):
    def __init__(self, learning_rate, gamma, last_epoch=-1, verbose=False):
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return self.base_lr * math.exp(-self.gamma * self.last_epoch)


class InverseTimeDecay(LRScheduler):
    def __init__(self, learning_rate, gamma, last_epoch=-1, verbose=False):
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return self.base_lr / (1 + self.gamma * self.last_epoch)


class PolynomialDecay(LRScheduler):
    def __init__(self, learning_rate, decay_steps, end_lr=0.0001, power=1.0,
                 cycle=False, last_epoch=-1, verbose=False):
        self.decay_steps, self.end_lr, self.power, self.cycle = decay_steps, end_lr, power, cycle
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        step = self.last_epoch
        decay_steps = self.decay_steps
        if self.cycle:
            div = math.ceil(step / decay_steps) if step > 0 else 1
            decay_steps = decay_steps * div
        else:
            step = min(step, decay_steps)
        return ((self.base_lr - self.end_lr) *
                (1 - step / decay_steps) ** self.power + self.end_lr)


class LinearWarmup(LRScheduler):
    def __init__(self, learning_rate, warmup_steps, start_lr, end_lr,
                 last_epoch=-1, verbose=False):
        self.lr_sched = learning_rate if isinstance(learning_rate, LRScheduler) else None
        self.final_lr = learning_rate if not isinstance(learning_rate, LRScheduler) else None
        self.warmup_steps, self.start_lr, self.end_lr = warmup_steps, start_lr, end_lr
        super().__init__(start_lr, last_epoch, verbose)

    def get_lr(self):
        if self.last_epoch < self.warmup_steps:
            return (self.end_lr - self.start_lr) * self.last_epoch / self.warmup_steps + self.start_lr
        if self.lr_sched is not None:
            self.lr_sched.last_epoch = self.last_epoch - self.warmup_steps
            return self.lr_sched.get_lr()
        return self.final_lr

    def state_dict(self):
        d = super().state_dict()
        if self.lr_sched is not None:
            d["lr_sched"] = self.lr_sched.state_dict()
        return d

    def set_state_dict(self, state):
        sub = state.pop("lr_sched", None)
        super().set_state_dict(state)
        if sub and self.lr_sched is not None:
            self.lr_sched.set_state_dict(sub)


class ExponentialDecay(LRScheduler):
    def __init__(self, learning_rate, gamma, last_epoch=-1, verbose=False):
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return self.base_lr * self.gamma ** self.last_epoch


class MultiStepDecay(LRScheduler):
    def __init__(self, learning_rate, milestones, gamma=0.1, last_epoch=-1, verbose=False):
        self.milestones, self.gamma = list(milestones), gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return self.base_lr * self.gamma ** bisect_right(self.milestones, self.last_epoch)


class StepDecay(LRScheduler):
    def __init__(self, learning_rate, step_size, gamma=0.1, last_epoch=-1, verbose=False):
        self.step_size, self.gamma = step_size, gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return self.base_lr * self.gamma ** (self.last_epoch // self.step_size)


class LambdaDecay(LRScheduler):
    def __init__(self, learning_rate, lr_lambda, last_epoch=-1, verbose=False):
        self.lr_lambda = lr_lambda
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return self.base_lr * self.lr_lambda(self.last_epoch)

    def state_dict(self):
        return {k: v for k, v in super().state_dict().items() if k != "lr_lambda"}


class MultiplicativeDecay(LRScheduler):
    def __init__(self, learning_rate, lr_lambda, last_epoch=-1, verbose=False):
        self.lr_lambda = lr_lambda
        self._cur = float(learning_rate)
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        if self.last_epoch > 0:
            self._cur = self._cur * self.lr_lambda(self.last_epoch)
        return self._cur


class CosineAnnealingDecay(LRScheduler):
    def __init__(self, learning_rate, T_max, eta_min=0, last_epoch=-1, verbose=False):
        self.T_max, self.eta_min = T_max, eta_min
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return (self.eta_min + (self.base_lr - self.eta_min) *
                (1 + math.cos(math.pi * self.last_epoch / self.T_max)) / 2)


class ReduceOnPlateau(LRScheduler):
    def __init__(self, learning_rate, mode="min", factor=0.1, patience=10,
                 threshold=1e-4, threshold_mode="rel", cooldown=0, min_lr=0,
                 epsilon=1e-8, verbose=False):
        self.mode, self.factor, self.patience = mode, factor, patience
        self.threshold, self.threshold_mode = threshold, threshold_mode
        self.cooldown, self.min_lr, self.epsilon = cooldown, min_lr, epsilon
        self.best = None
        self.cooldown_counter = 0
        self.num_bad_epochs = 0
        self.base_lr = float(learning_rate)
        self.last_lr = self.base_lr
        self.last_epoch = 0
        self.verbose = verbose

    def get_lr(self):
        return self.last_lr

    def step(self, metrics=None, epoch=None):
        if metrics is None:
            return
        current = float(metrics.item() if hasattr(metrics, "item") else metrics)
        self.last_epoch += 1
        if self.best is None:
            self.best = current
            return
        better = (current < self.best - abs(self.best) * self.threshold
                  if self.threshold_mode == "rel" else current < self.best - self.threshold)
        if self.mode == "max":
            better = (current > self.best + abs(self.best) * self.threshold
                      if self.threshold_mode == "rel" else current > self.best + self.threshold)
        if better:
            self.best = current
            self.num_bad_epochs = 0
        else:
            self.num_bad_epochs += 1
        if self.cooldown_counter > 0:
            self.cooldown_counter -= 1
            self.num_bad_epochs = 0
        if self.num_bad_epochs > self.patience:
            new_lr = max(self.last_lr * self.factor, self.min_lr)
            if self.last_lr - new_lr > self.epsilon:
                self.last_lr = new_lr
            self.cooldown_counter = self.cooldown
            self.num_bad_epochs = 0


class OneCycleLR(LRScheduler):
    def __init__(self, max_learning_rate, total_steps, divide_factor=25.0,
                 end_learning_rate=0.0001, phase_pct=0.3, anneal_strategy="cos",
                 three_phase=False, last_epoch=-1, verbose=False):
        self.max_lr = max_learning_rate
        self.total_steps = total_steps
        self.initial_lr = max_learning_rate / divide_factor
        self.end_lr = end_learning_rate
        self.phase_pct = phase_pct
        self.anneal = anneal_strategy
        super().__init__(self.initial_lr, last_epoch, verbose)

    def _interp(self, start, end, pct):
        if self.anneal == "cos":
            return end + (start - end) / 2.0 * (math.cos(math.pi * pct) + 1)
        return (end - start) * pct + start

    def get_lr(self):
        step = self.last_epoch
        up_steps = int(self.phase_pct * self.total_steps)
        if step <= up_steps:
            return self._interp(self.initial_lr, self.max_lr, step / max(up_steps, 1))
        down = (step - up_steps) / max(self.total_steps - up_steps, 1)
        return self._interp(self.max_lr, self.end_lr, min(down, 1.0))


class CyclicLR(LRScheduler):
    def __init__(self, base_learning_rate, max_learning_rate, step_size_up,
                 step_size_down=None, mode="triangular", exp_gamma=1.0,
                 scale_fn=None, scale_mode="cycle", last_epoch=-1, verbose=False):
        self.max_lr = max_learning_rate
        self.step_up = step_size_up
        self.step_down = step_size_down or step_size_up
        self.mode, self.exp_gamma = mode, exp_gamma
        super().__init__(base_learning_rate, last_epoch, verbose)

    def get_lr(self):
        cycle_len = self.step_up + self.step_down
        cycle = math.floor(1 + self.last_epoch / cycle_len)
        x = self.last_epoch - (cycle - 1) * cycle_len
        if x < self.step_up:
            pct = x / self.step_up
        else:
            pct = 1 - (x - self.step_up) / self.step_down
        amp = self.max_lr - self.base_lr
        if self.mode == "triangular2":
            amp = amp / (2 ** (cycle - 1))
        elif self.mode == "exp_range":
            amp = amp * (self.exp_gamma ** self.last_epoch)
        return self.base_lr + amp * pct
