"""Optimizer base + SGD/Momentum/Adam/AdamW/...

Reference: python/paddle/optimizer/optimizer.py (Optimizer base),
adam.py/adamw.py/momentum.py etc., lowering to phi kernels (sgd_kernel,
adam_kernel). TPU-native design: every optimizer is a PURE update rule
`(param, grad, state, lr, step) -> (param', state')` over jnp arrays, used

1. eagerly by `.step()` (per-parameter, jit-cached by shape), and
2. functionally by paddle_tpu.jit.TrainStep over whole pytrees — the fused,
   donated, XLA-compiled path where real training runs.

This removes the reference's duality of C++ optimizer kernels vs python
wrappers: one rule, two drivers.
"""
from __future__ import annotations

import math
from functools import partial
from typing import Dict, List, Optional

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor, Parameter
from ..core import autograd
from .lr import LRScheduler


def _global_norm_clip(grads, clip_norm):
    total = jnp.sqrt(sum(jnp.sum(jnp.square(g.astype(jnp.float32))) for g in grads))
    scale = jnp.minimum(1.0, clip_norm / jnp.maximum(total, 1e-12))
    return [g * scale.astype(g.dtype) for g in grads], total


class Optimizer:
    """Base optimizer (reference: optimizer/optimizer.py Optimizer).

    Subclasses implement `init_state(param) -> dict` and
    `update(param, grad, state, lr, step) -> (param, state)` as pure fns.
    """

    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        self._lr = learning_rate
        self._parameters = list(parameters) if parameters is not None else None
        self._weight_decay = 0.0 if weight_decay is None else weight_decay
        self._grad_clip = grad_clip
        self._step_count = 0
        self._states: Dict[int, dict] = {}
        self._accumulated_grads: Dict[int, jnp.ndarray] = {}

    # ------------------------------------------------------------- pure rule
    def init_state(self, param: jnp.ndarray) -> dict:
        return {}

    def state_spec(self, param, key, state_array, base_spec):
        """PartitionSpec for one optimizer-state entry (used by TrainStep's
        sharded placement). Default: param-shaped state follows the param's
        (possibly ZeRO-extended) spec; anything else replicates. Optimizers
        with non-param-shaped state (e.g. blockwise int8 moments) override
        to keep that state sharded."""
        from jax.sharding import PartitionSpec as P
        if tuple(state_array.shape) == tuple(param.shape):
            return base_spec
        return P()

    def update(self, param, grad, state, lr, step):
        raise NotImplementedError

    # ------------------------------------------------------------ eager API
    def get_lr(self) -> float:
        if isinstance(self._lr, LRScheduler):
            return float(self._lr.get_lr())
        return float(self._lr)

    def set_lr(self, value: float):
        if isinstance(self._lr, LRScheduler):
            raise RuntimeError("optimizer's lr is an LRScheduler; call scheduler.step()")
        self._lr = value

    @property
    def _param_list(self):
        if self._parameters is None:
            raise ValueError("Optimizer created without parameters; pass parameters=")
        return self._parameters

    def step(self):
        """Apply one eager update from `.grad` fields (reference:
        Optimizer.step → _apply_optimize)."""
        lr = self.get_lr()
        self._step_count += 1
        params, grads = [], []
        for p in self._param_list:
            if p.grad is None or p.stop_gradient:
                continue
            params.append(p)
            grads.append(p.grad._data)

        wd_applicable = [self._wd_for(p) for p in params]
        if self._grad_clip is not None and grads:
            cls = type(self._grad_clip).__name__
            if cls == "ClipGradByGlobalNorm":
                grads, _ = _global_norm_clip(grads, self._grad_clip.clip_norm)
            elif cls == "ClipGradByNorm":
                cn = self._grad_clip.clip_norm
                grads = [g * jnp.minimum(1.0, cn / jnp.maximum(
                    jnp.sqrt(jnp.sum(jnp.square(g.astype(jnp.float32)))), 1e-12)).astype(g.dtype)
                    for g in grads]
            elif cls == "ClipGradByValue":
                grads = [jnp.clip(g, self._grad_clip.min, self._grad_clip.max) for g in grads]

        for p, g, wd in zip(params, grads, wd_applicable):
            st = self._states.get(id(p))
            if st is None:
                try:
                    st = self.init_state(p._data, param_obj=p)
                except TypeError:
                    st = self.init_state(p._data)
                self._states[id(p)] = st
            new_p, new_st = self._jit_update(wd)(p._data, g, st, jnp.float32(lr),
                                                 jnp.int32(self._step_count))
            p._data = new_p
            p._node = None
            self._states[id(p)] = new_st

    def _wd_for(self, p) -> float:
        return float(self._weight_decay) if self._weight_decay else 0.0

    def _jit_update(self, wd):
        key = ("u", wd)
        cache = self.__dict__.setdefault("_jit_cache", {})
        if key not in cache:
            def fn(param, grad, state, lr, step, _wd=wd):
                return self.update(param, grad, state, lr, step, _wd)
            cache[key] = jax.jit(fn)
        return cache[key]

    def clear_grad(self, set_to_zero: bool = False):
        for p in self._param_list:
            p.clear_gradient(set_to_zero)

    clear_gradients = clear_grad

    def minimize(self, loss, startup_program=None, parameters=None, no_grad_set=None):
        from ..static.program import Variable as _StaticVar
        if isinstance(loss, _StaticVar):
            # static-graph path (reference: Optimizer.minimize appends
            # backward + update ops to the program; here the Executor fuses
            # grads + this optimizer's pure `update` rule into the jitted
            # replay — see static/executor.py)
            from ..static.program import append_backward, default_main_program
            pairs = append_backward(loss, parameter_list=parameters)
            prog = loss.program or default_main_program()
            prog._optimizer = self
            return None, pairs
        loss.backward()
        self.step()
        self.clear_grad()

    # --------------------------------------------------------- state_dict
    def state_dict(self) -> dict:
        out = {"master_step": self._step_count}
        if isinstance(self._lr, LRScheduler):
            out["LR_Scheduler"] = self._lr.state_dict()
        for i, p in enumerate(self._param_list):
            st = self._states.get(id(p))
            if st:
                for k, v in st.items():
                    out[f"{p.name or f'param_{i}'}__{k}"] = Tensor(v)
        return out

    def set_state_dict(self, state_dict: dict):
        self._step_count = int(state_dict.get("master_step", 0))
        if isinstance(self._lr, LRScheduler) and "LR_Scheduler" in state_dict:
            self._lr.set_state_dict(state_dict["LR_Scheduler"])
        for i, p in enumerate(self._param_list):
            prefix = f"{p.name or f'param_{i}'}__"
            st = {}
            for k, v in state_dict.items():
                if isinstance(k, str) and k.startswith(prefix):
                    st[k[len(prefix):]] = v._data if isinstance(v, Tensor) else jnp.asarray(v)
            if st:
                self._states[id(p)] = st

    # lr scheduler hookup
    @property
    def _learning_rate(self):
        return self._lr


class SGD(Optimizer):
    """Reference: optimizer/sgd.py → phi sgd kernel."""

    _fusable_elementwise = True
    _fused_state_keys = ()

    def update(self, param, grad, state, lr, step, wd=0.0):
        g = grad.astype(jnp.float32)
        if isinstance(wd, jnp.ndarray) or wd:
            g = g + wd * param.astype(jnp.float32)
        return (param - lr * g.astype(param.dtype)).astype(param.dtype), state


class Momentum(Optimizer):
    """Reference: optimizer/momentum.py (use_nesterov supported)."""

    # elementwise math — safe for the fused multi-tensor apply; the win is
    # biggest here: conv nets have hundreds of tiny BN scale/bias params
    # (r3 ResNet-50 profile: 628 per-weight update fusions, 5.8 ms of a
    # 38 ms step)
    _fusable_elementwise = True
    _fused_state_keys = ("velocity",)

    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._momentum = momentum
        self._nesterov = use_nesterov

    def init_state(self, param):
        return {"velocity": jnp.zeros_like(param, dtype=jnp.float32)}

    def update(self, param, grad, state, lr, step, wd=0.0):
        g = grad.astype(jnp.float32)
        # wd may be a per-element vector under the fused multi-tensor apply
        if isinstance(wd, jnp.ndarray) or wd:
            g = g + wd * param.astype(jnp.float32)
        v = self._momentum * state["velocity"] + g
        if self._nesterov:
            upd = g + self._momentum * v
        else:
            upd = v
        new_p = (param.astype(jnp.float32) - lr * upd).astype(param.dtype)
        return new_p, {"velocity": v}


_Q_BLOCK = 2048  # 8-bit moment quantization block (per-block absmax scale)


def _q8_encode(x32):
    """Blockwise SIGNED-SQRT int8 quantization (FIRST moment): code the
    sign-preserving sqrt, r = sign(x)*sqrt(|x|), linearly per block. Plain
    linear coding freezes any coordinate whose |m| stays ~254x below the
    block absmax (rounds to 0 forever); sqrt compression moves that
    underflow floor to ~max/64516, the same treatment the second moment
    gets. Returns (int8 codes [nb, B], f32 scales [nb] in the r domain)."""
    r = jnp.sign(x32) * jnp.sqrt(jnp.abs(x32))
    n = r.size
    nb = -(-n // _Q_BLOCK)
    flat = jnp.pad(r.reshape(-1), (0, nb * _Q_BLOCK - n))
    blocks = flat.reshape(nb, _Q_BLOCK)
    scale = jnp.max(jnp.abs(blocks), axis=1) / 127.0
    q = jnp.round(blocks / jnp.maximum(scale, 1e-30)[:, None])
    return q.astype(jnp.int8), scale.astype(jnp.float32)


def _q8_decode(q, scale, shape):
    r = (q.astype(jnp.float32) * scale[:, None]).reshape(-1)
    n = 1
    for s in shape:
        n *= s
    r = r[:n].reshape(shape)
    return jnp.sign(r) * jnp.square(r)


def _q8v_encode(v32):
    """SECOND-moment quantization: store sqrt(v) as uint8 per-block. Linear
    int8 on v itself underflows small entries to 0 → 1/(sqrt(0)+eps) blows
    the update up; sqrt halves the dynamic range and the +0.5-step decode
    bias below acts as a per-block adaptive epsilon."""
    r = jnp.sqrt(jnp.maximum(v32, 0.0))
    n = r.size
    nb = -(-n // _Q_BLOCK)
    flat = jnp.pad(r.reshape(-1), (0, nb * _Q_BLOCK - n))
    blocks = flat.reshape(nb, _Q_BLOCK)
    scale = jnp.max(blocks, axis=1) / 255.0
    q = jnp.round(blocks / jnp.maximum(scale, 1e-30)[:, None])
    return q.astype(jnp.uint8), scale.astype(jnp.float32)


def _q8v_decode(q, scale, shape):
    r = ((q.astype(jnp.float32) + 0.5) * scale[:, None]).reshape(-1)
    n = 1
    for s in shape:
        n *= s
    return jnp.square(r[:n].reshape(shape))


class Adam(Optimizer):
    """Reference: optimizer/adam.py → phi adam kernel (bias-corrected).

    `moment_dtype` ("float32" default) stores m/v in a narrower dtype —
    the dominant fixed HBM cost of large-model single-chip training is
    8 bytes/param of f32 moments:
      * "bfloat16": 4 bytes/param — f32-range exponent keeps v's dynamic
        range, only mantissa precision drops;
      * "int8": ~2 bytes/param — blockwise (2048) absmax-scaled symmetric
        quantization (the bitsandbytes-style 8-bit Adam); what fits
        GPT-2.7B + Adam on one 16G chip.
    The update itself always computes in f32."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=None, grad_clip=None, lazy_mode=False,
                 multi_precision=True, moment_dtype="float32",
                 q8_param_fun=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._beta1, self._beta2, self._eps = beta1, beta2, epsilon
        self._q8 = str(moment_dtype) in ("int8", "uint8")
        # q8_param_fun(name) -> bool: blockwise-int8 moments for SELECTED
        # params (embedding tables are the usual target: wte+wpe moments are
        # ~8% of a 1.3B model's optimizer HBM — the margin that fits the
        # S=8192 config) while the rest keeps moment_dtype. Mirrors
        # apply_decay_param_fun's shape.
        self._q8_param_fun = q8_param_fun
        self._moment_dtype = (jnp.dtype(jnp.int8) if self._q8
                              else jnp.dtype(moment_dtype))

    def init_state(self, param, param_obj=None, name=None):
        name = name or getattr(param_obj, "name", None)
        use_q8 = self._q8 or (self._q8_param_fun is not None and name
                              and self._q8_param_fun(name))
        if use_q8:
            q, s = _q8_encode(jnp.zeros(param.shape, jnp.float32))
            vq, vs = _q8v_encode(jnp.zeros(param.shape, jnp.float32))
            return {"moment1_q": q, "moment1_s": s,
                    "moment2_q": vq, "moment2_s": vs}
        return {"moment1": jnp.zeros_like(param, dtype=self._moment_dtype),
                "moment2": jnp.zeros_like(param, dtype=self._moment_dtype)}

    def _moments(self, state, grad32, b1, b2):
        if "moment1_q" in state:
            shape = grad32.shape
            m0 = _q8_decode(state["moment1_q"], state["moment1_s"], shape)
            v0 = _q8v_decode(state["moment2_q"], state["moment2_s"], shape)
        else:
            m0 = state["moment1"].astype(jnp.float32)
            v0 = state["moment2"].astype(jnp.float32)
        m = b1 * m0 + (1 - b1) * grad32
        v = b2 * v0 + (1 - b2) * grad32 * grad32
        return m, v

    def state_spec(self, param, key, state_array, base_spec):
        from jax.sharding import PartitionSpec as P
        if key.endswith(("_q", "_s")) and key.startswith("moment"):
            # codes [nb, BLOCK] / scales [nb]: shard the block dim over the
            # first axis the param's spec uses — the dominant 8-bit state
            # stays distributed (ZeRO axis included via base_spec). jax
            # requires the dim divisible by the axis size; replicate the
            # (small) remainder cases rather than fail.
            from ..distributed import mesh as _dmesh
            axes = [a for a in (base_spec or ()) if a is not None]
            for first in axes:
                names = (first,) if isinstance(first, str) else tuple(first)
                size = 1
                for nm in names:
                    size *= max(1, _dmesh.mesh_axis_size(nm))
                if size > 1 and state_array.shape[0] % size == 0:
                    return P(first) if state_array.ndim == 1 \
                        else P(first, None)
            return P()
        return super().state_spec(param, key, state_array, base_spec)

    def _pack_moments(self, m, v, q8=None):
        if (q8 if q8 is not None else self._q8):
            mq, ms = _q8_encode(m)
            vq, vs = _q8v_encode(v)
            return {"moment1_q": mq, "moment1_s": ms,
                    "moment2_q": vq, "moment2_s": vs}
        md = self._moment_dtype
        return {"moment1": m.astype(md), "moment2": v.astype(md)}

    # elementwise update math: concatenating params changes nothing, so the
    # fused multi-tensor apply in TrainStep may group small params into one
    # flat update (reference analog: distributed_fused_lamb.py:82's
    # flattened apply; LAMB itself is NOT elementwise — per-tensor trust
    # ratios — which is why only elementwise optimizers carry this flag)
    _fusable_elementwise = True
    _fused_state_keys = ("moment1", "moment2")

    def update(self, param, grad, state, lr, step, wd=0.0):
        b1, b2, eps = self._beta1, self._beta2, self._eps
        g = grad.astype(jnp.float32)
        p32 = param.astype(jnp.float32)
        # L2-regularization semantics (coupled), like reference Adam+L2Decay;
        # wd may be a per-element vector under the fused multi-tensor apply
        if isinstance(wd, jnp.ndarray) or wd:
            g = g + wd * p32
        m, v = self._moments(state, g, b1, b2)
        t = step.astype(jnp.float32)
        m_hat = m / (1 - jnp.power(b1, t))
        v_hat = v / (1 - jnp.power(b2, t))
        new_p = p32 - lr * m_hat / (jnp.sqrt(v_hat) + eps)
        return new_p.astype(param.dtype), self._pack_moments(
            m, v, q8="moment1_q" in state)


class AdamW(Adam):
    """Reference: optimizer/adamw.py — decoupled weight decay, with
    apply_decay_param_fun to exempt bias/norm params."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=0.01, lr_ratio=None,
                 apply_decay_param_fun=None, grad_clip=None, multi_precision=True,
                 moment_dtype="float32", q8_param_fun=None, name=None):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         None, grad_clip, moment_dtype=moment_dtype,
                         q8_param_fun=q8_param_fun, name=name)
        self._wd_coeff = weight_decay
        self._apply_decay_param_fun = apply_decay_param_fun

    def _wd_for(self, p):
        if self._apply_decay_param_fun is not None and not self._apply_decay_param_fun(p.name):
            return 0.0
        return float(self._wd_coeff)

    def update(self, param, grad, state, lr, step, wd=0.0):
        b1, b2, eps = self._beta1, self._beta2, self._eps
        g = grad.astype(jnp.float32)
        p32 = param.astype(jnp.float32)
        m, v = self._moments(state, g, b1, b2)
        t = step.astype(jnp.float32)
        m_hat = m / (1 - jnp.power(b1, t))
        v_hat = v / (1 - jnp.power(b2, t))
        p32 = p32 * (1 - lr * wd)  # decoupled decay
        new_p = p32 - lr * m_hat / (jnp.sqrt(v_hat) + eps)
        return new_p.astype(param.dtype), self._pack_moments(
            m, v, q8="moment1_q" in state)


class Adamax(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._beta1, self._beta2, self._eps = beta1, beta2, epsilon

    def init_state(self, param):
        return {"moment": jnp.zeros_like(param, dtype=jnp.float32),
                "inf_norm": jnp.zeros_like(param, dtype=jnp.float32)}

    def update(self, param, grad, state, lr, step, wd=0.0):
        b1, b2, eps = self._beta1, self._beta2, self._eps
        g = grad.astype(jnp.float32)
        if wd:
            g = g + wd * param.astype(jnp.float32)
        m = b1 * state["moment"] + (1 - b1) * g
        u = jnp.maximum(b2 * state["inf_norm"], jnp.abs(g))
        t = step.astype(jnp.float32)
        new_p = param.astype(jnp.float32) - (lr / (1 - jnp.power(b1, t))) * m / (u + eps)
        return new_p.astype(param.dtype), {"moment": m, "inf_norm": u}


class Adagrad(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, parameters=None,
                 weight_decay=None, grad_clip=None, initial_accumulator_value=0.0,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._eps = epsilon
        self._init_acc = initial_accumulator_value

    def init_state(self, param):
        return {"moment": jnp.full_like(param, self._init_acc, dtype=jnp.float32)}

    def update(self, param, grad, state, lr, step, wd=0.0):
        g = grad.astype(jnp.float32)
        if wd:
            g = g + wd * param.astype(jnp.float32)
        acc = state["moment"] + g * g
        new_p = param.astype(jnp.float32) - lr * g / (jnp.sqrt(acc) + self._eps)
        return new_p.astype(param.dtype), {"moment": acc}


class Adadelta(Optimizer):
    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95, parameters=None,
                 weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._eps, self._rho = epsilon, rho

    def init_state(self, param):
        return {"avg_squared_grad": jnp.zeros_like(param, dtype=jnp.float32),
                "avg_squared_update": jnp.zeros_like(param, dtype=jnp.float32)}

    def update(self, param, grad, state, lr, step, wd=0.0):
        g = grad.astype(jnp.float32)
        if wd:
            g = g + wd * param.astype(jnp.float32)
        e_g = self._rho * state["avg_squared_grad"] + (1 - self._rho) * g * g
        upd = g * jnp.sqrt(state["avg_squared_update"] + self._eps) / jnp.sqrt(e_g + self._eps)
        e_u = self._rho * state["avg_squared_update"] + (1 - self._rho) * upd * upd
        new_p = param.astype(jnp.float32) - lr * upd
        return new_p.astype(param.dtype), {"avg_squared_grad": e_g, "avg_squared_update": e_u}


class RMSProp(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, parameters=None, weight_decay=None, grad_clip=None,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._rho, self._eps, self._momentum, self._centered = rho, epsilon, momentum, centered

    def init_state(self, param):
        st = {"mean_square": jnp.zeros_like(param, dtype=jnp.float32),
              "momentum": jnp.zeros_like(param, dtype=jnp.float32)}
        if self._centered:
            st["mean_grad"] = jnp.zeros_like(param, dtype=jnp.float32)
        return st

    def update(self, param, grad, state, lr, step, wd=0.0):
        g = grad.astype(jnp.float32)
        if wd:
            g = g + wd * param.astype(jnp.float32)
        ms = self._rho * state["mean_square"] + (1 - self._rho) * g * g
        new_state = {"mean_square": ms}
        if self._centered:
            mg = self._rho * state["mean_grad"] + (1 - self._rho) * g
            denom = jnp.sqrt(ms - mg * mg + self._eps)
            new_state["mean_grad"] = mg
        else:
            denom = jnp.sqrt(ms + self._eps)
        mom = self._momentum * state["momentum"] + lr * g / denom
        new_state["momentum"] = mom
        new_p = param.astype(jnp.float32) - mom
        return new_p.astype(param.dtype), new_state


class Lamb(Optimizer):
    """Reference: optimizer/lamb.py — layerwise adaptive large-batch opt."""

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9,
                 beta2=0.999, epsilon=1e-6, parameters=None, grad_clip=None,
                 exclude_from_weight_decay_fn=None, name=None):
        super().__init__(learning_rate, parameters, lamb_weight_decay, grad_clip, name)
        self._beta1, self._beta2, self._eps = beta1, beta2, epsilon
        self._exclude_fn = exclude_from_weight_decay_fn

    def _wd_for(self, p):
        if self._exclude_fn is not None and self._exclude_fn(p):
            return 0.0
        return float(self._weight_decay)

    def init_state(self, param):
        return {"moment1": jnp.zeros_like(param, dtype=jnp.float32),
                "moment2": jnp.zeros_like(param, dtype=jnp.float32)}

    def update(self, param, grad, state, lr, step, wd=0.0):
        b1, b2, eps = self._beta1, self._beta2, self._eps
        g = grad.astype(jnp.float32)
        p32 = param.astype(jnp.float32)
        m = b1 * state["moment1"] + (1 - b1) * g
        v = b2 * state["moment2"] + (1 - b2) * g * g
        t = step.astype(jnp.float32)
        m_hat = m / (1 - jnp.power(b1, t))
        v_hat = v / (1 - jnp.power(b2, t))
        r = m_hat / (jnp.sqrt(v_hat) + eps) + wd * p32
        w_norm = jnp.linalg.norm(p32)
        r_norm = jnp.linalg.norm(r)
        trust = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
        new_p = p32 - lr * trust * r
        return new_p.astype(param.dtype), {"moment1": m, "moment2": v}


class LarsMomentum(Momentum):
    """Layer-wise Adaptive Rate Scaling (reference:
    fluid/optimizer.py LarsMomentumOptimizer + the fleet `lars` meta
    optimizer, meta_optimizers/lars_optimizer.py): the effective lr per
    parameter is scaled by ||w|| / (||g|| + wd*||w||), which keeps the
    update/weight ratio uniform across layers for very large batches."""

    def __init__(self, learning_rate=0.001, momentum=0.9, lars_coeff=0.001,
                 lars_weight_decay=0.0005, parameters=None, grad_clip=None,
                 epsilon=1e-9, name=None):
        super().__init__(learning_rate, momentum, parameters,
                         weight_decay=lars_weight_decay, grad_clip=grad_clip,
                         name=name)
        self._lars_coeff = lars_coeff
        self._eps = epsilon

    def update(self, param, grad, state, lr, step, wd=0.0):
        g = grad.astype(jnp.float32)
        w = param.astype(jnp.float32)
        w_norm = jnp.sqrt(jnp.sum(jnp.square(w)))
        g_norm = jnp.sqrt(jnp.sum(jnp.square(g)))
        local_lr = jnp.where(
            (w_norm > 0) & (g_norm > 0),
            self._lars_coeff * w_norm / (g_norm + wd * w_norm + self._eps),
            1.0)
        v = state["velocity"]
        v = self._momentum * v + lr * local_lr * (g + wd * w)
        return (w - v).astype(param.dtype), {"velocity": v}


class DGCMomentum(Momentum):
    """Deep Gradient Compression momentum (reference: the DGC op +
    DGCMomentumOptimizer, meta_optimizers/dgc_optimizer.py): momentum
    correction + error feedback, with only the top-`rampup` fraction of
    gradient magnitudes applied per step.

    On TPU the *communication* motivation disappears — XLA collectives over
    ICI are not the bottleneck NCCL rings were — so this is semantic parity:
    the same sparsified-update training dynamics (useful over DCN-separated
    slices), implemented densely with a per-step magnitude threshold (exact
    top-k is a sort per tensor; the quantile approximation keeps the update
    one fused XLA program)."""

    def __init__(self, learning_rate=0.001, momentum=0.9,
                 sparsity=0.999, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, momentum, parameters,
                         weight_decay=weight_decay, grad_clip=grad_clip,
                         name=name)
        self._sparsity = float(sparsity)

    def init_state(self, param):
        return {"velocity": jnp.zeros_like(param, dtype=jnp.float32),
                "error": jnp.zeros_like(param, dtype=jnp.float32)}

    def update(self, param, grad, state, lr, step, wd=0.0):
        g = grad.astype(jnp.float32)
        w = param.astype(jnp.float32)
        if wd:
            g = g + wd * w
        # momentum correction: accumulate velocity then add error feedback
        u = self._momentum * state["velocity"] + g
        acc = state["error"] + u
        if acc.size > 1:
            thresh = jnp.quantile(jnp.abs(acc).reshape(-1), self._sparsity)
            mask = (jnp.abs(acc) >= thresh).astype(jnp.float32)
        else:
            mask = jnp.ones_like(acc)
        comm = acc * mask          # the "transmitted" sparse update
        err = acc * (1.0 - mask)   # error feedback kept locally
        return (w - lr * comm).astype(param.dtype), \
            {"velocity": u, "error": err}
