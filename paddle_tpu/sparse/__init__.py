"""paddle.sparse analog — COO/CSR sparse tensors and ops.

Reference surface (SURVEY §2.3): python/paddle/sparse/ (3.5k LoC) over C++
SparseCooTensor/SparseCsrTensor (paddle/phi/core/sparse_coo_tensor.h,
sparse_csr_tensor.h) with dedicated PHI sparse kernels
(phi/kernels/sparse/). TPU-native: storage is jax.experimental.sparse
BCOO/BCSR (XLA-lowering batched-COO formats — TPUs have no cuSPARSE; XLA
lowers gather/scatter/segment-sum patterns instead), autograd rides the same
tape as dense ops because every sparse op here is expressed as a
jax-traceable function of (values, dense operands) with indices closed over
as structure.
"""
from __future__ import annotations

from typing import Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import sparse as jsparse

from ..core.tensor import Tensor, apply_op
from ..core.dtype import convert_dtype


class SparseCooTensor(Tensor):
    """COO sparse tensor (reference: phi/core/sparse_coo_tensor.h:38).
    `_data` holds dense *values*; `indices` [ndim, nnz] is structural (non-
    differentiable), so the autograd tape sees only values — matching the
    reference where gradients flow through values, never indices."""

    __slots__ = ("indices_", "dense_shape")

    def __init__(self, indices, values, shape, stop_gradient=True):
        vals = values._data if isinstance(values, Tensor) else jnp.asarray(values)
        super().__init__(vals, stop_gradient=stop_gradient)
        idx = indices._data if isinstance(indices, Tensor) else jnp.asarray(indices)
        self.indices_ = idx.astype(jnp.int32)
        self.dense_shape = tuple(int(s) for s in shape)

    # -- paddle API ----------------------------------------------------
    @property
    def shape(self):
        return list(self.dense_shape)

    def indices(self):
        return Tensor(self.indices_)

    def values(self):
        return Tensor(self._data, stop_gradient=self.stop_gradient)._replace_from(self)

    def nnz(self):
        return int(self._data.shape[0])

    def is_sparse_coo(self):
        return True

    def is_sparse_csr(self):
        return False

    def _bcoo(self) -> jsparse.BCOO:
        return jsparse.BCOO((self._data, self.indices_.T),
                            shape=self.dense_shape)

    def to_dense(self) -> Tensor:
        idx = self.indices_

        def fn(v):
            return jsparse.BCOO((v, idx.T), shape=self.dense_shape).todense()
        return apply_op("sparse_to_dense", fn, [self])

    def to_sparse_csr(self) -> "SparseCsrTensor":
        return _dense_to_csr(self.to_dense())

    def coalesce(self) -> "SparseCooTensor":
        """Merge duplicate indices (reference: sparse_coo_tensor coalesce)."""
        bcoo = self._bcoo().sum_duplicates()
        out = SparseCooTensor(bcoo.indices.T, bcoo.data, self.dense_shape,
                              stop_gradient=self.stop_gradient)
        return out

    def __repr__(self):
        return (f"SparseCooTensor(shape={self.shape}, nnz={self.nnz()}, "
                f"dtype={self.dtype.name})")


# small helper so values() keeps grad linkage with the source sparse tensor
def _replace_from(self, src):
    self._node = src._node
    self._out_idx = src._out_idx
    return self


Tensor._replace_from = _replace_from


class SparseCsrTensor(Tensor):
    """CSR sparse tensor (reference: phi/core/sparse_csr_tensor.h)."""

    __slots__ = ("crows_", "cols_", "dense_shape")

    def __init__(self, crows, cols, values, shape, stop_gradient=True):
        vals = values._data if isinstance(values, Tensor) else jnp.asarray(values)
        super().__init__(vals, stop_gradient=stop_gradient)
        self.crows_ = jnp.asarray(crows._data if isinstance(crows, Tensor) else crows,
                                  dtype=jnp.int32)
        self.cols_ = jnp.asarray(cols._data if isinstance(cols, Tensor) else cols,
                                 dtype=jnp.int32)
        self.dense_shape = tuple(int(s) for s in shape)

    @property
    def shape(self):
        return list(self.dense_shape)

    def crows(self):
        return Tensor(self.crows_)

    def cols(self):
        return Tensor(self.cols_)

    def values(self):
        return Tensor(self._data, stop_gradient=self.stop_gradient)

    def nnz(self):
        return int(self._data.shape[0])

    def is_sparse_coo(self):
        return False

    def is_sparse_csr(self):
        return True

    def _bcsr(self) -> jsparse.BCSR:
        return jsparse.BCSR((self._data, self.cols_, self.crows_),
                            shape=self.dense_shape)

    def to_dense(self) -> Tensor:
        cols, crows, shape = self.cols_, self.crows_, self.dense_shape

        def fn(v):
            return jsparse.BCSR((v, cols, crows), shape=shape).todense()
        return apply_op("sparse_csr_to_dense", fn, [self])

    def to_sparse_coo(self, sparse_dim=None) -> SparseCooTensor:
        bcoo = self._bcsr().to_bcoo()
        return SparseCooTensor(np.asarray(bcoo.indices).T, bcoo.data,
                               self.dense_shape,
                               stop_gradient=self.stop_gradient)

    def __repr__(self):
        return (f"SparseCsrTensor(shape={self.shape}, nnz={self.nnz()}, "
                f"dtype={self.dtype.name})")


# ------------------------------------------------------------- creation API
def sparse_coo_tensor(indices, values, shape=None, dtype=None,
                      stop_gradient=True) -> SparseCooTensor:
    """reference: paddle.sparse.sparse_coo_tensor (sparse/creation.py)."""
    idx = np.asarray(indices._data if isinstance(indices, Tensor) else indices)
    vals = values._data if isinstance(values, Tensor) else jnp.asarray(values)
    if dtype is not None:
        vals = vals.astype(convert_dtype(dtype))
    if shape is None:
        shape = tuple(int(m) + 1 for m in idx.max(axis=1))
    return SparseCooTensor(idx, vals, shape, stop_gradient=stop_gradient)


def sparse_csr_tensor(crows, cols, values, shape, dtype=None,
                      stop_gradient=True) -> SparseCsrTensor:
    vals = values._data if isinstance(values, Tensor) else jnp.asarray(values)
    if dtype is not None:
        vals = vals.astype(convert_dtype(dtype))
    return SparseCsrTensor(crows, cols, vals, shape, stop_gradient=stop_gradient)


def _dense_to_coo(x: Tensor, sparse_dim=None) -> SparseCooTensor:
    arr = np.asarray(x._data)
    idx = np.stack(np.nonzero(arr))
    vals_idx = tuple(idx)

    def fn(a):
        return a[vals_idx]
    vals = apply_op("dense_to_sparse_values", fn, [x])
    out = SparseCooTensor(idx, vals._data, arr.shape,
                          stop_gradient=x.stop_gradient)
    out._node = vals._node
    out._out_idx = vals._out_idx
    return out


def _dense_to_csr(x: Tensor) -> SparseCsrTensor:
    arr = np.asarray(x._data)
    assert arr.ndim == 2, "to_sparse_csr: 2-D only (reference kernel contract)"
    rows, cols = np.nonzero(arr)
    crows = np.zeros(arr.shape[0] + 1, np.int32)
    np.add.at(crows[1:], rows, 1)
    crows = np.cumsum(crows).astype(np.int32)
    vals = arr[rows, cols]
    return SparseCsrTensor(crows, cols, vals, arr.shape,
                           stop_gradient=x.stop_gradient)


def _tensor_to_sparse_coo(self, sparse_dim=None):
    return _dense_to_coo(self, sparse_dim)


def _tensor_to_sparse_csr(self):
    return _dense_to_csr(self)


Tensor.to_sparse_coo = _tensor_to_sparse_coo
Tensor.to_sparse_csr = _tensor_to_sparse_csr


# ------------------------------------------------------------------- math
def _coo_binary(name, op):
    def f(x: SparseCooTensor, y, name_=None):
        if isinstance(y, SparseCooTensor):
            # same-pattern fast path, else via dense (reference: sparse
            # elementwise kernels require matching patterns for coo+coo)
            if x.indices_.shape == y.indices_.shape and \
                    bool(jnp.all(x.indices_ == y.indices_)):
                out = apply_op(f"sparse_{name}", op, [x, y])
                res = SparseCooTensor(x.indices_, out._data, x.dense_shape,
                                      stop_gradient=out.stop_gradient)
                res._node, res._out_idx = out._node, out._out_idx
                return res
            return op_dense(x, y, op, name)
        raise TypeError(f"sparse.{name}: operand must be SparseCooTensor")
    f.__name__ = name
    return f


def op_dense(x, y, op, name):
    xd, yd = x.to_dense(), y.to_dense()
    out = apply_op(f"sparse_{name}_dense", op, [xd, yd])
    return _dense_to_coo(out)


add = _coo_binary("add", lambda a, b: a + b)
subtract = _coo_binary("subtract", lambda a, b: a - b)
multiply = _coo_binary("multiply", lambda a, b: a * b)
divide = _coo_binary("divide", lambda a, b: a / b)


def matmul(x, y, name=None) -> Tensor:
    """Sparse @ dense → dense (reference: sparse/matmul.py; phi kernel
    sparse/gpu/matmul_kernel.cu via cuSPARSE — here BCOO dot_general, which
    XLA lowers to segment-sum/gather for TPU)."""
    if isinstance(x, SparseCooTensor):
        idx, shape = x.indices_, x.dense_shape

        def fn(v, d):
            return jsparse.BCOO((v, idx.T), shape=shape) @ d
        return apply_op("sparse_matmul", fn, [x, _as_plain(y)])
    if isinstance(x, SparseCsrTensor):
        cols, crows, shape = x.cols_, x.crows_, x.dense_shape

        def fn(v, d):
            return jsparse.BCSR((v, cols, crows), shape=shape) @ d
        return apply_op("sparse_matmul", fn, [x, _as_plain(y)])
    raise TypeError("sparse.matmul: x must be sparse")


def masked_matmul(x: Tensor, y: Tensor, mask, name=None):
    """dense @ dense sampled at mask's sparsity (reference:
    sparse/matmul.py masked_matmul ≈ SDDMM)."""
    if not isinstance(mask, (SparseCooTensor, SparseCsrTensor)):
        raise TypeError("mask must be sparse")
    coo = mask if isinstance(mask, SparseCooTensor) else mask.to_sparse_coo()
    idx = coo.indices_

    def fn(a, b):
        rows, cols = idx[0], idx[1]
        return jnp.sum(a[rows, :] * b[:, cols].T, axis=-1)
    vals = apply_op("masked_matmul", fn, [_as_t(x), _as_t(y)])
    out = SparseCooTensor(idx, vals._data, coo.dense_shape,
                          stop_gradient=vals.stop_gradient)
    out._node, out._out_idx = vals._node, vals._out_idx
    return out


def mv(x, vec, name=None) -> Tensor:
    return matmul(x, vec, name)


def transpose(x: SparseCooTensor, perm, name=None) -> SparseCooTensor:
    idx = np.asarray(x.indices_)[list(perm), :]
    shape = tuple(x.dense_shape[p] for p in perm)
    out = SparseCooTensor(idx, x._data, shape, stop_gradient=x.stop_gradient)
    out._node, out._out_idx = x._node, x._out_idx
    return out


def _value_unary(name, fn):
    def f(x, name_=None):
        out = apply_op(f"sparse_{name}", fn, [x])
        if isinstance(x, SparseCooTensor):
            res = SparseCooTensor(x.indices_, out._data, x.dense_shape,
                                  stop_gradient=out.stop_gradient)
        elif isinstance(x, SparseCsrTensor):
            res = SparseCsrTensor(x.crows_, x.cols_, out._data, x.dense_shape,
                                  stop_gradient=out.stop_gradient)
        else:
            return out
        res._node, res._out_idx = out._node, out._out_idx
        return res
    f.__name__ = name
    return f


relu = _value_unary("relu", jax.nn.relu)
sinh = _value_unary("sinh", jnp.sinh)
asin = _value_unary("asin", jnp.arcsin)
asinh = _value_unary("asinh", jnp.arcsinh)
atan = _value_unary("atan", jnp.arctan)
atanh = _value_unary("atanh", jnp.arctanh)
tan = _value_unary("tan", jnp.tan)
expm1 = _value_unary("expm1", jnp.expm1)
log1p = _value_unary("log1p", jnp.log1p)
square = _value_unary("square", jnp.square)
neg = _value_unary("neg", jnp.negative)
deg2rad = _value_unary("deg2rad", jnp.deg2rad)
rad2deg = _value_unary("rad2deg", jnp.rad2deg)
relu6 = _value_unary("relu6", lambda a: jnp.clip(a, 0, 6))
leaky_relu = _value_unary("leaky_relu", lambda a: jax.nn.leaky_relu(a, 0.01))
sin = _value_unary("sin", jnp.sin)
tanh = _value_unary("tanh", jnp.tanh)
sqrt = _value_unary("sqrt", jnp.sqrt)
abs = _value_unary("abs", jnp.abs)  # noqa: A001


def pow(x, factor, name=None):  # noqa: A001
    """Elementwise power on sparse values (reference: paddle.sparse.pow)."""
    return _value_unary("pow", lambda a: jnp.power(a, factor))(x)


cast = None  # assigned below


def _cast(x, index_dtype=None, value_dtype=None):
    vd = convert_dtype(value_dtype) if value_dtype else None
    out = apply_op("sparse_cast", lambda a: a.astype(vd) if vd else a, [x])
    if isinstance(x, SparseCooTensor):
        idx = x.indices_.astype(convert_dtype(index_dtype)) if index_dtype \
            else x.indices_
        res = SparseCooTensor(idx, out._data, x.dense_shape,
                              stop_gradient=out.stop_gradient)
        res._node, res._out_idx = out._node, out._out_idx
        return res
    return out


cast = _cast


def _as_plain(y):
    if isinstance(y, Tensor):
        return Tensor(y._data, stop_gradient=y.stop_gradient)._replace_from(y)
    return Tensor(jnp.asarray(y))


def _as_t(x):
    return x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))


from . import nn  # noqa: E402,F401


def is_same_shape(x, y) -> bool:
    """reference: sparse.is_same_shape."""
    return tuple(x.shape) == tuple(y.shape)


def coalesce(x, name=None):
    """reference: sparse.coalesce — merge duplicate COO indices (sum
    values), sort lexicographically."""
    import numpy as np
    if not isinstance(x, SparseCooTensor):
        raise TypeError("coalesce expects a SparseCooTensor")
    idx = np.asarray(x.indices()._data)
    vals = x.values()._data
    nd, nnz = idx.shape
    dims = tuple(int(s) for s in x.shape[:nd])
    flat = np.ravel_multi_index(tuple(idx), dims)
    uniq, inv = np.unique(flat, return_inverse=True)

    def merge(v):
        import jax
        seg = jax.ops.segment_sum(v, jnp.asarray(inv), num_segments=len(uniq))
        return seg
    merged = apply_op("coalesce_values", merge, [x.values()])
    new_idx = np.stack(np.unravel_index(uniq, dims)).astype(idx.dtype)
    return sparse_coo_tensor(new_idx, merged, shape=tuple(x.shape))


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):  # noqa: A002
    """reference: sparse.addmm — beta*input + alpha*(x @ y), sparse x."""
    out = matmul(x, y)
    from ..core import ops as _ops
    return _ops.add(_ops.scale(input, beta), _ops.scale(_as_plain(out), alpha))


def reshape(x, shape, name=None):
    """reference: sparse.reshape — COO index remap through flat offsets."""
    import numpy as np
    if isinstance(x, SparseCsrTensor):
        raise NotImplementedError("sparse.reshape supports COO")
    old = tuple(int(s) for s in x.shape)
    new = []
    neg = -1
    total = int(np.prod(old))
    for i, s in enumerate(shape):
        new.append(int(s))
        if int(s) == -1:
            neg = i
    if neg >= 0:
        known = -int(np.prod(new))
        new[neg] = total // known
    idx = np.asarray(x.indices()._data)
    flat = np.ravel_multi_index(tuple(idx), old)
    new_idx = np.stack(np.unravel_index(flat, tuple(new))).astype(idx.dtype)
    return sparse_coo_tensor(new_idx, x.values(), shape=tuple(new))
