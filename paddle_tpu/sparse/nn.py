"""paddle.sparse.nn analog (reference: python/paddle/sparse/nn/ — ReLU,
Softmax, Conv3D/SubmConv3D, BatchNorm over sparse tensors, backed by
phi/kernels/sparse/). Activations operate on values; 3-D convs fall back to
a dense XLA conv — on TPU the MXU conv on a dense block beats scatter-based
submanifold kernels except at extreme (>99%) sparsity, and XLA has no sparse
conv lowering."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor, apply_op
from ..nn.layer import Layer
from . import SparseCooTensor, _dense_to_coo, _value_unary, relu as _relu, \
    relu6 as _relu6, leaky_relu as _leaky


class ReLU(Layer):
    def forward(self, x):
        return _relu(x)


class ReLU6(Layer):
    def forward(self, x):
        return _relu6(x)


class LeakyReLU(Layer):
    def __init__(self, negative_slope=0.01):
        super().__init__()
        self._slope = negative_slope

    def forward(self, x):
        return _value_unary(
            "leaky_relu", lambda a: jax.nn.leaky_relu(a, self._slope))(x)


class Softmax(Layer):
    """Softmax over the last dense dim of a CSR/COO matrix row-wise
    (reference: sparse/nn/layer/activation.py Softmax — rows of the sparse
    matrix, softmax over present entries only)."""

    def __init__(self, axis=-1):
        super().__init__()
        self._axis = axis

    def forward(self, x):
        if isinstance(x, SparseCooTensor):
            rows = x.indices_[0]
            nrows = x.dense_shape[0]

            def fn(v):
                mx = jax.ops.segment_max(v, rows, num_segments=nrows)
                e = jnp.exp(v - mx[rows])
                s = jax.ops.segment_sum(e, rows, num_segments=nrows)
                return e / s[rows]
            out = apply_op("sparse_softmax", fn, [x])
            res = SparseCooTensor(x.indices_, out._data, x.dense_shape,
                                  stop_gradient=out.stop_gradient)
            res._node, res._out_idx = out._node, out._out_idx
            return res
        raise TypeError("sparse Softmax expects SparseCooTensor")


class Conv3D(Layer):
    """Sparse 3-D conv via densify → XLA conv → sparsify (see module doc).
    Reference: sparse/nn/layer/conv.py Conv3D over NDHWC coo inputs."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, bias_attr=None,
                 data_format="NDHWC"):
        super().__init__()
        from ..nn.layers.conv import Conv3D as DenseConv3D
        self._conv = DenseConv3D(in_channels, out_channels, kernel_size,
                                 stride=stride, padding=padding,
                                 dilation=dilation, groups=groups,
                                 data_format="NCDHW")

    def forward(self, x):
        dense = x.to_dense() if isinstance(x, SparseCooTensor) else x
        # NDHWC → NCDHW for the dense conv, back after
        from ..core import ops as _ops
        y = self._conv(_ops.transpose(dense, [0, 4, 1, 2, 3]))
        y = _ops.transpose(y, [0, 2, 3, 4, 1])
        return _dense_to_coo(y)


SubmConv3D = Conv3D


class BatchNorm(Layer):
    """BatchNorm over sparse values (reference: sparse/nn/layer/norm.py —
    normalizes the channel dim of present values only)."""

    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 data_format="NDHWC"):
        super().__init__()
        from ..nn.layers.norm import BatchNorm1D
        self._bn = BatchNorm1D(num_features)

    def forward(self, x):
        if isinstance(x, SparseCooTensor):
            vals = self._bn(x.values())
            out = SparseCooTensor(x.indices_, vals._data, x.dense_shape,
                                  stop_gradient=vals.stop_gradient)
            out._node, out._out_idx = vals._node, vals._out_idx
            return out
        return self._bn(x)
