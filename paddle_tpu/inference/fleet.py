"""paddle_tpu.inference.fleet — fault-tolerant fleet serving (ISSUE 14).

Millions of users means N engine replicas behind a router, not one
engine. Every ingredient already existed — r12's graceful drain and
`overloaded_total` load-shedding signal, per-replica /healthz (r15),
fleet-scope aggregation (r16), the refcounted prefix-block trie (r11),
and the seeded chaos harness (r12) — this module is the layer that
survives a replica dying mid-request:

  ReplicaRegistry   fleet membership + health-driven ejection. Each
                    replica is a ReplicaHandle over a live ServingEngine
                    (in-process replicas — the same engines a spawned
                    fleet runs one-per-host); `probe()` scrapes every
                    member's health through the chaos site
                    ``fleet.scrape`` and ejects a member whose scrape
                    fails `fail_threshold` consecutive times (503/stale/
                    unreachable). Membership changes mirror into an
                    optional obs.FleetAggregator so the merged telemetry
                    surface tracks the registry, not a stale config.

  FleetRouter       prefix-aware request routing with retry/failover.
                    The routing key is the prompt's FIRST full
                    kv-block token tuple — exactly the radix trie's
                    node key — rendezvous-hashed (HRW) over the serving
                    replicas, so every request sharing a system prompt
                    lands on the replica already holding its blocks and
                    the prefix-cache hit rate becomes a FLEET property.
                    When a replica is ejected, only ITS keys move (each
                    to its own rendezvous successor); every other
                    key→replica assignment is untouched. Dispatch
                    retries replica-local refusals (`Request.retriable`
                    — overloaded/draining/queue_full) on the next
                    candidate, then backs off with the capped
                    exponential schedule of ``resilience.chaos.retry``
                    under a per-request deadline budget; terminal
                    refusals (kv_oom, shape rejects) return immediately
                    — the router never hot-loops a request no replica
                    will ever accept. In-flight requests on a replica
                    that dies mid-traffic (``chaos.ReplicaDown`` at the
                    ``fleet.step`` site) are re-submitted elsewhere;
                    greedy decode is deterministic per prompt, so the
                    redispatched output is bit-identical to a fault-free
                    run (asserted against an oracle in the chaos tests).
                    `step()` also consults each handle's attached
                    obs.Prober (ISSUE 19): a replica whose golden-canary
                    probe reports `failing` is drained + ejected exactly
                    like a dead one — wrong answers are a liveness
                    failure as far as routing is concerned.

  AutoscaleController  goodput-driven scaling over the registry. Each
                    `tick()` reads the members' /healthz payloads — the
                    summed `overloaded_total` delta (r12 named it "the
                    autoscaler signal"), queue depths, and goodput
                    (completed/requests delta) — and decides: scale UP
                    (spawn a replica into the registry) on overload /
                    deep queues / goodput under floor / membership
                    below min (the died-replica replacement); scale
                    DOWN only via the graceful handshake — pick the
                    least-loaded replica, `begin_drain()` (the router
                    stops routing to it), and REMOVE it only once its
                    queue and slots are empty. Never a hard kill.

Everything is synchronous and deterministic: the router's `step()`
drives one engine step per serving replica, chaos faults fire from a
seeded Injector, and the backoff sleep is injectable (the default
"sleep" for an in-process fleet STEPS the fleet instead of wall-
sleeping — while a real frontend waits, real replicas serve). The proof
harness is tools/fleet_chaos_smoke.py + tests/test_fleet_serving.py:
every failover claim is pinned by an injected fault.
"""
from __future__ import annotations

import hashlib
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

import numpy as np

from ..resilience.chaos import ReplicaDown, retry
from .serving import Request

__all__ = ["ReplicaHandle", "ReplicaRegistry", "FleetRouter",
           "FleetRequest", "AutoscaleController"]


# ---------------------------------------------------------------- handles

class ReplicaHandle:
    """One fleet member: a named ServingEngine + its liveness state."""

    def __init__(self, name: str, engine, *, url: Optional[str] = None):
        self.name = name
        self.engine = engine
        self.url = url                 # telemetry base url, when served
        self.state = "serving"         # serving | draining | ejected
        self.steps = 0                 # router step attempts (chaos ctx)
        self.consecutive_failures = 0
        self.ejected_reason: Optional[str] = None
        self.prober = None             # obs.Prober, when attached (r19)

    def health(self) -> dict:
        return self.engine.health()

    def __repr__(self):
        return f"ReplicaHandle({self.name}, {self.state})"


class ReplicaRegistry:
    """Fleet membership + health-driven ejection (module docstring)."""

    def __init__(self, replicas=None, *, aggregator=None, chaos=None,
                 fail_threshold: int = 2):
        if fail_threshold < 1:
            raise ValueError(f"fail_threshold must be >= 1, "
                             f"got {fail_threshold}")
        self.aggregator = aggregator   # obs.FleetAggregator (optional)
        self.chaos = chaos             # resilience.chaos.Injector
        self.fail_threshold = int(fail_threshold)
        self._handles: Dict[str, ReplicaHandle] = {}
        self.ejected: Dict[str, ReplicaHandle] = {}   # post-mortem log
        if replicas:
            items = replicas.items() if isinstance(replicas, dict) \
                else replicas
            for name, engine in items:
                self.add(name, engine)

    # ------------------------------------------------------- membership
    def add(self, name: str, engine, *,
            url: Optional[str] = None) -> ReplicaHandle:
        if name in self._handles:
            raise ValueError(f"replica {name!r} already registered")
        h = ReplicaHandle(name, engine, url=url)
        self._handles[name] = h
        if self.aggregator is not None and url is not None:
            self.aggregator.add_replica(name, url)
        return h

    def remove(self, name: str) -> Optional[ReplicaHandle]:
        h = self._handles.pop(name, None)
        if h is not None and self.aggregator is not None:
            self.aggregator.remove_replica(name)
        return h

    def eject(self, name: str, reason: str) -> Optional[ReplicaHandle]:
        """Take a dead/unreachable member out of every candidate set —
        its rendezvous successors absorb its keys on the next rank().
        The handle survives in `self.ejected` for post-mortems."""
        h = self.remove(name)
        if h is not None:
            h.state = "ejected"
            h.ejected_reason = reason
            self.ejected[name] = h
        return h

    def handle(self, name: str) -> ReplicaHandle:
        return self._handles[name]

    def handles(self, states=("serving",)) -> List[ReplicaHandle]:
        return [h for h in self._handles.values() if h.state in states]

    def names(self, states=("serving",)) -> List[str]:
        return [h.name for h in self.handles(states)]

    def __len__(self):
        return len(self._handles)

    def __contains__(self, name):
        return name in self._handles

    # ----------------------------------------------------------- health
    def probe(self) -> Dict[str, dict]:
        """Scrape every member's health (through the ``fleet.scrape``
        chaos site); a failing scrape counts toward `fail_threshold`
        consecutive failures, at which point the member is EJECTED
        (503/stale/unreachable). A draining member answering its scrape
        is healthy — scale-down removal is the autoscaler's graceful
        handshake, never an ejection. Returns {name: health payload}
        for the members that answered."""
        out: Dict[str, dict] = {}
        for h in list(self._handles.values()):
            try:
                if self.chaos is not None:
                    self.chaos.fire("fleet.scrape", replica=h.name)
                payload = h.health()
            except ReplicaDown as e:
                self.eject(h.name, f"unreachable: {e}")
                continue
            except Exception as e:   # noqa: BLE001 — scrape timeout /
                # transport class: degrade toward ejection, per contract
                h.consecutive_failures += 1
                if h.consecutive_failures >= self.fail_threshold:
                    self.eject(h.name,
                               f"{type(e).__name__} x"
                               f"{h.consecutive_failures}: {e}")
                continue
            h.consecutive_failures = 0
            out[h.name] = payload
        return out


# ----------------------------------------------------------------- router

@dataclass(eq=False)
class FleetRequest:
    """One request's life at FLEET scope: which replicas it was offered
    to, where it landed, how often it was redispatched, and the terminal
    engine Request carrying the generated tokens."""
    id: int
    prompt: np.ndarray
    max_new_tokens: Optional[int] = None
    deadline_s: Optional[float] = None      # END-TO-END queue budget:
    #   measured from t_submit, so retries and redispatches spend the
    #   same clock instead of restarting it
    t_submit: Optional[float] = None        # router clock at submit()
    key: bytes = b""
    status: str = "pending"   # pending|done|rejected|timeout|error
    reason: Optional[str] = None
    replica: Optional[str] = None           # current / last assignment
    attempts: List[dict] = field(default_factory=list)
    redispatches: int = 0
    request: Optional[Request] = None       # the engine-side request

    @property
    def tokens(self):
        return None if self.request is None else self.request.tokens

    @property
    def n_out(self) -> int:
        return 0 if self.request is None else self.request.n_out

    def record(self) -> dict:
        rec = {"id": self.id, "status": self.status,
               "replica": self.replica,
               "attempts": self.attempts,
               "redispatches": self.redispatches}
        if self.reason:
            rec["reason"] = self.reason
        return rec


class _AllShed(Exception):
    """Internal: one full candidate-ring pass found only retriable
    refusals — chaos.retry backs off and rings again."""

    def __init__(self, reason):
        self.reason = reason
        super().__init__(str(reason))


class FleetRouter:
    """Prefix-aware router with retry/failover (module docstring)."""

    def __init__(self, registry: ReplicaRegistry, *,
                 policy: str = "prefix",
                 key_tokens: Optional[int] = None,
                 chaos=None,
                 retry_budget_s: float = 1.0,
                 base_delay: float = 0.01,
                 max_delay: float = 0.25,
                 clock: Callable[[], float] = time.monotonic,
                 sleep: Optional[Callable[[float], None]] = None,
                 seed: int = 0):
        if policy not in ("prefix", "random"):
            raise ValueError(f"policy must be 'prefix' or 'random', "
                             f"got {policy!r}")
        self.registry = registry
        self.policy = policy
        self.chaos = chaos if chaos is not None else registry.chaos
        self.retry_budget_s = float(retry_budget_s)
        self.base_delay = float(base_delay)
        self.max_delay = float(max_delay)
        self.clock = clock
        # requests that reached a terminal state inside a nested backoff
        # step (below) — surfaced by the NEXT step()/drain() call so no
        # terminal FleetRequest is ever silently dropped
        self._pending_done: List[FleetRequest] = []
        # the in-process backoff "sleep" STEPS the fleet: while a real
        # frontend waits out a shed, real replicas serve — so a backoff
        # can actually free the capacity it is waiting for. Its results
        # are buffered, not discarded. Pass time.sleep for wall-clock
        # pacing against out-of-process replicas.
        self._sleep = sleep if sleep is not None \
            else (lambda delay: self._pending_done.extend(
                self._step_once()))
        self._rng = np.random.RandomState(seed)
        self._key_tokens = key_tokens
        self._next_id = 0
        self._inflight: Dict[str, Dict[int, FleetRequest]] = {}
        self.counters = {"dispatched": 0, "completed": 0, "rejected": 0,
                         "timeout": 0, "errors": 0, "retries": 0,
                         "backoffs": 0, "redispatched": 0,
                         "replicas_lost": 0, "probe_ejected": 0}

    # ---------------------------------------------------------- routing
    def _block_tokens(self) -> int:
        """Routing-key width: one kv block of the replicas' config (the
        trie's node key width) — falls back to the prompt cap for
        non-paged fleets."""
        if self._key_tokens is not None:
            return self._key_tokens
        for h in self.registry.handles(("serving", "draining")):
            cfg = h.engine.config
            return cfg.kv_block if cfg.paged else cfg.prompt_cap
        return 16

    def routing_key(self, prompt) -> bytes:
        """The prompt's first full-block token tuple, serialized — the
        same bytes for every request sharing the block-aligned prefix,
        whatever their suffixes do."""
        bt = self._block_tokens()
        ids = np.asarray(prompt).reshape(-1)[:bt]  # lint: allow(tracer-asarray)
        return b",".join(b"%d" % int(t) for t in ids)

    def rank(self, key: bytes) -> List[str]:
        """Serving replicas in rendezvous (highest-random-weight) order
        for `key`: candidate 0 owns the key; later entries are its
        failover successors. Removing a replica moves ONLY its keys
        (each to its own successor) — the property that keeps the other
        replicas' prefix caches hot through membership churn."""
        names = self.registry.names(("serving",))
        if self.policy == "random":
            names = list(names)
            self._rng.shuffle(names)
            return names

        def score(name: str) -> int:
            h = hashlib.blake2b(digest_size=8)
            h.update(name.encode("utf-8"))
            h.update(b"\x00")
            h.update(key)
            return int.from_bytes(h.digest(), "big")

        return sorted(names, key=score, reverse=True)

    # --------------------------------------------------------- dispatch
    def submit(self, prompt, max_new_tokens: Optional[int] = None,
               deadline_s: Optional[float] = None) -> FleetRequest:
        """Route one prompt into the fleet. Returns the FleetRequest:
        "pending" once accepted somewhere (drive `step()`/`drain()` to
        completion), "rejected" when terminal everywhere or the retry
        budget expired with every replica shedding."""
        freq = FleetRequest(id=self._next_id,
                            prompt=np.asarray(prompt),  # lint: allow(tracer-asarray)
                            max_new_tokens=max_new_tokens,
                            deadline_s=deadline_s,
                            t_submit=self.clock())
        self._next_id += 1
        freq.key = self.routing_key(freq.prompt)
        return self._dispatch(freq)

    def _remaining_deadline(self, freq: FleetRequest) -> Optional[float]:
        """The END-TO-END budget left: deadline_s minus time already
        spent since submit() — a retry or redispatch spends the same
        clock, it never restarts it."""
        if freq.deadline_s is None or freq.t_submit is None:
            return freq.deadline_s
        return freq.deadline_s - (self.clock() - freq.t_submit)

    def _dispatch(self, freq: FleetRequest) -> FleetRequest:
        def ring_pass():
            remaining = self._remaining_deadline(freq)
            if remaining is not None and remaining <= 0:
                # the budget expired before any replica accepted it —
                # terminal, exactly as if a queue deadline fired
                freq.status, freq.reason = "timeout", "queue_deadline"
                self.counters["timeout"] += 1
                return
            names = self.rank(freq.key)
            if not names:
                # nobody serving RIGHT NOW — retriable: the autoscaler
                # may be spawning a replacement this very backoff
                raise _AllShed("no_serving_replicas")
            last = None
            for name in names:
                handle = self.registry.handle(name)
                try:
                    req = handle.engine.submit(
                        freq.prompt, freq.max_new_tokens,
                        deadline_s=remaining)
                except ReplicaDown as e:
                    self._replica_lost(name, str(e))
                    continue
                freq.attempts.append({"replica": name,
                                      "status": req.status,
                                      "reason": req.reason})
                if req.status == "queued":
                    freq.replica = name
                    freq.request = req
                    self._inflight.setdefault(name, {})[req.id] = freq
                    self.counters["dispatched"] += 1
                    return
                if req.retriable is False:
                    # terminal everywhere: kv_oom / shape rejects — do
                    # NOT hot-loop it around the ring
                    freq.status, freq.reason = "rejected", req.reason
                    self.counters["rejected"] += 1
                    return
                last = req.reason
                self.counters["retries"] += 1
            raise _AllShed(last or "all_rejected")

        def on_backoff(attempt, delay, exc):
            self.counters["backoffs"] += 1

        try:
            retry(ring_pass, deadline=self.retry_budget_s,
                  base_delay=self.base_delay, max_delay=self.max_delay,
                  retry_on=(_AllShed,), sleep=self._sleep,
                  clock=self.clock, on_retry=on_backoff)
        except _AllShed as e:
            freq.status, freq.reason = "rejected", \
                f"fleet_shed:{e.reason}"
            self.counters["rejected"] += 1
        return freq

    def _replica_lost(self, name: str, detail: str):
        """A replica died under us: eject it and re-submit every
        request that was in flight there — the engine-side partial
        output is gone with the process; greedy decode re-runs to the
        SAME tokens elsewhere (bit-identical by determinism, pinned by
        the chaos tests)."""
        self.registry.eject(name, detail)
        self.counters["replicas_lost"] += 1
        lost = self._inflight.pop(name, {})
        for freq in lost.values():
            freq.redispatches += 1
            self.counters["redispatched"] += 1
            if self._dispatch(freq).status != "pending":
                # the redispatch itself went terminal (budget expired /
                # fleet-wide shed): surface it through the same buffer
                # as backoff-step completions — never silently dropped
                self._pending_done.append(freq)

    def check_probes(self):
        """Eject any replica whose attached Prober reports `failing`
        (ISSUE 19): a correctness-failing replica leaves routing exactly
        like a dead one — drained (stops accepting work it would answer
        wrongly) and ejected, with its in-flight requests redispatched
        elsewhere where greedy determinism re-produces the SAME tokens.
        The LB stops trusting a replica the moment it stops being
        correct, not merely fast."""
        for h in list(self.registry.handles(("serving", "draining"))):
            prober = getattr(h, "prober", None)
            if prober is None or not prober.failing:
                continue
            bad = sorted(n for n, v in prober.probez()["variants"].items()
                         if v.get("failing"))
            try:
                h.engine.begin_drain()
            except Exception:
                pass               # ejection must not depend on the drain
            self.counters["probe_ejected"] += 1
            self._replica_lost(h.name, "probe_fail:" + ",".join(bad))

    # ------------------------------------------------------ the step loop
    def step(self) -> List[FleetRequest]:
        """One engine step on every serving+draining replica (through
        the ``fleet.step`` chaos site — a ReplicaKill fault manifests
        here as ReplicaDown). Consults probe status first — a
        correctness-failing replica is ejected before it can emit more
        wrong tokens. Returns every FleetRequest that reached a terminal
        status — including any that finished inside a backoff step since
        the last call."""
        self.check_probes()
        out, self._pending_done = self._pending_done, []
        out.extend(self._step_once())
        return out

    def _settle(self, freq: FleetRequest, req) -> FleetRequest:
        freq.request = req
        freq.status = req.status
        freq.reason = req.reason
        if req.status == "done":
            self.counters["completed"] += 1
        elif req.status == "timeout":
            self.counters["timeout"] += 1
        elif req.status == "error":
            self.counters["errors"] += 1
        return freq

    def _step_once(self) -> List[FleetRequest]:
        done: List[FleetRequest] = []
        for h in list(self.registry.handles(("serving", "draining"))):
            h.steps += 1
            try:
                if self.chaos is not None:
                    self.chaos.fire("fleet.step", replica=h.name,
                                    step=h.steps)
                finished = h.engine.step() if h.engine.busy else []
            except ReplicaDown as e:
                self._replica_lost(h.name, str(e))
                continue
            pending = self._inflight.get(h.name, {})
            for req in finished:
                freq = pending.pop(req.id, None)
                if freq is None:
                    continue        # a replica-local caller's request
                done.append(self._settle(freq, req))
            # the mirror case: a replica-local step loop on the same
            # engine (a Prober cycle riding real decode) may have driven
            # one of OUR requests terminal — that step()'s `finished`
            # went to the local caller, not here. The Request object is
            # shared, so its status is authoritative; without this sweep
            # the FleetRequest pends forever.
            for rid in [rid for rid, fq in pending.items()
                        if fq.request is not None and fq.request.status
                        in ("done", "timeout", "error")]:
                freq = pending.pop(rid)
                done.append(self._settle(freq, freq.request))
        return done

    @property
    def inflight(self) -> int:
        return sum(len(v) for v in self._inflight.values())

    def drain(self, max_steps: Optional[int] = None,
              tick=None) -> List[FleetRequest]:
        """step() until nothing is in flight anywhere (or `max_steps`).
        `tick` is an optional callable run between steps — the place an
        AutoscaleController.tick rides the serving loop."""
        out: List[FleetRequest] = []
        n = 0
        while self._pending_done or self.inflight or \
                any(h.engine.busy for h in
                    self.registry.handles(("serving", "draining"))):
            if max_steps is not None and n >= max_steps:
                break
            out.extend(self.step())
            n += 1
            if tick is not None:
                tick()
        return out

    # -------------------------------------------------------- reporting
    def fleet_prefix_stats(self) -> dict:
        """Fleet-scope prefix-cache effectiveness: summed hit/miss/saved
        counters over every live member (the A/B number the routing
        policy moves)."""
        hits = misses = saved = 0
        for h in self.registry.handles(("serving", "draining")):
            c = h.engine.metrics.counters
            hits += c["prefix_hit"]
            misses += c["prefix_miss"]
            saved += c["prefill_tokens_saved"]
        total = hits + misses
        return {"prefix_hit": hits, "prefix_miss": misses,
                "prefill_tokens_saved": saved,
                "hit_rate": hits / total if total else None}

    def metrics_text(self, prefix: str = "paddle_tpu_router") -> str:
        """Prometheus exposition of the router's own counters — register
        it beside the members' pages (or the FleetAggregator's merged
        one) so routing behavior is scrapeable like everything else."""
        from ..profiler._metrics import counter_lines, gauge_lines
        helps = {"dispatched": "requests accepted by some replica",
                 "completed": "requests finished successfully",
                 "rejected": "requests refused (terminal or budget "
                             "exhausted)",
                 "timeout": "requests expired in a replica queue",
                 "errors": "requests lost to replica exceptions",
                 "retries": "per-replica refusals retried elsewhere",
                 "backoffs": "full-ring shed passes backed off",
                 "redispatched": "in-flight requests re-submitted after "
                                 "a replica died",
                 "replicas_lost": "replicas ejected after dying "
                                  "mid-traffic",
                 "probe_ejected": "replicas ejected on golden-probe "
                                  "correctness failure"}
        lines: List[str] = []
        for name, value in self.counters.items():
            lines.extend(counter_lines(prefix, f"{name}_total", value,
                                       helps[name]))
        lines.extend(gauge_lines(prefix, "inflight", self.inflight,
                                 "requests currently assigned to a "
                                 "replica"))
        lines.extend(gauge_lines(
            prefix, "replicas_serving",
            len(self.registry.names(("serving",))),
            "registry members accepting new work"))
        return "\n".join(lines) + "\n"


# ------------------------------------------------------------- autoscaler

class AutoscaleController:
    """Goodput-driven scaling over a ReplicaRegistry (module docstring).

    `spawn(name) -> engine` builds a replacement/scale-up replica — in
    process that is a fresh ServingEngine over the SHARED model (shared
    executables: a spawned replica adds zero compiles); a real fleet
    plugs in its pod launcher. Scale-down is only ever the graceful
    handshake: begin_drain → (router reroutes) → remove-once-empty."""

    def __init__(self, registry: ReplicaRegistry,
                 spawn: Callable[[str], object], *,
                 min_replicas: int = 1, max_replicas: int = 8,
                 scale_up_queue_depth: float = 4.0,
                 goodput_floor: float = 0.9,
                 idle_ticks_before_scale_down: int = 3):
        if not (1 <= min_replicas <= max_replicas):
            raise ValueError(f"need 1 <= min_replicas <= max_replicas, "
                             f"got {min_replicas}..{max_replicas}")
        self.registry = registry
        self.spawn = spawn
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.scale_up_queue_depth = float(scale_up_queue_depth)
        self.goodput_floor = float(goodput_floor)
        self.idle_ticks_before_scale_down = int(
            idle_ticks_before_scale_down)
        # PER-REPLICA counter baselines: deltas are computed member by
        # member, so one transiently-unscraped replica contributes zero
        # this tick instead of bouncing the fleet totals down and back
        # up (a bounce would read as phantom overload on recovery)
        self._last: Dict[str, dict] = {}
        self._idle_ticks = 0
        self._spawned = 0
        self.decisions: List[dict] = []

    def _spawn_into_registry(self, action: str) -> str:
        name = f"auto{self._spawned}"
        self._spawned += 1
        engine = self.spawn(name)
        self.registry.add(name, engine)
        self.decisions.append({"action": action, "replica": name})
        return name

    def tick(self) -> dict:
        """One control-loop pass; returns the signal/decision record
        (also appended to `self.decisions` when membership changed)."""
        # finish any graceful scale-down first: a draining member whose
        # queue AND slots emptied leaves the registry — never earlier
        for h in list(self.registry.handles(("draining",))):
            if not h.engine.busy and h.engine.queue_depth == 0:
                self.registry.remove(h.name)
                self.decisions.append({"action": "scale_down_done",
                                       "replica": h.name})
        payloads = self.registry.probe()
        serving = self.registry.handles(("serving",))
        live = {n: p for n, p in payloads.items()
                if n in self.registry and
                self.registry.handle(n).state == "serving"}
        d_over = d_req = d_done = 0
        queue_depth = inflight = 0
        cur: Dict[str, dict] = {}
        for name, p in live.items():
            snap = {"overloaded": p.get("overloaded_total", 0) or 0,
                    "requests": p.get("requests_total", 0) or 0,
                    "completed": p.get("completed_total", 0) or 0}
            base = self._last.get(name, snap)  # first sight: delta 0 —
            # a freshly added replica's history is not this tick's news
            d_over += snap["overloaded"] - base["overloaded"]
            d_req += snap["requests"] - base["requests"]
            d_done += snap["completed"] - base["completed"]
            cur[name] = snap
            queue_depth += p.get("queue_depth", 0)
            inflight += p.get("inflight", 0)
        # members that did not answer keep their old baseline (their
        # delta resumes cleanly when the scrape recovers); baselines of
        # removed/ejected members are pruned
        self._last = {n: cur.get(n, self._last.get(n))
                      for n in self.registry.names(("serving",
                                                    "draining"))
                      if n in cur or n in self._last}
        goodput = d_done / d_req if d_req > 0 else None
        mean_q = queue_depth / max(len(serving), 1)
        rec = {"serving": len(serving), "overloaded_delta": max(d_over, 0),
               "queue_depth": queue_depth, "inflight": inflight,
               "goodput": goodput, "action": None}

        if len(serving) < self.min_replicas:
            # the died-replica replacement: membership dropped below the
            # floor (ejection), restore it
            rec["action"] = "replace"
            rec["replica"] = self._spawn_into_registry("replace")
            self._idle_ticks = 0
        elif (d_over > 0 or mean_q > self.scale_up_queue_depth
              or (goodput is not None and goodput < self.goodput_floor)) \
                and len(serving) < self.max_replicas:
            rec["action"] = "scale_up"
            rec["replica"] = self._spawn_into_registry("scale_up")
            self._idle_ticks = 0
        elif (queue_depth == 0 and inflight == 0 and d_over <= 0
              and d_req == 0 and len(serving) > self.min_replicas):
            self._idle_ticks += 1
            if self._idle_ticks >= self.idle_ticks_before_scale_down:
                # graceful scale-down: drain the least-loaded member —
                # the router stops routing to it NOW; removal happens in
                # a later tick once it is empty (it already is here, but
                # in-flight work on a busier pick would finish first)
                victim = min(serving,
                             key=lambda h: (h.engine.queue_depth,
                                            h.name))
                victim.engine.begin_drain()
                victim.state = "draining"
                rec["action"] = "scale_down_begin"
                rec["replica"] = victim.name
                self.decisions.append({"action": "scale_down_begin",
                                       "replica": victim.name})
                self._idle_ticks = 0
        else:
            self._idle_ticks = 0
        return rec
