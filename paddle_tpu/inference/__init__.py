"""paddle_tpu.inference — deployment predictor API.

TPU-native redesign of the reference inference stack (SURVEY §2.4:
paddle/fluid/inference/ AnalysisPredictor, analysis_predictor.cc:253 Init,
:885 ZeroCopyRun, paddle_analysis_config.h). The reference needs 98k LoC of
IR passes, subgraph capture and per-engine op converters (TensorRT: 131
converters, op_teller.h:68) because optimization happens op-by-op at load
time; here the artifact IS an AOT-compiled StableHLO module produced by
`static.save_inference_model` or `jit.save(..., input_spec=...)` — XLA did
all fusion/layout work at export, so the predictor is: deserialize, bind
buffers, call. Zero-copy semantics come from jax device arrays (handles hold
device buffers; copy_to_cpu is the only host transfer).

API shape mirrors paddle.inference: Config → create_predictor → named
input/output handles → run().
"""
from __future__ import annotations

import json
import os
from typing import Dict, List, Optional

import numpy as np
import jax
import jax.numpy as jnp
from jax import export as jax_export


class Config:
    """reference: paddle_analysis_config.h AnalysisConfig. Knobs that steer
    CUDA/TRT/MKLDNN engine selection in the reference are accepted and
    recorded (summary() shows them) but are no-ops: XLA owns optimization."""

    def __init__(self, prog_file: str = None, params_file: str = None):
        # accept either a path prefix (our native artifact) or the
        # reference's (model, params) file pair pointing at the same prefix
        self._prefix = None
        if prog_file is not None:
            self._prefix = prog_file[:-8] if prog_file.endswith(".pdmodel") else prog_file
        self._params_file = params_file
        self._use_device = "tpu"
        self._memory_optim = True
        self._ir_optim = True
        self._glog_info = True
        self._profile = False
        self._cpu_math_threads = 1

    # --- device selection (reference: enable_use_gpu / disable_gpu) ---
    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0):
        self._use_device = "tpu"  # accelerator = the TPU on this platform

    def disable_gpu(self):
        self._use_device = "cpu"

    def use_gpu(self):
        return self._use_device != "cpu"

    def enable_xpu(self, *a, **kw):
        self._use_device = "tpu"

    # --- optimization toggles (XLA always optimizes; recorded for summary) ---
    def switch_ir_optim(self, x=True):
        self._ir_optim = bool(x)

    def ir_optim(self):
        return self._ir_optim

    def enable_memory_optim(self, x=True):
        self._memory_optim = bool(x)

    def enable_tensorrt_engine(self, *a, **kw):
        pass  # engine dispatch does not exist: one compiler path

    def enable_mkldnn(self):
        pass

    def set_cpu_math_library_num_threads(self, n):
        self._cpu_math_threads = int(n)

    def disable_glog_info(self):
        self._glog_info = False

    def enable_profile(self):
        """reference: AnalysisConfig::EnableProfile — per-run latency
        profiling. Here it attaches request-level ServingMetrics to the
        predictor: every run() observes its (synced) wall time into a
        log-bucket latency histogram; read `predictor.profile_summary()`
        (p50/p90/p99 + counters) or scrape `predictor.metrics_text()`."""
        self._profile = True

    def model_dir(self):
        return os.path.dirname(self._prefix or "")

    def prog_file(self):
        return (self._prefix or "") + ".pdmodel"

    def params_file(self):
        return self._params_file or ((self._prefix or "") + ".pdiparams.npz")

    def summary(self) -> str:
        rows = [("model_prefix", self._prefix), ("device", self._use_device),
                ("ir_optim", self._ir_optim), ("memory_optim", self._memory_optim),
                ("cpu_math_threads", self._cpu_math_threads),
                ("profile", self._profile)]
        return "\n".join(f"{k:>20}: {v}" for k, v in rows)


class Tensor:
    """Named I/O handle (reference: paddle_infer::Tensor / ZeroCopyTensor).
    Holds a device buffer; copy_from_cpu stages host data, copy_to_cpu is the
    only device→host transfer."""

    def __init__(self, name, aval=None):
        self.name = name
        self._aval = aval
        self._buf = None

    def reshape(self, shape):
        pass  # shapes bind at copy_from_cpu; symbolic-batch artifacts adapt

    def copy_from_cpu(self, data: np.ndarray):
        arr = np.asarray(data)
        if self._aval is not None and arr.dtype != self._aval.dtype:
            arr = arr.astype(self._aval.dtype)
        self._buf = jnp.asarray(arr)

    def share_external_data(self, data):
        self.copy_from_cpu(data)

    def copy_to_cpu(self) -> np.ndarray:
        if self._buf is None:
            raise RuntimeError(f"handle {self.name!r} has no data; run() first")
        return np.asarray(self._buf)

    def shape(self):
        if self._buf is not None:
            return list(self._buf.shape)
        return list(self._aval.shape) if self._aval is not None else None

    def type(self):
        if self._buf is not None:
            return np.dtype(self._buf.dtype)
        return np.dtype(self._aval.dtype) if self._aval is not None else None


class Predictor:
    """reference: paddle_infer::Predictor over AnalysisPredictor."""

    def __init__(self, config: Config):
        self.config = config
        prefix = config._prefix
        if prefix is None:
            raise ValueError("Config needs a model path prefix")
        with open(prefix + ".pdmodel", "rb") as f:
            self._exported = jax_export.deserialize(f.read())
        with open(prefix + ".pdmeta") as f:
            self._meta = json.load(f)
        self._inputs = {
            n: Tensor(n, jax.ShapeDtypeStruct(tuple(s), np.dtype(d)))
            for n, s, d in zip(self._meta["feed_names"],
                               self._meta["feed_shapes"],
                               self._meta["feed_dtypes"])}
        self._outputs = {n: Tensor(n) for n in self._meta["fetch_names"]}
        self._metrics = None
        if config._profile:
            from .serving import ServingMetrics
            self._metrics = ServingMetrics()

    def get_input_names(self) -> List[str]:
        return list(self._meta["feed_names"])

    def get_input_handle(self, name) -> Tensor:
        return self._inputs[name]

    def get_output_names(self) -> List[str]:
        return list(self._meta["fetch_names"])

    def get_output_handle(self, name) -> Tensor:
        return self._outputs[name]

    def run(self, inputs: Optional[List[np.ndarray]] = None):
        """ZeroCopyRun (analysis_predictor.cc:885): executes the AOT module
        on the bound input buffers. With `inputs` given, behaves like the
        legacy run(feeds)->fetches API."""
        import time as _time
        t0 = _time.perf_counter() if self._metrics is not None else None
        if inputs is not None:
            for n, a in zip(self._meta["feed_names"], inputs):
                self._inputs[n].copy_from_cpu(a)
        feeds = []
        for n in self._meta["feed_names"]:
            h = self._inputs[n]
            if h._buf is None:
                raise RuntimeError(f"input {n!r} not set; copy_from_cpu first")
            feeds.append(h._buf)
        outs = self._exported.call(*feeds)
        outs = outs if isinstance(outs, (tuple, list)) else (outs,)
        for n, o in zip(self._meta["fetch_names"], outs):
            self._outputs[n]._buf = o
        if self._metrics is not None:
            # profile mode measures the DEVICE-complete call, not the
            # dispatch: sync before closing the span (outside profile mode
            # run() stays fully async until copy_to_cpu)
            jax.block_until_ready(outs)
            items = int(feeds[0].shape[0]) if feeds and feeds[0].ndim else 1
            self._metrics.observe_call(_time.perf_counter() - t0,
                                       items=items)
        if inputs is not None:
            return [np.asarray(o) for o in outs]
        return True

    # -- enable_profile surface (reference: AnalysisConfig profiling) ----
    def profile_summary(self) -> Optional[dict]:
        """Aggregate run() latency/counters (Config.enable_profile());
        None when profiling is off."""
        return None if self._metrics is None else self._metrics.summary()

    def metrics_text(self, prefix: str = "paddle_tpu_infer") -> str:
        """Prometheus exposition of the per-run latency histogram +
        counters — empty string when profiling is off."""
        return "" if self._metrics is None else \
            self._metrics.metrics_text(prefix=prefix)

    def clone(self):
        """Share-weights clone (reference AnalysisPredictor::Clone): the
        exported module is immutable, so a shallow copy suffices."""
        p = Predictor.__new__(Predictor)
        p.config = self.config
        p._exported = self._exported
        p._meta = self._meta
        p._inputs = {n: Tensor(n, t._aval) for n, t in self._inputs.items()}
        p._outputs = {n: Tensor(n) for n in self._outputs}
        if self._metrics is not None:     # profiling is per-predictor
            from .serving import ServingMetrics
            p._metrics = ServingMetrics()
        else:
            p._metrics = None
        return p


def create_predictor(config: Config) -> Predictor:
    """reference: paddle_infer::CreatePredictor (analysis_predictor.cc:1387)."""
    return Predictor(config)


def get_version() -> str:
    from .. import __version__
    return __version__


PrecisionType = type("PrecisionType", (), {"Float32": 0, "Half": 1, "Int8": 2,
                                           "Bfloat16": 3})
PlaceType = type("PlaceType", (), {"CPU": 0, "GPU": 1, "XPU": 2, "CUSTOM": 3})


# ---- surface completion (reference: paddle/inference/__init__.py) ----

class DataType:
    """reference: paddle_infer.DataType enum."""
    FLOAT32 = "float32"
    FLOAT16 = "float16"
    INT32 = "int32"
    INT64 = "int64"
    UINT8 = "uint8"
    INT8 = "int8"
    BOOL = "bool"


def get_num_bytes_of_data_type(dtype) -> int:
    import numpy as np
    return int(np.dtype(str(dtype).replace("DataType.", "").lower()).itemsize)


class PredictorPool:
    """reference: paddle_infer.PredictorPool — N predictors sharing one
    loaded artifact (clone() shares weights here)."""

    def __init__(self, config, size: int = 1):
        first = create_predictor(config)
        self._preds = [first] + [first.clone() for _ in range(size - 1)]

    def retrive(self, idx: int):  # reference spells it 'retrive'
        return self._preds[idx]

    retrieve = retrive


def convert_to_mixed_precision(model_file, params_file, mixed_model_file,
                               mixed_params_file, mixed_precision="bfloat16",
                               backend=None, keep_io_types=True,
                               black_list=None, **kwargs):
    """reference: convert_to_mixed_precision — rewrite a saved artifact's
    params to a lower precision (bf16-native here; the XLA artifact recompiles
    at load with the narrow dtype)."""
    import numpy as np
    from ..framework.io import load as _load, save as _save
    state = _load(params_file)
    dt = np.dtype("bfloat16" if mixed_precision in ("bfloat16", "bf16")
                  else mixed_precision)
    try:
        import ml_dtypes  # numpy bf16 support ships with jax
        if dt == np.dtype("bfloat16"):
            dt = ml_dtypes.bfloat16
    except ImportError:
        pass
    black = set(black_list or ())
    out = {}
    for k, v in state.items():
        arr = np.asarray(v)
        if k not in black and arr.dtype in (np.float32, np.float64):
            arr = arr.astype(dt)
        out[k] = arr
    import shutil
    if model_file != mixed_model_file:
        shutil.copy(model_file, mixed_model_file)
    _save(out, mixed_params_file)


def get_trt_compile_version():
    """No TensorRT in the TPU stack (XLA owns codegen; SURVEY §2.4
    N/A-by-design row)."""
    return (0, 0, 0)


def get_trt_runtime_version():
    return (0, 0, 0)


def _get_phi_kernel_name(op_name: str) -> str:
    """reference: internal helper mapping fluid op names to phi kernels;
    here ops ARE their kernel (one XLA lowering per op)."""
    return op_name


# ---- request-level serving (exceeds reference: the reference snapshot has
# no serving engine — fused_multi_transformer is driven by external
# frontends; see inference/serving.py) ----
from .serving import (ServingEngine, ServingConfig, ServingMetrics,  # noqa: E402,F401
                      Request, RequestTrace, synthetic_traffic,
                      shared_prefix_traffic, repeated_traffic,
                      model_draft_fn)
from .kv_cache import BlockPool, HostSpillTier  # noqa: E402,F401
from .prefix_cache import PrefixCache  # noqa: E402,F401
from .fleet import (ReplicaHandle, ReplicaRegistry, FleetRouter,  # noqa: E402,F401
                    FleetRequest, AutoscaleController)
