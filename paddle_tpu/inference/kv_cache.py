"""paddle_tpu.inference.kv_cache — block-paged KV-cache pool for serving.

The static serving stack (generate_static_ragged / prefill_static +
decode_static) right-pads every ragged prompt to a fixed cap and reserves a
full [B, max_len] KV slab per batch slot, so mixed-length traffic holds HBM
hostage for padding and a finished row's slot stays pinned until the whole
micro-batch drains. The TPU-idiomatic fix (Ragged Paged Attention,
arxiv 2604.15464; PAPERS.md serving studies) is a BLOCK pool:

  * device state is ONE fixed-shape tensor per layer —
    ``[num_blocks, block_size, num_heads, head_dim]`` — plus an int32 block
    table ``[B, max_blocks]`` and a length vector ``[B]``. Every shape is
    pinned, so a single compiled executable serves ANY mix of request
    lengths (the whole point: zero steady-state recompiles);
  * a request owns ``ceil(tokens / block_size)`` blocks, scattered anywhere
    in the pool — blocks free the moment the request finishes, and a queued
    request is spliced into the vacated batch slot mid-flight.

``BlockPool`` is the HOST-side allocator: free-list bookkeeping, per-owner
block lists, occupancy accounting. The device pool arrays it creates are
handed to the caller (ServingEngine / prefill_paged), which threads them
through jitted steps with the buffers DONATED — XLA updates the pool in
place instead of round-tripping a copy.

Block 0 is reserved as the TRASH block: block-table padding entries and
masked writes (right-padded prompt garbage, post-EOS decode steps of a
fixed-shape chunk) all land there, so scatter updates never need a mask and
can never corrupt another request's blocks. Usable capacity is therefore
``(num_blocks - 1) * block_size`` tokens.

Blocks are REFCOUNTED (ISSUE 10): the prefix cache maps one physical
block into many requests' tables (``alloc(..., shared=...)``) and holds
its own reference on cached blocks (:meth:`retain`); a block returns to
the free list only when its last reference drops (:meth:`free` /
:meth:`release`). The trash block is never issued, never shared, never
counted. ``cache_dtype="int8"`` pools carry int8 code payloads plus
per-(block-row, head) f32 factored scales — same quantization scheme as
the static int8 KV path (ops.attention.quantize_kv), so the pool holds
~2x the resident tokens for the same HBM.
"""
from __future__ import annotations

import math
from typing import Dict, List, Optional

import numpy as np


class BlockPool:
    """Fixed-size KV block allocator (host bookkeeping + device pools).

    Parameters
    ----------
    num_blocks : total blocks in the pool, INCLUDING the reserved trash
        block 0 (usable capacity is ``(num_blocks - 1) * block_size``).
    block_size : KV rows (token positions) per block.
    num_layers / num_heads / head_dim / dtype : pool tensor geometry —
        normally taken from the model via :meth:`for_model`.
    cache_dtype : None = pools carry the model dtype; "int8" = pools are
        (codes int8, scale f32) pairs with per-(row, head) factored
        scales (the static int8-KV trick ported to the paged pool).
    """

    def __init__(self, *, num_blocks: int, block_size: int,
                 num_layers: int, num_heads: int, head_dim: int,
                 dtype="float32", cache_dtype=None):
        if num_blocks < 2:
            raise ValueError("num_blocks must be >= 2 (block 0 is the "
                             "reserved trash block)")
        if block_size < 1:
            raise ValueError("block_size must be >= 1")
        if cache_dtype not in (None, "int8"):
            raise ValueError(f"cache_dtype must be None or 'int8'; "
                             f"got {cache_dtype!r}")
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        self.num_layers = int(num_layers)
        self.num_heads = int(num_heads)
        self.head_dim = int(head_dim)
        self.dtype = dtype
        self.cache_dtype = cache_dtype
        # LIFO free list: recently freed blocks are re-issued first, which
        # keeps the hot working set of pool pages small
        self._free: List[int] = list(range(self.num_blocks - 1, 0, -1))
        self._rows: Dict[int, List[int]] = {}
        self._refs: Dict[int, int] = {}     # block id -> reference count
        # observer poked after every occupancy change (alloc/free/take/
        # release/reset) — the MemoryLedger's per-owner delta stream rides
        # this; must stay host-side and cheap, it sits on the alloc path
        self.on_change = None

    @classmethod
    def for_model(cls, model, *, num_blocks: int, block_size: int,
                  cache_dtype=None):
        """Geometry from a GPTForCausalLM-style model (config + dtype)."""
        cfg = model.config
        dtype = model.gpt.wte.weight._data.dtype
        return cls(num_blocks=num_blocks, block_size=block_size,
                   num_layers=cfg.num_layers, num_heads=cfg.num_heads,
                   head_dim=cfg.head_dim, dtype=dtype,
                   cache_dtype=cache_dtype)

    def make_pools(self):
        """Fresh zeroed device pools. Per layer: ``(k_pool, v_pool)``
        each ``[num_blocks, block_size, num_heads, head_dim]`` — or, for
        ``cache_dtype="int8"``, ``(k_codes, k_scale, v_codes, v_scale)``
        with int8 ``[NB, bs, H, D]`` codes and f32 ``[NB, bs, H]``
        factored scales. The caller owns them from here — jitted steps
        donate and replace them, so the allocator deliberately does NOT
        keep a reference.

        Under an active mesh with an ``mp`` axis (multi-chip serving,
        ISSUE 16) the pools come up HEAD-SHARDED: ``[NB, bs, H, D]``
        with H split over mp (int8 scale pools ``[NB, bs, H]`` shard the
        same axis, so codes and their scales always live on the same
        shard). Block tables, the free list, refcounts, and every other
        allocator structure stay host-side and replicated — sharding is
        purely a device-placement property of the arrays."""
        import jax.numpy as jnp
        from ..distributed import mesh as _mesh
        mp = _mesh.mesh_axis_size("mp")
        if mp > 1 and self.num_heads % mp != 0:
            raise ValueError(
                f"head-sharded pools need num_heads divisible by the mp "
                f"axis; got num_heads={self.num_heads}, mp={mp}")
        pool_sh = _mesh.named_sharding(None, None, "mp", None)
        scale_sh = _mesh.named_sharding(None, None, "mp")

        def _zeros(shape, dtype, sh):
            z = jnp.zeros(shape, dtype)
            if sh is not None:
                import jax
                z = jax.device_put(z, sh)
            return z

        shape = (self.num_blocks, self.block_size,
                 self.num_heads, self.head_dim)
        if self.cache_dtype == "int8":
            sshape = shape[:3]
            return [(_zeros(shape, jnp.int8, pool_sh),
                     _zeros(sshape, jnp.float32, scale_sh),
                     _zeros(shape, jnp.int8, pool_sh),
                     _zeros(sshape, jnp.float32, scale_sh))
                    for _ in range(self.num_layers)]
        return [(_zeros(shape, self.dtype, pool_sh),
                 _zeros(shape, self.dtype, pool_sh))
                for _ in range(self.num_layers)]

    # ------------------------------------------------------------- sizing
    def blocks_needed(self, tokens: int) -> int:
        return max(0, math.ceil(int(tokens) / self.block_size))

    @property
    def capacity_blocks(self) -> int:
        """Allocatable blocks (trash block excluded)."""
        return self.num_blocks - 1

    @property
    def capacity_tokens(self) -> int:
        return self.capacity_blocks * self.block_size

    @property
    def bytes_per_block(self) -> int:
        """HBM bytes ONE block pins across every layer's K+V pools — the
        unit the prefix cache's byte budget is charged in."""
        import numpy as np_
        rows = self.block_size * self.num_heads
        if self.cache_dtype == "int8":
            per = rows * self.head_dim * 1 + rows * 4    # codes + f32 scale
        else:
            per = rows * self.head_dim * np_.dtype(self.dtype).itemsize
        return 2 * per * self.num_layers                 # K and V

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    @property
    def used_blocks(self) -> int:
        return self.capacity_blocks - len(self._free)

    def fits_ever(self, tokens: int) -> bool:
        """Could a request needing `tokens` KV rows EVER be served by this
        pool (i.e. with every other request drained)? False means reject —
        waiting in the queue would never help."""
        return self.blocks_needed(tokens) <= self.capacity_blocks

    # --------------------------------------------------------- alloc/free
    def alloc(self, owner: int, tokens: int,
              shared=None) -> Optional[np.ndarray]:
        """Reserve blocks covering `tokens` KV rows for `owner`.

        `shared` (prefix cache, ISSUE 10) maps already-populated blocks —
        in PREFIX ORDER — into the reservation instead of allocating
        fresh ones: each gains a reference, and only
        ``blocks_needed(tokens) - len(shared)`` fresh blocks come off the
        free list, appended after the shared run (so the returned vector
        is the request's block-table row in position order).

        Returns the block-id vector (int32) on success, None when the pool
        has too few FREE blocks right now (the caller decides whether to
        wait or reject — see `fits_ever` for the never-fits case). An owner
        can hold only one reservation; double-alloc raises."""
        if owner in self._rows:
            raise ValueError(f"owner {owner} already holds "
                             f"{len(self._rows[owner])} blocks; free first")
        shared = [int(b) for b in (shared or ())]
        if any(b == 0 for b in shared):
            raise ValueError("the trash block (0) is never shared")
        n = self.blocks_needed(tokens) - len(shared)
        if n < 0:
            raise ValueError(f"shared prefix ({len(shared)} blocks) longer "
                             f"than the reservation ({tokens} tokens)")
        if n > len(self._free):
            return None
        for b in shared:
            if self._refs.get(b, 0) < 1:
                raise ValueError(f"block {b} is not live; cannot share")
            self._refs[b] += 1
        fresh = [self._free.pop() for _ in range(n)]
        for b in fresh:
            self._refs[b] = 1
        blocks = shared + fresh
        self._rows[owner] = blocks
        self._notify()
        return np.asarray(blocks, dtype=np.int32)  # lint: allow(tracer-asarray)

    def free(self, owner: int) -> int:
        """Drop `owner`'s reference on every block it holds; returns how
        many actually RETURNED to the free list (a block another owner or
        the prefix cache still references stays resident). Freeing an
        unknown owner is a no-op (0) — finish paths may race a reject."""
        blocks = self._rows.pop(owner, None)
        if not blocks:
            return 0
        freed = self._deref(reversed(blocks))
        self._notify()
        return freed

    def take(self, n: int = 1) -> Optional[List[int]]:
        """Reserve `n` OWNERLESS blocks at refcount 1 — the rehydrate
        path's allocation (ISSUE 14): a spilled prefix block coming back
        from host RAM belongs to the cache, not to any request, exactly
        like a retained block whose computing owner already finished.
        Balanced by :meth:`release`. Returns the block ids, or None when
        the free list is short (the caller evicts/reclaims and retries
        or drops the rehydrate)."""
        if n < 1 or n > len(self._free):
            return None
        out = []
        for _ in range(n):
            b = self._free.pop()
            self._refs[b] = 1
            out.append(b)
        self._notify()
        return out

    # ------------------------------------------------- cache references
    def retain(self, blocks) -> None:
        """Add one reference per block — how the prefix cache pins a
        cached prefix independent of the request that computed it."""
        for b in blocks:
            b = int(b)
            if b == 0:
                raise ValueError("the trash block (0) is never retained")
            if self._refs.get(b, 0) < 1:
                raise ValueError(f"block {b} is not live; cannot retain")
            self._refs[b] += 1

    def release(self, blocks) -> int:
        """Drop one reference per block (cache eviction path); returns
        how many hit zero and went back to the free list."""
        freed = self._deref(int(b) for b in blocks)
        self._notify()
        return freed

    def refcount(self, block: int) -> int:
        return self._refs.get(int(block), 0)

    def _deref(self, blocks) -> int:
        freed = 0
        for b in blocks:
            b = int(b)
            r = self._refs.get(b, 0)
            if r < 1:
                raise ValueError(f"refcount underflow on block {b}")
            if r == 1:
                del self._refs[b]
                self._free.append(b)
                freed += 1
            else:
                self._refs[b] = r - 1
        return freed

    def owned(self, owner: int) -> List[int]:
        return list(self._rows.get(owner, ()))

    def table_row(self, owner: int, width: int) -> np.ndarray:
        """The owner's int32 block-table row, zero-padded (trash block) to
        `width` entries — the fixed-shape row a [B, max_blocks] device
        table carries per batch slot."""
        blocks = self._rows.get(owner, ())
        if len(blocks) > width:
            raise ValueError(f"owner {owner} holds {len(blocks)} blocks "
                             f"> table width {width}")
        row = np.zeros((width,), dtype=np.int32)
        row[:len(blocks)] = blocks
        return row

    # --------------------------------------------------------- accounting
    def occupancy(self, live_tokens: int) -> float:
        """TRUE-token occupancy: live (attended) KV rows over pooled
        capacity. This is the gauge that proves paging — padded-slot
        accounting can't go above the padding ratio."""
        return live_tokens / max(self.capacity_tokens, 1)

    def slots_occupancy(self) -> float:
        """Block-granular occupancy: allocated blocks over capacity (the
        continuity analog of the old padded-slot gauge — includes
        within-block padding and worst-case reservations)."""
        return self.used_blocks / max(self.capacity_blocks, 1)

    def reset(self):
        self._free = list(range(self.num_blocks - 1, 0, -1))
        self._rows.clear()
        self._refs.clear()
        self._notify()

    def _notify(self):
        cb = self.on_change
        if cb is not None:
            try:
                cb()
            except Exception:   # noqa: BLE001 — an observability observer
                pass            # must never take the allocator down

    # ------------------------------------------- spill payloads (ISSUE 14)
    def _spill_sig(self) -> tuple:
        from ..distributed import mesh as _mesh
        return ("spill_scatter", self.num_blocks, self.block_size,
                self.num_layers, self.num_heads, self.head_dim,
                str(self.dtype), self.cache_dtype,
                _mesh.mesh_axis_size("mp"))

    def read_block(self, pools, block: int) -> tuple:
        """ONE block's payload gathered to host — the spill tier's
        device→host serialization. Every layer's planes for `block` are
        stacked device-side into one array per storage dtype (f32 pools:
        one [2L, bs, H, D] stack; int8 pools: an int8 code stack plus an
        f32 scale stack) and fetched in a single `jax.device_get` call,
        so a spill costs one transfer per payload array, not one per
        layer. Returns the tuple of host ndarrays `write_block` takes
        back verbatim — the round trip is bit-identical by construction
        (same bytes, no recompute).

        SHARD CONSISTENCY (ISSUE 16): on head-sharded pools the
        `device_get` GATHERS across the mp shards, so the host payload
        is always the full-width ``[2L, bs, H, D]`` array regardless of
        shard count — a block spilled by an mp=4 engine rehydrates
        bit-identically into an mp=1 (or mp=2) pool and vice versa. The
        fleet spill tier's codec is therefore shard-count-independent by
        construction (gather-on-spill / reshard-on-rehydrate)."""
        import jax
        import jax.numpy as jnp
        if self.cache_dtype == "int8":
            codes = jnp.stack([layer[i][block] for layer in pools
                               for i in (0, 2)])
            scales = jnp.stack([layer[i][block] for layer in pools
                                for i in (1, 3)])
            return tuple(jax.device_get((codes, scales)))  # lint: allow(device-get)
        planes = jnp.stack([p[block] for layer in pools for p in layer])
        return (jax.device_get(planes),)  # lint: allow(device-get)

    def write_block(self, pools, block: int, payload: tuple):
        """Scatter one spilled payload back into pool position `block` —
        the REHYDRATE path: one host→device copy per payload array (the
        stacked planes ship as a single jit input), one donated in-place
        executable shared by every pool of this geometry. The block id
        is a data input, so rehydrating any block reuses the same
        compiled program. Returns the replaced pools (the old ones are
        donated/consumed).

        On head-sharded pools the full-width host payload enters as a
        replicated jit input and the scatter RE-SHARDS it: the updated
        pool keeps the operand's head-sharding (each shard writes only
        its own H-slice of the payload), so rehydration never moves pool
        bytes across shards. The executable cache key includes the mp
        axis size — engines at different shard counts never share a
        scatter program."""
        import jax
        sig = self._spill_sig()
        fn = _SPILL_SCATTER_CACHE.get(sig)
        if fn is None:
            from ..jit.api import _note_cache_miss
            _note_cache_miss()     # a new serving executable, counted
            # exactly like the models' compiled-runner builds
            if self.cache_dtype == "int8":
                def run(pools, blk, codes, scales):
                    return [(kc.at[blk].set(codes[2 * i]),
                             ks.at[blk].set(scales[2 * i]),
                             vc.at[blk].set(codes[2 * i + 1]),
                             vs.at[blk].set(scales[2 * i + 1]))
                            for i, (kc, ks, vc, vs) in enumerate(pools)]
            else:
                def run(pools, blk, planes):
                    return [(k.at[blk].set(planes[2 * i]),
                             v.at[blk].set(planes[2 * i + 1]))
                            for i, (k, v) in enumerate(pools)]
            fn = _SPILL_SCATTER_CACHE[sig] = jax.jit(
                run, donate_argnums=(0,))
        return fn(pools, np.int32(block), *payload)

    def __repr__(self):
        return (f"BlockPool(blocks={self.num_blocks}x{self.block_size}, "
                f"free={self.free_blocks}/{self.capacity_blocks}, "
                f"owners={len(self._rows)})")


# one scatter executable per pool geometry, shared across engines (all
# replicas of one model share shapes, so one compile serves the fleet)
_SPILL_SCATTER_CACHE: Dict[tuple, object] = {}


class HostSpillTier:
    """Host-RAM budget + stats for spilled prefix blocks (ISSUE 14).

    The PrefixCache owns the trie-side mechanics (which node spills,
    where payloads live, LRU ordering); this class is the ACCOUNTING the
    capacity model and the metrics surface need: a byte budget charged
    at ``bytes_per_block`` per spilled block (the host copy carries the
    same payload bytes as the device block), occupancy, and the
    spill/rehydrate/drop/copy counters the smoke tests pin. Cached-
    prefix capacity becomes host-memory-sized instead of HBM-sized: an
    LRU-evicted full block serializes here instead of vanishing, and a
    later trie hit rehydrates it with one host→device copy — orders
    cheaper than recomputing its prefill."""

    def __init__(self, *, bytes_per_block: int, byte_budget: int):
        if byte_budget < bytes_per_block:
            raise ValueError(
                f"spill byte_budget {byte_budget} holds zero blocks "
                f"(one block = {bytes_per_block} bytes)")
        self.bytes_per_block = int(bytes_per_block)
        self.byte_budget = int(byte_budget)
        self.spilled_blocks = 0       # resident in the tier right now
        self.spilled_total = 0        # blocks ever serialized to host
        self.rehydrated_total = 0     # blocks copied back to device
        self.dropped_total = 0        # tier-LRU final deaths (payload
        #                               discarded for good)
        self.upgraded_total = 0       # spilled entries replaced in
        #                               place by a recomputed device
        #                               block (prefix survives — NOT a
        #                               drop)
        self.d2h_copies = 0           # host arrays fetched (spill side)
        self.h2d_copies = 0           # host arrays shipped (rehydrate)

    @property
    def capacity_blocks(self) -> int:
        return self.byte_budget // self.bytes_per_block

    @property
    def host_bytes(self) -> int:
        return self.spilled_blocks * self.bytes_per_block

    @property
    def over_budget_blocks(self) -> int:
        """Blocks the tier must drop to get back under budget."""
        return max(0, self.spilled_blocks - self.capacity_blocks)

    def stats(self) -> dict:
        return {"spilled_blocks": self.spilled_blocks,
                "host_bytes": self.host_bytes,
                "byte_budget": self.byte_budget,
                "spilled_total": self.spilled_total,
                "rehydrated_total": self.rehydrated_total,
                "dropped_total": self.dropped_total,
                "upgraded_total": self.upgraded_total,
                "d2h_copies": self.d2h_copies,
                "h2d_copies": self.h2d_copies}

    def metrics_text(self, prefix: str = "paddle_tpu_spill") -> str:
        """Prometheus exposition of the tier — registered beside the
        serving producers in `ServingEngine.metrics_registry()`."""
        from ..profiler._metrics import counter_lines, gauge_lines
        lines: List[str] = []
        for name, help_ in (
                ("spilled", "prefix blocks serialized to host RAM"),
                ("rehydrated", "spilled blocks copied back to device"),
                ("dropped", "spilled blocks evicted from the host tier "
                            "(payload lost for good)"),
                ("upgraded", "spilled entries replaced in place by a "
                             "recomputed device block"),
                ("d2h_copies", "device->host payload arrays (spill)"),
                ("h2d_copies", "host->device payload arrays (rehydrate)")):
            attr = name if name.endswith("copies") else f"{name}_total"
            lines.extend(counter_lines(prefix, f"{name}_total",
                                       getattr(self, attr), help_))
        lines.extend(gauge_lines(prefix, "host_blocks",
                                 self.spilled_blocks,
                                 "spilled blocks resident in host RAM"))
        lines.extend(gauge_lines(prefix, "host_bytes", self.host_bytes,
                                 "host RAM the spill tier pins"))
        return "\n".join(lines) + "\n"

    def __repr__(self):
        return (f"HostSpillTier(blocks={self.spilled_blocks}/"
                f"{self.capacity_blocks}, bytes={self.host_bytes}/"
                f"{self.byte_budget})")
