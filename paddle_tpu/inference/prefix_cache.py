"""paddle_tpu.inference.prefix_cache — radix-trie prefix cache over the
paged KV block pool (ISSUE 10).

Production traffic is millions of users hitting a handful of system
prompts; the paged serving stack (kv_cache.BlockPool + ServingEngine)
re-ran full prefill for every request anyway. This module caches the KV
of already-computed token prefixes AT BLOCK GRANULARITY and lets
admission map them straight into a new request's block table:

  radix trie    one node per FULL block of tokens, keyed by the block's
                token tuple — so matching a prompt is a walk of
                ``len(prompt) // block_size`` dict lookups, and two
                prompts sharing 3 system-prompt blocks share 3 trie nodes
                (and 3 physical pool blocks).
  alignment     only FULL blocks are cached/shared. A partially filled
                block keeps taking decode writes from its owner, so it is
                never safe to map into another request; the suffix past
                the matched blocks is prefilled (or, when it is just the
                final prompt token, re-decoded) privately.
  refcounts     the cache RETAINS every block it caches (BlockPool
                refcounts); a request mapping a cached block adds its own
                reference. A cached block whose refcount is 1 (cache-only)
                is reclaimable; one a live request maps is not.
  copy-on-write the engine copies the LAST matched block into a private
                block when a full-hit request must write into it (the
                re-decode of the final prompt token lands at position
                ``plen - 1``, inside that block) — shared blocks are
                never mutated, asserted by checksum in tests.
  eviction      LRU over reclaimable leaves, cascading up the trie, under
                an optional byte budget (``bytes_per_block`` per node) —
                and on demand when admission runs out of free blocks
                (``reclaim``): cached-but-idle prefixes are soft capacity.

The trie stores HOST data only (block ids + token keys); pool payloads
stay on device and are never read back — EXCEPT through the optional
host-RAM SPILL TIER (ISSUE 14, :meth:`PrefixCache.attach_spill`): with a
``kv_cache.HostSpillTier`` attached, an LRU-evicted full block
serializes its device payload to a pinned host array instead of
vanishing (``node.block = SPILLED``, payload parked on the node), and a
later trie hit REHYDRATES it — one ownerless pool block
(``BlockPool.take``), one host→device copy of the stacked payload —
orders cheaper than recomputing its prefill, refcount- and COW-safe
(the rehydrated block is a normal cache-referenced block by the time
admission maps it), and bit-identical to recompute (the round trip
moves bytes, never recomputes them). Cached-prefix capacity becomes
host-memory-sized instead of HBM-sized; the tier's own byte budget
drops LRU spilled leaves for good when host RAM runs out. Invariant: a
spilled node's descendants are all spilled (spill cascades deepest-
first, rehydrate/upgrade walk root-down), so the tier's LRU always
finds a childless spilled leaf to drop.

Content correctness rests on determinism: K/V rows at a position are a
pure function of the token prefix and the weights, so any block reached
by the same token path holds bit-identical payloads — insert can
therefore keep the FIRST block cached under a key and drop later
duplicates without comparing device bytes (and an insert that passes a
spilled node upgrades it in place with the freshly recomputed block).

MULTI-CHIP (ISSUE 16, ``ServingConfig(shards=N)``): the trie is a
host-side control-plane structure, so head-sharding the device pools
changes NOTHING here — block ids, token keys, refcounts and LRU state
stay replicated host facts. The two places sharding touches are both
downstream contracts this module relies on: the engine's COW copy is
shard-local by construction (source gather and target scatter carry the
same head sharding — zero collectives, gated by ``serving_comm_plan(0)``
in the graph_lint sharded target), and the spill tier's
``read_block``/``write_block`` codec is shard-CONSISTENT (read gathers
ONE full-width host payload whatever the shard count, write reshards on
rehydrate — see kv_cache), so a node spilled under one shard count
rehydrates under another.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

# node.block sentinel: the payload lives in the host spill tier, not in
# any pool block (real ids are >= 1; 0 is the pool's trash block)
SPILLED = -1


class _Node:
    """One cached full block: token key, pool block id (or SPILLED),
    LRU stamp, and — while spilled — the host payload."""
    __slots__ = ("key", "block", "parent", "children", "last_used",
                 "payload")

    def __init__(self, key, block, parent):
        self.key = key                       # tuple of block_size token ids
        self.block = block                   # pool block id (never 0)
        self.parent = parent                 # _Node or the root
        self.children: Dict[tuple, "_Node"] = {}
        self.last_used = 0
        self.payload = None                  # host arrays while spilled


class PrefixCache:
    """Radix trie of cached token prefixes over one :class:`BlockPool`.

    The cache does NOT own the device pools — it holds references on pool
    blocks (``pool.retain``) and releases them on eviction. All methods
    are host-side and O(prompt blocks) except eviction scans, which are
    O(cached blocks) and only run on insert-over-budget / reclaim."""

    def __init__(self, pool, *, byte_budget: Optional[int] = None):
        if byte_budget is not None and byte_budget < pool.bytes_per_block:
            raise ValueError(
                f"byte_budget {byte_budget} holds zero blocks "
                f"(one block = {pool.bytes_per_block} bytes)")
        self.pool = pool
        self.byte_budget = byte_budget
        self._root = _Node(key=None, block=0, parent=None)
        self._count = 0                     # device-cached blocks (nodes)
        self._spilled = 0                   # host-spilled nodes
        self._tick = 0                      # monotonic LRU clock
        self.inserted_total = 0
        self.evicted_total = 0
        # host spill tier (ISSUE 14): attach_spill wires these
        self._spill = None                  # kv_cache.HostSpillTier
        self._read = None                   # reader(block) -> payload
        self._write = None                  # writer(block, payload)
        self._rehydrating = None            # node mid-rehydrate: the
        #                                     tier's own LRU must not
        #                                     drop it (its eviction path
        #                                     can run INSIDE _rehydrate)

    def attach_spill(self, tier, *, reader, writer) -> "PrefixCache":
        """Wire the host-RAM spill tier: ``reader(block) -> payload``
        serializes one device block (the engine's ``pool.read_block``
        over its live pools), ``writer(block, payload)`` scatters a
        payload into a fresh device block AND re-binds the engine's
        donated pools — both are closures over the engine because the
        cache deliberately never holds the device arrays."""
        self._spill = tier
        self._read = reader
        self._write = writer
        return self

    # ------------------------------------------------------------ stats
    @property
    def cached_blocks(self) -> int:
        return self._count

    @property
    def spilled_blocks(self) -> int:
        return self._spilled

    @property
    def cached_bytes(self) -> int:
        return self._count * self.pool.bytes_per_block

    # ------------------------------------------------------------ match
    def _key(self, tokens, i: int) -> tuple:
        bs = self.pool.block_size
        return tuple(int(t) for t in tokens[i * bs:(i + 1) * bs])

    def match(self, tokens) -> Tuple[List[int], int]:
        """Longest cached full-block-aligned prefix of `tokens`.

        Returns ``(block_ids, matched_tokens)`` — block ids in prefix
        order, ``matched_tokens = len(block_ids) * block_size``. Stamps
        the matched chain's LRU clock (a hit is a use). A SPILLED node
        on the walk is rehydrated in place (one fresh pool block, one
        host→device copy) before its id joins the match; when no pool
        block can be found even after evicting, the walk stops there —
        the request simply prefills the rest, and its insert upgrades
        the spilled node with the recomputed block."""
        self._tick += 1
        node = self._root
        blocks: List[int] = []
        for i in range(int(len(tokens)) // self.pool.block_size):
            child = node.children.get(self._key(tokens, i))
            if child is None:
                break
            if child.block == SPILLED and not self._rehydrate(child,
                                                              blocks):
                break
            child.last_used = self._tick
            blocks.append(child.block)
            node = child
        return blocks, len(blocks) * self.pool.block_size

    def _rehydrate(self, node: _Node, protect) -> bool:
        """Bring one spilled node back on device: take an ownerless pool
        block (evicting/spilling a colder one if the free list is dry,
        sparing the `protect` run this match already claimed), scatter
        the host payload into it (ONE host→device copy — the writer's
        stacked-payload executable), and make the node a normal
        device-cached entry again."""
        # the eviction below may spill another block, whose _trim_spill
        # scans LRU spilled leaves — this very node is one (stale stamp,
        # childless) and must survive until its payload is written back
        self._rehydrating = node
        try:
            got = self.pool.take(1)
            if got is None and self.evict(1, protect=protect):
                got = self.pool.take(1)
        finally:
            self._rehydrating = None
        if got is None:
            return False
        blk = got[0]
        self._write(blk, node.payload)
        t = self._spill
        t.h2d_copies += len(node.payload)
        t.rehydrated_total += 1
        t.spilled_blocks -= 1
        node.block = blk
        node.payload = None
        self._spilled -= 1
        self._count += 1
        return True

    def lookup_continuation(self, tokens, n: int):
        """Prompt-lookup drafting (ISSUE 11): the next up-to-``n`` tokens
        the trie remembers AFTER the prefix ``tokens``.

        Walks the full blocks of ``tokens`` exactly like :meth:`match`,
        then follows children whose keys extend the partial tail — a
        matched node's cached token key IS the continuation, so repeated
        / agentic traffic (identical prompts, retries, multi-turn
        histories) drafts its own future from what earlier requests
        already computed, with no draft model at all. Returns a list of
        ints (possibly empty; shorter than ``n`` when the cached path
        runs out). Read-only: does NOT stamp the LRU clock — peeking for
        a draft must not pin a prefix resident the way serving KV from
        it does. When several cached paths extend the same tail the
        first child wins (dict insertion order — deterministic within a
        process); a wrong guess costs one rejected draft token, nothing
        more."""
        bs = self.pool.block_size
        node = self._root
        n_full = int(len(tokens)) // bs
        for i in range(n_full):
            child = node.children.get(self._key(tokens, i))
            if child is None:
                return []             # history diverged from every cache
            node = child
        tail = tuple(int(t) for t in tokens[n_full * bs:])
        out: List[int] = []
        while len(out) < n:
            nxt = None
            for key, child in node.children.items():
                if key[:len(tail)] == tail:
                    out.extend(key[len(tail):])
                    nxt = child
                    break
            if nxt is None:
                break
            node, tail = nxt, ()
        return out[:n]

    # ----------------------------------------------------------- insert
    def insert(self, tokens, blocks) -> int:
        """Cache the full-block prefix of `tokens`, whose K/V already
        lives in `blocks` (the owning request's table, prefix order).

        Existing nodes are kept as-is (same token path = bit-identical
        payload — see module docstring) and only stamped; each NEW node
        retains its block in the pool. Returns how many blocks were newly
        cached; evicts LRU reclaimable entries past the byte budget."""
        self._tick += 1
        node = self._root
        n = min(int(len(tokens)) // self.pool.block_size, len(blocks))
        added = 0
        for i in range(n):
            key = self._key(tokens, i)
            child = node.children.get(key)
            if child is None:
                blk = int(blocks[i])
                if blk == 0:
                    break                   # trash is never cached
                self.pool.retain([blk])
                child = _Node(key=key, block=blk, parent=node)
                node.children[key] = child
                self._count += 1
                added += 1
            elif child.block == SPILLED:
                # the inserting request RECOMPUTED this block's KV (its
                # match stopped short of a rehydrate) — upgrade in
                # place: adopt the fresh device block, drop the host
                # payload (determinism: same token path ⇒ bit-identical
                # bytes either way)
                blk = int(blocks[i])
                if blk == 0:
                    break
                self.pool.retain([blk])
                child.block = blk
                child.payload = None
                self._spilled -= 1
                self._count += 1
                added += 1
                if self._spill is not None:
                    self._spill.spilled_blocks -= 1
                    self._spill.upgraded_total += 1
            child.last_used = self._tick
            node = child
        self.inserted_total += added
        if self.byte_budget is not None:
            self.evict_to_bytes(self.byte_budget)
        return added

    # --------------------------------------------------------- eviction
    def _reclaimable_leaves(self, protect=frozenset()) -> List[_Node]:
        out, stack = [], list(self._root.children.values())
        while stack:
            n = stack.pop()
            if n.children:
                stack.extend(n.children.values())
            elif n.block not in protect and \
                    self.pool.refcount(n.block) == 1:  # cache-only ref
                out.append(n)
        return out

    def _spill_candidates(self, protect=frozenset()) -> List[_Node]:
        """Device-resident, cache-only-referenced nodes whose children
        are ALL spilled (or absent) — the spill analog of a reclaimable
        leaf. The all-spilled condition keeps the invariant that a
        spilled node's descendants are spilled, so the tier's LRU drop
        always finds a childless victim."""
        out, stack = [], list(self._root.children.values())
        while stack:
            n = stack.pop()
            stack.extend(n.children.values())
            if (n.block != SPILLED and n.block not in protect
                    and self.pool.refcount(n.block) == 1
                    and all(c.block == SPILLED
                            for c in n.children.values())):
                out.append(n)
        return out

    def _drop(self, node: _Node) -> None:
        """Remove `node` from the trie for good: a device node releases
        its pool block; a spilled node releases its host payload (the
        tier's final-death accounting — its device eviction was already
        counted when it spilled)."""
        del node.parent.children[node.key]
        if node.block == SPILLED:
            node.payload = None
            self._spilled -= 1
            if self._spill is not None:
                self._spill.spilled_blocks -= 1
                self._spill.dropped_total += 1
        else:
            self.pool.release([node.block])
            self._count -= 1
            self.evicted_total += 1

    def _spill_node(self, node: _Node) -> None:
        """Device→host spill of one node: serialize the block's payload
        (one stacked device→host fetch), free the device block, keep the
        node in the trie as SPILLED. Trims the tier's own LRU afterwards
        so host RAM stays inside its budget."""
        payload = self._read(node.block)
        self.pool.release([node.block])
        node.block = SPILLED
        node.payload = payload
        self._count -= 1
        self._spilled += 1
        self.evicted_total += 1
        t = self._spill
        t.spilled_blocks += 1
        t.spilled_total += 1
        t.d2h_copies += len(payload)
        self._trim_spill()

    def _trim_spill(self) -> None:
        """Drop LRU childless spilled leaves until the host tier is back
        under its byte budget — the spill tier's own final eviction."""
        t = self._spill
        while t.over_budget_blocks > 0:
            leaves = []
            stack = list(self._root.children.values())
            while stack:
                n = stack.pop()
                stack.extend(n.children.values())
                if n.block == SPILLED and not n.children \
                        and n is not self._rehydrating:
                    leaves.append(n)
            if not leaves:
                break
            leaves.sort(key=lambda n: n.last_used)
            for leaf in leaves[:t.over_budget_blocks]:
                self._drop(leaf)

    def evict(self, n_blocks: int = 1, protect=()) -> int:
        """Free up to `n_blocks` DEVICE blocks from LRU reclaimable
        entries (cascading: an evicted leaf may expose its parent).
        With a spill tier attached the evicted payloads serialize to
        host RAM (the node survives as SPILLED and can rehydrate);
        without one this is the final death it always was. `protect`
        names blocks an in-flight admission has matched but not yet
        mapped — they must survive even at refcount 1. Returns how many
        blocks went back to the pool's free list."""
        protect = frozenset(int(b) for b in protect)
        spill = self._spill is not None
        freed = 0
        while freed < n_blocks:
            leaves = self._spill_candidates(protect) if spill \
                else self._reclaimable_leaves(protect)
            if not leaves:
                break
            leaves.sort(key=lambda n: n.last_used)
            for leaf in leaves:
                if freed >= n_blocks:
                    break
                self._spill_node(leaf) if spill else self._drop(leaf)
                freed += 1
                # walk up while the parent became a candidate —
                # deepest-first keeps the hot prefix roots resident
                p = leaf.parent
                while (freed < n_blocks and p is not self._root
                       and p.block != SPILLED
                       and p.block not in protect
                       and self.pool.refcount(p.block) == 1
                       and (all(c.block == SPILLED
                                for c in p.children.values())
                            if spill else not p.children)):
                    self._spill_node(p) if spill else self._drop(p)
                    freed += 1
                    p = p.parent
        return freed

    def evict_to_bytes(self, budget: int) -> int:
        """Evict LRU entries until ``cached_bytes <= budget`` (or nothing
        reclaimable remains); returns blocks freed."""
        over = self.cached_bytes - budget
        if over <= 0:
            return 0
        need = -(-over // self.pool.bytes_per_block)
        return self.evict(need)

    def reclaim(self, n_blocks: int, protect=()) -> bool:
        """Admission pressure valve: evict until the pool has `n_blocks`
        free (cached-but-idle prefixes are soft capacity), sparing the
        `protect` blocks the admission is about to map. Returns True
        when the pool can now serve the allocation."""
        short = n_blocks - self.pool.free_blocks
        if short > 0:
            self.evict(short, protect=protect)
        return self.pool.free_blocks >= n_blocks

    def clear(self, release: bool = True) -> int:
        """Drop every cached entry — device AND spilled. ``release=
        False`` skips the pool deref — for recovery after
        ``pool.reset()`` already wiped the refcounts (the engine's
        exception path); spilled payloads are dropped either way."""
        dropped = device_dropped = 0
        stack = list(self._root.children.values())
        while stack:
            n = stack.pop()
            stack.extend(n.children.values())
            if n.block == SPILLED:
                # its DEVICE eviction was already counted at spill time
                n.payload = None
                if self._spill is not None:
                    self._spill.spilled_blocks -= 1
                    self._spill.dropped_total += 1
            else:
                if release:
                    self.pool.release([n.block])
                device_dropped += 1
            dropped += 1
        self._root.children.clear()
        self._count = 0
        self._spilled = 0
        self.evicted_total += device_dropped
        return dropped

    def __repr__(self):
        return (f"PrefixCache(blocks={self._count}, "
                f"spilled={self._spilled}, "
                f"bytes={self.cached_bytes}, "
                f"budget={self.byte_budget})")
