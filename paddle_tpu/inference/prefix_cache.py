"""paddle_tpu.inference.prefix_cache — radix-trie prefix cache over the
paged KV block pool (ISSUE 10).

Production traffic is millions of users hitting a handful of system
prompts; the paged serving stack (kv_cache.BlockPool + ServingEngine)
re-ran full prefill for every request anyway. This module caches the KV
of already-computed token prefixes AT BLOCK GRANULARITY and lets
admission map them straight into a new request's block table:

  radix trie    one node per FULL block of tokens, keyed by the block's
                token tuple — so matching a prompt is a walk of
                ``len(prompt) // block_size`` dict lookups, and two
                prompts sharing 3 system-prompt blocks share 3 trie nodes
                (and 3 physical pool blocks).
  alignment     only FULL blocks are cached/shared. A partially filled
                block keeps taking decode writes from its owner, so it is
                never safe to map into another request; the suffix past
                the matched blocks is prefilled (or, when it is just the
                final prompt token, re-decoded) privately.
  refcounts     the cache RETAINS every block it caches (BlockPool
                refcounts); a request mapping a cached block adds its own
                reference. A cached block whose refcount is 1 (cache-only)
                is reclaimable; one a live request maps is not.
  copy-on-write the engine copies the LAST matched block into a private
                block when a full-hit request must write into it (the
                re-decode of the final prompt token lands at position
                ``plen - 1``, inside that block) — shared blocks are
                never mutated, asserted by checksum in tests.
  eviction      LRU over reclaimable leaves, cascading up the trie, under
                an optional byte budget (``bytes_per_block`` per node) —
                and on demand when admission runs out of free blocks
                (``reclaim``): cached-but-idle prefixes are soft capacity.

The trie stores HOST data only (block ids + token keys); pool payloads
stay on device and are never read back. Content correctness rests on
determinism: K/V rows at a position are a pure function of the token
prefix and the weights, so any block reached by the same token path holds
bit-identical payloads — insert can therefore keep the FIRST block cached
under a key and drop later duplicates without comparing device bytes.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple


class _Node:
    """One cached full block: token key, pool block id, LRU stamp."""
    __slots__ = ("key", "block", "parent", "children", "last_used")

    def __init__(self, key, block, parent):
        self.key = key                       # tuple of block_size token ids
        self.block = block                   # pool block id (never 0)
        self.parent = parent                 # _Node or the root
        self.children: Dict[tuple, "_Node"] = {}
        self.last_used = 0


class PrefixCache:
    """Radix trie of cached token prefixes over one :class:`BlockPool`.

    The cache does NOT own the device pools — it holds references on pool
    blocks (``pool.retain``) and releases them on eviction. All methods
    are host-side and O(prompt blocks) except eviction scans, which are
    O(cached blocks) and only run on insert-over-budget / reclaim."""

    def __init__(self, pool, *, byte_budget: Optional[int] = None):
        if byte_budget is not None and byte_budget < pool.bytes_per_block:
            raise ValueError(
                f"byte_budget {byte_budget} holds zero blocks "
                f"(one block = {pool.bytes_per_block} bytes)")
        self.pool = pool
        self.byte_budget = byte_budget
        self._root = _Node(key=None, block=0, parent=None)
        self._count = 0                     # cached blocks (nodes)
        self._tick = 0                      # monotonic LRU clock
        self.inserted_total = 0
        self.evicted_total = 0

    # ------------------------------------------------------------ stats
    @property
    def cached_blocks(self) -> int:
        return self._count

    @property
    def cached_bytes(self) -> int:
        return self._count * self.pool.bytes_per_block

    # ------------------------------------------------------------ match
    def _key(self, tokens, i: int) -> tuple:
        bs = self.pool.block_size
        return tuple(int(t) for t in tokens[i * bs:(i + 1) * bs])

    def match(self, tokens) -> Tuple[List[int], int]:
        """Longest cached full-block-aligned prefix of `tokens`.

        Returns ``(block_ids, matched_tokens)`` — block ids in prefix
        order, ``matched_tokens = len(block_ids) * block_size``. Stamps
        the matched chain's LRU clock (a hit is a use)."""
        self._tick += 1
        node = self._root
        blocks: List[int] = []
        for i in range(int(len(tokens)) // self.pool.block_size):
            child = node.children.get(self._key(tokens, i))
            if child is None:
                break
            child.last_used = self._tick
            blocks.append(child.block)
            node = child
        return blocks, len(blocks) * self.pool.block_size

    def lookup_continuation(self, tokens, n: int):
        """Prompt-lookup drafting (ISSUE 11): the next up-to-``n`` tokens
        the trie remembers AFTER the prefix ``tokens``.

        Walks the full blocks of ``tokens`` exactly like :meth:`match`,
        then follows children whose keys extend the partial tail — a
        matched node's cached token key IS the continuation, so repeated
        / agentic traffic (identical prompts, retries, multi-turn
        histories) drafts its own future from what earlier requests
        already computed, with no draft model at all. Returns a list of
        ints (possibly empty; shorter than ``n`` when the cached path
        runs out). Read-only: does NOT stamp the LRU clock — peeking for
        a draft must not pin a prefix resident the way serving KV from
        it does. When several cached paths extend the same tail the
        first child wins (dict insertion order — deterministic within a
        process); a wrong guess costs one rejected draft token, nothing
        more."""
        bs = self.pool.block_size
        node = self._root
        n_full = int(len(tokens)) // bs
        for i in range(n_full):
            child = node.children.get(self._key(tokens, i))
            if child is None:
                return []             # history diverged from every cache
            node = child
        tail = tuple(int(t) for t in tokens[n_full * bs:])
        out: List[int] = []
        while len(out) < n:
            nxt = None
            for key, child in node.children.items():
                if key[:len(tail)] == tail:
                    out.extend(key[len(tail):])
                    nxt = child
                    break
            if nxt is None:
                break
            node, tail = nxt, ()
        return out[:n]

    # ----------------------------------------------------------- insert
    def insert(self, tokens, blocks) -> int:
        """Cache the full-block prefix of `tokens`, whose K/V already
        lives in `blocks` (the owning request's table, prefix order).

        Existing nodes are kept as-is (same token path = bit-identical
        payload — see module docstring) and only stamped; each NEW node
        retains its block in the pool. Returns how many blocks were newly
        cached; evicts LRU reclaimable entries past the byte budget."""
        self._tick += 1
        node = self._root
        n = min(int(len(tokens)) // self.pool.block_size, len(blocks))
        added = 0
        for i in range(n):
            key = self._key(tokens, i)
            child = node.children.get(key)
            if child is None:
                blk = int(blocks[i])
                if blk == 0:
                    break                   # trash is never cached
                self.pool.retain([blk])
                child = _Node(key=key, block=blk, parent=node)
                node.children[key] = child
                self._count += 1
                added += 1
            child.last_used = self._tick
            node = child
        self.inserted_total += added
        if self.byte_budget is not None:
            self.evict_to_bytes(self.byte_budget)
        return added

    # --------------------------------------------------------- eviction
    def _reclaimable_leaves(self, protect=frozenset()) -> List[_Node]:
        out, stack = [], list(self._root.children.values())
        while stack:
            n = stack.pop()
            if n.children:
                stack.extend(n.children.values())
            elif n.block not in protect and \
                    self.pool.refcount(n.block) == 1:  # cache-only ref
                out.append(n)
        return out

    def _drop(self, node: _Node) -> None:
        del node.parent.children[node.key]
        self.pool.release([node.block])
        self._count -= 1
        self.evicted_total += 1

    def evict(self, n_blocks: int = 1, protect=()) -> int:
        """Evict up to `n_blocks` LRU reclaimable leaves (cascading: an
        evicted leaf may expose its parent). `protect` names blocks an
        in-flight admission has matched but not yet mapped — they must
        survive even at refcount 1. Returns how many blocks went back to
        the pool's free list."""
        protect = frozenset(int(b) for b in protect)
        freed = 0
        while freed < n_blocks:
            leaves = self._reclaimable_leaves(protect)
            if not leaves:
                break
            leaves.sort(key=lambda n: n.last_used)
            for leaf in leaves:
                if freed >= n_blocks:
                    break
                self._drop(leaf)
                freed += 1
                # walk up while the parent became a reclaimable leaf —
                # deepest-first keeps the hot prefix roots resident
                p = leaf.parent
                while (freed < n_blocks and p is not self._root
                       and not p.children and p.block not in protect
                       and self.pool.refcount(p.block) == 1):
                    self._drop(p)
                    freed += 1
                    p = p.parent
        return freed

    def evict_to_bytes(self, budget: int) -> int:
        """Evict LRU entries until ``cached_bytes <= budget`` (or nothing
        reclaimable remains); returns blocks freed."""
        over = self.cached_bytes - budget
        if over <= 0:
            return 0
        need = -(-over // self.pool.bytes_per_block)
        return self.evict(need)

    def reclaim(self, n_blocks: int, protect=()) -> bool:
        """Admission pressure valve: evict until the pool has `n_blocks`
        free (cached-but-idle prefixes are soft capacity), sparing the
        `protect` blocks the admission is about to map. Returns True
        when the pool can now serve the allocation."""
        short = n_blocks - self.pool.free_blocks
        if short > 0:
            self.evict(short, protect=protect)
        return self.pool.free_blocks >= n_blocks

    def clear(self, release: bool = True) -> int:
        """Drop every cached entry. ``release=False`` skips the pool
        deref — for recovery after ``pool.reset()`` already wiped the
        refcounts (the engine's exception path)."""
        dropped = 0
        stack = list(self._root.children.values())
        while stack:
            n = stack.pop()
            stack.extend(n.children.values())
            if release:
                self.pool.release([n.block])
            dropped += 1
        self._root.children.clear()
        self._count = 0
        self.evicted_total += dropped
        return dropped

    def __repr__(self):
        return (f"PrefixCache(blocks={self._count}, "
                f"bytes={self.cached_bytes}, "
                f"budget={self.byte_budget})")
