"""paddle_tpu.inference.serving — an instrumented continuous-batching
engine over the static decode stack, with request-level observability as
the headline.

The training side has step metrics (profiler.StepMonitor, r7) and numerics
sentinels (debugging, r8); serving quality is judged by a DIFFERENT set of
signals — TTFT/TPOT latency distributions, queue wait, batch fill and
KV-slot utilization under load (cf. the ragged-paged-attention and
Gemma-on-TPU serving studies, PAPERS.md). This module provides:

  ServingEngine   admits per-request prompts into a bounded queue,
                  assembles FIXED-SHAPE micro-batches (right-padded ragged
                  prompts + per-row lens), and drives the model's
                  `prefill_static` / `decode_static` executables. Decode
                  runs in chunks of [1, c, c, ...]: the 1-token first
                  chunk makes time-to-first-token a measured host fact
                  (not an estimate), later chunks let a batch stop as soon
                  as every row finished. Every shape is pinned by the
                  config, so after one warmup batch the loop adds ZERO jit
                  compilations — guarded at runtime via the PR-2 cache-miss
                  counter, with a shape-delta warning through
                  `StepMonitor.record_compile` when a request would force
                  a new executable (it is rejected instead).

  RequestTrace    per-request span timestamps (enqueue → admit → prefill →
                  first token → finish); each engine phase also runs under
                  a `jax.profiler.TraceAnnotation` ("serving/prefill",
                  "serving/decode") so device traces attribute kernel time
                  to serving phases exactly like annotate_layers does for
                  modules.

  ServingMetrics  log-bucketed latency histograms (TTFT, per-output-token
                  time, end-to-end, queue wait — p50/p90/p99 derived from
                  buckets, no per-request retention), gauges (queue depth,
                  batch-fill ratio, KV-slot occupancy) and counters
                  (requests/tokens in+out/rejections/timeouts/batches),
                  rendered to Prometheus exposition text by the SAME
                  `profiler._metrics` formatter StepMonitor uses, plus one
                  JSONL record per finished request (the StepMonitor row
                  convention: a nested payload under "request" + "ts").

Greedy engine output is bit-identical to `model.generate_static_ragged`
on the same prompts (tested): padding rows to the fixed batch and chunking
the decode change nothing — attention masks make cache length and batch
company value-invariant, and chunked greedy decode replays the same
argmax chain.

`ServingConfig(paged=True)` (ISSUE 5) swaps the per-slot padded KV slabs
for a BLOCK POOL (inference/kv_cache.py + the ragged paged attention op):
each batch slot runs its own request against blocks it owns, EOS/budget
frees those blocks immediately, and `_admit_paged` splices a queued
request into the vacated slot mid-flight — prefill into fresh blocks
([1, cap], one executable), then the row simply joins the next decode
chunk. No waiting for the batch to drain, no bucket-mismatch rejection
for anything that fits the pool, and the same two guarantees hold:
greedy output bit-identical to generate_static_ragged per row, zero jit
cache misses after the {prefill, decode} pair compiles once. The pool
buffers are DONATED through every call, so XLA updates KV in place.
(Bit-identity caveat: bf16 models on TPU route through the f32-score
Pallas paged kernel while the static path stores bf16 scores, so parity
there is approximate near argmax ties — exact whenever both sides share
a numerics class: f32 models anywhere, or the CPU reference path; see
ops/pallas/paged_attention.py and tools/validate_paged_tpu.py.)

`ServingConfig(paged=True, prefix_cache=True)` (ISSUE 10) adds the
radix-trie PREFIX CACHE (inference/prefix_cache.py): admission matches
each prompt against cached full-block token prefixes, maps shared
refcounted pool blocks into the request's table, and prefills only the
uncached suffix — a full hit skips prefill entirely (the last prompt
token re-enters as the decode pending token, so TTFT is one decode
step, with copy-on-write of the last shared block when the hit is
block-aligned). `cache_dtype="int8"` now composes with paged=True: the
pools carry int8 codes + per-block factored scales (the static int8-KV
trick ported to the paged kernel), holding ~2x the resident requests.
Greedy output stays bit-identical with the cache on vs off, and the
steady loop still adds zero compilations — the suffix-prefill and COW
executables are part of the warmup set.

`ServingConfig(spec_decode=True)` (ISSUE 11) turns each decode step into
a DRAFT-VERIFY window through the ragged [B, k] multi-token
paged-attention kernel: a draft proposes `spec_k` tokens per row, the
target model scores pending + drafts in ONE fixed-shape call
(`model.verify_paged`), and the longest-accepted-prefix rule emits
1..spec_k+1 tokens per launch with greedy output BIT-IDENTICAL to the
plain chain. The default drafter is prompt-lookup from the prefix radix
trie — a matched node's cached continuation tokens ARE the draft, and
finished requests cache their generated chains too, so repeated /
agentic traffic drafts its own future with no draft model at all
(`spec_draft` also takes a callable; `model_draft_fn` adapts a tiny
GPT). Rejected-position KV writes land below the next window's start
(or in the trash block past a row's budget), so acceptance is data, not
shape: one verify executable per window size, zero steady recompiles.
`prefill_chunk=N` additionally caps per-step prefill work at [1, N]
tokens through the same start-offset executable, so a cap-length prompt
no longer monopolizes the engine for one monolithic prefill call.
"""
from __future__ import annotations

import json
import logging
import math
import time
import uuid
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

import numpy as np
import jax

from ..profiler import StepMonitor
from ..profiler.monitor import _jit_cache_misses
from ..profiler._metrics import (LogHistogram, counter_lines, gauge_lines,
                                 histogram_lines)

_logger = logging.getLogger("paddle_tpu.inference.serving")


# --------------------------------------------------------------- requests

@dataclass
class RequestTrace:
    """Span TREE of one request's life (engine clock seconds).

    enqueue → admit is queue wait; admit → prefill_done is the batched
    prefill; first_token lands after the 1-token decode chunk; finish is
    stamped at the end of the decode CHUNK in which the row hit EOS or its
    budget (every chunk ends in a host sync, so chunk granularity is free
    — a short request co-batched with long ones is not charged for decode
    chunks past its own completion).

    `trace_id` names the request across export surfaces (JSONL rows, the
    /tracez ring, logs); `events` are the engine-call WINDOWS the request
    rode, appended as (name, t0, t1) tuples — "prefill",
    "suffix_prefill", "prefill_chunk", "decode", "spec_verify" — so an
    exported trace explains WHERE a slow e2e went (ISSUE 12: one window
    per device call the row participated in; a zero-prefill cache hit
    shows no prefill window at all, which is the point). `span_tree()`
    renders the stamps + windows as one structured tree."""
    t_enqueue: Optional[float] = None
    t_admit: Optional[float] = None
    t_prefill_done: Optional[float] = None
    t_first_token: Optional[float] = None
    t_finish: Optional[float] = None
    batch_id: Optional[int] = None
    trace_id: Optional[str] = None
    events: List[tuple] = field(default_factory=list)

    @property
    def queue_s(self) -> Optional[float]:
        if self.t_admit is None or self.t_enqueue is None:
            return None
        return self.t_admit - self.t_enqueue

    @property
    def ttft_s(self) -> Optional[float]:
        if self.t_first_token is None or self.t_enqueue is None:
            return None
        return self.t_first_token - self.t_enqueue

    @property
    def e2e_s(self) -> Optional[float]:
        if self.t_finish is None or self.t_enqueue is None:
            return None
        return self.t_finish - self.t_enqueue

    def tpot_s(self, n_out: int) -> Optional[float]:
        """Per-output-token time over the post-first-token stretch."""
        if self.t_finish is None or self.t_first_token is None or n_out < 2:
            return None
        return (self.t_finish - self.t_first_token) / (n_out - 1)

    def to_dict(self) -> dict:
        d = {k: getattr(self, k) for k in
             ("t_enqueue", "t_admit", "t_prefill_done", "t_first_token",
              "t_finish", "batch_id")}
        return {k: v for k, v in d.items() if v is not None}

    def span_tree(self) -> dict:
        """The structured trace a /tracez consumer renders: the request
        root span plus its children — the derived queue span and every
        engine-call window this request rode, in time order."""
        spans = []
        if self.t_enqueue is not None and self.t_admit is not None:
            spans.append({"name": "queue", "t0": self.t_enqueue,
                          "t1": self.t_admit})
        for name, a, b in self.events:
            spans.append({"name": name, "t0": a, "t1": b})
        spans.sort(key=lambda s: s["t0"])
        return {"trace_id": self.trace_id,
                "t0": self.t_enqueue, "t1": self.t_finish,
                "spans": spans}


@dataclass(eq=False)     # holds an ndarray: identity, not value, equality
class Request:
    """One admitted (or refused) generation request."""
    id: int
    prompt: np.ndarray                      # 1-D int token ids
    max_new_tokens: int
    status: str = "queued"   # queued|active|done|rejected|timeout
    reason: Optional[str] = None            # rejection/timeout detail
    # rejection taxonomy (ISSUE 14 satellite): True = the refusal is
    # replica-local (overloaded/draining/queue_full — retry ELSEWHERE),
    # False = terminal everywhere (kv_oom never fits, shape-recompile
    # rejects) so a router cannot hot-loop a request no replica will
    # ever accept; None until a rejection stamps it
    retriable: Optional[bool] = None
    deadline_s: Optional[float] = None      # max queue wait before admit
    tokens: Optional[np.ndarray] = None     # generated ids (done only)
    n_out: int = 0                          # tokens up to & incl. EOS
    # speculative decoding (ISSUE 11): draft tokens proposed for this
    # request across its verify windows, and how many the target accepted
    spec_proposed: int = 0
    spec_accepted: int = 0
    # active probing (ISSUE 19): golden-canary requests ride the normal
    # submit()/decode path but are excluded end-to-end from user-facing
    # SLO/latency/goodput accounting — they feed probe_* families instead
    probe: bool = False
    trace: RequestTrace = field(default_factory=RequestTrace)

    @property
    def prompt_len(self) -> int:
        return int(self.prompt.shape[0])

    def record(self) -> dict:
        """The JSONL payload ServingMetrics streams per finished request."""
        t = self.trace
        rec = {"id": self.id, "status": self.status,
               "prompt_tokens": self.prompt_len,
               "output_tokens": self.n_out,
               "spans": t.to_dict()}
        if t.trace_id is not None:
            rec["trace_id"] = t.trace_id
        if self.probe:
            rec["probe"] = True
        if self.retriable is not None:
            rec["retriable"] = self.retriable
        if t.events:
            # the engine-call windows (ISSUE 12): rounded for the wire,
            # ordering preserved — span_tree() derives the tree view
            rec["events"] = [[n, round(a, 6), round(b, 6)]
                             for n, a, b in t.events]
        if self.reason:
            rec["reason"] = self.reason
        if self.spec_proposed:
            rec["spec"] = {"proposed": self.spec_proposed,
                           "accepted": self.spec_accepted,
                           "accept_rate": round(
                               self.spec_accepted / self.spec_proposed, 4)}
        for key, val in (("queue_s", t.queue_s), ("ttft_s", t.ttft_s),
                         ("tpot_s", t.tpot_s(self.n_out)),
                         ("e2e_s", t.e2e_s)):
            if val is not None:
                rec[key] = round(val, 6)
        return rec


# submit() rejection-reason taxonomy (ISSUE 14 satellite): which
# refusals a fleet router may retry on ANOTHER replica vs which are
# terminal everywhere (identically-configured replicas refuse them too)
_REJECT_RETRIABLE = {
    "draining": True,         # this replica is shutting down; others serve
    "overloaded": True,       # load shed — exactly the retry-elsewhere hint
    "queue_full": True,       # hard cap here; another queue may have room
    "prompt_shape": False,    # would force a new executable on any replica
    "kv_oom": False,          # never fits the pool even fully drained
    "max_new_tokens": False,  # unservable by construction
}


# ---------------------------------------------------------------- metrics

class ServingMetrics:
    """Request-level serving telemetry: histograms + gauges + counters.

    Latency series are LogHistograms — percentiles derive from bucket
    counts, so memory stays O(buckets) however many requests pass through.
    `record_request` consumes a finished Request; `observe_call` is the
    light entry point `inference.Predictor.run` uses under
    `Config.enable_profile()` (one call = one request, e2e only).
    Mirrors StepMonitor's reporting surface: `jsonl_path` streams one row
    per request, `on_record` is the exporter hook, `summary()` returns the
    aggregate dict and `metrics_text()` the Prometheus exposition."""

    HISTS = (("ttft_seconds", "time to first token (enqueue -> token 1)"),
             ("tpot_seconds", "per-output-token time after the first"),
             ("e2e_seconds", "end-to-end request latency"),
             ("queue_seconds", "queue wait (enqueue -> admit)"),
             ("spec_accept_len", "tokens emitted per speculative verify "
                                 "window (accepted drafts + the bonus "
                                 "token)"))

    def __init__(self, *, jsonl_path: Optional[str] = None,
                 on_record: Optional[Callable[[dict], None]] = None,
                 trace_buffer=None,
                 hist_lo: float = 1e-4, hist_hi: float = 1e3,
                 per_decade: int = 10):
        self.jsonl_path = jsonl_path
        self.on_record = on_record
        # obs.TraceBuffer (ISSUE 12): every terminal request record also
        # lands in the tail-sampling ring the /tracez endpoint snapshots
        self.trace_buffer = trace_buffer
        self.hists = {name: LogHistogram(lo=hist_lo, hi=hist_hi,
                                         per_decade=per_decade)
                      for name, _ in self.HISTS
                      if name != "spec_accept_len"}
        # the accept-length series (ISSUE 11) counts 1..spec_k+1 tokens,
        # not latencies: half-integer bounds resolve every integer
        # exactly, so the derived percentiles are exact, not interpolated
        self.hists["spec_accept_len"] = LogHistogram(
            bounds=[i + 0.5 for i in range(33)])
        self.counters = {"requests": 0, "completed": 0, "rejected": 0,
                         "overloaded": 0, "timeout": 0, "errors": 0,
                         "tokens_in": 0, "tokens_out": 0, "items": 0,
                         "batches": 0,
                         # prefix cache (ISSUE 10): admissions that
                         # mapped >= 1 cached block / that mapped none,
                         # and prompt tokens whose prefill was skipped
                         # because their KV was already pooled
                         "prefix_hit": 0, "prefix_miss": 0,
                         "prefill_tokens_saved": 0,
                         # speculative decoding (ISSUE 11): draft tokens
                         # proposed / accepted across verify windows, and
                         # where each window's draft came from
                         "spec_windows": 0, "spec_proposed": 0,
                         "spec_accepted": 0, "spec_drafts_trie": 0,
                         "spec_drafts_model": 0,
                         # HBM ledger (ISSUE 18): oversubscription-wait
                         # episodes (admission stalled on the free list)
                         "mem_pressure_episodes": 0}
        self.gauges = {"queue_depth": 0, "inflight": 0,
                       "batch_fill_ratio": None, "kv_occupancy": None,
                       "kv_slots_occupancy": None,
                       "kv_shared_tokens": None}
        # active probing (ISSUE 19): golden-canary requests are accounted
        # HERE, never in the user-facing counters/hists above — probe
        # traffic must not move SLO burn rates, goodput, or the r12
        # autoscaler's overload signal. Rejection reasons keep their own
        # dimension (the satellite fix: a probe shed during drain is
        # prober noise, not a user-facing rejected_total increment).
        # Rendered by probe_metrics_text() as a separate producer so a
        # no-prober exposition stays byte-identical by construction.
        self.probe_counters = {"requests": 0, "completed": 0,
                               "rejected": 0, "timeout": 0, "errors": 0}
        self.probe_reject_reasons: Dict[str, int] = {}

    # -- recording ------------------------------------------------------
    def observe_call(self, e2e_s: float, items: int = 1):
        """One synchronous predictor call: e2e latency + item (batch-row)
        count — NOT tokens; a Predictor serves arbitrary feeds."""
        self.counters["requests"] += 1
        self.counters["completed"] += 1
        self.counters["items"] += int(items)
        self.hists["e2e_seconds"].observe(e2e_s)

    def record_request(self, req: Request):
        if req.probe:
            # golden-canary traffic (ISSUE 19): full exclusion from the
            # user-facing families — no counter, no histogram, no trace
            # ring. The request stream stays a complete audit log (the
            # row just carries its own key).
            return self._record_probe_request(req)
        self.counters["requests"] += 1
        if req.status == "done":
            self.counters["completed"] += 1
            self.counters["tokens_in"] += req.prompt_len
            self.counters["tokens_out"] += req.n_out
            t = req.trace
            for name, val in (("ttft_seconds", t.ttft_s),
                              ("tpot_seconds", t.tpot_s(req.n_out)),
                              ("e2e_seconds", t.e2e_s),
                              ("queue_seconds", t.queue_s)):
                if val is not None:
                    self.hists[name].observe(max(val, 0.0))
        elif req.status == "timeout":
            self.counters["timeout"] += 1
            # the longest queue waits in the system are the expired ones —
            # leaving them out would make queue_seconds p99 look healthy
            # exactly when queueing collapsed
            t = req.trace
            if t.t_finish is not None and t.t_enqueue is not None:
                self.hists["queue_seconds"].observe(
                    max(t.t_finish - t.t_enqueue, 0.0))
        elif req.status == "rejected":
            self.counters["rejected"] += 1
            if req.reason == "overloaded":
                # the autoscaler signal — kept in lockstep with the
                # request record by construction, so any future shed
                # site that sets reason="overloaded" counts too
                self.counters["overloaded"] += 1
        elif req.status == "error":
            self.counters["errors"] += 1
        rec = req.record()
        if self.trace_buffer is not None:
            self.trace_buffer.add(rec)
        return self._emit({"request": rec, "ts": time.time()})

    def _record_probe_request(self, req: Request) -> dict:
        pc = self.probe_counters
        pc["requests"] += 1
        if req.status == "done":
            pc["completed"] += 1
        elif req.status == "rejected":
            pc["rejected"] += 1
            reason = req.reason or "unknown"
            self.probe_reject_reasons[reason] = \
                self.probe_reject_reasons.get(reason, 0) + 1
        elif req.status == "timeout":
            pc["timeout"] += 1
        elif req.status == "error":
            pc["errors"] += 1
        # distinct row key: consumers counting {"request"} rows (tracez,
        # stitchers) never see probe traffic; the flight recorder's
        # trigger bus ignores unknown keys
        return self._emit({"probe_request": req.record(),
                           "ts": time.time()})

    def probe_metrics_text(self,
                           prefix: str = "paddle_tpu_probe_serving") \
            -> str:
        """The engine-side probe families (submit/admission accounting;
        the Prober renders verdicts separately). A separate producer on
        purpose: metrics_text() is byte-identical with or without a
        prober attached."""
        lines: List[str] = []
        helps = {"requests": "probe requests observed at terminal "
                             "status",
                 "completed": "probe requests served to completion",
                 "rejected": "probe requests refused at submit "
                             "(prober noise, never user-facing "
                             "rejected_total)",
                 "timeout": "probe requests expired in queue",
                 "errors": "probe requests lost to engine exceptions"}
        for name, value in self.probe_counters.items():
            lines.extend(counter_lines(prefix, f"{name}_total", value,
                                       helps[name]))
        if self.probe_reject_reasons:
            p = prefix
            lines += [f"# HELP {p}_rejected_reason_total probe "
                      f"rejections by reason (the probe label "
                      f"dimension of the submit taxonomy)",
                      f"# TYPE {p}_rejected_reason_total counter"]
            lines += [f'{p}_rejected_reason_total{{reason="{r}"}} {c}'
                      for r, c in
                      sorted(self.probe_reject_reasons.items())]
        return "\n".join(lines) + "\n"

    def _emit(self, row: dict) -> dict:
        """One emission path for per-request and drain-summary rows —
        JSONL append + exporter hook stay in lockstep."""
        if self.jsonl_path:
            with open(self.jsonl_path, "a") as f:
                f.write(json.dumps(row) + "\n")
        if self.on_record is not None:
            self.on_record(row)
        return row

    def record_batch(self, *, n_real: int, capacity: int,
                     kv_tokens: int, kv_slots: int, kv_capacity: int,
                     queue_depth: int, kv_shared_tokens: int = 0):
        """kv_tokens = PHYSICAL live (attendable) KV rows — a block
        mapped into several requests' tables (prefix sharing) counts
        ONCE; kv_slots = rows the allocation granularity pins (padded
        slots / reserved blocks); kv_capacity = total pooled rows.
        kv_occupancy is the true-token gauge (ISSUE 5 satellite —
        padded-slot accounting could not go above the padding ratio);
        kv_slots_occupancy keeps the old slot-granular value for
        dashboard continuity. kv_shared_tokens (ISSUE 10) is the LOGICAL
        volume served out of shared blocks — summed over requests, so
        (kv_shared_tokens - distinct shared rows) is exactly the HBM the
        prefix cache is saving right now."""
        self.counters["batches"] += 1
        self.gauges["batch_fill_ratio"] = n_real / max(capacity, 1)
        self.gauges["kv_occupancy"] = kv_tokens / max(kv_capacity, 1)
        self.gauges["kv_slots_occupancy"] = kv_slots / max(kv_capacity, 1)
        self.gauges["kv_shared_tokens"] = kv_shared_tokens
        self.gauges["queue_depth"] = queue_depth

    # -- reporting ------------------------------------------------------
    def summary(self) -> dict:
        out = {**{f"{k}_total": v for k, v in self.counters.items()},
               **{k: v for k, v in self.gauges.items()}}
        for name, _ in self.HISTS:
            h = self.hists[name]
            if h.count:
                out[name] = h.summary()
        return out

    def flush(self) -> dict:
        """Drain-time flush: zero the liveness gauges (an empty engine
        must not keep advertising its last batch's occupancy) and emit one
        terminal `{"drain": summary}` row to the JSONL stream/on_record
        hook — the scrape a collector takes after graceful shutdown."""
        for k in ("queue_depth", "inflight"):
            self.gauges[k] = 0
        for k in ("batch_fill_ratio", "kv_occupancy",
                  "kv_slots_occupancy", "kv_shared_tokens"):
            self.gauges[k] = None
        return self._emit({"drain": self.summary(), "ts": time.time()})

    def metrics_text(self, prefix: str = "paddle_tpu_serving") -> str:
        """Prometheus text exposition — same format/renderer as
        StepMonitor.metrics_text, so one scrape handler concatenates
        both."""
        lines: List[str] = []
        helps = {"requests": "requests observed (all terminal statuses)",
                 "completed": "requests finished successfully",
                 "rejected": "requests refused at submit "
                             "(queue full / shape / draining)",
                 "overloaded": "requests shed at the queue high-watermark "
                               "(subset of rejected)",
                 "timeout": "requests expired in queue past their deadline",
                 "errors": "requests lost to an engine exception "
                           "mid-batch",
                 "tokens_in": "prompt tokens admitted",
                 "tokens_out": "tokens generated (up to and incl. EOS)",
                 "items": "batch rows processed by profiled predictor "
                          "calls",
                 "batches": "micro-batches executed",
                 "prefix_hit": "admissions that mapped >= 1 cached "
                               "prefix block",
                 "prefix_miss": "admissions that found no cached prefix",
                 "prefill_tokens_saved": "prompt tokens whose prefill "
                                         "was skipped (KV already "
                                         "pooled)",
                 "spec_windows": "speculative verify windows run "
                                 "(drafted rows only)",
                 "spec_proposed": "draft tokens proposed to the target "
                                  "model",
                 "spec_accepted": "draft tokens the target accepted "
                                  "(longest matching prefix)",
                 "spec_drafts_trie": "verify windows whose draft came "
                                     "from the prefix-trie prompt "
                                     "lookup",
                 "spec_drafts_model": "verify windows whose draft came "
                                      "from the draft-model hook",
                 "mem_pressure_episodes": "admission stalls waiting on "
                                          "KV blocks (one per episode, "
                                          "not per step)"}
        for name, value in self.counters.items():
            lines.extend(counter_lines(prefix, f"{name}_total", value,
                                       helps[name]))
        ghelp = {"queue_depth": "requests waiting in the admission queue",
                 "inflight": "requests currently being served",
                 "batch_fill_ratio": "real rows / batch capacity of the "
                                     "last micro-batch",
                 "kv_occupancy": "live (attendable) KV rows / pooled "
                                 "capacity — true-token occupancy",
                 "kv_slots_occupancy": "allocation-granular KV rows "
                                       "(padded slots / reserved blocks) "
                                       "/ pooled capacity",
                 "kv_shared_tokens": "logical KV rows served from "
                                     "shared prefix blocks (summed over "
                                     "requests)"}
        for name, value in self.gauges.items():
            lines.extend(gauge_lines(prefix, name, value, ghelp[name]))
        for name, help_ in self.HISTS:
            lines.extend(histogram_lines(prefix, name, self.hists[name],
                                         help_))
        return "\n".join(lines) + "\n"


# ----------------------------------------------------------------- engine

@dataclass
class ServingConfig:
    """Fixed-shape envelope of a ServingEngine. Everything that affects a
    compiled signature lives here — the engine NEVER recompiles to fit a
    request; requests that don't fit are rejected with a logged shape
    delta."""
    max_batch: int = 4              # micro-batch rows (padded with dummies)
    prompt_cap: int = 64            # right-padding cap; longer = rejected
    max_new_tokens: int = 32        # per-request budget ceiling
    decode_chunk: Optional[int] = None  # tokens per post-first-token call;
    #                                 default max_new_tokens-1 = one chunk
    queue_capacity: int = 256       # bounded admission queue
    # load shedding (ISSUE 7 satellite): queue depth at/above this sheds
    # new requests with a structured "overloaded" rejection BEFORE the
    # queue hits capacity — the backpressure signal a frontend can act on
    # (retry elsewhere) while the engine still has headroom; None = shed
    # only at queue_capacity
    queue_high_watermark: Optional[int] = None
    deadline_s: Optional[float] = None  # default queue-wait deadline
    eos_token_id: Optional[int] = None
    pad_token_id: int = 0
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    seed: int = 0
    weight_dtype: Optional[str] = None   # "int8" -> weight-only int8 GEMMs
    cache_dtype: Optional[str] = None    # "int8" -> int8 KV cache
    # --- paged KV pool (ISSUE 5): slot-level continuous batching ---
    paged: bool = False             # block-pool KV + mid-flight admission
    kv_block: int = 16              # KV rows per pool block
    kv_blocks: Optional[int] = None  # total pool blocks INCL. trash block;
    #                            default = worst case for max_batch rows
    # --- multi-chip tensor-parallel serving (ISSUE 16): shard the paged
    # pools' HEAD axis over an `mp` mesh of this many devices. The
    # executables run through the mpu tensor-parallel layers; block
    # tables, the allocator, refcounts and the radix trie stay host-side
    # and replicated. None/1 = single-chip (no mesh built). Requires
    # paged=True and num_heads % shards == 0; greedy output is
    # bit-identical across shard counts (the per-shard invariant suite).
    shards: Optional[int] = None
    # --- prefix cache (ISSUE 10): radix-trie prefix reuse over the pool.
    # A full-block-aligned cached prefix maps shared (refcounted) blocks
    # straight into the new request's table — full hit skips prefill
    # entirely (TTFT = one decode step, COW on the last block), partial
    # hit prefills only the suffix. Requires paged=True.
    prefix_cache: bool = False
    prefix_cache_bytes: Optional[int] = None  # LRU eviction budget for
    #                            cached (refcount-free) blocks; None =
    #                            bounded by the pool itself (admission
    #                            reclaims cached blocks under pressure)
    # --- host-RAM spill tier (ISSUE 14): LRU-evicted full prefix blocks
    # serialize to pinned host arrays instead of vanishing; a later trie
    # hit rehydrates via ONE host→device copy — cached-prefix capacity
    # becomes host-memory-sized instead of HBM-sized. The value is the
    # host byte budget; None disables (eviction stays final).
    spill_host_bytes: Optional[int] = None
    # --- speculative decoding (ISSUE 11): draft-verify through the
    # ragged [B, k] multi-token paged-attention kernel. Each decode step
    # scores `spec_k` drafted tokens + the pending token in ONE
    # fixed-shape verify call; the longest-accepted-prefix rule keeps
    # greedy output bit-identical to the plain chain, and rows advance
    # 1..spec_k+1 tokens per launch. Requires paged=True and greedy
    # sampling (temperature 0 — acceptance IS argmax equality).
    spec_decode: bool = False
    spec_k: int = 4                 # draft tokens per verify window
    # draft source: "trie" = prompt-lookup from the prefix radix trie (a
    # matched node's cached continuation tokens ARE the draft — requires
    # prefix_cache=True; finished requests' generated chains are cached
    # too, so repeated/agentic traffic drafts its own future. NOTE
    # drafts are BLOCK-granular: a finished chain contributes drafts
    # only once its generated tokens fill at least one pool block past
    # the prompt — keep kv_block below the typical generation length);
    # or a callable (context_tokens: np.ndarray, k: int) -> up-to-k
    # token ids (see `model_draft_fn` for the tiny-GPT adapter). A
    # callable composes with the trie: the trie drafts when it can, the
    # callable fills the misses.
    spec_draft: object = "trie"
    # --- chunked prefill (ISSUE 11 satellite): cap per-step prefill work
    # at [1, prefill_chunk] tokens so one long prompt never monopolizes
    # the engine for a whole prefill — offsets are DATA through the
    # start-form prefill executable (zero new executables per prompt
    # length). None = whole-prompt/suffix prefill at admission (the
    # ISSUE-5/10 behavior).
    prefill_chunk: Optional[int] = None
    # --- static analysis (ISSUE 6): True / "error" / analysis.GraphLint —
    # the engine audits each of its {prefill, decode} executables with
    # the graph lint once, the first step it is built (findings
    # accumulate on engine.lint_findings; guard mode raises before the
    # steady-state loop proceeds)
    lint: object = None

    def __post_init__(self):
        from ..analysis.findings import ConfigValidationError, Finding
        if self.max_batch < 1 or self.prompt_cap < 1 \
                or self.max_new_tokens < 1:
            raise ValueError("max_batch, prompt_cap and max_new_tokens "
                             "must be >= 1")
        if self.decode_chunk is None:
            self.decode_chunk = max(1, self.max_new_tokens - 1)
        elif self.decode_chunk < 1:
            raise ValueError(f"decode_chunk must be >= 1, "
                             f"got {self.decode_chunk}")
        if self.queue_high_watermark is not None and \
                not (1 <= self.queue_high_watermark <= self.queue_capacity):
            raise ValueError(
                f"queue_high_watermark must be in [1, queue_capacity="
                f"{self.queue_capacity}], got {self.queue_high_watermark}")
        if self.shards is not None:
            if self.shards < 1:
                raise ValueError(f"shards must be >= 1, got {self.shards}")
            if self.shards > 1 and not self.paged:
                raise ConfigValidationError(Finding(
                    "config", "sharded_requires_paged", "error",
                    f"shards={self.shards} requires paged=True: tensor-"
                    f"parallel serving shards the paged block pools' head "
                    f"axis over the mp mesh; the padded static engine has "
                    f"no pools to shard",
                    executable="ServingConfig",
                    data={"shards": self.shards, "paged": False}))
        if self.prefix_cache and not self.paged:
            raise ValueError("prefix_cache=True requires paged=True (the "
                             "trie shares BLOCK-pool blocks; the padded "
                             "engine has no blocks to share)")
        if self.spill_host_bytes is not None and not self.prefix_cache:
            raise ValueError("spill_host_bytes requires prefix_cache="
                             "True (the spill tier holds EVICTED trie "
                             "blocks; without the trie nothing is ever "
                             "evicted into it)")
        if self.spec_decode:
            if not self.paged:
                raise ValueError("spec_decode=True requires paged=True "
                                 "(the verify call runs the [B, k] "
                                 "multi-token kernel over the block "
                                 "pool)")
            if not (1 <= self.spec_k <= 31):
                # the upper bound keeps the spec_accept_len histogram's
                # exact-integer buckets (bounds cover counts <= 32 =
                # spec_k + 1) honest; windows wider than that are far
                # past any useful acceptance length anyway
                raise ValueError(f"spec_k must be in [1, 31], "
                                 f"got {self.spec_k}")
            if self.temperature > 0.0:
                raise ValueError(
                    "spec_decode=True requires greedy sampling "
                    "(temperature=0): the bit-exact acceptance rule is "
                    "argmax equality; sampled speculative decoding needs "
                    "a rejection-sampling rule this engine does not "
                    "implement")
            if self.spec_draft == "trie":
                if not self.prefix_cache:
                    raise ValueError(
                        "spec_draft='trie' requires prefix_cache=True "
                        "(prompt-lookup drafts are the radix trie's "
                        "cached continuation tokens); pass a callable "
                        "spec_draft to use a draft model instead")
            elif not callable(self.spec_draft):
                raise ValueError(f"spec_draft must be 'trie' or a "
                                 f"callable (context, k) -> tokens; got "
                                 f"{self.spec_draft!r}")
        if self.prefill_chunk is not None:
            if not self.paged:
                raise ValueError("prefill_chunk requires paged=True (the "
                                 "chunk windows write pool blocks via "
                                 "the start-offset executable)")
            if not (1 <= self.prefill_chunk <= self.prompt_cap):
                raise ValueError(
                    f"prefill_chunk must be in [1, prompt_cap="
                    f"{self.prompt_cap}], got {self.prefill_chunk}")
        if self.paged:
            if self.cache_dtype not in (None, "int8"):
                # int8 paged KV landed (ISSUE 10: per-block factored
                # scales, the static int8 trick ported to the paged
                # kernel); every OTHER narrow dtype is still refused with
                # a structured config-validation finding (same schema as
                # the graph passes) so tools print WHY — ConfigValidation-
                # Error is a ValueError, existing callers keep working
                raise ConfigValidationError(Finding(
                    "config", "paged_cache_dtype", "error",
                    f"cache_dtype={self.cache_dtype!r} with paged=True is "
                    f"not supported: paged pools carry the MODEL dtype or "
                    f"the int8 (codes, factored-scale) form. Use "
                    f"cache_dtype='int8' (halves resident KV), "
                    f"cache_dtype=None, or paged=False with "
                    f"cache_dtype={self.cache_dtype!r}",
                    executable="ServingConfig",
                    data={"cache_dtype": str(self.cache_dtype),
                          "paged": True}))
            if self.kv_block < 1:
                raise ValueError(f"kv_block must be >= 1, "
                                 f"got {self.kv_block}")
            if self.kv_blocks is None:
                # worst case: every slot holds a cap prompt decoding its
                # full budget (+1 for the reserved trash block). Smaller
                # pools oversubscribe deliberately — admission then waits
                # on freed blocks.
                self.kv_blocks = self.max_batch * self.table_width + 1

    @property
    def row_kv_rows(self) -> int:
        """Worst-case KV rows one request can write: cap prompt + full
        budget, minus the never-written last sampled token."""
        return self.prompt_cap + self.max_new_tokens - 1

    @property
    def table_width(self) -> int:
        """Block-table columns per batch slot (worst-case blocks/row)."""
        return -(-self.row_kv_rows // self.kv_block)

    @property
    def chunk_schedule(self) -> List[int]:
        """Decode-call sizes per batch: [1, c, c, ...] covering
        max_new_tokens (the tail chunk still runs full width — fixed
        shapes — and over-generated tokens are truncated per row)."""
        if self.max_new_tokens == 1:
            return [1]
        k = math.ceil((self.max_new_tokens - 1) / self.decode_chunk)
        return [1] + [self.decode_chunk] * k

    @property
    def max_len(self) -> int:
        """KV rows per batch slot: prompt cap + the chunk schedule's
        worst-case cache writes (the last sampled token is never
        written)."""
        return self.prompt_cap + max(sum(self.chunk_schedule), 2) - 1


class ServingEngine:
    """Continuous-batching serving loop over the static decode stack.

    Synchronous by design: `submit()` enqueues, `step()` runs ONE
    micro-batch to completion, `drain()` loops until the queue empties.
    The engine is NOT internally synchronized — submit/step touch shared
    state beyond the queue (request ids, metrics counters/gauges, the
    JSONL stream), so a frontend thread driving submit while a worker
    loops step() must hold one lock around every engine call. The calls
    are short on the submit side; step() blocks for a batch.

    `clock` is injectable (tests drive deadlines deterministically).
    """

    def __init__(self, model, config: ServingConfig, *,
                 metrics: Optional[ServingMetrics] = None,
                 monitor: Optional[StepMonitor] = None,
                 chaos=None,
                 clock: Callable[[], float] = time.monotonic):
        self.model = model
        self.config = config
        self.metrics = metrics or ServingMetrics()
        # fault injection (ISSUE 12 Injector): fired at serving.step so
        # the OOM post-mortem path is rehearsable without a real OOM
        self.chaos = chaos
        # HBM ledger (ISSUE 18): attach_memory_ledger wires the pool /
        # prefix-cache / spill owners; None = unattributed engine
        self._memz = None
        self._mem_pressure_t0 = None   # oversubscription-wait episode
        # active probing (ISSUE 19): serve_telemetry wires a Prober /
        # InvariantAuditor here; the config fingerprint is cached (env
        # and versions are process-stable)
        self._prober = None
        self._invariants = None
        self._fingerprint = None
        # the monitor carries batch step timing + the recompile guard; the
        # serving engine measures dispatch-to-sync walls (truthful: every
        # chunk ends in a host sync for the token handoff)
        self.monitor = monitor or StepMonitor(unit="tokens/s",
                                              track_memory=False)
        self.clock = clock
        from ..analysis import GraphLint
        from ..analysis.recompile import abstract_signature
        # graph lint (ISSUE 6): audit the engine's {prefill, decode}
        # executables right after the warmup batch builds them
        self._lint = GraphLint.coerce(config.lint)
        self._lint_seen = set()   # executables already audited
        self.lint_findings = None
        # the abstract batch signature the engine's executables key on —
        # the "old" side of the preflight recompile differ
        self._engine_abstract = abstract_signature(
            jax.ShapeDtypeStruct((config.max_batch, config.prompt_cap),
                                 np.int64),
            jax.ShapeDtypeStruct((config.max_batch,), np.int32))
        self._queue: deque = deque()
        self._draining = False     # graceful drain: stop admitting
        self._next_id = 0
        self._batch_id = 0
        self._t_start = self.clock()    # statusz uptime anchor
        # trace ids are unique across engine incarnations: a fleet's
        # collectors merge many replicas' JSONL/tracez streams, where a
        # bare per-engine request counter would collide instantly
        self._run_id = uuid.uuid4().hex[:8]
        self._max_depth = 0        # deepest (prefill + k chunks) run so far
        self._rejected_shapes = set()   # shape-delta warned once per shape
        # the engine's one-and-only batch signature (leaves shaped like
        # StepMonitor.record_compile expects for shape_delta rendering)
        self._shape_sig = (((config.max_batch, config.prompt_cap), "int64"),
                           ((config.max_batch,), "int32"))
        self._spill = None     # host spill tier (paged + prefix + spill)
        # multi-chip serving (ISSUE 16): a private mp mesh over the first
        # `shards` devices. The engine activates it around pool creation
        # and every step — NOT globally — so interleaved engines at
        # different shard counts (the bit-identity suite, the bench's
        # single-chip twin) never see each other's mesh.
        self._mesh = None
        if config.paged and (config.shards or 1) > 1:
            from ..distributed import mesh as _dist_mesh
            shards = int(config.shards)
            devs = jax.devices()
            if len(devs) < shards:
                raise ValueError(
                    f"shards={shards} needs {shards} devices, have "
                    f"{len(devs)} (CPU hosts: set "
                    f"--xla_force_host_platform_device_count)")
            nh = model.config.num_heads
            if nh % shards != 0:
                raise ValueError(
                    f"shards={shards} must divide num_heads={nh} (pools "
                    f"shard the head axis)")
            self._mesh = _dist_mesh.build_mesh({"mp": shards},
                                               devs[:shards])
        if config.paged:
            # slot-level continuous batching over a paged block pool: each
            # batch slot runs its own request; EOS/budget frees the slot's
            # blocks immediately and _admit_paged splices a queued request
            # into the vacancy mid-flight. Device state is the donated
            # per-layer pools; tables/lens/pending/done are tiny host
            # vectors edited per slot and shipped with every chunk.
            from .kv_cache import BlockPool
            B, MB = config.max_batch, config.table_width
            self._pool = BlockPool.for_model(model,
                                             num_blocks=config.kv_blocks,
                                             block_size=config.kv_block,
                                             cache_dtype=config.cache_dtype)
            with self._mesh_scope():
                self._pools = self._pool.make_pools()
            self._slots: List[Optional[Request]] = [None] * B
            self._tables = np.zeros((B, MB), np.int32)
            self._lens = np.zeros((B,), np.int32)
            self._pending = np.zeros((B,), np.int32)
            self._done = np.ones((B,), bool)
            self._calls = 0            # PRNG stream cursor (sampling mode)
            self._paged_seen = set()   # executables already compiled
            self._kv_snapshot = (0, 0, 0)  # (physical live tokens, slot
            #                      rows, logical shared tokens) at the
            #                      last step's decode entry
            # prefix cache (ISSUE 10): per-slot count of lens tokens that
            # live in blocks the request mapped SHARED from the trie —
            # the kv_shared_tokens gauge and the hit bookkeeping
            self._shared_tok = np.zeros((B,), np.int64)
            self._prefix = None
            if config.prefix_cache:
                from .prefix_cache import PrefixCache
                self._prefix = PrefixCache(
                    self._pool, byte_budget=config.prefix_cache_bytes)
                if config.spill_host_bytes is not None:
                    # host-RAM spill tier (ISSUE 14): the cache owns the
                    # trie mechanics; the engine owns the device pools,
                    # so both transfer directions are closures over it
                    from .kv_cache import HostSpillTier
                    self._spill = HostSpillTier(
                        bytes_per_block=self._pool.bytes_per_block,
                        byte_budget=config.spill_host_bytes)
                    self._prefix.attach_spill(
                        self._spill,
                        reader=lambda blk: self._pool.read_block(
                            self._pools, blk),
                        writer=self._spill_write)
            # chunked prefill (ISSUE 11): next prompt position to prefill
            # per slot; -1 = not mid-prefill (a plain decode row)
            self._prefill_pos = np.full((B,), -1, np.int64)
            # spec decoding (ISSUE 11): the optional draft-model hook —
            # the trie (when present) drafts first, the hook fills misses
            self._draft_fn = config.spec_draft \
                if callable(config.spec_draft) else None

    def _mesh_scope(self):
        """Activate the engine's private mp mesh (multi-chip serving) for
        the duration of a step — a no-op nullcontext on single-chip
        engines. Every compiled-signature component that depends on the
        shard count reads `mesh_axis_size("mp")` under this scope, so
        engines at different shard counts never collide in the compiled-
        runner caches."""
        import contextlib
        if self._mesh is None:
            return contextlib.nullcontext()
        from ..distributed import mesh as _dist_mesh
        return _dist_mesh.mesh_scope(self._mesh)

    # -- admission ------------------------------------------------------
    @property
    def queue_depth(self) -> int:
        return len(self._queue)

    @property
    def busy(self) -> bool:
        """Work remains: queued requests, or (paged) live batch slots
        still decoding — the public loop condition drain() and external
        replayers (tools/serve_bench.py) share."""
        # host-side deque/slot-list reads  # lint: allow(tracer-bool)
        return bool(self._queue) or \
            (self.config.paged and bool(self._live()))  # lint: allow(tracer-bool)

    def preflight(self, prompt, max_new_tokens: Optional[int] = None):
        """Static admission check (analysis.recompile): Findings for
        everything about this request that would force a new executable
        or is statically unservable — BEFORE any tracing happens. Empty
        findings = admissible (dynamic conditions like queue capacity
        are submit()'s business). `submit` rejects through this, so the
        refusal reason and the would-be recompile explanation come from
        the same differ the lint suite uses."""
        from ..analysis.findings import Finding, Findings
        from ..analysis.recompile import (abstract_signature,
                                          diff_signatures)
        cfg = self.config
        p = np.asarray(prompt, dtype=np.int64).reshape(-1)  # lint: allow(tracer-asarray)
        want = cfg.max_new_tokens if max_new_tokens is None \
            else min(int(max_new_tokens), cfg.max_new_tokens)
        out = Findings()
        if want < 1:
            out.add(Finding(
                "config", "max_new_tokens", "error",
                f"token budget {want} < 1 is unservable (the caller "
                f"asked to pay for nothing)", executable="serving"))
        plen = int(p.shape[0])
        if plen < 1 or plen > cfg.prompt_cap:
            # ShapeDtypeStructs, not real arrays: the rejection path must
            # not allocate a [max_batch, plen] buffer for an oversized
            # prompt just to describe its shape
            req_sig = abstract_signature(
                jax.ShapeDtypeStruct((cfg.max_batch, plen), np.int64),
                jax.ShapeDtypeStruct((cfg.max_batch,), np.int32))
            diffs = diff_signatures(
                self._engine_abstract, req_sig,
                executable="serving_batch",
                names=("input_ids", "prompt_lens"))
            why = "; ".join(f.message for f in diffs) \
                or f"prompt length {plen} outside [1, {cfg.prompt_cap}]"
            out.add(Finding(
                "recompile_hazard", "prompt_shape", "error",
                f"prompt length {plen} would force a new prefill "
                f"executable: {why}", executable="serving_batch",
                data={"prompt_len": plen, "cap": cfg.prompt_cap}))
        if cfg.paged and plen >= 1 and want >= 1 \
                and not self._pool.fits_ever(plen + want - 1):
            msg = (f"request needs {plen + want - 1} KV rows — more than "
                   f"the whole pool holds even fully drained")
            data = {"rows": plen + want - 1}
            if self._memz is not None:
                # the ledger's census answers the operator's next question
                # ("who do I evict to make room?") inside the reject itself
                top = self._memz.top_owners(3)
                if top:
                    data["top_owners"] = top
                    msg += "; top HBM owners: " + ", ".join(
                        f"{t['owner']}={t['bytes']}B" for t in top)
            out.add(Finding(
                "config", "kv_oom", "error", msg,
                executable="serving", data=data))
        return out

    def submit(self, prompt, max_new_tokens: Optional[int] = None,
               deadline_s: Optional[float] = None,
               enqueue_at: Optional[float] = None,
               probe: bool = False) -> Request:
        """Admit one prompt into the bounded queue.

        Returns the Request; check `.status` — "queued" on success,
        "rejected" (queue full, or a shape the engine's executables cannot
        serve) otherwise. `enqueue_at` backdates the enqueue span for
        open-loop replay (tools/serve_bench.py): queue-wait/TTFT are then
        measured from the request's SCHEDULED arrival, not from when the
        single-threaded replayer got around to calling submit. Backdating
        only — a future timestamp clamps to now (a request cannot be
        served before it arrives; negative queue waits would corrupt the
        accounting this engine exists to make honest)."""
        cfg = self.config
        prompt = np.asarray(prompt, dtype=np.int64).reshape(-1)  # lint: allow(tracer-asarray)
        want = cfg.max_new_tokens if max_new_tokens is None \
            else min(int(max_new_tokens), cfg.max_new_tokens)
        # probe tag stamped BEFORE any rejection path (ISSUE 19): a probe
        # shed here (draining/overload/queue_full) lands in the probe
        # families, never in the user-facing rejection counters
        req = Request(id=self._next_id, prompt=prompt,
                      max_new_tokens=want, probe=probe,
                      deadline_s=cfg.deadline_s if deadline_s is None
                      else deadline_s)
        self._next_id += 1
        req.trace.trace_id = f"{self._run_id}-{req.id}"
        now = self.clock()
        req.trace.t_enqueue = now if enqueue_at is None \
            else min(enqueue_at, now)
        # static admission: the recompile-hazard differ decides BEFORE any
        # tracing whether this request fits the engine's one executable
        # set (preflight's findings carry the exact would-be shape delta).
        # A "prompt_shape" refusal additionally logs through the r7
        # recompile channel — count=False keeps the compiles/recompiles
        # COUNTERS a pure signal of real executable churn (nothing was
        # built — the request was refused precisely so nothing would be);
        # each offending shape WARNS once per engine, abusive traffic must
        # not spam the recompile log/event stream. Every refusal still
        # counts in rejected_total and gets its per-request JSONL record:
        # the request stream is the audit log, deliberately complete.
        # A "kv_oom" refusal means the pool could not hold the request
        # even fully drained — anything smaller is ADMITTABLE (it waits
        # for freed blocks at worst; no bucket-mismatch rejection inside
        # the cap).
        # graceful drain (ISSUE 7): a draining engine finishes what it has
        # and admits nothing — the structured refusal tells the frontend
        # to route elsewhere, not to retry here
        if self._draining:
            req.status, req.reason = "rejected", "draining"
            req.retriable = _REJECT_RETRIABLE["draining"]
            self.metrics.record_request(req)
            return req
        pf = self.preflight(prompt, want)
        if pf:
            finding = pf[0]
            req.status, req.reason = "rejected", finding.code
            req.retriable = _REJECT_RETRIABLE.get(finding.code, False)
            if finding.code == "prompt_shape":
                plen = int(prompt.shape[0])
                if plen not in self._rejected_shapes:
                    self._rejected_shapes.add(plen)
                    self.monitor.record_compile(
                        "serving_reject",
                        (((cfg.max_batch, plen), "int64"),
                         self._shape_sig[1]),
                        prev_sig=self._shape_sig, count=False)
            self.metrics.record_request(req)
            return req
        # load shedding: at the high-watermark the engine is still alive
        # but past its SLO-holding depth — shed with a reason the metrics
        # count separately (overloaded_total is the autoscaler signal;
        # queue_full means the hard cap, i.e. shedding came too late)
        if cfg.queue_high_watermark is not None and \
                len(self._queue) >= cfg.queue_high_watermark:
            req.status, req.reason = "rejected", "overloaded"
            req.retriable = _REJECT_RETRIABLE["overloaded"]
            self.metrics.record_request(req)
            return req
        if len(self._queue) >= cfg.queue_capacity:
            req.status, req.reason = "rejected", "queue_full"
            req.retriable = _REJECT_RETRIABLE["queue_full"]
            self.metrics.record_request(req)
            return req
        self._queue.append(req)
        self.metrics.gauges["queue_depth"] = len(self._queue)
        return req

    def _admit(self):
        """Pop up to max_batch live requests; expire the deadline-blown.
        Returns (admitted, expired) — both are terminal outcomes the
        caller must surface (a timed-out request is a served SLO miss,
        not something to silently drop from the accounting)."""
        now = self.clock()
        admitted: List[Request] = []
        expired: List[Request] = []
        while self._queue and len(admitted) < self.config.max_batch:
            req = self._queue.popleft()
            if req.deadline_s is not None and \
                    now - req.trace.t_enqueue > req.deadline_s:
                req.status, req.reason = "timeout", "queue_deadline"
                req.trace.t_finish = now       # terminal time: its queue
                self.metrics.record_request(req)  # wait IS its life
                expired.append(req)
                continue
            req.status = "active"
            req.trace.t_admit = now
            req.trace.batch_id = self._batch_id
            admitted.append(req)
        self.metrics.gauges["queue_depth"] = len(self._queue)
        return admitted, expired

    # -- the batch loop -------------------------------------------------
    def step(self) -> List[Request]:
        """Assemble and run ONE micro-batch; returns every request that
        reached a terminal status this step — served rows AND queue-
        deadline timeouts (excluding expired traffic from the results
        would hide exactly the overload signal the metrics exist for).

        If the batch dies mid-flight (device OOM, interrupt), the admitted
        requests are recorded as status="error" before the exception
        propagates — an accounting layer must not lose in-flight requests.

        With `ServingConfig(lint=...)`, every step runs under
        `analysis.lint_capture` and each executable the engine builds is
        audited by GraphLint ONCE, the first step it appears — covering
        the whole {prefill, decode} set even when early traffic finishes
        at prefill (budget-1 / instant-EOS) and decode only compiles
        later. Findings accumulate on `self.lint_findings` (stored BEFORE
        the guard fires, so a caller catching GraphLintError can still
        read them); a guard-mode lint raises as soon as an audited
        executable violates — after that batch was served, since the
        program must exist to be lowered."""
        with self._mesh_scope():
            return self._step_inner()

    def _step_inner(self) -> List[Request]:
        if self._lint is None:
            return self._step_dispatch()
        from ..analysis import lint_capture
        from ..analysis.findings import Findings
        from ..analysis.lint import _kind_name
        with lint_capture() as calls:
            out = self._step_dispatch()
        new = [c for c in calls
               if (id(c[1]), _kind_name(c[0])) not in self._lint_seen]
        if new:
            for kind, fn, _ in new:
                self._lint_seen.add((id(fn), _kind_name(kind)))
            if self.lint_findings is None:
                self.lint_findings = Findings()
            fs = self._lint.check_calls(new, guard=False)
            self.lint_findings.extend(fs)
            self._lint._guard(fs, "serving executables")
        return out

    def _step_dispatch(self) -> List[Request]:
        if self.config.paged:
            return self._step_paged()
        reqs, expired = self._admit()
        if not reqs:
            return expired
        try:
            return expired + self._run_batch(reqs)
        except BaseException:
            now = self.clock()
            for r in reqs:
                if r.status == "active":
                    r.status, r.reason = "error", "engine_exception"
                    r.trace.t_finish = now
                    self.metrics.record_request(r)
            self.metrics.gauges["inflight"] = 0
            self.monitor.end_step(items=0)   # no-op if begin never ran
            raise

    def _run_batch(self, reqs: List[Request]) -> List[Request]:
        cfg = self.config
        self.metrics.gauges["inflight"] = len(reqs)
        batch_id = self._batch_id
        self._batch_id += 1

        # fixed-shape assembly: right-padded [B, prompt_cap] int64 + lens;
        # unfilled rows are 1-token pad dummies (their outputs are dropped,
        # and per-row attention/masks keep them from touching real rows)
        B, cap = cfg.max_batch, cfg.prompt_cap
        ids = np.full((B, cap), cfg.pad_token_id, dtype=np.int64)
        lens = np.ones((B,), dtype=np.int32)
        for i, r in enumerate(reqs):
            ids[i, :r.prompt_len] = r.prompt
            lens[i] = r.prompt_len

        miss0 = _jit_cache_misses()
        need = max(r.max_new_tokens for r in reqs)
        self.monitor.begin_step()
        t_pf0 = self.clock()
        with jax.profiler.TraceAnnotation("serving/prefill"):
            st = self.model.prefill_static(
                ids, max_len=cfg.max_len, prompt_lens=lens,
                weight_dtype=cfg.weight_dtype, cache_dtype=cfg.cache_dtype)
            jax.block_until_ready(st["last_logits"])
        t_prefill = self.clock()
        for r in reqs:
            r.trace.t_prefill_done = t_prefill
            r.trace.events.append(("prefill", t_pf0, t_prefill))

        parts: List[np.ndarray] = []
        schedule = cfg.chunk_schedule
        for ci, chunk in enumerate(schedule):
            t_c0 = self.clock()
            with jax.profiler.TraceAnnotation("serving/decode"):
                # per-(batch, chunk) seed: every decode_static call builds
                # a fresh PRNG stream from its seed, so reusing one seed
                # across chunks would replay the same draws
                # donate_cache: the state is used LINEARLY here (st is
                # replaced every chunk, the prefill state never reused),
                # so XLA updates the KV tuples in place instead of
                # re-threading them by value each chunk
                toks, st = self.model.decode_static(
                    st, chunk, temperature=cfg.temperature,
                    top_k=cfg.top_k, top_p=cfg.top_p,
                    seed=cfg.seed + batch_id * len(schedule) + ci,
                    eos_token_id=cfg.eos_token_id, return_state=True,
                    donate_cache=True)
                part = np.asarray(toks.numpy())     # host sync per chunk  # lint: allow(tracer-asarray)
            parts.append(part)
            t_chunk = self.clock()
            if ci == 0:
                for r in reqs:
                    r.trace.t_first_token = t_chunk
            # the decode window rides every row still in flight at chunk
            # entry — a row finished in an EARLIER chunk is not charged
            # this one (same rule as the t_finish stamp below)
            for r in reqs:
                if r.trace.t_finish is None:
                    r.trace.events.append(("decode", t_c0, t_chunk))
            # per-row finish at chunk granularity: a row is complete once
            # it hit EOS or its own budget — its e2e/TPOT must not be
            # charged for chunks the batch ran for OTHER rows
            produced = sum(p.shape[1] for p in parts)
            so_far = part if len(parts) == 1 else \
                np.concatenate(parts, axis=1)
            for i, r in enumerate(reqs):
                if r.trace.t_finish is None and \
                        (produced >= r.max_new_tokens or
                         _hit_eos(so_far[i, :r.max_new_tokens],
                                  cfg.eos_token_id)):
                    r.trace.t_finish = t_chunk
            if produced >= need:
                break
            if cfg.eos_token_id is not None:
                done = np.asarray(st["done"])  # lint: allow(tracer-asarray)
                if done[:len(reqs)].all():
                    break               # every real row hit EOS: stop early

        gen = np.concatenate(parts, axis=1)
        out_tokens = 0
        for i, r in enumerate(reqs):
            row = gen[i, :r.max_new_tokens]
            r.tokens = row
            r.n_out = _n_out(row, cfg.eos_token_id)
            r.status = "done"
            if r.trace.t_finish is None:    # unreachable in practice: both
                r.trace.t_finish = t_chunk  # loop exits finish every row
            out_tokens += r.n_out
            self.metrics.record_request(r)
        # true live tokens: real prompt rows + decode rows actually
        # written (prompt + produced - 1 each; the last sampled token is
        # returned but never written). Slots accounting: every admitted
        # row pins a FULL padded [max_len] slab — that gap between the two
        # gauges is exactly what the paged engine exists to close.
        kv_tokens = int(lens[:len(reqs)].sum()) + \
            int((gen.shape[1] - 1) * len(reqs))
        self.metrics.record_batch(
            n_real=len(reqs), capacity=B, kv_tokens=kv_tokens,
            kv_slots=len(reqs) * cfg.max_len,
            kv_capacity=B * cfg.max_len, queue_depth=len(self._queue))
        self.metrics.gauges["inflight"] = 0

        # compile accounting BEFORE closing the step so the monitor marks
        # this record `compiled` and keeps it out of the steady-state
        # median/throughput: warmup's wall time is compile-dominated.
        # Warmth is per chunk DEPTH, not per engine — an EOS early-exit or
        # small-budget batch may stop before the deeper chunk executables
        # ever compiled, and their eventual first compile is not shape
        # churn. A jit miss at an already-seen depth is: every executable
        # at that depth was cached, so something reshaped — log it as a
        # recompile through the r7 detector.
        depth = 1 + len(parts)               # prefill + decode calls made
        dm = _jit_cache_misses() - miss0
        if dm:
            self.monitor.record_compile(
                "serving_batch",
                (("jit_cache_misses", dm),),
                prev_sig=(("jit_cache_misses", 0),)
                if depth <= self._max_depth else None)
        self._max_depth = max(self._max_depth, depth)
        self.monitor.end_step(items=out_tokens)
        return reqs

    # ------------------------------------- paged slot-level batching loop
    def _live(self) -> List[int]:
        return [i for i, r in enumerate(self._slots) if r is not None]

    def _step_paged(self) -> List[Request]:
        """One paged engine step: splice queued requests into free slots
        (per-slot prefill into fresh blocks), then run ONE decode chunk
        over the live batch; rows hitting EOS/budget free their blocks
        immediately. Executable set = {prefill [1, cap], decode [B, c]} —
        both compile once, so a steady mixed-length loop adds zero jit
        cache misses however requests arrive."""
        miss0 = _jit_cache_misses()
        ran = set()
        self.monitor.begin_step()
        out_tokens = 0
        # spill/rehydrate device calls ride admission (match/evict): tag
        # them into `ran` so their one-time compiles are warmup, not
        # shape churn, in the recompile accounting below
        spill0 = (self._spill.spilled_total, self._spill.rehydrated_total) \
            if self._spill is not None else (0, 0)
        try:
            if self.chaos is not None:
                # rehearsal seam for the OOM forensics path: an injected
                # AllocFailure raises here exactly like a device
                # RESOURCE_EXHAUSTED unwinding out of the chunk call
                self.chaos.fire("serving.step", step=self._batch_id,
                                queue_depth=len(self._queue))
            finished, expired, admit_ran = self._admit_paged()
            ran |= admit_ran
            pf_done, pf_ran = self._advance_prefill()
            ran |= pf_ran
            finished.extend(pf_done)
            live_entry = self._decodable()
            if live_entry:
                if self.config.spec_decode:
                    chunk_done, out_tokens, dec_ran = \
                        self._decode_chunk_spec(live_entry)
                    ran |= dec_ran
                else:
                    chunk_done, out_tokens = self._decode_chunk_paged(
                        live_entry)
                    ran.add("decode")
                finished.extend(chunk_done)
        except BaseException as step_exc:
            # OOM forensics (ISSUE 18): dump the census BEFORE the
            # recovery below resets the pool — the artifact must show the
            # occupancy that failed, not the post-reset emptiness
            if self._memz is not None:
                from ..obs.memz import looks_like_oom
                if looks_like_oom(step_exc):
                    inflight = [
                        {"id": r.id, "prompt_len": len(r.prompt),
                         "n_out": r.n_out}
                        for r in self._slots if r is not None]
                    self._memz.post_mortem(
                        error=step_exc,
                        context={"site": "serving.step",
                                 "batch_id": self._batch_id,
                                 "queue_depth": len(self._queue),
                                 "inflight": inflight})
            now = self.clock()
            for i, r in enumerate(self._slots):
                if r is not None:
                    r.status, r.reason = "error", "engine_exception"
                    r.trace.t_finish = now
                    self.metrics.record_request(r)
                    self._slots[i] = None
                    self._pool.free(r.id)
                    self._clear_slot(i)
            # the failed call may have CONSUMED the donated pools — rebuild
            # so the engine stays usable (the padded engine's contract).
            # pool.reset() wiped the refcounts, so the prefix cache's
            # entries point at reissued blocks: drop them WITHOUT deref
            if self._prefix is not None:
                self._prefix.clear(release=False)
            self._pool.reset()
            self._pools = self._pool.make_pools()
            self.metrics.gauges["inflight"] = 0
            self.monitor.end_step(items=0)
            raise
        self.metrics.gauges["inflight"] = len(self._live())
        if ran:
            # gauges describe the step's micro-batch: fill = rows live at
            # decode-chunk entry (instant admission-finishes recycle one
            # slot sequentially, so cap admission-only steps at capacity);
            # occupancy is snapshotted at chunk entry too — the state the
            # step actually served, not the post-free emptiness
            n_real = len(live_entry) if live_entry else \
                min(len(finished), len(self._slots))
            kv_tokens, kv_slots, kv_shared = self._kv_snapshot
            self.metrics.record_batch(
                n_real=n_real, capacity=len(self._slots),
                kv_tokens=kv_tokens, kv_slots=kv_slots,
                kv_capacity=self._pool.capacity_tokens,
                queue_depth=len(self._queue),
                kv_shared_tokens=kv_shared)
        if self._spill is not None:
            if self._spill.spilled_total > spill0[0]:
                ran.add("spill")
            if self._spill.rehydrated_total > spill0[1]:
                ran.add("rehydrate")
        # compile accounting, same convention as the static engine: a miss
        # while every executable this step ran was already seen is shape
        # churn — log it through the r7 recompile detector
        dm = _jit_cache_misses() - miss0
        if dm:
            self.monitor.record_compile(
                "serving_batch", (("jit_cache_misses", dm),),
                prev_sig=(("jit_cache_misses", 0),)
                if ran and ran <= self._paged_seen else None)
        self._paged_seen |= ran
        self.monitor.end_step(items=out_tokens)
        return expired + finished

    def _clear_slot(self, slot: int):
        self._tables[slot] = 0         # trash block: writes go nowhere
        self._lens[slot] = 0
        self._pending[slot] = 0
        self._done[slot] = True
        self._shared_tok[slot] = 0
        self._prefill_pos[slot] = -1

    def _decodable(self) -> List[int]:
        """Live slots whose prefill completed — the decode batch. Rows
        still mid-(chunked-)prefill are excluded and neutralized in the
        shipped device state (`_ship_decode_state`)."""
        return [i for i in self._live() if self._prefill_pos[i] < 0]

    def _ship_decode_state(self):
        """The decode/verify-call view of the slot state: rows still
        mid-chunked-prefill ship a trash table row + done=True so the
        fixed-[B] call cannot write into (or attend) their in-progress
        blocks; their real host state is untouched."""
        pf = self._prefill_pos >= 0
        if not pf.any():
            return self._tables, self._lens, self._pending, self._done
        tables = self._tables.copy()
        tables[pf] = 0
        lens = self._lens.copy()
        lens[pf] = 0
        pending = self._pending.copy()
        pending[pf] = 0
        done = self._done.copy()
        done[pf] = True
        return tables, lens, pending, done

    def _kv_physical(self):
        """(physical live tokens, logical shared tokens) over live slots.

        Physical occupancy counts each DISTINCT block once (ISSUE 10
        satellite — summing per-slot lens would bill a shared prefix once
        per request): walk every live slot's owned blocks in position
        order, credit each block its live rows, and take the max where
        two slots map the same block (shared prefix blocks are full, so
        the max is just bs). Logical shared tokens = the per-slot
        shared-mapped volume summed — what the requests are READING out
        of blocks they did not allocate."""
        bs = self._pool.block_size
        rows: dict = {}
        shared = 0
        for s in self._live():
            ln = int(self._lens[s])
            shared += int(self._shared_tok[s])
            for j, blk in enumerate(self._pool.owned(self._slots[s].id)):
                r = min(max(ln - j * bs, 0), bs)
                if r == 0:
                    break
                rows[blk] = max(rows.get(blk, 0), r)
        return sum(rows.values()), shared

    def _snapshot_kv(self):
        phys, shared = self._kv_physical()
        self._kv_snapshot = (
            phys, self._pool.used_blocks * self._pool.block_size, shared)

    def _insert_prefix(self, req: Request, blocks, written: int,
                       tokens=None):
        """Cache the request's blocks whose KV is WRITTEN — the full
        blocks among positions [0, written). The partial tail keeps
        taking decode writes and is never shared; a block whose rows are
        not on device yet (the zero-prefill pending position) must not
        be cached either. Shared runs dedup against their own nodes.
        `tokens` defaults to the prompt; the spec-decode finish path
        passes the prompt + generated chain (ISSUE 11) so later
        identical traffic can zero-prefill AND prompt-lookup-draft its
        continuation from these blocks' token keys."""
        if self._prefix is None:
            return
        bs = self._pool.block_size
        toks = req.prompt if tokens is None else tokens
        n_full = min(int(written), len(toks)) // bs
        if n_full:
            self._prefix.insert(toks[:n_full * bs], blocks[:n_full])

    def warmup_prefix_cache(self, vocab_size: int, *, seed: int = 2,
                            clear: bool = True):
        """Compile the prefix-cache executable set before measuring: a
        full-prefill miss, an identical block-aligned repeat (the COW
        copy), and a mid-prefix divergence (suffix prefill), each run to
        completion so decode compiles too. With spec_decode the same
        choreography also lowers the verify executable — the repeated
        prompt's decode drafts the first run's cached chain from the
        trie — and with prefill_chunk the chunked-window executable
        replaces the one-shot prefill pair. `clear=True` then drops the
        warmup's cached prefixes so measured traffic starts cold. The
        shared choreography serve_bench / bench.py / graph_lint use —
        steady-state zero-recompile assertions are only meaningful after
        this whole set has lowered."""
        if self._prefix is None:
            raise ValueError("warmup_prefix_cache needs "
                             "ServingConfig(prefix_cache=True)")
        bs = self.config.kv_block
        aligned = (self.config.prompt_cap // bs) * bs
        if aligned < max(bs, 2):
            raise ValueError(f"prompt_cap {self.config.prompt_cap} holds "
                             f"no full kv_block ({bs}); nothing to warm")
        rng = np.random.RandomState(seed)
        p = rng.randint(1, vocab_size, (aligned,)).astype(np.int64)
        for prompt in (p, p):        # miss, then aligned full hit (COW)
            self.submit(prompt)
            self.drain()
        if aligned > bs:             # partial hit -> suffix prefill
            d = p.copy()
            d[bs:] = rng.randint(1, vocab_size, (aligned - bs,))
            self.submit(d)
            self.drain()
        if self._spill is not None:
            # spill + rehydrate leg: force every cached block through
            # the host tier and back so the stacked d2h gather and the
            # donated h2d scatter executables lower during warmup too —
            # the zero-post-warmup-miss assertions cover them
            self._prefix.evict(self._prefix.cached_blocks)
            self.submit(p)
            self.drain()
        if clear:
            self._prefix.clear()
        return self

    def _spill_write(self, blk: int, payload):
        """Rehydrate one spilled payload into pool block `blk`: the ONE
        host→device copy (the stacked payload ships as a single jit
        input) through the pool's donated scatter executable — the
        engine re-binds its pools because the call consumed them."""
        self._pools = self._pool.write_block(self._pools, blk, payload)

    def _cow_copy(self, src: int, dst: int):
        """Copy one pool block (every layer, K and V — codes AND scales
        in int8 mode) into a private block: the copy-on-write an aligned
        full-prefix hit needs before its re-decode of the last prompt
        token writes at position plen-1, INSIDE the last shared block.
        src/dst are data inputs of one tiny donated executable — steady
        COW traffic adds zero compilations."""
        import jax as _jax
        from ..distributed import mesh as _dist_mesh
        sig = ("paged_cow", self._pool.num_blocks, self._pool.block_size,
               self._pool.num_layers, str(self._pool.dtype),
               self._pool.cache_dtype, _dist_mesh.mesh_axis_size("mp"))

        def build():
            def run(pools, s, d):
                return _jax.tree_util.tree_map(
                    lambda p: p.at[d].set(p[s]), pools)
            return _jax.jit(run, donate_argnums=(0,))

        fn = self.model._gen_cache_get(sig, build)
        self._pools = fn(self._pools, np.int32(src), np.int32(dst))

    def _admit_paged(self):
        """Fill every free slot from the queue: consult the prefix trie,
        map shared blocks / allocate fresh ones, prefill what the cache
        does not already hold ([1, cap] — one fixed executable per mode),
        splice the row into the live decode batch. Returns (finished,
        expired, ran_tags) — a budget-1 or instant-EOS request can finish
        here without ever joining a decode chunk.

        Prefix-cache admission (ISSUE 10) splits three ways on the
        matched full-block token count t vs the prompt length plen:

          t == 0           full prefill, exactly the ISSUE-5 path;
          0 < t < plen-1   partial hit: prefill ONLY the suffix (start=t
                           suffix-prefill executable — attends across
                           the shared prefix blocks);
          t >= plen-1      zero-prefill hit: every prompt position except
                           the last already has pooled KV. The last
                           token re-enters as the decode `pending` token
                           (lens = plen-1), so TTFT is ONE decode step
                           and prefill runs on 0 tokens. When t == plen
                           (block-aligned full hit) that re-decode would
                           write INTO the last shared block — it is
                           copy-on-write'd into a private block first;
                           shared blocks are never mutated.

        Every admitted prompt's full blocks are inserted into the trie
        afterwards (dedup'd), so the NEXT identical prefix hits."""
        cfg = self.config
        bs = self._pool.block_size
        finished: List[Request] = []
        expired: List[Request] = []
        ran = set()
        free = [i for i, r in enumerate(self._slots) if r is None]
        while self._queue and free:
            now = self.clock()
            req = self._queue[0]
            if req.deadline_s is not None and \
                    now - req.trace.t_enqueue > req.deadline_s:
                self._queue.popleft()
                req.status, req.reason = "timeout", "queue_deadline"
                req.trace.t_finish = now
                self.metrics.record_request(req)
                expired.append(req)
                continue
            plen = req.prompt_len
            need_rows = plen + req.max_new_tokens - 1
            matched, t = ([], 0) if self._prefix is None \
                else self._prefix.match(req.prompt)
            # COW: an aligned full hit (t == plen) shares all matched
            # blocks EXCEPT the last, which is replaced by a private copy
            # (the re-decode write lands in it); otherwise the shared run
            # is the matched run and fresh blocks carry the suffix
            cow = t == plen and t > 0
            shared = matched[:-1] if cow else matched
            blocks = self._pool.alloc(req.id, need_rows, shared=shared)
            if blocks is None and self._prefix is not None:
                # cached-but-idle prefixes are SOFT capacity: evict LRU
                # refcount-free entries before deciding to wait —
                # protecting the whole matched run (`shared` plus the
                # COW source) from being reclaimed out from under this
                # very admission
                n_fresh = self._pool.blocks_needed(need_rows) - len(shared)
                if self._prefix.reclaim(n_fresh, protect=matched):
                    blocks = self._pool.alloc(req.id, need_rows,
                                              shared=shared)
                if blocks is None and not self._live():
                    # nothing in flight will ever free blocks, so waiting
                    # cannot help: a request that fits the pool alone
                    # (preflight's fits_ever) must not starve on its own
                    # protected cached prefix — drop the hit, reclaim
                    # freely, full-prefill
                    matched, t, cow, shared = [], 0, False, []
                    if self._prefix.reclaim(
                            self._pool.blocks_needed(need_rows)):
                        blocks = self._pool.alloc(req.id, need_rows)
            if blocks is None:
                # oversubscription wait: queued head outsizes the free
                # list. One structured row per EPISODE (ISSUE 18) — the
                # enter transition carries the flight-recorder trigger
                # key; steady-state waiting stays silent
                self._mem_pressure_enter(req, need_rows)
                break            # wait for live rows to free their blocks
            self._mem_pressure_exit()
            self._queue.popleft()
            slot = free.pop(0)
            req.status = "active"
            req.trace.t_admit = now
            req.trace.batch_id = self._batch_id
            # install into the slot BEFORE the device call: if prefill
            # dies mid-flight, _step_paged's handler finds the request
            # here and records it as status="error" — the engine's
            # in-flight accounting contract
            self._slots[slot] = req
            table_row = self._pool.table_row(req.id, self._tables.shape[1])
            self._tables[slot] = table_row
            self._shared_tok[slot] = len(shared) * bs
            # probe admissions (ISSUE 19) stay out of the cache-efficiency
            # counters: a prober's hit/miss variants are DESIGNED to
            # always hit / always miss, so counting them would turn the
            # fleet hit-rate and prefill-savings signals into artifacts
            # of the probe cadence
            if self._prefix is not None and not req.probe:
                self.metrics.counters[
                    "prefix_hit" if t else "prefix_miss"] += 1
            if t >= plen - 1 and t > 0:
                # zero-prefill admission: the whole prompt (minus the
                # re-decoded last token) is served from cached blocks
                if cow:
                    self._cow_copy(matched[-1], int(blocks[len(shared)]))
                    ran.add("cow")
                self._lens[slot] = plen - 1
                self._pending[slot] = int(req.prompt[plen - 1])
                self._done[slot] = False
                req._chunks = []
                req._produced = 0
                req.trace.t_prefill_done = now   # nothing to prefill
                if not req.probe:
                    self.metrics.counters["prefill_tokens_saved"] += \
                        plen - 1
                # re-stamp the matched chain; only positions < t hold
                # written KV here (the pending re-decode hasn't run), so
                # the insert must not cache any fresh block yet
                self._insert_prefix(req, blocks, t)
            elif cfg.prefill_chunk is not None:
                # chunked prefill (ISSUE 11 satellite): admission only
                # installs the slot — _advance_prefill runs one
                # [1, prefill_chunk] window per engine step from position
                # t, so a cap-length prompt costs cap/chunk STEPS of
                # bounded work instead of one monopolizing call, and the
                # decode batch keeps stepping between windows. The slot's
                # decode state stays neutral (lens 0 / done) until the
                # final window samples the first token.
                self._prefill_pos[slot] = t
                req._chunks = []
                req._produced = 0
                if t and not req.probe:
                    self.metrics.counters["prefill_tokens_saved"] += t
            else:
                suffix = plen - t
                ids = np.full((1, cfg.prompt_cap), cfg.pad_token_id,
                              dtype=np.int64)
                ids[0, :suffix] = req.prompt[t:]
                start = None if t == 0 else np.asarray([t], np.int32)  # lint: allow(tracer-asarray)
                t_pf0 = self.clock()
                with jax.profiler.TraceAnnotation("serving/prefill"):
                    self._pools, first = self.model.prefill_paged(
                        ids, np.asarray([suffix], np.int32),  # lint: allow(tracer-asarray)
                        self._pools, table_row[None],
                        temperature=cfg.temperature, top_k=cfg.top_k,
                        top_p=cfg.top_p, seed=cfg.seed + self._calls,
                        weight_dtype=cfg.weight_dtype,
                        cache_dtype=cfg.cache_dtype, start=start)
                    tok = int(np.asarray(first.numpy())[0])  # lint: allow(tracer-asarray)
                self._calls += 1
                ran.add("prefill" if t == 0 else "prefix_prefill")
                req.trace.events.append(
                    ("prefill" if t == 0 else "suffix_prefill",
                     t_pf0, self.clock()))
                if t and not req.probe:
                    self.metrics.counters["prefill_tokens_saved"] += t
                if self._complete_prefill(slot, req, tok, self.clock()):
                    finished.append(req)
                    free.insert(0, slot)
            self._batch_id += 1
        if not self._queue:
            # waiting head left some other way (deadline expiry, error
            # recovery draining the queue): close the episode truthfully
            self._mem_pressure_exit()
        self.metrics.gauges["queue_depth"] = len(self._queue)
        if ran:
            # admission-only steps (budget-1 / instant-EOS traffic) still
            # report the post-admission pool state; a following decode
            # chunk overwrites this with its own entry snapshot
            self._snapshot_kv()
        return finished, expired, ran

    def _decode_chunk_paged(self, live: List[int]):
        """One fixed-shape decode chunk over the whole slot batch (dummy
        rows write the trash block and are ignored); finish + free every
        row that hit EOS or its budget. Returns (finished, real tokens)."""
        cfg = self.config
        c = cfg.decode_chunk
        self._snapshot_kv()
        tables, lens, pending, done = self._ship_decode_state()
        t_c0 = self.clock()
        with jax.profiler.TraceAnnotation("serving/decode"):
            toks, self._pools, _, done_d = self.model.decode_paged(
                self._pools, tables, lens, pending,
                done, c, temperature=cfg.temperature,
                top_k=cfg.top_k, top_p=cfg.top_p,
                seed=cfg.seed + self._calls,
                eos_token_id=cfg.eos_token_id,
                weight_dtype=cfg.weight_dtype,
                cache_dtype=cfg.cache_dtype)
            arr = np.asarray(toks.numpy())          # host sync per chunk  # lint: allow(tracer-asarray)
        self._calls += 1
        t = self.clock()
        pend_new = arr[:, -1].astype(np.int32)
        done_new = np.array(done_d)        # copy: slot edits need a
        #                                    writable host array
        pf = self._prefill_pos >= 0        # mid-prefill rows rode as
        pend_new[pf] = self._pending[pf]   # neutralized dummies — their
        done_new[pf] = self._done[pf]      # real state must survive
        self._pending = pend_new
        self._done = done_new
        finished: List[Request] = []
        out_tokens = 0
        for slot in live:
            req = self._slots[slot]
            req.trace.events.append(("decode", t_c0, t))
            take = min(c, req.max_new_tokens - req._produced)
            req._chunks.append(arr[slot, :take])
            req._produced += take
            out_tokens += take
            if req.trace.t_first_token is None:
                # zero-prefill admission (prefix cache): this chunk's
                # first token IS the request's first token — TTFT was
                # one decode step, measured not estimated
                req.trace.t_first_token = t
            self._lens[slot] += c     # device wrote c rows regardless
            # EOS scan covers only the FRESH slice: earlier chunks were
            # checked when they landed (an EOS there already finished the
            # row), so the per-generation host cost stays O(n)
            row_done = req._produced >= req.max_new_tokens or \
                _hit_eos(arr[slot, :take], cfg.eos_token_id)
            if row_done:
                self._finish_paged_row(slot, t)
                finished.append(req)
        return finished, out_tokens

    def _advance_prefill(self):
        """One [1, prefill_chunk] prefill window for every slot mid-
        chunked-prefill (ISSUE 11 satellite). The window offset is DATA
        through the start-form prefill executable, so ONE [1, chunk]
        program serves every (offset, remainder) of every prompt length
        — zero new executables however prompts are sized. The final
        window's sampled token is the request's first token (its last
        real column is the prompt's last token) and the row joins the
        next decode chunk. Returns (finished, ran_tags) — a budget-1 /
        instant-EOS request can finish the moment its prefill lands."""
        cfg = self.config
        finished: List[Request] = []
        ran = set()
        if cfg.prefill_chunk is None:
            return finished, ran
        pc = cfg.prefill_chunk
        for slot in self._live():
            off = int(self._prefill_pos[slot])
            if off < 0:
                continue
            req = self._slots[slot]
            plen = req.prompt_len
            clen = min(pc, plen - off)
            final = off + clen >= plen
            ids = np.full((1, pc), cfg.pad_token_id, dtype=np.int64)
            ids[0, :clen] = req.prompt[off:off + clen]
            t_pf0 = self.clock()
            with jax.profiler.TraceAnnotation("serving/prefill"):
                self._pools, first = self.model.prefill_paged(
                    ids, np.asarray([clen], np.int32),  # lint: allow(tracer-asarray)
                    self._pools, self._tables[slot][None],
                    temperature=cfg.temperature, top_k=cfg.top_k,
                    top_p=cfg.top_p, seed=cfg.seed + self._calls,
                    weight_dtype=cfg.weight_dtype,
                    cache_dtype=cfg.cache_dtype,
                    start=np.asarray([off], np.int32))  # lint: allow(tracer-asarray)
                # only the FINAL window's sampled token is meaningful —
                # syncing the intermediate ones would serialize every
                # window on a host round-trip for a value that gets
                # discarded (exactly the long-prompt stall chunked
                # prefill exists to remove)
                tok = int(np.asarray(first.numpy())[0]) if final else 0  # lint: allow(tracer-asarray)
            self._calls += 1
            ran.add("prefill_chunk")
            req.trace.events.append(("prefill_chunk", t_pf0,
                                     self.clock()))
            off += clen
            if not final:
                self._prefill_pos[slot] = off
                continue
            # prefill complete: the slot becomes a decode row
            self._prefill_pos[slot] = -1
            if self._complete_prefill(slot, req, tok, self.clock()):
                finished.append(req)
        return finished, ran

    def _complete_prefill(self, slot: int, req: Request, tok: int,
                          tp: float) -> bool:
        """Shared prefill-completion bookkeeping (one-shot admission AND
        the final chunked-prefill window): the sampled token becomes the
        row's pending/first token, the prompt's full blocks enter the
        trie, and a budget-1 / instant-EOS request finishes on the spot.
        Returns True when the request instant-finished (the slot is free
        again)."""
        cfg = self.config
        plen = req.prompt_len
        req.trace.t_prefill_done = tp
        req.trace.t_first_token = tp  # sampled with the prefill
        self._lens[slot] = plen
        self._pending[slot] = tok
        hit_eos = (cfg.eos_token_id is not None
                   and tok == cfg.eos_token_id)
        self._done[slot] = hit_eos
        req._chunks = [np.asarray([tok], np.int64)]  # lint: allow(tracer-asarray)
        req._produced = 1
        # insert BEFORE any instant finish: the cache's retain must land
        # while the request still holds its blocks (finishing frees the
        # owner's references)
        self._insert_prefix(req, self._pool.owned(req.id), plen)
        if req._produced >= req.max_new_tokens or hit_eos:
            self._finish_paged_row(slot, tp)
            return True
        return False

    def _draft_context(self, req: Request):
        """The slot's draft context — prompt plus every emitted token
        (the pending token INCLUDED, since drafts continue after it) —
        maintained INCREMENTALLY: chunks land once each, so per-window
        host cost is O(new tokens), not O(history) re-concatenation."""
        ctx = getattr(req, "_ctx", None)
        if ctx is None:
            ctx = req._ctx = [int(t) for t in req.prompt]
            req._ctx_chunks = 0
        for c in req._chunks[req._ctx_chunks:]:
            ctx.extend(int(t) for t in c)
        req._ctx_chunks = len(req._chunks)
        return ctx

    def _draft_for_slot(self, slot: int):
        """Up to spec_k draft tokens for the slot's next positions + the
        source tag ("trie" | "model" | None)."""
        cfg = self.config
        req = self._slots[slot]
        context = self._draft_context(req)
        if self._prefix is not None:
            d = self._prefix.lookup_continuation(context, cfg.spec_k)
            if d:
                return np.asarray(d, np.int32), "trie"  # lint: allow(tracer-asarray)
        if self._draft_fn is not None:
            d = np.asarray(self._draft_fn(context,  # lint: allow(tracer-asarray)
                                          cfg.spec_k)).reshape(-1)
            if d.size:
                return d[:cfg.spec_k].astype(np.int32), "model"
        return None, None

    def _decode_chunk_spec(self, live: List[int]):
        """One speculative verify window over the slot batch (ISSUE 11):
        a fixed-shape [B, spec_k + 1] call through model.verify_paged.
        Rows with a draft advance by their accepted length + 1; rows
        without one ride along on pad drafts and advance by >= 1 (a pad
        column that happens to match the chain is a REAL acceptance —
        every emitted token is argmax-correct by construction). Steps
        where NO row has a draft fall back to the plain decode chunk —
        both executables are in the warm set, so the per-step choice is
        host data, never a compile. Returns (finished, real tokens,
        ran_tags)."""
        cfg = self.config
        B = len(self._slots)
        drafts = np.full((B, cfg.spec_k), cfg.pad_token_id, np.int32)
        src = {}
        for slot in live:
            d, tag = self._draft_for_slot(slot)
            if d is not None:
                drafts[slot, :len(d)] = d
                src[slot] = (tag, len(d))
        if not src:
            finished, out_tokens = self._decode_chunk_paged(live)
            return finished, out_tokens, {"decode"}
        self._snapshot_kv()
        tables, lens, pending, done = self._ship_decode_state()
        t_c0 = self.clock()
        with jax.profiler.TraceAnnotation("serving/decode"):
            toks, n_acc, self._pools, done_d = self.model.verify_paged(
                self._pools, tables, lens, pending, drafts, done,
                eos_token_id=cfg.eos_token_id,
                weight_dtype=cfg.weight_dtype,
                cache_dtype=cfg.cache_dtype)
            arr = np.asarray(toks.numpy())          # host sync per window  # lint: allow(tracer-asarray)
            acc = np.asarray(n_acc)  # lint: allow(tracer-asarray)
        self._calls += 1
        t = self.clock()
        done_new = np.array(done_d)
        finished: List[Request] = []
        out_tokens = 0
        mt = self.metrics
        for slot in live:
            req = self._slots[slot]
            req.trace.events.append(("spec_verify", t_c0, t))
            n_emit = int(acc[slot]) + 1
            take = min(n_emit, req.max_new_tokens - req._produced)
            fresh = arr[slot, :take]
            req._chunks.append(fresh)
            req._produced += take
            out_tokens += take
            if req.trace.t_first_token is None:
                # zero-prefill admission: this window's first token IS
                # the request's first token
                req.trace.t_first_token = t
            self._lens[slot] += n_emit   # the accepted frontier
            self._pending[slot] = np.int32(arr[slot, n_emit - 1])
            self._done[slot] = bool(done_new[slot])  # lint: allow(tracer-bool)
            if slot in src:
                # acceptance accounting covers DRAFTED rows only and
                # REAL draft tokens only: a short trie draft's pad
                # filler counts neither as proposed nor (if a pad
                # accidentally matches) as accepted. A budget-truncated
                # final window credits only the accepted drafts it
                # actually EMITTED, so sum over windows ties out against
                # speculative tokens out and the rate stays honest on
                # short-budget / block-granular-draft traffic.
                tag, dlen = src[slot]
                used = min(int(acc[slot]), take, dlen)
                req.spec_proposed += dlen
                req.spec_accepted += used
                if not req.probe:   # probe windows would skew the
                    #                 acceptance-rate signal (ISSUE 19)
                    mt.counters["spec_windows"] += 1
                    mt.counters["spec_proposed"] += dlen
                    mt.counters["spec_accepted"] += used
                    mt.counters["spec_drafts_trie" if tag == "trie"
                                else "spec_drafts_model"] += 1
                    mt.hists["spec_accept_len"].observe(take)
            row_done = req._produced >= req.max_new_tokens or \
                _hit_eos(fresh, cfg.eos_token_id)
            if row_done:
                self._finish_paged_row(slot, t)
                finished.append(req)
        return finished, out_tokens, {"spec_verify"}

    def _finish_paged_row(self, slot: int, t: float):
        """Terminal bookkeeping for one slot: blocks free IMMEDIATELY (the
        next _admit_paged can splice a queued request into this slot
        mid-flight — no waiting for the batch to drain)."""
        req = self._slots[slot]
        row = np.concatenate(req._chunks)[:req.max_new_tokens]
        req.tokens = row.astype(np.int64)
        req.n_out = _n_out(req.tokens, self.config.eos_token_id)
        req.status = "done"
        req.trace.t_finish = t
        if self.config.spec_decode and self._prefix is not None:
            # cache the WRITTEN chain (prompt + generated minus the
            # never-written last token), not just the prompt: the next
            # identical request then zero-prefills the whole history AND
            # prompt-lookup-drafts its continuation from these blocks'
            # token keys — the agentic/retry free lunch. Insert BEFORE
            # free: the trie's retain must land while the request still
            # holds its block references.
            chain = np.concatenate([req.prompt,
                                    req.tokens[:req.n_out]])
            self._insert_prefix(req, self._pool.owned(req.id),
                                req.prompt_len + req._produced - 1,
                                tokens=chain)
        self._pool.free(req.id)
        self._slots[slot] = None
        self._clear_slot(slot)
        self.metrics.record_request(req)

    @property
    def draining(self) -> bool:
        return self._draining

    def begin_drain(self):
        """Enter graceful-drain mode: submit() refuses new work with a
        structured "draining" rejection while queued + in-flight requests
        keep being served. The shutdown handshake of a preemptible
        serving fleet: SIGTERM → begin_drain() → drain(seal=True) →
        exit — in-flight users finish, the load balancer sees refusals
        and moves on."""
        self._draining = True
        return self

    def resume_admission(self):
        """Leave drain mode (a cancelled shutdown)."""
        self._draining = False
        return self

    def drain(self, max_batches: Optional[int] = None,
              seal: bool = False) -> List[Request]:
        """step() until the queue empties and every live slot finishes
        (or max_batches). `seal=True` is the graceful-shutdown form: stop
        admitting first (begin_drain), and flush the metrics gauges +
        emit the terminal summary row once empty — the engine then
        refuses traffic until resume_admission()."""
        if seal:
            self.begin_drain()
        out: List[Request] = []
        n = 0
        while self.busy:
            if max_batches is not None and n >= max_batches:
                break
            got = self.step()
            n += 1
            if not got and not self.busy:
                break
            out.extend(got)
        if seal:
            if not self.busy:
                self.metrics.flush()
            else:
                # bounded drain ran out of batches with work remaining:
                # the seal did NOT complete — no terminal flush, gauges
                # still live. Say so instead of returning as if the
                # shutdown handshake finished.
                _logger.warning(
                    "drain(seal=True) hit max_batches=%s with work "
                    "remaining (queue+slots still busy): terminal "
                    "metrics flush skipped, engine left in drain mode — "
                    "call drain() again to finish", max_batches)
        return out

    # -- reporting ------------------------------------------------------
    def summary(self) -> dict:
        s = self.metrics.summary()
        s["batch_step"] = self.monitor.report()
        return s

    def metrics_text(self, prefix: str = "paddle_tpu_serving") -> str:
        """The full /metrics payload: request metrics + the engine's batch
        StepMonitor block (steady tokens/s, recompile counters)."""
        return self.metrics.metrics_text(prefix=prefix) + \
            self.monitor.metrics_text(prefix=f"{prefix}_batch")

    # -- ops surface (ISSUE 12) -----------------------------------------
    def health(self) -> dict:
        """The /healthz payload — exactly the autoscaler/router inputs
        the r12 load-shedding work named: drain state, queue depth vs its
        shed thresholds, inflight rows, and the overloaded counter. Pure
        host-side reads; safe from any thread at scrape rate."""
        cfg, m = self.config, self.metrics
        inflight = len(self._live()) if cfg.paged \
            else m.gauges["inflight"]
        return {"status": "draining" if self._draining else "ok",
                "draining": self._draining,
                "queue_depth": len(self._queue),
                "queue_capacity": cfg.queue_capacity,
                "queue_high_watermark": cfg.queue_high_watermark,
                "inflight": inflight,
                "overloaded_total": m.counters["overloaded"],
                "rejected_total": m.counters["rejected"],
                # goodput inputs (ISSUE 14): the autoscale controller
                # derives completed/requests deltas per tick from here
                "requests_total": m.counters["requests"],
                "completed_total": m.counters["completed"],
                "kv_occupancy": m.gauges["kv_occupancy"]}

    def fingerprint(self) -> dict:
        """Deterministic config/build identity (ISSUE 19): the key
        goldens are minted under and the value fleet drift detection
        compares. Cached — model config, ServingConfig, jax versions
        and PADDLE_TPU_* env are all process-stable."""
        if self._fingerprint is None:
            from ..obs.probez import config_fingerprint
            self._fingerprint = config_fingerprint(self.model.config,
                                                   self.config)
        return self._fingerprint

    def statusz(self) -> dict:
        """The /statusz payload: engine identity + config envelope,
        compile/recompile accounting, KV/prefix-cache occupancy, the
        config/build fingerprint, and the full counter/gauge snapshot —
        the page a human (or a fleet inventory) reads to understand
        WHAT this replica is."""
        out = {"engine": {"run_id": self._run_id,
                          "uptime_s": round(self.clock() - self._t_start,
                                            3),
                          "draining": self._draining,
                          "paged": self.config.paged,
                          "requests_submitted": self._next_id,
                          "batches": self._batch_id},
               "config": {k: (v if isinstance(v, (int, float, str, bool,
                                                  type(None)))
                              else repr(v))
                          for k, v in vars(self.config).items()},
               "compile": {"compiles": self.monitor.compiles,
                           "recompiles": self.monitor.recompiles,
                           "jit_cache_misses": _jit_cache_misses()},
               "fingerprint": self.fingerprint(),
               "counters": dict(self.metrics.counters),
               "gauges": dict(self.metrics.gauges)}
        if self.config.paged:
            pool = self._pool
            kv_tokens, kv_slots, kv_shared = self._kv_snapshot
            out["kv"] = {"blocks_total": pool.num_blocks,
                         "block_size": pool.block_size,
                         "used_blocks": pool.used_blocks,
                         "capacity_tokens": pool.capacity_tokens,
                         "live_tokens": kv_tokens,
                         "slot_tokens": kv_slots,
                         "shared_tokens": kv_shared,
                         "cache_dtype": pool.cache_dtype}
            if self._prefix is not None:
                out["prefix_cache"] = {
                    "cached_blocks": self._prefix.cached_blocks,
                    "cached_bytes": self._prefix.cached_bytes,
                    "spilled_blocks": self._prefix.spilled_blocks,
                    "byte_budget": self._prefix.byte_budget}
            if self._spill is not None:
                out["spill"] = self._spill.stats()
        if self._memz is not None:
            # one curl shows compute, KV, and memory state together
            # (ISSUE 18 satellite): ledger summary + spill occupancy
            out["memory"] = self._memz.statusz_block()
        return out

    # -- HBM ledger (ISSUE 18) ------------------------------------------
    def attach_memory_ledger(self, ledger=None):
        """Wire a MemoryLedger to this engine's owners and return it.

        Registers reader-backed owners over accounting the engine already
        keeps host-side (a ledger read must never sync — pinned like
        every other scrape):

          model_params   named-parameter buffer bytes (live device copy)
          kv_pool        the pool's full reservation (num_blocks ×
                         bytes_per_block — the allocator-granularity
                         truth; `used_bytes` rides as detail) with shard
                         geometry in meta
          prefix_cache   retained-block bytes, an OVERLAY — those blocks
                         live inside kv_pool's reservation, reported but
                         never double-counted in the conservation sum
          spill_host     host-RAM tier (device=False: never summed
                         against HBM)

        The pool's `on_change` observer re-samples the pool/cache owners
        on every alloc/free/COW so the delta ring is a faithful growth
        curve; ledger rows (headroom_low, post-mortems) ride the metrics'
        structured-row stream, which is what the flight recorder taps."""
        if ledger is None:
            from ..obs.memz import MemoryLedger
            ledger = MemoryLedger()
        self._memz = ledger

        def _params_bytes():
            return int(sum(p._data.nbytes
                           for _, p in self.model.named_parameters()))
        ledger.register("model_params", _params_bytes, kind="params",
                        replace=True)
        if self.config.paged:
            pool = self._pool
            shards = int(self.config.shards or 1)

            def _pool_bytes():
                bpb = pool.bytes_per_block
                return {"bytes": pool.num_blocks * bpb,
                        "used_bytes": pool.used_blocks * bpb,
                        "used_blocks": pool.used_blocks,
                        "free_blocks": pool.free_blocks}
            ledger.register("kv_pool", _pool_bytes, kind="kv",
                            meta={"shards": shards,
                                  "block_size": pool.block_size,
                                  "num_blocks": pool.num_blocks},
                            replace=True)
            pool.on_change = lambda: ledger.sample("kv_pool",
                                                   "prefix_cache")
            if self._prefix is not None:
                prefix = self._prefix
                ledger.register(
                    "prefix_cache",
                    lambda: {"bytes": prefix.cached_bytes,
                             "cached_blocks": prefix.cached_blocks,
                             "spilled_blocks": prefix.spilled_blocks},
                    kind="kv", overlay=True, replace=True)
            if self._spill is not None:
                spill = self._spill
                ledger.register("spill_host",
                                lambda: int(spill.host_bytes),
                                kind="spill", device=False, replace=True)
        if ledger.on_row is None:
            ledger.on_row = self.metrics._emit
        # the StepMonitor's per-record memory sample reads the ledger's
        # free host counters instead of rationing live-array scans
        self.monitor.memz = ledger
        ledger.sample()
        return ledger

    def _mem_pressure_enter(self, req, need_rows: int):
        if self._mem_pressure_t0 is not None:
            return                       # already inside the episode
        self._mem_pressure_t0 = self.clock()
        body = {"request": req.id, "need_rows": int(need_rows),
                "free_blocks": self._pool.free_blocks,
                "used_blocks": self._pool.used_blocks,
                "queue_depth": len(self._queue)}
        if self._memz is not None:
            body["top_owners"] = self._memz.top_owners(3)
        self.metrics._emit({"mem_pressure": body, "ts": time.time()})
        self.metrics.counters["mem_pressure_episodes"] += 1

    def _mem_pressure_exit(self):
        if self._mem_pressure_t0 is None:
            return
        waited = self.clock() - self._mem_pressure_t0
        self._mem_pressure_t0 = None
        # *_clear key: inert on the flight-recorder trigger bus by the
        # transition-rows-only convention
        self.metrics._emit({"mem_pressure_clear":
                            {"waited_s": round(waited, 6),
                             "free_blocks": self._pool.free_blocks},
                            "ts": time.time()})

    def metrics_registry(self, prefix: str = "paddle_tpu_serving"):
        """The engine's exposition producers composed through the
        collision-checked obs.MetricsRegistry — the /metrics source
        `serve_telemetry` scrapes (callers add more producers: an SLO
        monitor, a co-hosted training monitor, ...)."""
        from ..obs import MetricsRegistry
        reg = MetricsRegistry()
        reg.register("serving",
                     lambda: self.metrics.metrics_text(prefix=prefix))
        reg.register("serving_batch",
                     lambda: self.monitor.metrics_text(
                         prefix=f"{prefix}_batch"))
        if self._spill is not None:
            # the spill tier's counters ride the same registry (ISSUE
            # 14): one scrape shows blocks spilled/rehydrated next to
            # the request metrics they are saving prefill for
            reg.register("spill",
                         lambda: self._spill.metrics_text(
                             prefix=f"{prefix}_spill"))
        if self._memz is not None:
            # hbm_bytes{owner=...} / hbm_headroom_bytes (ISSUE 18): the
            # gauges the SLO/flight-recorder machinery consumes
            reg.register("memz",
                         lambda: self._memz.metrics_text(
                             prefix="paddle_tpu"))
        if self._prober is not None:
            # probe_* families (ISSUE 19) — separate producers, so an
            # exposition without a prober is byte-identical by
            # construction (the probe/SLO isolation guarantee)
            reg.register("probe", self._prober.metrics_text)
            reg.register("probe_serving", self.metrics.probe_metrics_text)
        if self._invariants is not None:
            reg.register("invariant", self._invariants.metrics_text)
        return reg

    def serve_telemetry(self, *, host: str = "127.0.0.1", port: int = 0,
                        slo=None, poll_interval: Optional[float] = None,
                        registry=None, trace_capacity: int = 256,
                        flightrec=None, prober=None,
                        probe_interval: Optional[float] = None,
                        invariant_interval: Optional[float] = None):
        """Boot the replica's ops surface: a started obs.TelemetryServer
        wired to this engine — /metrics from `metrics_registry()` (+ the
        SLO monitor's burn gauges when one is passed), /healthz from
        `health()`, /statusz from `statusz()`, /tracez from the metrics'
        tail-sampling TraceBuffer (created and attached here when the
        metrics don't carry one yet), /memz from the HBM ledger (ISSUE
        18 — `attach_memory_ledger()` runs here when none is attached
        yet). Returns the server; `.close()` it on shutdown.

        `slo` is an obs.SLOMonitor or a parse_slo spec string
        ("ttft_p99=500ms,goodput=0.95" — built over this engine's
        metrics). With `poll_interval` (seconds) the SERVER owns the
        burn-rate cadence: a timer thread drives slo.poll() for the
        server's lifetime, so alerts fire without any external driver
        and the thread shuts down with the server (the r15 NOTE
        follow-up). The monitor rides `srv.slo` for introspection.

        `flightrec` is an obs.FlightRecorder (ISSUE 17): it attaches to
        this engine's StepMonitor (captures advance at the engine's
        device-call brackets), taps the SLO monitor's alert transitions
        and the metrics' structured rows as capture triggers, exports
        its counters on /metrics, and mounts the /profilez route. It
        rides `srv.flightrec`; detaching at shutdown stays with the
        caller (`flightrec.detach()`).

        `prober` is an obs.Prober (ISSUE 19) or True to build one over
        this engine; it mounts /probez, exports the probe_* families,
        and with `probe_interval` the server drives golden-canary
        cycles on a poller thread. `invariant_interval` schedules the
        deep InvariantAuditor audits (paged engines) the same way —
        both pollers hold the prober's lock; an external step-loop
        thread must share it (`srv.prober.lock`), per the engine's
        one-lock threading contract."""
        from ..obs import (InvariantAuditor, Prober, SLOMonitor,
                           TelemetryServer, TraceBuffer)
        if self.metrics.trace_buffer is None:
            self.metrics.trace_buffer = TraceBuffer(trace_capacity)
        if self._memz is None:
            # every served replica gets the HBM ledger (ISSUE 18): /memz,
            # the hbm_* gauges and the OOM post-mortem come up with the
            # ops surface unless the caller attached their own
            self.attach_memory_ledger()
        if prober is True:
            prober = Prober(self)
        if prober is not None:
            self._prober = prober
        if self.config.paged and (prober is not None or
                                  invariant_interval is not None):
            auditor = InvariantAuditor(
                self, lock=prober.lock if prober is not None else None)
            self._invariants = auditor
            if prober is not None:
                prober.auditor = auditor
        elif invariant_interval is not None:
            raise ValueError("invariant_interval needs a paged engine "
                             "(the audits walk the block pool)")
        reg = registry if registry is not None else self.metrics_registry()
        if isinstance(slo, str):
            slo = SLOMonitor(slo, self.metrics)
        if slo is not None:
            reg.register("slo", slo.metrics_text)
        elif poll_interval is not None:
            raise ValueError("poll_interval needs an slo monitor/spec "
                             "to poll")
        routes = {"/memz": self._memz.memz}
        if prober is not None:
            routes["/probez"] = prober.probez
        if flightrec is not None:
            # monitor: step brackets + straggler/recompile/numerics rows;
            # metrics: every structured row INCLUDING slo_alert (the SLO
            # monitor emits through metrics._emit — tapping on_alert too
            # would double-count each alert on the trigger bus)
            flightrec.attach(monitor=self.monitor, metrics=self.metrics)
            reg.register("flightrec", flightrec.metrics_text)
            routes["/profilez"] = flightrec.profilez
        srv = TelemetryServer(reg, host=host, port=port,
                              health=self.health, status=self.statusz,
                              tracez=self.metrics.trace_buffer,
                              routes=routes)
        srv.slo = slo
        srv.flightrec = flightrec
        srv.prober = prober
        srv.invariants = self._invariants
        if slo is not None and poll_interval is not None:
            srv.add_poller(slo.poll, poll_interval, name="slo")
        if prober is not None and probe_interval is not None:
            srv.add_poller(prober.probe_once, probe_interval,
                           name="probe")
        if self._invariants is not None and \
                invariant_interval is not None:
            srv.add_poller(self._invariants.audit, invariant_interval,
                           name="invariants")
        return srv.start()


def _hit_eos(row: np.ndarray, eos: Optional[int]) -> bool:
    return eos is not None and bool((row == eos).any())  # lint: allow(tracer-bool)


def _n_out(row: np.ndarray, eos: Optional[int]) -> int:
    """Tokens a row really produced: up to and including the first EOS."""
    if eos is None:
        return int(row.shape[0])
    hits = np.nonzero(row == eos)[0]
    return int(hits[0]) + 1 if hits.size else int(row.shape[0])


def synthetic_traffic(n_requests: int, *, prompt_cap: int, vocab_size: int,
                      rate: float = 50.0, seed: int = 0,
                      min_len: int = 1,
                      length_dist: str = "uniform") -> List[dict]:
    """Open-loop synthetic workload: Poisson arrivals at `rate` req/s,
    ragged prompt lengths in [min_len, prompt_cap]. Returns
    [{"at": arrival_offset_s, "prompt": ids}] sorted by arrival — shared
    by examples/serve_gpt.py and tools/serve_bench.py.

    length_dist:
      "uniform"  — lengths uniform over [min_len, prompt_cap];
      "longtail" — Pareto-shaped (alpha≈1.1) lengths clipped to the cap:
                   mostly-short traffic with a heavy tail of cap-length
                   prompts, the mix where right-padding wastes the most
                   HBM and the paged pool shows its gap (serve_bench's
                   padded-vs-paged comparison profile)."""
    if length_dist not in ("uniform", "longtail"):
        raise ValueError(f"unknown length_dist {length_dist!r}")
    rng = np.random.RandomState(seed)
    gaps = rng.exponential(1.0 / max(rate, 1e-9), size=n_requests)
    at = np.cumsum(gaps) - gaps[0]
    out = []
    for i in range(n_requests):
        if length_dist == "longtail":
            ln = min(prompt_cap, min_len + int(rng.pareto(1.1) * min_len))
        else:
            ln = int(rng.randint(min_len, prompt_cap + 1))
        out.append({"at": float(at[i]),  # lint: allow(tracer-float)
                    "prompt": rng.randint(1, vocab_size,
                                          (ln,)).astype(np.int64)})
    return out


def shared_prefix_traffic(n_requests: int, *, n_prefixes: int,
                          prefix_len: int, prompt_cap: int,
                          vocab_size: int, rate: float = 50.0,
                          seed: int = 0) -> List[dict]:
    """System-prompt workload (ISSUE 10): every request draws one of
    `n_prefixes` FIXED token prefixes (`prefix_len` tokens — the "system
    prompt") followed by a fresh random suffix, with Poisson arrivals at
    `rate` req/s. The traffic shape prefix caching exists for: after each
    prefix's first request, every later request sharing it should admit
    with only its suffix prefilled. Returns [{"at", "prompt",
    "prefix_id"}] sorted by arrival — serve_bench's --shared-prefix
    profile and the bench decode-paged-prefix row replay this."""
    if not (1 <= prefix_len < prompt_cap):
        raise ValueError(f"prefix_len must be in [1, prompt_cap), got "
                         f"{prefix_len} vs cap {prompt_cap}")
    if n_prefixes < 1:
        raise ValueError(f"n_prefixes must be >= 1, got {n_prefixes}")
    rng = np.random.RandomState(seed)
    prefixes = rng.randint(1, vocab_size,
                           (n_prefixes, prefix_len)).astype(np.int64)
    gaps = rng.exponential(1.0 / max(rate, 1e-9), size=n_requests)
    at = np.cumsum(gaps) - gaps[0]
    out = []
    for i in range(n_requests):
        p = int(rng.randint(0, n_prefixes))
        ln = int(rng.randint(1, prompt_cap - prefix_len + 1))
        suffix = rng.randint(1, vocab_size, (ln,)).astype(np.int64)
        out.append({"at": float(at[i]),  # lint: allow(tracer-float)
                    "prompt": np.concatenate([prefixes[p], suffix]),
                    "prefix_id": p})
    return out


def repeated_traffic(n_requests: int, *, n_prompts: int, prompt_len: int,
                     vocab_size: int, rate: float = 50.0,
                     seed: int = 0) -> List[dict]:
    """Agentic / retry workload (ISSUE 11): every request is one of
    `n_prompts` FIXED prompts repeated VERBATIM, Poisson arrivals at
    `rate` req/s. The degenerate shared-prefix shape (suffix shared too)
    — and the one where speculative prompt-lookup drafting pays in full:
    after each prompt's first completion, every later identical request
    zero-prefills its KV from the trie AND drafts its entire greedy
    continuation from the cached chain, so verify windows accept
    end-to-end. Returns [{"at", "prompt", "prompt_id"}] sorted by
    arrival — the bench decode-spec row and serve_bench --repeat replay
    this."""
    if n_prompts < 1 or prompt_len < 1:
        raise ValueError(f"need n_prompts >= 1 and prompt_len >= 1, got "
                         f"{n_prompts}, {prompt_len}")
    rng = np.random.RandomState(seed)
    prompts = rng.randint(1, vocab_size,
                          (n_prompts, prompt_len)).astype(np.int64)
    gaps = rng.exponential(1.0 / max(rate, 1e-9), size=n_requests)
    at = np.cumsum(gaps) - gaps[0]
    out = []
    for i in range(n_requests):
        p = int(rng.randint(0, n_prompts))
        out.append({"at": float(at[i]),  # lint: allow(tracer-float)
                    "prompt": prompts[p].copy(), "prompt_id": p})
    return out


def model_draft_fn(draft_model, *, window: int = 32):
    """Adapter turning a (small) GPTForCausalLM into a speculative draft
    source for ``ServingConfig(spec_draft=...)`` (ISSUE 11).

    The returned callable greedily continues the last ``window`` context
    tokens through ``draft_model.generate_static_ragged`` — fixed
    [1, window] shape, ragged length as data, so ONE draft executable
    per spec_k serves every request at every depth (it compiles on the
    first draft call; include a drafted request in warmup before
    asserting zero steady-state misses). Each call pays a full
    window-prefill in the draft model: cheap when the drafter is 10-50x
    smaller than the target, which is the configuration speculative
    decoding wants anyway."""
    def fn(context, k):
        ctx = np.asarray(context, dtype=np.int64)[-window:]  # lint: allow(tracer-asarray)
        ln = int(ctx.shape[0])
        ids = np.zeros((1, window), np.int64)
        ids[0, :ln] = ctx
        out = draft_model.generate_static_ragged(ids, [ln],
                                                 max_new_tokens=int(k))
        return np.asarray(out.numpy())[0, window:window + int(k)]  # lint: allow(tracer-asarray)
    return fn
