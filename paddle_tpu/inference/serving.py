"""paddle_tpu.inference.serving — an instrumented continuous-batching
engine over the static decode stack, with request-level observability as
the headline.

The training side has step metrics (profiler.StepMonitor, r7) and numerics
sentinels (debugging, r8); serving quality is judged by a DIFFERENT set of
signals — TTFT/TPOT latency distributions, queue wait, batch fill and
KV-slot utilization under load (cf. the ragged-paged-attention and
Gemma-on-TPU serving studies, PAPERS.md). This module provides:

  ServingEngine   admits per-request prompts into a bounded queue,
                  assembles FIXED-SHAPE micro-batches (right-padded ragged
                  prompts + per-row lens), and drives the model's
                  `prefill_static` / `decode_static` executables. Decode
                  runs in chunks of [1, c, c, ...]: the 1-token first
                  chunk makes time-to-first-token a measured host fact
                  (not an estimate), later chunks let a batch stop as soon
                  as every row finished. Every shape is pinned by the
                  config, so after one warmup batch the loop adds ZERO jit
                  compilations — guarded at runtime via the PR-2 cache-miss
                  counter, with a shape-delta warning through
                  `StepMonitor.record_compile` when a request would force
                  a new executable (it is rejected instead).

  RequestTrace    per-request span timestamps (enqueue → admit → prefill →
                  first token → finish); each engine phase also runs under
                  a `jax.profiler.TraceAnnotation` ("serving/prefill",
                  "serving/decode") so device traces attribute kernel time
                  to serving phases exactly like annotate_layers does for
                  modules.

  ServingMetrics  log-bucketed latency histograms (TTFT, per-output-token
                  time, end-to-end, queue wait — p50/p90/p99 derived from
                  buckets, no per-request retention), gauges (queue depth,
                  batch-fill ratio, KV-slot occupancy) and counters
                  (requests/tokens in+out/rejections/timeouts/batches),
                  rendered to Prometheus exposition text by the SAME
                  `profiler._metrics` formatter StepMonitor uses, plus one
                  JSONL record per finished request (the StepMonitor row
                  convention: a nested payload under "request" + "ts").

Greedy engine output is bit-identical to `model.generate_static_ragged`
on the same prompts (tested): padding rows to the fixed batch and chunking
the decode change nothing — attention masks make cache length and batch
company value-invariant, and chunked greedy decode replays the same
argmax chain.
"""
from __future__ import annotations

import json
import math
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, List, Optional

import numpy as np
import jax

from ..profiler import StepMonitor
from ..profiler.monitor import _jit_cache_misses
from ..profiler._metrics import (LogHistogram, counter_lines, gauge_lines,
                                 histogram_lines)


# --------------------------------------------------------------- requests

@dataclass
class RequestTrace:
    """Span timestamps of one request's life (engine clock seconds).

    enqueue → admit is queue wait; admit → prefill_done is the batched
    prefill; first_token lands after the 1-token decode chunk; finish is
    stamped at the end of the decode CHUNK in which the row hit EOS or its
    budget (every chunk ends in a host sync, so chunk granularity is free
    — a short request co-batched with long ones is not charged for decode
    chunks past its own completion)."""
    t_enqueue: Optional[float] = None
    t_admit: Optional[float] = None
    t_prefill_done: Optional[float] = None
    t_first_token: Optional[float] = None
    t_finish: Optional[float] = None
    batch_id: Optional[int] = None

    @property
    def queue_s(self) -> Optional[float]:
        if self.t_admit is None or self.t_enqueue is None:
            return None
        return self.t_admit - self.t_enqueue

    @property
    def ttft_s(self) -> Optional[float]:
        if self.t_first_token is None or self.t_enqueue is None:
            return None
        return self.t_first_token - self.t_enqueue

    @property
    def e2e_s(self) -> Optional[float]:
        if self.t_finish is None or self.t_enqueue is None:
            return None
        return self.t_finish - self.t_enqueue

    def tpot_s(self, n_out: int) -> Optional[float]:
        """Per-output-token time over the post-first-token stretch."""
        if self.t_finish is None or self.t_first_token is None or n_out < 2:
            return None
        return (self.t_finish - self.t_first_token) / (n_out - 1)

    def to_dict(self) -> dict:
        d = {k: getattr(self, k) for k in
             ("t_enqueue", "t_admit", "t_prefill_done", "t_first_token",
              "t_finish", "batch_id")}
        return {k: v for k, v in d.items() if v is not None}


@dataclass(eq=False)     # holds an ndarray: identity, not value, equality
class Request:
    """One admitted (or refused) generation request."""
    id: int
    prompt: np.ndarray                      # 1-D int token ids
    max_new_tokens: int
    status: str = "queued"   # queued|active|done|rejected|timeout
    reason: Optional[str] = None            # rejection/timeout detail
    deadline_s: Optional[float] = None      # max queue wait before admit
    tokens: Optional[np.ndarray] = None     # generated ids (done only)
    n_out: int = 0                          # tokens up to & incl. EOS
    trace: RequestTrace = field(default_factory=RequestTrace)

    @property
    def prompt_len(self) -> int:
        return int(self.prompt.shape[0])

    def record(self) -> dict:
        """The JSONL payload ServingMetrics streams per finished request."""
        t = self.trace
        rec = {"id": self.id, "status": self.status,
               "prompt_tokens": self.prompt_len,
               "output_tokens": self.n_out,
               "spans": t.to_dict()}
        if self.reason:
            rec["reason"] = self.reason
        for key, val in (("queue_s", t.queue_s), ("ttft_s", t.ttft_s),
                         ("tpot_s", t.tpot_s(self.n_out)),
                         ("e2e_s", t.e2e_s)):
            if val is not None:
                rec[key] = round(val, 6)
        return rec


# ---------------------------------------------------------------- metrics

class ServingMetrics:
    """Request-level serving telemetry: histograms + gauges + counters.

    Latency series are LogHistograms — percentiles derive from bucket
    counts, so memory stays O(buckets) however many requests pass through.
    `record_request` consumes a finished Request; `observe_call` is the
    light entry point `inference.Predictor.run` uses under
    `Config.enable_profile()` (one call = one request, e2e only).
    Mirrors StepMonitor's reporting surface: `jsonl_path` streams one row
    per request, `on_record` is the exporter hook, `summary()` returns the
    aggregate dict and `metrics_text()` the Prometheus exposition."""

    HISTS = (("ttft_seconds", "time to first token (enqueue -> token 1)"),
             ("tpot_seconds", "per-output-token time after the first"),
             ("e2e_seconds", "end-to-end request latency"),
             ("queue_seconds", "queue wait (enqueue -> admit)"))

    def __init__(self, *, jsonl_path: Optional[str] = None,
                 on_record: Optional[Callable[[dict], None]] = None,
                 hist_lo: float = 1e-4, hist_hi: float = 1e3,
                 per_decade: int = 10):
        self.jsonl_path = jsonl_path
        self.on_record = on_record
        self.hists = {name: LogHistogram(lo=hist_lo, hi=hist_hi,
                                         per_decade=per_decade)
                      for name, _ in self.HISTS}
        self.counters = {"requests": 0, "completed": 0, "rejected": 0,
                         "timeout": 0, "errors": 0, "tokens_in": 0,
                         "tokens_out": 0, "items": 0, "batches": 0}
        self.gauges = {"queue_depth": 0, "inflight": 0,
                       "batch_fill_ratio": None, "kv_slot_occupancy": None}

    # -- recording ------------------------------------------------------
    def observe_call(self, e2e_s: float, items: int = 1):
        """One synchronous predictor call: e2e latency + item (batch-row)
        count — NOT tokens; a Predictor serves arbitrary feeds."""
        self.counters["requests"] += 1
        self.counters["completed"] += 1
        self.counters["items"] += int(items)
        self.hists["e2e_seconds"].observe(e2e_s)

    def record_request(self, req: Request):
        self.counters["requests"] += 1
        if req.status == "done":
            self.counters["completed"] += 1
            self.counters["tokens_in"] += req.prompt_len
            self.counters["tokens_out"] += req.n_out
            t = req.trace
            for name, val in (("ttft_seconds", t.ttft_s),
                              ("tpot_seconds", t.tpot_s(req.n_out)),
                              ("e2e_seconds", t.e2e_s),
                              ("queue_seconds", t.queue_s)):
                if val is not None:
                    self.hists[name].observe(max(val, 0.0))
        elif req.status == "timeout":
            self.counters["timeout"] += 1
            # the longest queue waits in the system are the expired ones —
            # leaving them out would make queue_seconds p99 look healthy
            # exactly when queueing collapsed
            t = req.trace
            if t.t_finish is not None and t.t_enqueue is not None:
                self.hists["queue_seconds"].observe(
                    max(t.t_finish - t.t_enqueue, 0.0))
        elif req.status == "rejected":
            self.counters["rejected"] += 1
        elif req.status == "error":
            self.counters["errors"] += 1
        row = {"request": req.record(), "ts": time.time()}
        if self.jsonl_path:
            with open(self.jsonl_path, "a") as f:
                f.write(json.dumps(row) + "\n")
        if self.on_record is not None:
            self.on_record(row)
        return row

    def record_batch(self, *, n_real: int, capacity: int,
                     kv_used: int, kv_capacity: int, queue_depth: int):
        self.counters["batches"] += 1
        self.gauges["batch_fill_ratio"] = n_real / max(capacity, 1)
        self.gauges["kv_slot_occupancy"] = kv_used / max(kv_capacity, 1)
        self.gauges["queue_depth"] = queue_depth

    # -- reporting ------------------------------------------------------
    def summary(self) -> dict:
        out = {**{f"{k}_total": v for k, v in self.counters.items()},
               **{k: v for k, v in self.gauges.items()}}
        for name, _ in self.HISTS:
            h = self.hists[name]
            if h.count:
                out[name] = h.summary()
        return out

    def metrics_text(self, prefix: str = "paddle_tpu_serving") -> str:
        """Prometheus text exposition — same format/renderer as
        StepMonitor.metrics_text, so one scrape handler concatenates
        both."""
        lines: List[str] = []
        helps = {"requests": "requests observed (all terminal statuses)",
                 "completed": "requests finished successfully",
                 "rejected": "requests refused at submit "
                             "(queue full / shape)",
                 "timeout": "requests expired in queue past their deadline",
                 "errors": "requests lost to an engine exception "
                           "mid-batch",
                 "tokens_in": "prompt tokens admitted",
                 "tokens_out": "tokens generated (up to and incl. EOS)",
                 "items": "batch rows processed by profiled predictor "
                          "calls",
                 "batches": "micro-batches executed"}
        for name, value in self.counters.items():
            lines.extend(counter_lines(prefix, f"{name}_total", value,
                                       helps[name]))
        ghelp = {"queue_depth": "requests waiting in the admission queue",
                 "inflight": "requests currently being served",
                 "batch_fill_ratio": "real rows / batch capacity of the "
                                     "last micro-batch",
                 "kv_slot_occupancy": "used / allocated KV cache rows of "
                                      "the last micro-batch"}
        for name, value in self.gauges.items():
            lines.extend(gauge_lines(prefix, name, value, ghelp[name]))
        for name, help_ in self.HISTS:
            lines.extend(histogram_lines(prefix, name, self.hists[name],
                                         help_))
        return "\n".join(lines) + "\n"


# ----------------------------------------------------------------- engine

@dataclass
class ServingConfig:
    """Fixed-shape envelope of a ServingEngine. Everything that affects a
    compiled signature lives here — the engine NEVER recompiles to fit a
    request; requests that don't fit are rejected with a logged shape
    delta."""
    max_batch: int = 4              # micro-batch rows (padded with dummies)
    prompt_cap: int = 64            # right-padding cap; longer = rejected
    max_new_tokens: int = 32        # per-request budget ceiling
    decode_chunk: Optional[int] = None  # tokens per post-first-token call;
    #                                 default max_new_tokens-1 = one chunk
    queue_capacity: int = 256       # bounded admission queue
    deadline_s: Optional[float] = None  # default queue-wait deadline
    eos_token_id: Optional[int] = None
    pad_token_id: int = 0
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    seed: int = 0
    weight_dtype: Optional[str] = None   # "int8" -> weight-only int8 GEMMs
    cache_dtype: Optional[str] = None    # "int8" -> int8 KV cache

    def __post_init__(self):
        if self.max_batch < 1 or self.prompt_cap < 1 \
                or self.max_new_tokens < 1:
            raise ValueError("max_batch, prompt_cap and max_new_tokens "
                             "must be >= 1")
        if self.decode_chunk is None:
            self.decode_chunk = max(1, self.max_new_tokens - 1)
        elif self.decode_chunk < 1:
            raise ValueError(f"decode_chunk must be >= 1, "
                             f"got {self.decode_chunk}")

    @property
    def chunk_schedule(self) -> List[int]:
        """Decode-call sizes per batch: [1, c, c, ...] covering
        max_new_tokens (the tail chunk still runs full width — fixed
        shapes — and over-generated tokens are truncated per row)."""
        if self.max_new_tokens == 1:
            return [1]
        k = math.ceil((self.max_new_tokens - 1) / self.decode_chunk)
        return [1] + [self.decode_chunk] * k

    @property
    def max_len(self) -> int:
        """KV rows per batch slot: prompt cap + the chunk schedule's
        worst-case cache writes (the last sampled token is never
        written)."""
        return self.prompt_cap + max(sum(self.chunk_schedule), 2) - 1


class ServingEngine:
    """Continuous-batching serving loop over the static decode stack.

    Synchronous by design: `submit()` enqueues, `step()` runs ONE
    micro-batch to completion, `drain()` loops until the queue empties.
    The engine is NOT internally synchronized — submit/step touch shared
    state beyond the queue (request ids, metrics counters/gauges, the
    JSONL stream), so a frontend thread driving submit while a worker
    loops step() must hold one lock around every engine call. The calls
    are short on the submit side; step() blocks for a batch.

    `clock` is injectable (tests drive deadlines deterministically).
    """

    def __init__(self, model, config: ServingConfig, *,
                 metrics: Optional[ServingMetrics] = None,
                 monitor: Optional[StepMonitor] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.model = model
        self.config = config
        self.metrics = metrics or ServingMetrics()
        # the monitor carries batch step timing + the recompile guard; the
        # serving engine measures dispatch-to-sync walls (truthful: every
        # chunk ends in a host sync for the token handoff)
        self.monitor = monitor or StepMonitor(unit="tokens/s",
                                              track_memory=False)
        self.clock = clock
        self._queue: deque = deque()
        self._next_id = 0
        self._batch_id = 0
        self._max_depth = 0        # deepest (prefill + k chunks) run so far
        self._rejected_shapes = set()   # shape-delta warned once per shape
        # the engine's one-and-only batch signature (leaves shaped like
        # StepMonitor.record_compile expects for shape_delta rendering)
        self._shape_sig = (((config.max_batch, config.prompt_cap), "int64"),
                           ((config.max_batch,), "int32"))

    # -- admission ------------------------------------------------------
    @property
    def queue_depth(self) -> int:
        return len(self._queue)

    def submit(self, prompt, max_new_tokens: Optional[int] = None,
               deadline_s: Optional[float] = None,
               enqueue_at: Optional[float] = None) -> Request:
        """Admit one prompt into the bounded queue.

        Returns the Request; check `.status` — "queued" on success,
        "rejected" (queue full, or a shape the engine's executables cannot
        serve) otherwise. `enqueue_at` backdates the enqueue span for
        open-loop replay (tools/serve_bench.py): queue-wait/TTFT are then
        measured from the request's SCHEDULED arrival, not from when the
        single-threaded replayer got around to calling submit. Backdating
        only — a future timestamp clamps to now (a request cannot be
        served before it arrives; negative queue waits would corrupt the
        accounting this engine exists to make honest)."""
        cfg = self.config
        prompt = np.asarray(prompt, dtype=np.int64).reshape(-1)
        want = cfg.max_new_tokens if max_new_tokens is None \
            else min(int(max_new_tokens), cfg.max_new_tokens)
        req = Request(id=self._next_id, prompt=prompt,
                      max_new_tokens=want,
                      deadline_s=cfg.deadline_s if deadline_s is None
                      else deadline_s)
        self._next_id += 1
        now = self.clock()
        req.trace.t_enqueue = now if enqueue_at is None \
            else min(enqueue_at, now)
        if want < 1:
            # a zero/negative budget is unservable, not "serve 1 anyway" —
            # the caller explicitly asked to pay for nothing
            req.status, req.reason = "rejected", "max_new_tokens"
            self.metrics.record_request(req)
            return req
        if prompt.shape[0] < 1 or prompt.shape[0] > cfg.prompt_cap:
            # serving this prompt would need a new prefill executable —
            # refuse, and log the would-be shape delta where recompile
            # warnings already go (ISSUE 4 satellite). count=False keeps
            # the compiles/recompiles COUNTERS a pure signal of real
            # executable churn (nothing was built — the request was
            # refused precisely so nothing would be); the delta still
            # lands in the warning log and recompile_events under the
            # "serving_reject" kind. Each offending shape WARNS once per
            # engine — abusive traffic must not spam the recompile
            # log/event stream. Every refusal still counts in
            # rejected_total and gets its per-request JSONL record: the
            # request stream is the audit log, deliberately complete.
            req.status, req.reason = "rejected", "prompt_shape"
            plen = int(prompt.shape[0])
            if plen not in self._rejected_shapes:
                self._rejected_shapes.add(plen)
                self.monitor.record_compile(
                    "serving_reject",
                    (((cfg.max_batch, plen), "int64"), self._shape_sig[1]),
                    prev_sig=self._shape_sig, count=False)
            self.metrics.record_request(req)
            return req
        if len(self._queue) >= cfg.queue_capacity:
            req.status, req.reason = "rejected", "queue_full"
            self.metrics.record_request(req)
            return req
        self._queue.append(req)
        self.metrics.gauges["queue_depth"] = len(self._queue)
        return req

    def _admit(self):
        """Pop up to max_batch live requests; expire the deadline-blown.
        Returns (admitted, expired) — both are terminal outcomes the
        caller must surface (a timed-out request is a served SLO miss,
        not something to silently drop from the accounting)."""
        now = self.clock()
        admitted: List[Request] = []
        expired: List[Request] = []
        while self._queue and len(admitted) < self.config.max_batch:
            req = self._queue.popleft()
            if req.deadline_s is not None and \
                    now - req.trace.t_enqueue > req.deadline_s:
                req.status, req.reason = "timeout", "queue_deadline"
                req.trace.t_finish = now       # terminal time: its queue
                self.metrics.record_request(req)  # wait IS its life
                expired.append(req)
                continue
            req.status = "active"
            req.trace.t_admit = now
            req.trace.batch_id = self._batch_id
            admitted.append(req)
        self.metrics.gauges["queue_depth"] = len(self._queue)
        return admitted, expired

    # -- the batch loop -------------------------------------------------
    def step(self) -> List[Request]:
        """Assemble and run ONE micro-batch; returns every request that
        reached a terminal status this step — served rows AND queue-
        deadline timeouts (excluding expired traffic from the results
        would hide exactly the overload signal the metrics exist for).

        If the batch dies mid-flight (device OOM, interrupt), the admitted
        requests are recorded as status="error" before the exception
        propagates — an accounting layer must not lose in-flight requests."""
        reqs, expired = self._admit()
        if not reqs:
            return expired
        try:
            return expired + self._run_batch(reqs)
        except BaseException:
            now = self.clock()
            for r in reqs:
                if r.status == "active":
                    r.status, r.reason = "error", "engine_exception"
                    r.trace.t_finish = now
                    self.metrics.record_request(r)
            self.metrics.gauges["inflight"] = 0
            self.monitor.end_step(items=0)   # no-op if begin never ran
            raise

    def _run_batch(self, reqs: List[Request]) -> List[Request]:
        cfg = self.config
        self.metrics.gauges["inflight"] = len(reqs)
        batch_id = self._batch_id
        self._batch_id += 1

        # fixed-shape assembly: right-padded [B, prompt_cap] int64 + lens;
        # unfilled rows are 1-token pad dummies (their outputs are dropped,
        # and per-row attention/masks keep them from touching real rows)
        B, cap = cfg.max_batch, cfg.prompt_cap
        ids = np.full((B, cap), cfg.pad_token_id, dtype=np.int64)
        lens = np.ones((B,), dtype=np.int32)
        for i, r in enumerate(reqs):
            ids[i, :r.prompt_len] = r.prompt
            lens[i] = r.prompt_len

        miss0 = _jit_cache_misses()
        need = max(r.max_new_tokens for r in reqs)
        self.monitor.begin_step()
        with jax.profiler.TraceAnnotation("serving/prefill"):
            st = self.model.prefill_static(
                ids, max_len=cfg.max_len, prompt_lens=lens,
                weight_dtype=cfg.weight_dtype, cache_dtype=cfg.cache_dtype)
            jax.block_until_ready(st["last_logits"])
        t_prefill = self.clock()
        for r in reqs:
            r.trace.t_prefill_done = t_prefill

        parts: List[np.ndarray] = []
        schedule = cfg.chunk_schedule
        for ci, chunk in enumerate(schedule):
            with jax.profiler.TraceAnnotation("serving/decode"):
                # per-(batch, chunk) seed: every decode_static call builds
                # a fresh PRNG stream from its seed, so reusing one seed
                # across chunks would replay the same draws
                toks, st = self.model.decode_static(
                    st, chunk, temperature=cfg.temperature,
                    top_k=cfg.top_k, top_p=cfg.top_p,
                    seed=cfg.seed + batch_id * len(schedule) + ci,
                    eos_token_id=cfg.eos_token_id, return_state=True)
                part = np.asarray(toks.numpy())     # host sync per chunk
            parts.append(part)
            t_chunk = self.clock()
            if ci == 0:
                for r in reqs:
                    r.trace.t_first_token = t_chunk
            # per-row finish at chunk granularity: a row is complete once
            # it hit EOS or its own budget — its e2e/TPOT must not be
            # charged for chunks the batch ran for OTHER rows
            produced = sum(p.shape[1] for p in parts)
            so_far = part if len(parts) == 1 else \
                np.concatenate(parts, axis=1)
            for i, r in enumerate(reqs):
                if r.trace.t_finish is None and \
                        (produced >= r.max_new_tokens or
                         _hit_eos(so_far[i, :r.max_new_tokens],
                                  cfg.eos_token_id)):
                    r.trace.t_finish = t_chunk
            if produced >= need:
                break
            if cfg.eos_token_id is not None:
                done = np.asarray(st["done"])
                if done[:len(reqs)].all():
                    break               # every real row hit EOS: stop early

        gen = np.concatenate(parts, axis=1)
        out_tokens = 0
        for i, r in enumerate(reqs):
            row = gen[i, :r.max_new_tokens]
            r.tokens = row
            r.n_out = _n_out(row, cfg.eos_token_id)
            r.status = "done"
            if r.trace.t_finish is None:    # unreachable in practice: both
                r.trace.t_finish = t_chunk  # loop exits finish every row
            out_tokens += r.n_out
            self.metrics.record_request(r)
        # per-row cache rows actually written: prompt + produced - 1 (the
        # last sampled token is returned but never written)
        kv_used = int(lens[:len(reqs)].sum()) + \
            int((gen.shape[1] - 1) * len(reqs))
        self.metrics.record_batch(
            n_real=len(reqs), capacity=B, kv_used=kv_used,
            kv_capacity=B * cfg.max_len, queue_depth=len(self._queue))
        self.metrics.gauges["inflight"] = 0

        # compile accounting BEFORE closing the step so the monitor marks
        # this record `compiled` and keeps it out of the steady-state
        # median/throughput: warmup's wall time is compile-dominated.
        # Warmth is per chunk DEPTH, not per engine — an EOS early-exit or
        # small-budget batch may stop before the deeper chunk executables
        # ever compiled, and their eventual first compile is not shape
        # churn. A jit miss at an already-seen depth is: every executable
        # at that depth was cached, so something reshaped — log it as a
        # recompile through the r7 detector.
        depth = 1 + len(parts)               # prefill + decode calls made
        dm = _jit_cache_misses() - miss0
        if dm:
            self.monitor.record_compile(
                "serving_batch",
                (("jit_cache_misses", dm),),
                prev_sig=(("jit_cache_misses", 0),)
                if depth <= self._max_depth else None)
        self._max_depth = max(self._max_depth, depth)
        self.monitor.end_step(items=out_tokens)
        return reqs

    def drain(self, max_batches: Optional[int] = None) -> List[Request]:
        """step() until the queue empties (or max_batches)."""
        out: List[Request] = []
        n = 0
        while self._queue:
            if max_batches is not None and n >= max_batches:
                break
            got = self.step()
            n += 1
            if not got and not self._queue:
                break
            out.extend(got)
        return out

    # -- reporting ------------------------------------------------------
    def summary(self) -> dict:
        s = self.metrics.summary()
        s["batch_step"] = self.monitor.report()
        return s

    def metrics_text(self, prefix: str = "paddle_tpu_serving") -> str:
        """The full /metrics payload: request metrics + the engine's batch
        StepMonitor block (steady tokens/s, recompile counters)."""
        return self.metrics.metrics_text(prefix=prefix) + \
            self.monitor.metrics_text(prefix=f"{prefix}_batch")


def _hit_eos(row: np.ndarray, eos: Optional[int]) -> bool:
    return eos is not None and bool((row == eos).any())


def _n_out(row: np.ndarray, eos: Optional[int]) -> int:
    """Tokens a row really produced: up to and including the first EOS."""
    if eos is None:
        return int(row.shape[0])
    hits = np.nonzero(row == eos)[0]
    return int(hits[0]) + 1 if hits.size else int(row.shape[0])


def synthetic_traffic(n_requests: int, *, prompt_cap: int, vocab_size: int,
                      rate: float = 50.0, seed: int = 0,
                      min_len: int = 1) -> List[dict]:
    """Open-loop synthetic workload: Poisson arrivals at `rate` req/s,
    uniform ragged prompt lengths in [min_len, prompt_cap]. Returns
    [{"at": arrival_offset_s, "prompt": ids}] sorted by arrival — shared
    by examples/serve_gpt.py and tools/serve_bench.py."""
    rng = np.random.RandomState(seed)
    gaps = rng.exponential(1.0 / max(rate, 1e-9), size=n_requests)
    at = np.cumsum(gaps) - gaps[0]
    out = []
    for i in range(n_requests):
        ln = int(rng.randint(min_len, prompt_cap + 1))
        out.append({"at": float(at[i]),
                    "prompt": rng.randint(1, vocab_size,
                                          (ln,)).astype(np.int64)})
    return out
