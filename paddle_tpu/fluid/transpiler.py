"""fluid.DistributeTranspiler — fluid-1.x parameter-server training.

Reference: python/paddle/fluid/transpiler/distribute_transpiler.py:264 —
transpile() rewrites a fluid static Program into a trainer program (grads
sent to parameter servers, fresh params received) and per-endpoint pserver
programs (param shards + the optimizer applied server-side, behind a
Listen&Serv op).

TPU-native redesign (no program surgery): the static Program replays as one
jitted XLA step here, so the transpiler marks the program for PS execution
instead of rewriting it. The Executor then builds the SAME step minus the
optimizer apply, fetches the gradients, and the bridge below pushes them to
the PS runtime (distributed/fleet/ps_runtime.py: the same pickle-frame
PsServer/RemoteShard pair the sparse-table path uses) which applies the
update server-side and returns fresh rows. Dense params shard across
endpoints round-robin (the reference's slice_var_up=False layout).

Supported scope (documented subset): server-side optimizer = SGD (the
reference moves whatever optimizer server-side; here non-SGD raises),
single- or multi-trainer with ASYNCHRONOUS application semantics (trainer 0
initializes the tables; the reference's geo/async modes share this shape).
"""
from __future__ import annotations

import time
from typing import List, Optional

import numpy as np


class DistributeTranspilerConfig:
    """Accepted fluid-1.x knobs. Layout knobs are advisory here: params
    shard whole (round-robin) — the reference's slice_var_up=False mode."""

    slice_var_up = False
    split_method = None
    min_block_size = 8192
    enable_dc_asgd = False
    mode = "pserver"
    print_log = False
    wait_port = True


def _rows_view(arr):
    """Param -> (m, n) row matrix: dim-0 rows for >=2-D, one row for 1-D."""
    a = np.asarray(arr, np.float32)
    if a.ndim <= 1:
        return a.reshape(1, -1)
    return a.reshape(a.shape[0], -1)


class _PsTrainerBridge:
    """Push-grads / pull-params glue the Executor calls once per step."""

    def __init__(self, endpoints: List[str], trainer_id: int, trainers: int):
        self.endpoints = endpoints
        self.trainer_id = trainer_id
        self.trainers = trainers
        self._shards = None
        self._meta = None

    def _connect(self, params, lr):
        from ..distributed.fleet.ps_runtime import RemoteShard
        self._shards, self._meta = [], []
        self._lr0 = float(lr)
        self._fingerprint = tuple((p.name, tuple(p._data.shape))
                                  for p in params)
        for i, p in enumerate(params):
            rows = _rows_view(p._data)
            ep = self.endpoints[i % len(self.endpoints)]
            name = f"dtp_{p.name or f'param_{i}'}"
            sh = RemoteShard(ep, name, rows.shape[1], optimizer="sgd",
                             lr=float(lr), init_scale=0.0)
            ids = np.arange(rows.shape[0], dtype=np.int64)
            if self.trainer_id == 0:
                # ONE merge_delta both materializes the rows (exact zeros
                # under init_scale=0) and sets the initial values — atomic
                # under the server's per-table lock, so other trainers'
                # size probe can never observe half-initialized tables
                sh.merge_delta(ids, rows)
            else:
                deadline = time.time() + 120.0
                while len(sh) < rows.shape[0]:   # wait for trainer 0 init
                    if time.time() > deadline:
                        raise RuntimeError(
                            f"DistributeTranspiler: table {name} not "
                            "initialized by trainer 0 within 120s")
                    time.sleep(0.05)
            self._shards.append(sh)
            self._meta.append((ids, p._data.shape, p._data.dtype))

    def apply(self, params, grads, lr):
        import jax.numpy as jnp
        if self._shards is None:
            self._connect(params, lr)
        if float(lr) != self._lr0:
            raise NotImplementedError(
                "DistributeTranspiler: the server-side SGD applies the "
                f"creation-time lr ({self._lr0}); LR schedules are not "
                "supported in PS mode")
        if tuple((p.name, tuple(p._data.shape))
                 for p in params) != self._fingerprint:
            raise RuntimeError(
                "DistributeTranspiler: trainable-parameter set changed "
                "after the first step (e.g. stop_gradient toggled) — "
                "re-transpile to rebuild the table binding")
        for p, g, sh, (ids, shape, dtype) in zip(params, grads,
                                                 self._shards, self._meta):
            fresh = sh.push_pull(ids, _rows_view(g))
            p._data = jnp.asarray(fresh.reshape(shape), dtype=dtype)
            p._node = None

    def close(self):
        for sh in self._shards or []:
            sh.close()


class _PServerProgram:
    """What get_pserver_program returns; exe.run(it) blocks serving —
    the reference's Listen&Serv loop. `_ps_serve` is the Executor hook."""

    def __init__(self, endpoint: str):
        self.endpoint = endpoint
        self._server = None

    def _start(self):
        from ..distributed.fleet.ps_runtime import PsServer
        host, port = self.endpoint.rsplit(":", 1)
        self._server = PsServer(port=int(port),
                                host=host if host not in ("", "*") else
                                "0.0.0.0")
        return self._server

    def _ps_serve(self):
        self._start().serve_forever()
        return []

    def _ps_serve_in_thread(self):
        srv = self._start()
        th = srv.serve_in_thread()
        return srv, th


class DistributeTranspiler:
    """Reference API surface: transpile / get_trainer_program /
    get_pserver_program(s) / get_startup_program."""

    def __init__(self, config: Optional[DistributeTranspilerConfig] = None):
        self.config = config or DistributeTranspilerConfig()
        self._prog = None
        self._pservers: List[str] = []
        self._trainer_id = 0
        self._trainers = 1
        self._sync_mode = True

    def transpile(self, trainer_id, program=None, pservers="", trainers=1,
                  sync_mode=True, startup_program=None,
                  current_endpoint=None):
        from ..static.program import default_main_program
        self._prog = program or default_main_program()
        self._pservers = [e.strip() for e in str(pservers).split(",")
                          if e.strip()]
        if not self._pservers:
            raise ValueError("DistributeTranspiler.transpile: pservers "
                             "endpoint list is empty")
        self._trainer_id = int(trainer_id)
        self._trainers = int(trainers)
        self._sync_mode = bool(sync_mode)

    def get_trainer_program(self, wait_port=True):
        opt = getattr(self._prog, "_optimizer", None)
        if opt is not None and type(opt).__name__ not in ("SGD",):
            raise NotImplementedError(
                "DistributeTranspiler: server-side optimizer application "
                f"supports SGD (got {type(opt).__name__}) — the reference "
                "moves the optimizer to the pserver; richer rules belong "
                "to the fleet PS runtime (distributed/fleet)")
        if opt is not None:
            # the local executor path would clip and weight-decay; the PS
            # path ships raw grads to a plain-SGD server — refuse instead
            # of silently training a different objective
            if getattr(opt, "_grad_clip", None) is not None:
                raise NotImplementedError(
                    "DistributeTranspiler: grad_clip is applied by the "
                    "local executor path but not by the PS server — "
                    "unsupported in PS mode")
            if any(float(opt._wd_for(p) or 0.0) != 0.0
                   for p in self._prog._params if not p.stop_gradient):
                raise NotImplementedError(
                    "DistributeTranspiler: weight_decay/regularization is "
                    "not applied by the PS server's plain SGD — "
                    "unsupported in PS mode")
        self._prog._ps_dist = _PsTrainerBridge(
            self._pservers, self._trainer_id, self._trainers)
        return self._prog

    def get_pserver_program(self, endpoint):
        return _PServerProgram(endpoint)

    def get_pserver_programs(self, endpoint):
        prog = self.get_pserver_program(endpoint)
        return prog, self.get_startup_program(endpoint, prog)

    def get_startup_program(self, endpoint=None, pserver_program=None,
                            startup_program=None):
        # startup initializers already ran eagerly in this framework;
        # an empty program is a no-op under Executor.run
        from ..static.program import Program
        return Program()
