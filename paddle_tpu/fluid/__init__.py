"""Legacy `paddle.fluid` compatibility namespace.

Reference (SURVEY §2.3): python/paddle/fluid/ is 81.6k LoC of legacy API the
reference keeps for migration. Here it is a thin aliasing layer over the
modern modules — enough for common fluid-era call sites (Executor, program
guards, fluid.data, fluid.layers basics, dygraph guard, ParamAttr) to run
unchanged; new code should use the top-level namespaces.
"""
from __future__ import annotations

import contextlib

from ..static import (  # noqa: F401
    Executor, Program, program_guard, default_main_program,
    default_startup_program, global_scope, CompiledProgram,
)
from ..static.program import data  # noqa: F401
from ..core.tensor import Tensor, Parameter  # noqa: F401
from ..framework.io import save, load  # noqa: F401
from .. import nn as _nn
from ..core import ops as _ops


class ParamAttr:
    """reference: fluid/param_attr.py — initializer/regularizer/name bag."""

    def __init__(self, name=None, initializer=None, learning_rate=1.0,
                 regularizer=None, trainable=True, do_model_average=False,
                 need_clip=True):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.need_clip = need_clip


def CUDAPlace(dev_id=0):
    import jax
    return jax.devices()[dev_id]


def CPUPlace():
    import jax
    for d in jax.devices("cpu"):
        return d
    return jax.devices()[0]


def CUDAPinnedPlace():
    """Host-pinned memory place — on TPU runtimes host staging is managed
    by the transfer engine, so this is the host (CPU) device."""
    return CPUPlace()


def NPUPlace(dev_id=0):
    """Reference NPU backend place; maps to the accelerator device here
    (we ARE the single-accelerator backend, SURVEY §7 custom-device row)."""
    return CUDAPlace(dev_id)


def XPUPlace(dev_id=0):
    return CUDAPlace(dev_id)


def is_compiled_with_cuda():
    return False


@contextlib.contextmanager
def dygraph_guard():
    yield


class dygraph:
    """fluid.dygraph namespace shim."""
    Layer = _nn.Layer

    @staticmethod
    @contextlib.contextmanager
    def guard(place=None):
        yield

    @staticmethod
    def to_variable(value, name=None, zero_copy=None):
        from ..core.tensor import to_tensor
        return to_tensor(value)


class layers:
    """fluid.layers shim: the old functional layer API over modern ops."""
    @staticmethod
    def fc(input, size, num_flatten_dims=1, act=None, name=None, **kw):
        from ..static.nn import fc as _fc
        return _fc(input, size, num_flatten_dims, activation=act)

    @staticmethod
    def data(name, shape, dtype="float32", **kw):
        return data(name, shape, dtype)

    relu = staticmethod(_ops.relu) if hasattr(_ops, "relu") else None
    softmax = staticmethod(lambda x, axis=-1, name=None: _nn.functional.softmax(x, axis))
    cross_entropy = staticmethod(
        lambda input, label, **kw: _nn.functional.cross_entropy(input, label))
    mean = staticmethod(_ops.mean)
    concat = staticmethod(_ops.concat)
    reshape = staticmethod(lambda x, shape, **kw: _ops.reshape(x, shape))
    reduce_sum = staticmethod(lambda x, dim=None, keep_dim=False, name=None:
                              _ops.sum(x, axis=dim, keepdim=keep_dim))


core = type("core", (), {
    "Scope": None,
})
