"""Legacy `paddle.fluid` compatibility namespace.

Reference (SURVEY §2.3): python/paddle/fluid/ is 81.6k LoC of legacy API the
reference keeps for migration. Here it is a thin aliasing layer over the
modern modules — enough for common fluid-era call sites (Executor, program
guards, fluid.data, fluid.layers basics, dygraph guard, ParamAttr) to run
unchanged; new code should use the top-level namespaces.
"""
from __future__ import annotations

import contextlib

from ..static import (  # noqa: F401
    Program, default_main_program,
    default_startup_program, global_scope, CompiledProgram,
)
from ..static import Executor as _StaticExecutor
from ..static import program_guard as _static_program_guard
from ..static.program import (enable_static as _enable_static,
                              disable_static as _disable_static,
                              in_static_mode as _in_static_mode)


@contextlib.contextmanager
def program_guard(main_program, startup_program=None):
    """fluid-1.x scripts never call paddle.enable_static() — static WAS the
    default world (reference: fluid/framework.py program_guard). The shim
    therefore turns recording on for the guard's duration and restores the
    caller's mode after, so verbatim fluid scripts build programs while the
    surrounding process stays eager."""
    prev = _in_static_mode()
    _enable_static()
    try:
        with _static_program_guard(main_program, startup_program):
            yield
    finally:
        if not prev:
            _disable_static()


class Executor(_StaticExecutor):
    """fluid.Executor — static-mode-owning run() (same rationale as
    program_guard above: fluid-era call sites assume static is on)."""

    def run(self, *args, **kwargs):
        prev = _in_static_mode()
        _enable_static()
        try:
            return super().run(*args, **kwargs)
        finally:
            if not prev:
                _disable_static()
from ..static.program import data  # noqa: F401
from ..core.tensor import Tensor, Parameter  # noqa: F401
from ..framework.io import save, load  # noqa: F401
from .. import nn as _nn
from ..core import ops as _ops


class ParamAttr:
    """reference: fluid/param_attr.py — initializer/regularizer/name bag."""

    def __init__(self, name=None, initializer=None, learning_rate=1.0,
                 regularizer=None, trainable=True, do_model_average=False,
                 need_clip=True):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.need_clip = need_clip


def CUDAPlace(dev_id=0):
    import jax
    return jax.devices()[dev_id]


def CPUPlace():
    import jax
    for d in jax.devices("cpu"):
        return d
    return jax.devices()[0]


def CUDAPinnedPlace():
    """Host-pinned memory place — on TPU runtimes host staging is managed
    by the transfer engine, so this is the host (CPU) device."""
    return CPUPlace()


def NPUPlace(dev_id=0):
    """Reference NPU backend place; maps to the accelerator device here
    (we ARE the single-accelerator backend, SURVEY §7 custom-device row)."""
    return CUDAPlace(dev_id)


def XPUPlace(dev_id=0):
    return CUDAPlace(dev_id)


def is_compiled_with_cuda():
    return False


@contextlib.contextmanager
def dygraph_guard():
    yield


class dygraph:
    """fluid.dygraph namespace shim."""
    Layer = _nn.Layer

    @staticmethod
    @contextlib.contextmanager
    def guard(place=None):
        yield

    @staticmethod
    def to_variable(value, name=None, zero_copy=None):
        from ..core.tensor import to_tensor
        return to_tensor(value)


class layers:
    """fluid.layers shim: the old functional layer API over modern ops.

    Deep enough to run verbatim fluid-era training scripts (reference:
    python/paddle/fluid/layers/nn.py surface — fc/data/embedding +
    square_error_cost/cross_entropy/accuracy + activations), per
    MIGRATION.md's fluid-user path.
    """
    @staticmethod
    def fc(input, size, num_flatten_dims=1, act=None, name=None,
           param_attr=None, bias_attr=None, **kw):
        from ..static.nn import fc as _fc
        return _fc(input, size, num_flatten_dims, weight_attr=param_attr,
                   activation=act, bias_attr=bias_attr)

    @staticmethod
    def data(name, shape, dtype="float32", append_batch_size=True, **kw):
        """fluid.layers.data PREPENDS the batch dim (fluid/layers/io.py:
        append_batch_size=True) — unlike the newer fluid.data/static.data
        which take the full shape."""
        shape = list(shape)
        if append_batch_size and (not shape or shape[0] != -1):
            shape = [-1] + shape
        return data(name, shape, dtype)

    @staticmethod
    def embedding(input, size, is_sparse=False, padding_idx=None,
                  param_attr=None, dtype="float32", **kw):
        from ..static.nn import embedding as _emb
        return _emb(input, size, is_sparse=is_sparse,
                    padding_idx=padding_idx, weight_attr=param_attr)

    @staticmethod
    def square_error_cost(input, label):
        """reference: fluid/layers/loss.py square_error_cost — elementwise
        (input - label)^2, NO mean."""
        d = input - label
        return d * d

    @staticmethod
    def cross_entropy(input, label, soft_label=False, ignore_index=-100):
        """FLUID semantics (fluid/layers/loss.py cross_entropy): `input` is
        a PROBABILITY distribution (post-softmax, e.g. fc(act='softmax')),
        not logits; returns per-example -log p [N, 1], with 0 at
        ignore_index positions (the fluid padding-label contract)."""
        eps = 1e-12
        p = _ops.clip(input, min=eps, max=1.0)
        if soft_label:
            return -_ops.sum(label * _ops.log(p), axis=-1, keepdim=True)
        lab = label
        if len(lab.shape) == len(input.shape) - 1:
            lab = _ops.unsqueeze(lab, -1)
        lab = _ops.cast(lab, "int64")
        ignored = _ops.equal(lab, _ops.full_like(lab, ignore_index))
        safe = _ops.where(ignored, _ops.zeros_like(lab), lab)
        picked = _ops.take_along_axis(p, safe, axis=-1)
        loss = -_ops.log(picked)
        return _ops.where(ignored, _ops.zeros_like(loss), loss)

    @staticmethod
    def accuracy(input, label, k=1, **kw):
        from ..static import accuracy as _acc
        return _acc(input, label, k=k)

    relu = staticmethod(_ops.relu) if hasattr(_ops, "relu") else None
    softmax = staticmethod(lambda x, axis=-1, name=None: _nn.functional.softmax(x, axis))
    sigmoid = staticmethod(lambda x, name=None: _ops.sigmoid(x))
    tanh = staticmethod(lambda x, name=None: _ops.tanh(x))
    mean = staticmethod(_ops.mean)
    concat = staticmethod(_ops.concat)
    reshape = staticmethod(lambda x, shape, **kw: _ops.reshape(x, shape))
    reduce_sum = staticmethod(lambda x, dim=None, keep_dim=False, name=None:
                              _ops.sum(x, axis=dim, keepdim=keep_dim))
    reduce_mean = staticmethod(lambda x, dim=None, keep_dim=False, name=None:
                               _ops.mean(x, axis=dim, keepdim=keep_dim))


class optimizer:
    """fluid.optimizer namespace (reference: fluid/optimizer.py) — the
    fluid-era constructors (parameter_list/regularization kwargs) over the
    modern optimizers; .minimize(loss) works in program context."""

    @staticmethod
    def _translate(kw):
        out = dict(kw)
        if "parameter_list" in out:
            out["parameters"] = out.pop("parameter_list")
        reg = out.pop("regularization", None)
        if reg is not None:
            if isinstance(reg, regularizer.L1Decay):
                # the modern optimizers apply weight_decay as an L2
                # penalty; silently retargeting L1 to L2 would train to
                # different weights with no diagnostic
                raise NotImplementedError(
                    "fluid.regularizer.L1Decay is not supported by the "
                    "compat shim (weight_decay is L2 here); use L2Decay or "
                    "add an explicit L1 penalty term to the loss")
            out["weight_decay"] = getattr(reg, "coeff", reg)
        out.pop("name", None)
        return out

    @staticmethod
    def SGD(learning_rate=0.001, **kw):  # noqa: N802
        from .. import optimizer as _opt
        return _opt.SGD(learning_rate=learning_rate,
                        **optimizer._translate(kw))

    SGDOptimizer = SGD

    @staticmethod
    def Momentum(learning_rate=0.001, momentum=0.9, **kw):  # noqa: N802
        from .. import optimizer as _opt
        return _opt.Momentum(learning_rate=learning_rate, momentum=momentum,
                             **optimizer._translate(kw))

    MomentumOptimizer = Momentum

    @staticmethod
    def Adam(learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
             **kw):  # noqa: N802
        from .. import optimizer as _opt
        return _opt.Adam(learning_rate=learning_rate, beta1=beta1,
                         beta2=beta2, epsilon=epsilon,
                         **optimizer._translate(kw))

    AdamOptimizer = Adam

    @staticmethod
    def Adagrad(learning_rate=0.001, epsilon=1e-6, **kw):  # noqa: N802
        from .. import optimizer as _opt
        return _opt.Adagrad(learning_rate=learning_rate, epsilon=epsilon,
                            **optimizer._translate(kw))

    AdagradOptimizer = Adagrad

    # ---- the rest of the fluid/optimizer.py class roster (reference
    # fluid/optimizer.py:92-2762) over the modern rules; each keeps the
    # fluid-era kwargs via _translate ----
    @staticmethod
    def AdamW(learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
              **kw):  # noqa: N802
        from .. import optimizer as _opt
        kw = optimizer._translate(kw)
        wd = kw.pop("weight_decay", 0.01)
        return _opt.AdamW(learning_rate=learning_rate, beta1=beta1,
                          beta2=beta2, epsilon=epsilon, weight_decay=wd,
                          **kw)

    AdamWOptimizer = AdamW

    @staticmethod
    def Adamax(learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
               **kw):  # noqa: N802
        from .. import optimizer as _opt
        return _opt.Adamax(learning_rate=learning_rate, beta1=beta1,
                           beta2=beta2, epsilon=epsilon,
                           **optimizer._translate(kw))

    AdamaxOptimizer = Adamax

    @staticmethod
    def Adadelta(learning_rate=0.001, epsilon=1e-6, rho=0.95,
                 **kw):  # noqa: N802
        from .. import optimizer as _opt
        return _opt.Adadelta(learning_rate=learning_rate, epsilon=epsilon,
                             rho=rho, **optimizer._translate(kw))

    AdadeltaOptimizer = Adadelta

    @staticmethod
    def RMSProp(learning_rate=0.001, rho=0.95, epsilon=1e-6, momentum=0.0,
                centered=False, **kw):  # noqa: N802
        from .. import optimizer as _opt
        return _opt.RMSProp(learning_rate=learning_rate, rho=rho,
                            epsilon=epsilon, momentum=momentum,
                            centered=centered, **optimizer._translate(kw))

    RMSPropOptimizer = RMSProp

    @staticmethod
    def Lamb(learning_rate=0.001, lamb_weight_decay=None, beta1=0.9,
             beta2=0.999, epsilon=1e-6, **kw):  # noqa: N802
        from .. import optimizer as _opt
        kw = optimizer._translate(kw)
        # fluid's regularization=L2Decay(x) IS the LAMB decay term in the
        # reference (LAMB applies the regularizer as its weight-decay):
        # map it onto lamb_weight_decay unless the caller passed both.
        reg_wd = kw.pop("weight_decay", None)
        if lamb_weight_decay is None:
            lamb_weight_decay = 0.01 if reg_wd is None else reg_wd
        elif reg_wd is not None and float(reg_wd) != float(lamb_weight_decay):
            raise ValueError(
                "fluid.optimizer.Lamb: got both lamb_weight_decay="
                f"{lamb_weight_decay} and regularization coeff {reg_wd}; "
                "pass only one")
        return _opt.Lamb(learning_rate=learning_rate,
                         lamb_weight_decay=lamb_weight_decay, beta1=beta1,
                         beta2=beta2, epsilon=epsilon, **kw)

    LambOptimizer = Lamb

    @staticmethod
    def LarsMomentum(learning_rate=0.001, momentum=0.9,
                     lars_coeff=0.001, lars_weight_decay=0.0005,
                     **kw):  # noqa: N802
        from .. import optimizer as _opt
        return _opt.LarsMomentum(learning_rate=learning_rate,
                                 momentum=momentum, lars_coeff=lars_coeff,
                                 lars_weight_decay=lars_weight_decay,
                                 **optimizer._translate(kw))

    LarsMomentumOptimizer = LarsMomentum


class initializer:
    """fluid.initializer namespace (reference: fluid/initializer.py)."""
    from ..nn.initializer import (  # noqa: F401
        Constant, Normal, TruncatedNormal, Uniform, XavierUniform,
        XavierNormal, KaimingNormal, KaimingUniform)
    ConstantInitializer = Constant
    NormalInitializer = Normal
    UniformInitializer = Uniform
    XavierInitializer = XavierUniform
    # fluid's MSRAInitializer defaults to uniform=True (fluid/initializer.py)
    MSRAInitializer = KaimingUniform


class regularizer:
    """fluid.regularizer namespace (reference: fluid/regularizer.py)."""

    class L2Decay:
        def __init__(self, regularization_coeff=0.0):
            self.coeff = regularization_coeff

    class L1Decay:
        def __init__(self, regularization_coeff=0.0):
            self.coeff = regularization_coeff

    L2DecayRegularizer = L2Decay
    L1DecayRegularizer = L1Decay


core = type("core", (), {
    "Scope": None,
})


# fluid-1.x distributed transpiler (reference:
# fluid/transpiler/distribute_transpiler.py:264) — PS-mode training over
# the fleet PS runtime; see fluid/transpiler.py for the redesign notes
from . import transpiler  # noqa: F401,E402
from .transpiler import (  # noqa: F401,E402
    DistributeTranspiler, DistributeTranspilerConfig)
