"""paddle.incubate analog (reference: python/paddle/incubate/__init__.py) —
experimental surfaces: MoE, fused transformer layers, extra optimizers."""
from . import nn  # noqa: F401
from . import distributed  # noqa: F401
from . import optimizer  # noqa: F401
from .nn.functional import fused_matmul_bias  # noqa: F401

from . import asp  # noqa: E402,F401
from . import autograd  # noqa: E402,F401
from .ops import (  # noqa: E402,F401
    segment_sum, segment_mean, segment_max, segment_min, graph_send_recv,
    graph_sample_neighbors, graph_khop_sampler, graph_reindex,
    softmax_mask_fuse, softmax_mask_fuse_upper_triangle, identity_loss, unzip,
)
from .optimizer.lookahead import LookAhead, ModelAverage  # noqa: E402,F401
