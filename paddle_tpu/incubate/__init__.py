"""paddle.incubate analog (reference: python/paddle/incubate/__init__.py) —
experimental surfaces: MoE, fused transformer layers, extra optimizers."""
from . import nn  # noqa: F401
from . import distributed  # noqa: F401
from . import optimizer  # noqa: F401
from .nn.functional import fused_matmul_bias  # noqa: F401

from . import asp  # noqa: E402,F401
from . import autograd  # noqa: E402,F401
