"""Fused transformer layers (reference: python/paddle/incubate/nn/layer/
fused_transformer.py — FusedMultiHeadAttention:192, FusedFeedForward:497,
FusedTransformerEncoderLayer:725, FusedMultiTransformer:1021).

The reference backs these with CUDA megakernels (fused_attention_op.cu,
fused_feedforward_op.cu); on TPU each forward body is one apply_op whose
whole expression XLA fuses, and the attention core dispatches to the Pallas
flash kernel when shapes qualify. Parameter names/shapes follow the
reference so state_dicts line up.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.tensor import Tensor, apply_op
from ...core import random as _random
from ...nn.layer import Layer
from ...nn import initializer as I
from ...ops.attention import functional_attention, attention_reference
from .functional import _ln, _drop


class FusedMultiHeadAttention(Layer):
    """Pre/post-LN fused self-attention block (fused_transformer.py:192):
    residual + LN + QKV proj + SDPA + out proj + dropout in one fusion."""

    def __init__(self, embed_dim, num_heads, dropout_rate=0.5,
                 attn_dropout_rate=0.5, kdim=None, vdim=None,
                 normalize_before=False, need_weights=False, qkv_weight_attr=None,
                 epsilon=1e-5, name=None):
        super().__init__()
        assert embed_dim % num_heads == 0
        self.embed_dim, self.num_heads = embed_dim, num_heads
        self.head_dim = embed_dim // num_heads
        self.dropout_rate = dropout_rate
        self.attn_dropout_rate = attn_dropout_rate
        self.normalize_before = normalize_before
        self._epsilon = epsilon
        h = embed_dim
        self.qkv_weight = self.create_parameter(
            [3, num_heads, self.head_dim, h], default_initializer=I.XavierUniform())
        self.qkv_bias = self.create_parameter(
            [3, num_heads, self.head_dim], is_bias=True)
        self.linear_weight = self.create_parameter(
            [h, h], default_initializer=I.XavierUniform())
        self.linear_bias = self.create_parameter([h], is_bias=True)
        self.pre_ln_scale = self.create_parameter(
            [h], default_initializer=I.Constant(1.0))
        self.pre_ln_bias = self.create_parameter([h], is_bias=True)
        self.ln_scale = self.create_parameter(
            [h], default_initializer=I.Constant(1.0))
        self.ln_bias = self.create_parameter([h], is_bias=True)

    def _mha_head(self, x, qkv_w, qkv_b, pls, plb):
        """Shared pre-LN + fused QKV projection (both cache paths)."""
        residual = x
        if self.normalize_before:
            x = _ln(x, pls, plb, self._epsilon)
        qkv = jnp.einsum("bsh,tndh->bstnd", x, qkv_w) + qkv_b
        return residual, qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]

    def _mha_tail(self, o, residual, lw, lb, lns, lnb, out_p=0.0, k_out=None):
        """Shared out-projection + residual + post-LN (both cache paths)."""
        o = o.reshape(o.shape[0], o.shape[1], self.num_heads * self.head_dim)
        o = o @ lw + lb
        o = residual + _drop(o, out_p, k_out)
        if not self.normalize_before:
            o = _ln(o, lns, lnb, self._epsilon)
        return o

    def forward(self, query, key=None, value=None, attn_mask=None, cache=None):
        """cache: optional (k_past, v_past) Tensors [B, S_past, H, D] for
        incremental decode; returns (out, (k_new, v_new)) when given
        (reference Cache contract, fused_transformer.py:192).

        A 3-tuple (k_buf, v_buf, pos) selects STATIC-cache decode instead:
        fixed [B, L_max, H, D] buffers + write cursor, constant shapes at
        every step so a serving loop compiles once — the reference's
        fused_multi_transformer CacheKV workspace semantics
        (operators/fused/fused_multi_transformer_op.cu); same design as
        GPTForCausalLM.generate_static."""
        if (key is not None and key is not query) or \
                (value is not None and value is not query):
            raise NotImplementedError(
                "FusedMultiHeadAttention computes self-attention from the "
                "fused qkv projection (reference fused_attention_op semantics); "
                "cross-attention with distinct key/value is not supported — "
                "use nn.MultiHeadAttention")
        nh, hd, eps = self.num_heads, self.head_dim, self._epsilon
        attn_p = self.attn_dropout_rate if self.training else 0.0
        out_p = self.dropout_rate if self.training else 0.0
        pre = self.normalize_before
        mask = attn_mask._data if isinstance(attn_mask, Tensor) else attn_mask
        with_cache = cache is not None
        if with_cache and len(cache) in (3, 5):
            # STATIC-cache decode (shared preconditions for both forms) —
            # checked BEFORE any dropout key is drawn: this inference-
            # shaped path applies no dropout, and consuming op_keys it
            # never uses would silently advance the global RNG stream.
            # 3-tuple (k, v, pos): full-width buffers. 5-tuple
            # (k_codes, k_scale, v_codes, v_scale, pos): INT8 CacheKV (the
            # reference fused_multi_transformer cache-quant mode) — codes
            # int8 [B, L_max, H, D], scales f32 [B, L_max, H], same
            # factored-scale attention as GPTForCausalLM cache_dtype=int8.
            if attn_p or out_p:
                raise NotImplementedError(
                    "static-cache decode is inference-only (no dropout): "
                    "call .eval() or set dropout rates to 0, or use the "
                    "growing (k, v) cache for cached training")
            if mask is not None:
                raise NotImplementedError(
                    "static-cache decode builds its own position mask; "
                    "combine custom masks on the growing-cache path")
            q8 = len(cache) == 5
            if q8:
                # same fail-loud tag rule as models/gpt.py _is_q8_cache:
                # length alone is not a safe dispatch key — the codes
                # buffer's dtype is
                c0 = cache[0]
                cdt0 = c0._data.dtype if isinstance(c0, Tensor) else c0.dtype
                if cdt0 != jnp.int8:
                    raise ValueError(
                        f"5-tuple static CacheKV must carry int8 codes "
                        f"first (got {cdt0}); full-width caches are "
                        f"(k, v, pos)")
            sargs = [query, self.qkv_weight, self.qkv_bias,
                     self.linear_weight, self.linear_bias,
                     self.pre_ln_scale, self.pre_ln_bias,
                     self.ln_scale, self.ln_bias] + list(cache)
            from ...ops.attention import (static_cache_update,
                                          static_cache_update_q8,
                                          static_cache_mask,
                                          attention_q8_cache)

            def fn_static(x, qkv_w, qkv_b, lw, lb, pls, plb, lns, lnb,
                          *cbufs):
                residual, q, k, v = self._mha_head(x, qkv_w, qkv_b, pls, plb)
                if q8:
                    kcb, ksb, vcb, vsb, p = cbufs
                    kc2, ks2 = static_cache_update_q8(kcb, ksb, k, p)
                    vc2, vs2 = static_cache_update_q8(vcb, vsb, v, p)
                    pmask = static_cache_mask(kc2.shape[1], q.shape[1], p)
                    o = attention_q8_cache(q, kc2, ks2, vc2, vs2, pmask)
                    new = (kc2, ks2, vc2, vs2)
                else:
                    kb, vb, p = cbufs
                    k2 = static_cache_update(kb, k, p)
                    v2 = static_cache_update(vb, v, p)
                    pmask = static_cache_mask(k2.shape[1], q.shape[1], p)
                    o = attention_reference(q, k2, v2, mask=pmask,
                                            score_dtype=q.dtype)
                    new = (k2, v2)
                o = self._mha_tail(o, residual, lw, lb, lns, lnb)
                return (o,) + new

            name = "fused_mha_static_cache" + ("_q8" if q8 else "")
            outs = apply_op(name, fn_static, sargs)
            o, new = outs[0], outs[1:]
            return o, tuple(t.detach() for t in new) + (
                cache[-1] + query.shape[1],)
        # dropout keys ride through apply_op as inputs (op_key → symbolic
        # under static recording: fresh mask every Executor.run)
        has_ka, has_ko = bool(attn_p), bool(out_p)

        def fn(x, qkv_w, qkv_b, lw, lb, pls, plb, lns, lnb, *rest):
            rest = list(rest)
            k_attn = rest.pop(0) if has_ka else None
            k_out = rest.pop(0) if has_ko else None
            past = rest
            residual, q, k, v = self._mha_head(x, qkv_w, qkv_b, pls, plb)
            if past:
                k = jnp.concatenate([past[0], k], axis=1)
                v = jnp.concatenate([past[1], v], axis=1)
            o = None
            if not past and mask is None:
                # short-seq fused MHA with in-kernel PRNG dropout (the
                # fused_attention_op.cu capability this layer mirrors):
                # same 2.2x-class win as BertAttention — the S² dropout
                # bits never exist in HBM. Pack cost is O(B·S·3F) copies.
                from ...ops.pallas.fused_mha import fused_mha, use_fused_mha
                from ...distributed import mesh as _dmesh
                b_, s_, nh_, hd_ = q.shape
                if (use_fused_mha(s_, nh_, hd_)
                        and _dmesh.mesh_axis_size("mp") == 1
                        and _dmesh.mesh_axis_size("sp") == 1):
                    qkvp = jnp.concatenate(
                        [q.reshape(b_, s_, nh_ * hd_),
                         k.reshape(b_, s_, nh_ * hd_),
                         v.reshape(b_, s_, nh_ * hd_)], axis=-1)
                    seed = (jax.random.randint(k_attn, (), 0, 2 ** 31 - 1)
                            if attn_p else None)
                    o = fused_mha(qkvp, nh_, dropout_p=attn_p,
                                  dropout_seed=seed
                                  ).reshape(b_, s_, nh_, hd_)
            if o is None and (attn_p or mask is not None):
                o = attention_reference(q, k, v, mask=mask, dropout_p=attn_p,
                                        dropout_key=k_attn)
            elif o is None:
                o = functional_attention(q, k, v)
            o = self._mha_tail(o, residual, lw, lb, lns, lnb, out_p, k_out)
            return (o, k, v) if past else o

        args = [query, self.qkv_weight, self.qkv_bias, self.linear_weight,
                self.linear_bias, self.pre_ln_scale, self.pre_ln_bias,
                self.ln_scale, self.ln_bias]
        if has_ka:
            args.append(_random.op_key())
        if has_ko:
            args.append(_random.op_key())
        if with_cache:
            args += [cache[0], cache[1]]
            o, k_new, v_new = apply_op("fused_multi_head_attention", fn, args)
            return o, (k_new.detach(), v_new.detach())
        return apply_op("fused_multi_head_attention", fn, args)


class FusedFeedForward(Layer):
    """Fused FFN block (fused_transformer.py:497)."""

    def __init__(self, d_model, dim_feedforward, dropout_rate=0.1,
                 epsilon=1e-5, activation="relu", act_dropout_rate=None,
                 normalize_before=False, name=None):
        super().__init__()
        self.d_model, self.dim_feedforward = d_model, dim_feedforward
        self.dropout_rate = dropout_rate
        self.act_dropout_rate = dropout_rate if act_dropout_rate is None \
            else act_dropout_rate
        self.normalize_before = normalize_before
        self._epsilon = epsilon
        self._act = {"relu": jax.nn.relu, "gelu": jax.nn.gelu}[activation]
        self.linear1_weight = self.create_parameter(
            [d_model, dim_feedforward], default_initializer=I.XavierUniform())
        self.linear1_bias = self.create_parameter([dim_feedforward], is_bias=True)
        self.linear2_weight = self.create_parameter(
            [dim_feedforward, d_model], default_initializer=I.XavierUniform())
        self.linear2_bias = self.create_parameter([d_model], is_bias=True)
        self.ln1_scale = self.create_parameter(
            [d_model], default_initializer=I.Constant(1.0))
        self.ln1_bias = self.create_parameter([d_model], is_bias=True)
        self.ln2_scale = self.create_parameter(
            [d_model], default_initializer=I.Constant(1.0))
        self.ln2_bias = self.create_parameter([d_model], is_bias=True)

    def forward(self, src, cache=None):
        eps = self._epsilon
        act = self._act
        pre = self.normalize_before
        p_act = self.act_dropout_rate if self.training else 0.0
        p_out = self.dropout_rate if self.training else 0.0
        has_ka, has_ko = bool(p_act), bool(p_out)

        def fn(x, w1, b1, w2, b2, s1, bb1, s2, bb2, *keys):
            keys = list(keys)
            k_act = keys.pop(0) if has_ka else None
            k_out = keys.pop(0) if has_ko else None
            residual = x
            if pre:
                x = _ln(x, s1, bb1, eps)
            h = _drop(act(x @ w1 + b1), p_act, k_act)
            y = _drop(h @ w2 + b2, p_out, k_out)
            y = residual + y
            if not pre:
                y = _ln(y, s2, bb2, eps)
            return y

        args = [src, self.linear1_weight, self.linear1_bias, self.linear2_weight,
                self.linear2_bias, self.ln1_scale, self.ln1_bias,
                self.ln2_scale, self.ln2_bias]
        if has_ka:
            args.append(_random.op_key())
        if has_ko:
            args.append(_random.op_key())
        return apply_op("fused_feedforward", fn, args)


class FusedTransformerEncoderLayer(Layer):
    """Attention + FFN encoder layer (fused_transformer.py:725)."""

    def __init__(self, d_model, nhead, dim_feedforward, dropout_rate=0.1,
                 activation="relu", attn_dropout_rate=None,
                 act_dropout_rate=None, normalize_before=False, name=None):
        super().__init__()
        ad = dropout_rate if attn_dropout_rate is None else attn_dropout_rate
        self.fused_attn = FusedMultiHeadAttention(
            d_model, nhead, dropout_rate=dropout_rate, attn_dropout_rate=ad,
            normalize_before=normalize_before)
        self.ffn = FusedFeedForward(
            d_model, dim_feedforward, dropout_rate=dropout_rate,
            activation=activation, act_dropout_rate=act_dropout_rate,
            normalize_before=normalize_before)

    def forward(self, src, src_mask=None, cache=None):
        if cache is not None:
            o, new_cache = self.fused_attn(src, attn_mask=src_mask, cache=cache)
            return self.ffn(o), new_cache
        return self.ffn(self.fused_attn(src, attn_mask=src_mask))


class FusedMultiTransformer(Layer):
    """Inference-oriented stacked transformer (fused_transformer.py:1021):
    N identical pre-LN layers executed in one module, the TPU analog of
    fused_multi_transformer_op.cu."""

    def __init__(self, embed_dim, num_heads, dim_feedforward, dropout_rate=0.0,
                 activation="gelu", normalize_before=True, num_layers=1,
                 epsilon=1e-5, name=None):
        super().__init__()
        self.layers = [
            FusedTransformerEncoderLayer(
                embed_dim, num_heads, dim_feedforward,
                dropout_rate=dropout_rate, activation=activation,
                attn_dropout_rate=dropout_rate, act_dropout_rate=dropout_rate,
                normalize_before=normalize_before)
            for _ in range(num_layers)]
        for i, l in enumerate(self.layers):
            self.add_sublayer(f"layer_{i}", l)

    def forward(self, src, attn_mask=None, caches=None):
        """caches: optional list of per-layer (k, v) Tensors; returns
        (out, new_caches) when given — incremental decode attends over the
        accumulated sequence (fused_multi_transformer_op CacheKV contract)."""
        x = src
        if caches is not None:
            new_caches = []
            for l, c in zip(self.layers, caches):
                x, nc = l(x, src_mask=attn_mask, cache=c)
                new_caches.append(nc)
            return x, new_caches
        for l in self.layers:
            x = l(x, src_mask=attn_mask)
        return x


class FusedEcMoe(Layer):
    """Fused expert-computation MoE (reference: incubate/nn/layer/
    fused_ec_moe.py) — thin facade over the expert-parallel MoELayer."""

    def __init__(self, hidden_size, inter_size, num_experts, act_type="gelu",
                 weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        act = {"relu": jax.nn.relu, "gelu": jax.nn.gelu}[act_type]
        from ..distributed.models.moe import MoELayer
        self.moe = MoELayer(hidden_size, inter_size, num_experts,
                            gate="gshard", activation=act)

    def forward(self, x, gate_logits=None):
        return self.moe(x, gate_logits=gate_logits)
