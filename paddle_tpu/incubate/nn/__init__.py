from .fused_transformer import (  # noqa: F401
    FusedMultiHeadAttention, FusedFeedForward, FusedTransformerEncoderLayer,
    FusedMultiTransformer, FusedEcMoe,
)
from . import functional  # noqa: F401
from ...nn.layer import Layer as _Layer


class FusedLinear(_Layer):
    """reference: incubate/nn/layer/fused_linear.py — Linear through the
    fused matmul+bias op."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 bias_attr=None, transpose_weight=False, name=None):
        super().__init__()
        from ...nn import initializer as I
        self.transpose_weight = transpose_weight
        shape = ([out_features, in_features] if transpose_weight
                 else [in_features, out_features])
        self.weight = self.create_parameter(
            shape, default_initializer=I.XavierNormal())
        self.bias = None
        if bias_attr is not False:
            self.bias = self.create_parameter(
                [out_features], is_bias=True,
                default_initializer=I.Constant(0.0))

    def forward(self, x):
        from .functional import fused_linear
        return fused_linear(x, self.weight, self.bias,
                            transpose_weight=self.transpose_weight)


class FusedBiasDropoutResidualLayerNorm(_Layer):
    """reference: incubate/nn/layer/fused_dropout_add.py analog — owns the
    LN scale/shift for the fused bias+dropout+residual+layernorm op."""

    def __init__(self, embed_dim, dropout_rate=0.5, weight_attr=None,
                 bias_attr=None, epsilon=1e-5, name=None):
        super().__init__()
        from ...nn import initializer as I
        self.dropout_rate = dropout_rate
        self.epsilon = epsilon
        self.ln_scale = self.create_parameter(
            [embed_dim], default_initializer=I.Constant(1.0))
        self.ln_bias = self.create_parameter(
            [embed_dim], is_bias=True, default_initializer=I.Constant(0.0))

    def forward(self, x, residual, bias=None):
        from .functional import fused_bias_dropout_residual_layer_norm
        return fused_bias_dropout_residual_layer_norm(
            x, residual, bias, self.ln_scale, self.ln_bias,
            dropout_rate=self.dropout_rate if self.training else 0.0,
            ln_epsilon=self.epsilon)
