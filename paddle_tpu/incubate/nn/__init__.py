from .fused_transformer import (  # noqa: F401
    FusedMultiHeadAttention, FusedFeedForward, FusedTransformerEncoderLayer,
    FusedMultiTransformer, FusedEcMoe,
)
from . import functional  # noqa: F401
