"""Fused functional ops (reference: python/paddle/incubate/nn/functional/).

On TPU "fused" is a compiler property: these compose jnp primitives inside
one apply_op so the whole expression jits as a single XLA fusion — the same
effect the reference gets from hand-written CUDA megakernels
(fused_matmul_bias via cublasLt, fused_bias_dropout_residual_layer_norm,
paddle/fluid/operators/fused/).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.tensor import Tensor, apply_op
from ...core import random as _random


def _ln(x, scale, bias, eps):
    """Shared layer-norm body (also used by fused_transformer layers)."""
    mu = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    y = (x - mu) * jax.lax.rsqrt(var + eps)
    if scale is not None:
        y = y * scale
    if bias is not None:
        y = y + bias
    return y


def _drop(x, p, key):
    """Shared inverted-scale dropout body."""
    if key is None or p == 0.0:
        return x
    keep = jax.random.bernoulli(key, 1.0 - p, x.shape)
    return jnp.where(keep, x / (1.0 - p), 0.0)


def fused_matmul_bias(x, y, bias=None, transpose_x=False, transpose_y=False,
                      name=None):
    """Reference: incubate/nn/functional/fused_matmul_bias.py (cublasLt
    epilogue fusion); here XLA fuses the bias add into the MXU matmul."""
    def fn(a, b, *rest):
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2)
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2)
        out = a @ b
        if rest:
            out = out + rest[0]
        return out
    args = [x, y] + ([bias] if bias is not None else [])
    return apply_op("fused_matmul_bias", fn, args)


def fused_linear(x, weight, bias=None, transpose_weight=False, name=None):
    return fused_matmul_bias(x, weight, bias, transpose_y=transpose_weight)


def fused_bias_dropout_residual_layer_norm(
        x, residual, bias=None, ln_scale=None, ln_bias=None,
        dropout_rate=0.5, ln_epsilon=1e-5, training=True, name=None):
    """Reference: fused_bias_dropout_residual_layer_norm op
    (operators/fused/fused_bias_dropout_residual_layer_norm_op.cu)."""
    has_key = dropout_rate > 0.0 and training

    def fn(xv, res, *rest):
        rest = list(rest)
        key = rest.pop() if has_key else None
        i = 0
        if bias is not None:
            xv = xv + rest[i]; i += 1
        xv = _drop(xv, dropout_rate if has_key else 0.0, key)
        y = xv + res
        scale = rest[i] if ln_scale is not None else None
        i += ln_scale is not None
        lb = rest[i] if ln_bias is not None else None
        return _ln(y, scale, lb, ln_epsilon)

    args = [x, residual] + [t for t in (bias, ln_scale, ln_bias)
                            if t is not None]
    if has_key:
        args.append(_random.op_key())
    return apply_op("fused_bias_dropout_residual_ln", fn, args)


def fused_linear_cross_entropy_array(x, weight, labels, *, chunk_size=128,
                                     transpose_weight=False):
    """Array-level fused LM-head + softmax cross-entropy, chunked over the
    sequence so the [B, S, vocab] logits are NEVER materialized.

    Beyond the reference: its closest op is the TP-sharded
    c_softmax_with_cross_entropy (operators/collective/
    c_softmax_with_cross_entropy_op.cu), which still takes full logits as
    input. Here the head matmul itself is inside the loss: a lax.map over
    sequence chunks computes per-chunk f32 logits -> logsumexp -> gold
    logit, and jax.checkpoint recomputes them in the backward, so peak HBM
    holds ONE chunk of logits (B*chunk*V) instead of the whole tensor —
    the difference between fitting B=16 and OOM at 1.3B/50k-vocab on a
    15.75G chip.

    x: [B, S, H]; weight: [V, H] ([H, V] with transpose_weight); labels
    [B, S] int. Returns per-token loss [B, S] float32.
    """
    B, S, H = x.shape
    # weight is [V, H] by default, [H, V] when transpose_weight
    V = weight.shape[-1] if transpose_weight else weight.shape[0]
    from ...ops.pallas.linear_ce import use_linear_ce, linear_cross_entropy
    if weight.ndim == 2 and use_linear_ce(B * S, H, V):
        # Pallas path: online-logsumexp head kernel — the [T, V] logits
        # never exist in HBM in the forward, and the backward rebuilds
        # bf16 dlogits from the saved lse instead of re-running the
        # checkpointed f32 chunk chain (ops/pallas/linear_ce.py).
        per_tok = linear_cross_entropy(
            x.reshape(B * S, H), weight, labels.reshape(B * S),
            w_layout="hv" if transpose_weight else "vh")
        return per_tok.reshape(B, S)
    if transpose_weight:
        weight = weight.T
    C = min(chunk_size, S)
    while S % C:
        C -= 1
    nc = S // C
    xs = x.reshape(B, nc, C, H).transpose(1, 0, 2, 3)
    ls = labels.reshape(B, nc, C).transpose(1, 0, 2).astype(jnp.int32)

    def chunk_loss(xc, lc):
        logits = jnp.einsum("bch,vh->bcv", xc, weight).astype(jnp.float32)
        lse = jax.nn.logsumexp(logits, axis=-1)
        gold = jnp.take_along_axis(logits, lc[..., None], axis=-1)[..., 0]
        return lse - gold

    losses = jax.lax.map(
        lambda args: jax.checkpoint(chunk_loss)(*args), (xs, ls))  # [nc,B,C]
    return losses.transpose(1, 0, 2).reshape(B, S)


def fused_linear_cross_entropy(x, weight, labels, chunk_size=128,
                               transpose_weight=False, name=None):
    """Tensor-level wrapper of fused_linear_cross_entropy_array."""
    def fn(xa, wa, la):
        return fused_linear_cross_entropy_array(
            xa, wa, la, chunk_size=chunk_size,
            transpose_weight=transpose_weight)
    return apply_op("fused_linear_cross_entropy", fn, [x, weight, labels])
