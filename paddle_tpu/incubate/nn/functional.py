"""Fused functional ops (reference: python/paddle/incubate/nn/functional/).

On TPU "fused" is a compiler property: these compose jnp primitives inside
one apply_op so the whole expression jits as a single XLA fusion — the same
effect the reference gets from hand-written CUDA megakernels
(fused_matmul_bias via cublasLt, fused_bias_dropout_residual_layer_norm,
paddle/fluid/operators/fused/).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.tensor import Tensor, apply_op
from ...core import random as _random


def _ln(x, scale, bias, eps):
    """Shared layer-norm body (also used by fused_transformer layers)."""
    mu = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    y = (x - mu) * jax.lax.rsqrt(var + eps)
    if scale is not None:
        y = y * scale
    if bias is not None:
        y = y + bias
    return y


def _drop(x, p, key):
    """Shared inverted-scale dropout body."""
    if key is None or p == 0.0:
        return x
    keep = jax.random.bernoulli(key, 1.0 - p, x.shape)
    return jnp.where(keep, x / (1.0 - p), 0.0)


def fused_matmul_bias(x, y, bias=None, transpose_x=False, transpose_y=False,
                      name=None):
    """Reference: incubate/nn/functional/fused_matmul_bias.py (cublasLt
    epilogue fusion); here XLA fuses the bias add into the MXU matmul."""
    def fn(a, b, *rest):
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2)
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2)
        out = a @ b
        if rest:
            out = out + rest[0]
        return out
    args = [x, y] + ([bias] if bias is not None else [])
    return apply_op("fused_matmul_bias", fn, args)


def fused_linear(x, weight, bias=None, transpose_weight=False, name=None):
    return fused_matmul_bias(x, weight, bias, transpose_y=transpose_weight)


def fused_bias_dropout_residual_layer_norm(
        x, residual, bias=None, ln_scale=None, ln_bias=None,
        dropout_rate=0.5, ln_epsilon=1e-5, training=True, name=None):
    """Reference: fused_bias_dropout_residual_layer_norm op
    (operators/fused/fused_bias_dropout_residual_layer_norm_op.cu)."""
    has_key = dropout_rate > 0.0 and training

    def fn(xv, res, *rest):
        rest = list(rest)
        key = rest.pop() if has_key else None
        i = 0
        if bias is not None:
            xv = xv + rest[i]; i += 1
        xv = _drop(xv, dropout_rate if has_key else 0.0, key)
        y = xv + res
        scale = rest[i] if ln_scale is not None else None
        i += ln_scale is not None
        lb = rest[i] if ln_bias is not None else None
        return _ln(y, scale, lb, ln_epsilon)

    args = [x, residual] + [t for t in (bias, ln_scale, ln_bias)
                            if t is not None]
    if has_key:
        args.append(_random.op_key())
    return apply_op("fused_bias_dropout_residual_ln", fn, args)
