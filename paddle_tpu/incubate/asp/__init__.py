"""Automatic SParsity (ASP) — 2:4 structured sparsity workflow.

Reference (SURVEY §2.3 incubate): python/paddle/incubate/asp/ — prune_model
applies n:m magnitude masks to supported weights, decorate(optimizer) makes
step() re-apply masks so pruned weights stay zero through training
(reference: asp/asp.py ASPHelper). On TPU the masked matmul runs dense
(the MXU has no sparse path), so ASP here is about model compression +
export; masks are plain jnp multiplies that XLA folds into the matmul.
"""
from __future__ import annotations

from typing import Dict, List

import numpy as np
import jax.numpy as jnp

from ...core.tensor import Tensor
from ...nn.layer import Layer
from ... import nn as _nn

_MASKS: Dict[int, jnp.ndarray] = {}


def calculate_density(x) -> float:
    arr = np.asarray(x._data if isinstance(x, Tensor) else x)
    return float((arr != 0).mean())


def _nm_mask_2d(w: np.ndarray, n: int = 2, m: int = 4) -> np.ndarray:
    """Keep the n largest-magnitude entries of every m consecutive weights
    along the input dim (reference: asp/utils.py create_mask n:m best-fit)."""
    rows, cols = w.shape
    pad = (-cols) % m
    wp = np.pad(np.abs(w), [(0, 0), (0, pad)])
    groups = wp.reshape(rows, -1, m)
    order = np.argsort(-groups, axis=-1)
    mask = np.zeros_like(groups)
    np.put_along_axis(mask, order[:, :, :n], 1.0, axis=-1)
    return mask.reshape(rows, -1)[:, :cols]


def _supported(layer, pname, p) -> bool:
    return isinstance(layer, _nn.Linear) and pname == "weight" and p.ndim == 2


def prune_model(model: Layer, n: int = 2, m: int = 4, mask_algo: str = "mask_1d",
                with_mask: bool = True) -> Dict[str, np.ndarray]:
    """Apply n:m masks to supported weights (reference: asp.prune_model)."""
    masks = {}
    for lname, layer in ([("", model)] + list(model.named_sublayers())):
        params = getattr(layer, "_parameters", None) or {}
        for pname, p in params.items():
            if p is None or not _supported(layer, pname, p):
                continue
            w = p.numpy()
            mask = _nm_mask_2d(w.T, n, m).T  # n:m along input dim
            p.set_value(w * mask)
            key = f"{lname}.{pname}" if lname else pname
            masks[key] = mask
            _MASKS[id(p)] = jnp.asarray(mask)
    return masks


def decorate(optimizer):
    """Wrap optimizer.step to re-apply masks after each update
    (reference: asp.decorate → OptimizerWithSparsityGuarantee)."""
    inner_step = optimizer.step

    def step():
        inner_step()
        for p in optimizer._param_list:
            mask = _MASKS.get(id(p))
            if mask is not None:
                p._data = p._data * mask
                p._node = None
    optimizer.step = step
    return optimizer


def reset_excluded_layers(model=None):
    pass  # exclusion list not yet tracked


def set_excluded_layers(model, layers):
    for layer in layers:
        for _, p in layer.named_parameters():
            _MASKS.pop(id(p), None)
