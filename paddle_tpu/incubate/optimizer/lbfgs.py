"""L-BFGS optimizer (reference: python/paddle/incubate/optimizer/lbfgs.py).

Closure-style API like the reference: `opt.step(closure)` re-evaluates the
loss as the line search probes points. History (s, y, rho) is kept as jax
arrays on device; the two-loop recursion is plain Python over the (small)
history so XLA sees only vector ops.
"""
from __future__ import annotations

from typing import Callable, List

import jax.numpy as jnp


def _flat_params(params):
    return jnp.concatenate([p._data.reshape(-1) for p in params])


def _flat_grads(params):
    return jnp.concatenate([
        (p.grad._data if p.grad is not None else jnp.zeros(p._data.size,
                                                           p._data.dtype)).reshape(-1)
        for p in params])


def _assign(params, flat):
    off = 0
    for p in params:
        n = p._data.size
        p.set_value(flat[off:off + n].reshape(p._data.shape))
        off += n


class LBFGS:
    def __init__(self, learning_rate=1.0, max_iter=20, max_eval=None,
                 tolerance_grad=1e-7, tolerance_change=1e-9, history_size=100,
                 line_search_fn=None, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        assert parameters is not None, "LBFGS requires parameters"
        if line_search_fn not in (None, "strong_wolfe"):
            raise ValueError(
                f"unsupported line_search_fn {line_search_fn!r}; expected "
                "'strong_wolfe' or None (reference contract, lbfgs.py)")
        self._params = list(parameters)
        self.lr = learning_rate
        self.max_iter = max_iter
        self.tol_grad = tolerance_grad
        self.tol_change = tolerance_change
        self.history_size = history_size
        self.line_search_fn = line_search_fn
        self._s: List = []
        self._y: List = []
        self._rho: List = []
        self._prev_flat_grad = None

    def clear_grad(self):
        for p in self._params:
            p.clear_grad()

    def _direction(self, g):
        q = g
        alphas = []
        for s, y, rho in zip(reversed(self._s), reversed(self._y),
                             reversed(self._rho)):
            a = rho * jnp.dot(s, q)
            alphas.append(a)
            q = q - a * y
        if self._s:
            s, y = self._s[-1], self._y[-1]
            gamma = jnp.dot(s, y) / jnp.maximum(jnp.dot(y, y), 1e-20)
            q = q * gamma
        for (s, y, rho), a in zip(zip(self._s, self._y, self._rho),
                                  reversed(alphas)):
            b = rho * jnp.dot(y, q)
            q = q + s * (a - b)
        return -q

    def step(self, closure: Callable):
        """closure() -> loss Tensor; must call backward itself (reference
        contract: lbfgs.py step(closure))."""
        loss = closure()
        flat_g = _flat_grads(self._params)
        if float(jnp.max(jnp.abs(flat_g))) <= self.tol_grad:
            return loss
        x0 = _flat_params(self._params)

        for _ in range(self.max_iter):
            d = self._direction(flat_g)
            t = self.lr
            if self.line_search_fn == "strong_wolfe":
                # backtracking with sufficient-decrease (Armijo) condition
                f0 = float(loss)
                g_dot_d = float(jnp.dot(flat_g, d))
                for _ls in range(20):
                    _assign(self._params, x0 + t * d)
                    self.clear_grad()
                    loss = closure()
                    if float(loss) <= f0 + 1e-4 * t * g_dot_d:
                        break
                    t *= 0.5
            else:  # None: fixed step, like the reference default
                _assign(self._params, x0 + t * d)
                self.clear_grad()
                loss = closure()
            new_g = _flat_grads(self._params)
            x1 = _flat_params(self._params)
            s, y = x1 - x0, new_g - flat_g
            ys = float(jnp.dot(y, s))
            if ys > 1e-10:
                self._s.append(s)
                self._y.append(y)
                self._rho.append(1.0 / ys)
                if len(self._s) > self.history_size:
                    self._s.pop(0); self._y.pop(0); self._rho.pop(0)
            if float(jnp.max(jnp.abs(x1 - x0))) < self.tol_change:
                break
            x0, flat_g = x1, new_g
        return loss
