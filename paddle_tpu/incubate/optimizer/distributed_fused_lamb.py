"""DistributedFusedLamb (reference: incubate/optimizer/distributed_fused_lamb.py:82).

The reference flattens all params into fused fp16/fp32 buffers, shards
moments across ranks, and runs a single fused CUDA LAMB kernel with a
sharded global norm. On TPU the same math falls out of the standard Lamb
update + ZeRO sharding: TrainStep already compiles the whole update into one
XLA program (the "fused" part), and `distributed.shard_optimizer_state`
shards moments over the dp/sdp axis (the "distributed" part). This class is
the API-compat facade wiring those two together.
"""
from __future__ import annotations

from ...optimizer.optimizer import Lamb


class DistributedFusedLamb(Lamb):
    # Always request ZeRO-1 sharding; the axis resolves LAZILY against the
    # mesh active when TrainStep builds, so construction order vs
    # dist.set_mesh doesn't matter (on a mesh without sdp/dp axes the axis
    # size is 1 and state stays replicated).
    _sharding_stage = 1

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01,
                 beta1=0.9, beta2=0.999, epsilon=1e-6, parameters=None,
                 grad_clip=None, exclude_from_weight_decay_fn=None,
                 clip_after_allreduce=True, is_grad_scaled_by_nranks=True,
                 use_master_param_norm=True, gradient_accumulation_steps=1,
                 use_master_acc_grad=True, nproc_per_node=None, name=None):
        super().__init__(learning_rate=learning_rate,
                         lamb_weight_decay=lamb_weight_decay,
                         beta1=beta1, beta2=beta2, epsilon=epsilon,
                         parameters=parameters, grad_clip=grad_clip,
                         exclude_from_weight_decay_fn=exclude_from_weight_decay_fn)

    @property
    def _sharding_axis(self) -> str:
        from ...distributed import mesh as _mesh
        m = _mesh.get_mesh()
        if m is not None and "sdp" in m.shape and m.shape["sdp"] > 1:
            return "sdp"
        if m is not None and "dp" in m.shape and m.shape["dp"] > 1:
            return "dp"
        return "sdp"
