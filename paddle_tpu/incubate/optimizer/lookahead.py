"""LookAhead and ModelAverage wrapper optimizers (reference:
python/paddle/incubate/optimizer/{lookahead.py,modelaverage.py}).

Both wrap an inner optimizer: LookAhead keeps slow weights updated every k
steps toward the fast weights; ModelAverage maintains a running average of
parameters applied at eval time.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ...optimizer.optimizer import Optimizer


class LookAhead(Optimizer):
    """reference: lookahead.py — slow = slow + alpha * (fast - slow) every
    k inner steps."""

    def __init__(self, inner_optimizer, alpha=0.5, k=5, name=None):
        self.inner = inner_optimizer
        self.alpha = float(alpha)
        self.k = int(k)
        self._step_count = 0
        self._slow = {}
        # delegate bookkeeping to the inner optimizer
        self._parameters = inner_optimizer._parameters
        self._grad_clip = inner_optimizer._grad_clip
        self._weight_decay = inner_optimizer._weight_decay
        self._lr = inner_optimizer._lr
        self._states = {}
        self._accumulated_grads = {}

    def get_lr(self):
        return self.inner.get_lr()

    def set_lr(self, lr):
        return self.inner.set_lr(lr)

    def _wd_for(self, p):
        return self.inner._wd_for(p)

    def init_state(self, param):
        st = self.inner.init_state(param)
        st = dict(st)
        st["slow"] = param.astype(jnp.float32)
        st["la_count"] = jnp.zeros((), jnp.int32)
        return st

    def update(self, param, grad, state, lr, step, wd=0.0):
        inner_state = {k: v for k, v in state.items()
                       if k not in ("slow", "la_count")}
        new_p, new_inner = self.inner.update(param, grad, inner_state, lr,
                                             step, wd)
        cnt = state["la_count"] + 1
        sync = (cnt % self.k) == 0
        slow = state["slow"]
        merged = slow + self.alpha * (new_p.astype(jnp.float32) - slow)
        new_slow = jnp.where(sync, merged, slow)
        new_p = jnp.where(sync, merged.astype(new_p.dtype), new_p)
        out = dict(new_inner)
        out["slow"] = new_slow
        out["la_count"] = cnt
        return new_p, out

    def step(self):
        return Optimizer.step(self)

    def clear_grad(self, set_to_zero=True):
        return self.inner.clear_grad(set_to_zero)


class ModelAverage(Optimizer):
    """reference: modelaverage.py — running parameter average; apply()/
    restore() swap averaged weights in for evaluation."""

    def __init__(self, average_window_rate=0.15, parameters=None,
                 min_average_window=10000, max_average_window=10000,
                 name=None):
        super().__init__(learning_rate=0.0, parameters=parameters)
        self.rate = average_window_rate
        self._sums = {}
        self._counts = {}
        self._backup = {}

    def init_state(self, param):
        return {}

    def update(self, param, grad, state, lr, step, wd=0.0):
        return param, state

    def step(self):
        """Accumulate the current parameter values into the average."""
        for p in self._param_list:
            s = self._sums.get(id(p))
            arr = np.asarray(p._data, np.float32)
            self._sums[id(p)] = arr if s is None else s + arr
            self._counts[id(p)] = self._counts.get(id(p), 0) + 1

    def minimize(self, loss=None, startup_program=None, parameters=None,
                 no_grad_set=None):
        self.step()

    def apply(self, executor=None, need_restore=True):
        import contextlib

        @contextlib.contextmanager
        def _ctx():
            for p in self._param_list:
                if id(p) in self._sums and self._counts.get(id(p)):
                    self._backup[id(p)] = p._data
                    avg = self._sums[id(p)] / self._counts[id(p)]
                    p._data = jnp.asarray(avg.astype(
                        np.asarray(p._data).dtype))
            try:
                yield
            finally:
                if need_restore:
                    self.restore()
        return _ctx()

    def restore(self, executor=None):
        for p in self._param_list:
            if id(p) in self._backup:
                p._data = self._backup.pop(id(p))
