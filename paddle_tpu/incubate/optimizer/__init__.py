"""Incubating optimizers (reference: python/paddle/incubate/optimizer/):
LBFGS (lbfgs.py) and DistributedFusedLamb (distributed_fused_lamb.py:82).
"""
from .lbfgs import LBFGS  # noqa: F401
from .distributed_fused_lamb import DistributedFusedLamb  # noqa: F401
