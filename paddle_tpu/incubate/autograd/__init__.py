"""paddle.incubate.autograd — functional/higher-order autodiff.

Reference (SURVEY §2.1 "Prim/composite autodiff"): incubate/autograd/
primx.py builds a primitive-op graph so static programs can take 2nd-order
derivatives; paddle.incubate.autograd exposes jvp/vjp/Jacobian/Hessian.
TPU-native: the substrate is already functional — these are direct
projections of jax.jvp/vjp/jacfwd/jacrev/hessian onto the Tensor API, and
they compose to any order (the whole reason the reference needed the prim
rewrite is structural here)."""
from __future__ import annotations

from typing import Callable, Sequence, Union

import jax
import jax.numpy as jnp

from ...core.tensor import Tensor
from ...core import autograd as _eager_autograd


def _unwrap(xs):
    if isinstance(xs, (tuple, list)):
        return tuple(_unwrap(x) for x in xs)
    return xs._data if isinstance(xs, Tensor) else jnp.asarray(xs)


def _wrap(xs):
    if isinstance(xs, (tuple, list)):
        return tuple(_wrap(x) for x in xs)
    return Tensor(xs)


def _as_pure(func: Callable) -> Callable:
    def pure(*arrays):
        out = func(*_wrap(arrays))
        return _unwrap(out)
    return pure


def jvp(func: Callable, xs, v=None):
    """Forward-mode: (outputs, J·v). reference: incubate/autograd/functional.py."""
    xs = xs if isinstance(xs, (tuple, list)) else (xs,)
    v = v if v is not None else tuple(
        Tensor(jnp.ones_like(_unwrap(x))) for x in xs)
    v = v if isinstance(v, (tuple, list)) else (v,)
    out, tangent = jax.jvp(_as_pure(func), _unwrap(xs), _unwrap(v))
    return _wrap(out), _wrap(tangent)


def vjp(func: Callable, xs, v=None):
    """Reverse-mode: (outputs, vᵀ·J). reference: functional.py vjp."""
    xs = xs if isinstance(xs, (tuple, list)) else (xs,)
    out, vjp_fn = jax.vjp(_as_pure(func), *_unwrap(xs))
    if v is None:
        v = jax.tree.map(jnp.ones_like, out)
    else:
        v = _unwrap(v if isinstance(v, (tuple, list)) else (v,))
        if not isinstance(out, tuple):
            v = v[0]
    grads = vjp_fn(v)
    return _wrap(out), _wrap(grads)


class Jacobian:
    """Lazy full Jacobian (reference: incubate/autograd/functional.py
    Jacobian — row-wise lazy evaluation; here jacrev, computed on access)."""

    def __init__(self, func: Callable, xs, is_batched: bool = False):
        self._func = func
        self._xs = xs if isinstance(xs, (tuple, list)) else (xs,)
        self._batched = is_batched
        self._val = None

    def _compute(self):
        if self._val is None:
            f = _as_pure(self._func)
            argnums = tuple(range(len(self._xs)))
            if self._batched:
                # per-example Jacobians [B, out, in] (reference Jacobian
                # is_batched contract) — vmap over the leading batch dim
                jac_fn = jax.vmap(jax.jacrev(f, argnums=argnums))
            else:
                jac_fn = jax.jacrev(f, argnums=argnums)
            jac = jac_fn(*_unwrap(self._xs))
            self._val = jac[0] if len(self._xs) == 1 else jac
        return self._val

    def __getitem__(self, idx):
        return _wrap(self._compute()[idx] if not isinstance(self._compute(), tuple)
                     else tuple(j[idx] for j in self._compute()))

    @property
    def shape(self):
        v = self._compute()
        v = v[0] if isinstance(v, tuple) else v
        return list(v.shape)

    def numpy(self):
        import numpy as np
        v = self._compute()
        return np.asarray(v if not isinstance(v, tuple) else v[0])


class Hessian(Jacobian):
    """Lazy Hessian of a scalar function (reference: functional.py Hessian)."""

    def _compute(self):
        if self._val is None:
            h = jax.hessian(lambda *a: _as_pure(self._func)(*a).reshape(()),
                            argnums=tuple(range(len(self._xs))))(
                *_unwrap(self._xs))
            if len(self._xs) == 1:
                h = h[0][0] if isinstance(h, tuple) else h
            self._val = h
        return self._val


def grad(func: Callable, xs, order: int = 1):
    """n-th order gradient of a scalar function (the capability the
    reference's prim/composite-grad machinery exists to provide). With
    multiple inputs, returns a tuple of gradients matching xs."""
    single = not isinstance(xs, (tuple, list))
    xs = (xs,) if single else tuple(xs)
    if len(xs) > 1 and order > 1:
        raise NotImplementedError(
            "grad(order>1) supports a single input; for second derivatives "
            "over multiple inputs use incubate.autograd.Hessian")
    pure = lambda *a: _as_pure(func)(*a).reshape(())  # noqa: E731
    argnums = tuple(range(len(xs)))
    g = pure
    for _ in range(order):
        g = jax.grad(g, argnums=argnums if len(xs) > 1 else 0)
    return _wrap(g(*_unwrap(xs)))


def forward_grad(func, xs, v=None):
    return jvp(func, xs, v)[1]
