"""incubate op surface (reference: python/paddle/incubate/__init__.py —
segment ops, graph message-passing ops, fused softmax-mask, misc)."""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor, apply_op

__all__ = [
    "segment_sum", "segment_mean", "segment_max", "segment_min",
    "graph_send_recv", "graph_sample_neighbors", "graph_khop_sampler",
    "graph_reindex", "softmax_mask_fuse", "softmax_mask_fuse_upper_triangle",
    "identity_loss", "unzip",
]


def _segment(op_label, jax_fn):
    def op(data, segment_ids, name=None):
        ids_np = np.asarray(segment_ids._data if isinstance(segment_ids, Tensor)
                            else segment_ids)
        n = int(ids_np.max()) + 1 if ids_np.size else 0

        def fn(d, ids):
            return jax_fn(d, ids, num_segments=n)
        return apply_op(op_label, fn, [data, segment_ids])
    op.__name__ = op_label
    return op


segment_sum = _segment("segment_sum", jax.ops.segment_sum)
segment_mean = _segment(
    "segment_mean",
    lambda d, ids, num_segments: jax.ops.segment_sum(d, ids, num_segments) /
    jnp.maximum(jax.ops.segment_sum(jnp.ones(d.shape[:1], d.dtype), ids,
                                    num_segments), 1.0).reshape(
        (-1,) + (1,) * (d.ndim - 1)))
segment_max = _segment("segment_max", jax.ops.segment_max)
segment_min = _segment("segment_min", jax.ops.segment_min)


def graph_send_recv(x, src_index, dst_index, pool_type="sum", out_size=None,
                    name=None):
    """reference: incubate.graph_send_recv — gather x rows at src, scatter-
    reduce to dst (GNN message passing). pool: sum|mean|max|min."""
    ids_np = np.asarray(dst_index._data if isinstance(dst_index, Tensor)
                        else dst_index)
    n = out_size or (int(np.asarray(
        x._data if isinstance(x, Tensor) else x).shape[0]))
    red = {"sum": jax.ops.segment_sum, "mean": None,
           "max": jax.ops.segment_max, "min": jax.ops.segment_min}[pool_type]

    def fn(xa, si, di):
        msgs = xa[si]
        if pool_type == "mean":
            s = jax.ops.segment_sum(msgs, di, num_segments=n)
            cnt = jax.ops.segment_sum(jnp.ones(msgs.shape[:1], xa.dtype), di,
                                      num_segments=n)
            return s / jnp.maximum(cnt, 1.0).reshape(
                (-1,) + (1,) * (s.ndim - 1))
        out = red(msgs, di, num_segments=n)
        if pool_type in ("max", "min"):
            out = jnp.where(jnp.isfinite(out), out, 0.0)
        return out
    return apply_op("graph_send_recv", fn, [x, src_index, dst_index])


def graph_sample_neighbors(row, colptr, input_nodes, sample_size=-1,
                           eids=None, return_eids=False, perm_buffer=None,
                           flag_perm_buffer=False, name=None):
    """reference: incubate.graph_sample_neighbors over CSC (colptr/row).
    Host-side sampling (the reference's CPU kernel path); returns
    (out_neighbors, out_count)."""
    rown = np.asarray(row._data if isinstance(row, Tensor) else row)
    cp = np.asarray(colptr._data if isinstance(colptr, Tensor) else colptr)
    nodes = np.asarray(input_nodes._data if isinstance(input_nodes, Tensor)
                       else input_nodes).reshape(-1)
    rng = np.random.RandomState(
        int(jax.random.randint(_split_key(), (), 0, 2**31 - 1)))
    neigh, counts = [], []
    for nd in nodes.tolist():
        s, e = int(cp[nd]), int(cp[nd + 1])
        cand = rown[s:e]
        if sample_size >= 0 and len(cand) > sample_size:
            cand = rng.choice(cand, sample_size, replace=False)
        neigh.append(cand)
        counts.append(len(cand))
    out = np.concatenate(neigh) if neigh else np.empty(0, rown.dtype)
    return (Tensor(jnp.asarray(out)),
            Tensor(jnp.asarray(np.asarray(counts, np.int32))))


def graph_khop_sampler(row, colptr, input_nodes, sample_sizes,
                       sorted_eids=None, return_eids=False, name=None):
    """reference: incubate.graph_khop_sampler — multi-hop expansion.
    Returns (edge_src, edge_dst, sample_index, reindex_nodes)."""
    frontier = np.asarray(input_nodes._data if isinstance(input_nodes, Tensor)
                          else input_nodes).reshape(-1)
    all_src, all_dst = [], []
    seen = list(frontier.tolist())
    for k in sample_sizes:
        nb, cnt = graph_sample_neighbors(row, colptr,
                                         Tensor(jnp.asarray(frontier)), k)
        nb_np = np.asarray(nb._data)
        cnt_np = np.asarray(cnt._data)
        dst = np.repeat(frontier, cnt_np)
        all_src.append(nb_np)
        all_dst.append(dst)
        frontier = np.unique(nb_np)
        seen.extend(frontier.tolist())
    src = np.concatenate(all_src) if all_src else np.empty(0, np.int64)
    dst = np.concatenate(all_dst) if all_dst else np.empty(0, np.int64)
    uniq = np.asarray(sorted(set(seen)), np.int64)
    remap = {int(v): i for i, v in enumerate(uniq)}
    r_src = np.asarray([remap[int(v)] for v in src], np.int64)
    r_dst = np.asarray([remap[int(v)] for v in dst], np.int64)
    seeds = np.asarray(input_nodes._data if isinstance(input_nodes, Tensor)
                       else input_nodes).reshape(-1)
    # reindex of the INPUT nodes: where each seed landed in sample_index
    reindex_nodes = np.asarray([remap[int(v)] for v in seeds], np.int64)
    return (Tensor(jnp.asarray(r_src)), Tensor(jnp.asarray(r_dst)),
            Tensor(jnp.asarray(uniq)),
            Tensor(jnp.asarray(reindex_nodes)))


def graph_reindex(x, neighbors, count, value_buffer=None, index_buffer=None,
                  flag_buffer_hashtable=False, name=None):
    """reference: incubate.graph_reindex — contiguous relabeling of
    (x, neighbors) ids. Returns (reindexed_src, reindexed_dst, out_nodes)."""
    xa = np.asarray(x._data if isinstance(x, Tensor) else x).reshape(-1)
    nb = np.asarray(neighbors._data if isinstance(neighbors, Tensor)
                    else neighbors).reshape(-1)
    cnt = np.asarray(count._data if isinstance(count, Tensor)
                     else count).reshape(-1)
    order = []
    seen = set()
    for v in np.concatenate([xa, nb]).tolist():
        if v not in seen:
            seen.add(v)
            order.append(v)
    remap = {v: i for i, v in enumerate(order)}
    r_nb = np.asarray([remap[int(v)] for v in nb], np.int64)
    dst = np.repeat(xa, cnt)
    r_dst = np.asarray([remap[int(v)] for v in dst], np.int64)
    out_nodes = np.asarray(order, np.int64)
    return (Tensor(jnp.asarray(r_nb)), Tensor(jnp.asarray(r_dst)),
            Tensor(jnp.asarray(out_nodes)))


def softmax_mask_fuse(x, mask, name=None):
    """reference: incubate.softmax_mask_fuse (fused_softmax_mask op,
    SURVEY §5.7) — softmax(x + mask) in one fusion."""
    def fn(a, m):
        return jax.nn.softmax(a.astype(jnp.float32) + m.astype(jnp.float32),
                              axis=-1).astype(a.dtype)
    return apply_op("softmax_mask_fuse", fn, [x, mask])


def softmax_mask_fuse_upper_triangle(x, name=None):
    """reference: fused_softmax_mask_upper_triangle — causal-masked softmax
    (the attention-score path of the reference's fused attention)."""
    def fn(a):
        s_q, s_k = a.shape[-2], a.shape[-1]
        cmask = jnp.tril(jnp.ones((s_q, s_k), bool), k=s_k - s_q)
        logits = jnp.where(cmask, a.astype(jnp.float32), -1e30)
        return jax.nn.softmax(logits, axis=-1).astype(a.dtype)
    return apply_op("softmax_mask_fuse_upper_triangle", fn, [x])


def identity_loss(x, reduction="none", name=None):
    """reference: incubate.identity_loss (IPU-era loss marker)."""
    from ..core import ops as _ops
    if reduction in (0, "sum"):
        return _ops.sum(x)
    if reduction in (1, "mean"):
        return _ops.mean(x)
    return x


def unzip(input, lod, len_=None, name=None):  # noqa: A002
    """reference: incubate.operators.unzip — scatter rows back to lod
    offsets (sparse-feature widening)."""
    arr = np.asarray(input._data if isinstance(input, Tensor) else input)
    lod_np = np.asarray(lod._data if isinstance(lod, Tensor) else lod)
    n = int(lod_np[-1])
    out = np.zeros((n,) + arr.shape[1:], arr.dtype)
    for i in range(len(lod_np) - 1):
        s, e = int(lod_np[i]), int(lod_np[i + 1])
        if e > s:
            out[s:e] = arr[i]
    return Tensor(jnp.asarray(out))


def _split_key():
    from ..core import random as _r
    return _r.split_key()
