"""Gate configuration objects (reference: incubate/distributed/models/moe/gate/
naive_gate.py, gshard_gate.py, switch_gate.py).

In the reference each gate is an nn.Layer owning the routing projection; here
the projection lives in MoELayer (one einsum) and gates are declarative
configs selecting top-k and the aux-loss formula — the routing math itself is
the XLA-friendly one-hot dispatch in moe_layer._topk_dispatch.
"""
from __future__ import annotations


class BaseGate:
    gate_type = "naive"
    top_k = 2

    def __init__(self, d_model=None, num_experts=None, top_k=None):
        self.d_model = d_model
        self.num_experts = num_experts
        if top_k is not None:
            self.top_k = top_k


class NaiveGate(BaseGate):
    """Plain top-k routing, no auxiliary loss (naive_gate.py)."""
    gate_type = "naive"
    top_k = 2


class GShardGate(BaseGate):
    """Top-2 routing + load-balance aux loss (gshard_gate.py)."""
    gate_type = "gshard"
    top_k = 2


class SwitchGate(BaseGate):
    """Top-1 routing + load-balance aux loss (switch_gate.py)."""
    gate_type = "switch"
    top_k = 1
