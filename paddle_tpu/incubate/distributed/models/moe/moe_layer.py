"""Mixture-of-Experts layer with expert parallelism over the `ep` mesh axis.

Reference surface: python/paddle/incubate/distributed/models/moe/moe_layer.py:260
(MoELayer with Naive/GShard/Switch gates, moe/gate/*.py) whose expert-parallel
all-to-all is the global_scatter/global_gather op pair
(paddle/fluid/operators/collective/global_scatter_op.cu).

TPU-native inversion: experts live as STACKED weights [E, ...] annotated
P("ep", ...) — each ep shard owns E/ep experts — and dispatch/combine are
GShard-style one-hot einsums with a static capacity, so the whole layer is
three einsums XLA lowers onto the MXU; the resharding of the dispatched
[E, C, M] tensor across the ep axis IS the all-to-all (XLA inserts it from
the sharding annotations — no bespoke global_scatter kernel). Static capacity
(capacity_factor) replaces the reference's dynamic per-expert buffers because
XLA requires static shapes; overflow tokens are dropped exactly as GShard
does.
"""
from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from .....core.tensor import apply_op
from .....nn.layer import Layer
from .....nn import initializer as I
from .....distributed import mesh as _mesh


def _capacity(num_tokens: int, num_experts: int, top_k: int,
              capacity_factor: float) -> int:
    c = int(math.ceil(capacity_factor * top_k * num_tokens / num_experts))
    return max(4, c + (-c) % 4)   # pad to a multiple of 4 lanes


# ---- drop-rate telemetry (bench/debug) -------------------------------------
# When enabled, each EAGER MoE forward accumulates how many (token, slot)
# assignments overflowed their expert's static capacity — the quantity the
# capacity_factor knob trades against padding compute. Tracer-safe: inside
# jit traces the values are symbolic and recording is skipped, so enable it
# and run one eager forward (bench.py bench_moe does exactly that).
_DROP_REC = {"on": False, "kept": 0, "assigned": 0}


def record_drop_rate(on: bool = True):
    """Toggle (and reset) eager drop-rate accumulation."""
    _DROP_REC.update(on=bool(on), kept=0, assigned=0)


def measured_drop_rate():
    """Fraction of (token, slot) assignments dropped since enabling, or
    None if nothing eager was recorded."""
    a = _DROP_REC["assigned"]
    return None if a == 0 else 1.0 - _DROP_REC["kept"] / a


def _record_keeps(kept, assigned):
    if _DROP_REC["on"] and not isinstance(kept, jax.core.Tracer):
        _DROP_REC["kept"] += int(kept)
        _DROP_REC["assigned"] += int(assigned)


def _topk_dispatch(probs, top_k: int, capacity: int):
    """GShard one-hot dispatch: probs [N, E] -> combine/dispatch [N, E, C].

    Returns (combine weights, boolean dispatch mask, fraction-routed per
    expert from the top-1 slot — the aux-loss ingredient).
    """
    n, e = probs.shape
    gate_vals, idx = lax.top_k(probs, top_k)                  # [N, k]
    if top_k > 1:
        denom = jnp.sum(gate_vals, axis=-1, keepdims=True)
        gate_vals = gate_vals / jnp.maximum(denom, 1e-9)
    # top_k == 1 (Switch): keep the RAW router probability so the output is
    # scaled by it and the router learns from the task loss (renormalizing
    # would make the weight a constant 1 with zero gradient).
    combine = jnp.zeros((n, e, capacity), probs.dtype)
    counts = jnp.zeros((e,), jnp.int32)
    frac_top1 = None
    for slot in range(top_k):
        oh = jax.nn.one_hot(idx[:, slot], e, dtype=jnp.int32)  # [N, E]
        if frac_top1 is None:
            frac_top1 = jnp.mean(oh.astype(probs.dtype), axis=0)
        pos = jnp.cumsum(oh, axis=0) - 1 + counts              # [N, E]
        counts = counts + jnp.sum(oh, axis=0)
        loc = jnp.sum(pos * oh, axis=-1)                       # [N]
        keep = (loc < capacity).astype(probs.dtype)
        loc_oh = jax.nn.one_hot(loc, capacity, dtype=probs.dtype)  # [N, C]
        combine = combine + (gate_vals[:, slot] * keep)[:, None, None] \
            * oh.astype(probs.dtype)[:, :, None] * loc_oh[:, None, :]
    dispatch = combine > 0
    return combine, dispatch, frac_top1


def _topk_routing(probs, top_k: int, capacity: int):
    """Index-form routing: per (token, slot) the expert id, capacity slot,
    and keep flag — same GShard cumsum assignment as _topk_dispatch but
    WITHOUT materializing [N, E, C] one-hot tensors."""
    n, e = probs.shape
    gate_vals, idx = lax.top_k(probs, top_k)                  # [N, k]
    if top_k > 1:
        denom = jnp.sum(gate_vals, axis=-1, keepdims=True)
        gate_vals = gate_vals / jnp.maximum(denom, 1e-9)
    # ONE slot-major pass (r5): flattening [N, k] slot-major makes a single
    # cumsum reproduce the loop's priority order (every slot-0 assignment
    # outranks every slot-1 assignment) with k fewer op chains
    ohf = jax.nn.one_hot(idx.T.reshape(-1), e, dtype=jnp.int32)  # [k·N, E]
    frac_top1 = jnp.mean(ohf[:n].astype(probs.dtype), axis=0)
    pos = jnp.cumsum(ohf, axis=0) - 1
    loc_f = jnp.sum(pos * ohf, axis=-1)                          # [k·N]
    locs = loc_f.reshape(top_k, n).T                             # [N, k]
    keeps = locs < capacity
    return gate_vals, idx, locs, keeps, frac_top1


def _moe_forward(x, gw, w1, b1, w2, b2, *, top_k, capacity_factor, gate_type,
                 activation, ext_logits=None):
    b, s, m = x.shape
    e = w1.shape[0]
    tokens = x.reshape(b * s, m)
    if ext_logits is None:
        logits = jnp.einsum("nm,me->ne", tokens, gw,
                            preferred_element_type=jnp.float32)
    else:
        logits = ext_logits.reshape(b * s, e).astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)
    cap = _capacity(b * s, e, top_k, capacity_factor)
    import os
    n = tokens.shape[0]
    env = os.environ.get("PADDLE_TPU_MOE_GATHER")
    if env is not None:
        gather_mode = env == "1"
    else:
        # jax<0.5 SPMD-partitioner bug (r8, bisected with the numerics
        # stats): a gather whose operand feeds/consumes an ep-sharded
        # constraint partitions WRONG — routing indices stay exact but
        # tokens_ext[src] (dispatch) and out_ext[safe_pos] (combine) read
        # other shards' rows (~100% of outputs off; replicating the gather
        # operands fixes it, proving the partitioning is at fault). The
        # one-hot einsum dispatch is exact under the same mesh, so on old
        # runtimes with a real ep axis we take it; the index-gather fast
        # path stays the default everywhere else.
        old_jax = jax.__version_info__ < (0, 5, 0)
        gather_mode = not (old_jax and _mesh.mesh_axis_size("ep") > 1)

    if gather_mode:
        # INDEX dispatch (r4): the one-hot einsum pair costs
        # O(N·E·C·M) MXU FLOPs — at the measured bench shape as much as
        # the experts themselves (66% routing overhead). Scatter each
        # (token, slot) id into its [E·C] slot and GATHER rows instead:
        # O(N·k·M) bytes, zero matmul FLOPs. Dropped tokens (loc >= C)
        # target the sentinel row; empty slots read the appended zero row.
        gate_vals, idx, locs, keeps, frac = _topk_routing(probs, top_k, cap)
        if _DROP_REC["on"]:  # guard BEFORE the reduction: off = zero cost
            _record_keeps(jnp.sum(keeps), keeps.size)
        me = jnp.mean(probs, axis=0)
        aux = e * jnp.sum(me * frac) if gate_type in ("gshard", "switch") \
            else jnp.zeros((), probs.dtype)

        flatpos = idx * cap + locs                             # [N, k]
        safe_pos = jnp.where(keeps, flatpos, e * cap)          # drop slot
        src = jnp.full((e * cap,), n, jnp.int32)
        tok_ids = jnp.broadcast_to(jnp.arange(n, dtype=jnp.int32)[:, None],
                                   (n, top_k))
        src = src.at[safe_pos.reshape(-1)].set(tok_ids.reshape(-1),
                                               mode="drop")
        tokens_ext = jnp.concatenate(
            [tokens, jnp.zeros((1, m), tokens.dtype)], axis=0)
        expert_in = tokens_ext[src].reshape(e, cap, m)
        expert_in = _mesh.shard_constraint(expert_in, "ep", None, None)
        h = activation(jnp.einsum("ecm,emh->ech", expert_in, w1)
                       + b1[:, None, :])
        out = jnp.einsum("ech,ehm->ecm", h, w2) + b2[:, None, :]
        out = _mesh.shard_constraint(out, "ep", None, None)
        out_ext = jnp.concatenate(
            [out.reshape(e * cap, m), jnp.zeros((1, m), out.dtype)], axis=0)
        # ONE batched combine gather (r5): all N·k rows in a single gather
        # + a k-reduction, instead of k sequential gather/axpy chains
        rows = out_ext[safe_pos]                               # [N, k, M]
        w_all = (gate_vals * keeps.astype(probs.dtype)).astype(x.dtype)
        y = jnp.einsum("nk,nkm->nm", w_all, rows)
        return y.reshape(b, s, m), aux.astype(jnp.float32)

    combine, dispatch, frac = _topk_dispatch(probs, top_k, cap)
    if _DROP_REC["on"]:  # guard BEFORE the [N,E,C] reduction
        _record_keeps(jnp.sum(dispatch), n * top_k)

    # load-balance aux loss: GShard/Switch  E * sum_e mean_prob_e * frac_e
    me = jnp.mean(probs, axis=0)
    aux = e * jnp.sum(me * frac) if gate_type in ("gshard", "switch") \
        else jnp.zeros((), probs.dtype)

    # dispatch -> [E, C, M], sharded over ep: XLA inserts the all-to-all here
    expert_in = jnp.einsum("nec,nm->ecm", dispatch.astype(x.dtype), tokens)
    expert_in = _mesh.shard_constraint(expert_in, "ep", None, None)
    h = activation(jnp.einsum("ecm,emh->ech", expert_in, w1) + b1[:, None, :])
    out = jnp.einsum("ech,ehm->ecm", h, w2) + b2[:, None, :]
    out = _mesh.shard_constraint(out, "ep", None, None)
    y = jnp.einsum("nec,ecm->nm", combine.astype(x.dtype), out)
    return y.reshape(b, s, m), aux.astype(jnp.float32)


class MoELayer(Layer):
    """Top-k routed expert FFN (reference: moe_layer.py:260).

    gate: "naive" (top-k, no aux loss), "gshard" (top-2 + load-balance
    loss), or "switch" (top-1 + load-balance loss). The auxiliary loss of
    the latest forward is exposed as `.aux_loss` and should be added to the
    training loss (reference handles this inside its gates the same way).
    """

    def __init__(self, d_model: int, d_hidden: int, num_experts: int,
                 gate: str = "gshard", top_k: Optional[int] = None,
                 capacity_factor: float = 1.25, activation=None,
                 moe_group=None, name=None):
        super().__init__()
        from .gate import BaseGate
        if isinstance(gate, BaseGate):
            if top_k is None:
                top_k = gate.top_k
            gate = gate.gate_type
        if gate not in ("naive", "gshard", "switch"):
            raise ValueError(f"unknown gate {gate!r}")
        self.d_model, self.d_hidden, self.num_experts = d_model, d_hidden, num_experts
        self.gate_type = gate
        self.top_k = top_k if top_k is not None else (1 if gate == "switch" else 2)
        if gate == "switch" and self.top_k != 1:
            raise ValueError("switch gate is top-1 by definition")
        self.capacity_factor = capacity_factor
        self._activation = activation if activation is not None else jax.nn.gelu
        self.aux_loss = None

        self.gate_weight = self.create_parameter(
            [d_model, num_experts], default_initializer=I.XavierUniform())
        self.w1 = self.create_parameter(
            [num_experts, d_model, d_hidden], default_initializer=I.XavierUniform())
        self.b1 = self.create_parameter(
            [num_experts, d_hidden], default_initializer=I.Constant(0.0))
        self.w2 = self.create_parameter(
            [num_experts, d_hidden, d_model], default_initializer=I.XavierUniform())
        self.b2 = self.create_parameter(
            [num_experts, d_model], default_initializer=I.Constant(0.0))
        # expert-parallel shardings (no-ops on meshes without an ep axis)
        self.w1.pspec = P("ep", None, None)
        self.b1.pspec = P("ep", None)
        self.w2.pspec = P("ep", None, None)
        self.b2.pspec = P("ep", None)

    def forward(self, x, gate_logits=None):
        """gate_logits: optional externally computed router logits
        [B, S, E] (FusedEcMoe contract); routes with them instead of the
        internal gate projection."""
        args = [x, self.gate_weight, self.w1, self.b1, self.w2, self.b2]
        if gate_logits is not None:
            args.append(gate_logits)

        def fn(a, gw, w1, b1, w2, b2, *ext):
            return _moe_forward(
                a, gw, w1, b1, w2, b2, top_k=self.top_k,
                capacity_factor=self.capacity_factor,
                gate_type=self.gate_type, activation=self._activation,
                ext_logits=ext[0] if ext else None)

        y, aux = apply_op("moe_layer", fn, args)
        self.aux_loss = aux
        return y
