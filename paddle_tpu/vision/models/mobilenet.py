"""MobileNet V1/V2/V3 (reference: python/paddle/vision/models/mobilenetv{1,2,3}.py).

Depthwise convs lower to grouped lax.conv_general_dilated; XLA maps them to
the MXU when channel counts are lane-aligned (multiples of 128 ideal).
"""
from __future__ import annotations

from ... import nn

__all__ = ["MobileNetV1", "MobileNetV2", "MobileNetV3Small", "MobileNetV3Large",
           "mobilenet_v1", "mobilenet_v2", "mobilenet_v3_small", "mobilenet_v3_large"]


def _make_divisible(v, divisor=8, min_value=None):
    min_value = min_value or divisor
    new_v = max(min_value, int(v + divisor / 2) // divisor * divisor)
    if new_v < 0.9 * v:
        new_v += divisor
    return new_v


class ConvBNLayer(nn.Layer):
    def __init__(self, in_c, out_c, kernel, stride=1, groups=1, act=nn.ReLU):
        super().__init__()
        self.conv = nn.Conv2D(in_c, out_c, kernel, stride=stride,
                              padding=kernel // 2, groups=groups, bias_attr=False)
        self.bn = nn.BatchNorm2D(out_c)
        self.act = act() if act else None

    def forward(self, x):
        x = self.bn(self.conv(x))
        return self.act(x) if self.act else x


class DepthwiseSeparable(nn.Layer):
    def __init__(self, in_c, out_c, stride):
        super().__init__()
        self.depthwise = ConvBNLayer(in_c, in_c, 3, stride=stride, groups=in_c)
        self.pointwise = ConvBNLayer(in_c, out_c, 1)

    def forward(self, x):
        return self.pointwise(self.depthwise(x))


class MobileNetV1(nn.Layer):
    """reference mobilenetv1.py:103"""

    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes, self.with_pool = num_classes, with_pool
        s = lambda c: int(c * scale)
        cfg = [(32, 64, 1), (64, 128, 2), (128, 128, 1), (128, 256, 2),
               (256, 256, 1), (256, 512, 2)] + [(512, 512, 1)] * 5 + \
              [(512, 1024, 2), (1024, 1024, 1)]
        self.conv1 = ConvBNLayer(3, s(32), 3, stride=2)
        self.blocks = nn.Sequential(
            *[DepthwiseSeparable(s(i), s(o), st) for i, o, st in cfg])
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.fc = nn.Linear(s(1024), num_classes)

    def forward(self, x):
        x = self.blocks(self.conv1(x))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(x.flatten(1))
        return x


class InvertedResidual(nn.Layer):
    def __init__(self, inp, oup, stride, expand_ratio):
        super().__init__()
        hidden = int(round(inp * expand_ratio))
        self.use_res = stride == 1 and inp == oup
        layers = []
        if expand_ratio != 1:
            layers.append(ConvBNLayer(inp, hidden, 1, act=nn.ReLU6))
        layers += [ConvBNLayer(hidden, hidden, 3, stride=stride, groups=hidden,
                               act=nn.ReLU6),
                   ConvBNLayer(hidden, oup, 1, act=None)]
        self.conv = nn.Sequential(*layers)

    def forward(self, x):
        return x + self.conv(x) if self.use_res else self.conv(x)


class MobileNetV2(nn.Layer):
    """reference mobilenetv2.py:84"""

    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes, self.with_pool = num_classes, with_pool
        cfg = [(1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 3, 2), (6, 64, 4, 2),
               (6, 96, 3, 1), (6, 160, 3, 2), (6, 320, 1, 1)]
        in_c = _make_divisible(32 * scale)
        last_c = _make_divisible(1280 * max(1.0, scale))
        feats = [ConvBNLayer(3, in_c, 3, stride=2, act=nn.ReLU6)]
        for t, c, n, s in cfg:
            out_c = _make_divisible(c * scale)
            for i in range(n):
                feats.append(InvertedResidual(in_c, out_c, s if i == 0 else 1, t))
                in_c = out_c
        feats.append(ConvBNLayer(in_c, last_c, 1, act=nn.ReLU6))
        self.features = nn.Sequential(*feats)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = nn.Sequential(nn.Dropout(0.2),
                                            nn.Linear(last_c, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.classifier(x.flatten(1))
        return x


class SqueezeExcite(nn.Layer):
    def __init__(self, channels, reduction=4):
        super().__init__()
        squeeze = _make_divisible(channels // reduction)
        self.pool = nn.AdaptiveAvgPool2D(1)
        self.fc1 = nn.Conv2D(channels, squeeze, 1)
        self.fc2 = nn.Conv2D(squeeze, channels, 1)
        self.relu = nn.ReLU()
        self.hsig = nn.Hardsigmoid()

    def forward(self, x):
        s = self.hsig(self.fc2(self.relu(self.fc1(self.pool(x)))))
        return x * s


class _V3Block(nn.Layer):
    def __init__(self, in_c, exp, out_c, kernel, stride, use_se, act):
        super().__init__()
        self.use_res = stride == 1 and in_c == out_c
        layers = []
        if exp != in_c:
            layers.append(ConvBNLayer(in_c, exp, 1, act=act))
        layers.append(ConvBNLayer(exp, exp, kernel, stride=stride, groups=exp,
                                  act=act))
        if use_se:
            layers.append(SqueezeExcite(exp))
        layers.append(ConvBNLayer(exp, out_c, 1, act=None))
        self.conv = nn.Sequential(*layers)

    def forward(self, x):
        return x + self.conv(x) if self.use_res else self.conv(x)


_V3_SMALL = [  # k, exp, c, se, act, s
    (3, 16, 16, True, nn.ReLU, 2), (3, 72, 24, False, nn.ReLU, 2),
    (3, 88, 24, False, nn.ReLU, 1), (5, 96, 40, True, nn.Hardswish, 2),
    (5, 240, 40, True, nn.Hardswish, 1), (5, 240, 40, True, nn.Hardswish, 1),
    (5, 120, 48, True, nn.Hardswish, 1), (5, 144, 48, True, nn.Hardswish, 1),
    (5, 288, 96, True, nn.Hardswish, 2), (5, 576, 96, True, nn.Hardswish, 1),
    (5, 576, 96, True, nn.Hardswish, 1)]
_V3_LARGE = [
    (3, 16, 16, False, nn.ReLU, 1), (3, 64, 24, False, nn.ReLU, 2),
    (3, 72, 24, False, nn.ReLU, 1), (5, 72, 40, True, nn.ReLU, 2),
    (5, 120, 40, True, nn.ReLU, 1), (5, 120, 40, True, nn.ReLU, 1),
    (3, 240, 80, False, nn.Hardswish, 2), (3, 200, 80, False, nn.Hardswish, 1),
    (3, 184, 80, False, nn.Hardswish, 1), (3, 184, 80, False, nn.Hardswish, 1),
    (3, 480, 112, True, nn.Hardswish, 1), (3, 672, 112, True, nn.Hardswish, 1),
    (5, 672, 160, True, nn.Hardswish, 2), (5, 960, 160, True, nn.Hardswish, 1),
    (5, 960, 160, True, nn.Hardswish, 1)]


class _MobileNetV3(nn.Layer):
    """reference mobilenetv3.py:133 (MobileNetV3 base)."""

    def __init__(self, cfg, last_exp, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes, self.with_pool = num_classes, with_pool
        s = lambda c: _make_divisible(c * scale)
        in_c = s(16)
        feats = [ConvBNLayer(3, in_c, 3, stride=2, act=nn.Hardswish)]
        for k, exp, c, se, act, st in cfg:
            feats.append(_V3Block(in_c, s(exp), s(c), k, st, se, act))
            in_c = s(c)
        feats.append(ConvBNLayer(in_c, s(last_exp), 1, act=nn.Hardswish))
        self.features = nn.Sequential(*feats)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Linear(s(last_exp), 1280), nn.Hardswish(), nn.Dropout(0.2),
                nn.Linear(1280, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.classifier(x.flatten(1))
        return x


class MobileNetV3Small(_MobileNetV3):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_V3_SMALL, 576, scale, num_classes, with_pool)


class MobileNetV3Large(_MobileNetV3):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_V3_LARGE, 960, scale, num_classes, with_pool)


def _no_pretrained(pretrained):
    if pretrained:
        raise NotImplementedError("pretrained weights require network access")


def mobilenet_v1(pretrained=False, scale=1.0, **kw):
    _no_pretrained(pretrained)
    return MobileNetV1(scale=scale, **kw)


def mobilenet_v2(pretrained=False, scale=1.0, **kw):
    _no_pretrained(pretrained)
    return MobileNetV2(scale=scale, **kw)


def mobilenet_v3_small(pretrained=False, scale=1.0, **kw):
    _no_pretrained(pretrained)
    return MobileNetV3Small(scale=scale, **kw)


def mobilenet_v3_large(pretrained=False, scale=1.0, **kw):
    _no_pretrained(pretrained)
    return MobileNetV3Large(scale=scale, **kw)
