"""Swin Transformer (BASELINE.md bench config; beyond the reference zoo —
the reference era serves hierarchical ViTs through generic nn layers only,
python/paddle/nn/layer/transformer.py).

TPU mapping: window attention is a *batched small-sequence* attention —
[B*num_windows, 49, heads, hd] — which XLA lowers to one batched MXU matmul
chain; the parallel axis is the window count, not sequence length, so the
right sharding is dp over images (windows ride along). Shifted windows use
jnp.roll (a cheap HBM-local rotate on TPU); the shift attention mask and the
relative-position-bias index table are static per stage and precomputed on
host at build time, so the traced computation stays shape-static.

r6 channels-last PR: window partition/merge and patch merging each run as
ONE jit-visible op (the roll/reshape/transpose plumbing no longer fragments
the graph into per-step eager ops), and under FLAGS_conv_channels_last the
patch-embed conv runs NHWC — its output reshapes straight into sequence
form, deleting the [B,C,hw]->[B,hw,C] transpose.
"""
from __future__ import annotations

import math
from typing import Sequence

import numpy as np
import jax
import jax.numpy as jnp

from ...core.tensor import apply_op
from ...core import ops
from ...core import random as _random
from ...nn.layer import Layer, LayerList
from ...nn import functional as F
from ...nn import initializer as I
from ...nn.layers.common import Dropout, Linear
from ...nn.layers.norm import LayerNorm

__all__ = ["SwinTransformer", "swin_t", "swin_s", "swin_b", "swin_l"]


def _rel_pos_index(ws: int) -> np.ndarray:
    """Static [ws*ws, ws*ws] index into the (2ws-1)^2 relative-bias table."""
    coords = np.stack(np.meshgrid(np.arange(ws), np.arange(ws),
                                  indexing="ij"))              # [2, ws, ws]
    flat = coords.reshape(2, -1)                               # [2, N]
    rel = flat[:, :, None] - flat[:, None, :]                  # [2, N, N]
    rel = rel.transpose(1, 2, 0) + (ws - 1)                    # [N, N, 2]
    return (rel[..., 0] * (2 * ws - 1) + rel[..., 1]).astype(np.int32)


def _shift_mask(H: int, W: int, ws: int, shift: int) -> np.ndarray:
    """Static additive mask [nW, N, N] forbidding attention across the
    wrap-around seam of a shifted window partition."""
    img = np.zeros((H, W), np.int32)
    cnt = 0
    for hs in (slice(0, -ws), slice(-ws, -shift), slice(-shift, None)):
        for wsl in (slice(0, -ws), slice(-ws, -shift), slice(-shift, None)):
            img[hs, wsl] = cnt
            cnt += 1
    win = img.reshape(H // ws, ws, W // ws, ws).transpose(0, 2, 1, 3)
    win = win.reshape(-1, ws * ws)                             # [nW, N]
    diff = win[:, :, None] != win[:, None, :]
    return np.where(diff, -1e9, 0.0).astype(np.float32)


class WindowAttention(Layer):
    """Multi-head attention inside ws×ws windows with learned relative
    position bias (one table per block, indexed by the static table)."""

    def __init__(self, dim: int, num_heads: int, window_size: int,
                 attn_drop: float = 0.0, proj_drop: float = 0.0):
        super().__init__()
        self.dim, self.num_heads, self.ws = dim, num_heads, window_size
        self.head_dim = dim // num_heads
        self.scale = self.head_dim ** -0.5
        self.qkv = Linear(dim, 3 * dim)
        self.proj = Linear(dim, dim)
        self.attn_drop = Dropout(attn_drop)
        self.proj_drop = Dropout(proj_drop)
        n_rel = (2 * window_size - 1) ** 2
        self.rel_bias_table = self.create_parameter(
            [n_rel, num_heads], default_initializer=I.TruncatedNormal(std=0.02))
        self._rel_index = _rel_pos_index(window_size)          # static numpy

    def _bias_plan(self, bnw, n_windows, mask):
        """Static plan for window-BATCHED fused attention: group W_g
        windows into one length W_g·N sequence with a block-diagonal
        additive bias (periodic over the batch with period R = nW/W_g).
        Returns (W_g, static_bias [R, S, S] numpy) or None."""
        nW = n_windows if n_windows else (mask.shape[0]
                                          if mask is not None else 1)
        n = self.ws * self.ws
        from ...ops.pallas.fused_mha_bias import use_fused_mha_bias
        divisor_of = nW if nW > 1 else bnw
        # try group sizes largest-first: a rejected candidate (VMEM plan)
        # can still admit a smaller one — stage 4's nh=24+ rejects wg=8
        # but runs fused at wg=4
        wg = next((w for w in (8, 4, 2)
                   if divisor_of % w == 0
                   and use_fused_mha_bias(w * n, self.num_heads,
                                          self.head_dim)), 1)
        if wg == 1:
            return None
        r_n = max(1, nW // wg)
        cached = getattr(self, "_bias_static_cache", None)
        if cached is not None and cached[0] == (wg, r_n):
            return wg, cached[1]
        s = wg * n
        static = np.full((r_n, s, s), -1e9, np.float32)
        for r in range(r_n):
            for w in range(wg):
                blk = (mask[r * wg + w] if (mask is not None and nW > 1)
                       else 0.0)
                static[r, w * n:(w + 1) * n, w * n:(w + 1) * n] = blk
        self._bias_static_cache = ((wg, r_n), static)
        return wg, static

    def forward(self, xw, mask: np.ndarray | None, n_windows: int = 0):
        """xw: [B*nW, N, C]; mask: static numpy [nW, N, N] or None."""
        nh, hd, scale = self.num_heads, self.head_dim, self.scale
        n = self.ws * self.ws
        rel_index = self._rel_index
        qkv = self.qkv(xw)                                     # [BnW, N, 3C]
        p_drop = self.attn_drop.p if self.training else 0.0
        drop_key = _random.split_key() if p_drop > 0.0 else None

        plan = (self._bias_plan(int(xw.shape[0]), n_windows, mask)
                if p_drop == 0.0 else None)
        if plan is not None:
            wg, static = plan

            def attend_fused(a, table):
                from ...ops.pallas.fused_mha_bias import fused_mha_bias
                bnw = a.shape[0]
                rel = table[rel_index.reshape(-1)].reshape(n, n, nh)
                rel = rel.transpose(2, 0, 1).astype(jnp.float32)
                tiled = jnp.tile(rel, (1, wg, wg))      # [nh, S, S]
                bias = jnp.asarray(static)[:, None] + tiled[None]
                ag = a.reshape(bnw // wg, wg * n, a.shape[-1])
                o = fused_mha_bias(ag, nh, bias, scale=scale)
                return o.reshape(bnw, n, nh * hd)

            ctx = apply_op("swin_window_attention_fused", attend_fused,
                           [qkv, self.rel_bias_table])
            out = self.proj(ctx)
            if self.training and self.proj_drop.p:
                out = self.proj_drop(out)
            return out

        def attend(a, table):
            from ...ops.attention import attention_reference
            bnw = a.shape[0]
            a = a.reshape(bnw, n, 3, nh, hd)
            q, k, v = a[:, :, 0], a[:, :, 1], a[:, :, 2]
            # relative-position bias (+ shift mask) fold into ONE additive
            # mask for attention_reference, which owns the mixed-precision
            # softmax: score_dtype=model dtype stores the [BnW, nh, N, N]
            # logits/probs in bf16 (f32 dot accumulation + f32 stats) —
            # windows are tiny but BnW is huge, so score traffic dominates
            bias = table[rel_index.reshape(-1)].reshape(n, n, nh)
            add = bias.transpose(2, 0, 1)[None].astype(jnp.float32)
            if mask is not None:
                nw = mask.shape[0]
                m = jnp.asarray(mask)[:, None]                 # [nW, 1, N, N]
                # broadcast+reshape (not tile): stays a lazy broadcast for
                # XLA to fuse into the logits+mask addition
                add = jnp.broadcast_to((add + m)[None],
                                       (bnw // nw, nw, nh, n, n))
                add = add.reshape(bnw, nh, n, n)
            o = attention_reference(q, k, v, mask=add, scale=scale,
                                    dropout_p=p_drop, dropout_key=drop_key,
                                    score_dtype=a.dtype)
            return o.reshape(bnw, n, nh * hd)

        ctx = apply_op("swin_window_attention", attend,
                       [qkv, self.rel_bias_table])
        out = self.proj(ctx)
        if self.training and self.proj_drop.p:
            out = self.proj_drop(out)
        return out


class SwinBlock(Layer):
    def __init__(self, dim, input_resolution, num_heads, window_size=7,
                 shift_size=0, mlp_ratio=4.0, dropout=0.0):
        super().__init__()
        self.dim = dim
        self.H, self.W = input_resolution
        self.ws = min(window_size, self.H, self.W)
        # a window covering the whole map needs no shifted pass
        self.shift = 0 if self.ws >= min(self.H, self.W) else shift_size
        self.norm1 = LayerNorm(dim)
        self.attn = WindowAttention(dim, num_heads, self.ws, proj_drop=dropout)
        self.norm2 = LayerNorm(dim)
        hidden = int(dim * mlp_ratio)
        self.fc1 = Linear(dim, hidden)
        self.fc2 = Linear(hidden, dim)
        self.drop = Dropout(dropout)
        self._mask = (_shift_mask(self.H, self.W, self.ws, self.shift)
                      if self.shift > 0 else None)

    def _windows(self, x):
        """[B, H*W, C] -> [B*nW, ws*ws, C] (with cyclic shift).

        ONE jit-visible op: the roll + reshape + transpose chain that used
        to be 4-5 separate eager ops (each a tape node and an XLA fusion
        boundary) collapses into a single layout block, so all windows of
        the image land in one batched tensor for one batched attention
        matmul downstream."""
        H, W, ws, shift, dim = self.H, self.W, self.ws, self.shift, self.dim

        def fn(a):
            v = a.reshape(-1, H, W, dim)
            if shift:
                v = jnp.roll(v, (-shift, -shift), axis=(1, 2))
            v = v.reshape(-1, H // ws, ws, W // ws, ws, dim)
            v = v.transpose(0, 1, 3, 2, 4, 5)
            return v.reshape(-1, ws * ws, dim)
        return apply_op("swin_window_partition", fn, [x])

    def _unwindows(self, xw):
        H, W, ws, shift, dim = self.H, self.W, self.ws, self.shift, self.dim

        def fn(a):
            v = a.reshape(-1, H // ws, W // ws, ws, ws, dim)
            v = v.transpose(0, 1, 3, 2, 4, 5)
            v = v.reshape(-1, H, W, dim)
            if shift:
                v = jnp.roll(v, (shift, shift), axis=(1, 2))
            return v.reshape(-1, H * W, dim)
        return apply_op("swin_window_merge", fn, [xw])

    def forward(self, x):
        shortcut = x
        xw = self._windows(self.norm1(x))
        aw = self.attn(xw, self._mask,
                       n_windows=(self.H // self.ws) * (self.W // self.ws))
        x = shortcut + self._unwindows(aw)
        y = self.fc2(F.gelu(self.fc1(self.norm2(x)), approximate=True))
        if self.training and self.drop.p:
            y = self.drop(y)
        return x + y


class PatchMerging(Layer):
    """Downsample 2x: concat 2x2 neighbors -> LN -> Linear(4C, 2C)."""

    def __init__(self, input_resolution, dim):
        super().__init__()
        self.H, self.W = input_resolution
        self.dim = dim
        self.norm = LayerNorm(4 * dim)
        self.reduction = Linear(4 * dim, 2 * dim, bias_attr=False)

    def forward(self, x):
        H, W, dim = self.H, self.W, self.dim
        nw, nb = self.norm.weight, self.norm.bias
        rw = self.reduction.weight
        eps = self.norm._epsilon
        if nw is not None and nb is not None and self.reduction.bias is None:
            # one jit-visible block: 2x2 gather + LN + reduction matmul —
            # the epilogue-fused equivalent of the 5-op eager chain below
            def fn(a, w_n, b_n, w_r):
                v = a.reshape(-1, H // 2, 2, W // 2, 2, dim)
                v = v.transpose(0, 1, 3, 2, 4, 5)
                v = v.reshape(-1, (H // 2) * (W // 2), 4 * dim)
                mu = v.mean(axis=-1, keepdims=True)
                var = ((v - mu) ** 2).mean(axis=-1, keepdims=True)
                v = ((v - mu) * jax.lax.rsqrt(var + eps) * w_n + b_n)
                return (v.astype(a.dtype) @ w_r).astype(a.dtype)
            return apply_op("swin_patch_merge", fn, [x, nw, nb, rw])
        b = x.shape[0]
        x = ops.reshape(x, [b, self.H // 2, 2, self.W // 2, 2, self.dim])
        x = ops.transpose(x, [0, 1, 3, 2, 4, 5])
        x = ops.reshape(x, [b, (self.H // 2) * (self.W // 2), 4 * self.dim])
        return self.reduction(self.norm(x))


class SwinTransformer(Layer):
    """Hierarchical windowed transformer; 4 stages, patch-merging between."""

    def __init__(self, image_size=224, patch_size=4, num_channels=3,
                 embed_dim=96, depths: Sequence[int] = (2, 2, 6, 2),
                 num_heads: Sequence[int] = (3, 6, 12, 24), window_size=7,
                 mlp_ratio=4.0, dropout=0.0, num_classes=1000):
        super().__init__()
        assert image_size % patch_size == 0
        # every stage's feature map must tile into windows (no padding path)
        res_check = image_size // patch_size
        for i in range(len(depths)):
            ws_eff = min(window_size, res_check)
            if res_check % ws_eff:
                raise ValueError(
                    f"stage {i}: feature map {res_check}x{res_check} is not "
                    f"divisible by window_size {ws_eff} — choose image_size/"
                    f"patch_size/window_size so every stage tiles exactly "
                    f"(e.g. 224/4/7 or 256/4/8)")
            res_check //= 2
        self.embed_dim = embed_dim
        self.num_classes = num_classes
        from ...nn.layers.conv import Conv2D
        self.patch_embed = Conv2D(num_channels, embed_dim, patch_size,
                                  stride=patch_size)
        self.patch_norm = LayerNorm(embed_dim)
        res = image_size // patch_size
        self.stages = LayerList()
        self.merges = LayerList()
        dim = embed_dim
        for i, (depth, heads) in enumerate(zip(depths, num_heads)):
            blocks = LayerList([
                SwinBlock(dim, (res, res), heads, window_size,
                          shift_size=0 if j % 2 == 0 else window_size // 2,
                          mlp_ratio=mlp_ratio, dropout=dropout)
                for j in range(depth)])
            self.stages.append(blocks)
            if i < len(depths) - 1:
                self.merges.append(PatchMerging((res, res), dim))
                dim *= 2
                res //= 2
        self.norm = LayerNorm(dim)
        self.final_dim = dim
        if num_classes > 0:
            self.head = Linear(dim, num_classes)

    def forward(self, pixel_values):
        from ...nn import layout as _layout
        if _layout.channels_last_enabled():
            # channels-last patch embed: ONE input transpose, conv in the
            # TPU-preferred NHWC layout, and the [B,C,hw]->[B,hw,C]
            # transpose disappears entirely — NHWC output reshapes straight
            # into the sequence-form the transformer trunk wants
            x = self.patch_embed(_layout.to_nhwc(pixel_values))  # [B,h,w,C]
            x = ops.reshape(x, [x.shape[0], -1, self.embed_dim])
        else:
            x = self.patch_embed(pixel_values)                 # [B, C, h, w]
            b, c = x.shape[0], x.shape[1]
            x = ops.transpose(ops.reshape(x, [b, c, -1]), [0, 2, 1])
        x = self.patch_norm(x)
        for i, blocks in enumerate(self.stages):
            for blk in blocks:
                x = blk(x)
            if i < len(self.merges):
                x = self.merges[i](x)
        x = self.norm(x)
        x = ops.mean(x, axis=1)                                # global pool
        if self.num_classes > 0:
            return self.head(x)
        return x


def swin_t(pretrained=False, **kw):
    assert not pretrained, "no pretrained weights in this environment"
    return SwinTransformer(embed_dim=96, depths=(2, 2, 6, 2),
                           num_heads=(3, 6, 12, 24), **kw)


def swin_s(pretrained=False, **kw):
    assert not pretrained, "no pretrained weights in this environment"
    return SwinTransformer(embed_dim=96, depths=(2, 2, 18, 2),
                           num_heads=(3, 6, 12, 24), **kw)


def swin_b(pretrained=False, **kw):
    assert not pretrained, "no pretrained weights in this environment"
    return SwinTransformer(embed_dim=128, depths=(2, 2, 18, 2),
                           num_heads=(4, 8, 16, 32), **kw)


def swin_l(pretrained=False, **kw):
    assert not pretrained, "no pretrained weights in this environment"
    return SwinTransformer(embed_dim=192, depths=(2, 2, 18, 2),
                           num_heads=(6, 12, 24, 48), **kw)
