"""YOLO-style anchor-free detector (PP-YOLOE capability class).

Reference entrypoint: PP-YOLOE (BASELINE.md config list; the reference repo
hosts the op layer — yolo_box op, operators/detection/ — while the model
lives in PaddleDetection). This module supplies the model family the
reference ecosystem trains with those ops: an anchor-free detector with a
conv backbone, FPN neck, decoupled head, FCOS-style center assignment and
GIoU+BCE loss, decoding through vision.ops.nms.

TPU-first: every stage is static-shape jnp (assignment is a dense mask over
the feature grid — no dynamic gather of positives, so the whole loss jits
and shards over dp like any other model); NMS runs on host at inference
(variable-length output is host-side by nature, same as the reference's
multiclass_nms on CPU).
"""
from __future__ import annotations

import math
from dataclasses import dataclass
from typing import List, Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp

from ...core.tensor import Tensor, apply_op
from ...core import ops
from ...nn.layer import Layer, LayerList
from ...nn import functional as F
from ...nn.layers.common import Linear
from ...nn.layers.conv import Conv2D
from ...nn.layers.norm import BatchNorm2D
from .. import ops as vops

__all__ = ["YOLOConfig", "YOLODetector", "yolo_lite", "yolo_loss",
           "ppyoloe_s", "ppyoloe_m", "ppyoloe_l"]


@dataclass
class YOLOConfig:
    num_classes: int = 80
    width: int = 32                  # base channel width
    strides: Sequence[int] = (8, 16, 32)
    score_thresh: float = 0.25
    nms_iou: float = 0.5
    # PP-YOLOE ET-head options: DFL regression (distance as the softmax
    # expectation over reg_max+1 bins) and varifocal (IoU-quality-aware)
    # classification — 0/False reproduces the plain FCOS-style head
    reg_max: int = 0
    use_varifocal: bool = False
    # "tal" = task-aligned assignment (PP-YOLOE's production assigner;
    # reference ppdet TaskAlignedAssigner), "center" = FCOS-style
    # center/size-range assignment (the simplified fallback)
    assigner: str = "center"
    tal_topk: int = 13
    tal_alpha: float = 1.0
    tal_beta: float = 6.0


class ConvBNAct(Layer):
    def __init__(self, cin, cout, k=3, s=1):
        super().__init__()
        self.conv = Conv2D(cin, cout, k, stride=s, padding=k // 2,
                           bias_attr=False)
        self.bn = BatchNorm2D(cout)

    def forward(self, x):
        return F.silu(self.bn(self.conv(x)))


class CSPBlock(Layer):
    """Cross-stage-partial block (PP-YOLOE's CSPRepResNet spirit: split,
    transform half, merge — keeps channels MXU-friendly multiples)."""

    def __init__(self, c, n=2):
        super().__init__()
        self.cv1 = ConvBNAct(c, c // 2, 1)
        self.cv2 = ConvBNAct(c, c // 2, 1)
        self.blocks = LayerList([ConvBNAct(c // 2, c // 2, 3)
                                 for _ in range(n)])
        self.out = ConvBNAct(c, c, 1)

    def forward(self, x):
        a = self.cv1(x)
        b = self.cv2(x)
        for blk in self.blocks:
            b = blk(b)
        return self.out(ops.concat([a, b], axis=1))


class Backbone(Layer):
    """4-stage conv backbone returning strides 8/16/32 feature maps."""

    def __init__(self, w):
        super().__init__()
        self.stem = ConvBNAct(3, w, 3, 2)            # /2
        self.s1 = ConvBNAct(w, w * 2, 3, 2)          # /4
        self.c1 = CSPBlock(w * 2)
        self.s2 = ConvBNAct(w * 2, w * 4, 3, 2)      # /8
        self.c2 = CSPBlock(w * 4)
        self.s3 = ConvBNAct(w * 4, w * 8, 3, 2)      # /16
        self.c3 = CSPBlock(w * 8)
        self.s4 = ConvBNAct(w * 8, w * 16, 3, 2)     # /32
        self.c4 = CSPBlock(w * 16)

    def forward(self, x):
        x = self.c1(self.s1(self.stem(x)))
        p3 = self.c2(self.s2(x))      # stride 8
        p4 = self.c3(self.s3(p3))     # stride 16
        p5 = self.c4(self.s4(p4))     # stride 32
        return p3, p4, p5


class FPN(Layer):
    """Top-down neck: upsample + concat + fuse (PAN's top-down half)."""

    def __init__(self, w):
        super().__init__()
        self.lat5 = ConvBNAct(w * 16, w * 4, 1)
        self.lat4 = ConvBNAct(w * 8, w * 4, 1)
        self.lat3 = ConvBNAct(w * 4, w * 4, 1)
        self.fuse4 = CSPBlock(w * 8)
        self.red4 = ConvBNAct(w * 8, w * 4, 1)
        self.fuse3 = CSPBlock(w * 8)
        self.red3 = ConvBNAct(w * 8, w * 4, 1)

    def forward(self, p3, p4, p5):
        t5 = self.lat5(p5)
        up5 = F.interpolate(t5, scale_factor=2, mode="nearest")
        t4 = self.red4(self.fuse4(ops.concat([self.lat4(p4), up5], axis=1)))
        up4 = F.interpolate(t4, scale_factor=2, mode="nearest")
        t3 = self.red3(self.fuse3(ops.concat([self.lat3(p3), up4], axis=1)))
        return t3, t4, t5


class Head(Layer):
    """Decoupled anchor-free head (PP-YOLOE ET-head): per-scale cls logits
    [B,C,H,W] and either direct ltrb distances [B,4,H,W] (reg_max=0) or
    DFL bin logits [B,4*(reg_max+1),H,W]."""

    def __init__(self, c, num_classes, reg_max=0):
        super().__init__()
        self.reg_max = reg_max
        self.cls_conv = ConvBNAct(c, c, 3)
        self.reg_conv = ConvBNAct(c, c, 3)
        self.cls_pred = Conv2D(c, num_classes, 1)
        self.reg_pred = Conv2D(c, 4 * (reg_max + 1) if reg_max else 4, 1)

    def forward(self, x):
        cls = self.cls_pred(self.cls_conv(x))
        raw = self.reg_pred(self.reg_conv(x))
        if self.reg_max:
            return cls, raw                       # DFL bin logits
        return cls, F.softplus(raw)               # distances >= 0


def _dfl_expectation(raw, reg_max):
    """[B, 4*(R+1), H, W] bin logits -> [B, 4, H, W] distances: the
    softmax-expectation decode of DFL (PP-YOLOE's integral regression)."""
    B, _, H, W = raw.shape
    bins = raw.reshape(B, 4, reg_max + 1, H, W)
    p = jax.nn.softmax(bins, axis=2)
    proj = jnp.arange(reg_max + 1, dtype=p.dtype).reshape(1, 1, -1, 1, 1)
    return (p * proj).sum(axis=2)


class YOLODetector(Layer):
    """Full detector. forward(images[B,3,H,W]) -> list over scales of
    (cls_logits, reg_ltrb)."""

    def __init__(self, config: Optional[YOLOConfig] = None, **kw):
        super().__init__()
        self.config = config or YOLOConfig(**kw)
        w = self.config.width
        self.backbone = Backbone(w)
        self.neck = FPN(w)
        self.heads = LayerList([Head(w * 4, self.config.num_classes,
                                     reg_max=self.config.reg_max)
                                for _ in self.config.strides])

    def forward(self, images):
        feats = self.neck(*self.backbone(images))
        return [self.heads[i](f) for i, f in enumerate(feats)]

    # -- inference ------------------------------------------------------
    def decode(self, images, score_thresh=None, nms_iou=None, max_dets=100):
        """Host-side decode: returns per-image (boxes[N,4] xyxy, scores[N],
        classes[N]) after NMS (reference: yolo_box op + multiclass_nms)."""
        cfg = self.config
        score_thresh = cfg.score_thresh if score_thresh is None else score_thresh
        nms_iou = cfg.nms_iou if nms_iou is None else nms_iou
        outs = self.forward(images)
        B = images.shape[0]
        results = []
        all_boxes, all_scores, all_cls = [], [], []
        for (cls, reg), stride in zip(outs, cfg.strides):
            c = np.asarray(cls._data)      # [B,C,H,W]
            if cfg.reg_max:
                r = np.asarray(_dfl_expectation(reg._data, cfg.reg_max))
            else:
                r = np.asarray(reg._data)  # [B,4,H,W]
            Bc, C, H, W = c.shape
            ys, xs = np.meshgrid(np.arange(H), np.arange(W), indexing="ij")
            cx = (xs + 0.5) * stride
            cy = (ys + 0.5) * stride
            l, t, rr, b = (r[:, i] * stride for i in range(4))
            boxes = np.stack([cx[None] - l, cy[None] - t,
                              cx[None] + rr, cy[None] + b], axis=-1)  # [B,H,W,4]
            prob = 1.0 / (1.0 + np.exp(-c))                           # [B,C,H,W]
            all_boxes.append(boxes.reshape(B, -1, 4))
            all_scores.append(prob.max(axis=1).reshape(B, -1))
            all_cls.append(prob.argmax(axis=1).reshape(B, -1))
        boxes = np.concatenate(all_boxes, axis=1)
        scores = np.concatenate(all_scores, axis=1)
        classes = np.concatenate(all_cls, axis=1)
        for b in range(B):
            keep = scores[b] >= score_thresh
            bb, ss, cc = boxes[b][keep], scores[b][keep], classes[b][keep]
            if len(bb):
                idx = vops.nms(Tensor(jnp.asarray(bb)),
                               iou_threshold=nms_iou,
                               scores=Tensor(jnp.asarray(ss)))
                idx = np.asarray(idx._data)[:max_dets]
                bb, ss, cc = bb[idx], ss[idx], cc[idx]
            results.append((bb, ss, cc))
        return results


def tal_assign(align, inside, topk):
    """Task-aligned assignment core (reference: PP-YOLOE's
    TaskAlignedAssigner, ppdet task_aligned_assigner.py — the production
    assigner the center-window scheme approximated).

    align  [B, M, A]: alignment metric s^alpha * iou^beta per (gt, anchor)
    inside [B, M, A]: anchor-center-inside-gt AND gt-valid mask
    Returns (assigned_gt [B, A] int32, pos [B, A] bool): each positive
    anchor's gt, where per gt the top-k anchors by metric are candidates
    and an anchor claimed by several gts goes to the highest-metric one —
    all static-shape (top_k + one-hot scatter, no dynamic gather).
    """
    B, M, A = align.shape
    masked = jnp.where(inside, align, -jnp.inf)
    k = min(topk, A)
    top_v, top_i = jax.lax.top_k(masked, k)                 # [B, M, k]
    # scatter: candidate[b,m,top_i] = top_v finite
    onehot = jax.nn.one_hot(top_i, A, dtype=jnp.float32)    # [B, M, k, A]
    cand = (onehot * jnp.isfinite(top_v)[..., None].astype(
        jnp.float32)).sum(2) > 0                            # [B, M, A]
    cand_align = jnp.where(cand, align, -jnp.inf)           # [B, M, A]
    assigned_gt = jnp.argmax(cand_align, axis=1).astype(jnp.int32)  # [B, A]
    pos = jnp.isfinite(jnp.max(cand_align, axis=1))         # [B, A]
    return assigned_gt, pos


def _yolo_loss_tal(outputs, gt_boxes, gt_labels, gt_mask, config):
    """Task-aligned loss over ALL scales jointly (TAL is cross-scale by
    design: every anchor competes for every gt on the combined metric)."""
    C = config.num_classes
    R = config.reg_max

    flat_args = []
    for cls_t, reg_t in outputs:
        flat_args += [cls_t, reg_t]

    def fn(*arrs):
        *scale_arrs, boxes, labels, mask = arrs
        cls_list, dist_list, bins_list, cx_list, cy_list, st_list = \
            [], [], [], [], [], []
        for i in range(len(config.strides)):
            cls, reg = scale_arrs[2 * i], scale_arrs[2 * i + 1]
            B, _, H, W = cls.shape
            stride = config.strides[i]
            ys, xs = jnp.meshgrid(jnp.arange(H), jnp.arange(W),
                                  indexing="ij")
            cx_list.append(((xs + 0.5) * stride).reshape(-1))
            cy_list.append(((ys + 0.5) * stride).reshape(-1))
            st_list.append(jnp.full((H * W,), float(stride)))
            cls_list.append(jnp.moveaxis(cls, 1, -1).reshape(B, -1, C))
            if R:
                dist_list.append(jnp.moveaxis(
                    _dfl_expectation(reg, R), 1, -1).reshape(B, -1, 4))
                bins_list.append(                            # [B, HW, 4, R+1]
                    reg.reshape(B, 4, R + 1, H * W).transpose(0, 3, 1, 2))
            else:
                dist_list.append(jnp.moveaxis(reg, 1, -1).reshape(B, -1, 4))
        logits = jnp.concatenate(cls_list, axis=1)          # [B, A, C]
        dist = jnp.concatenate(dist_list, axis=1)           # [B, A, 4]
        cx = jnp.concatenate(cx_list)                       # [A]
        cy = jnp.concatenate(cy_list)
        st = jnp.concatenate(st_list)
        bins = jnp.concatenate(bins_list, axis=1) if R else None  # [B,A,4,R+1]
        B, A = logits.shape[0], logits.shape[1]
        M = boxes.shape[1]

        # predicted boxes (xyxy, image coords)
        px1 = cx[None] - dist[..., 0] * st[None]
        py1 = cy[None] - dist[..., 1] * st[None]
        px2 = cx[None] + dist[..., 2] * st[None]
        py2 = cy[None] + dist[..., 3] * st[None]

        x1, y1, x2, y2 = (boxes[..., i] for i in range(4))  # [B, M]

        def pair_iou():
            iw = jnp.maximum(
                jnp.minimum(px2[:, None], x2[..., None]) -
                jnp.maximum(px1[:, None], x1[..., None]), 0)
            ih = jnp.maximum(
                jnp.minimum(py2[:, None], y2[..., None]) -
                jnp.maximum(py1[:, None], y1[..., None]), 0)
            inter = iw * ih                                  # [B, M, A]
            pa = jnp.maximum((px2 - px1) * (py2 - py1), 0)[:, None]
            ga = jnp.maximum((x2 - x1) * (y2 - y1), 0)[..., None]
            return inter / jnp.maximum(pa + ga - inter, 1e-9)

        iou = pair_iou()                                     # [B, M, A]
        p = jax.nn.sigmoid(logits)                           # [B, A, C]
        lab_idx = jnp.clip(labels, 0, C - 1).astype(jnp.int32)
        s = jnp.take_along_axis(
            p.transpose(0, 2, 1),                            # [B, C, A]
            jnp.broadcast_to(lab_idx[..., None], (B, M, A)),
            axis=1)                                          # [B, M, A]
        align = jnp.power(jnp.maximum(s, 1e-9), config.tal_alpha) * \
            jnp.power(iou, config.tal_beta)
        inside = ((cx[None, None] >= x1[..., None]) &
                  (cx[None, None] <= x2[..., None]) &
                  (cy[None, None] >= y1[..., None]) &
                  (cy[None, None] <= y2[..., None]) &
                  (mask[..., None] > 0))
        assigned, pos = tal_assign(align, inside, config.tal_topk)

        def take_gt(v):                                      # [B,M] -> [B,A]
            return jnp.take_along_axis(v, assigned, axis=1)

        tx1, ty1, tx2, ty2 = take_gt(x1), take_gt(y1), take_gt(x2), take_gt(y2)
        tlab = take_gt(labels.astype(jnp.int32))
        # per-anchor metric of its assigned gt
        t_anchor = jnp.take_along_axis(
            align.transpose(0, 2, 1), assigned[..., None], axis=2)[..., 0]
        iou_anchor = jnp.take_along_axis(
            iou.transpose(0, 2, 1), assigned[..., None], axis=2)[..., 0]
        # normalize: per gt, target peaks at its max IoU (PP-YOLOE's
        # t_norm = t / max_t * max_iou)
        neg_inf = -jnp.inf
        t_gt_max = jnp.max(jnp.where(inside, align, neg_inf), axis=2)  # [B,M]
        iou_gt_max = jnp.max(jnp.where(inside, iou, neg_inf), axis=2)
        t_max_a = take_gt(jnp.where(jnp.isfinite(t_gt_max), t_gt_max, 1.0))
        iou_max_a = take_gt(jnp.where(jnp.isfinite(iou_gt_max),
                                      iou_gt_max, 0.0))
        q = jnp.where(pos, t_anchor / jnp.maximum(t_max_a, 1e-9) *
                      iou_max_a, 0.0)
        q = jax.lax.stop_gradient(jnp.clip(q, 0.0, 1.0))

        npos = jnp.maximum(jnp.sum(pos), 1.0)

        # GIoU regression on positives
        iw = jnp.maximum(jnp.minimum(px2, tx2) - jnp.maximum(px1, tx1), 0)
        ih = jnp.maximum(jnp.minimum(py2, ty2) - jnp.maximum(py1, ty1), 0)
        inter = iw * ih
        pa = jnp.maximum((px2 - px1) * (py2 - py1), 0)
        ta = jnp.maximum((tx2 - tx1) * (ty2 - ty1), 0)
        union = pa + ta - inter
        iou_a = inter / jnp.maximum(union, 1e-9)
        ex1, ey1 = jnp.minimum(px1, tx1), jnp.minimum(py1, ty1)
        ex2, ey2 = jnp.maximum(px2, tx2), jnp.maximum(py2, ty2)
        enc = jnp.maximum((ex2 - ex1) * (ey2 - ey1), 1e-9)
        giou = iou_a - (enc - union) / enc
        reg_loss = jnp.sum((1.0 - giou) * pos * q) / jnp.maximum(
            jnp.sum(pos * q), 1e-9)

        # varifocal classification with the task-aligned quality target
        onehot = jax.nn.one_hot(tlab, C, axis=-1)            # [B, A, C]
        tgt = onehot * q[..., None]
        alpha, gamma = 0.75, 2.0
        w = jnp.where(tgt > 0, tgt, alpha * jnp.power(p, gamma))
        bce = jnp.maximum(logits, 0) - logits * tgt + \
            jnp.log1p(jnp.exp(-jnp.abs(logits)))
        cls_loss = jnp.sum(w * bce) / npos

        dfl_loss = 0.0
        if R:
            logp = jax.nn.log_softmax(bins, axis=-1)         # [B,A,4,R+1]
            tdist = jnp.stack([
                (cx[None] - tx1) / st[None], (cy[None] - ty1) / st[None],
                (tx2 - cx[None]) / st[None], (ty2 - cy[None]) / st[None]],
                axis=-1)                                     # [B, A, 4]
            tdist = jnp.clip(tdist, 0.0, R - 1e-3)
            lo_bin = jnp.floor(tdist).astype(jnp.int32)
            hi_w = tdist - lo_bin
            lp_lo = jnp.take_along_axis(logp, lo_bin[..., None],
                                        axis=-1)[..., 0]
            lp_hi = jnp.take_along_axis(logp, (lo_bin + 1)[..., None],
                                        axis=-1)[..., 0]
            per = -((1 - hi_w) * lp_lo + hi_w * lp_hi)       # [B, A, 4]
            dfl_loss = jnp.sum(per.mean(-1) * pos) / npos * 0.25
        return cls_loss + reg_loss + dfl_loss

    return apply_op("yolo_loss_tal", fn,
                    flat_args + [gt_boxes, gt_labels, gt_mask])


def yolo_loss(outputs, gt_boxes, gt_labels, gt_mask, config: YOLOConfig):
    """Dense detection loss, fully static-shape. config.assigner picks
    "tal" (task-aligned, the PP-YOLOE production assigner — see
    _yolo_loss_tal) or "center" (FCOS-style center/size-range windows).

    gt_boxes: [B, M, 4] xyxy (padded), gt_labels: [B, M] int,
    gt_mask: [B, M] 1/0 valid. "center" assignment: a grid cell is
    positive for the smallest valid gt box containing its center, at the
    scale whose stride range covers the box size.
    """
    if config.assigner == "tal":
        return _yolo_loss_tal(outputs, gt_boxes, gt_labels, gt_mask, config)
    num_classes = config.num_classes
    size_ranges = []
    lo = 0.0
    for i, s in enumerate(config.strides):
        hi = float("inf") if i == len(config.strides) - 1 else s * 8.0
        size_ranges.append((lo, hi))
        lo = s * 8.0

    def one_scale(cls_t, reg_t, stride, lo, hi):
        def fn(cls, reg, boxes, labels, mask):
            B, C, H, W = cls.shape
            M = boxes.shape[1]
            ys, xs = jnp.meshgrid(jnp.arange(H), jnp.arange(W), indexing="ij")
            cx = (xs + 0.5) * stride     # [H,W]
            cy = (ys + 0.5) * stride
            x1, y1, x2, y2 = (boxes[..., i] for i in range(4))   # [B,M]
            # center-inside test: [B,M,H,W]
            inside = ((cx[None, None] >= x1[:, :, None, None]) &
                      (cx[None, None] <= x2[:, :, None, None]) &
                      (cy[None, None] >= y1[:, :, None, None]) &
                      (cy[None, None] <= y2[:, :, None, None]))
            size = jnp.maximum(x2 - x1, y2 - y1)                  # [B,M]
            in_range = (size >= lo) & (size < hi)
            valid = inside & in_range[:, :, None, None] & \
                (mask[:, :, None, None] > 0)
            area = jnp.maximum((x2 - x1) * (y2 - y1), 1.0)
            # choose smallest containing gt per cell
            area_w = jnp.where(valid, area[:, :, None, None], jnp.inf)
            gt_idx = jnp.argmin(area_w, axis=1)                   # [B,H,W]
            pos = jnp.isfinite(jnp.min(area_w, axis=1))           # [B,H,W]

            def take(v):   # v: [B,M] -> [B,H,W] by gt_idx
                return jnp.take_along_axis(
                    v[:, :, None, None].repeat(H, 2).repeat(W, 3),
                    gt_idx[:, None], axis=1)[:, 0]

            tx1, ty1, tx2, ty2 = take(x1), take(y1), take(x2), take(y2)
            tlab = take(labels.astype(jnp.float32)).astype(jnp.int32)

            # regression distances (DFL: softmax expectation over bins)
            if config.reg_max:
                dist = _dfl_expectation(reg, config.reg_max)
            else:
                dist = reg
            l, t, r, b = (dist[:, i] * stride for i in range(4))
            px1, py1 = cx[None] - l, cy[None] - t
            px2, py2 = cx[None] + r, cy[None] + b
            iw = jnp.maximum(jnp.minimum(px2, tx2) - jnp.maximum(px1, tx1), 0)
            ih = jnp.maximum(jnp.minimum(py2, ty2) - jnp.maximum(py1, ty1), 0)
            inter = iw * ih
            pa = jnp.maximum((px2 - px1) * (py2 - py1), 0)
            ta = jnp.maximum((tx2 - tx1) * (ty2 - ty1), 0)
            union = pa + ta - inter
            iou = inter / jnp.maximum(union, 1e-9)
            ex1, ey1 = jnp.minimum(px1, tx1), jnp.minimum(py1, ty1)
            ex2, ey2 = jnp.maximum(px2, tx2), jnp.maximum(py2, ty2)
            enc = jnp.maximum((ex2 - ex1) * (ey2 - ey1), 1e-9)
            giou = iou - (enc - union) / enc
            npos = jnp.maximum(jnp.sum(pos), 1.0)
            reg_loss = jnp.sum((1.0 - giou) * pos) / npos

            # classification AFTER regression so varifocal can use the
            # IoU as the quality target (PP-YOLOE: VFL(q = IoU))
            onehot = jax.nn.one_hot(tlab, C, axis=-1)             # [B,H,W,C]
            logits = jnp.moveaxis(cls, 1, -1)                     # [B,H,W,C]
            if config.use_varifocal:
                q = jax.lax.stop_gradient(
                    jnp.clip(iou, 0.0, 1.0)) * pos                # [B,H,W]
                tgt = onehot * q[..., None]
                p = jax.nn.sigmoid(logits)
                alpha, gamma = 0.75, 2.0
                w = jnp.where(tgt > 0, tgt, alpha * jnp.power(p, gamma))
                bce = jnp.maximum(logits, 0) - logits * tgt +                     jnp.log1p(jnp.exp(-jnp.abs(logits)))
                cls_loss = jnp.sum(w * bce) / npos
            else:
                tgt = onehot * pos[..., None]
                cls_loss = jnp.mean(
                    jnp.maximum(logits, 0) - logits * tgt +
                    jnp.log1p(jnp.exp(-jnp.abs(logits))))

            # DFL: CE against the two integer bins bracketing the target
            # distance (on positives)
            dfl_loss = 0.0
            if config.reg_max:
                R = config.reg_max
                B2, _, H2, W2 = dist.shape
                bins = reg.reshape(B2, 4, R + 1, H2, W2)
                logp = jax.nn.log_softmax(bins, axis=2)
                tdist = jnp.stack([
                    cx[None] - tx1, cy[None] - ty1,
                    tx2 - cx[None], ty2 - cy[None]], axis=1) / stride
                tdist = jnp.clip(tdist, 0.0, R - 1e-3)            # [B,4,H,W]
                lo_bin = jnp.floor(tdist).astype(jnp.int32)
                hi_w = tdist - lo_bin
                lp_lo = jnp.take_along_axis(logp, lo_bin[:, :, None], 2)[:, :, 0]
                lp_hi = jnp.take_along_axis(logp, (lo_bin + 1)[:, :, None], 2)[:, :, 0]
                per = -((1 - hi_w) * lp_lo + hi_w * lp_hi)        # [B,4,H,W]
                dfl_loss = jnp.sum(per.mean(1) * pos) / npos * 0.25
            return cls_loss + reg_loss + dfl_loss

        return apply_op("yolo_loss_scale", fn,
                        [cls_t, reg_t, gt_boxes, gt_labels, gt_mask])

    total = None
    for (cls_t, reg_t), stride, (lo, hi) in zip(outputs, config.strides,
                                                size_ranges):
        term = one_scale(cls_t, reg_t, stride, lo, hi)
        total = term if total is None else total + term
    return total / len(config.strides)


def yolo_lite(num_classes=80, **kw):
    """Small PP-YOLOE-class detector preset."""
    return YOLODetector(YOLOConfig(num_classes=num_classes, **kw))


def _ppyoloe(width, num_classes, **kw):
    kw.setdefault("reg_max", 16)
    kw.setdefault("use_varifocal", True)
    kw.setdefault("assigner", "tal")
    return YOLODetector(YOLOConfig(num_classes=num_classes, width=width, **kw))


def ppyoloe_s(num_classes=80, **kw):
    """PP-YOLOE-S-class entrypoint (BASELINE.md toolkit config): DFL
    integral regression + varifocal classification on the anchor-free
    head."""
    return _ppyoloe(32, num_classes, **kw)


def ppyoloe_m(num_classes=80, **kw):
    return _ppyoloe(48, num_classes, **kw)


def ppyoloe_l(num_classes=80, **kw):
    return _ppyoloe(64, num_classes, **kw)
