"""Vision model zoo (reference: python/paddle/vision/models/__init__.py)."""
from .resnet import *  # noqa: F401,F403
from .vgg import *  # noqa: F401,F403
from .mobilenet import *  # noqa: F401,F403
from .small import *  # noqa: F401,F403
from .densenet import *  # noqa: F401,F403
from .swin import *  # noqa: F401,F403

from .resnet import __all__ as _r
from .vgg import __all__ as _v
from .mobilenet import __all__ as _m
from .small import __all__ as _s
from .densenet import __all__ as _d
from .swin import __all__ as _sw

__all__ = list(_r) + list(_v) + list(_m) + list(_s) + list(_d) + list(_sw)
from .yolo import (  # noqa: F401
    YOLOConfig, YOLODetector, yolo_lite, yolo_loss,
    ppyoloe_s, ppyoloe_m, ppyoloe_l,
)
