"""DenseNet / GoogLeNet / InceptionV3 / ShuffleNetV2 (reference:
python/paddle/vision/models/{densenet,googlenet,inceptionv3,shufflenetv2}.py)."""
from __future__ import annotations

import paddle_tpu as paddle
from ... import nn

__all__ = ["DenseNet", "densenet121", "densenet161", "densenet169",
           "densenet201", "densenet264", "GoogLeNet", "googlenet",
           "InceptionV3", "inception_v3", "ShuffleNetV2", "shufflenet_v2_x0_25",
           "shufflenet_v2_x0_33", "shufflenet_v2_x0_5", "shufflenet_v2_x1_0",
           "shufflenet_v2_x1_5", "shufflenet_v2_x2_0", "shufflenet_v2_swish"]


class _DenseLayer(nn.Layer):
    def __init__(self, in_c, growth, bn_size, drop):
        super().__init__()
        self.bn1 = nn.BatchNorm2D(in_c)
        self.conv1 = nn.Conv2D(in_c, bn_size * growth, 1, bias_attr=False)
        self.bn2 = nn.BatchNorm2D(bn_size * growth)
        self.conv2 = nn.Conv2D(bn_size * growth, growth, 3, padding=1,
                               bias_attr=False)
        self.relu = nn.ReLU()
        self.drop = nn.Dropout(drop) if drop else None

    def forward(self, x):
        out = self.conv1(self.relu(self.bn1(x)))
        out = self.conv2(self.relu(self.bn2(out)))
        if self.drop:
            out = self.drop(out)
        return paddle.concat([x, out], axis=1)


class _Transition(nn.Layer):
    def __init__(self, in_c, out_c):
        super().__init__()
        self.bn = nn.BatchNorm2D(in_c)
        self.conv = nn.Conv2D(in_c, out_c, 1, bias_attr=False)
        self.relu = nn.ReLU()
        self.pool = nn.AvgPool2D(2, stride=2)

    def forward(self, x):
        return self.pool(self.conv(self.relu(self.bn(x))))


_DENSE_CFG = {121: (64, 32, [6, 12, 24, 16]), 161: (96, 48, [6, 12, 36, 24]),
              169: (64, 32, [6, 12, 32, 32]), 201: (64, 32, [6, 12, 48, 32]),
              264: (64, 32, [6, 12, 64, 48])}


class DenseNet(nn.Layer):
    """reference densenet.py:207."""

    def __init__(self, layers=121, bn_size=4, dropout=0.0, num_classes=1000,
                 with_pool=True):
        super().__init__()
        num_init, growth, cfg = _DENSE_CFG[layers]
        self.num_classes, self.with_pool = num_classes, with_pool
        feats = [nn.Conv2D(3, num_init, 7, stride=2, padding=3, bias_attr=False),
                 nn.BatchNorm2D(num_init), nn.ReLU(), nn.MaxPool2D(3, 2, padding=1)]
        c = num_init
        for i, n in enumerate(cfg):
            for _ in range(n):
                feats.append(_DenseLayer(c, growth, bn_size, dropout))
                c += growth
            if i != len(cfg) - 1:
                feats.append(_Transition(c, c // 2))
                c //= 2
        feats += [nn.BatchNorm2D(c), nn.ReLU()]
        self.features = nn.Sequential(*feats)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = nn.Linear(c, num_classes)

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.classifier(x.flatten(1))
        return x


def _densenet(layers, pretrained=False, **kw):
    if pretrained:
        raise NotImplementedError("pretrained weights require network access")
    return DenseNet(layers, **kw)


def densenet121(pretrained=False, **kw):
    return _densenet(121, pretrained, **kw)


def densenet161(pretrained=False, **kw):
    return _densenet(161, pretrained, **kw)


def densenet169(pretrained=False, **kw):
    return _densenet(169, pretrained, **kw)


def densenet201(pretrained=False, **kw):
    return _densenet(201, pretrained, **kw)


def densenet264(pretrained=False, **kw):
    return _densenet(264, pretrained, **kw)


class _Inception(nn.Layer):
    """GoogLeNet inception block (reference googlenet.py:36)."""

    def __init__(self, in_c, c1, c3r, c3, c5r, c5, proj):
        super().__init__()
        self.b1 = nn.Sequential(nn.Conv2D(in_c, c1, 1), nn.ReLU())
        self.b2 = nn.Sequential(nn.Conv2D(in_c, c3r, 1), nn.ReLU(),
                                nn.Conv2D(c3r, c3, 3, padding=1), nn.ReLU())
        self.b3 = nn.Sequential(nn.Conv2D(in_c, c5r, 1), nn.ReLU(),
                                nn.Conv2D(c5r, c5, 5, padding=2), nn.ReLU())
        self.b4 = nn.Sequential(nn.MaxPool2D(3, 1, padding=1),
                                nn.Conv2D(in_c, proj, 1), nn.ReLU())

    def forward(self, x):
        return paddle.concat([self.b1(x), self.b2(x), self.b3(x), self.b4(x)],
                             axis=1)


class GoogLeNet(nn.Layer):
    """reference googlenet.py:88."""

    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes, self.with_pool = num_classes, with_pool
        self.stem = nn.Sequential(
            nn.Conv2D(3, 64, 7, stride=2, padding=3), nn.ReLU(),
            nn.MaxPool2D(3, 2, padding=1),
            nn.Conv2D(64, 64, 1), nn.ReLU(),
            nn.Conv2D(64, 192, 3, padding=1), nn.ReLU(),
            nn.MaxPool2D(3, 2, padding=1))
        self.i3a = _Inception(192, 64, 96, 128, 16, 32, 32)
        self.i3b = _Inception(256, 128, 128, 192, 32, 96, 64)
        self.pool3 = nn.MaxPool2D(3, 2, padding=1)
        self.i4a = _Inception(480, 192, 96, 208, 16, 48, 64)
        self.i4b = _Inception(512, 160, 112, 224, 24, 64, 64)
        self.i4c = _Inception(512, 128, 128, 256, 24, 64, 64)
        self.i4d = _Inception(512, 112, 144, 288, 32, 64, 64)
        self.i4e = _Inception(528, 256, 160, 320, 32, 128, 128)
        self.pool4 = nn.MaxPool2D(3, 2, padding=1)
        self.i5a = _Inception(832, 256, 160, 320, 32, 128, 128)
        self.i5b = _Inception(832, 384, 192, 384, 48, 128, 128)
        if with_pool:
            self.pool5 = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.drop = nn.Dropout(0.4)
            self.fc = nn.Linear(1024, num_classes)

    def forward(self, x):
        x = self.stem(x)
        x = self.pool3(self.i3b(self.i3a(x)))
        x = self.pool4(self.i4e(self.i4d(self.i4c(self.i4b(self.i4a(x))))))
        x = self.i5b(self.i5a(x))
        if self.with_pool:
            x = self.pool5(x)
        if self.num_classes > 0:
            x = self.fc(self.drop(x.flatten(1)))
        return x


def googlenet(pretrained=False, **kw):
    if pretrained:
        raise NotImplementedError("pretrained weights require network access")
    return GoogLeNet(**kw)


class _BasicConv(nn.Layer):
    def __init__(self, in_c, out_c, kernel, **kwargs):
        super().__init__()
        self.conv = nn.Conv2D(in_c, out_c, kernel, bias_attr=False, **kwargs)
        self.bn = nn.BatchNorm2D(out_c)
        self.relu = nn.ReLU()

    def forward(self, x):
        return self.relu(self.bn(self.conv(x)))


class _InceptionA(nn.Layer):
    def __init__(self, in_c, pool_feats):
        super().__init__()
        self.b1 = _BasicConv(in_c, 64, 1)
        self.b5 = nn.Sequential(_BasicConv(in_c, 48, 1),
                                _BasicConv(48, 64, 5, padding=2))
        self.b3 = nn.Sequential(_BasicConv(in_c, 64, 1),
                                _BasicConv(64, 96, 3, padding=1),
                                _BasicConv(96, 96, 3, padding=1))
        self.bp = nn.Sequential(nn.AvgPool2D(3, 1, padding=1),
                                _BasicConv(in_c, pool_feats, 1))

    def forward(self, x):
        return paddle.concat([self.b1(x), self.b5(x), self.b3(x), self.bp(x)], 1)


class _InceptionB(nn.Layer):
    def __init__(self, in_c):
        super().__init__()
        self.b3 = _BasicConv(in_c, 384, 3, stride=2)
        self.b3d = nn.Sequential(_BasicConv(in_c, 64, 1),
                                 _BasicConv(64, 96, 3, padding=1),
                                 _BasicConv(96, 96, 3, stride=2))
        self.pool = nn.MaxPool2D(3, 2)

    def forward(self, x):
        return paddle.concat([self.b3(x), self.b3d(x), self.pool(x)], 1)


class _InceptionC(nn.Layer):
    def __init__(self, in_c, c7):
        super().__init__()
        self.b1 = _BasicConv(in_c, 192, 1)
        self.b7 = nn.Sequential(_BasicConv(in_c, c7, 1),
                                _BasicConv(c7, c7, (1, 7), padding=(0, 3)),
                                _BasicConv(c7, 192, (7, 1), padding=(3, 0)))
        self.b7d = nn.Sequential(_BasicConv(in_c, c7, 1),
                                 _BasicConv(c7, c7, (7, 1), padding=(3, 0)),
                                 _BasicConv(c7, c7, (1, 7), padding=(0, 3)),
                                 _BasicConv(c7, c7, (7, 1), padding=(3, 0)),
                                 _BasicConv(c7, 192, (1, 7), padding=(0, 3)))
        self.bp = nn.Sequential(nn.AvgPool2D(3, 1, padding=1),
                                _BasicConv(in_c, 192, 1))

    def forward(self, x):
        return paddle.concat([self.b1(x), self.b7(x), self.b7d(x), self.bp(x)], 1)


class _InceptionD(nn.Layer):
    def __init__(self, in_c):
        super().__init__()
        self.b3 = nn.Sequential(_BasicConv(in_c, 192, 1),
                                _BasicConv(192, 320, 3, stride=2))
        self.b7 = nn.Sequential(_BasicConv(in_c, 192, 1),
                                _BasicConv(192, 192, (1, 7), padding=(0, 3)),
                                _BasicConv(192, 192, (7, 1), padding=(3, 0)),
                                _BasicConv(192, 192, 3, stride=2))
        self.pool = nn.MaxPool2D(3, 2)

    def forward(self, x):
        return paddle.concat([self.b3(x), self.b7(x), self.pool(x)], 1)


class _InceptionE(nn.Layer):
    def __init__(self, in_c):
        super().__init__()
        self.b1 = _BasicConv(in_c, 320, 1)
        self.b3_1 = _BasicConv(in_c, 384, 1)
        self.b3_2a = _BasicConv(384, 384, (1, 3), padding=(0, 1))
        self.b3_2b = _BasicConv(384, 384, (3, 1), padding=(1, 0))
        self.bd_1 = nn.Sequential(_BasicConv(in_c, 448, 1),
                                  _BasicConv(448, 384, 3, padding=1))
        self.bd_2a = _BasicConv(384, 384, (1, 3), padding=(0, 1))
        self.bd_2b = _BasicConv(384, 384, (3, 1), padding=(1, 0))
        self.bp = nn.Sequential(nn.AvgPool2D(3, 1, padding=1),
                                _BasicConv(in_c, 192, 1))

    def forward(self, x):
        b3 = self.b3_1(x)
        b3 = paddle.concat([self.b3_2a(b3), self.b3_2b(b3)], 1)
        bd = self.bd_1(x)
        bd = paddle.concat([self.bd_2a(bd), self.bd_2b(bd)], 1)
        return paddle.concat([self.b1(x), b3, bd, self.bp(x)], 1)


class InceptionV3(nn.Layer):
    """reference inceptionv3.py:478."""

    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes, self.with_pool = num_classes, with_pool
        self.stem = nn.Sequential(
            _BasicConv(3, 32, 3, stride=2), _BasicConv(32, 32, 3),
            _BasicConv(32, 64, 3, padding=1), nn.MaxPool2D(3, 2),
            _BasicConv(64, 80, 1), _BasicConv(80, 192, 3), nn.MaxPool2D(3, 2))
        self.blocks = nn.Sequential(
            _InceptionA(192, 32), _InceptionA(256, 64), _InceptionA(288, 64),
            _InceptionB(288),
            _InceptionC(768, 128), _InceptionC(768, 160), _InceptionC(768, 160),
            _InceptionC(768, 192), _InceptionD(768),
            _InceptionE(1280), _InceptionE(2048))
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.drop = nn.Dropout()
            self.fc = nn.Linear(2048, num_classes)

    def forward(self, x):
        x = self.blocks(self.stem(x))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(self.drop(x.flatten(1)))
        return x


def inception_v3(pretrained=False, **kw):
    if pretrained:
        raise NotImplementedError("pretrained weights require network access")
    return InceptionV3(**kw)


def _channel_shuffle(x, groups):
    n, c, h, w = x.shape
    x = x.reshape([n, groups, c // groups, h, w])
    x = x.transpose([0, 2, 1, 3, 4])
    return x.reshape([n, c, h, w])


class _ShuffleUnit(nn.Layer):
    def __init__(self, in_c, out_c, stride, act):
        super().__init__()
        self.stride = stride
        branch_c = out_c // 2
        if stride == 2:
            self.branch1 = nn.Sequential(
                nn.Conv2D(in_c, in_c, 3, stride=2, padding=1, groups=in_c,
                          bias_attr=False),
                nn.BatchNorm2D(in_c), nn.Conv2D(in_c, branch_c, 1, bias_attr=False),
                nn.BatchNorm2D(branch_c), act())
            b2_in = in_c
        else:
            self.branch1 = None
            b2_in = in_c // 2
        self.branch2 = nn.Sequential(
            nn.Conv2D(b2_in, branch_c, 1, bias_attr=False),
            nn.BatchNorm2D(branch_c), act(),
            nn.Conv2D(branch_c, branch_c, 3, stride=stride, padding=1,
                      groups=branch_c, bias_attr=False),
            nn.BatchNorm2D(branch_c),
            nn.Conv2D(branch_c, branch_c, 1, bias_attr=False),
            nn.BatchNorm2D(branch_c), act())

    def forward(self, x):
        if self.stride == 1:
            c = x.shape[1] // 2
            x1, x2 = x[:, :c], x[:, c:]
            out = paddle.concat([x1, self.branch2(x2)], axis=1)
        else:
            out = paddle.concat([self.branch1(x), self.branch2(x)], axis=1)
        return _channel_shuffle(out, 2)


_SHUFFLE_CFG = {0.25: [24, 24, 48, 96, 512], 0.33: [24, 32, 64, 128, 512],
                0.5: [24, 48, 96, 192, 1024], 1.0: [24, 116, 232, 464, 1024],
                1.5: [24, 176, 352, 704, 1024], 2.0: [24, 244, 488, 976, 2048]}


class ShuffleNetV2(nn.Layer):
    """reference shufflenetv2.py:109."""

    def __init__(self, scale=1.0, act="relu", num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes, self.with_pool = num_classes, with_pool
        act_layer = nn.Swish if act == "swish" else nn.ReLU
        cfg = _SHUFFLE_CFG[scale]
        stage_repeats = [4, 8, 4]
        self.conv1 = nn.Sequential(
            nn.Conv2D(3, cfg[0], 3, stride=2, padding=1, bias_attr=False),
            nn.BatchNorm2D(cfg[0]), act_layer())
        self.maxpool = nn.MaxPool2D(3, 2, padding=1)
        blocks = []
        in_c = cfg[0]
        for stage, reps in enumerate(stage_repeats):
            out_c = cfg[stage + 1]
            for i in range(reps):
                blocks.append(_ShuffleUnit(in_c, out_c, 2 if i == 0 else 1,
                                           act_layer))
                in_c = out_c
        self.blocks = nn.Sequential(*blocks)
        self.conv_last = nn.Sequential(
            nn.Conv2D(in_c, cfg[-1], 1, bias_attr=False),
            nn.BatchNorm2D(cfg[-1]), act_layer())
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.fc = nn.Linear(cfg[-1], num_classes)

    def forward(self, x):
        x = self.conv_last(self.blocks(self.maxpool(self.conv1(x))))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(x.flatten(1))
        return x


def _shufflenet(scale, act="relu", pretrained=False, **kw):
    if pretrained:
        raise NotImplementedError("pretrained weights require network access")
    return ShuffleNetV2(scale=scale, act=act, **kw)


def shufflenet_v2_x0_25(pretrained=False, **kw):
    return _shufflenet(0.25, pretrained=pretrained, **kw)


def shufflenet_v2_x0_33(pretrained=False, **kw):
    return _shufflenet(0.33, pretrained=pretrained, **kw)


def shufflenet_v2_x0_5(pretrained=False, **kw):
    return _shufflenet(0.5, pretrained=pretrained, **kw)


def shufflenet_v2_x1_0(pretrained=False, **kw):
    return _shufflenet(1.0, pretrained=pretrained, **kw)


def shufflenet_v2_x1_5(pretrained=False, **kw):
    return _shufflenet(1.5, pretrained=pretrained, **kw)


def shufflenet_v2_x2_0(pretrained=False, **kw):
    return _shufflenet(2.0, pretrained=pretrained, **kw)


def shufflenet_v2_swish(pretrained=False, **kw):
    return _shufflenet(1.0, act="swish", pretrained=pretrained, **kw)
