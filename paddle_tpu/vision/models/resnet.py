"""ResNet family (reference: python/paddle/vision/models/resnet.py).

Re-designed TPU-first: every Conv→BN(→ReLU) triple — including the residual
add — executes through `F.fused_conv_bn_act`, ONE jit-visible op whose
epilogue (bias/residual/act) XLA fuses onto the conv's MXU output; inference
mode folds the BN scale/shift into the conv kernel entirely. Under
FLAGS_conv_channels_last the whole trunk additionally runs internally NHWC
(nn.layout), with layout transposes only at trunk entry/exit. Width/grouping
variants (wide_resnet, resnext) follow the reference's single BottleneckBlock
parameterisation.
"""
from __future__ import annotations

from ... import nn
from ...nn import functional as F
from ...nn import layout as _layout
from ...nn.layers.norm import _BatchNormBase


__all__ = [
    "ResNet", "resnet18", "resnet34", "resnet50", "resnet101", "resnet152",
    "resnext50_32x4d", "resnext50_64x4d", "resnext101_32x4d", "resnext101_64x4d",
    "resnext152_32x4d", "resnext152_64x4d", "wide_resnet50_2", "wide_resnet101_2",
]


def _fused_cba(x, conv, bn, act=None, residual=None):
    """Run `act(bn(conv(x)) [+ residual])` as one fused op, honoring the
    channels-last tag on `x` (see nn.layout)."""
    df = "NHWC" if (_layout.is_nhwc(x) and conv._data_format == "NCHW") \
        else conv._data_format
    out = F.fused_conv_bn_act(
        x, conv.weight, conv.bias, bn._mean, bn._variance, bn.weight,
        bn.bias, stride=conv._stride, padding=conv._padding,
        dilation=conv._dilation, groups=conv._groups, data_format=df,
        training=bn.training, momentum=bn._momentum, epsilon=bn._epsilon,
        use_global_stats=bn._use_global_stats, act=act, residual=residual)
    return _layout.tag_nhwc(out) if df == "NHWC" else out


def _can_fuse(*bns):
    return all(isinstance(bn, _BatchNormBase) for bn in bns)


def _downsample_out(ds, x):
    """Projection shortcut: fuse its Conv+BN too when it is the standard
    Sequential(Conv2D, BatchNorm) pair; any other module is not
    layout-aware, so leave the NHWC region before calling it."""
    if (isinstance(ds, nn.Sequential) and len(ds) == 2
            and isinstance(ds[0], nn.Conv2D) and _can_fuse(ds[1])):
        return _fused_cba(x, ds[0], ds[1])
    return ds(_layout.to_nchw(x))


class BasicBlock(nn.Layer):
    expansion = 1

    def __init__(self, inplanes, planes, stride=1, downsample=None, groups=1,
                 base_width=64, dilation=1, norm_layer=None):
        super().__init__()
        norm_layer = norm_layer or nn.BatchNorm2D
        if groups != 1 or base_width != 64:
            raise ValueError("BasicBlock only supports groups=1 and base_width=64")
        self.conv1 = nn.Conv2D(inplanes, planes, 3, padding=1, stride=stride,
                               bias_attr=False)
        self.bn1 = norm_layer(planes)
        self.relu = nn.ReLU()
        self.conv2 = nn.Conv2D(planes, planes, 3, padding=1, bias_attr=False)
        self.bn2 = norm_layer(planes)
        self.downsample = downsample
        self.stride = stride

    def forward(self, x):
        identity = x
        if _can_fuse(self.bn1, self.bn2):
            out = _fused_cba(x, self.conv1, self.bn1, act="relu")
            if self.downsample is not None:
                identity = _downsample_out(self.downsample, x)
                if _layout.is_nhwc(out) and not _layout.is_nhwc(identity):
                    # non-layout-aware shortcut exited the NHWC region:
                    # the residual epilogue needs matching layouts
                    out = _layout.to_nchw(out)
            # residual add + final relu ride the second conv's epilogue
            return _fused_cba(out, self.conv2, self.bn2, act="relu",
                              residual=identity)
        # unfused fallback: bare activations drop the layout annotation, so
        # leave the NHWC region first (no-op on untagged input)
        x = identity = _layout.to_nchw(x)
        out = self.relu(self.bn1(self.conv1(x)))
        out = self.bn2(self.conv2(out))
        if self.downsample is not None:
            identity = self.downsample(x)
        return self.relu(out + identity)


class BottleneckBlock(nn.Layer):
    expansion = 4

    def __init__(self, inplanes, planes, stride=1, downsample=None, groups=1,
                 base_width=64, dilation=1, norm_layer=None):
        super().__init__()
        norm_layer = norm_layer or nn.BatchNorm2D
        width = int(planes * (base_width / 64.0)) * groups
        self.conv1 = nn.Conv2D(inplanes, width, 1, bias_attr=False)
        self.bn1 = norm_layer(width)
        self.conv2 = nn.Conv2D(width, width, 3, padding=dilation, stride=stride,
                               groups=groups, dilation=dilation, bias_attr=False)
        self.bn2 = norm_layer(width)
        self.conv3 = nn.Conv2D(width, planes * self.expansion, 1, bias_attr=False)
        self.bn3 = norm_layer(planes * self.expansion)
        self.relu = nn.ReLU()
        self.downsample = downsample
        self.stride = stride

    def forward(self, x):
        identity = x
        if _can_fuse(self.bn1, self.bn2, self.bn3):
            out = _fused_cba(x, self.conv1, self.bn1, act="relu")
            out = _fused_cba(out, self.conv2, self.bn2, act="relu")
            if self.downsample is not None:
                identity = _downsample_out(self.downsample, x)
                if _layout.is_nhwc(out) and not _layout.is_nhwc(identity):
                    out = _layout.to_nchw(out)
            return _fused_cba(out, self.conv3, self.bn3, act="relu",
                              residual=identity)
        x = identity = _layout.to_nchw(x)
        out = self.relu(self.bn1(self.conv1(x)))
        out = self.relu(self.bn2(self.conv2(out)))
        out = self.bn3(self.conv3(out))
        if self.downsample is not None:
            identity = self.downsample(x)
        return self.relu(out + identity)


class ResNet(nn.Layer):
    """ResNet (reference resnet.py:249 class ResNet)."""

    def __init__(self, block, depth=50, width=64, num_classes=1000, with_pool=True,
                 groups=1):
        super().__init__()
        layer_cfg = {18: [2, 2, 2, 2], 34: [3, 4, 6, 3], 50: [3, 4, 6, 3],
                     101: [3, 4, 23, 3], 152: [3, 8, 36, 3]}
        layers = layer_cfg[depth]
        self.groups = groups
        self.base_width = width
        self.num_classes = num_classes
        self.with_pool = with_pool
        self._norm_layer = nn.BatchNorm2D
        self.inplanes = 64
        self.dilation = 1

        self.conv1 = nn.Conv2D(3, self.inplanes, 7, stride=2, padding=3,
                               bias_attr=False)
        self.bn1 = self._norm_layer(self.inplanes)
        self.relu = nn.ReLU()
        self.maxpool = nn.MaxPool2D(3, stride=2, padding=1)
        self.layer1 = self._make_layer(block, 64, layers[0])
        self.layer2 = self._make_layer(block, 128, layers[1], stride=2)
        self.layer3 = self._make_layer(block, 256, layers[2], stride=2)
        self.layer4 = self._make_layer(block, 512, layers[3], stride=2)
        if with_pool:
            self.avgpool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.fc = nn.Linear(512 * block.expansion, num_classes)

    def _make_layer(self, block, planes, blocks, stride=1):
        norm_layer = self._norm_layer
        downsample = None
        if stride != 1 or self.inplanes != planes * block.expansion:
            downsample = nn.Sequential(
                nn.Conv2D(self.inplanes, planes * block.expansion, 1,
                          stride=stride, bias_attr=False),
                norm_layer(planes * block.expansion))
        layers = [block(self.inplanes, planes, stride, downsample, self.groups,
                        self.base_width, self.dilation, norm_layer)]
        self.inplanes = planes * block.expansion
        for _ in range(1, blocks):
            layers.append(block(self.inplanes, planes, groups=self.groups,
                                base_width=self.base_width, norm_layer=norm_layer))
        return nn.Sequential(*layers)

    def forward(self, x):
        if _layout.channels_last_enabled() and _can_fuse(self.bn1):
            # trunk entry: ONE transpose; every layer below propagates the
            # NHWC tag (exit transpose after the pool, where the map is 1x1).
            # Gated on the fused stem: the unfused path routes through bare
            # activations that do not carry the annotation.
            x = _layout.to_nhwc(x)
        if _can_fuse(self.bn1):
            x = self.maxpool(_fused_cba(x, self.conv1, self.bn1, act="relu"))
        else:
            x = self.maxpool(self.relu(self.bn1(self.conv1(x))))
        x = self.layer4(self.layer3(self.layer2(self.layer1(x))))
        if self.with_pool:
            x = self.avgpool(x)
        x = _layout.to_nchw(x)
        if self.num_classes > 0:
            x = self.fc(x.flatten(1))
        return x


def _resnet(block, depth, pretrained=False, **kwargs):
    if pretrained:
        raise NotImplementedError("pretrained weights require network access; "
                                  "load a local state_dict instead")
    return ResNet(block, depth, **kwargs)


def resnet18(pretrained=False, **kw):
    return _resnet(BasicBlock, 18, pretrained, **kw)


def resnet34(pretrained=False, **kw):
    return _resnet(BasicBlock, 34, pretrained, **kw)


def resnet50(pretrained=False, **kw):
    return _resnet(BottleneckBlock, 50, pretrained, **kw)


def resnet101(pretrained=False, **kw):
    return _resnet(BottleneckBlock, 101, pretrained, **kw)


def resnet152(pretrained=False, **kw):
    return _resnet(BottleneckBlock, 152, pretrained, **kw)


def resnext50_32x4d(pretrained=False, **kw):
    return _resnet(BottleneckBlock, 50, pretrained, groups=32, width=4, **kw)


def resnext50_64x4d(pretrained=False, **kw):
    return _resnet(BottleneckBlock, 50, pretrained, groups=64, width=4, **kw)


def resnext101_32x4d(pretrained=False, **kw):
    return _resnet(BottleneckBlock, 101, pretrained, groups=32, width=4, **kw)


def resnext101_64x4d(pretrained=False, **kw):
    return _resnet(BottleneckBlock, 101, pretrained, groups=64, width=4, **kw)


def resnext152_32x4d(pretrained=False, **kw):
    return _resnet(BottleneckBlock, 152, pretrained, groups=32, width=4, **kw)


def resnext152_64x4d(pretrained=False, **kw):
    return _resnet(BottleneckBlock, 152, pretrained, groups=64, width=4, **kw)


def wide_resnet50_2(pretrained=False, **kw):
    return _resnet(BottleneckBlock, 50, pretrained, width=128, **kw)


def wide_resnet101_2(pretrained=False, **kw):
    return _resnet(BottleneckBlock, 101, pretrained, width=128, **kw)
