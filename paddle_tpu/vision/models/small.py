"""LeNet / AlexNet / SqueezeNet (reference: python/paddle/vision/models/{lenet,alexnet,squeezenet}.py)."""
from __future__ import annotations

from ... import nn

__all__ = ["LeNet", "AlexNet", "SqueezeNet", "alexnet", "squeezenet1_0",
           "squeezenet1_1"]


class LeNet(nn.Layer):
    """reference lenet.py:21 — MNIST-scale CNN."""

    def __init__(self, num_classes=10):
        super().__init__()
        self.num_classes = num_classes
        self.features = nn.Sequential(
            nn.Conv2D(1, 6, 3, stride=1, padding=1), nn.ReLU(),
            nn.MaxPool2D(2, 2),
            nn.Conv2D(6, 16, 5, stride=1), nn.ReLU(),
            nn.MaxPool2D(2, 2))
        if num_classes > 0:
            self.fc = nn.Sequential(
                nn.Linear(400, 120), nn.Linear(120, 84),
                nn.Linear(84, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.num_classes > 0:
            x = self.fc(x.flatten(1))
        return x


class AlexNet(nn.Layer):
    """reference alexnet.py:90."""

    def __init__(self, num_classes=1000):
        super().__init__()
        self.num_classes = num_classes
        self.features = nn.Sequential(
            nn.Conv2D(3, 64, 11, stride=4, padding=2), nn.ReLU(),
            nn.MaxPool2D(3, 2),
            nn.Conv2D(64, 192, 5, padding=2), nn.ReLU(), nn.MaxPool2D(3, 2),
            nn.Conv2D(192, 384, 3, padding=1), nn.ReLU(),
            nn.Conv2D(384, 256, 3, padding=1), nn.ReLU(),
            nn.Conv2D(256, 256, 3, padding=1), nn.ReLU(), nn.MaxPool2D(3, 2))
        self.avgpool = nn.AdaptiveAvgPool2D((6, 6))
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Dropout(), nn.Linear(256 * 6 * 6, 4096), nn.ReLU(),
                nn.Dropout(), nn.Linear(4096, 4096), nn.ReLU(),
                nn.Linear(4096, num_classes))

    def forward(self, x):
        x = self.avgpool(self.features(x))
        if self.num_classes > 0:
            x = self.classifier(x.flatten(1))
        return x


class _Fire(nn.Layer):
    def __init__(self, in_c, squeeze, e1, e3):
        super().__init__()
        self.squeeze = nn.Conv2D(in_c, squeeze, 1)
        self.expand1 = nn.Conv2D(squeeze, e1, 1)
        self.expand3 = nn.Conv2D(squeeze, e3, 3, padding=1)
        self.relu = nn.ReLU()

    def forward(self, x):
        import paddle_tpu as paddle
        x = self.relu(self.squeeze(x))
        return paddle.concat([self.relu(self.expand1(x)),
                              self.relu(self.expand3(x))], axis=1)


class SqueezeNet(nn.Layer):
    """reference squeezenet.py:71."""

    def __init__(self, version="1.0", num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes, self.with_pool = num_classes, with_pool
        if version == "1.0":
            self.features = nn.Sequential(
                nn.Conv2D(3, 96, 7, stride=2), nn.ReLU(), nn.MaxPool2D(3, 2),
                _Fire(96, 16, 64, 64), _Fire(128, 16, 64, 64),
                _Fire(128, 32, 128, 128), nn.MaxPool2D(3, 2),
                _Fire(256, 32, 128, 128), _Fire(256, 48, 192, 192),
                _Fire(384, 48, 192, 192), _Fire(384, 64, 256, 256),
                nn.MaxPool2D(3, 2), _Fire(512, 64, 256, 256))
        else:
            self.features = nn.Sequential(
                nn.Conv2D(3, 64, 3, stride=2), nn.ReLU(), nn.MaxPool2D(3, 2),
                _Fire(64, 16, 64, 64), _Fire(128, 16, 64, 64),
                nn.MaxPool2D(3, 2), _Fire(128, 32, 128, 128),
                _Fire(256, 32, 128, 128), nn.MaxPool2D(3, 2),
                _Fire(256, 48, 192, 192), _Fire(384, 48, 192, 192),
                _Fire(384, 64, 256, 256), _Fire(512, 64, 256, 256))
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Dropout(0.5), nn.Conv2D(512, num_classes, 1), nn.ReLU())
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)

    def forward(self, x):
        x = self.features(x)
        if self.num_classes > 0:
            x = self.classifier(x)
        if self.with_pool:
            x = self.pool(x)
        return x.flatten(1)


def alexnet(pretrained=False, **kw):
    if pretrained:
        raise NotImplementedError("pretrained weights require network access")
    return AlexNet(**kw)


def squeezenet1_0(pretrained=False, **kw):
    if pretrained:
        raise NotImplementedError("pretrained weights require network access")
    return SqueezeNet("1.0", **kw)


def squeezenet1_1(pretrained=False, **kw):
    if pretrained:
        raise NotImplementedError("pretrained weights require network access")
    return SqueezeNet("1.1", **kw)
