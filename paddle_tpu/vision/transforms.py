"""Image transforms (reference: python/paddle/vision/transforms/transforms.py).

Operate on numpy HWC uint8/float arrays (or Tensors) on the host; device work
happens after batching via DataLoader. TPU-first: keep per-sample work in
numpy on host CPU, feed the device large batched arrays.
"""
from __future__ import annotations

import numbers
import random

import numpy as np

__all__ = ["Compose", "BaseTransform", "ToTensor", "Resize", "RandomResizedCrop",
           "CenterCrop", "RandomCrop", "RandomHorizontalFlip", "RandomVerticalFlip",
           "Normalize", "Transpose", "Pad", "RandomRotation", "Grayscale",
           "BrightnessTransform", "ContrastTransform", "SaturationTransform",
           "HueTransform", "ColorJitter", "RandomErasing"]


def _to_hwc_array(img):
    arr = np.asarray(img)
    if arr.ndim == 2:
        arr = arr[:, :, None]
    return arr


def _resize_np(img, size, interpolation="bilinear"):
    """Pure-numpy bilinear/nearest resize (no PIL/cv2 dependency)."""
    img = _to_hwc_array(img)
    h, w = img.shape[:2]
    if isinstance(size, int):
        if h < w:
            oh, ow = size, int(size * w / h)
        else:
            oh, ow = int(size * h / w), size
    else:
        oh, ow = size
    if (oh, ow) == (h, w):
        return img
    if interpolation == "nearest":
        ys = (np.arange(oh) * h / oh).astype(np.int64).clip(0, h - 1)
        xs = (np.arange(ow) * w / ow).astype(np.int64).clip(0, w - 1)
        return img[ys][:, xs]
    ys = (np.arange(oh) + 0.5) * h / oh - 0.5
    xs = (np.arange(ow) + 0.5) * w / ow - 0.5
    y0 = np.floor(ys).clip(0, h - 1).astype(np.int64)
    x0 = np.floor(xs).clip(0, w - 1).astype(np.int64)
    y1 = (y0 + 1).clip(0, h - 1)
    x1 = (x0 + 1).clip(0, w - 1)
    wy = (ys - y0).clip(0, 1)[:, None, None]
    wx = (xs - x0).clip(0, 1)[None, :, None]
    f = img.astype(np.float64)
    out = (f[y0][:, x0] * (1 - wy) * (1 - wx) + f[y0][:, x1] * (1 - wy) * wx +
           f[y1][:, x0] * wy * (1 - wx) + f[y1][:, x1] * wy * wx)
    return out.astype(img.dtype) if img.dtype != np.uint8 else \
        np.round(out).clip(0, 255).astype(np.uint8)


class BaseTransform:
    """reference transforms.py:139 BaseTransform."""

    def __call__(self, img):
        return self._apply_image(img)

    def _apply_image(self, img):
        raise NotImplementedError


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, data):
        for t in self.transforms:
            data = t(data)
        return data


class ToTensor(BaseTransform):
    """HWC uint8 [0,255] -> CHW float32 [0,1]."""

    def __init__(self, data_format="CHW"):
        self.data_format = data_format

    def _apply_image(self, img):
        src = _to_hwc_array(img)
        arr = src.astype(np.float32)
        if src.dtype == np.uint8:
            arr = arr / 255.0
        if self.data_format == "CHW":
            arr = arr.transpose(2, 0, 1)
        return arr


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear"):
        self.size, self.interpolation = size, interpolation

    def _apply_image(self, img):
        return _resize_np(img, self.size, self.interpolation)


class CenterCrop(BaseTransform):
    def __init__(self, size):
        self.size = (size, size) if isinstance(size, numbers.Number) else size

    def _apply_image(self, img):
        img = _to_hwc_array(img)
        h, w = img.shape[:2]
        th, tw = self.size
        i, j = max(0, (h - th) // 2), max(0, (w - tw) // 2)
        return img[i:i + th, j:j + tw]


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed=False):
        self.size = (size, size) if isinstance(size, numbers.Number) else size
        self.padding, self.pad_if_needed = padding, pad_if_needed

    def _apply_image(self, img):
        img = _to_hwc_array(img)
        if self.padding:
            p = self.padding if isinstance(self.padding, (list, tuple)) \
                else [self.padding] * 4
            img = np.pad(img, ((p[1], p[3]), (p[0], p[2]), (0, 0)))
        h, w = img.shape[:2]
        th, tw = self.size
        if self.pad_if_needed and (h < th or w < tw):
            img = np.pad(img, ((0, max(0, th - h)), (0, max(0, tw - w)), (0, 0)))
            h, w = img.shape[:2]
        i = random.randint(0, h - th)
        j = random.randint(0, w - tw)
        return img[i:i + th, j:j + tw]


class RandomResizedCrop(BaseTransform):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation="bilinear"):
        self.size = (size, size) if isinstance(size, numbers.Number) else size
        self.scale, self.ratio, self.interpolation = scale, ratio, interpolation

    def _apply_image(self, img):
        img = _to_hwc_array(img)
        h, w = img.shape[:2]
        area = h * w
        for _ in range(10):
            target = random.uniform(*self.scale) * area
            ar = np.exp(random.uniform(np.log(self.ratio[0]), np.log(self.ratio[1])))
            cw = int(round(np.sqrt(target * ar)))
            ch = int(round(np.sqrt(target / ar)))
            if 0 < cw <= w and 0 < ch <= h:
                i = random.randint(0, h - ch)
                j = random.randint(0, w - cw)
                return _resize_np(img[i:i + ch, j:j + cw], self.size,
                                  self.interpolation)
        return _resize_np(CenterCrop(min(h, w))._apply_image(img), self.size,
                          self.interpolation)


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5):
        self.prob = prob

    def _apply_image(self, img):
        if random.random() < self.prob:
            return _to_hwc_array(img)[:, ::-1].copy()
        return _to_hwc_array(img)


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5):
        self.prob = prob

    def _apply_image(self, img):
        if random.random() < self.prob:
            return _to_hwc_array(img)[::-1].copy()
        return _to_hwc_array(img)


class Normalize(BaseTransform):
    """(x - mean) / std; accepts CHW or HWC via data_format."""

    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False):
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)
        self.data_format = data_format

    def _apply_image(self, img):
        arr = np.asarray(img, np.float32)
        shape = (-1, 1, 1) if self.data_format == "CHW" else (1, 1, -1)
        return (arr - self.mean.reshape(shape)) / self.std.reshape(shape)


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1)):
        self.order = order

    def _apply_image(self, img):
        return _to_hwc_array(img).transpose(self.order)


class Pad(BaseTransform):
    def __init__(self, padding, fill=0, padding_mode="constant"):
        self.padding = padding if isinstance(padding, (list, tuple)) \
            else [padding] * 4
        self.fill, self.padding_mode = fill, padding_mode

    def _apply_image(self, img):
        img = _to_hwc_array(img)
        p = self.padding
        if len(p) == 2:
            p = [p[0], p[1], p[0], p[1]]
        mode = {"constant": "constant", "edge": "edge", "reflect": "reflect",
                "symmetric": "symmetric"}[self.padding_mode]
        kw = {"constant_values": self.fill} if mode == "constant" else {}
        return np.pad(img, ((p[1], p[3]), (p[0], p[2]), (0, 0)), mode, **kw)


class RandomRotation(BaseTransform):
    """Rotation via inverse nearest remap (scipy/PIL-free)."""

    def __init__(self, degrees, interpolation="nearest", expand=False):
        if isinstance(degrees, numbers.Number):
            degrees = (-degrees, degrees)
        self.degrees, self.expand = degrees, expand

    def _apply_image(self, img):
        img = _to_hwc_array(img)
        angle = np.deg2rad(random.uniform(*self.degrees))
        h, w = img.shape[:2]
        c, s = np.cos(angle), np.sin(angle)
        if self.expand:
            oh = int(np.ceil(abs(h * c) + abs(w * s)))
            ow = int(np.ceil(abs(w * c) + abs(h * s)))
        else:
            oh, ow = h, w
        cy, cx = (h - 1) / 2, (w - 1) / 2          # source center
        ocy, ocx = (oh - 1) / 2, (ow - 1) / 2      # output center
        ys, xs = np.mgrid[0:oh, 0:ow]
        sy = (c * (ys - ocy) + s * (xs - ocx) + cy).round().astype(np.int64)
        sx = (-s * (ys - ocy) + c * (xs - ocx) + cx).round().astype(np.int64)
        valid = (sy >= 0) & (sy < h) & (sx >= 0) & (sx < w)
        out = np.zeros((oh, ow, img.shape[2]), img.dtype)
        out[valid] = img[sy[valid], sx[valid]]
        return out


class Grayscale(BaseTransform):
    def __init__(self, num_output_channels=1):
        self.num_output_channels = num_output_channels

    def _apply_image(self, img):
        img = _to_hwc_array(img)
        gray = (img[..., :3].astype(np.float32)
                @ np.array([0.299, 0.587, 0.114], np.float32))
        gray = gray.astype(img.dtype)[..., None]
        return np.repeat(gray, self.num_output_channels, axis=-1)


class BrightnessTransform(BaseTransform):
    def __init__(self, value):
        self.value = value

    def _apply_image(self, img):
        img = _to_hwc_array(img)
        factor = random.uniform(max(0, 1 - self.value), 1 + self.value)
        out = img.astype(np.float32) * factor
        return out.clip(0, 255).astype(img.dtype) if img.dtype == np.uint8 else out


class ContrastTransform(BaseTransform):
    def __init__(self, value):
        self.value = value

    def _apply_image(self, img):
        img = _to_hwc_array(img)
        factor = random.uniform(max(0, 1 - self.value), 1 + self.value)
        mean = img.astype(np.float32).mean()
        out = (img.astype(np.float32) - mean) * factor + mean
        return out.clip(0, 255).astype(img.dtype) if img.dtype == np.uint8 else out


class SaturationTransform(BaseTransform):
    def __init__(self, value):
        self.value = value

    def _apply_image(self, img):
        img = _to_hwc_array(img)
        factor = random.uniform(max(0, 1 - self.value), 1 + self.value)
        gray = Grayscale(img.shape[-1])._apply_image(img).astype(np.float32)
        out = img.astype(np.float32) * factor + gray * (1 - factor)
        return out.clip(0, 255).astype(img.dtype) if img.dtype == np.uint8 else out


class HueTransform(BaseTransform):
    """Hue rotation by a random angle in [-value, value] (value in [0, 0.5],
    fraction of a full hue circle), via the YIQ-space rotation matrix."""

    def __init__(self, value):
        if not 0 <= value <= 0.5:
            raise ValueError("hue value must be in [0, 0.5]")
        self.value = value

    def _apply_image(self, img):
        img = _to_hwc_array(img)
        theta = random.uniform(-self.value, self.value) * 2 * np.pi
        c, s = np.cos(theta), np.sin(theta)
        to_yiq = np.array([[0.299, 0.587, 0.114],
                           [0.596, -0.274, -0.321],
                           [0.211, -0.523, 0.311]], np.float32)
        rot = np.array([[1, 0, 0], [0, c, -s], [0, s, c]], np.float32)
        m = np.linalg.inv(to_yiq) @ rot @ to_yiq
        out = img[..., :3].astype(np.float32) @ m.T
        if img.shape[-1] > 3:
            out = np.concatenate([out, img[..., 3:].astype(np.float32)], -1)
        return out.clip(0, 255).astype(img.dtype) if img.dtype == np.uint8 else out


class ColorJitter(BaseTransform):
    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0):
        self.transforms = []
        if brightness:
            self.transforms.append(BrightnessTransform(brightness))
        if contrast:
            self.transforms.append(ContrastTransform(contrast))
        if saturation:
            self.transforms.append(SaturationTransform(saturation))
        if hue:
            self.transforms.append(HueTransform(hue))

    def _apply_image(self, img):
        ts = list(self.transforms)
        random.shuffle(ts)
        for t in ts:
            img = t(img)
        return img


class RandomErasing(BaseTransform):
    def __init__(self, prob=0.5, scale=(0.02, 0.33), ratio=(0.3, 3.3), value=0):
        self.prob, self.scale, self.ratio, self.value = prob, scale, ratio, value

    def _apply_image(self, img):
        img = _to_hwc_array(img).copy()
        if random.random() >= self.prob:
            return img
        h, w = img.shape[:2]
        area = h * w
        for _ in range(10):
            target = random.uniform(*self.scale) * area
            ar = random.uniform(*self.ratio)
            eh, ew = int(round(np.sqrt(target / ar))), int(round(np.sqrt(target * ar)))
            if eh < h and ew < w:
                i, j = random.randint(0, h - eh), random.randint(0, w - ew)
                img[i:i + eh, j:j + ew] = self.value
                break
        return img
