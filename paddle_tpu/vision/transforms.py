"""Image transforms (reference: python/paddle/vision/transforms/transforms.py).

Operate on numpy HWC uint8/float arrays (or Tensors) on the host; device work
happens after batching via DataLoader. TPU-first: keep per-sample work in
numpy on host CPU, feed the device large batched arrays.
"""
from __future__ import annotations

import numbers
import random

import numpy as np

__all__ = ["Compose", "BaseTransform", "ToTensor", "Resize", "RandomResizedCrop",
           "CenterCrop", "RandomCrop", "RandomHorizontalFlip", "RandomVerticalFlip",
           "Normalize", "Transpose", "Pad", "RandomRotation", "Grayscale",
           "BrightnessTransform", "ContrastTransform", "SaturationTransform",
           "HueTransform", "ColorJitter", "RandomErasing"]


def _to_hwc_array(img):
    arr = np.asarray(img)
    if arr.ndim == 2:
        arr = arr[:, :, None]
    return arr


def _resize_np(img, size, interpolation="bilinear"):
    """Pure-numpy bilinear/nearest resize (no PIL/cv2 dependency)."""
    img = _to_hwc_array(img)
    h, w = img.shape[:2]
    if isinstance(size, int):
        if h < w:
            oh, ow = size, int(size * w / h)
        else:
            oh, ow = int(size * h / w), size
    else:
        oh, ow = size
    if (oh, ow) == (h, w):
        return img
    if interpolation == "nearest":
        ys = (np.arange(oh) * h / oh).astype(np.int64).clip(0, h - 1)
        xs = (np.arange(ow) * w / ow).astype(np.int64).clip(0, w - 1)
        return img[ys][:, xs]
    ys = (np.arange(oh) + 0.5) * h / oh - 0.5
    xs = (np.arange(ow) + 0.5) * w / ow - 0.5
    y0 = np.floor(ys).clip(0, h - 1).astype(np.int64)
    x0 = np.floor(xs).clip(0, w - 1).astype(np.int64)
    y1 = (y0 + 1).clip(0, h - 1)
    x1 = (x0 + 1).clip(0, w - 1)
    wy = (ys - y0).clip(0, 1)[:, None, None]
    wx = (xs - x0).clip(0, 1)[None, :, None]
    f = img.astype(np.float64)
    out = (f[y0][:, x0] * (1 - wy) * (1 - wx) + f[y0][:, x1] * (1 - wy) * wx +
           f[y1][:, x0] * wy * (1 - wx) + f[y1][:, x1] * wy * wx)
    return out.astype(img.dtype) if img.dtype != np.uint8 else \
        np.round(out).clip(0, 255).astype(np.uint8)


class BaseTransform:
    """reference transforms.py:139 BaseTransform."""

    def __call__(self, img):
        return self._apply_image(img)

    def _apply_image(self, img):
        raise NotImplementedError


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, data):
        for t in self.transforms:
            data = t(data)
        return data


class ToTensor(BaseTransform):
    """HWC uint8 [0,255] -> CHW float32 [0,1]."""

    def __init__(self, data_format="CHW"):
        self.data_format = data_format

    def _apply_image(self, img):
        src = _to_hwc_array(img)
        arr = src.astype(np.float32)
        if src.dtype == np.uint8:
            arr = arr / 255.0
        if self.data_format == "CHW":
            arr = arr.transpose(2, 0, 1)
        return arr


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear"):
        self.size, self.interpolation = size, interpolation

    def _apply_image(self, img):
        return _resize_np(img, self.size, self.interpolation)


class CenterCrop(BaseTransform):
    def __init__(self, size):
        self.size = (size, size) if isinstance(size, numbers.Number) else size

    def _apply_image(self, img):
        img = _to_hwc_array(img)
        h, w = img.shape[:2]
        th, tw = self.size
        i, j = max(0, (h - th) // 2), max(0, (w - tw) // 2)
        return img[i:i + th, j:j + tw]


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed=False):
        self.size = (size, size) if isinstance(size, numbers.Number) else size
        self.padding, self.pad_if_needed = padding, pad_if_needed

    def _apply_image(self, img):
        img = _to_hwc_array(img)
        if self.padding:
            p = self.padding if isinstance(self.padding, (list, tuple)) \
                else [self.padding] * 4
            img = np.pad(img, ((p[1], p[3]), (p[0], p[2]), (0, 0)))
        h, w = img.shape[:2]
        th, tw = self.size
        if self.pad_if_needed and (h < th or w < tw):
            img = np.pad(img, ((0, max(0, th - h)), (0, max(0, tw - w)), (0, 0)))
            h, w = img.shape[:2]
        i = random.randint(0, h - th)
        j = random.randint(0, w - tw)
        return img[i:i + th, j:j + tw]


class RandomResizedCrop(BaseTransform):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation="bilinear"):
        self.size = (size, size) if isinstance(size, numbers.Number) else size
        self.scale, self.ratio, self.interpolation = scale, ratio, interpolation

    def _apply_image(self, img):
        img = _to_hwc_array(img)
        h, w = img.shape[:2]
        area = h * w
        for _ in range(10):
            target = random.uniform(*self.scale) * area
            ar = np.exp(random.uniform(np.log(self.ratio[0]), np.log(self.ratio[1])))
            cw = int(round(np.sqrt(target * ar)))
            ch = int(round(np.sqrt(target / ar)))
            if 0 < cw <= w and 0 < ch <= h:
                i = random.randint(0, h - ch)
                j = random.randint(0, w - cw)
                return _resize_np(img[i:i + ch, j:j + cw], self.size,
                                  self.interpolation)
        return _resize_np(CenterCrop(min(h, w))._apply_image(img), self.size,
                          self.interpolation)


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5):
        self.prob = prob

    def _apply_image(self, img):
        if random.random() < self.prob:
            return _to_hwc_array(img)[:, ::-1].copy()
        return _to_hwc_array(img)


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5):
        self.prob = prob

    def _apply_image(self, img):
        if random.random() < self.prob:
            return _to_hwc_array(img)[::-1].copy()
        return _to_hwc_array(img)


class Normalize(BaseTransform):
    """(x - mean) / std; accepts CHW or HWC via data_format."""

    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False):
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)
        self.data_format = data_format

    def _apply_image(self, img):
        arr = np.asarray(img, np.float32)
        shape = (-1, 1, 1) if self.data_format == "CHW" else (1, 1, -1)
        return (arr - self.mean.reshape(shape)) / self.std.reshape(shape)


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1)):
        self.order = order

    def _apply_image(self, img):
        return _to_hwc_array(img).transpose(self.order)


class Pad(BaseTransform):
    def __init__(self, padding, fill=0, padding_mode="constant"):
        self.padding = padding if isinstance(padding, (list, tuple)) \
            else [padding] * 4
        self.fill, self.padding_mode = fill, padding_mode

    def _apply_image(self, img):
        img = _to_hwc_array(img)
        p = self.padding
        if len(p) == 2:
            p = [p[0], p[1], p[0], p[1]]
        mode = {"constant": "constant", "edge": "edge", "reflect": "reflect",
                "symmetric": "symmetric"}[self.padding_mode]
        kw = {"constant_values": self.fill} if mode == "constant" else {}
        return np.pad(img, ((p[1], p[3]), (p[0], p[2]), (0, 0)), mode, **kw)


class RandomRotation(BaseTransform):
    """Rotation via inverse nearest remap (scipy/PIL-free)."""

    def __init__(self, degrees, interpolation="nearest", expand=False):
        if isinstance(degrees, numbers.Number):
            degrees = (-degrees, degrees)
        self.degrees, self.expand = degrees, expand

    def _apply_image(self, img):
        img = _to_hwc_array(img)
        angle = np.deg2rad(random.uniform(*self.degrees))
        h, w = img.shape[:2]
        c, s = np.cos(angle), np.sin(angle)
        if self.expand:
            oh = int(np.ceil(abs(h * c) + abs(w * s)))
            ow = int(np.ceil(abs(w * c) + abs(h * s)))
        else:
            oh, ow = h, w
        cy, cx = (h - 1) / 2, (w - 1) / 2          # source center
        ocy, ocx = (oh - 1) / 2, (ow - 1) / 2      # output center
        ys, xs = np.mgrid[0:oh, 0:ow]
        sy = (c * (ys - ocy) + s * (xs - ocx) + cy).round().astype(np.int64)
        sx = (-s * (ys - ocy) + c * (xs - ocx) + cx).round().astype(np.int64)
        valid = (sy >= 0) & (sy < h) & (sx >= 0) & (sx < w)
        out = np.zeros((oh, ow, img.shape[2]), img.dtype)
        out[valid] = img[sy[valid], sx[valid]]
        return out


class Grayscale(BaseTransform):
    def __init__(self, num_output_channels=1):
        self.num_output_channels = num_output_channels

    def _apply_image(self, img):
        img = _to_hwc_array(img)
        gray = (img[..., :3].astype(np.float32)
                @ np.array([0.299, 0.587, 0.114], np.float32))
        gray = gray.astype(img.dtype)[..., None]
        return np.repeat(gray, self.num_output_channels, axis=-1)


class BrightnessTransform(BaseTransform):
    def __init__(self, value):
        self.value = value

    def _apply_image(self, img):
        img = _to_hwc_array(img)
        factor = random.uniform(max(0, 1 - self.value), 1 + self.value)
        out = img.astype(np.float32) * factor
        return out.clip(0, 255).astype(img.dtype) if img.dtype == np.uint8 else out


class ContrastTransform(BaseTransform):
    def __init__(self, value):
        self.value = value

    def _apply_image(self, img):
        img = _to_hwc_array(img)
        factor = random.uniform(max(0, 1 - self.value), 1 + self.value)
        mean = img.astype(np.float32).mean()
        out = (img.astype(np.float32) - mean) * factor + mean
        return out.clip(0, 255).astype(img.dtype) if img.dtype == np.uint8 else out


class SaturationTransform(BaseTransform):
    def __init__(self, value):
        self.value = value

    def _apply_image(self, img):
        img = _to_hwc_array(img)
        factor = random.uniform(max(0, 1 - self.value), 1 + self.value)
        gray = Grayscale(img.shape[-1])._apply_image(img).astype(np.float32)
        out = img.astype(np.float32) * factor + gray * (1 - factor)
        return out.clip(0, 255).astype(img.dtype) if img.dtype == np.uint8 else out


class HueTransform(BaseTransform):
    """Hue rotation by a random angle in [-value, value] (value in [0, 0.5],
    fraction of a full hue circle), via the YIQ-space rotation matrix."""

    def __init__(self, value):
        if not 0 <= value <= 0.5:
            raise ValueError("hue value must be in [0, 0.5]")
        self.value = value

    def _apply_image(self, img):
        img = _to_hwc_array(img)
        theta = random.uniform(-self.value, self.value) * 2 * np.pi
        c, s = np.cos(theta), np.sin(theta)
        to_yiq = np.array([[0.299, 0.587, 0.114],
                           [0.596, -0.274, -0.321],
                           [0.211, -0.523, 0.311]], np.float32)
        rot = np.array([[1, 0, 0], [0, c, -s], [0, s, c]], np.float32)
        m = np.linalg.inv(to_yiq) @ rot @ to_yiq
        out = img[..., :3].astype(np.float32) @ m.T
        if img.shape[-1] > 3:
            out = np.concatenate([out, img[..., 3:].astype(np.float32)], -1)
        return out.clip(0, 255).astype(img.dtype) if img.dtype == np.uint8 else out


class ColorJitter(BaseTransform):
    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0):
        self.transforms = []
        if brightness:
            self.transforms.append(BrightnessTransform(brightness))
        if contrast:
            self.transforms.append(ContrastTransform(contrast))
        if saturation:
            self.transforms.append(SaturationTransform(saturation))
        if hue:
            self.transforms.append(HueTransform(hue))

    def _apply_image(self, img):
        ts = list(self.transforms)
        random.shuffle(ts)
        for t in ts:
            img = t(img)
        return img


class RandomErasing(BaseTransform):
    def __init__(self, prob=0.5, scale=(0.02, 0.33), ratio=(0.3, 3.3), value=0):
        self.prob, self.scale, self.ratio, self.value = prob, scale, ratio, value

    def _apply_image(self, img):
        img = _to_hwc_array(img).copy()
        if random.random() >= self.prob:
            return img
        h, w = img.shape[:2]
        area = h * w
        for _ in range(10):
            target = random.uniform(*self.scale) * area
            ar = random.uniform(*self.ratio)
            eh, ew = int(round(np.sqrt(target / ar))), int(round(np.sqrt(target * ar)))
            if eh < h and ew < w:
                i, j = random.randint(0, h - eh), random.randint(0, w - ew)
                img[i:i + eh, j:j + ew] = self.value
                break
        return img


# ---------------------------------------------------------------------------
# Functional API (reference: python/paddle/vision/transforms/functional.py).
# All work on HWC numpy arrays / PIL images; Tensor passthrough where noted.

def to_tensor(pic, data_format="CHW"):
    from ..core.tensor import Tensor
    import jax.numpy as jnp
    was_uint8 = np.asarray(pic).dtype == np.uint8
    arr = _to_hwc_array(pic).astype(np.float32)
    if was_uint8:
        arr = arr / 255.0
    if data_format == "CHW":
        arr = arr.transpose(2, 0, 1)
    return Tensor(jnp.asarray(arr))


def hflip(img):
    return _to_hwc_array(img)[:, ::-1]


def vflip(img):
    return _to_hwc_array(img)[::-1]


def resize(img, size, interpolation="bilinear"):
    return _resize_np(img, size, interpolation)


def crop(img, top, left, height, width):
    return _to_hwc_array(img)[top:top + height, left:left + width]


def center_crop(img, output_size):
    return CenterCrop(output_size)._apply_image(img)


def pad(img, padding, fill=0, padding_mode="constant"):
    img = _to_hwc_array(img)
    p = padding if isinstance(padding, (list, tuple)) else [padding] * 4
    if len(p) == 2:
        p = [p[0], p[1], p[0], p[1]]
    mode = {"constant": "constant", "edge": "edge", "reflect": "reflect",
            "symmetric": "symmetric"}[padding_mode]
    kw = {"constant_values": fill} if padding_mode == "constant" else {}
    return np.pad(img, ((p[1], p[3]), (p[0], p[2]), (0, 0)), mode=mode, **kw)


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    arr = np.asarray(img, np.float32)
    mean = np.asarray(mean, np.float32)
    std = np.asarray(std, np.float32)
    if data_format == "CHW":
        return (arr - mean.reshape(-1, 1, 1)) / std.reshape(-1, 1, 1)
    return (arr - mean) / std


def to_grayscale(img, num_output_channels=1):
    return Grayscale(num_output_channels)._apply_image(img)


def adjust_brightness(img, brightness_factor):
    arr = _to_hwc_array(img)
    out = arr.astype(np.float32) * brightness_factor
    return (np.clip(out, 0, 255).astype(np.uint8) if arr.dtype == np.uint8
            else out.astype(arr.dtype))


def adjust_contrast(img, contrast_factor):
    arr = _to_hwc_array(img)
    f = arr.astype(np.float32)
    mean = f.mean()
    out = (f - mean) * contrast_factor + mean
    return (np.clip(out, 0, 255).astype(np.uint8) if arr.dtype == np.uint8
            else out.astype(arr.dtype))


def adjust_hue(img, hue_factor):
    if not -0.5 <= hue_factor <= 0.5:
        raise ValueError("hue_factor must be in [-0.5, 0.5]")
    return _hue_shift(_to_hwc_array(img), hue_factor)


def _hue_shift(arr, hue_factor):
    f = arr.astype(np.float32) / (255.0 if arr.dtype == np.uint8 else 1.0)
    r, g, b = f[..., 0], f[..., 1], f[..., 2]
    mx, mn = f[..., :3].max(-1), f[..., :3].min(-1)
    d = mx - mn + 1e-12
    h = np.where(mx == r, ((g - b) / d) % 6,
                 np.where(mx == g, (b - r) / d + 2, (r - g) / d + 4)) / 6.0
    s = np.where(mx > 0, d / (mx + 1e-12), 0.0)
    v = mx
    h = (h + hue_factor) % 1.0
    i = np.floor(h * 6).astype(np.int64) % 6
    fr = h * 6 - np.floor(h * 6)
    p, q, t = v * (1 - s), v * (1 - fr * s), v * (1 - (1 - fr) * s)
    rgb = np.select(
        [(i == k)[..., None] for k in range(6)],
        [np.stack([v, t, p], -1), np.stack([q, v, p], -1),
         np.stack([p, v, t], -1), np.stack([p, q, v], -1),
         np.stack([t, p, v], -1), np.stack([v, p, q], -1)])
    out = rgb
    if arr.shape[-1] > 3:
        out = np.concatenate([rgb, f[..., 3:]], -1)
    return (np.round(out * 255).clip(0, 255).astype(np.uint8)
            if arr.dtype == np.uint8 else out.astype(arr.dtype))


def rotate(img, angle, interpolation="nearest", expand=False, center=None,
           fill=0):
    t = RandomRotation((angle, angle), interpolation, expand)
    return t._apply_image(img)


def _inverse_map_sample(img, inv):
    """Sample img at inverse-mapped integer coords; inv(ys, xs)->(sy, sx)."""
    h, w = img.shape[:2]
    ys, xs = np.mgrid[0:h, 0:w]
    sy, sx = inv(ys, xs)
    sy = np.round(sy).astype(np.int64)
    sx = np.round(sx).astype(np.int64)
    valid = (sy >= 0) & (sy < h) & (sx >= 0) & (sx < w)
    out = np.zeros_like(img)
    out[valid] = img[sy[valid], sx[valid]]
    return out


def affine(img, angle, translate, scale, shear, interpolation="nearest",
           fill=0, center=None):
    """reference: functional.affine — inverse-warp with the affine matrix."""
    img = _to_hwc_array(img)
    h, w = img.shape[:2]
    cy, cx = ((h - 1) / 2, (w - 1) / 2) if center is None else \
        (center[1], center[0])
    a = np.deg2rad(angle)
    sx_, sy_ = [np.deg2rad(s) for s in (shear if isinstance(shear, (list, tuple))
                                        else (shear, 0.0))]
    # forward matrix: T(center) R S Shear T(-center) + translate
    m = np.array([[np.cos(a + sy_), -np.sin(a + sx_)],
                  [np.sin(a + sy_), np.cos(a + sx_)]]) * scale
    minv = np.linalg.inv(m)
    ty, tx = translate[1], translate[0]

    def inv(ys, xs):
        y = ys - cy - ty
        x = xs - cx - tx
        sy = minv[0, 0] * y + minv[0, 1] * x + cy
        sx = minv[1, 0] * y + minv[1, 1] * x + cx
        return sy, sx
    return _inverse_map_sample(img, inv)


def perspective(img, startpoints, endpoints, interpolation="nearest", fill=0):
    """reference: functional.perspective — 4-point homography inverse warp."""
    img = _to_hwc_array(img)
    src = np.asarray(endpoints, np.float64)   # output quad
    dst = np.asarray(startpoints, np.float64)  # input quad
    A = []
    for (x, y), (u, v) in zip(src, dst):
        A.append([x, y, 1, 0, 0, 0, -u * x, -u * y])
        A.append([0, 0, 0, x, y, 1, -v * x, -v * y])
    A = np.asarray(A)
    b = dst.reshape(-1)
    coef, *_ = np.linalg.lstsq(A, b, rcond=None)
    Hm = np.append(coef, 1.0).reshape(3, 3)

    def inv(ys, xs):
        denom = Hm[2, 0] * xs + Hm[2, 1] * ys + Hm[2, 2]
        sx = (Hm[0, 0] * xs + Hm[0, 1] * ys + Hm[0, 2]) / denom
        sy = (Hm[1, 0] * xs + Hm[1, 1] * ys + Hm[1, 2]) / denom
        return sy, sx
    return _inverse_map_sample(img, inv)


def erase(img, i, j, h, w, v, inplace=False):
    from ..core.tensor import Tensor
    if isinstance(img, Tensor):
        import jax.numpy as jnp
        arr = np.asarray(img._data).copy()
        if arr.ndim == 3:  # CHW
            arr[:, i:i + h, j:j + w] = v
        else:
            arr[..., :, i:i + h, j:j + w] = v
        out = Tensor(jnp.asarray(arr))
        if inplace:
            img._data = out._data
            return img
        return out
    arr = _to_hwc_array(img).copy()
    arr[i:i + h, j:j + w] = v
    return arr


class RandomAffine(BaseTransform):
    """reference: transforms.RandomAffine."""

    def __init__(self, degrees, translate=None, scale=None, shear=None,
                 interpolation="nearest", fill=0, center=None):
        if isinstance(degrees, numbers.Number):
            degrees = (-degrees, degrees)
        if isinstance(shear, numbers.Number):
            shear = (-shear, shear)
        self.degrees = degrees
        self.translate = translate
        self.scale_range = scale
        self.shear = shear
        self.center = center

    def _apply_image(self, img):
        img = _to_hwc_array(img)
        h, w = img.shape[:2]
        angle = random.uniform(*self.degrees)
        tx = ty = 0
        if self.translate:
            tx = random.uniform(-self.translate[0], self.translate[0]) * w
            ty = random.uniform(-self.translate[1], self.translate[1]) * h
        sc = random.uniform(*self.scale_range) if self.scale_range else 1.0
        sh = random.uniform(*self.shear) if self.shear else 0.0
        return affine(img, angle, (tx, ty), sc, (sh, 0.0), center=self.center)


class RandomPerspective(BaseTransform):
    """reference: transforms.RandomPerspective."""

    def __init__(self, prob=0.5, distortion_scale=0.5,
                 interpolation="nearest", fill=0):
        self.prob = prob
        self.distortion_scale = distortion_scale

    def _apply_image(self, img):
        img = _to_hwc_array(img)
        if random.random() > self.prob:
            return img
        h, w = img.shape[:2]
        d = self.distortion_scale
        def jitter(x, y):
            return (x + random.uniform(-d, d) * w / 2,
                    y + random.uniform(-d, d) * h / 2)
        start = [(0, 0), (w - 1, 0), (w - 1, h - 1), (0, h - 1)]
        end = [jitter(*p) for p in start]
        return perspective(img, start, end)
