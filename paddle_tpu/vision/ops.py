"""Vision ops: boxes, NMS, RoI ops (align/pool/psroi), DeformConv (DCNv1/
v2), SSD prior_box, RPN generate_proposals (reference:
python/paddle/vision/ops.py; detection ops from
paddle/fluid/operators/detection/).

TPU-first: NMS/proposal generation are static-shape masked suppression
(padded tensors + counts for ragged results), DeformConv's gather feeds
one MXU einsum, prior boxes fold to constants at trace time.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor, apply_op
from ..nn.layer import Layer

__all__ = ["yolo_box", "box_coder", "nms", "roi_align", "roi_pool",
           "distribute_fpn_proposals", "box_iou", "psroi_pool",
           "deform_conv2d", "prior_box", "generate_proposals"]


def _data(x):
    return x._data if isinstance(x, Tensor) else jnp.asarray(x)


def box_iou(boxes1, boxes2):
    """IoU matrix [N, M] for xyxy boxes."""
    b1, b2 = _data(boxes1), _data(boxes2)
    area1 = (b1[:, 2] - b1[:, 0]) * (b1[:, 3] - b1[:, 1])
    area2 = (b2[:, 2] - b2[:, 0]) * (b2[:, 3] - b2[:, 1])
    lt = jnp.maximum(b1[:, None, :2], b2[None, :, :2])
    rb = jnp.minimum(b1[:, None, 2:], b2[None, :, 2:])
    wh = jnp.clip(rb - lt, 0)
    inter = wh[..., 0] * wh[..., 1]
    return Tensor(inter / (area1[:, None] + area2[None, :] - inter + 1e-10))


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None):
    """reference ops.py:1461 paddle.vision.ops.nms.

    Masked O(N^2) suppression with static shapes: returns kept indices
    sorted by score (host-materialised, like the reference's dynamic out).
    """
    b = _data(boxes)
    n = b.shape[0]
    s = _data(scores) if scores is not None else jnp.arange(n, 0, -1, jnp.float32)
    if category_idxs is not None:
        # offset boxes per category so cross-category IoU is 0 (batched NMS trick)
        c = _data(category_idxs).astype(b.dtype)
        offset = (b.max() + 1.0) * c
        b = b + offset[:, None]
    order = jnp.argsort(-s)
    b_sorted = b[order]
    iou = box_iou(Tensor(b_sorted), Tensor(b_sorted))._data
    # keep[i] = no earlier kept box overlaps i above threshold
    import numpy as np
    iou_np = np.asarray(iou)
    keep_mask = np.ones(n, bool)
    for i in range(n):
        if not keep_mask[i]:
            continue
        keep_mask[i + 1:] &= iou_np[i, i + 1:] <= iou_threshold
    kept = np.asarray(order)[keep_mask]
    if top_k is not None:
        kept = kept[:top_k]
    return Tensor(jnp.asarray(kept, jnp.int32))


def _roi_grid(bd, boxes_num, n_rois, oh, ow, spatial_scale, aligned, samples):
    """Per-roi sample coordinates: ys [R, oh*samples], xs [R, ow*samples]."""
    bn = _data(boxes_num).astype(jnp.int32)
    batch_idx = jnp.repeat(jnp.arange(bn.shape[0]), bn, total_repeat_length=n_rois)
    off = 0.5 if aligned else 0.0
    x1 = bd[:, 0] * spatial_scale - off
    y1 = bd[:, 1] * spatial_scale - off
    x2 = bd[:, 2] * spatial_scale - off
    y2 = bd[:, 3] * spatial_scale - off
    rw = jnp.maximum(x2 - x1, 1e-3 if aligned else 1.0)
    rh = jnp.maximum(y2 - y1, 1e-3 if aligned else 1.0)
    # `samples` sub-points per bin along each axis, at (j+0.5)/samples of the bin
    sub = (jnp.arange(samples) + 0.5) / samples
    grid_y = (jnp.arange(oh)[:, None] + sub[None, :]).reshape(-1)  # [oh*samples]
    grid_x = (jnp.arange(ow)[:, None] + sub[None, :]).reshape(-1)
    ys = y1[:, None] + grid_y[None, :] * (rh[:, None] / oh)
    xs = x1[:, None] + grid_x[None, :] * (rw[:, None] / ow)
    return batch_idx, ys, xs


def _bilinear_sample(img, yy, xx, H, W):
    """img [C,H,W]; yy [Ny], xx [Nx] -> [C,Ny,Nx]."""
    y0 = jnp.clip(jnp.floor(yy), 0, H - 1).astype(jnp.int32)
    x0 = jnp.clip(jnp.floor(xx), 0, W - 1).astype(jnp.int32)
    y1i = jnp.clip(y0 + 1, 0, H - 1)
    x1i = jnp.clip(x0 + 1, 0, W - 1)
    wy = jnp.clip(yy - y0, 0, 1)[None, :, None]
    wx = jnp.clip(xx - x0, 0, 1)[None, None, :]
    v00 = img[:, y0][:, :, x0]
    v01 = img[:, y0][:, :, x1i]
    v10 = img[:, y1i][:, :, x0]
    v11 = img[:, y1i][:, :, x1i]
    return (v00 * (1 - wy) * (1 - wx) + v01 * (1 - wy) * wx +
            v10 * wy * (1 - wx) + v11 * wy * wx)


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True):
    """reference ops.py:1080 — average of sampling_ratio^2 bilinear samples
    per bin (2x2 when sampling_ratio is adaptive/-1, like the reference's
    default for typical bin sizes)."""
    import jax
    xd = _data(x)
    bd = _data(boxes)
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size
    n_rois = bd.shape[0]
    C, H, W = xd.shape[1:]
    samples = sampling_ratio if sampling_ratio > 0 else 2
    batch_idx, ys, xs = _roi_grid(bd, boxes_num, n_rois, oh, ow, spatial_scale,
                                  aligned, samples)
    out = jax.vmap(lambda bi, yy, xx: _bilinear_sample(xd[bi], yy, xx, H, W))(
        batch_idx, ys, xs)  # [R, C, oh*s, ow*s]
    out = out.reshape(n_rois, C, oh, samples, ow, samples)
    return Tensor(out.mean(axis=(3, 5)))


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0):
    """Max-pool RoI variant (reference ops.py:989): max over a dense sample
    grid per bin (4x4 sub-samples approximates the reference's integer-pixel
    max with static shapes)."""
    import jax
    xd = _data(x)
    bd = _data(boxes)
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size
    n_rois = bd.shape[0]
    C, H, W = xd.shape[1:]
    samples = 4
    batch_idx, ys, xs = _roi_grid(bd, boxes_num, n_rois, oh, ow, spatial_scale,
                                  aligned=False, samples=samples)
    # nearest-pixel max, as the reference pools over integer pixel coords
    ys = jnp.clip(jnp.round(ys), 0, H - 1).astype(jnp.int32)
    xs = jnp.clip(jnp.round(xs), 0, W - 1).astype(jnp.int32)
    out = jax.vmap(lambda bi, yy, xx: xd[bi][:, yy][:, :, xx])(
        batch_idx, ys, xs)  # [R, C, oh*s, ow*s]
    out = out.reshape(n_rois, C, oh, samples, ow, samples)
    return Tensor(out.max(axis=(3, 5)))


def box_coder(prior_box, prior_box_var, target_box, code_type="encode_center_size",
              box_normalized=True):
    """reference detection box_coder (encode/decode center-size)."""
    pb, tb = _data(prior_box), _data(target_box)
    pbv = _data(prior_box_var) if prior_box_var is not None else None
    norm = 0.0 if box_normalized else 1.0
    pw = pb[:, 2] - pb[:, 0] + norm
    ph = pb[:, 3] - pb[:, 1] + norm
    pcx = pb[:, 0] + pw / 2
    pcy = pb[:, 1] + ph / 2
    if code_type == "encode_center_size":
        tw = tb[:, 2] - tb[:, 0] + norm
        th = tb[:, 3] - tb[:, 1] + norm
        tcx = tb[:, 0] + tw / 2
        tcy = tb[:, 1] + th / 2
        out = jnp.stack([(tcx - pcx) / pw, (tcy - pcy) / ph,
                         jnp.log(tw / pw), jnp.log(th / ph)], axis=1)
        if pbv is not None:
            out = out / pbv
    else:  # decode
        d = tb
        if pbv is not None:
            d = d * pbv
        cx = d[..., 0] * pw + pcx
        cy = d[..., 1] * ph + pcy
        w = jnp.exp(d[..., 2]) * pw
        h = jnp.exp(d[..., 3]) * ph
        out = jnp.stack([cx - w / 2, cy - h / 2, cx + w / 2 - norm,
                         cy + h / 2 - norm], axis=-1)
    return Tensor(out)


def yolo_box(x, img_size, anchors, class_num, conf_thresh, downsample_ratio,
             clip_bbox=True, scale_x_y=1.0):
    """reference ops.py:373 — decode YOLO head to boxes+scores."""
    xd = _data(x)
    n, _, h, w = xd.shape
    na = len(anchors) // 2
    anc = jnp.asarray(anchors, jnp.float32).reshape(na, 2)
    xd = xd.reshape(n, na, 5 + class_num, h, w)
    gx = jnp.arange(w, dtype=jnp.float32)[None, None, None, :]
    gy = jnp.arange(h, dtype=jnp.float32)[None, None, :, None]
    sig = jax_sigmoid = lambda v: 1 / (1 + jnp.exp(-v))
    bx = (sig(xd[:, :, 0]) * scale_x_y - 0.5 * (scale_x_y - 1) + gx) / w
    by = (sig(xd[:, :, 1]) * scale_x_y - 0.5 * (scale_x_y - 1) + gy) / h
    bw = jnp.exp(xd[:, :, 2]) * anc[None, :, 0, None, None] / (w * downsample_ratio)
    bh = jnp.exp(xd[:, :, 3]) * anc[None, :, 1, None, None] / (h * downsample_ratio)
    conf = sig(xd[:, :, 4])
    probs = sig(xd[:, :, 5:]) * conf[:, :, None]
    img_h = _data(img_size)[:, 0].astype(jnp.float32)[:, None, None, None]
    img_w = _data(img_size)[:, 1].astype(jnp.float32)[:, None, None, None]
    x1 = (bx - bw / 2) * img_w
    y1 = (by - bh / 2) * img_h
    x2 = (bx + bw / 2) * img_w
    y2 = (by + bh / 2) * img_h
    if clip_bbox:
        x1 = jnp.clip(x1, 0)
        y1 = jnp.clip(y1, 0)
        x2 = jnp.minimum(x2, img_w - 1)
        y2 = jnp.minimum(y2, img_h - 1)
    boxes = jnp.stack([x1, y1, x2, y2], axis=-1).reshape(n, -1, 4)
    scores = probs.transpose(0, 1, 3, 4, 2).reshape(n, -1, class_num)
    mask = (conf > conf_thresh).reshape(n, -1)
    boxes = boxes * mask[..., None]
    scores = scores * mask[..., None]
    return Tensor(boxes), Tensor(scores)


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, rois_num=None):
    """reference ops.py:701 — assign RoIs to FPN levels by scale."""
    import numpy as np
    rois = np.asarray(_data(fpn_rois))
    scale = np.sqrt(np.maximum(
        (rois[:, 2] - rois[:, 0]) * (rois[:, 3] - rois[:, 1]), 0))
    level = np.floor(np.log2(scale / refer_scale + 1e-8)) + refer_level
    level = np.clip(level, min_level, max_level).astype(np.int64)
    outs, restore = [], np.empty(len(rois), np.int64)
    pos = 0
    nums = []
    for lv in range(min_level, max_level + 1):
        idx = np.nonzero(level == lv)[0]
        outs.append(Tensor(jnp.asarray(rois[idx])))
        # restore_index[orig_idx] = position in the concatenated output, as in
        # the reference kernel (distribute_fpn_proposals_kernel.cc:110-117)
        restore[idx] = np.arange(pos, pos + len(idx))
        pos += len(idx)
        nums.append(Tensor(jnp.asarray([len(idx)], jnp.int32)))
    return outs, Tensor(jnp.asarray(restore, jnp.int32)), nums


# ---------------------------------------------------------------------------
# Surface completion (reference: python/paddle/vision/ops.py __all__).

class RoIAlign(Layer):
    """reference: vision/ops.py RoIAlign layer over roi_align."""

    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num, aligned=True):
        return roi_align(x, boxes, boxes_num, self.output_size,
                         self.spatial_scale, aligned=aligned)


class RoIPool(Layer):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num):
        return roi_pool(x, boxes, boxes_num, self.output_size,
                        self.spatial_scale)


def psroi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0, name=None):
    """reference: psroi_pool op (position-sensitive RoI pooling, R-FCN):
    input channels C = out_c * oh * ow; bin (i, j) pools its OWN channel
    group — avg pooled."""
    oh, ow = ((output_size, output_size) if isinstance(output_size, int)
              else output_size)
    import jax.numpy as jnp

    def fn(xa, ba, bn):
        n, c, H, W = xa.shape
        out_c = c // (oh * ow)
        n_rois = ba.shape[0]
        img_of_roi = jnp.repeat(jnp.arange(bn.shape[0]), bn,
                                total_repeat_length=n_rois)
        outs = []
        ys = jnp.arange(oh)
        xs = jnp.arange(ow)

        def one_roi(r):
            img = xa[img_of_roi[r]]
            x1, y1, x2, y2 = [ba[r, k] * spatial_scale for k in range(4)]
            rh = jnp.maximum(y2 - y1, 1.0) / oh
            rw = jnp.maximum(x2 - x1, 1.0) / ow
            def one_bin(i, j):
                grp = img.reshape(out_c, oh * ow, H, W)[:, i * ow + j]
                ys0 = jnp.clip(jnp.floor(y1 + i * rh).astype(jnp.int32), 0, H - 1)
                ys1 = jnp.clip(jnp.ceil(y1 + (i + 1) * rh).astype(jnp.int32), 1, H)
                xs0 = jnp.clip(jnp.floor(x1 + j * rw).astype(jnp.int32), 0, W - 1)
                xs1 = jnp.clip(jnp.ceil(x1 + (j + 1) * rw).astype(jnp.int32), 1, W)
                # dynamic region avg via masked mean (static shapes for XLA)
                yy = jnp.arange(H)[:, None]
                xx = jnp.arange(W)[None, :]
                m = ((yy >= ys0) & (yy < ys1) & (xx >= xs0) & (xx < xs1))
                s = (grp * m[None]).sum(axis=(1, 2))
                cnt = jnp.maximum(m.sum(), 1)
                return s / cnt
            bins = jnp.stack([jnp.stack([one_bin(i, j) for j in range(ow)], -1)
                              for i in range(oh)], -2)   # [out_c, oh, ow]
            return bins
        return jax.vmap(one_roi)(jnp.arange(n_rois))
    return apply_op("psroi_pool", fn, [x, boxes, boxes_num])


class PSRoIPool(Layer):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num):
        return psroi_pool(x, boxes, boxes_num, self.output_size,
                          self.spatial_scale)


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None,
                  name=None):
    """reference: deform_conv2d (DCNv1; DCNv2 with mask) — bilinear sampling
    at offset-shifted taps, then a dense 1x1-style contraction. TPU mapping:
    the gather+interp is jnp vectorized; the contraction is one einsum on
    the MXU."""
    import jax.numpy as jnp
    s = (stride, stride) if isinstance(stride, int) else tuple(stride)
    p = (padding, padding) if isinstance(padding, int) else tuple(padding)
    d = (dilation, dilation) if isinstance(dilation, int) else tuple(dilation)
    args = [x, offset, weight] + ([bias] if bias is not None else []) + \
        ([mask] if mask is not None else [])
    has_bias = bias is not None
    has_mask = mask is not None

    def fn(xa, off, w, *rest):
        b = 0
        bias_a = rest[0] if has_bias else None
        mask_a = rest[-1] if has_mask else None
        n, cin, H, W = xa.shape
        cout, cin_g, kh, kw = w.shape
        oh = (H + 2 * p[0] - d[0] * (kh - 1) - 1) // s[0] + 1
        ow = (W + 2 * p[1] - d[1] * (kw - 1) - 1) // s[1] + 1
        xp = jnp.pad(xa, [(0, 0), (0, 0), (p[0], p[0]), (p[1], p[1])])
        Hp, Wp = xp.shape[2], xp.shape[3]
        # base sampling grid per output position and tap
        ys = jnp.arange(oh) * s[0]
        xs = jnp.arange(ow) * s[1]
        ky = jnp.arange(kh) * d[0]
        kx = jnp.arange(kw) * d[1]
        base_y = ys[:, None, None, None] + ky[None, None, :, None]  # oh,1,kh,1
        base_x = xs[None, :, None, None] + kx[None, None, None, :]  # 1,ow,1,kw
        off = off.reshape(n, deformable_groups, kh * kw, 2, oh, ow)
        dy = off[:, :, :, 0].reshape(n, deformable_groups, kh, kw, oh, ow)
        dx = off[:, :, :, 1].reshape(n, deformable_groups, kh, kw, oh, ow)
        sy = base_y.transpose(2, 3, 0, 1)[None, None] + dy.transpose(0, 1, 2, 3, 4, 5)
        # shapes: [n, dg, kh, kw, oh, ow]
        sx = base_x.transpose(2, 3, 0, 1)[None, None] + dx

        y0 = jnp.floor(sy)
        x0 = jnp.floor(sx)
        wy = sy - y0
        wx = sx - x0

        def gather(img_c, yy, xx):
            yc = jnp.clip(yy.astype(jnp.int32), 0, Hp - 1)
            xc = jnp.clip(xx.astype(jnp.int32), 0, Wp - 1)
            valid = ((yy >= 0) & (yy <= Hp - 1) & (xx >= 0) & (xx <= Wp - 1))
            return img_c[yc, xc] * valid
        cg = cin // deformable_groups

        def per_image(img, syi, sxi, y0i, x0i, wyi, wxi, mi):
            # img [cin, Hp, Wp]; channels within a deformable group share
            # grids, so gather whole groups at once (one vectorized gather
            # per corner per group, not cin unrolled subgraphs)
            img_g = img.reshape(deformable_groups, cg, Hp, Wp)

            def per_group(img_c, y0g, x0g, wyg, wxg, mg):
                def g4(yy, xx):
                    yc = jnp.clip(yy.astype(jnp.int32), 0, Hp - 1)
                    xc = jnp.clip(xx.astype(jnp.int32), 0, Wp - 1)
                    valid = ((yy >= 0) & (yy <= Hp - 1) &
                             (xx >= 0) & (xx <= Wp - 1))
                    return img_c[:, yc, xc] * valid[None]
                val = (g4(y0g, x0g) * (1 - wyg) * (1 - wxg) +
                       g4(y0g, x0g + 1) * (1 - wyg) * wxg +
                       g4(y0g + 1, x0g) * wyg * (1 - wxg) +
                       g4(y0g + 1, x0g + 1) * wyg * wxg)
                return val * mg[None]          # [cg, kh, kw, oh, ow]
            vals = jax.vmap(per_group)(img_g, y0i, x0i, wyi, wxi, mi)
            return vals.reshape(cin, *vals.shape[2:])
        m6 = None
        if mask_a is not None:
            m6 = mask_a.reshape(n, deformable_groups, kh, kw, oh, ow)
        cols = jax.vmap(per_image)(
            xp, sy, sx, y0, x0, wy, wx,
            m6 if m6 is not None else jnp.ones((n, deformable_groups, kh, kw,
                                                oh, ow), xa.dtype))
        # cols [n, cin, kh, kw, oh, ow] x w [cout, cin/g, kh, kw]
        if groups == 1:
            out = jnp.einsum("nijkab,oijk->noab", cols, w)
        else:
            xs_ = jnp.split(cols, groups, axis=1)
            ws_ = jnp.split(w, groups, axis=0)
            out = jnp.concatenate(
                [jnp.einsum("nijkab,oijk->noab", xi, wi)
                 for xi, wi in zip(xs_, ws_)], axis=1)
        if bias_a is not None:
            out = out + bias_a.reshape(1, -1, 1, 1)
        return out
    return apply_op("deform_conv2d", fn, args)


class DeformConv2D(Layer):
    """reference: vision/ops.py DeformConv2D — owns weight/bias; offsets
    (and DCNv2 mask) come in at forward."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, deformable_groups=1, groups=1,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        from ..nn import initializer as I
        k = ((kernel_size, kernel_size) if isinstance(kernel_size, int)
             else tuple(kernel_size))
        self._cfg = dict(stride=stride, padding=padding, dilation=dilation,
                         deformable_groups=deformable_groups, groups=groups)
        import math as _m
        std = 1.0 / _m.sqrt(in_channels * k[0] * k[1])
        self.weight = self.create_parameter(
            [out_channels, in_channels // groups, k[0], k[1]],
            default_initializer=I.Uniform(-std, std))
        self.bias = None
        if bias_attr is not False:
            self.bias = self.create_parameter(
                [out_channels], is_bias=True,
                default_initializer=I.Constant(0.0))

    def forward(self, x, offset, mask=None):
        return deform_conv2d(x, offset, self.weight, self.bias,
                             mask=mask, **self._cfg)


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5,
              min_max_aspect_ratios_order=False, name=None):
    """reference: vision/ops.py:424 prior_box (SSD anchor generator).

    Returns (box, var), each [H, W, num_priors, 4]; boxes are normalized
    (xmin, ymin, xmax, ymax). Per cell: one box per expanded aspect ratio
    per min_size (ar 1 first; `flip` adds 1/ar), plus one sqrt(min*max)
    box per max_size — appended after the ar boxes by default, or right
    after the first min box when min_max_aspect_ratios_order=True (the
    Caffe-SSD layout). Pure shape math: computed with numpy at trace time
    (anchors are constants; XLA folds them), like the reference's CPU
    kernel feeding a const."""
    import numpy as np
    xa, ia = _data(input), _data(image)
    H, W = int(xa.shape[2]), int(xa.shape[3])
    img_h, img_w = int(ia.shape[2]), int(ia.shape[3])
    step_w = float(steps[0]) or img_w / W
    step_h = float(steps[1]) or img_h / H
    min_sizes = [float(m) for m in np.atleast_1d(min_sizes)]
    max_sizes = [float(m) for m in np.atleast_1d(max_sizes)] \
        if max_sizes is not None else []
    if max_sizes:
        assert len(max_sizes) == len(min_sizes)

    ars = [1.0]
    for ar in aspect_ratios:
        if any(abs(ar - e) < 1e-6 for e in ars):
            continue
        ars.append(float(ar))
        if flip:
            ars.append(1.0 / float(ar))

    whs = []           # per-cell prior (w, h) list, in the reference order
    for i, ms in enumerate(min_sizes):
        per = [(ms * (ar ** 0.5), ms / (ar ** 0.5)) for ar in ars]
        if max_sizes:
            sq = (ms * max_sizes[i]) ** 0.5
            if min_max_aspect_ratios_order:
                per.insert(1, (sq, sq))
            else:
                per.append((sq, sq))
        whs.extend(per)
    whs = np.asarray(whs, np.float32)                       # [P, 2]

    cx = (np.arange(W, dtype=np.float32) + offset) * step_w  # [W]
    cy = (np.arange(H, dtype=np.float32) + offset) * step_h  # [H]
    cxg, cyg = np.meshgrid(cx, cy)                           # [H, W]
    half_w = whs[:, 0] / 2.0
    half_h = whs[:, 1] / 2.0
    box = np.stack([
        (cxg[..., None] - half_w) / img_w,
        (cyg[..., None] - half_h) / img_h,
        (cxg[..., None] + half_w) / img_w,
        (cyg[..., None] + half_h) / img_h,
    ], axis=-1).astype(np.float32)                          # [H, W, P, 4]
    if clip:
        box = np.clip(box, 0.0, 1.0)
    var = np.broadcast_to(np.asarray(variance, np.float32),
                          box.shape).copy()
    return Tensor(jnp.asarray(box)), Tensor(jnp.asarray(var))


def _nms_keep_mask(boxes_sorted, iou_threshold):
    """Trace-safe masked NMS over score-DESC-sorted boxes -> bool keep
    mask (static shapes; the sequential suppression runs as a fori_loop,
    the TPU analog of the reference's dynamic CPU loop)."""
    n = boxes_sorted.shape[0]
    x1, y1, x2, y2 = (boxes_sorted[:, i] for i in range(4))
    area = jnp.maximum(x2 - x1, 0) * jnp.maximum(y2 - y1, 0)
    iw = jnp.maximum(jnp.minimum(x2[:, None], x2[None]) -
                     jnp.maximum(x1[:, None], x1[None]), 0)
    ih = jnp.maximum(jnp.minimum(y2[:, None], y2[None]) -
                     jnp.maximum(y1[:, None], y1[None]), 0)
    inter = iw * ih
    iou = inter / jnp.maximum(area[:, None] + area[None] - inter, 1e-9)
    idx = jnp.arange(n)

    def body(i, keep):
        sup = (iou[i] > iou_threshold) & keep[i] & (idx > i)
        return keep & ~sup

    return jax.lax.fori_loop(0, n, body, jnp.ones((n,), bool))


def generate_proposals(scores, bbox_deltas, img_size, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0,
                       pixel_offset=False, return_rois_num=False, name=None):
    """reference: generate_proposals_v2 (RPN proposal stage,
    operators/detection/generate_proposals_v2_op.cc; python surface
    vision/ops.py generate_proposals).

    scores [N,A,H,W], bbox_deltas [N,4A,H,W], img_size [N,2] (h, w),
    anchors/variances [H,W,A,4]. TPU redesign: everything static-shape —
    per-image top-k -> delta decode -> clip -> min-size mask -> masked-NMS
    fori_loop -> top-k; rejected slots carry score 0 and rois_num reports
    the true count (the reference's dynamic LoD output, expressed as
    padded tensors + counts like every other TPU-side ragged result here).

    Returns (rois [N*post, 4], roi_probs [N*post, 1], rois_num [N]).
    """
    args = [scores, bbox_deltas, img_size, anchors, variances]

    def fn(sc, bd, ims, an, va):
        N, A, H, W = sc.shape
        an4 = an.reshape(-1, 4)
        va4 = va.reshape(-1, 4) if va is not None else jnp.ones_like(an4)
        K = an4.shape[0]                      # = H*W*A
        pre_n = min(pre_nms_top_n, K)
        post_n = min(post_nms_top_n, pre_n)
        # bound the O(n^2) masked suppression: candidates beyond a few
        # multiples of post_n essentially never survive NMS (the reference
        # CPU loop likewise stops after post_n keeps); this caps the IoU
        # matrix at (4*post_n)^2 instead of pre_n^2
        nms_n = min(pre_n, max(4 * post_n, 256))
        off = 1.0 if pixel_offset else 0.0

        def per_image(s_i, d_i, hw):
            # [A,H,W] -> [H,W,A] flat, matching anchors' [H,W,A] layout
            s_flat = jnp.transpose(s_i, (1, 2, 0)).reshape(-1)
            d_flat = jnp.transpose(d_i.reshape(A, 4, H, W),
                                   (2, 3, 0, 1)).reshape(-1, 4)
            top_s, top_i = jax.lax.top_k(s_flat, pre_n)
            anc = an4[top_i]
            var = va4[top_i]
            dlt = d_flat[top_i]
            aw = anc[:, 2] - anc[:, 0] + off
            ah = anc[:, 3] - anc[:, 1] + off
            acx = anc[:, 0] + 0.5 * aw
            acy = anc[:, 1] + 0.5 * ah
            bound = jnp.log(1000.0 / 16.0)
            pcx = dlt[:, 0] * var[:, 0] * aw + acx
            pcy = dlt[:, 1] * var[:, 1] * ah + acy
            pw = jnp.exp(jnp.minimum(dlt[:, 2] * var[:, 2], bound)) * aw
            ph = jnp.exp(jnp.minimum(dlt[:, 3] * var[:, 3], bound)) * ah
            x1 = pcx - 0.5 * pw
            y1 = pcy - 0.5 * ph
            x2 = pcx + 0.5 * pw - off
            y2 = pcy + 0.5 * ph - off
            imh, imw = hw[0], hw[1]
            x1 = jnp.clip(x1, 0, imw - off)
            x2 = jnp.clip(x2, 0, imw - off)
            y1 = jnp.clip(y1, 0, imh - off)
            y2 = jnp.clip(y2, 0, imh - off)
            boxes = jnp.stack([x1, y1, x2, y2], axis=1)
            wide = ((x2 - x1 + off) >= min_size) & \
                   ((y2 - y1 + off) >= min_size)
            s_kept = jnp.where(wide, top_s, -jnp.inf)
            # (top_k already sorted desc; re-sort after the min-size mask)
            order = jnp.argsort(-s_kept)[:nms_n]
            boxes = boxes[order]
            s_kept = s_kept[order]
            keep = _nms_keep_mask(boxes, nms_thresh) & \
                jnp.isfinite(s_kept)
            final_s = jnp.where(keep, s_kept, -jnp.inf)
            sel_s, sel_i = jax.lax.top_k(final_s, post_n)
            rois = boxes[sel_i] * (sel_s > -jnp.inf)[:, None]
            probs = jnp.where(sel_s > -jnp.inf, sel_s, 0.0)
            count = jnp.sum(sel_s > -jnp.inf).astype(jnp.int32)
            return rois, probs[:, None], count

        rois, probs, counts = jax.vmap(per_image)(sc, bd, ims)
        return (rois.reshape(-1, 4), probs.reshape(-1, 1),
                counts.reshape(-1))

    rois, probs, num = apply_op("generate_proposals", fn, args, n_outputs=3)
    if return_rois_num:
        return rois, probs, num
    return rois, probs


def read_file(filename, name=None):
    """reference: vision/ops.py read_file — file bytes as a uint8 tensor."""
    import jax.numpy as jnp
    import numpy as np
    from ..core.tensor import Tensor
    with open(filename, "rb") as f:
        data = np.frombuffer(f.read(), np.uint8)
    return Tensor(jnp.asarray(data))


def decode_jpeg(x, mode="unchanged", name=None):
    """reference: decode_jpeg (nvjpeg) — here via PIL on host (the data
    pipeline runs host-side; the decoded tensor feeds the device)."""
    import io as _io
    import numpy as np
    import jax.numpy as jnp
    from PIL import Image
    from ..core.tensor import Tensor
    raw = bytes(np.asarray(_data(x), np.uint8).tobytes())
    img = Image.open(_io.BytesIO(raw))
    if mode.lower() == "gray":
        img = img.convert("L")
    elif mode.lower() in ("rgb",):
        img = img.convert("RGB")
    arr = np.asarray(img)
    if arr.ndim == 2:
        arr = arr[None]
    else:
        arr = arr.transpose(2, 0, 1)
    return Tensor(jnp.asarray(arr))


def matrix_nms(bboxes, scores, score_threshold, post_threshold, nms_top_k,
               keep_top_k, use_gaussian=False, gaussian_sigma=2.0,
               background_label=0, normalized=True, return_index=False,
               return_rois_num=True, name=None):
    """reference: matrix_nms op (SOLOv2) — soft suppression via the decay
    matrix min over higher-scored same-class boxes."""
    import numpy as np
    import jax.numpy as jnp
    from ..core.tensor import Tensor
    bb = np.asarray(_data(bboxes), np.float32)   # [N, M, 4]
    sc = np.asarray(_data(scores), np.float32)   # [N, C, M]
    outs, idxs, nums = [], [], []
    for n in range(bb.shape[0]):
        dets = []
        det_idx = []
        for c in range(sc.shape[1]):
            if c == background_label:
                continue
            s = sc[n, c]
            keep = np.where(s > score_threshold)[0]
            if keep.size == 0:
                continue
            order = keep[np.argsort(-s[keep])][:nms_top_k]
            boxes_c = bb[n, order]
            s_c = s[order]
            m = len(order)
            # IoU matrix
            x1 = np.maximum(boxes_c[:, None, 0], boxes_c[None, :, 0])
            y1 = np.maximum(boxes_c[:, None, 1], boxes_c[None, :, 1])
            x2 = np.minimum(boxes_c[:, None, 2], boxes_c[None, :, 2])
            y2 = np.minimum(boxes_c[:, None, 3], boxes_c[None, :, 3])
            inter = np.clip(x2 - x1, 0, None) * np.clip(y2 - y1, 0, None)
            area = ((boxes_c[:, 2] - boxes_c[:, 0]) *
                    (boxes_c[:, 3] - boxes_c[:, 1]))
            iou = inter / np.maximum(area[:, None] + area[None] - inter, 1e-9)
            iou = np.triu(iou, 1)
            comp = iou.max(axis=0)  # comp[i]: suppressor i's own max IoU
            if use_gaussian:
                decay = np.exp(-(iou ** 2 - comp[:, None] ** 2)
                               / gaussian_sigma).min(axis=0)
            else:
                decay = ((1 - iou) /
                         np.maximum(1 - comp[:, None], 1e-9)).min(axis=0)
            s_new = s_c * decay
            ok = s_new > post_threshold
            for t in np.where(ok)[0]:
                dets.append([c, s_new[t], *boxes_c[t]])
                det_idx.append(order[t])
        dets = np.asarray(dets, np.float32).reshape(-1, 6)
        det_idx = np.asarray(det_idx, np.int64)
        if len(dets) > keep_top_k >= 0:
            top = np.argsort(-dets[:, 1])[:keep_top_k]
            dets, det_idx = dets[top], det_idx[top]
        outs.append(dets)
        idxs.append(det_idx)
        nums.append(len(dets))
    out = Tensor(jnp.asarray(np.concatenate(outs, 0) if outs
                             else np.zeros((0, 6), np.float32)))
    rois_num = Tensor(jnp.asarray(np.asarray(nums, np.int32)))
    index = Tensor(jnp.asarray(np.concatenate(idxs, 0) if idxs
                               else np.zeros((0,), np.int64)))
    res = [out]
    if return_index:
        res.append(index)
    if return_rois_num:
        res.append(rois_num)
    return tuple(res) if len(res) > 1 else out


def yolo_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
              ignore_thresh, downsample_ratio, gt_score=None,
              use_label_smooth=True, scale_x_y=1.0, name=None):
    """reference: yolov3_loss op — thin delegate to the model-zoo YOLO loss
    (vision/models/yolo.py implements the anchor-free capability class;
    grid-anchor YOLOv3 loss composes box-IoU + BCE terms here)."""
    raise NotImplementedError(
        "grid-anchor yolov3 loss: use paddle_tpu.vision.models.yolo_loss "
        "(the zoo's detector criterion) — kept separate because this build's "
        "detector family is anchor-free (vision/models/yolo.py docstring)")
