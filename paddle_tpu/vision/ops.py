"""Vision ops: boxes, NMS, RoI ops, DeformConv stub (reference:
python/paddle/vision/ops.py).

TPU-first: NMS is implemented as a fixed-iteration lax.while-free masked
suppression (compile-friendly static shapes), not a dynamic loop.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import Tensor

__all__ = ["yolo_box", "box_coder", "nms", "roi_align", "roi_pool",
           "distribute_fpn_proposals", "box_iou"]


def _data(x):
    return x._data if isinstance(x, Tensor) else jnp.asarray(x)


def box_iou(boxes1, boxes2):
    """IoU matrix [N, M] for xyxy boxes."""
    b1, b2 = _data(boxes1), _data(boxes2)
    area1 = (b1[:, 2] - b1[:, 0]) * (b1[:, 3] - b1[:, 1])
    area2 = (b2[:, 2] - b2[:, 0]) * (b2[:, 3] - b2[:, 1])
    lt = jnp.maximum(b1[:, None, :2], b2[None, :, :2])
    rb = jnp.minimum(b1[:, None, 2:], b2[None, :, 2:])
    wh = jnp.clip(rb - lt, 0)
    inter = wh[..., 0] * wh[..., 1]
    return Tensor(inter / (area1[:, None] + area2[None, :] - inter + 1e-10))


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None):
    """reference ops.py:1461 paddle.vision.ops.nms.

    Masked O(N^2) suppression with static shapes: returns kept indices
    sorted by score (host-materialised, like the reference's dynamic out).
    """
    b = _data(boxes)
    n = b.shape[0]
    s = _data(scores) if scores is not None else jnp.arange(n, 0, -1, jnp.float32)
    if category_idxs is not None:
        # offset boxes per category so cross-category IoU is 0 (batched NMS trick)
        c = _data(category_idxs).astype(b.dtype)
        offset = (b.max() + 1.0) * c
        b = b + offset[:, None]
    order = jnp.argsort(-s)
    b_sorted = b[order]
    iou = box_iou(Tensor(b_sorted), Tensor(b_sorted))._data
    # keep[i] = no earlier kept box overlaps i above threshold
    import numpy as np
    iou_np = np.asarray(iou)
    keep_mask = np.ones(n, bool)
    for i in range(n):
        if not keep_mask[i]:
            continue
        keep_mask[i + 1:] &= iou_np[i, i + 1:] <= iou_threshold
    kept = np.asarray(order)[keep_mask]
    if top_k is not None:
        kept = kept[:top_k]
    return Tensor(jnp.asarray(kept, jnp.int32))


def _roi_grid(bd, boxes_num, n_rois, oh, ow, spatial_scale, aligned, samples):
    """Per-roi sample coordinates: ys [R, oh*samples], xs [R, ow*samples]."""
    bn = _data(boxes_num).astype(jnp.int32)
    batch_idx = jnp.repeat(jnp.arange(bn.shape[0]), bn, total_repeat_length=n_rois)
    off = 0.5 if aligned else 0.0
    x1 = bd[:, 0] * spatial_scale - off
    y1 = bd[:, 1] * spatial_scale - off
    x2 = bd[:, 2] * spatial_scale - off
    y2 = bd[:, 3] * spatial_scale - off
    rw = jnp.maximum(x2 - x1, 1e-3 if aligned else 1.0)
    rh = jnp.maximum(y2 - y1, 1e-3 if aligned else 1.0)
    # `samples` sub-points per bin along each axis, at (j+0.5)/samples of the bin
    sub = (jnp.arange(samples) + 0.5) / samples
    grid_y = (jnp.arange(oh)[:, None] + sub[None, :]).reshape(-1)  # [oh*samples]
    grid_x = (jnp.arange(ow)[:, None] + sub[None, :]).reshape(-1)
    ys = y1[:, None] + grid_y[None, :] * (rh[:, None] / oh)
    xs = x1[:, None] + grid_x[None, :] * (rw[:, None] / ow)
    return batch_idx, ys, xs


def _bilinear_sample(img, yy, xx, H, W):
    """img [C,H,W]; yy [Ny], xx [Nx] -> [C,Ny,Nx]."""
    y0 = jnp.clip(jnp.floor(yy), 0, H - 1).astype(jnp.int32)
    x0 = jnp.clip(jnp.floor(xx), 0, W - 1).astype(jnp.int32)
    y1i = jnp.clip(y0 + 1, 0, H - 1)
    x1i = jnp.clip(x0 + 1, 0, W - 1)
    wy = jnp.clip(yy - y0, 0, 1)[None, :, None]
    wx = jnp.clip(xx - x0, 0, 1)[None, None, :]
    v00 = img[:, y0][:, :, x0]
    v01 = img[:, y0][:, :, x1i]
    v10 = img[:, y1i][:, :, x0]
    v11 = img[:, y1i][:, :, x1i]
    return (v00 * (1 - wy) * (1 - wx) + v01 * (1 - wy) * wx +
            v10 * wy * (1 - wx) + v11 * wy * wx)


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True):
    """reference ops.py:1080 — average of sampling_ratio^2 bilinear samples
    per bin (2x2 when sampling_ratio is adaptive/-1, like the reference's
    default for typical bin sizes)."""
    import jax
    xd = _data(x)
    bd = _data(boxes)
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size
    n_rois = bd.shape[0]
    C, H, W = xd.shape[1:]
    samples = sampling_ratio if sampling_ratio > 0 else 2
    batch_idx, ys, xs = _roi_grid(bd, boxes_num, n_rois, oh, ow, spatial_scale,
                                  aligned, samples)
    out = jax.vmap(lambda bi, yy, xx: _bilinear_sample(xd[bi], yy, xx, H, W))(
        batch_idx, ys, xs)  # [R, C, oh*s, ow*s]
    out = out.reshape(n_rois, C, oh, samples, ow, samples)
    return Tensor(out.mean(axis=(3, 5)))


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0):
    """Max-pool RoI variant (reference ops.py:989): max over a dense sample
    grid per bin (4x4 sub-samples approximates the reference's integer-pixel
    max with static shapes)."""
    import jax
    xd = _data(x)
    bd = _data(boxes)
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size
    n_rois = bd.shape[0]
    C, H, W = xd.shape[1:]
    samples = 4
    batch_idx, ys, xs = _roi_grid(bd, boxes_num, n_rois, oh, ow, spatial_scale,
                                  aligned=False, samples=samples)
    # nearest-pixel max, as the reference pools over integer pixel coords
    ys = jnp.clip(jnp.round(ys), 0, H - 1).astype(jnp.int32)
    xs = jnp.clip(jnp.round(xs), 0, W - 1).astype(jnp.int32)
    out = jax.vmap(lambda bi, yy, xx: xd[bi][:, yy][:, :, xx])(
        batch_idx, ys, xs)  # [R, C, oh*s, ow*s]
    out = out.reshape(n_rois, C, oh, samples, ow, samples)
    return Tensor(out.max(axis=(3, 5)))


def box_coder(prior_box, prior_box_var, target_box, code_type="encode_center_size",
              box_normalized=True):
    """reference detection box_coder (encode/decode center-size)."""
    pb, tb = _data(prior_box), _data(target_box)
    pbv = _data(prior_box_var) if prior_box_var is not None else None
    norm = 0.0 if box_normalized else 1.0
    pw = pb[:, 2] - pb[:, 0] + norm
    ph = pb[:, 3] - pb[:, 1] + norm
    pcx = pb[:, 0] + pw / 2
    pcy = pb[:, 1] + ph / 2
    if code_type == "encode_center_size":
        tw = tb[:, 2] - tb[:, 0] + norm
        th = tb[:, 3] - tb[:, 1] + norm
        tcx = tb[:, 0] + tw / 2
        tcy = tb[:, 1] + th / 2
        out = jnp.stack([(tcx - pcx) / pw, (tcy - pcy) / ph,
                         jnp.log(tw / pw), jnp.log(th / ph)], axis=1)
        if pbv is not None:
            out = out / pbv
    else:  # decode
        d = tb
        if pbv is not None:
            d = d * pbv
        cx = d[..., 0] * pw + pcx
        cy = d[..., 1] * ph + pcy
        w = jnp.exp(d[..., 2]) * pw
        h = jnp.exp(d[..., 3]) * ph
        out = jnp.stack([cx - w / 2, cy - h / 2, cx + w / 2 - norm,
                         cy + h / 2 - norm], axis=-1)
    return Tensor(out)


def yolo_box(x, img_size, anchors, class_num, conf_thresh, downsample_ratio,
             clip_bbox=True, scale_x_y=1.0):
    """reference ops.py:373 — decode YOLO head to boxes+scores."""
    xd = _data(x)
    n, _, h, w = xd.shape
    na = len(anchors) // 2
    anc = jnp.asarray(anchors, jnp.float32).reshape(na, 2)
    xd = xd.reshape(n, na, 5 + class_num, h, w)
    gx = jnp.arange(w, dtype=jnp.float32)[None, None, None, :]
    gy = jnp.arange(h, dtype=jnp.float32)[None, None, :, None]
    sig = jax_sigmoid = lambda v: 1 / (1 + jnp.exp(-v))
    bx = (sig(xd[:, :, 0]) * scale_x_y - 0.5 * (scale_x_y - 1) + gx) / w
    by = (sig(xd[:, :, 1]) * scale_x_y - 0.5 * (scale_x_y - 1) + gy) / h
    bw = jnp.exp(xd[:, :, 2]) * anc[None, :, 0, None, None] / (w * downsample_ratio)
    bh = jnp.exp(xd[:, :, 3]) * anc[None, :, 1, None, None] / (h * downsample_ratio)
    conf = sig(xd[:, :, 4])
    probs = sig(xd[:, :, 5:]) * conf[:, :, None]
    img_h = _data(img_size)[:, 0].astype(jnp.float32)[:, None, None, None]
    img_w = _data(img_size)[:, 1].astype(jnp.float32)[:, None, None, None]
    x1 = (bx - bw / 2) * img_w
    y1 = (by - bh / 2) * img_h
    x2 = (bx + bw / 2) * img_w
    y2 = (by + bh / 2) * img_h
    if clip_bbox:
        x1 = jnp.clip(x1, 0)
        y1 = jnp.clip(y1, 0)
        x2 = jnp.minimum(x2, img_w - 1)
        y2 = jnp.minimum(y2, img_h - 1)
    boxes = jnp.stack([x1, y1, x2, y2], axis=-1).reshape(n, -1, 4)
    scores = probs.transpose(0, 1, 3, 4, 2).reshape(n, -1, class_num)
    mask = (conf > conf_thresh).reshape(n, -1)
    boxes = boxes * mask[..., None]
    scores = scores * mask[..., None]
    return Tensor(boxes), Tensor(scores)


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, rois_num=None):
    """reference ops.py:701 — assign RoIs to FPN levels by scale."""
    import numpy as np
    rois = np.asarray(_data(fpn_rois))
    scale = np.sqrt(np.maximum(
        (rois[:, 2] - rois[:, 0]) * (rois[:, 3] - rois[:, 1]), 0))
    level = np.floor(np.log2(scale / refer_scale + 1e-8)) + refer_level
    level = np.clip(level, min_level, max_level).astype(np.int64)
    outs, restore = [], np.empty(len(rois), np.int64)
    pos = 0
    nums = []
    for lv in range(min_level, max_level + 1):
        idx = np.nonzero(level == lv)[0]
        outs.append(Tensor(jnp.asarray(rois[idx])))
        # restore_index[orig_idx] = position in the concatenated output, as in
        # the reference kernel (distribute_fpn_proposals_kernel.cc:110-117)
        restore[idx] = np.arange(pos, pos + len(idx))
        pos += len(idx)
        nums.append(Tensor(jnp.asarray([len(idx)], jnp.int32)))
    return outs, Tensor(jnp.asarray(restore, jnp.int32)), nums
