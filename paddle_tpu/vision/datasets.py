"""Vision datasets (reference: python/paddle/vision/datasets/{mnist,cifar,folder}.py).

Zero-egress environment: datasets read from local files (standard archive
formats); `FakeData` provides synthetic samples for tests/smoke runs.
"""
from __future__ import annotations

import gzip
import os
import pickle
import struct
import tarfile

import numpy as np

from ..io import Dataset

__all__ = ["MNIST", "FashionMNIST", "Cifar10", "Cifar100", "Flowers",
           "VOC2012", "DatasetFolder",
           "ImageFolder", "FakeData"]


class MNIST(Dataset):
    """IDX-format reader (reference mnist.py:24 — download replaced by
    local-path loading; this env has no egress)."""

    NAME = "mnist"

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, backend="cv2", root=None):
        self.mode = mode.lower()
        self.transform = transform
        root = root or os.path.join(os.path.expanduser("~"), ".cache",
                                    "paddle_tpu", "datasets", self.NAME)
        tag = "train" if self.mode == "train" else "t10k"
        image_path = image_path or os.path.join(root, f"{tag}-images-idx3-ubyte.gz")
        label_path = label_path or os.path.join(root, f"{tag}-labels-idx1-ubyte.gz")
        for p in (image_path, label_path):
            if not os.path.exists(p):
                raise FileNotFoundError(
                    f"{p} not found; place the {self.NAME} IDX files there "
                    "(no network downloads in this environment)")
        self.images = self._read_idx(image_path, 2051)
        self.labels = self._read_idx(label_path, 2049)

    @staticmethod
    def _read_idx(path, want_magic):
        opener = gzip.open if path.endswith(".gz") else open
        with opener(path, "rb") as f:
            data = f.read()
        magic, = struct.unpack(">i", data[:4])
        assert magic == want_magic, f"bad IDX magic {magic} in {path}"
        ndim = magic % 256
        dims = struct.unpack(f">{ndim}i", data[4:4 + 4 * ndim])
        arr = np.frombuffer(data, np.uint8, offset=4 + 4 * ndim)
        return arr.reshape(dims)

    def __getitem__(self, idx):
        img = self.images[idx][..., None]  # HWC
        if self.transform is not None:
            img = self.transform(img)
        return img, np.asarray(self.labels[idx], np.int64)

    def __len__(self):
        return len(self.images)


class FashionMNIST(MNIST):
    NAME = "fashion-mnist"


class Cifar10(Dataset):
    """python-pickle CIFAR tarball reader (reference cifar.py:30)."""

    _NAME = "cifar-10-python.tar.gz"
    _TRAIN_MEMBER = "data_batch"
    _TEST_MEMBER = "test_batch"
    _LABEL_KEY = b"labels"

    def __init__(self, data_file=None, mode="train", transform=None, root=None):
        self.mode = mode.lower()
        self.transform = transform
        root = root or os.path.join(os.path.expanduser("~"), ".cache",
                                    "paddle_tpu", "datasets")
        data_file = data_file or os.path.join(root, self._NAME)
        if not os.path.exists(data_file):
            raise FileNotFoundError(
                f"{data_file} not found; place the CIFAR archive there "
                "(no network downloads in this environment)")
        want = self._TRAIN_MEMBER if self.mode == "train" else self._TEST_MEMBER
        images, labels = [], []
        with tarfile.open(data_file, "r:*") as tf:
            for member in sorted(tf.getmembers(), key=lambda m: m.name):
                if want in os.path.basename(member.name):
                    batch = pickle.load(tf.extractfile(member), encoding="bytes")
                    images.append(batch[b"data"].reshape(-1, 3, 32, 32))
                    labels.extend(batch[self._LABEL_KEY])
        self.images = np.concatenate(images).transpose(0, 2, 3, 1)  # NHWC
        self.labels = np.asarray(labels, np.int64)

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[idx]

    def __len__(self):
        return len(self.images)


class Cifar100(Cifar10):
    _NAME = "cifar-100-python.tar.gz"
    _TRAIN_MEMBER = "train"
    _TEST_MEMBER = "test"
    _LABEL_KEY = b"fine_labels"


_IMG_EXTS = (".jpg", ".jpeg", ".png", ".ppm", ".bmp", ".npy")


def _load_image(path):
    if path.endswith(".npy"):
        return np.load(path)
    try:
        from PIL import Image
        return np.asarray(Image.open(path).convert("RGB"))
    except ImportError as e:
        raise ImportError("reading encoded images requires PIL; "
                          "use .npy files or install pillow") from e


class DatasetFolder(Dataset):
    """class-per-subdir layout (reference folder.py:42)."""

    def __init__(self, root, loader=None, extensions=_IMG_EXTS, transform=None,
                 is_valid_file=None):
        self.root, self.transform = root, transform
        self.loader = loader or _load_image
        classes = sorted(d for d in os.listdir(root)
                         if os.path.isdir(os.path.join(root, d)))
        if not classes:
            raise RuntimeError(f"no class folders in {root}")
        self.classes = classes
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples = []
        for c in classes:
            cdir = os.path.join(root, c)
            for dirpath, _, files in sorted(os.walk(cdir)):
                for fname in sorted(files):
                    path = os.path.join(dirpath, fname)
                    ok = is_valid_file(path) if is_valid_file else \
                        fname.lower().endswith(extensions)
                    if ok:
                        self.samples.append((path, self.class_to_idx[c]))

    def __getitem__(self, idx):
        path, target = self.samples[idx]
        img = self.loader(path)
        if self.transform is not None:
            img = self.transform(img)
        return img, target

    def __len__(self):
        return len(self.samples)


class ImageFolder(Dataset):
    """flat folder of images, no labels (reference folder.py:215)."""

    def __init__(self, root, loader=None, extensions=_IMG_EXTS, transform=None,
                 is_valid_file=None):
        self.root, self.transform = root, transform
        self.loader = loader or _load_image
        self.samples = []
        for dirpath, _, files in sorted(os.walk(root)):
            for fname in sorted(files):
                path = os.path.join(dirpath, fname)
                ok = is_valid_file(path) if is_valid_file else \
                    fname.lower().endswith(extensions)
                if ok:
                    self.samples.append(path)

    def __getitem__(self, idx):
        img = self.loader(self.samples[idx])
        if self.transform is not None:
            img = self.transform(img)
        return [img]

    def __len__(self):
        return len(self.samples)


class FakeData(Dataset):
    """Synthetic dataset for tests/benchmarks (no reference analog needed:
    stands in for downloads in the zero-egress environment)."""

    def __init__(self, size=100, image_shape=(3, 224, 224), num_classes=10,
                 transform=None, seed=0):
        self.size, self.image_shape = size, tuple(image_shape)
        self.num_classes, self.transform = num_classes, transform
        self.seed = seed

    def __getitem__(self, idx):
        rng = np.random.default_rng(self.seed + idx)
        img = rng.standard_normal(self.image_shape, np.float32)
        label = np.int64(rng.integers(0, self.num_classes))
        if self.transform is not None:
            img = self.transform(img)
        return img, label

    def __len__(self):
        return self.size


class Flowers(Dataset):
    """Flowers-102 (reference: vision/datasets/flowers.py). Zero-egress:
    reads an extracted local archive — `data_file` points at a directory of
    class-numbered images plus labels (setid/labels .npy or .mat), or a
    DatasetFolder-style tree."""

    def __init__(self, data_file=None, label_file=None, setid_file=None,
                 mode="train", transform=None, download=False, backend=None):
        if download:
            raise RuntimeError("no network in this environment; pass "
                               "data_file= pointing at the extracted archive")
        if data_file is None or not os.path.isdir(data_file):
            raise RuntimeError("Flowers needs data_file=<extracted dir>")
        self._inner = DatasetFolder(data_file, transform=transform)
        self.transform = transform
        self.mode = mode
        # deterministic 80/10/10 split by sample index when no setid file
        # is given (the archive's setid.mat is unavailable offline)
        n = len(self._inner)
        bucket = {"train": 0, "valid": 1, "test": 2}.get(mode, 0)
        self._index = [i for i in range(n)
                       if (i % 10 < 8, i % 10 == 8, i % 10 == 9)[bucket]]

    def __getitem__(self, idx):
        return self._inner[self._index[idx]]

    def __len__(self):
        return len(self._index)


class VOC2012(Dataset):
    """Pascal VOC 2012 segmentation (reference: vision/datasets/voc2012.py).
    Reads the standard extracted layout: JPEGImages/, SegmentationClass/,
    ImageSets/Segmentation/{train,val,trainval}.txt."""

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=False, backend=None):
        if download:
            raise RuntimeError("no network in this environment; pass "
                               "data_file= pointing at the extracted VOCdevkit")
        if data_file is None or not os.path.isdir(data_file):
            raise RuntimeError("VOC2012 needs data_file=<extracted dir>")
        root = data_file
        for sub in ("VOCdevkit/VOC2012", "VOC2012", ""):
            cand = os.path.join(root, sub) if sub else root
            if os.path.isdir(os.path.join(cand, "JPEGImages")):
                root = cand
                break
        split = {"train": "train", "valid": "val", "test": "val",
                 "trainval": "trainval"}.get(mode, "train")
        list_file = os.path.join(root, "ImageSets", "Segmentation",
                                 split + ".txt")
        with open(list_file) as f:
            names = [l.strip() for l in f if l.strip()]
        self._imgs = [os.path.join(root, "JPEGImages", n + ".jpg")
                      for n in names]
        self._masks = [os.path.join(root, "SegmentationClass", n + ".png")
                       for n in names]
        self.transform = transform

    def __getitem__(self, idx):
        img = _load_image(self._imgs[idx])
        from PIL import Image
        mask = np.asarray(Image.open(self._masks[idx]))
        if self.transform is not None:
            img = self.transform(img)
        return img, mask

    def __len__(self):
        return len(self._imgs)
