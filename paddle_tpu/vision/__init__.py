"""Vision kit (reference: python/paddle/vision/).

Model zoo + transforms + datasets + box ops, TPU-native: NCHW user-facing
layout (converted once to NHWC-friendly convs inside lax), bf16-ready.
"""
from . import models, transforms, datasets, ops
from .models import *  # noqa: F401,F403
from .models import __all__ as _models_all

__all__ = ["models", "transforms", "datasets", "ops"] + list(_models_all)


_image_backend = "pil"


def set_image_backend(backend: str):
    """reference: vision/image.py — 'pil' | 'cv2' | 'tensor'; only pil/
    numpy paths exist in this environment."""
    global _image_backend
    if backend not in ("pil", "cv2", "tensor"):
        raise ValueError(f"unknown image backend {backend!r}")
    _image_backend = backend


def get_image_backend() -> str:
    return _image_backend


def image_load(path, backend=None):
    """reference: vision/image.py image_load."""
    b = backend or _image_backend
    if b == "cv2":
        raise RuntimeError("cv2 is not available in this environment")
    from PIL import Image
    img = Image.open(path)
    if b == "tensor":
        import numpy as np
        from ..core.tensor import Tensor
        import jax.numpy as jnp
        return Tensor(jnp.asarray(np.asarray(img)))
    return img
