"""Vision kit (reference: python/paddle/vision/).

Model zoo + transforms + datasets + box ops, TPU-native: NCHW user-facing
layout (converted once to NHWC-friendly convs inside lax), bf16-ready.
"""
from . import models, transforms, datasets, ops
from .models import *  # noqa: F401,F403
from .models import __all__ as _models_all

__all__ = ["models", "transforms", "datasets", "ops"] + list(_models_all)
