"""Device management (reference: python/paddle/device/__init__.py).

The reference juggles CUDAPlace/XPUPlace/NPUPlace and streams
(paddle/phi/common/place.h, device/cuda/streams). On TPU there is a single
logical device space managed by XLA; placement happens via shardings, and
stream semantics do not exist (XLA program order). We expose the same API
shape with TPU-truthful behavior.
"""
from __future__ import annotations

import jax

_current = [None]


def get_all_devices():
    return jax.devices()


def device_count() -> int:
    return len(jax.devices())


def set_device(device: str):
    """Accepts 'tpu', 'tpu:N', 'cpu', 'cpu:N'. Returns the jax device."""
    if ":" in device:
        kind, idx = device.split(":")
        idx = int(idx)
    else:
        kind, idx = device, 0
    if kind in ("gpu", "cuda"):
        raise ValueError("paddle_tpu is a TPU framework; no CUDA devices. "
                         "Use 'tpu' or 'cpu'.")
    devs = [d for d in jax.devices() if d.platform in (kind, "axon" if kind == "tpu" else kind)]
    if not devs:
        devs = jax.devices()
    _current[0] = devs[idx % len(devs)]
    return _current[0]


def get_device() -> str:
    d = _current[0] or jax.devices()[0]
    plat = "tpu" if d.platform in ("tpu", "axon") else d.platform
    return f"{plat}:{d.id}"


def current_device():
    return _current[0] or jax.devices()[0]


def synchronize():
    """Block until all dispatched work completes (reference:
    paddle.device.cuda.synchronize). jax.block_until_ready on a trivial op."""
    jax.block_until_ready(jax.numpy.zeros(()))


def is_compiled_with_cuda() -> bool:
    return False


# -------- accelerator capability + memory telemetry ------------------------

# bf16 peak matmul FLOP/s per chip by TPU generation (public spec sheets) —
# the denominator of every MFU figure (bench.py, profiler.StepMonitor)
_PEAK_FLOPS = {"v2": 46e12, "v3": 123e12, "v4": 275e12,
               "v5 lite": 197e12, "v5e": 197e12, "v5litepod": 197e12,
               "v5p": 459e12, "v6e": 918e12, "v6p": 918e12}


def chip_peak_flops(device=None) -> float:
    """Peak bf16 matmul FLOP/s of one chip (assumes v4 when unknown)."""
    d = device if device is not None else (_current[0] or jax.devices()[0])
    kind = getattr(d, "device_kind", "").lower()
    for key, val in _PEAK_FLOPS.items():
        if key in kind:
            return val
    return 275e12


# observed peak live bytes per device id — the fallback tracker for
# runtimes whose allocator exposes no peak (CPU host platform); on TPU the
# allocator's own peak_bytes_in_use wins. _peak_baseline records the
# allocator's CUMULATIVE peak at the last reset so max_memory_allocated
# can report a since-reset figure even though XLA's counter never resets.
_observed_peak = {}
_peak_baseline = {}
_has_alloc_stats = {}


def has_allocator_stats(device=None) -> bool:
    """Whether the runtime exposes real allocator counters for this device
    (cached probe — callers use it to pick a sampling rate for the
    live-array fallback, which scans every live buffer)."""
    d = device if device is not None else (_current[0] or jax.devices()[0])
    cached = _has_alloc_stats.get(d.id)
    if cached is None:
        try:
            cached = d.memory_stats() is not None
        except Exception:
            cached = False
        _has_alloc_stats[d.id] = cached
    return cached


def memory_stats(device=None) -> dict:
    """Allocator statistics for one device (reference:
    paddle.device.cuda.memory_stats; here the XLA allocator).

    TPU: the runtime's own counters (bytes_in_use, peak_bytes_in_use,
    bytes_limit, ...). Host-platform fallback (no allocator stats): live
    bytes are summed over jax.live_arrays() placed on the device — an
    approximation (sharded arrays count full size), with the peak tracked
    across memory_stats() calls."""
    d = device if device is not None else (_current[0] or jax.devices()[0])
    stats = None
    try:
        stats = d.memory_stats()
    except Exception:
        stats = None
    if stats is None:
        live = 0
        try:
            for a in jax.live_arrays():
                try:
                    if d in a.devices():
                        live += a.nbytes
                except Exception:
                    continue
        except Exception:
            pass
        peak = max(_observed_peak.get(d.id, 0), live)
        _observed_peak[d.id] = peak
        stats = {"bytes_in_use": live, "peak_bytes_in_use": peak,
                 "source": "live_arrays"}
    else:
        stats = dict(stats)
        # since-reset peak: XLA's peak_bytes_in_use is process-cumulative;
        # after reset_max_memory_allocated it only counts if a NEW
        # high-water mark was set, else the live figure stands in
        raw_peak = stats.get("peak_bytes_in_use", 0)
        base = _peak_baseline.get(d.id, 0)
        eff = raw_peak if raw_peak > base else stats.get("bytes_in_use", 0)
        peak = max(_observed_peak.get(d.id, 0), eff,
                   stats.get("bytes_in_use", 0))
        _observed_peak[d.id] = peak
        stats["peak_bytes_in_use"] = peak
        stats.setdefault("source", "allocator")
    return stats


def max_memory_allocated(device=None) -> int:
    """Peak device bytes in use (reference:
    paddle.device.cuda.max_memory_allocated)."""
    return int(memory_stats(device).get("peak_bytes_in_use", 0))


def memory_allocated(device=None) -> int:
    """Current device bytes in use."""
    return int(memory_stats(device).get("bytes_in_use", 0))


def reset_max_memory_allocated(device=None):
    """Start a new peak-tracking window (reference:
    paddle.device.cuda.reset_max_memory_allocated): clears the tracked
    peak and, on allocator-backed runtimes, baselines XLA's cumulative
    counter so only a NEW high-water mark counts after this call."""
    d = device if device is not None else (_current[0] or jax.devices()[0])
    _observed_peak.pop(d.id, None)
    try:
        alloc = d.memory_stats()
    except Exception:
        alloc = None
    _peak_baseline[d.id] = (alloc or {}).get("peak_bytes_in_use", 0)
    return memory_stats(d)


class Stream:
    """Compat no-op: XLA has no user-visible streams; ordering is program
    order (replaces reference stream/event machinery,
    paddle/phi/backends/gpu/gpu_context.h:97)."""

    def synchronize(self):
        synchronize()


def cuda_empty_cache():
    pass

from . import cuda  # noqa: E402,F401


# -------- surface completion (reference: python/paddle/device/__init__.py)

class Event:
    """reference: device.Event — cross-stream sync marker. XLA owns
    scheduling (SURVEY §7 StreamSafe row): record/query/synchronize map to
    program-order completion."""

    def __init__(self, device=None, enable_timing=False, blocking=False,
                 interprocess=False):
        self._recorded = False

    def record(self, stream=None):
        self._recorded = True

    def query(self):
        return True

    def synchronize(self):
        synchronize()


def current_stream(device=None):
    return Stream()


def set_stream(stream):
    return stream


def stream_guard(stream):
    import contextlib
    return contextlib.nullcontext()


def XPUPlace(dev_id=0):  # noqa: N802
    from ..fluid import XPUPlace as _x
    return _x(dev_id)


def IPUPlace():  # noqa: N802
    raise RuntimeError("IPU backend is not available in paddle_tpu")


def MLUPlace(dev_id=0):  # noqa: N802
    raise RuntimeError("MLU backend is not available in paddle_tpu")


def get_cudnn_version():
    return None  # no cuDNN in the TPU stack


def is_compiled_with_rocm() -> bool:
    return False


def is_compiled_with_xpu() -> bool:
    return False


def is_compiled_with_npu() -> bool:
    return False


def is_compiled_with_mlu() -> bool:
    return False


def is_compiled_with_ipu() -> bool:
    return False


def is_compiled_with_cinn() -> bool:
    return False


def is_compiled_with_custom_device(device_type: str) -> bool:
    return False


def get_all_device_type():
    import jax
    return sorted({d.platform for d in jax.devices()})


def get_all_custom_device_type():
    return []


def get_available_device():
    import jax
    return [f"{d.platform}:{d.id}" for d in jax.devices()]


def get_available_custom_device():
    return []
