"""Device management (reference: python/paddle/device/__init__.py).

The reference juggles CUDAPlace/XPUPlace/NPUPlace and streams
(paddle/phi/common/place.h, device/cuda/streams). On TPU there is a single
logical device space managed by XLA; placement happens via shardings, and
stream semantics do not exist (XLA program order). We expose the same API
shape with TPU-truthful behavior.
"""
from __future__ import annotations

import jax

_current = [None]


def get_all_devices():
    return jax.devices()


def device_count() -> int:
    return len(jax.devices())


def set_device(device: str):
    """Accepts 'tpu', 'tpu:N', 'cpu', 'cpu:N'. Returns the jax device."""
    if ":" in device:
        kind, idx = device.split(":")
        idx = int(idx)
    else:
        kind, idx = device, 0
    if kind in ("gpu", "cuda"):
        raise ValueError("paddle_tpu is a TPU framework; no CUDA devices. "
                         "Use 'tpu' or 'cpu'.")
    devs = [d for d in jax.devices() if d.platform in (kind, "axon" if kind == "tpu" else kind)]
    if not devs:
        devs = jax.devices()
    _current[0] = devs[idx % len(devs)]
    return _current[0]


def get_device() -> str:
    d = _current[0] or jax.devices()[0]
    plat = "tpu" if d.platform in ("tpu", "axon") else d.platform
    return f"{plat}:{d.id}"


def current_device():
    return _current[0] or jax.devices()[0]


def synchronize():
    """Block until all dispatched work completes (reference:
    paddle.device.cuda.synchronize). jax.block_until_ready on a trivial op."""
    jax.block_until_ready(jax.numpy.zeros(()))


def is_compiled_with_cuda() -> bool:
    return False


class Stream:
    """Compat no-op: XLA has no user-visible streams; ordering is program
    order (replaces reference stream/event machinery,
    paddle/phi/backends/gpu/gpu_context.h:97)."""

    def synchronize(self):
        synchronize()


def cuda_empty_cache():
    pass

from . import cuda  # noqa: E402,F401
