"""Device management (reference: python/paddle/device/__init__.py).

The reference juggles CUDAPlace/XPUPlace/NPUPlace and streams
(paddle/phi/common/place.h, device/cuda/streams). On TPU there is a single
logical device space managed by XLA; placement happens via shardings, and
stream semantics do not exist (XLA program order). We expose the same API
shape with TPU-truthful behavior.
"""
from __future__ import annotations

import jax

_current = [None]


def get_all_devices():
    return jax.devices()


def device_count() -> int:
    return len(jax.devices())


def set_device(device: str):
    """Accepts 'tpu', 'tpu:N', 'cpu', 'cpu:N'. Returns the jax device."""
    if ":" in device:
        kind, idx = device.split(":")
        idx = int(idx)
    else:
        kind, idx = device, 0
    if kind in ("gpu", "cuda"):
        raise ValueError("paddle_tpu is a TPU framework; no CUDA devices. "
                         "Use 'tpu' or 'cpu'.")
    devs = [d for d in jax.devices() if d.platform in (kind, "axon" if kind == "tpu" else kind)]
    if not devs:
        devs = jax.devices()
    _current[0] = devs[idx % len(devs)]
    return _current[0]


def get_device() -> str:
    d = _current[0] or jax.devices()[0]
    plat = "tpu" if d.platform in ("tpu", "axon") else d.platform
    return f"{plat}:{d.id}"


def current_device():
    return _current[0] or jax.devices()[0]


def synchronize():
    """Block until all dispatched work completes (reference:
    paddle.device.cuda.synchronize). jax.block_until_ready on a trivial op."""
    jax.block_until_ready(jax.numpy.zeros(()))


def is_compiled_with_cuda() -> bool:
    return False


class Stream:
    """Compat no-op: XLA has no user-visible streams; ordering is program
    order (replaces reference stream/event machinery,
    paddle/phi/backends/gpu/gpu_context.h:97)."""

    def synchronize(self):
        synchronize()


def cuda_empty_cache():
    pass

from . import cuda  # noqa: E402,F401


# -------- surface completion (reference: python/paddle/device/__init__.py)

class Event:
    """reference: device.Event — cross-stream sync marker. XLA owns
    scheduling (SURVEY §7 StreamSafe row): record/query/synchronize map to
    program-order completion."""

    def __init__(self, device=None, enable_timing=False, blocking=False,
                 interprocess=False):
        self._recorded = False

    def record(self, stream=None):
        self._recorded = True

    def query(self):
        return True

    def synchronize(self):
        synchronize()


def current_stream(device=None):
    return Stream()


def set_stream(stream):
    return stream


def stream_guard(stream):
    import contextlib
    return contextlib.nullcontext()


def XPUPlace(dev_id=0):  # noqa: N802
    from ..fluid import XPUPlace as _x
    return _x(dev_id)


def IPUPlace():  # noqa: N802
    raise RuntimeError("IPU backend is not available in paddle_tpu")


def MLUPlace(dev_id=0):  # noqa: N802
    raise RuntimeError("MLU backend is not available in paddle_tpu")


def get_cudnn_version():
    return None  # no cuDNN in the TPU stack


def is_compiled_with_rocm() -> bool:
    return False


def is_compiled_with_xpu() -> bool:
    return False


def is_compiled_with_npu() -> bool:
    return False


def is_compiled_with_mlu() -> bool:
    return False


def is_compiled_with_ipu() -> bool:
    return False


def is_compiled_with_cinn() -> bool:
    return False


def is_compiled_with_custom_device(device_type: str) -> bool:
    return False


def get_all_device_type():
    import jax
    return sorted({d.platform for d in jax.devices()})


def get_all_custom_device_type():
    return []


def get_available_device():
    import jax
    return [f"{d.platform}:{d.id}" for d in jax.devices()]


def get_available_custom_device():
    return []
