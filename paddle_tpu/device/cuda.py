"""paddle.device.cuda API-parity surface, mapped to the TPU/XLA runtime.

Reference (SURVEY §2.3 paddle.device): device/cuda/__init__.py (streams,
events, memory stats) and device/cuda/graphs.py (CUDAGraph capture). The
name is kept for migration; semantics map to XLA:
- memory stats come from the device allocator's live statistics
  (jax device.memory_stats — the stat_allocator.h counters' analog);
- streams/events are ordering no-ops: XLA program order + async dispatch
  replaces user-managed streams (SURVEY §5.2 "deterministic-by-construction
  replaces stream races");
- CUDAGraph's "capture once, replay cheap" is exactly jax.jit.
"""
from __future__ import annotations

import jax


def _dev(device=None):
    devs = jax.devices()
    if device is None:
        return devs[0]
    if isinstance(device, int):
        return devs[device]
    return device


def _stat(name, device=None) -> int:
    stats = _dev(device).memory_stats() or {}
    return int(stats.get(name, 0))


def max_memory_allocated(device=None) -> int:
    """reference: paddle.device.cuda.max_memory_allocated."""
    return _stat("peak_bytes_in_use", device)


def memory_allocated(device=None) -> int:
    return _stat("bytes_in_use", device)


def max_memory_reserved(device=None) -> int:
    return _stat("peak_bytes_in_use", device)


def memory_reserved(device=None) -> int:
    return _stat("bytes_limit", device)


def device_count() -> int:
    return len(jax.devices())


def get_device_properties(device=None):
    d = _dev(device)
    return type("DeviceProperties", (), {
        "name": getattr(d, "device_kind", str(d)),
        "total_memory": _stat("bytes_limit", device),
        "multi_processor_count": getattr(d, "core_count", 1),
    })()


def get_device_name(device=None) -> str:
    return getattr(_dev(device), "device_kind", str(_dev(device)))


def synchronize(device=None):
    """Block until all dispatched work on the device finishes."""
    import jax.numpy as jnp
    jnp.zeros(()).block_until_ready()


def empty_cache():
    pass  # XLA owns the arena; nothing to trim


class Stream:
    """Ordering no-op (XLA schedules; kept for API migration)."""

    def __init__(self, device=None, priority=2):
        self.device = _dev(device)

    def synchronize(self):
        synchronize(self.device)

    def wait_event(self, event):
        pass

    def wait_stream(self, stream):
        pass

    def record_event(self, event=None):
        return event or Event()


class Event:
    def __init__(self, enable_timing=False, blocking=False, interprocess=False):
        pass

    def record(self, stream=None):
        pass

    def query(self) -> bool:
        return True

    def synchronize(self):
        synchronize()


def current_stream(device=None) -> Stream:
    return Stream(device)


def stream_guard(stream):
    import contextlib
    return contextlib.nullcontext()


class CUDAGraph:
    """reference: device/cuda/graphs.py CUDAGraph — capture/replay. The XLA
    equivalence: wrap the captured callable in jax.jit (compile once, replay
    as one executable); provided for code that structurally depends on the
    capture API."""

    def __init__(self, place=None, mode="thread_local"):
        self._fn = None
        self._jitted = None

    def capture_begin(self):
        pass

    def capture_end(self):
        pass

    def replay(self):
        if self._jitted is not None:
            self._jitted()

    def reset(self):
        self._jitted = None
