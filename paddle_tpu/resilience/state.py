"""TrainState — ONE snapshot of everything a bit-exact resume needs.

"Resumable training" usually means params + optimizer; bit-exact resume
means the full closure of the training process: miss any one of these
and the post-resume trajectory silently diverges from the uninterrupted
run —

  step counter      jnp.int32(step) is a step input (bias correction,
                    schedules)
  params/opt state  TrainStep's device pytrees (NOT optimizer._states —
                    the compiled step owns its own)
  GradScaler        (scale, good, bad): a resume that resets loss scale
                    replays different update-skip decisions
  RNG key           core/random's global key — dropout masks and
                    sampling continue the same stream
  dataloader cursor (epoch, batch_idx, seed): the model must see the
                    SAME remaining batches in the same order
  StepMonitor       compiles/recompiles/steps counters — telemetry
                    continuity (a resume is not a recompile storm)

The kill-at-step-k parity oracle (tests/test_resilience.py, the r9/r10
decode-parity style) pins the definition: resume at k must reproduce
the uninterrupted loss trajectory BIT-identically.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, Optional


def rng_state_dict() -> Dict[str, Any]:
    """Serializable snapshot of the global eager RNG stream."""
    from ..core import random as _random
    return _random.key_state_dict()

def rng_load_state_dict(state: Dict[str, Any]):
    from ..core import random as _random
    _random.set_key_state_dict(state)


class TrainState:
    """Compose the resumable pieces; state_dict() nests their snapshots
    under stable keys (the CheckpointManager's nested-dict format).

        ts = TrainState(train_step=step, loader=loader, monitor=mon)
        manager.save(step_i, ts.state_dict())
        ...
        n, sd = manager.restore_latest()
        ts.load_state_dict(sd)       # params, opt, scaler, RNG, cursor

    Every component is optional; `extra` is a (state_dict_fn,
    load_state_dict_fn) pair for anything else that must ride along."""

    def __init__(self, train_step=None, *, loader=None, monitor=None,
                 include_rng: bool = True,
                 extra: Optional[tuple] = None):
        self.train_step = train_step
        self.loader = loader
        self.monitor = monitor
        self.include_rng = include_rng
        self.extra = extra

    @property
    def step(self) -> int:
        return int(getattr(self.train_step, "_step_i", 0) or 0)

    def state_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {"step": self.step}
        if self.train_step is not None:
            out["train"] = self.train_step.state_dict()
        if self.loader is not None:
            out["loader"] = self.loader.state_dict()
        if self.monitor is not None:
            out["monitor"] = self.monitor.state_dict()
        if self.include_rng:
            out["rng"] = rng_state_dict()
        if self.extra is not None:
            out["extra"] = self.extra[0]()
        return out

    def load_state_dict(self, state: Dict[str, Any]):
        if self.train_step is not None and "train" in state:
            self.train_step.set_state_dict(state["train"])
        if self.loader is not None and "loader" in state:
            self.loader.set_state_dict(state["loader"])
        if self.monitor is not None and "monitor" in state:
            self.monitor.set_state_dict(state["monitor"])
        if self.include_rng and "rng" in state:
            rng_load_state_dict(state["rng"])
        if self.extra is not None and "extra" in state:
            self.extra[1](state["extra"])
        return self
