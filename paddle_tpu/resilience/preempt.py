"""Preemption handling — turn SIGTERM into a checkpoint + resume-me exit.

Preemptible TPU capacity delivers SIGTERM with a grace window (spot VMs:
~30s); the difference between "lost everything since the last manual
save" and "interruption is a non-event" is what happens inside that
window. The contract here:

  1. the signal handler only sets a flag — the in-flight jitted step
     ALWAYS completes (python runs handlers between bytecodes; the XLA
     launch is never torn),
  2. at the next step boundary ``poll()`` takes one synchronous
     emergency checkpoint (waiting out any in-flight async save first),
  3. the process exits with ``RESUME_EXIT_CODE`` by raising
     ``Preempted`` — a SystemExit subclass, so an unhandled one exits
     cleanly with the resume-me code that ``fleet.elastic``'s restart
     supervisor recognizes.

Wiring: ``TrainStep(preemption=handler)`` polls after every step /
run_steps launch; ``hapi.callbacks.PreemptionCallback`` polls per fit
batch. Tests deliver real signals (os.kill) and fake ones
(``handler.request()``) — same code path either way.
"""
from __future__ import annotations

import logging
import signal
import threading
from typing import Callable, Optional, Sequence

_logger = logging.getLogger("paddle_tpu.resilience.preempt")

# the resume-me exit status: "I checkpointed, restart me". Distinct from
# 0 (done) and from crash codes — fleet.elastic.run_with_restarts
# restarts on exactly this without charging the crash budget.
RESUME_EXIT_CODE = 42


class Preempted(SystemExit):
    """Raised at a step boundary after the emergency checkpoint landed.
    SystemExit subclass: unhandled, the process exits with `.code`
    (RESUME_EXIT_CODE) — no traceback spew, the supervisor restarts."""

    def __init__(self, code: int = RESUME_EXIT_CODE, *,
                 step: Optional[int] = None,
                 checkpoint_path: Optional[str] = None,
                 signum: Optional[int] = None):
        self.step = step
        self.checkpoint_path = checkpoint_path
        self.signum = signum
        super().__init__(code)


class PreemptionHandler:
    """Flag-setting signal handler + emergency-checkpoint policy.

        handler = PreemptionHandler(manager=ckpt_mgr, state=train_state)
        with handler:                       # installs SIGTERM/SIGINT
            step = TrainStep(..., preemption=handler)
            for batch in loader:            # each step polls; on a
                step(*batch)                # signal: save + Preempted

    `manager`: a CheckpointManager for the emergency save (optional —
    without one, poll() raises Preempted immediately and the caller owns
    persistence). `state`: anything with ``state_dict()`` (a
    resilience.TrainState, a TrainStep, ...). A second SIGINT while
    already draining raises KeyboardInterrupt — ctrl-C twice still
    means NOW."""

    def __init__(self, *, manager=None, state=None,
                 signals: Sequence[int] = (signal.SIGTERM, signal.SIGINT),
                 exit_code: int = RESUME_EXIT_CODE,
                 on_preempt: Optional[Callable] = None):
        self.manager = manager
        self.state = state
        self.signals = tuple(signals)
        self.exit_code = exit_code
        self.on_preempt = on_preempt
        self._requested = threading.Event()
        self._signum: Optional[int] = None
        self._count = 0
        self._sigint_count = 0
        self._prev = {}
        self._installed = False

    # ------------------------------------------------------------ signals
    def _handle(self, signum, frame):
        self._count += 1
        if signum == signal.SIGINT:
            # count ctrl-C on its own: a SIGTERM (spot preemption)
            # followed by ONE SIGINT must still drain gracefully — only
            # the SECOND ctrl-C means NOW
            self._sigint_count += 1
            if self._sigint_count > 1:
                raise KeyboardInterrupt
        self._signum = signum
        self._requested.set()
        _logger.warning(
            "signal %d received: finishing the in-flight step, then "
            "emergency checkpoint + exit(%d)", signum, self.exit_code)

    def install(self) -> "PreemptionHandler":
        """Install handlers (main thread only — python's signal rule).
        Idempotent; previous handlers are restored by uninstall()."""
        if self._installed:
            return self
        for s in self.signals:
            self._prev[s] = signal.signal(s, self._handle)
        self._installed = True
        return self

    def uninstall(self):
        if not self._installed:
            return
        for s, prev in self._prev.items():
            try:
                signal.signal(s, prev)
            except (ValueError, TypeError):   # non-main thread/teardown
                pass
        self._prev.clear()
        self._installed = False

    def __enter__(self):
        return self.install()

    def __exit__(self, *exc):
        self.uninstall()
        return False

    # ------------------------------------------------------------- state
    @property
    def requested(self) -> bool:
        return self._requested.is_set()

    def request(self, signum: Optional[int] = None):
        """Programmatic preemption (tests / external orchestrators): same
        flag the signal handler sets, same everything after."""
        self._signum = signum
        self._requested.set()

    def clear(self):
        self._requested.clear()
        self._signum = None
        self._count = 0
        self._sigint_count = 0

    @staticmethod
    def _stamp_exit(reason: str, **meta):
        """Goodput accounting: stamp the installed timeline recorder's
        segment end, so the stitched report attributes the gap to the
        next segment's first span as `restart_downtime`. Best-effort —
        a failure to stamp must never block the exit path."""
        try:
            from ..profiler.timeline import current as _tl_current
            tl = _tl_current()
            if tl is not None:
                tl.mark_exit(reason, **meta)
        except Exception:       # pragma: no cover - never block the exit
            pass

    # -------------------------------------------------------------- poll
    def poll(self, state=None, step: Optional[int] = None):
        """Call at a step boundary. No signal -> no-op (one Event read).
        Signal pending -> take the emergency checkpoint (synchronous;
        waits out any in-flight async save first) and raise Preempted
        carrying the checkpoint path + step."""
        if not self._requested.is_set():
            return
        # the request is consumed (clear()) only at the raise points
        # below: a handler shared across in-process run_with_restarts
        # cycles must not re-fire at the restarted run's first boundary
        # — but an emergency save that FAILS (retry deadline on a
        # transient fault) must leave the flag armed so the next
        # boundary retries instead of training on past the grace window
        signum = self._signum
        state = state if state is not None else self.state
        path = None
        if self.manager is not None:
            if state is None:
                # a manager was configured — the resume-me exit code is a
                # PROMISE that durable progress exists. With nothing to
                # save, keeping that promise would let the supervisor
                # free-restart (no crash budget charged) a job that loses
                # all work every cycle. Exit as a crash instead.
                _logger.error(
                    "preemption: manager configured but no state to "
                    "checkpoint — exiting as a crash (code 1), not "
                    "resume-me, so the restart supervisor charges its "
                    "budget instead of looping a job that makes no "
                    "durable progress")
                self.clear()
                self._stamp_exit("preemption-crash", step=step,
                                 signum=signum)
                raise Preempted(1, step=step, signum=signum)
            sd = state.state_dict()
            if step is None:
                step = sd.get("step", 0) if isinstance(sd, dict) else 0
            self.manager.wait()
            path = self.manager.save(int(step or 0), sd,
                                     meta={"reason": "preemption",
                                           "signum": signum})
            _logger.warning("emergency checkpoint at step %s: %s",
                            step, path)
        if self.on_preempt is not None:
            self.on_preempt(self)
        self.clear()
        self._stamp_exit("preemption", step=step, signum=signum)
        raise Preempted(self.exit_code, step=step, checkpoint_path=path,
                        signum=signum)


def exit_for_resume(step: Optional[int] = None,
                    checkpoint_path: Optional[str] = None):
    """Explicit resume-me exit for driver scripts that already saved."""
    raise Preempted(RESUME_EXIT_CODE, step=step,
                    checkpoint_path=checkpoint_path)


def is_resume_exit(code: Optional[int]) -> bool:
    return code == RESUME_EXIT_CODE

