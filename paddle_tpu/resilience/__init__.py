"""paddle_tpu.resilience — fault-tolerant training & serving.

Four pieces (see each module's docstring for the full contract):

  checkpoint  CheckpointManager: atomic tmp-then-rename commits with a
              per-leaf checksummed manifest, async save that overlaps
              training, retention GC, and verifying restore
              (CheckpointCorruptError names the bad leaf).
  state       TrainState: the one snapshot bit-exact resume needs —
              step, params, optimizer state, GradScaler, RNG key,
              dataloader cursor, StepMonitor counters.
  preempt     PreemptionHandler: SIGTERM/SIGINT -> finish the in-flight
              step, emergency checkpoint, exit(RESUME_EXIT_CODE);
              fleet.elastic.run_with_restarts restarts-and-resumes.
  chaos       the deterministic fault-injection harness + retry():
              every recovery claim above is proven by an injected fault
              in tests, not by inspection.

Reference mapping (SURVEY §5.4): dist_save/dist_load -> CheckpointManager
/ distributed.checkpoint; fleet elastic manager -> preempt +
fleet.elastic restart supervision.
"""
from .checkpoint import (CheckpointManager, CheckpointCorruptError,
                         AsyncHandle, atomic_write_bytes)  # noqa: F401
from .chaos import (Injector, Fault, KillAfterStep, KillAtSite,
                    RaiseInStep, AllocFailure, TruncateDuringSave,
                    TransientIOErrors, TransientIOError, SimulatedKill,
                    ReplicaDown, ReplicaKill, ScrapeTimeout,
                    CorruptKVBlock, corrupt_leaf, retry)  # noqa: F401
from .preempt import (PreemptionHandler, Preempted, RESUME_EXIT_CODE,
                      exit_for_resume, is_resume_exit)  # noqa: F401
from .state import TrainState  # noqa: F401

__all__ = [
    "CheckpointManager", "CheckpointCorruptError", "AsyncHandle",
    "atomic_write_bytes",
    "Injector", "Fault", "KillAfterStep", "KillAtSite", "RaiseInStep",
    "AllocFailure",
    "TruncateDuringSave", "TransientIOErrors", "TransientIOError",
    "SimulatedKill", "ReplicaDown", "ReplicaKill", "ScrapeTimeout",
    "CorruptKVBlock", "corrupt_leaf", "retry",
    "PreemptionHandler", "Preempted", "RESUME_EXIT_CODE",
    "exit_for_resume", "is_resume_exit",
    "TrainState",
]
