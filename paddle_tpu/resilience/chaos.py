"""Deterministic fault injection + retry — the proof harness for every
recovery claim in paddle_tpu.resilience.

Reliability code rots unless its failure paths run; on preemptible TPU
fleets the failure paths ARE the steady state (ROADMAP north star: spot
capacity is only cheap if interruption is a non-event). This module makes
faults a first-class, SEEDED test input:

  Injector        a seeded fault scheduler. Production code calls
                  ``injector.fire(site, **ctx)`` at named fault sites
                  (checkpoint leaf writes, pre-commit, step boundaries);
                  each registered Fault decides — deterministically, from
                  the seed and its own counters — whether to trigger.
                  ``Injector(None)``-style absence costs one ``is None``
                  check on the hot path (managers hold ``chaos=None`` by
                  default).

  Faults          KillAfterStep / TruncateDuringSave / RaiseInStep /
                  TransientIOErrors — the interruption taxonomy of a
                  preemptible fleet: process death, torn writes, host
                  exceptions, flaky storage. CorruptLeaf is post-hoc
                  (``corrupt_leaf``): bitrot happens to data at rest, not
                  to code in flight. ReplicaKill / ScrapeTimeout
                  (ISSUE 14) extend the taxonomy to FLEET faults: a
                  serving replica dying mid-traffic (observed as
                  ReplicaDown by the router) and a flaky health scrape.
                  CorruptKVBlock (ISSUE 19) is the SILENT class: flip
                  bytes inside one live KV block with no exception and
                  no accounting change — only an active golden-probe
                  comparison can observe it.

  SimulatedKill   BaseException (like SystemExit): nothing should catch
                  it accidentally — ``except Exception`` recovery blocks
                  must NOT absorb a simulated process death, or the test
                  would prove recovery that a real SIGKILL will not get.

  retry()         generic exponential-backoff with a wall-clock deadline,
                  used by checkpoint I/O. Deterministic delays (no
                  jitter) so tests assert the exact schedule.

Every guarantee the resilience layer states is pinned by an injected
fault in tests/test_resilience.py — not by inspection.
"""
from __future__ import annotations

import os
import time
from dataclasses import dataclass, field
from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np


class SimulatedKill(BaseException):
    """A simulated process death (kill -9 at this exact point). Derives
    from BaseException so ordinary ``except Exception`` recovery code
    cannot absorb it — a real SIGKILL is not catchable either."""

    def __init__(self, site: str, detail: str = ""):
        self.site = site
        self.detail = detail
        super().__init__(f"simulated kill at {site}" +
                         (f" ({detail})" if detail else ""))


class TransientIOError(OSError):
    """An injected transient storage fault (the NFS hiccup / GCS 503
    class). OSError subclass: real checkpoint I/O retries exactly the
    errnos this models."""


class ReplicaDown(ConnectionError):
    """A replica death observed from OUTSIDE the replica (ISSUE 14) —
    what a router's dispatch/step call sees when the peer process died.
    Unlike SimulatedKill (THIS process dying, deliberately uncatchable),
    a peer's death is exactly what fleet code must catch and route
    around, so it derives from ConnectionError like the real thing."""

    def __init__(self, replica: str, detail: str = ""):
        self.replica = replica
        self.detail = detail
        super().__init__(f"replica {replica} is down" +
                         (f" ({detail})" if detail else ""))


# --------------------------------------------------------------- faults

class Fault:
    """One scheduled fault. Subclasses implement ``matches`` (am I armed
    for this site/context?) and ``trigger`` (do the damage)."""

    kind = "fault"

    def matches(self, site: str, ctx: dict) -> bool:
        raise NotImplementedError

    def trigger(self, injector: "Injector", site: str, ctx: dict):
        raise NotImplementedError


@dataclass
class KillAfterStep(Fault):
    """Die (SimulatedKill) at the first ``step.end`` whose step >= k —
    the mid-training preemption/crash."""
    step: int
    kind: str = "kill_after_step"
    fired: bool = field(default=False, init=False)

    def matches(self, site, ctx):
        return (not self.fired and site == "step.end"
                and ctx.get("step", -1) >= self.step)

    def trigger(self, injector, site, ctx):
        self.fired = True
        raise SimulatedKill(site, f"step={ctx.get('step')}")


@dataclass
class RaiseInStep(Fault):
    """Raise an ordinary exception at ``step.end`` — the host-side bug /
    OOM class that recovery code IS allowed to catch."""
    step: int
    exc: type = RuntimeError
    kind: str = "raise_in_step"
    fired: bool = field(default=False, init=False)

    def matches(self, site, ctx):
        return (not self.fired and site == "step.end"
                and ctx.get("step", -1) >= self.step)

    def trigger(self, injector, site, ctx):
        self.fired = True
        raise self.exc(f"injected fault at step {ctx.get('step')}")


@dataclass
class TruncateDuringSave(Fault):
    """Tear the Nth leaf file written by a checkpoint save (truncate to
    half its bytes), then optionally die — the kill-mid-write torn-page
    case the atomic commit protocol must survive. Site: ``ckpt.leaf``
    (fired after each leaf lands, ctx: path/index/leaf)."""
    nth_leaf: int = 0
    kill: bool = True
    kind: str = "truncate_during_save"
    fired: bool = field(default=False, init=False)

    def matches(self, site, ctx):
        return (not self.fired and site == "ckpt.leaf"
                and ctx.get("index", -1) >= self.nth_leaf)

    def trigger(self, injector, site, ctx):
        self.fired = True
        path = ctx["path"]
        size = os.path.getsize(path)
        with open(path, "r+b") as f:
            f.truncate(size // 2)
        if self.kill:
            raise SimulatedKill(site, f"truncated {ctx.get('leaf')}")


@dataclass
class KillAtSite(Fault):
    """Die the Nth time `site` fires — pointed at ``ckpt.pre_commit`` /
    ``ckpt.manifest`` this walks a kill through every byte-position class
    of the commit protocol."""
    site: str
    nth: int = 0
    kind: str = "kill_at_site"
    _seen: int = field(default=0, init=False)
    fired: bool = field(default=False, init=False)

    def matches(self, site, ctx):
        if self.fired or site != self.site:
            return False
        self._seen += 1
        return self._seen - 1 >= self.nth

    def trigger(self, injector, site, ctx):
        self.fired = True
        raise SimulatedKill(site)


@dataclass
class AllocFailure(Fault):
    """Raise a device-allocation-failure-shaped RuntimeError the Nth
    time `site` fires (default ``serving.step``) — the RESOURCE_EXHAUSTED
    class the HBM ledger's OOM forensics path (ISSUE 18) exists for. The
    message matches `obs.memz.looks_like_oom`, so the post-mortem dump
    rehearses end to end without a real OOM; tests assert the artifact
    AND injector.fired."""
    site: str = "serving.step"
    nth: int = 0
    bytes: int = 1 << 30
    kind: str = "alloc_failure"
    _seen: int = field(default=0, init=False)
    fired: bool = field(default=False, init=False)

    def matches(self, site, ctx):
        if self.fired or site != self.site:
            return False
        self._seen += 1
        return self._seen - 1 >= self.nth

    def trigger(self, injector, site, ctx):
        self.fired = True
        raise RuntimeError(
            f"RESOURCE_EXHAUSTED: Out of memory allocating {self.bytes} "
            f"bytes (injected at {site}, ctx={dict(ctx)})")


@dataclass
class TransientIOErrors(Fault):
    """Fail the first `times` fires of `site` (default the checkpoint
    write path) with TransientIOError — absorbed by ``retry``; tests
    assert recovery happened AND the fault really fired."""
    times: int = 2
    site: str = "ckpt.io"
    kind: str = "transient_io"
    remaining: int = field(default=-1, init=False)

    def __post_init__(self):
        self.remaining = self.times

    def matches(self, site, ctx):
        return self.remaining > 0 and site == self.site

    def trigger(self, injector, site, ctx):
        self.remaining -= 1
        raise TransientIOError(
            f"injected transient IO fault at {ctx.get('path', site)} "
            f"({self.times - self.remaining}/{self.times})")


@dataclass
class ReplicaKill(Fault):
    """Kill one named replica the first time the router steps it at or
    past `step` (site ``fleet.step``, ctx: replica/step) — the
    replica-dies-mid-traffic case the fleet failover path exists for.
    The router observes the death as a ReplicaDown at the step call and
    must eject + redispatch; the fleet chaos tests assert the fault
    FIRED (injector.fired) so a green run proves recovery ran, not that
    nothing happened."""
    replica: str
    step: int = 0
    kind: str = "replica_kill"
    fired: bool = field(default=False, init=False)

    def matches(self, site, ctx):
        return (not self.fired and site == "fleet.step"
                and ctx.get("replica") == self.replica
                and ctx.get("step", -1) >= self.step)

    def trigger(self, injector, site, ctx):
        self.fired = True
        raise ReplicaDown(self.replica,
                          f"killed at step {ctx.get('step')}")


@dataclass
class ScrapeTimeout(Fault):
    """Time out the next `times` health scrapes of one named replica
    (site ``fleet.scrape``) — the flaky-network / overloaded-ops-surface
    case. A registry must tolerate `fail_threshold - 1` consecutive
    timeouts without ejecting (transients are the steady state) and
    eject at the threshold; both sides are asserted in tests."""
    replica: str
    times: int = 1
    kind: str = "scrape_timeout"
    remaining: int = field(default=-1, init=False)

    def __post_init__(self):
        self.remaining = self.times

    def matches(self, site, ctx):
        return (self.remaining > 0 and site == "fleet.scrape"
                and ctx.get("replica") == self.replica)

    def trigger(self, injector, site, ctx):
        self.remaining -= 1
        raise TimeoutError(
            f"injected scrape timeout on {self.replica} "
            f"({self.times - self.remaining}/{self.times})")


@dataclass
class CorruptKVBlock(Fault):
    """Silently flip bytes inside ONE live KV block of a paged engine
    (ISSUE 19) — the silent-wrong-answer fault class: no exception, no
    accounting change, every passive metric stays green, only an active
    probe comparing output chains against a golden can see it. Fires
    once at the `nth` match of `site` (default ``probe.cycle``, fired by
    the Prober at the top of each cycle, so "detected within one probe
    cycle" is exact in tests). `block` picks the target device block; if
    None the trigger corrupts the first live refcounted block. The
    damage rides the pool's own read_block/write_block round-trip, so
    host-side invariants (refcounts, owner rows, trie) remain intact —
    exactly what makes the corruption invisible to everything but the
    golden comparison."""
    engine: object = None
    site: str = "probe.cycle"
    nth: int = 0
    block: Optional[int] = None
    n_bytes: int = 64
    seed: int = 0
    kind: str = "corrupt_kv_block"
    seen: int = field(default=0, init=False)
    fired: bool = field(default=False, init=False)
    corrupted_block: Optional[int] = field(default=None, init=False)

    def matches(self, site, ctx):
        if self.fired or site != self.site:
            return False
        hit = self.seen >= self.nth
        self.seen += 1
        return hit

    def trigger(self, injector, site, ctx):
        self.fired = True
        eng = self.engine
        pool = eng._pool
        blk = self.block
        if blk is None:
            live = sorted(b for b, r in pool._refs.items() if r > 0)
            if not live:
                raise RuntimeError("CorruptKVBlock: no live block to hit")
            blk = live[0]
        payload = tuple(np.array(p) for p in pool.read_block(eng._pools, blk))
        rng = np.random.RandomState(self.seed)
        flat = payload[0].view(np.uint8).reshape(-1)
        n = min(self.n_bytes, flat.size)
        for i in rng.randint(0, flat.size, size=max(1, n)):
            flat[i] ^= 0xFF
        eng._pools = pool.write_block(eng._pools, blk, payload)
        self.corrupted_block = int(blk)


class Injector:
    """Seeded, deterministic fault scheduler.

    ``Injector(seed, faults=[...])`` arms explicit faults;
    ``Injector.random_kill(seed, lo, hi)`` derives a kill step from the
    seed (the chaos_train driver's mode: the seed IS the scenario, so a
    failing run reproduces from its seed alone). ``fire(site, **ctx)``
    consults every armed fault; ``log`` records what actually triggered
    — tests assert the fault fired, not just that nothing broke."""

    def __init__(self, seed: int = 0, faults: Sequence[Fault] = ()):
        self.seed = int(seed)
        self.rng = np.random.RandomState(self.seed)
        self.faults: List[Fault] = list(faults)
        self.log: List[Tuple[str, str, dict]] = []

    @classmethod
    def random_kill(cls, seed: int, lo: int, hi: int) -> "Injector":
        inj = cls(seed)
        step = int(inj.rng.randint(lo, hi + 1))
        inj.faults.append(KillAfterStep(step))
        return inj

    @property
    def kill_step(self) -> Optional[int]:
        for f in self.faults:
            if isinstance(f, KillAfterStep):
                return f.step
        return None

    def add(self, fault: Fault) -> "Injector":
        self.faults.append(fault)
        return self

    def fire(self, site: str, **ctx):
        for f in self.faults:
            if f.matches(site, ctx):
                self.log.append((site, f.kind, dict(ctx)))
                f.trigger(self, site, ctx)

    def fired(self, kind: Optional[str] = None) -> int:
        return sum(1 for _, k, _ in self.log if kind is None or k == kind)


def corrupt_leaf(ckpt_dir: str, leaf: str, *, seed: int = 0) -> str:
    """Flip bytes of ONE committed leaf's region of the data file
    (bitrot-at-rest). `leaf` is the manifest key ("params/fc1.weight");
    returns the corrupted file path. Restore must then raise
    CheckpointCorruptError naming exactly `leaf` — neighboring leaves in
    the same blob stay intact."""
    import json
    with open(os.path.join(ckpt_dir, "MANIFEST.json")) as f:
        manifest = json.load(f)
    entry = manifest["leaves"][leaf]
    path = os.path.join(ckpt_dir, manifest.get("data_file", "leaves.bin"))
    rng = np.random.RandomState(seed)
    off, nbytes = entry["offset"], entry["nbytes"]
    with open(path, "r+b") as f:
        f.seek(off)
        data = bytearray(f.read(nbytes))
        n = max(1, len(data) // 64)
        for i in rng.randint(0, len(data), size=n):
            data[i] ^= 0xFF
        f.seek(off)
        f.write(bytes(data))
    return path


# ---------------------------------------------------------------- retry

def retry(fn: Callable, *args, deadline: float = 5.0,
          base_delay: float = 0.01, max_delay: float = 0.5,
          factor: float = 2.0, retry_on=(OSError,),
          sleep: Callable[[float], None] = time.sleep,
          clock: Callable[[], float] = time.monotonic,
          on_retry: Optional[Callable] = None, **kwargs):
    """Call ``fn(*args, **kwargs)``; on a `retry_on` exception, back off
    exponentially (base_delay * factor^n, capped at max_delay) and try
    again until `deadline` seconds have elapsed, then re-raise the last
    exception. Delays are DETERMINISTIC (no jitter): a seeded chaos run
    replays the same schedule, and tests assert it exactly. SimulatedKill
    (BaseException) is never retried — a dead process does not back off."""
    t0 = clock()
    attempt = 0
    while True:
        try:
            return fn(*args, **kwargs)
        except retry_on as e:
            delay = min(base_delay * (factor ** attempt), max_delay)
            attempt += 1
            if clock() - t0 + delay > deadline:
                raise
            if on_retry is not None:
                on_retry(attempt, delay, e)
            sleep(delay)
