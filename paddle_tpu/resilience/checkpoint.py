"""Atomic, async, verifying checkpoint manager.

Reference (SURVEY §5.4): dist_save/dist_load write state then hope — a
crash mid-save corrupts the newest checkpoint and a resume trusts
whatever bytes it finds. On preemptible TPU capacity that is the common
case, not the edge case, so this manager makes two hard promises:

  1. COMMIT ATOMICITY — a save writes every leaf into ``tmp.<uuid>/``,
     writes a per-leaf MANIFEST (shape/dtype/crc32 per leaf), writes a
     COMMIT marker carrying the manifest's own crc32, and only then
     ``os.replace``s the directory to ``step_<n>``. A kill at ANY byte
     leaves either an ignorable ``tmp.*`` orphan or a fully committed
     checkpoint: ``latest()`` only ever sees committed steps, so the
     previous checkpoint stays authoritative through any crash. A
     RE-SAVE of an existing step publishes in two renames through a
     sealed ``publish.<step>.<uuid>`` dir (itself committed: all_steps/
     restore see it, and recovery finishes the swap) so the old dir is
     only deleted once the new bytes are discoverable — the kill-anywhere
     promise holds even when a step is overwritten.
     ``durability="process"`` (default) is atomic against process death
     (the preemption threat model — no fsync, near-zero commit cost);
     ``durability="power"`` adds fsync on every file + directory for
     kernel-panic/power-loss durability (the archive tier).

  2. VERIFIED RESTORE — every leaf is checksummed on read; a mismatch
     (truncation, bitrot) raises ``CheckpointCorruptError`` NAMING the
     bad leaf, and ``restore_latest(fallback=True)`` walks back to the
     newest intact checkpoint instead of resuming from garbage.

Leaves are stored as raw bytes + dtype/shape in the manifest (not .npy:
raw bytes round-trip bfloat16/float8 via ml_dtypes and make truncation
detection exact). Python scalars inline into the manifest. Async save
snapshots device arrays to host ON the caller thread (the one deliberate
sync — the device→host gather IS the job here), then serializes/commits
on a background thread so training overlaps checkpoint I/O (the orbax
AsyncCheckpointer idea, portable to this manifest format). Retention:
``keep_last`` newest + every ``keep_every``-th step survive GC; the
newest committed step always survives.

All file I/O goes through ``chaos.retry`` (exponential backoff,
deadline) and fires injector sites (``ckpt.io``, ``ckpt.leaf``,
``ckpt.manifest``, ``ckpt.pre_commit``, ``ckpt.publish``) so
tests/test_resilience.py can
kill/tear/flake every stage and prove the two promises above.

Checkpoint layout::

    <dir>/step_00000012/
        MANIFEST.json     {"step", "data_file", "leaves": {key: {offset,
                           nbytes, dtype, shape, crc32}}, "scalars":
                           {key: value}, "meta"}
        COMMIT            {"step", "manifest_crc32"}
        leaves.bin        every leaf's raw bytes, concatenated in sorted
                          key order (ONE data file: a save is 3 file
                          opens however many leaves — per-leaf files cost
                          ~0.7ms of metadata syscalls EACH on overlay
                          filesystems, which was the entire async-save
                          overhead on the CPU toy)
"""
from __future__ import annotations

import json
import os
import shutil
import threading
import uuid
import zlib
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from .chaos import Injector, retry

MANIFEST = "MANIFEST.json"
COMMIT = "COMMIT"
DATA_FILE = "leaves.bin"
_STEP_PREFIX = "step_"
_TMP_PREFIX = "tmp."
_PUB_PREFIX = "publish."
_FORMAT = 2


class CheckpointCorruptError(RuntimeError):
    """A committed checkpoint failed verification. Structured: `.leaf`
    names the failing manifest key (None = the manifest itself), `.step`
    and `.path` locate the checkpoint."""

    def __init__(self, message: str, *, leaf: Optional[str] = None,
                 step: Optional[int] = None, path: Optional[str] = None):
        self.leaf = leaf
        self.step = step
        self.path = path
        super().__init__(message)


# ------------------------------------------------------- state flattening

def _flatten(state: Dict[str, Any], prefix: str = "") -> Dict[str, Any]:
    """Nested dicts -> {"a/b/c": leaf}. Keys must be str without '/'."""
    out: Dict[str, Any] = {}
    for k, v in state.items():
        if not isinstance(k, str) or "/" in k:
            raise ValueError(f"checkpoint keys must be '/'-free strings, "
                             f"got {k!r}")
        key = prefix + k
        if isinstance(v, dict):
            out.update(_flatten(v, key + "/"))
        else:
            out[key] = v
    return out


def _unflatten(flat: Dict[str, Any]) -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    for key, v in flat.items():
        node = out
        parts = key.split("/")
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = v
    return out


def _to_host(v):
    """Leaf -> host value: arrays become numpy (THE deliberate
    device->host sync of the checkpoint path), scalars pass through."""
    if isinstance(v, (bool, int, float, str)) or v is None:
        return v
    if hasattr(v, "_data"):           # paddle_tpu Tensor, no import needed
        v = v._data
    if isinstance(v, (np.integer, np.floating, np.bool_)):
        return v.item()  # lint: allow(tracer-item)
    # device array / numpy array: gather to host. At save time syncing is
    # the job — this is the allowlisted host-transfer site of the r11 lint
    return np.asarray(v)  # lint: allow(tracer-asarray)


def _crc(data) -> int:
    return zlib.crc32(data) & 0xFFFFFFFF


def _deprioritize_current_thread():
    """Drop the calling thread's CPU priority (Linux: per-thread nice).
    The async writer runs serialization/crc/IO concurrently with
    training; on a host where compute is CPU-bound (the CPU toy — or a
    TPU host doing data loading) a niced writer only consumes cycles the
    training threads leave idle, which is what makes async_save ≈ free."""
    try:
        os.setpriority(os.PRIO_PROCESS, threading.get_native_id(), 10)
    except (AttributeError, OSError, PermissionError):
        pass


class AsyncHandle:
    """Returned by save(async_save=True): `wait()` blocks until the
    commit is durable and re-raises any writer-thread exception;
    `done()` polls. The snapshot was taken before save() returned — the
    training loop may donate/overwrite its arrays immediately."""

    def __init__(self, thread: threading.Thread, box: dict):
        self._thread = thread
        self._box = box

    def wait(self):
        self._thread.join()
        if self._box.get("exc") is not None:
            raise self._box["exc"]
        return self._box.get("path")

    def done(self) -> bool:
        return not self._thread.is_alive()

    def cancel(self):
        """Ask the writer to abort BEFORE it publishes: a commit that is
        not yet discoverable is discarded (tmp removed, no GC); one that
        already published stays — cancel never deletes a committed
        checkpoint."""
        ev = self._box.get("cancel")
        if ev is not None:
            ev.set()


class CheckpointManager:
    """See module docstring.

    manager = CheckpointManager(dir, keep_last=3, keep_every=100)
    manager.save(step, state_dict)              # atomic, verified
    h = manager.save(step, state, async_save=True); ... ; h.wait()
    step, state = manager.restore_latest()      # newest INTACT ckpt

    `state` is a nested dict of arrays/Tensors/python scalars.
    `chaos`: a chaos.Injector — fault sites fire through it (tests).
    `retry_deadline`: transient-IO budget per file operation.
    """

    def __init__(self, directory: str, *, keep_last: Optional[int] = None,
                 keep_every: Optional[int] = None,
                 chaos: Optional[Injector] = None,
                 retry_deadline: float = 5.0,
                 retry_base_delay: float = 0.01,
                 durability: str = "process",
                 _retry_sleep=None):
        # durability model: "process" (default) — atomic against process
        # death (preemption/SIGKILL/crash): written bytes survive the
        # process, os.replace publishes, no fsync anywhere — the commit
        # costs two renames-worth of syscalls, so async saves overlap
        # training with near-zero on-thread tax. "power" — additionally
        # fsync every leaf + manifest + COMMIT + the directories, so a
        # committed checkpoint survives kernel panic / power loss; use for
        # the long-horizon archive tier (keep_every), not the per-minute
        # preemption tier.
        if durability not in ("process", "power"):
            raise ValueError(f"durability must be 'process' or 'power', "
                             f"got {durability!r}")
        self.directory = os.path.abspath(directory)
        self.keep_last = keep_last
        self.keep_every = keep_every
        self.durability = durability
        self.chaos = chaos
        self.retry_deadline = retry_deadline
        self.retry_base_delay = retry_base_delay
        self._retry_sleep = _retry_sleep   # tests: no real sleeping
        # goodput accounting (profiler.timeline): the manager records the
        # time the CALLER pays — `ckpt_blocking` for a sync commit / the
        # async host snapshot, `ckpt_drain` for blocking on the writer
        # thread (wait/discard). The writer thread's own overlapped work
        # is deliberately NOT badput. Explicit recorder here, or the
        # process-wide installed one.
        self.timeline = None
        # HBM ledger (ISSUE 18): when attached, an async save's host
        # snapshot registers as the `ckpt_inflight` owner (host tier —
        # the copy lives in RAM, not HBM) for the writer's lifetime
        self.memz = None
        self._inflight: Optional[AsyncHandle] = None
        # serializes the save()/wait()/discard_inflight() handoff of
        # _inflight — the fallback manager behind dist_save is shared
        # across callers, and two racing saves must not both pass wait()
        # and then overwrite each other's handle (the loser's writer
        # would be orphaned and killed at interpreter exit mid-commit).
        # RLock: save() re-enters through its own wait().
        self._save_lock = threading.RLock()
        self._lock = threading.Lock()
        os.makedirs(self.directory, exist_ok=True)
        # finish any publish.<step>.* rename a previous process's kill cut
        # short (see _write_commit: a sealed publish dir IS committed)
        with self._lock:
            self._recover_locked()

    # ------------------------------------------------------------ naming
    def _step_dir(self, step: int) -> str:
        return os.path.join(self.directory, f"{_STEP_PREFIX}{step:08d}")

    def all_steps(self) -> List[int]:
        """Committed steps, ascending. Uncommitted/tmp dirs are invisible
        — the atomicity contract's read side. Sealed ``publish.<step>.*``
        dirs (a re-save whose final rename was cut short) count as
        committed: at every kill point some dir holds the step."""
        out = set()
        try:
            names = os.listdir(self.directory)
        except FileNotFoundError:
            return []
        for name in names:
            if name.startswith(_STEP_PREFIX):
                raw = name[len(_STEP_PREFIX):]
            elif name.startswith(_PUB_PREFIX):
                raw = name[len(_PUB_PREFIX):].split(".", 1)[0]
            else:
                continue
            path = os.path.join(self.directory, name)
            if not os.path.exists(os.path.join(path, COMMIT)):
                continue
            try:
                out.add(int(raw))
            except ValueError:
                continue
        return sorted(out)

    def _resolve_step_path(self, step: int) -> Optional[str]:
        """Directory holding committed checkpoint `step`. A sealed
        publish dir is the NEWER save of the step (its final rename was
        interrupted), so it wins over an existing step_ dir."""
        try:
            names = os.listdir(self.directory)
        except FileNotFoundError:
            return None
        pub_prefix = f"{_PUB_PREFIX}{step:08d}."
        for name in names:
            if name.startswith(pub_prefix):
                path = os.path.join(self.directory, name)
                if os.path.exists(os.path.join(path, COMMIT)):
                    return path
        final = self._step_dir(step)
        if os.path.exists(os.path.join(final, COMMIT)):
            return final
        return None

    def latest_step(self) -> Optional[int]:
        steps = self.all_steps()
        return steps[-1] if steps else None

    def latest(self) -> Optional[str]:
        """Path of the newest COMMITTED checkpoint dir (None if empty)."""
        step = self.latest_step()
        return None if step is None else self._resolve_step_path(step)

    # -------------------------------------------------------------- save
    def save(self, step: int, state: Dict[str, Any], *,
             async_save: bool = False, meta: Optional[dict] = None):
        """Atomically persist `state` as checkpoint `step`.

        Sync: returns the committed directory path. Async: snapshots to
        host NOW (so donated buffers may be reused immediately), then
        writes/commits on a background thread; returns an AsyncHandle.
        Saves serialize: a new save first waits for the in-flight one
        (two concurrent commits could GC each other's tmp dirs)."""
        with self._save_lock:
            return self._save_locked(step, state, async_save=async_save,
                                     meta=meta)

    def _tl(self):
        tl = self.timeline
        if tl is not None:
            return tl
        # lazy: this module stays importable before jax initializes, and
        # importing paddle_tpu.profiler pulls jax in
        from ..profiler.timeline import current as _tl_current
        return _tl_current()

    def _save_locked(self, step, state, *, async_save, meta):
        self.wait()                      # records its own ckpt_drain
        tl = self._tl()
        t0 = tl.now() if tl is not None else None
        flat = _flatten(state)
        leaves: Dict[str, np.ndarray] = {}
        scalars: Dict[str, Any] = {}
        for key, v in flat.items():
            hv = _to_host(v)
            if isinstance(hv, np.ndarray):
                # async: the snapshot must OWN its bytes — np.asarray of
                # a numpy/CPU-jax leaf can be a zero-copy view, and the
                # caller is promised it may donate/overwrite immediately
                # after save() returns. This memcpy IS the documented
                # on-thread snapshot cost (~1ms/MB).
                leaves[key] = hv.copy() if async_save else hv
            else:
                scalars[key] = hv
        if not async_save:
            try:
                return self._write_commit(int(step), leaves, scalars, meta)
            finally:
                if tl is not None:
                    tl.record("ckpt_blocking", t0, tl.now(),
                              step=int(step), mode="sync")
        box: dict = {"cancel": threading.Event()}
        memz = self.memz
        if memz is not None:
            snap_bytes = sum(a.nbytes for a in leaves.values())
            memz.set("ckpt_inflight", snap_bytes, kind="checkpoint",
                     device=False)

        def writer():
            _deprioritize_current_thread()
            try:
                box["path"] = self._write_commit(int(step), leaves,
                                                 scalars, meta,
                                                 cancel=box["cancel"])
            except BaseException as e:   # surfaced by handle.wait()
                box["exc"] = e
            finally:
                if memz is not None:
                    # committed or died, the snapshot is no longer held
                    memz.set("ckpt_inflight", 0)

        t = threading.Thread(target=writer, daemon=True,
                             name=f"ckpt-save-{step}")
        handle = AsyncHandle(t, box)
        self._inflight = handle
        t.start()
        if tl is not None:
            # the on-thread cost of an async save ends here: snapshot +
            # writer dispatch. Serialization/commit overlap training on
            # the niced writer and are not badput.
            tl.record("ckpt_blocking", t0, tl.now(), step=int(step),
                      mode="async_snapshot")
        return handle

    def wait(self):
        """Block until any in-flight async save committed (re-raising its
        failure). The emergency-checkpoint path calls this first: a
        preemption must not race its own background save."""
        with self._save_lock:
            # join INSIDE the lock: a second waiter that saw _inflight
            # already None must still not start a new save while the
            # first waiter is joining the old writer
            h, self._inflight = self._inflight, None
            if h is not None:
                tl = self._tl()
                t0 = tl.now() if tl is not None else None
                try:
                    h.wait()
                finally:
                    if tl is not None:
                        tl.record("ckpt_drain", t0, tl.now())

    def discard_inflight(self):
        """Chaos fidelity: a SimulatedKill at step k models a SIGKILL at
        that instant — the writer thread would have died mid-commit, so a
        save still in flight AT the kill must not land post-mortem (it
        would let a simulated kill resume from a checkpoint a real kill
        never produced). A save whose commit already PUBLISHED is
        legitimately durable and is kept — cancellation happens before
        the publish rename (inside _write_commit), never by deleting a
        committed step dir, so the previous checkpoint can never be
        GC'd away and then the new one dropped (which would leave ZERO
        checkpoints — a state no real SIGKILL can produce).
        tools/chaos_train.py calls this when it catches SimulatedKill."""
        with self._save_lock:
            h, self._inflight = self._inflight, None
            if h is None:
                return
            h.cancel()
            tl = self._tl()
            t0 = tl.now() if tl is not None else None
            try:
                h.wait()
            except BaseException:
                pass                     # writer died on its own: no commit
            finally:
                if tl is not None:
                    tl.record("ckpt_drain", t0, tl.now(), discarded=True)

    # I/O primitives: every one fires the injector and retries transients
    def _fire(self, site: str, **ctx):
        if self.chaos is not None:
            self.chaos.fire(site, **ctx)

    def _retry(self, fn, *args, **kwargs):
        return retry(fn, *args, deadline=self.retry_deadline,
                     base_delay=self.retry_base_delay,
                     **({"sleep": self._retry_sleep}
                        if self._retry_sleep is not None else {}),
                     **kwargs)

    def _write_bytes(self, path: str, data: bytes):
        fsync = self.durability == "power"

        def write():
            self._fire("ckpt.io", path=path)
            with open(path, "wb") as f:
                f.write(data)
                if fsync:
                    f.flush()
                    os.fsync(f.fileno())
        self._retry(write)

    def _write_commit(self, step: int, leaves: Dict[str, np.ndarray],
                      scalars: Dict[str, Any],
                      meta: Optional[dict],
                      cancel: Optional[threading.Event] = None
                      ) -> Optional[str]:
        with self._lock:
            if cancel is not None and cancel.is_set():
                return None          # discarded before any bytes landed
            # normalize any interrupted publish FIRST: a stale sealed
            # publish dir must land (or be discarded) before this save
            # decides whether `final` exists — otherwise the recovery in
            # _gc_locked below could clobber the checkpoint we are about
            # to write with the older interrupted one
            self._recover_locked()
            tmp = os.path.join(self.directory,
                               f"{_TMP_PREFIX}{uuid.uuid4().hex}")
            os.makedirs(tmp)
            manifest = {"format": _FORMAT, "step": step,
                        "data_file": DATA_FILE,
                        "leaves": {}, "scalars": scalars,
                        "meta": meta or {}}
            data_path = os.path.join(tmp, DATA_FILE)

            def write_leaves():
                self._fire("ckpt.io", path=data_path)
                entries: Dict[str, dict] = {}
                offset = 0
                with open(data_path, "wb") as f:
                    for i, (key, arr) in enumerate(sorted(leaves.items())):
                        # zero-copy: write/crc the array's buffer directly
                        # (tobytes() would duplicate every leaf; on the
                        # async path this thread competes with training
                        # for CPU, so copies are overhead twice over).
                        # ml_dtypes leaves (bfloat16/float8) have no
                        # buffer protocol — those fall back to tobytes.
                        # shape is captured BEFORE ascontiguousarray,
                        # which promotes 0-d arrays to (1,) — a scalar
                        # leaf must restore as a scalar or the resumed
                        # pytree's avals change and force a recompile
                        shape = list(np.shape(arr))
                        arr = np.ascontiguousarray(arr)
                        try:
                            data = memoryview(arr).cast("B")
                        except (ValueError, TypeError):
                            data = arr.tobytes()
                        f.write(data)
                        entries[key] = {
                            "offset": offset, "nbytes": len(data),
                            "dtype": str(arr.dtype),
                            "shape": shape, "crc32": _crc(data)}
                        offset += len(data)
                        # fault site: this leaf's bytes just landed —
                        # TruncateDuringSave flushes-then-tears the data
                        # file here / kills, proving torn tmp dirs are
                        # inert
                        if self.chaos is not None:
                            f.flush()
                            self._fire("ckpt.leaf", step=step, leaf=key,
                                       index=i, path=data_path)
                    if self.durability == "power":
                        f.flush()
                        os.fsync(f.fileno())
                return entries
            manifest["leaves"] = self._retry(write_leaves)
            # compact separators: indent forces json's python-level
            # encoder (~9ms for a 90-leaf manifest vs ~0.5ms compact) —
            # writer-thread CPU is contention on a saturated host
            mbytes = json.dumps(manifest, sort_keys=True,
                                separators=(",", ":")).encode()
            self._write_bytes(os.path.join(tmp, MANIFEST), mbytes)
            self._fire("ckpt.manifest", step=step, path=tmp)
            # COMMIT seals the manifest (its crc) INSIDE tmp, then one
            # atomic rename publishes: presence of the final dir name
            # implies a full, sealed checkpoint
            self._write_bytes(os.path.join(tmp, COMMIT),
                              json.dumps({"step": step,
                                          "manifest_crc32": _crc(mbytes)
                                          }).encode())
            if self.durability == "power":
                self._fsync_dir(tmp)
            self._fire("ckpt.pre_commit", step=step, path=tmp)
            if cancel is not None and cancel.is_set():
                # discard_inflight beat the publish: the save must not
                # become discoverable post-mortem. No publish, no GC —
                # the previous checkpoint stays authoritative.
                shutil.rmtree(tmp, ignore_errors=True)
                return None
            final = self._step_dir(step)
            if os.path.exists(final):
                # re-save of the same step wins — but the old committed
                # dir must stay authoritative until the new one is
                # discoverable. Rename tmp to a sealed publish.<step>.*
                # dir first (committed from this instant: all_steps and
                # restore see it), THEN drop the old and take its name.
                # A kill between the renames leaves the publish dir;
                # _recover_locked finishes the swap on the next
                # manager/gc. At no kill point does the step lack a
                # committed checkpoint.
                pub = os.path.join(
                    self.directory,
                    f"{_PUB_PREFIX}{step:08d}.{uuid.uuid4().hex}")
                self._retry(os.replace, tmp, pub)
                self._fire("ckpt.publish", step=step, path=pub)
                shutil.rmtree(final)
                self._retry(os.replace, pub, final)
            else:
                self._retry(os.replace, tmp, final)
            if self.durability == "power":
                self._fsync_dir(self.directory)
            self._gc_locked()
            return final

    @staticmethod
    def _fsync_dir(path: str):
        try:
            fd = os.open(path, os.O_RDONLY)
        except OSError:          # platforms without dir fds
            return
        try:
            os.fsync(fd)
        except OSError:
            pass
        finally:
            os.close(fd)

    # ---------------------------------------------------------------- gc
    def gc(self):
        """Apply retention + sweep tmp orphans (also runs after every
        commit). keep_last=N keeps the N newest; keep_every=K
        additionally keeps step % K == 0 — the cheap long-horizon
        archive. The newest committed step ALWAYS survives (a
        keep_every-only config must never delete the checkpoint a resume
        needs). No retention config = keep everything."""
        with self._lock:
            self._gc_locked()

    def _recover_locked(self):
        """Finish interrupted publishes: a sealed publish.<step>.* dir is
        a COMMITTED re-save whose final rename was cut short — complete
        the swap (the newer save wins over the step_ dir it was
        replacing); an unsealed one is torn — discard it."""
        try:
            names = os.listdir(self.directory)
        except FileNotFoundError:
            return
        for name in names:
            if not name.startswith(_PUB_PREFIX):
                continue
            path = os.path.join(self.directory, name)
            raw = name[len(_PUB_PREFIX):].split(".", 1)[0]
            try:
                step = int(raw)
            except ValueError:
                step = None
            if step is None or \
                    not os.path.exists(os.path.join(path, COMMIT)):
                shutil.rmtree(path, ignore_errors=True)
                continue
            final = self._step_dir(step)
            if os.path.exists(final):
                shutil.rmtree(final)
            os.replace(path, final)

    def _gc_locked(self):
        self._recover_locked()
        for name in os.listdir(self.directory):
            if name.startswith(_TMP_PREFIX):
                shutil.rmtree(os.path.join(self.directory, name),
                              ignore_errors=True)
        if self.keep_last is None and self.keep_every is None:
            return
        steps = self.all_steps()
        # the newest step always survives; keep_last=0 / keep_last=None +
        # keep_every means "only the archive tier (plus the newest)" —
        # NOT "keep everything" (a falsy keep_last must not disable
        # retention that was explicitly configured)
        keep = {steps[-1]} if steps else set()
        if self.keep_last:
            keep |= set(steps[-self.keep_last:])
        if self.keep_every:
            keep |= {s for s in steps if s % self.keep_every == 0}
        for s in steps:
            if s not in keep:
                shutil.rmtree(self._step_dir(s), ignore_errors=True)

    # ------------------------------------------------------------ restore
    def restore(self, step: Optional[int] = None,
                verify: bool = True) -> Tuple[int, Dict[str, Any]]:
        """Load checkpoint `step` (default: newest committed) into a
        nested dict of numpy arrays + python scalars. `verify=True`
        (default) checksums the manifest against COMMIT and every leaf
        against the manifest — a mismatch raises CheckpointCorruptError
        naming the bad leaf. Verification reads every byte anyway to
        build arrays, so it is nearly free.

        Restored arrays are READ-ONLY zero-copy views over one shared
        blob (peak RAM = 1x the checkpoint, not 2x) — `.copy()` a leaf
        before in-place surgery; feeding them to jnp.asarray /
        set_state_dict copies onto device anyway."""
        if step is None:
            step = self.latest_step()
            if step is None:
                raise FileNotFoundError(
                    f"no committed checkpoint in {self.directory}")
        path = self._resolve_step_path(step)
        if path is None:
            raise FileNotFoundError(
                f"checkpoint step {step} is not committed in "
                f"{self.directory}")
        mbytes = self._read(os.path.join(path, MANIFEST), step, path)
        if verify:
            commit = json.loads(self._read(os.path.join(path, COMMIT),
                                           step, path))
            if commit.get("manifest_crc32") != _crc(mbytes):
                raise CheckpointCorruptError(
                    f"checkpoint step {step}: manifest checksum mismatch "
                    f"({path})", leaf=None, step=step, path=path)
        manifest = json.loads(mbytes)
        flat: Dict[str, Any] = dict(manifest.get("scalars", {}))
        blob = memoryview(b"")
        if manifest["leaves"]:
            # memoryview: bytes-slicing every leaf would transiently
            # hold ~2x the checkpoint in RAM (crc32 and np.frombuffer
            # both accept views)
            blob = memoryview(self._read(
                os.path.join(path, manifest.get("data_file", DATA_FILE)),
                step, path))
        for key, entry in manifest["leaves"].items():
            off = entry["offset"]
            data = blob[off:off + entry["nbytes"]]
            if verify and (len(data) != entry["nbytes"]
                           or _crc(data) != entry["crc32"]):
                raise CheckpointCorruptError(
                    f"checkpoint step {step}: leaf {key!r} failed "
                    f"verification (dtype={entry['dtype']}, "
                    f"shape={entry['shape']}, offset={off}): "
                    f"expected {entry['nbytes']}B crc {entry['crc32']}, "
                    f"got {len(data)}B crc {_crc(data)}",
                    leaf=key, step=step, path=path)
            # jnp.dtype resolves ml_dtypes names (bfloat16/float8) that
            # plain numpy does not know — lazy import keeps this module
            # importable before jax initializes
            try:
                dt = np.dtype(entry["dtype"])
            except TypeError:
                import jax.numpy as jnp
                dt = np.dtype(jnp.dtype(entry["dtype"]))
            flat[key] = np.frombuffer(data, dtype=dt).reshape(
                entry["shape"])
        return manifest["step"], _unflatten(flat)

    def restore_latest(self, fallback: bool = True,
                       verify: bool = True) -> Tuple[int, Dict[str, Any]]:
        """Restore the newest committed checkpoint; with `fallback` (the
        default) a corrupt one is skipped and the next older tried — a
        resuming job prefers losing a few steps over dying on bitrot.
        Raises the newest corruption error if nothing intact remains."""
        steps = self.all_steps()
        if not steps:
            raise FileNotFoundError(
                f"no committed checkpoint in {self.directory}")
        last_err: Optional[Exception] = None
        for s in reversed(steps):
            try:
                return self.restore(s, verify=verify)
            except CheckpointCorruptError as e:
                last_err = last_err or e
                if not fallback:
                    raise
        raise last_err

    def _read(self, path: str, step: int, ckpt_path: str,
              leaf: Optional[str] = None) -> bytes:
        # a missing file is corruption, not a transient: fail immediately
        # instead of burning the retry deadline on ENOENT
        if not os.path.exists(path):
            raise CheckpointCorruptError(
                f"checkpoint step {step}: missing file {path}",
                leaf=leaf, step=step, path=ckpt_path)

        def read():
            self._fire("ckpt.io", path=path)
            with open(path, "rb") as f:
                return f.read()
        return self._retry(read)


# ------------------------------------------------- plain-file atomic write

def atomic_write_bytes(path: str, data: bytes, fsync: bool = False):
    """tmp-then-rename write for SINGLE files (framework.io.save path):
    a kill at any byte leaves either the old file or the new one, never
    a truncation. Same-directory tmp so os.replace stays one atom."""
    with atomic_writer(path, fsync=fsync) as f:
        f.write(data)


class atomic_writer:
    """Context manager giving a binary file handle whose contents only
    replace `path` on a CLEAN exit (flush + os.replace, one atom); any
    exception — including SimulatedKill — discards the tmp file and
    leaves the previous `path` bytes untouched. The streaming form of
    atomic_write_bytes: pickle/json writers dump straight into it
    without staging the whole payload in memory.

    `fsync=False` (default) is the "process" durability tier: atomic
    against process death, no fsync stall on every save (the same
    threat-model default as CheckpointManager). Pass fsync=True for the
    power-loss tier."""

    def __init__(self, path: str, fsync: bool = False):
        # write THROUGH a symlinked target (plain open(path,'wb') did):
        # os.replace over the link itself would destroy the link and
        # land the bytes beside it instead of where it points
        self.path = os.path.realpath(path)
        self._fsync = fsync
        d = os.path.dirname(self.path) or "."
        self.tmp = os.path.join(
            d, f".{os.path.basename(self.path)}.tmp.{uuid.uuid4().hex}")
        self._f = None

    def __enter__(self):
        d = os.path.dirname(self.path)
        if d:
            os.makedirs(d, exist_ok=True)
        # sweep orphans from REAL kills first (SimulatedKill unwinds
        # through __exit__, a SIGKILL mid-save does not — without this a
        # preemption-heavy fleet leaks a full-size tmp per interrupted
        # save, forever). Concurrent writers to the SAME target already
        # race on os.replace; sequential periodic saves are the contract.
        prefix = f".{os.path.basename(self.path)}.tmp."
        try:
            for name in os.listdir(os.path.dirname(self.path) or "."):
                if name.startswith(prefix):
                    try:
                        os.unlink(os.path.join(
                            os.path.dirname(self.path) or ".", name))
                    except OSError:
                        pass
        except OSError:
            pass
        self._f = open(self.tmp, "wb")
        return self._f

    def __exit__(self, exc_type, exc, tb):
        try:
            if exc_type is None:
                self._f.flush()
                if self._fsync:
                    os.fsync(self._f.fileno())
                self._f.close()
                try:
                    # os.replace discards the target's existing mode
                    # (e.g. a group-writable shared checkpoint) for the
                    # tmp file's umask default — carry it over
                    os.chmod(self.tmp,
                             os.stat(self.path).st_mode & 0o7777)
                except OSError:
                    pass                 # no previous file: umask rules
                os.replace(self.tmp, self.path)
            else:
                self._f.close()
        finally:
            if os.path.exists(self.tmp):
                try:
                    os.unlink(self.tmp)
                except OSError:
                    pass
        return False
