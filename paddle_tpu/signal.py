"""paddle.signal namespace (reference: python/paddle/signal.py — stft/istft
built on frame/overlap_add ops). TPU-native: expressed as jnp strided
framing + rfft; XLA lowers both to fused gathers + batched FFT custom calls.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from .core.tensor import Tensor, apply_op

__all__ = ["stft", "istft"]


def _frame(a, frame_length, hop):
    n_frames = 1 + (a.shape[-1] - frame_length) // hop
    idx = (np.arange(frame_length)[None, :] +
           hop * np.arange(n_frames)[:, None])
    return a[..., idx]          # [..., n_frames, frame_length]


def stft(x, n_fft, hop_length=None, win_length=None, window=None,
         center=True, pad_mode="reflect", normalized=False, onesided=True,
         name=None):
    """reference: signal.py stft — returns [..., n_fft//2+1, n_frames]
    complex (onesided) like the reference."""
    hop = hop_length or n_fft // 4
    wl = win_length or n_fft
    warr = None if window is None else (
        window._data if isinstance(window, Tensor) else jnp.asarray(window))

    def fn(a, *w):
        if center:
            pad = n_fft // 2
            a = jnp.pad(a, [(0, 0)] * (a.ndim - 1) + [(pad, pad)],
                        mode=pad_mode)
        frames = _frame(a, n_fft, hop)              # [..., T, n_fft]
        if w:
            win = w[0]
            if wl < n_fft:   # center-pad window to n_fft
                lp = (n_fft - wl) // 2
                win = jnp.pad(win, (lp, n_fft - wl - lp))
            frames = frames * win
        sp = jnp.fft.rfft(frames, axis=-1) if onesided else \
            jnp.fft.fft(frames, axis=-1)
        if normalized:
            sp = sp / np.sqrt(n_fft)
        return jnp.swapaxes(sp, -1, -2)             # [..., freq, T]
    args = [x] + ([Tensor(warr)] if warr is not None else [])
    return apply_op("stft", fn, args)


def istft(x, n_fft, hop_length=None, win_length=None, window=None,
          center=True, normalized=False, onesided=True, length=None,
          return_complex=False, name=None):
    """reference: signal.py istft — overlap-add inverse with window
    normalization (COLA)."""
    hop = hop_length or n_fft // 4
    wl = win_length or n_fft
    warr = None if window is None else (
        window._data if isinstance(window, Tensor) else jnp.asarray(window))

    def fn(sp, *w):
        sp = jnp.swapaxes(sp, -1, -2)               # [..., T, freq]
        if normalized:
            sp = sp * np.sqrt(n_fft)
        frames = jnp.fft.irfft(sp, n=n_fft, axis=-1) if onesided else \
            jnp.fft.ifft(sp, axis=-1).real
        if w:
            win = w[0]
            if wl < n_fft:
                lp = (n_fft - wl) // 2
                win = jnp.pad(win, (lp, n_fft - wl - lp))
        else:
            win = jnp.ones((n_fft,), frames.dtype)
        frames = frames * win
        T = frames.shape[-2]
        out_len = n_fft + hop * (T - 1)
        lead = frames.shape[:-2]
        out = jnp.zeros(lead + (out_len,), frames.dtype)
        wsum = jnp.zeros((out_len,), frames.dtype)
        for t in range(T):     # static unroll: T known at trace time
            sl = slice(t * hop, t * hop + n_fft)
            out = out.at[..., sl].add(frames[..., t, :])
            wsum = wsum.at[sl].add(win * win)
        out = out / jnp.maximum(wsum, 1e-11)
        if center:
            pad = n_fft // 2
            out = out[..., pad:out_len - pad]
        if length is not None:
            out = out[..., :length]
        return out
    args = [x] + ([Tensor(warr)] if warr is not None else [])
    return apply_op("istft", fn, args)
