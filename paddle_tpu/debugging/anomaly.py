"""Anomaly detection over the numerics stats stream.

Consumes StatsTree fetches (plus the loss / global grad-norm scalars the
step already produces) and turns them into structured NumericsEvents:

  nan / inf        — a stats row counted non-finite values; the event names
                     the offending layer's qualified path
  grad_explosion   — global grad norm is a rolling-z-score outlier
  loss_spike       — loss is a rolling-z-score outlier (or non-finite)
  dead_layer       — an activation row's absmax collapsed to ~0

The detectors are host-side and only run when stats are actually fetched
(every N steps / on demand), so the compiled hot path never pays for them.
Reference analog: the TensorCheckerConfig debug modes (CHECK_NAN_INF_AND_ABORT
etc.) of paddle.amp.debugging — here abort is one policy (raise_on_event)
rather than the only one.
"""
from __future__ import annotations

import collections
import json
import math
import time
from typing import Callable, Dict, List, Optional

from .sentinel import StatsTree


class NumericsEvent:
    """One detected numerics anomaly (structured; JSONL-friendly)."""

    __slots__ = ("kind", "step", "path", "value", "message", "details", "ts")

    def __init__(self, kind: str, step: int, path: Optional[str] = None,
                 value: Optional[float] = None, message: str = "",
                 details: Optional[dict] = None):
        self.kind = kind
        self.step = step
        self.path = path
        self.value = value
        self.message = message
        self.details = details or {}
        self.ts = time.time()

    def to_dict(self) -> dict:
        return {"kind": self.kind, "step": self.step, "path": self.path,
                "value": self.value, "message": self.message,
                "details": self.details, "ts": self.ts}

    def __repr__(self):
        loc = f" at {self.path}" if self.path else ""
        return f"NumericsEvent({self.kind}{loc}, step={self.step}: {self.message})"


class _Rolling:
    """Rolling mean/std window for z-score outlier tests."""

    def __init__(self, window: int):
        self.buf = collections.deque(maxlen=window)

    def zscore(self, x: float) -> Optional[float]:
        n = len(self.buf)
        if n < 2:
            return None
        mean = sum(self.buf) / n
        var = sum((v - mean) ** 2 for v in self.buf) / n
        std = math.sqrt(var)
        # floor the std so a perfectly flat history doesn't turn numerical
        # dust into an infinite z-score
        std = max(std, 1e-3 * abs(mean), 1e-12)
        return (x - mean) / std

    def push(self, x: float):
        self.buf.append(x)


class AnomalyDetector:
    """Stateful detector; call observe() with each fetched sample.

    min_history: z-score detectors stay silent until this many finite
    samples are in the window (a cold-start loss drop is not a spike).
    Non-finite rows fire every observation; dead_layer fires once per path
    until the layer comes back to life.
    """

    def __init__(self, window: int = 50, grad_z: float = 6.0,
                 loss_z: float = 6.0, dead_absmax: float = 1e-8,
                 min_history: int = 5):
        self.window = window
        self.grad_z = grad_z
        self.loss_z = loss_z
        self.dead_absmax = dead_absmax
        self.min_history = min_history
        self._grad = _Rolling(window)
        self._loss = _Rolling(window)
        self._dead_fired = set()
        self.events: List[NumericsEvent] = []

    # -- individual detectors -------------------------------------------
    def _nonfinite_events(self, step, tree: StatsTree) -> List[NumericsEvent]:
        out = []
        for path, r in tree.nonfinite_rows():
            kind = "nan" if r["nan"] else "inf"
            out.append(NumericsEvent(
                kind, step, path=path, value=r["nan"] or r["inf"],
                message=(f"{path}: {int(r['nan'])} NaN / {int(r['inf'])} Inf "
                         f"of {int(r['finite'] + r['nan'] + r['inf'])} elements"),
                details=r))
        return out

    def _dead_events(self, step, tree: StatsTree) -> List[NumericsEvent]:
        out = []
        for path, r in tree.rows():
            # activation rows only: zero grads and zero-init params
            # (biases!) are normal, a zero activation map is not
            if path.startswith(("grad:", "param:")):
                continue
            total = r["finite"] + r["nan"] + r["inf"]
            dead = total > 0 and not r["nan"] and not r["inf"] \
                and r["absmax"] <= self.dead_absmax
            if dead and path not in self._dead_fired:
                self._dead_fired.add(path)
                out.append(NumericsEvent(
                    "dead_layer", step, path=path, value=r["absmax"],
                    message=f"{path}: activation absmax {r['absmax']:.3g} ~ 0",
                    details=r))
            elif not dead:
                self._dead_fired.discard(path)
        return out

    def _scalar_event(self, step, kind, roll: _Rolling, x: Optional[float],
                      thresh: float) -> List[NumericsEvent]:
        if x is None:
            return []
        if not math.isfinite(x):
            return [NumericsEvent(kind, step, value=x,
                                  message=f"{kind.split('_')[0]} is {x}")]
        z = roll.zscore(x)
        fired = []
        if z is not None and len(roll.buf) >= self.min_history \
                and z > thresh:
            fired.append(NumericsEvent(
                kind, step, value=x,
                message=f"z-score {z:.1f} (window mean "
                        f"{sum(roll.buf) / len(roll.buf):.4g})",
                details={"zscore": z}))
        roll.push(x)
        return fired

    # -- entry point ----------------------------------------------------
    def observe(self, step: int, tree: Optional[StatsTree] = None,
                loss: Optional[float] = None,
                grad_norm: Optional[float] = None) -> List[NumericsEvent]:
        events: List[NumericsEvent] = []
        if tree is not None:
            events += self._nonfinite_events(step, tree)
            events += self._dead_events(step, tree)
        events += self._scalar_event(step, "loss_spike", self._loss, loss,
                                     self.loss_z)
        events += self._scalar_event(step, "grad_explosion", self._grad,
                                     grad_norm, self.grad_z)
        self.events.extend(events)
        return events


def write_events_jsonl(events, path: str):
    with open(path, "a") as f:
        for e in events:
            f.write(json.dumps(e.to_dict()) + "\n")
