"""paddle_tpu.debugging — numerics observability that works INSIDE jit.

PR 2 gave the framework performance observability (trace analytics, MFU /
HBM / recompile telemetry); this package is the correctness half: when a
10k-step run NaNs at step 7,312 it tells you which layer, which quantity,
and hands you a replayable dump — without a host sync per step.

Three pieces:

  sentinel  — per-layer tensor stats (finite/nan/inf counts, absmax, mean,
              l2) reduced ON DEVICE and threaded out of the compiled
              TrainStep as one compact [rows, 6] array. Install with
              ``check_layer_numerics(model)``; TrainStep(numerics=...) does
              it for you and adds per-layer grad rows + the in-graph
              found-inf scalar dynamic loss scaling keys off.
  anomaly   — host-side detectors over the fetched stream: NaN/Inf naming
              the layer path, grad-norm explosion (rolling z-score), loss
              spike, dead layer. Each fires a structured NumericsEvent.
  dump      — on a firing event, the offending batch + params/opt-state +
              step + RNG key + stats tree persist to ``dump_dir``;
              ``tools/replay_dump.py`` replays the failure standalone.

Typical wiring::

    cfg = debugging.NumericsConfig(every_n_steps=10, dump_dir="dumps/")
    step = TrainStep(model, opt, loss_fn, numerics=cfg)
    ...
    step.numerics_stats()        # on-demand fetch -> StatsTree
    cfg.detector.events          # everything that fired

The legacy surface (paddle.amp.debugging.check_numerics,
TensorCheckerConfig, FLAGS_check_nan_inf) is a facade over this package —
see paddle_tpu/amp/debugging.py.
"""
from __future__ import annotations

from typing import Callable, Optional

from .sentinel import (STAT_NAMES, N_STATS, StatsTree, StatsCollector,
                       array_stats, merge_stat_rows, merge_stacked,
                       collect_stats, active_collector, check_layer_numerics,
                       grad_layer_groups, grad_stat_rows, found_inf,
                       model_param_stats)
from .anomaly import AnomalyDetector, NumericsEvent, write_events_jsonl
from .dump import (write_dump, load_dump, replay, Dump, ReplayResult,
                   tree_spec, tree_build)

__all__ = [
    "STAT_NAMES", "N_STATS", "StatsTree", "StatsCollector", "array_stats",
    "collect_stats", "active_collector", "check_layer_numerics",
    "found_inf", "model_param_stats", "AnomalyDetector", "NumericsEvent",
    "write_events_jsonl", "write_dump", "load_dump", "replay", "Dump",
    "ReplayResult", "NumericsConfig",
]


class NumericsConfig:
    """Configuration for TrainStep's numerics mode (and NumericsCallback).

    every_n_steps: fetch + detect cadence. 0 = never automatically — stats
        still ride along as device arrays and ``TrainStep.numerics_stats()``
        fetches on demand; the hot path pays only the on-device reductions.
    grad_stats: add per-layer gradient rows (and the global grad-norm
        scalar) to the stats tree.
    skip_nonfinite_updates: select away the parameter/optimizer update when
        the in-graph found-inf sentinel fires — parameters never ingest a
        NaN, so the dump on disk holds the exact pre-step state and the run
        can continue (GradScaler semantics; the reference's
        check_nan_inf-and-abort is `raise_on_nonfinite`).
    dump_dir: where anomaly dumps land (None = no dumps).
    detector / on_event / monitor: the AnomalyDetector consuming fetches, a
        callback fired per NumericsEvent, and a profiler.StepMonitor that
        records events + loss/grad-norm into its JSONL stream.
    raise_on_nonfinite: raise FloatingPointError on a fetched NaN/Inf event
        (after dumping) — FLAGS_check_nan_inf abort semantics under jit.
    """

    def __init__(self, every_n_steps: int = 0, grad_stats: bool = True,
                 skip_nonfinite_updates: bool = True,
                 dump_dir: Optional[str] = None,
                 detector: Optional[AnomalyDetector] = None,
                 on_event: Optional[Callable[[NumericsEvent], None]] = None,
                 monitor=None, raise_on_nonfinite: bool = False):
        self.every_n_steps = int(every_n_steps)
        self.grad_stats = grad_stats
        self.skip_nonfinite_updates = skip_nonfinite_updates
        self.dump_dir = dump_dir
        self.detector = detector or AnomalyDetector()
        self.on_event = on_event
        self.monitor = monitor
        self.raise_on_nonfinite = raise_on_nonfinite

    @classmethod
    def coerce(cls, numerics) -> Optional["NumericsConfig"]:
        """Normalize TrainStep's `numerics=` argument: None/False -> None,
        True -> defaults, a NumericsConfig passes through."""
        if numerics is None or numerics is False:
            return None
        if numerics is True:
            return cls()
        if isinstance(numerics, cls):
            return numerics
        if hasattr(numerics, "to_numerics_config"):   # TensorCheckerConfig
            return numerics.to_numerics_config()
        raise TypeError(
            f"numerics must be bool or NumericsConfig, got {type(numerics)}")
