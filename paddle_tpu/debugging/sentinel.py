"""In-graph numerics sentinels — per-tensor stats that live INSIDE jit.

The reference's training-health surface (FLAGS_check_nan_inf,
paddle.amp.debugging.check_numerics, nan_inf_utils.cc per-op scans) is an
eager host-side scan: every check is a device->host round trip, and none of
it exists once the step is one compiled XLA program. Here the check IS part
of the program: each instrumented layer reduces its output to a 6-float
stats row on device, the rows stack into one compact [rows, 6] float32
array threaded out of the jitted step as an ordinary output, and the host
only reads it when asked (every N steps or on demand) — zero per-step
syncs, a few scalar reductions of cost.

Stats columns (STAT_NAMES order):
  finite  — count of finite elements
  nan     — count of NaNs
  inf     — count of +/-Inf
  absmax  — max |x| over finite elements (0 if none)
  mean    — mean over finite elements
  l2      — sqrt(sum x^2) over finite elements

Non-finite values are masked out of absmax/mean/l2 so one NaN doesn't
poison the magnitudes the anomaly detectors (dead-layer, grad explosion)
read — the nan/inf counts carry the non-finite signal on their own.
"""
from __future__ import annotations

import contextlib
import threading
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np
import jax
import jax.numpy as jnp

STAT_NAMES = ("finite", "nan", "inf", "absmax", "mean", "l2")
N_STATS = len(STAT_NAMES)

_tls = threading.local()


def array_stats(a) -> jnp.ndarray:
    """[N_STATS] float32 stats row for one array (trace-safe).

    Five reductions per tensor (nan, inf, absmax, sum, sumsq — the finite
    count is derived), elementwise masks fused into them by XLA. Everything
    downstream (found-inf, the global grad norm) derives from these rows so
    the hot path never re-scans a tensor it already statted.

    The nan/inf masks are computed in the tensor's NATIVE dtype, so a
    finite float64 value beyond float32 range counts as finite; only the
    magnitude columns (absmax/mean/l2) reduce in float32 and may saturate
    to inf for such values."""
    x = a if jnp.issubdtype(a.dtype, jnp.floating) else a.astype(jnp.float32)
    isn = x != x
    absx = jnp.abs(x)
    isi = absx == jnp.inf
    nonfin = jnp.logical_or(isn, isi)
    n_nan = jnp.sum(isn, dtype=jnp.float32)
    n_inf = jnp.sum(isi, dtype=jnp.float32)
    n_fin = jnp.float32(x.size) - n_nan - n_inf
    xz = jnp.where(nonfin, 0.0, x).astype(jnp.float32)
    absmax = jnp.max(jnp.where(nonfin, 0.0, absx).astype(jnp.float32)) \
        if x.size else jnp.float32(0.0)
    mean = jnp.sum(xz) / jnp.maximum(n_fin, 1.0)
    l2 = jnp.sqrt(jnp.sum(xz * xz))
    return jnp.stack([n_fin, n_nan, n_inf, absmax, mean, l2])


def merge_stat_rows(rows: Sequence[jnp.ndarray]) -> jnp.ndarray:
    """Combine stats rows of DISJOINT arrays into one row (e.g. the grads of
    all params under one layer): counts add, absmax maxes, mean re-weights
    by finite count, l2 combines in quadrature."""
    st = jnp.stack(list(rows))                       # [k, N_STATS]
    n_fin = jnp.sum(st[:, 0])
    mean = jnp.sum(st[:, 0] * st[:, 4]) / jnp.maximum(n_fin, 1.0)
    return jnp.stack([n_fin, jnp.sum(st[:, 1]), jnp.sum(st[:, 2]),
                      jnp.max(st[:, 3]), mean,
                      jnp.sqrt(jnp.sum(st[:, 5] ** 2))])


def merge_stacked(stacked) -> jnp.ndarray:
    """Reduce [k, R, N_STATS] microbatch/step-stacked stats to [R, N_STATS]
    with merge_stat_rows semantics along axis 0 (grad-accum scan output)."""
    n_fin = jnp.sum(stacked[..., 0], axis=0)
    mean = jnp.sum(stacked[..., 0] * stacked[..., 4], axis=0) \
        / jnp.maximum(n_fin, 1.0)
    return jnp.stack([
        n_fin,
        jnp.sum(stacked[..., 1], axis=0),
        jnp.sum(stacked[..., 2], axis=0),
        jnp.max(stacked[..., 3], axis=0),
        mean,
        jnp.sqrt(jnp.sum(stacked[..., 5] ** 2, axis=0)),
    ], axis=-1)


class StatsTree:
    """Host-side view of one fetched stats array: named rows of STAT_NAMES
    columns. Activation rows are qualified layer paths (the
    profiler.annotate_layers naming, e.g. ``GPT/decoder/layers/0/mlp``);
    gradient rows carry a ``grad:`` prefix."""

    def __init__(self, paths: Sequence[str], values, step: Optional[int] = None):
        self.paths = list(paths)
        self.values = np.asarray(values, dtype=np.float32)
        self.step = step
        if self.values.ndim != 2 or self.values.shape[0] != len(self.paths) \
                or self.values.shape[1] != N_STATS:
            raise ValueError(
                f"stats shape {self.values.shape} does not match "
                f"{len(self.paths)} paths x {N_STATS} stats")

    def __len__(self):
        return len(self.paths)

    def row(self, path: str) -> Dict[str, float]:
        i = self.paths.index(path)
        return dict(zip(STAT_NAMES, (float(v) for v in self.values[i])))

    def rows(self):
        for p, v in zip(self.paths, self.values):
            yield p, dict(zip(STAT_NAMES, (float(x) for x in v)))

    def nonfinite_rows(self) -> List[Tuple[str, Dict[str, float]]]:
        return [(p, r) for p, r in self.rows() if r["nan"] or r["inf"]]

    def first_nonfinite(self) -> Optional[Tuple[str, Dict[str, float]]]:
        bad = self.nonfinite_rows()
        return bad[0] if bad else None

    def to_dict(self) -> dict:
        return {"step": self.step, "stat_names": list(STAT_NAMES),
                "rows": {p: [float(x) for x in v]
                         for p, v in zip(self.paths, self.values)}}

    def format(self) -> str:
        w = max((len(p) for p in self.paths), default=4)
        head = f"{'row':<{w}}  " + "".join(f"{s:>12}" for s in STAT_NAMES)
        lines = [head]
        for p, v in zip(self.paths, self.values):
            cells = "".join(
                f"{int(x):>12}" if i < 3 else f"{x:>12.4g}"
                for i, x in enumerate(v))
            lines.append(f"{p:<{w}}  {cells}")
        return "\n".join(lines)


# ---------------------------------------------------------------------------
# collection scope: instrumented layers record (path, stats_row) here while a
# scope is active — eagerly OR under a jit trace (rows are then tracers that
# become part of the compiled program's outputs)


class StatsCollector:
    def __init__(self):
        self.paths: List[str] = []
        self.rows: List[jnp.ndarray] = []
        self._counts: Dict[str, int] = {}

    def record(self, path: str, stats_row):
        # a layer called twice in one forward (weight-tied decode, recompute)
        # gets distinct rows: path, path#2, ...
        n = self._counts.get(path, 0) + 1
        self._counts[path] = n
        self.paths.append(path if n == 1 else f"{path}#{n}")
        self.rows.append(stats_row)

    def stacked(self) -> Optional[jnp.ndarray]:
        return jnp.stack(self.rows) if self.rows else None

    def tree(self, step: Optional[int] = None) -> Optional[StatsTree]:
        if not self.rows:
            return None
        return StatsTree(self.paths, np.asarray(self.stacked()), step=step)


def _stack() -> list:
    s = getattr(_tls, "stack", None)
    if s is None:
        s = _tls.stack = []
    return s


def active_collector() -> Optional[StatsCollector]:
    s = getattr(_tls, "stack", None)
    return s[-1] if s else None


@contextlib.contextmanager
def collect_stats():
    """Open a collection scope: instrumented layers (check_layer_numerics)
    record their output stats into the yielded collector. Nestable;
    tracer-safe (inside jit the rows are traced values)."""
    col = StatsCollector()
    _stack().append(col)
    try:
        yield col
    finally:
        _stack().pop()


# ---------------------------------------------------------------------------
# layer instrumentation


class _SentinelHandle:
    """Returned by check_layer_numerics; .remove() uninstalls the hooks."""

    def __init__(self, removers, paths):
        self._removers = removers
        self.paths = paths

    def remove(self):
        for r in self._removers:
            r.remove()
        self._removers = []


def _first_float_leaves(outputs):
    """The jax arrays to stat in a layer output (Tensor / tuple / dict)."""
    from ..core.tensor import Tensor
    leaves = jax.tree.leaves(
        outputs, is_leaf=lambda o: isinstance(o, Tensor))
    arrs = []
    for o in leaves:
        a = o._data if isinstance(o, Tensor) else o
        if hasattr(a, "dtype") and jnp.issubdtype(a.dtype, jnp.floating):
            arrs.append(a)
    return arrs


def check_layer_numerics(model, root: Optional[str] = None) -> _SentinelHandle:
    """Instrument every sublayer of `model` so that, while a collect_stats()
    scope is active (TrainStep's numerics mode opens one inside the traced
    step), each forward reduces its floating outputs to one stats row named
    by the layer's qualified path — the same ``Type/attr/...`` naming
    profiler.annotate_layers stamps on device traces.

    Outside a scope the hook is a dict lookup and a None check — safe to
    leave installed. Idempotent per layer. Returns a handle whose
    ``.remove()`` uninstalls."""
    root = root or type(model).__name__
    removers, paths = [], []
    for name, layer in model.named_sublayers(include_self=True):
        path = root if not name else f"{root}/{name.replace('.', '/')}"
        if getattr(layer, "_numerics_path", None) is not None:
            continue

        def _hook(lyr, inputs, outputs, _path=path):
            col = active_collector()
            if col is None:
                return None
            arrs = _first_float_leaves(outputs)
            if not arrs:
                return None
            row = array_stats(arrs[0]) if len(arrs) == 1 else \
                merge_stat_rows([array_stats(a) for a in arrs])
            col.record(_path, row)
            return None

        h = layer.register_forward_post_hook(_hook)
        layer._numerics_path = path

        class _Remover:
            def __init__(self, lyr, hook_handle):
                self._lyr, self._h = lyr, hook_handle

            def remove(self):
                self._h.remove()
                self._lyr._numerics_path = None

        removers.append(_Remover(layer, h))
        paths.append(path)
    return _SentinelHandle(removers, paths)


# ---------------------------------------------------------------------------
# gradient rows


def grad_layer_groups(param_names: Sequence[str], root: str
                      ) -> List[Tuple[str, List[int]]]:
    """Group param indices by owning layer path: 'moe.w1' -> 'Root/moe';
    a root-level param -> 'Root'. Order: first appearance."""
    groups: Dict[str, List[int]] = {}
    order: List[str] = []
    for i, name in enumerate(param_names):
        head = name.rsplit(".", 1)[0] if "." in name else ""
        path = root if not head else f"{root}/{head.replace('.', '/')}"
        key = f"grad:{path}"
        if key not in groups:
            groups[key] = []
            order.append(key)
        groups[key].append(i)
    return [(k, groups[k]) for k in order]


def grad_stat_rows(grads, groups) -> Tuple[List[str], List[jnp.ndarray]]:
    """Per-layer grad stats rows (trace-safe) for grad_layer_groups output."""
    paths, rows = [], []
    for key, idxs in groups:
        per = [array_stats(grads[i]) for i in idxs]
        rows.append(per[0] if len(per) == 1 else merge_stat_rows(per))
        paths.append(key)
    return paths, rows


def found_inf(grads) -> jnp.ndarray:
    """ONE fused reduction: True iff any grad leaf holds a non-finite value.
    Trace-safe — this is the in-graph sentinel dynamic loss scaling keys off
    (vs the reference's per-tensor eager check_finite_and_unscale)."""
    flags = [jnp.all(jnp.isfinite(g)) for g in jax.tree.leaves(grads)]
    if not flags:
        return jnp.asarray(False)
    return jnp.logical_not(jnp.all(jnp.stack(flags)))


def model_param_stats(model, root: Optional[str] = None,
                      grads: bool = False) -> StatsTree:
    """Eager stats tree over a model's parameters (and optionally their
    .grad) — the host-side fallback NumericsCallback uses when the training
    loop is not a TrainStep. One device->host fetch for the whole tree."""
    root = root or type(model).__name__
    paths, rows = [], []
    for name, p in model.named_parameters():
        head = name.rsplit(".", 1)[0] if "." in name else ""
        path = root if not head else f"{root}/{head.replace('.', '/')}"
        src = p.grad if grads else p
        if src is None:
            continue
        paths.append((f"grad:{path}/{name.rsplit('.', 1)[-1]}" if grads
                      else f"param:{path}/{name.rsplit('.', 1)[-1]}"))
        rows.append(array_stats(src._data))
    values = np.asarray(jnp.stack(rows)) if rows else \
        np.zeros((0, N_STATS), np.float32)
    return StatsTree(paths, values)
