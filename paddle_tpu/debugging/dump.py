"""Anomaly dumps — persist a failing step so it replays standalone.

When a NumericsEvent fires, the step that produced it is written to
``<dump_dir>/step<K>_<kind>/``:

  meta.json      — step index, the firing event(s), the RNG key (raw key
                   data words), loss, stats-row paths, batch tree spec
  batch.npz      — the offending batch's array leaves (leaf0, leaf1, ...)
  params.npz     — parameter arrays by qualified name
  opt_state.npz  — optimizer-state arrays as "<param>::<slot>"
  stats.npz      — the fetched [rows, N_STATS] stats array

Because TrainStep's numerics mode selects AWAY non-finite updates
(skip_nonfinite_updates), the params on disk are the exact pre-step values
— replaying the dump re-runs the very computation that blew up, not its
aftermath. ``tools/replay_dump.py`` is the CLI; ``replay()`` is the
library entry (rebuild the model, load params, re-run forward+backward
under the dumped RNG key with sentinels installed, return the reproduced
stats tree + events).
"""
from __future__ import annotations

import json
import os
from typing import Callable, List, Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp

from .sentinel import (StatsTree, N_STATS, check_layer_numerics,
                       collect_stats, array_stats, grad_layer_groups,
                       grad_stat_rows)
from .anomaly import AnomalyDetector, NumericsEvent


# -- tiny tree spec: tuple/list/dict/leaf, enough for batch pytrees ----------

def tree_spec(obj):
    if isinstance(obj, (list, tuple)):
        return {"t": "tuple" if isinstance(obj, tuple) else "list",
                "c": [tree_spec(o) for o in obj]}
    if isinstance(obj, dict):
        return {"t": "dict", "k": sorted(obj),
                "c": [tree_spec(obj[k]) for k in sorted(obj)]}
    if obj is None:
        return {"t": "none"}
    return {"t": "leaf"}


def tree_build(spec, leaves: List):
    """Inverse of tree_spec; consumes `leaves` left-to-right (same order as
    jax.tree.flatten, which sorts dict keys)."""
    t = spec["t"]
    if t == "leaf":
        return leaves.pop(0)
    if t == "none":
        return None
    if t == "dict":
        return {k: tree_build(c, leaves) for k, c in zip(spec["k"], spec["c"])}
    seq = [tree_build(c, leaves) for c in spec["c"]]
    return tuple(seq) if t == "tuple" else seq


# -- writer ------------------------------------------------------------------

def _key_data(key) -> Optional[list]:
    if key is None:
        return None
    try:
        return np.asarray(jax.random.key_data(key)).tolist()
    except Exception:
        return np.asarray(key).tolist()


def write_dump(dump_dir: str, *, step: int, events: Sequence[NumericsEvent],
               batch_leaves: Sequence, batch_spec: dict,
               param_names: Sequence[str], param_arrays: Sequence,
               opt_state: Optional[Sequence] = None, key=None,
               loss: Optional[float] = None,
               stats: Optional[StatsTree] = None,
               extra_meta: Optional[dict] = None) -> str:
    """Persist one failing step; returns the dump directory path."""
    kind = events[0].kind if events else "manual"
    out = os.path.join(dump_dir, f"step{step}_{kind}")
    os.makedirs(out, exist_ok=True)

    meta = {
        "step": step,
        "events": [e.to_dict() for e in events],
        "rng_key_data": _key_data(key),
        "loss": None if loss is None else float(loss),
        "batch_spec": batch_spec,
        "n_batch_leaves": len(batch_leaves),
        "param_names": list(param_names),
        "stats_paths": stats.paths if stats is not None else None,
    }
    meta.update(extra_meta or {})
    with open(os.path.join(out, "meta.json"), "w") as f:
        json.dump(meta, f, indent=2)

    np.savez(os.path.join(out, "batch.npz"),
             **{f"leaf{i}": np.asarray(a) for i, a in enumerate(batch_leaves)})
    np.savez(os.path.join(out, "params.npz"),
             **{n: np.asarray(a) for n, a in zip(param_names, param_arrays)})
    if opt_state is not None:
        slots = {}
        for n, st in zip(param_names, opt_state):
            for k, v in (st or {}).items():
                slots[f"{n}::{k}"] = np.asarray(v)
        np.savez(os.path.join(out, "opt_state.npz"), **slots)
    if stats is not None:
        np.savez(os.path.join(out, "stats.npz"), stats=stats.values)
    return out


# -- loader / replay ---------------------------------------------------------

class Dump:
    """A loaded anomaly dump."""

    def __init__(self, path: str):
        self.path = path
        with open(os.path.join(path, "meta.json")) as f:
            self.meta = json.load(f)
        bz = np.load(os.path.join(path, "batch.npz"))
        self.batch_leaves = [bz[f"leaf{i}"]
                             for i in range(self.meta["n_batch_leaves"])]
        pz = np.load(os.path.join(path, "params.npz"))
        self.params = {n: pz[n] for n in pz.files}
        op = os.path.join(path, "opt_state.npz")
        self.opt_state = None
        if os.path.exists(op):
            oz = np.load(op)
            self.opt_state = {n: oz[n] for n in oz.files}
        sp = os.path.join(path, "stats.npz")
        self.stats = None
        if os.path.exists(sp) and self.meta.get("stats_paths"):
            self.stats = StatsTree(self.meta["stats_paths"],
                                   np.load(sp)["stats"],
                                   step=self.meta["step"])

    @property
    def step(self) -> int:
        return self.meta["step"]

    @property
    def events(self) -> List[dict]:
        return self.meta["events"]

    def batch(self):
        """The batch pytree, rebuilt from its spec (arrays, not Tensors)."""
        leaves = [jnp.asarray(a) for a in self.batch_leaves]
        return tree_build(self.meta["batch_spec"], list(leaves))

    def rng_key(self):
        kd = self.meta.get("rng_key_data")
        if kd is None:
            return None
        data = jnp.asarray(np.asarray(kd, dtype=np.uint32))
        try:
            return jax.random.wrap_key_data(data)
        except Exception:
            return data


def load_dump(path: str) -> Dump:
    return Dump(path)


class ReplayResult:
    def __init__(self, loss, stats: Optional[StatsTree],
                 events: List[NumericsEvent], matches: Optional[bool]):
        self.loss = loss
        self.stats = stats
        self.events = events
        self.matches = matches   # reproduced stats == dumped stats (where both exist)


def replay(dump: Dump, model, loss_fn: Callable,
           detector: Optional[AnomalyDetector] = None,
           compute_grads: bool = True) -> ReplayResult:
    """Re-run the dumped step against a freshly built `model`.

    Loads the dumped params into the model by qualified name, installs the
    numerics sentinels, replays ``loss_fn(*batch)`` (and its backward when
    `compute_grads`) under the dumped RNG key, and returns the reproduced
    stats tree + the events a fresh detector raises on it. `matches` is True
    when every dumped stats row that exists in the replay reproduces its
    nan/inf counts — "the same bad value", modulo rows the eager replay
    doesn't emit (e.g. in-graph grad rows when compute_grads=False)."""
    from ..core.tensor import Tensor
    from ..core import random as _random

    # load params by name (subset-tolerant: extra model params keep init)
    name_to_param = dict(model.named_parameters())
    for n, arr in dump.params.items():
        if n in name_to_param:
            p = name_to_param[n]
            p._data = jnp.asarray(arr).astype(p._data.dtype)
            p._node = None

    handle = check_layer_numerics(model)
    root = type(model).__name__
    batch = dump.batch()
    leaves, _ = jax.tree.flatten(batch)
    tensors = jax.tree.unflatten(jax.tree.structure(batch),
                                 [Tensor(l) for l in leaves])
    key = dump.rng_key()

    try:
        import contextlib
        scope = _random.trace_key_scope(key) if key is not None \
            else contextlib.nullcontext()
        with scope, collect_stats() as col:
            if isinstance(tensors, (list, tuple)):
                out = loss_fn(*tensors)
            else:
                out = loss_fn(tensors)
            loss = out
            if compute_grads and isinstance(out, Tensor) \
                    and not out.stop_gradient:
                out.backward()
        paths = list(col.paths)
        rows = list(col.rows)
        if compute_grads:
            names = [n for n, p in model.named_parameters()
                     if p.grad is not None]
            grads = [name_to_param[n].grad._data for n in names]
            if grads:
                gpaths, grows = grad_stat_rows(
                    grads, grad_layer_groups(names, root))
                paths += gpaths
                rows += grows
        stats = StatsTree(paths, np.asarray(jnp.stack(rows)),
                          step=dump.step) if rows else None
    finally:
        handle.remove()

    det = detector or AnomalyDetector()
    events = det.observe(dump.step, tree=stats) if stats is not None else []

    matches = None
    if stats is not None and dump.stats is not None:
        matches = True
        for p, r in stats.rows():
            if p in dump.stats.paths:
                ref = dump.stats.row(p)
                if (r["nan"] > 0) != (ref["nan"] > 0) or \
                        (r["inf"] > 0) != (ref["inf"] > 0):
                    matches = False
    loss_val = float(np.asarray(loss._data).astype(np.float64)) \
        if isinstance(loss, Tensor) and loss.size == 1 else None
    return ReplayResult(loss_val, stats, events, matches)
