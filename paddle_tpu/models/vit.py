"""Vision Transformer (capability: BASELINE.md ViT-L/16 bench config; the
reference era serves ViT through its generic nn.TransformerEncoder,
python/paddle/nn/layer/transformer.py).

TPU-native: patch embedding is one strided conv (MXU-friendly), encoder
re-uses the same mp-sharded projections as GPT/BERT.
"""
from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Optional

import jax.numpy as jnp

from ..core.tensor import apply_op
from ..core import ops
from ..nn.layer import Layer, LayerList
from ..nn import functional as F
from ..nn import initializer as I
from ..nn.layers.common import Dropout, Linear
from ..nn.layers.conv import Conv2D
from ..nn.layers.norm import LayerNorm
from ..distributed.mpu import ColumnParallelLinear, RowParallelLinear
from ..distributed import mesh as _mesh
from ..ops.attention import functional_attention

__all__ = ["ViTConfig", "VisionTransformer", "vit_config"]


@dataclass
class ViTConfig:
    image_size: int = 224
    patch_size: int = 16
    num_channels: int = 3
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: Optional[int] = None
    hidden_dropout: float = 0.0
    layer_norm_epsilon: float = 1e-6
    initializer_range: float = 0.02
    num_classes: int = 1000
    param_dtype: str = "float32"

    def __post_init__(self):
        if self.intermediate_size is None:
            self.intermediate_size = 4 * self.hidden_size
        assert self.image_size % self.patch_size == 0

    @property
    def head_dim(self):
        return self.hidden_size // self.num_heads

    @property
    def num_patches(self):
        return (self.image_size // self.patch_size) ** 2


PRESETS = {
    "vit-b16": dict(hidden_size=768, num_layers=12, num_heads=12),
    "vit-l16": dict(hidden_size=1024, num_layers=24, num_heads=16),
    "vit-h14": dict(hidden_size=1280, num_layers=32, num_heads=16,
                    patch_size=14),
}


def vit_config(preset: str, **overrides) -> ViTConfig:
    cfg = dict(PRESETS[preset])
    cfg.update(overrides)
    return ViTConfig(**cfg)


class ViTBlock(Layer):
    """Pre-LN block, mp-sharded projections."""

    def __init__(self, config: ViTConfig):
        super().__init__()
        h, m = config.hidden_size, config.intermediate_size
        self.num_heads = config.num_heads
        self.head_dim = config.head_dim
        init = I.Normal(std=config.initializer_range)
        self.ln_1 = LayerNorm(h, epsilon=config.layer_norm_epsilon)
        self.qkv = ColumnParallelLinear(h, 3 * h, gather_output=False)
        self.qkv.weight.set_value(init([h, 3 * h], self.qkv.weight.dtype))
        self.out = RowParallelLinear(h, h, input_is_parallel=True)
        self.out.weight.set_value(
            init([h, h], self.out.weight.dtype)
            / math.sqrt(2 * config.num_layers))
        self.ln_2 = LayerNorm(h, epsilon=config.layer_norm_epsilon)
        self.up = ColumnParallelLinear(h, m, gather_output=False)
        self.up.weight.set_value(init([h, m], self.up.weight.dtype))
        self.down = RowParallelLinear(m, h, input_is_parallel=True)
        self.down.weight.set_value(
            init([m, h], self.down.weight.dtype)
            / math.sqrt(2 * config.num_layers))
        self.dropout = Dropout(config.hidden_dropout)

    def forward(self, x):
        from ..ops.pallas.fused_mha import fused_mha, use_fused_mha
        nh, hd = self.num_heads, self.head_dim
        qkv = self.qkv(self.ln_1(x))
        b, s = qkv.shape[0], qkv.shape[1]
        if (use_fused_mha(s, nh, hd)
                and _mesh.mesh_axis_size("mp") == 1
                and _mesh.mesh_axis_size("sp") == 1):
            # Whole-sequence fused MHA on the PACKED projection output
            # (ops/pallas/fused_mha.py): no [B,S,3,nh,hd] reshape, no
            # head-major transposes, and no padding — Mosaic masks the
            # ragged S=197 block dims natively. The r3 XLA path left
            # ~12 ms of layout copies + ~9 ms of softmax per ViT-L step
            # on the table; measured 54% -> 57.8% MFU on v5e.
            ctx = apply_op("vit_attention",
                           lambda a: fused_mha(a, nh), [qkv])
            x = x + self.out(ctx)
            y = self.down(F.gelu(self.up(self.ln_2(x)), approximate=True))
            if self.training and self.dropout.p:
                y = self.dropout(y)
            return x + y
        qkv = ops.reshape(qkv, [b, s, 3, nh, hd])

        def attend(a):
            q, k, v = a[:, :, 0], a[:, :, 1], a[:, :, 2]
            q = _mesh.shard_constraint(q, "dp", None, "mp", None)
            k = _mesh.shard_constraint(k, "dp", None, "mp", None)
            v = _mesh.shard_constraint(v, "dp", None, "mp", None)
            # bf16 models store the S×S scores in bf16 (f32 accumulation
            # stays inside the dots/softmax stats): halves the dominant
            # O(S²) HBM traffic of the XLA path — measured +5 MFU points
            # on ViT-L/16 B=32 v5e. A head-major inline variant and a
            # padded-flash route both measured NO better at S=197.
            o = functional_attention(q, k, v, is_causal=False,
                                     score_dtype=q.dtype)
            return _mesh.shard_constraint(o, "dp", None, "mp", None)

        ctx = apply_op("vit_attention", attend, [qkv])
        x = x + self.out(ops.reshape(ctx, [b, s, nh * hd]))
        y = self.down(F.gelu(self.up(self.ln_2(x)), approximate=True))
        if self.training and self.dropout.p:
            y = self.dropout(y)
        return x + y


def _patchify_matmul(img, w, bias, p):
    """[B,C,H,W] -> [B, N, hidden] patch embedding: space-to-depth then one
    einsum with the Conv2D weight [hidden, C, p, p] flattened — exactly the
    stride-p conv, expressed so forward AND backward are plain matmuls.
    Partial trailing patches are floored away like the strided conv."""
    B, C, H, W = img.shape
    gh, gw = H // p, W // p
    if (H % p) or (W % p):
        img = img[:, :, :gh * p, :gw * p]
    x = img.reshape(B, C, gh, p, gw, p)
    x = x.transpose(0, 2, 4, 1, 3, 5).reshape(B, gh * gw, C * p * p)
    wm = w.reshape(w.shape[0], -1)                    # [hidden, C*p*p]
    return jnp.einsum("bnk,hk->bnh", x, wm) + bias


class VisionTransformer(Layer):
    """ViT backbone + classification head (cls-token pooling)."""

    def __init__(self, config: ViTConfig):
        super().__init__()
        self.config = config
        h = config.hidden_size
        self.patch_embed = Conv2D(config.num_channels, h, config.patch_size,
                                  stride=config.patch_size)
        self.cls_token = self.create_parameter(
            [1, 1, h], default_initializer=I.TruncatedNormal(std=0.02))
        self.pos_embed = self.create_parameter(
            [1, config.num_patches + 1, h],
            default_initializer=I.TruncatedNormal(std=0.02))
        self.dropout = Dropout(config.hidden_dropout)
        self.blocks = LayerList([ViTBlock(config)
                                 for _ in range(config.num_layers)])
        self.ln = LayerNorm(h, epsilon=config.layer_norm_epsilon)
        if config.num_classes > 0:
            self.head = Linear(h, config.num_classes)
        if config.param_dtype != "float32":
            self.to(dtype=config.param_dtype)

    def forward(self, pixel_values):
        # Patchify as space-to-depth + ONE matmul on the conv's own weight
        # (numerically the strided conv, same parameters/state dict). The
        # conv formulation cost ~17 ms/step of ViT-L's 107 ms on v5e —
        # XLA's conv/conv-grad kernels + layout transposes for a kernel
        # that is really a reshape — vs matmul fwd+bwd on the MXU
        # (r3 profile, VERDICT r2 #4).
        p = self.config.patch_size
        pe = self.patch_embed
        if pe.bias is not None:
            x = apply_op(
                "vit_patchify",
                lambda img, w, bias: _patchify_matmul(img, w, bias, p),
                [pixel_values, pe.weight, pe.bias])
        else:
            x = apply_op(
                "vit_patchify",
                lambda img, w: _patchify_matmul(img, w, 0.0, p),
                [pixel_values, pe.weight])
        b, h = x.shape[0], x.shape[2]
        cls = ops.expand(self.cls_token, [b, 1, h])
        x = ops.concat([cls, x], axis=1) + self.pos_embed
        if self.training and self.config.hidden_dropout:
            x = self.dropout(x)
        # Measured dead end (r3, v5e): flattening the residual stream to
        # [B*S, H] for the whole encoder is ~7% SLOWER end-to-end (45.0%
        # vs 48.4% MFU) — cleaner LN layouts, but XLA re-materializes
        # attention-side transposes at every 2D<->4D boundary.
        for blk in self.blocks:
            x = blk(x)
        x = self.ln(x)
        if self.config.num_classes > 0:
            return self.head(x[:, 0])
        return x
