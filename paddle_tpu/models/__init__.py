"""Model zoo.

Reference: python/paddle/vision/models (ResNet/VGG/MobileNet/... listing,
SURVEY §2.3) for vision; PaddleNLP entrypoints (BASELINE.md configs) for the
language flagship. Everything is built on paddle_tpu.nn layers and the
distributed mpu layers, so every model is single-chip AND hybrid-parallel
capable from the same code.
"""
from .gpt import (  # noqa: F401
    GPTConfig, GPTModel, GPTForCausalLM, GPTPretrainingCriterion,
    gpt_config, PRESETS as GPT_PRESETS,
)
from .gpt_stacked import (  # noqa: F401
    GPTStackedForCausalLM,
)
from .bert import (  # noqa: F401
    BertConfig, BertModel, BertForMaskedLM, BertForSequenceClassification,
    BertForPretraining, bert_config,
)
from .vit import (  # noqa: F401
    ViTConfig, VisionTransformer, vit_config,
)
from .ernie import (  # noqa: F401
    ErnieConfig, ErnieModel, ErnieForSequenceClassification,
    ErnieForMaskedLM, ernie_config,
)
