"""ERNIE model family (reference entrypoint class: ERNIE pretraining /
fine-tuning configs listed in BASELINE.md; architecture = BERT-style encoder
with task-id embeddings, per the original ERNIE 1.0/2.0 papers).

TPU-native: reuses the mpu-sharded BERT encoder stack (models/bert.py) —
ERNIE's delta over BERT is the extra `task_type_embeddings` table and its
knowledge-masking *data* strategy (a masking policy, not an architecture
change), so the module adds exactly that and keeps every sharding/fusion
property of the BERT path.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from ..core import ops
from ..nn.layer import Layer
from ..nn.layers.common import Embedding, Dropout, Linear
from ..nn import functional as F
from .bert import (BertConfig, BertEmbeddings, BertLayer, BertPooler,
                   _tied_logits)
from ..nn.layer import LayerList

__all__ = ["ErnieConfig", "ErnieModel", "ErnieForSequenceClassification",
           "ErnieForMaskedLM", "ernie_config"]


@dataclass
class ErnieConfig(BertConfig):
    vocab_size: int = 18000
    use_task_id: bool = True
    task_type_vocab_size: int = 3


_PRESETS = {
    "ernie-1.0": dict(vocab_size=18000, hidden_size=768, num_layers=12,
                      num_heads=12, max_position_embeddings=513),
    "ernie-3.0-medium": dict(vocab_size=40000, hidden_size=768, num_layers=6,
                             num_heads=12, max_position_embeddings=2048),
    "ernie-tiny": dict(vocab_size=18000, hidden_size=312, num_layers=4,
                       num_heads=12, max_position_embeddings=512,
                       intermediate_size=1248),
}


def ernie_config(preset: str, **overrides) -> ErnieConfig:
    cfg = dict(_PRESETS[preset])
    cfg.update(overrides)
    return ErnieConfig(**cfg)


class ErnieModel(Layer):
    """Encoder trunk: BERT embeddings + task-type embeddings + N sharded
    transformer layers + pooler."""

    def __init__(self, config: ErnieConfig):
        super().__init__()
        self.config = config
        self.embeddings = BertEmbeddings(config)
        if config.use_task_id:
            self.task_type_embeddings = Embedding(
                config.task_type_vocab_size, config.hidden_size)
        self.layers = LayerList([BertLayer(config)
                                 for _ in range(config.num_layers)])
        self.pooler = BertPooler(config)

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None, task_type_ids=None):
        h = self.embeddings(input_ids, token_type_ids, position_ids)
        if self.config.use_task_id:
            if task_type_ids is None:
                task_type_ids = ops.zeros_like(input_ids)
            h = h + self.task_type_embeddings(task_type_ids)
        for layer in self.layers:
            h = layer(h, attention_mask)
        return h, self.pooler(h)


class ErnieForSequenceClassification(Layer):
    def __init__(self, config: ErnieConfig, num_classes: int = 2,
                 dropout: Optional[float] = None):
        super().__init__()
        self.ernie = ErnieModel(config)
        self.dropout = Dropout(config.hidden_dropout
                               if dropout is None else dropout)
        self.classifier = Linear(config.hidden_size, num_classes)

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None, task_type_ids=None):
        _, pooled = self.ernie(input_ids, token_type_ids, position_ids,
                               attention_mask, task_type_ids)
        return self.classifier(self.dropout(pooled))


class ErnieForMaskedLM(Layer):
    def __init__(self, config: ErnieConfig):
        super().__init__()
        self.ernie = ErnieModel(config)

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None, task_type_ids=None):
        h, _ = self.ernie(input_ids, token_type_ids, position_ids,
                          attention_mask, task_type_ids)
        return _tied_logits(h, self.ernie.embeddings.word_embeddings)

    def loss(self, input_ids, labels, token_type_ids=None, position_ids=None,
             attention_mask=None, task_type_ids=None, loss_mask=None,
             chunk_size: int = 256, ignore_index: int = -100):
        """Fused MLM loss (chunked tied-decoder CE; see
        BertForMaskedLM.loss)."""
        from ..incubate.nn.functional import fused_linear_cross_entropy
        from ..core import ops
        from .gpt import _masked_mean
        h, _ = self.ernie(input_ids, token_type_ids, position_ids,
                          attention_mask, task_type_ids)
        w = self.ernie.embeddings.word_embeddings.weight
        safe_labels = ops.where(labels == ignore_index,
                                ops.zeros_like(labels), labels)
        per_tok = fused_linear_cross_entropy(h, w, safe_labels,
                                             chunk_size=chunk_size)
        mask = ops.cast(labels != ignore_index, "float32")
        if loss_mask is not None:
            mask = mask * ops.cast(loss_mask, "float32")
        return _masked_mean(per_tok, mask)
